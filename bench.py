#!/usr/bin/env python
"""Headline benchmark — one JSON line for the driver.

Current flagship config: exact brute-force kNN on SIFT-shaped synthetic
data (1M × 128 float32, k=10, query batch 10 — the reference's
"batch size 10" headline regime, ``docs/source/raft_ann_benchmarks.md``).
Exact search ⇒ recall@10 is 1.0 by construction; the figure of merit is
QPS.

``vs_baseline`` normalizes QPS by the single-chip HBM roofline for this
config: each batch must stream the whole dataset (512 MB) from HBM, so
roofline QPS = batch · BW / bytes  =  10 · 819e9 / 512e6 ≈ 16k QPS on
TPU v5e. A value of 1.0 means memory-bound optimal; >1 means the cache/
fusion behavior beats the naive stream estimate. (The reference repo
publishes no numeric tables to compare against — see BASELINE.md.)
"""

import json
import time

import jax
import jax.numpy as jnp

from raft_tpu.neighbors import brute_force

N, D, K, BATCH = 1_000_000, 128, 10, 10
V5E_HBM_BYTES_PER_S = 819e9
ROOFLINE_QPS = BATCH * V5E_HBM_BYTES_PER_S / (N * D * 4)


def main():
    key = jax.random.key(0)
    kd, kq = jax.random.split(key)
    dataset = jax.random.normal(kd, (N, D), jnp.float32)
    queries = jax.random.normal(kq, (BATCH, D), jnp.float32)
    index = brute_force.build(None, dataset)

    def run():
        d, i = brute_force.search(None, index, queries, K, db_tile=262144)
        jax.block_until_ready((d, i))
        return d, i

    run()  # compile + warm
    n_iters = 20
    t0 = time.perf_counter()
    for _ in range(n_iters):
        run()
    dt = (time.perf_counter() - t0) / n_iters
    qps = BATCH / dt

    print(json.dumps({
        "metric": "brute_force_knn_qps_sift1m_shape_b10_k10",
        "value": round(qps, 2),
        "unit": "QPS",
        "vs_baseline": round(qps / ROOFLINE_QPS, 4),
    }))


if __name__ == "__main__":
    main()
