#!/usr/bin/env python
"""Headline benchmark — one JSON line on stdout for the driver.

Flagship config: exact brute-force kNN on SIFT-shaped synthetic data
(1M × 128 float32, k=10, query batch 10 — the reference's "batch size
10" headline regime, ``docs/source/raft_ann_benchmarks.md``). Exact
search ⇒ recall@10 is 1.0 by construction; the figure of merit is QPS.

``vs_baseline`` normalizes QPS by the single-chip HBM roofline for this
config: each batch must stream the whole dataset (512 MB) from HBM, so
roofline QPS = batch · BW / bytes = 10 · 819e9 / 512e6 ≈ 16k QPS on
TPU v5e. A value of 1.0 means memory-bound optimal. (The reference
repo publishes no numeric tables to compare against — see BASELINE.md.)

Resilience layout (the round-1 artifact was lost to a wedged TPU
relay): the parent process never imports jax. It (1) probes backend
init in a subprocess, retrying with backoff because relay wedges can
clear; (2) runs the measurement in a child subprocess; (3) if the TPU
child exceeds its deadline it is ABANDONED, never killed — killing an
in-flight TPU process wedges the relay for hours (STATUS.md) — and a
CPU child (axon plugin disabled via env) produces an annotated
fallback metric instead.

Timing inside the child is pipelined (dispatch a run of iterations,
fetch once): ``block_until_ready`` does not block on relayed backends,
and a per-iteration host fetch would pay the ~65 ms relay round-trip
every call. The measured call goes through the serving path
(``SearchExecutor``: bucketed batch, AOT-compiled executable), and the
JSON line carries ``compile_count`` / ``cache_hits`` /
``warmup_seconds`` so the trajectory catches recompile regressions.

Progress goes to stderr so a slow run is diagnosable; stdout carries
exactly one JSON line. Env knobs: BENCH_N / BENCH_DIM / BENCH_BATCH /
BENCH_K / BENCH_SECONDS (measurement budget, default 45) /
BENCH_DTYPE (float32|bfloat16 dataset storage; default bfloat16 on
TPU — validated in-run against exact-f32 ids — and float32 on CPU) /
BENCH_PROBE_PLAN ("timeout:sleep,timeout:sleep,..." probe schedule) /
BENCH_CHILD_DEADLINE (seconds before the parent abandons a child) /
RAFT_TPU_DISABLE_FUSED=1 (force the XLA tile-scan path). Opt-in
riders: BENCH_IVF_SWEEP=1 (probe-scan engine A/B with roofline
annotations), BENCH_MULTICHIP=1 (mesh-native serving: per-chip QPS,
compile counts and modeled lean collective bytes for the list-sharded
index across every visible chip), BENCH_SERVING=1 (request frontend:
bursty open-loop load through the DynamicBatcher — p50/p95/p99
latency, shed rate and batch occupancy next to the one-request-per-
call baseline QPS), BENCH_BQ=1 (RaBitQ IVF-BQ: fused
estimate-then-rerank vs estimate+refine recall at equal over-fetch,
modeled bytes/vector and one-stream bytes vs the two-pass model,
achieved GB/s vs the stream_read_sum roofline), BENCH_CAGRA=1
(graftbeam CAGRA A/B: random-pool vs coarse-plane seeding vs
coarse + BQ-coded traversal — recall, QPS, modeled gather bytes vs
the stream roofline, survivor-fraction estimator replay, pad waste
and compiles-during-measure), BENCH_TIERED=1
(grafttier: hot/cold tiered storage — bit-identity vs the all-HBM
index, hot GB/s vs the HBM roofline and cold GB/s vs a host-link
roofline, two live placement epochs with zero backend compiles and
deterministic swap bytes), BENCH_FLEET=1 (graftroute: the fleet
router through the device-free N-replica harness — steer and
f32-wire fan-out bit-identity vs the solo oracle, bf16-wire recall,
modeled merge-payload bytes per wire dtype).
"""

import json
import os
import subprocess
import sys
import time

T0 = time.perf_counter()

N = int(os.environ.get("BENCH_N", 1_000_000))
D = int(os.environ.get("BENCH_DIM", 128))
BATCH = int(os.environ.get("BENCH_BATCH", 10))
K = int(os.environ.get("BENCH_K", 10))
BUDGET_S = float(os.environ.get("BENCH_SECONDS", 45))
V5E_HBM_BYTES_PER_S = 819e9
ROOFLINE_QPS = BATCH * V5E_HBM_BYTES_PER_S / (N * D * 4)


def log(msg):
    print(f"[bench +{time.perf_counter() - T0:.1f}s] {msg}",
          file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# parent: probe / dispatch / fallback (no jax import in this process)
# ---------------------------------------------------------------------------


def _probe_once(timeout_s: float) -> bool:
    """Probe backend init in a subprocess. A wedged TPU relay blocks
    ~25 min before erroring, which would eat the whole bench budget —
    so the probe, not the bench, takes that hit. Killing a process
    that is stuck in *init* (make_c_api_client) has not been observed
    to wedge the relay; killing one mid-*execution* has, which is why
    only probes ever get a timeout-kill."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); "
             "print('BACKEND=' + jax.default_backend())"],
            capture_output=True, timeout=timeout_s, text=True,
        )
        # a probe that "succeeds" via jax's silent CPU fallback is NOT
        # a healthy accelerator — the metric would be CPU-measured but
        # labeled as the TPU number
        for line in r.stdout.splitlines():
            if line.startswith("BACKEND="):
                backend = line.split("=", 1)[1].strip()
                log(f"probe backend: {backend}")
                return backend != "cpu"
        return False
    except subprocess.TimeoutExpired:
        return False


def _probe_plan():
    """Parse BENCH_PROBE_PLAN 'timeout:sleep,...'. Default: three
    attempts with backoff (~17 min worst case) — wedges can clear."""
    default = "240:60,360:120,240:0"
    plan = os.environ.get("BENCH_PROBE_PLAN", default)
    out = []
    for item in plan.split(","):
        if not item.strip():
            continue
        t, _, s = item.partition(":")
        try:
            out.append((float(t), float(s or 0)))
        except ValueError:
            log(f"ignoring malformed BENCH_PROBE_PLAN item {item!r}")
    if not out:
        log(f"BENCH_PROBE_PLAN empty/malformed; using default {default!r}")
        out = [(240.0, 60.0), (360.0, 120.0), (240.0, 0.0)]
    return out


def _relay_port_open():
    """Instant TCP pre-check of the relay's listener ports — when the
    relay process is gone (round-2 failure mode) nothing in-container
    can bring it back, so the multi-minute init probes are pointless.
    Returns None (inconclusive) when the pool IP env var is unset —
    then the full probe plan runs as before."""
    import socket

    host = (os.environ.get("PALLAS_AXON_POOL_IPS") or "").split(",")[0]
    if not host:
        return None
    for port in (8082, 8083, 8093):
        try:
            with socket.create_connection((host, port), timeout=2):
                return True
        except OSError:
            continue
    return False


def _backend_healthy() -> bool:
    if _relay_port_open() is False:
        log("relay listener ports closed — relay process is down; "
            "one short probe then CPU fallback")
        return _probe_once(60.0)
    for i, (timeout_s, sleep_s) in enumerate(_probe_plan()):
        log(f"probe attempt {i + 1}: init timeout {timeout_s:.0f}s")
        if _probe_once(timeout_s):
            log("backend probe OK")
            return True
        log(f"probe attempt {i + 1} failed/hung"
            + (f"; backing off {sleep_s:.0f}s" if sleep_s else ""))
        if sleep_s:
            time.sleep(sleep_s)
    return False


def _spawn_child(cpu: bool):
    env = dict(os.environ)
    env["BENCH_CHILD"] = "1"
    if cpu:
        # disable the axon PJRT plugin entirely: with the pool IP set,
        # even JAX_PLATFORMS=cpu goes through plugin registration and
        # hangs on a wedged relay
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_SUFFIX"] = "_cpu_fallback"
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE, stderr=sys.stderr, text=True, env=env,
    )


def _await_child(child, deadline_s: float):
    """Wait for the child's JSON line. On deadline: abandon (no kill —
    an in-flight TPU process must never be killed, STATUS.md). A child
    that printed its result but wedged in runtime teardown still counts:
    the captured lines are scanned either way."""
    import threading

    lines = []

    def drain():
        for line in child.stdout:
            lines.append(line)

    t = threading.Thread(target=drain, daemon=True)
    t.start()
    t.join(deadline_s)
    if not t.is_alive():
        child.wait()
    for line in reversed(list(lines)):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue  # truncated line from a dying child; keep scanning
    return None


def _is_cpu_hog(argv) -> bool:
    """Known-CPU-only-by-construction background jobs: hnswlib /
    ivf_flat_cpu competitor sweeps, the prebuild scripts (both pin
    jax_platforms=cpu), pytest (the conftest forces CPU). Matching is
    per-TOKEN equality/suffix, never
    a substring scan of the joined cmdline — a process whose ARGUMENT
    merely mentions one of these words (a shell -c script, an agent
    prompt) must not be frozen. Deliberately narrow overall: a broad
    'bench' pattern could catch an abandoned in-flight TPU process,
    and SIGSTOPping one of those is the mid-transaction freeze the
    relay rules forbid."""
    toks = [t for t in argv if t]
    short = {t for t in toks if len(t) < 64}
    # basename equality (not suffix): a token with embedded spaces (a
    # bash -c script mentioning these names) must not match
    names = {t.rsplit("/", 1)[-1] for t in short}
    if names & {"pytest", "prebuild_sweep_indexes.py",
                "tpu_prebuild_indexes.py"}:
        return True
    if "raft_tpu.bench" not in short or "run" not in short:
        return False
    # --algos may arrive as "a" / "--algos=a" / a comma list; the sweep
    # is CPU-only iff EVERY requested family is a CPU competitor (a
    # mixed list includes raft algos that may run on the TPU)
    competitors = {"hnswlib", "ivf_flat_cpu"}
    for t in short:
        if t.startswith("--algos="):
            t = t[len("--algos="):]
        parts = [p.strip() for p in t.split(",")]
        if parts and all(p in competitors for p in parts):
            return True
    return False


def _is_cpu_pinned_bench(argv, environ) -> bool:
    """A raft-family sweep is CPU-only when its own environment pins
    jax to the host (the CPU-rehearsal launch convention:
    JAX_PLATFORMS=cpu with the axon pool IP unset) — safe to pause no
    matter which algo families it runs."""
    toks = {t for t in argv if t and len(t) < 64}
    return ("raft_tpu.bench" in toks and "run" in toks
            and environ.get("JAX_PLATFORMS") == "cpu"
            and "PALLAS_AXON_POOL_IPS" not in environ)


def _proc_environ(pid_s: str):
    try:
        with open(f"/proc/{pid_s}/environ", "rb") as fh:
            raw = fh.read().decode(errors="replace")
    except OSError:
        return {}
    out = {}
    for item in raw.split("\0"):
        k, sep, v = item.partition("=")
        if sep:
            out[k] = v
    return out


def _ancestor_pids():
    """This process's ancestor chain — the shells running bench.py
    must never be paused (their cmdline can embed arbitrary text)."""
    out = set()
    pid = os.getpid()
    for _ in range(64):
        try:
            with open(f"/proc/{pid}/stat") as fh:
                ppid = int(fh.read().rsplit(")", 1)[1].split()[1])
        except (OSError, IndexError, ValueError):
            break
        if ppid <= 1:
            break
        out.add(ppid)
        pid = ppid
    return out


def _iter_cpu_hog_pids():
    """Yield (pid_str, argv) for every running (not already-stopped)
    CPU-only background job — ONE definition of the walk shared by the
    per-bench pause and the shell plans' window-wide pause, so the two
    can't drift. A pid already in state T is excluded: its pause is
    owned by some outer guard and must not be listed, re-stopped, or
    resumed by anyone else. The environ read (for the CPU-pinned-bench
    rule) happens only after the argv prefilter — scanning every
    process's environ on each call would be waste."""
    skip = _ancestor_pids() | {os.getpid()}
    for pid_s in os.listdir("/proc"):
        if not pid_s.isdigit() or int(pid_s) in skip:
            continue
        try:
            with open(f"/proc/{pid_s}/cmdline", "rb") as fh:
                argv = fh.read().decode(errors="replace").split("\0")
            toks = {t for t in argv if t and len(t) < 64}
            maybe_bench = "raft_tpu.bench" in toks and "run" in toks
            if not (_is_cpu_hog(argv)
                    or (maybe_bench and _is_cpu_pinned_bench(
                        argv, _proc_environ(pid_s)))):
                continue
            with open(f"/proc/{pid_s}/stat") as fh:
                state = fh.read().rsplit(")", 1)[1].split()[0]
            if state == "T":
                continue  # an outer guard owns this pause
            yield pid_s, argv
        except (OSError, IndexError, ValueError):
            continue  # raced with process exit / unreadable proc entry


def _pause_cpu_hogs():
    """SIGSTOP known-CPU-only background jobs for the measurement's
    duration — the single-core host: a background 1M hnswlib sweep
    halved the round-4 headline capture (VERDICT r4). Returns only the
    pids THIS process stopped (already-stopped pids are excluded by
    the walk) so the exit resume can't unpause someone else's guard."""
    import signal

    stopped = []
    for pid_s, argv in _iter_cpu_hog_pids():
        try:
            os.kill(int(pid_s), signal.SIGSTOP)
        except OSError:
            continue  # raced with process exit
        stopped.append(int(pid_s))
        log(f"paused background CPU job {pid_s}: "
            f"{' '.join(t for t in argv if t)[:80]}")
    return stopped


def _resume_pids(pids):
    import signal

    for pid in pids:
        try:
            os.kill(pid, signal.SIGCONT)
        except OSError:
            pass


def parent_main():
    import signal

    # a finally: does not run on an unhandled fatal signal — without
    # these, a driver-side SIGTERM would leave the background jobs
    # frozen forever. An inherited SIG_IGN disposition is respected:
    # under nohup, SIGHUP must stay ignored or a terminal hangup kills
    # the detached measurement this script is documented to survive
    for sig in (signal.SIGTERM, signal.SIGINT, signal.SIGHUP):
        if signal.getsignal(sig) != signal.SIG_IGN:
            signal.signal(sig, lambda s, f: sys.exit(128 + s))
    paused = _pause_cpu_hogs()
    try:
        _parent_main_inner()
    finally:
        _resume_pids(paused)


def _parent_main_inner():
    healthy = _backend_healthy()
    # default deadline scales with the measurement budget: data-gen +
    # compile margin on top of the worst-case measurement loop
    # the default-on-TPU bf16 storage adds two index builds + two
    # full-dataset search compiles of validation work before the first
    # JSON line, so the compile margin doubles unless f32 is forced
    margin = 600 if os.environ.get("BENCH_DTYPE") == "float32" else 1200
    deadline = float(os.environ.get(
        "BENCH_CHILD_DEADLINE", max(1200 + margin, 3 * BUDGET_S + margin)))
    if healthy:
        log("dispatching TPU measurement child")
        rec = _await_child(_spawn_child(cpu=False), deadline)
        if rec is not None:
            print(json.dumps(rec))
            return
        log(f"TPU child produced no result within {deadline:.0f}s; "
            "abandoning it (never killed — relay safety) and falling "
            "back to CPU")
    else:
        log("backend unhealthy after all probe attempts; falling back "
            "to CPU — metric annotated accordingly")
    rec = _await_child(_spawn_child(cpu=True), deadline)
    if rec is None:
        log("CPU fallback child also failed — emitting error metric")
        tag = os.environ.get("BENCH_TAG", "")
        tag = f"_{tag}" if tag else ""
        rec = {"metric": ("brute_force_knn_qps_sift1m_shape"
                          f"_b{BATCH}_k{K}{tag}_failed"),
               "value": 0.0, "unit": "QPS", "vs_baseline": 0.0}
    print(json.dumps(rec))


# ---------------------------------------------------------------------------
# child: the actual measurement
# ---------------------------------------------------------------------------


def child_main():
    log(f"child: importing jax (config {N}x{D}, batch {BATCH}, k {K})")
    # fused_knn sizes tiles from a per-device-generation VMEM budget;
    # a relayed backend with an unrecognized device_kind would fall to
    # the conservative 16 MB and shrink tiles. Pin the measured-safe
    # v5e budget (explicit env still wins).
    os.environ.setdefault("RAFT_TPU_VMEM_MB", "64")
    # persistent compile cache shared with the profile/sweep scripts:
    # re-runs (and the bf16-validation programs) skip recompiles, the
    # relay's highest-risk phase. Non-fatal if the backend can't.
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "results", "jaxcache"))
    import jax
    import jax.numpy as jnp

    from raft_tpu.neighbors import brute_force

    log(f"child backend: {jax.default_backend()}")
    key = jax.random.key(0)
    kd, kq = jax.random.split(key)
    dataset = jax.random.normal(kd, (N, D), jnp.float32)
    queries = jax.random.normal(kq, (BATCH, D), jnp.float32)
    jax.block_until_ready((dataset, queries))
    log("data generated")

    # Storage dtype: bf16 on TPU (the MXU-native layout — halves the
    # HBM stream, the config's bottleneck), f32 on the CPU fallback
    # (emulated bf16 matmuls are slower there) or when BENCH_DTYPE
    # forces it. bf16 "exactness" is validated below against true-f32
    # ids and the run falls back to f32 if recall@K slips under 0.99.
    want = os.environ.get("BENCH_DTYPE")
    if want not in (None, "float32", "bfloat16"):
        log(f"unrecognized BENCH_DTYPE={want!r}; using the default")
        want = None
    if want is None:
        want = "float32" if jax.default_backend() == "cpu" else "bfloat16"
    storage = jnp.float32 if want == "float32" else jnp.bfloat16

    recall = None
    bf16_fell_back = False
    if storage == jnp.bfloat16:
        from raft_tpu.utils import eval_recall

        index32 = brute_force.build(None, dataset)
        d32, ids32 = brute_force.search(None, index32, queries, K,
                                        db_tile=262144)
        index = brute_force.build(None, dataset, storage_dtype=storage)
        d16, ids16 = brute_force.search(None, index, queries, K,
                                        db_tile=262144)
        import numpy as np
        # tie-aware: a different id at an equal distance is not a miss.
        # eps=1e-2 relative, not the 1e-3 default: the actual distances
        # carry bf16 rounding (~0.4% relative), so a true tie shows up
        # at sub-percent, not sub-tenth-percent, agreement
        recall, _, _ = eval_recall(np.asarray(ids32), np.asarray(ids16),
                                   np.asarray(d32), np.asarray(d16),
                                   eps=1e-2)
        recall = float(recall)
        log(f"bf16 recall@{K} vs exact f32 ids: {recall:.4f}")
        if recall < 0.99:
            log("bf16 recall under 0.99 — falling back to f32 storage")
            index, recall = index32, None
            bf16_fell_back = True
        del index32
    else:
        index = brute_force.build(None, dataset, storage_dtype=storage)
    jax.block_until_ready(index.norms)
    log(f"index built (storage {index.dataset.dtype}, norms cached)")

    # Serving path: AOT-warm the batch's bucket, then measure through
    # the compiled executable — the steady state a frontend would see.
    # The executor's counters ride along in the JSON line so the bench
    # trajectory catches recompile regressions (a healthy run compiles
    # during warmup only; cache_hits ≈ the iteration count).
    from raft_tpu import SearchExecutor

    executor = SearchExecutor()
    t_warm = time.perf_counter()
    executor.warmup(index, buckets=(executor.bucket_for(BATCH),), k=K,
                    db_tile=262144)
    warmup_seconds = time.perf_counter() - t_warm
    log(f"executor warmup: {warmup_seconds:.2f}s "
        f"({executor.stats.compile_count} compiles)")

    def run():
        return executor.search(index, queries, K, db_tile=262144)

    # Two-stage measurement, robust to mid-measurement relay wedges
    # (the parent keeps the LAST parseable JSON line captured, so a
    # hang after the first print still yields a result):
    #   1. pipelined dispatch timing — known-safe, printed immediately.
    #      Its per-iteration number includes the relay's serialized
    #      per-dispatch gap (~0.5-4 ms depending on session), so it
    #      UNDERSTATES on-chip throughput.
    #   2. slope timing — the fused kernel's `passes` mode repeats the
    #      dataset stream M times inside ONE dispatch (grid wrap, same
    #      compiled shape family as a normal call); per-pass time from
    #      the slope between two pass counts cancels the overhead.
    from raft_tpu.bench.prims import timeit_slope, timeit_stats

    tag = os.environ.get("BENCH_TAG", "")
    tag = f"_{tag}" if tag else ""
    suffix = os.environ.get("BENCH_SUFFIX", "")
    # when BENCH_DTYPE=bfloat16 was explicitly requested but validation
    # forced f32 storage, say so in the metric name — otherwise an
    # external tag like BENCH_TAG=bf16 would label an f32 measurement
    # as bf16 with no machine-readable hint (ADVICE r3)
    if index.dataset.dtype == jnp.bfloat16:
        sdt = "_bf16"
    elif bf16_fell_back and os.environ.get("BENCH_DTYPE") == "bfloat16":
        sdt = "_f32fallback"
    else:
        sdt = ""
    metric = (f"brute_force_knn_qps_sift1m_shape_b{BATCH}_k{K}{sdt}"
              f"{tag}{suffix}")

    last_rec = {}

    def emit(dt):
        # vs_baseline stays normalized by the f32-config roofline: the
        # problem solved (same vectors, queries, k, recall~1) is the
        # reference config; bf16 storage is this framework's internal
        # layout choice, and its measured recall is reported alongside
        qps = BATCH / dt
        rec = {
            "metric": metric,
            "value": round(qps, 2),
            "unit": "QPS",
            "vs_baseline": round(qps / ROOFLINE_QPS, 4),
            "storage_dtype": str(index.dataset.dtype),
            "compile_count": executor.stats.compile_count,
            "cache_hits": executor.stats.cache_hits,
            "warmup_seconds": round(warmup_seconds, 3),
        }
        if recall is not None:
            rec["recall_at_k_vs_f32_exact"] = round(recall, 4)
        last_rec.clear()
        last_rec.update(rec)
        print(json.dumps(rec), flush=True)

    stats = timeit_stats(run, BUDGET_S)
    dt = stats["best_s"]
    log(f"single-iter estimate {stats['single_iter_est_s'] * 1e3:.1f} ms; "
        f"{stats['batches']} batches of {stats['pipe']}, "
        f"best {dt * 1e3:.2f} ms/iter, "
        f"median {stats['median_s'] * 1e3:.2f} ms/iter")
    emit(dt)

    from raft_tpu.neighbors.brute_force import _use_fused_kernel
    from raft_tpu.ops.fused_topk import fused_knn

    if _use_fused_kernel(index.metric, K, BATCH):
        def make_passes(m):
            return lambda: fused_knn(queries, index.dataset, K,
                                     index.metric,
                                     dataset_norms=index.norms, passes=m)

        try:
            from raft_tpu.bench.prims import slope_passes

            lo, hi = slope_passes(index.dataset.dtype)
            sl = timeit_slope(make_passes, lo, hi)
            log(f"slope timing: T({sl['m1']})={sl['t1_s'] * 1e3:.1f} ms, "
                f"T({sl['m2']})={sl['t2_s'] * 1e3:.1f} ms -> "
                f"{sl['slope_s'] * 1e3:.2f} ms/iter")
            # sanity gates: no slower than the dispatch-bound number it
            # refines, and no faster than 1.1x the device HBM roofline
            # in REAL bytes — a noise-dominated slope must not
            # overwrite the honest pipelined result. (The old 2 TB/s
            # ceiling let a physically impossible bf16 slope through in
            # round 3; any stream "faster" than the roofline is jitter,
            # not throughput.)
            itemsize = index.dataset.dtype.itemsize
            floor_s = (N * D * itemsize) / (1.1 * V5E_HBM_BYTES_PER_S)
            if floor_s <= sl["slope_s"] <= dt * 1.2:
                emit(min(sl["slope_s"], dt))
            else:
                log(f"slope {sl['slope_s'] * 1e3:.3f} ms outside "
                    f"[{floor_s * 1e3:.3f}, {dt * 1.2 * 1e3:.3f}] ms; "
                    "keeping pipelined result")
        except Exception as e:  # noqa: BLE001 — keep pipelined result
            log(f"slope timing failed ({e}); keeping pipelined result")
    else:
        log("fused kernel not in play for this config; keeping "
            "pipelined result")

    # opt-in rider: IVF-Flat probe-scan engine sweep with
    # distance-to-roofline annotations; the enriched record re-emits
    # with the headline fields intact (the parent keeps the LAST line)
    # Each rider FOLDS its block into last_rec before printing, so the
    # final JSON line — the one ci/bench_compare.py reads — carries
    # EVERY rider that ran. (Before PR 12 each rider copied only the
    # headline record: with BENCH_SERVING and BENCH_BQ both pinned,
    # the last line held just "bq" and every serving.* tolerance band
    # was silently ungated — compare() skips baseline-missing columns.)
    if os.environ.get("BENCH_IVF_SWEEP") == "1" and last_rec:
        try:
            last_rec["ivf_sweep"] = _ivf_engine_sweep()
            print(json.dumps(last_rec), flush=True)
        except Exception as e:  # noqa: BLE001 — keep headline record
            log(f"ivf engine sweep failed ({e}); keeping headline record")

    # opt-in rider: mesh-native serving — list-sharded IVF through the
    # mesh-aware executor across every visible chip
    if os.environ.get("BENCH_MULTICHIP") == "1" and last_rec:
        try:
            last_rec["multichip"] = _multichip_rider()
            print(json.dumps(last_rec), flush=True)
        except Exception as e:  # noqa: BLE001 — keep headline record
            log(f"multichip rider failed ({e}); keeping headline record")

    # opt-in rider: the request frontend — bursty open-loop load
    # through the DynamicBatcher vs one-request-per-call dispatch
    if os.environ.get("BENCH_SERVING") == "1" and last_rec:
        try:
            last_rec["serving"] = _serving_rider()
            print(json.dumps(last_rec), flush=True)
        except Exception as e:  # noqa: BLE001 — keep headline record
            log(f"serving rider failed ({e}); keeping headline record")

    # opt-in rider: RaBitQ IVF-BQ — fused estimate-then-rerank vs the
    # legacy estimate+refine path, with one-stream byte accounting
    if os.environ.get("BENCH_BQ") == "1" and last_rec:
        try:
            last_rec["bq"] = _bq_rider()
            print(json.dumps(last_rec), flush=True)
        except Exception as e:  # noqa: BLE001 — keep headline record
            log(f"bq rider failed ({e}); keeping headline record")

    # opt-in rider: graftbeam — the rebuilt CAGRA serving path, three
    # seed/traversal arms on one index with modeled gather bytes
    if os.environ.get("BENCH_CAGRA") == "1" and last_rec:
        try:
            last_rec["cagra"] = _cagra_rider()
            print(json.dumps(last_rec), flush=True)
        except Exception as e:  # noqa: BLE001 — keep headline record
            log(f"cagra rider failed ({e}); keeping headline record")

    # opt-in rider: grafttier — hot/cold tiered storage under the
    # dual-roofline accounting, with placement epochs live
    if os.environ.get("BENCH_TIERED") == "1" and last_rec:
        try:
            last_rec["tiered"] = _tiered_rider()
            print(json.dumps(last_rec), flush=True)
        except Exception as e:  # noqa: BLE001 — keep headline record
            log(f"tiered rider failed ({e}); keeping headline record")

    # opt-in rider: graftroute — the fleet router through the
    # device-free N-replica harness: steer/fan-out bit-identity,
    # bf16-wire recall, and the modeled merge-payload bytes
    if os.environ.get("BENCH_FLEET") == "1" and last_rec:
        try:
            last_rec["fleet"] = _fleet_rider()
            print(json.dumps(last_rec), flush=True)
        except Exception as e:  # noqa: BLE001 — keep headline record
            log(f"fleet rider failed ({e}); keeping headline record")


def _ivf_engine_sweep():
    """BENCH_IVF_SWEEP=1 rider: A/B the IVF-Flat probe-scan engines
    (pallas list-major / xla list-major / legacy rank-major) through
    the serving path. Each case carries the modeled probe-scan HBM
    bytes (gathered lists for rank-major, the probed-list union
    streamed once for list-major) converted to achieved GB/s, next to
    a ``stream_read_sum`` roofline probe of the same packed tensor —
    so the BENCH json shows distance-to-roofline, not just wall time.
    Env knobs: BENCH_IVF_N / BENCH_IVF_LISTS / BENCH_IVF_PROBES /
    BENCH_IVF_SECONDS (per-case budget)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu import SearchExecutor
    from raft_tpu.bench.prims import timeit_stats
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.ops.fused_topk import stream_read_sum
    from raft_tpu.ops.ivf_scan import resolve_scan_engine, unique_lists

    n = int(os.environ.get("BENCH_IVF_N", 200_000))
    n_lists = int(os.environ.get("BENCH_IVF_LISTS", 256))
    n_probes = int(os.environ.get("BENCH_IVF_PROBES", 20))
    budget = float(os.environ.get("BENCH_IVF_SECONDS", 8))
    kd, kq = jax.random.split(jax.random.key(1))
    x = jax.random.normal(kd, (n, D), jnp.float32)
    queries = jax.random.normal(kq, (BATCH, D), jnp.float32)
    log(f"ivf sweep: building index ({n}x{D}, {n_lists} lists)")
    index = ivf_flat.build(None, ivf_flat.IvfFlatIndexParams(
        n_lists=n_lists, kmeans_n_iters=10), x)
    m = index.max_list_size
    itemsize = index.data.dtype.itemsize
    jax.block_until_ready(index.data)

    # roofline: a pure streamed read of the packed list tensor — the
    # ceiling every scan engine is judged against
    flat = index.data.reshape(n_lists * m, D)
    interp = jax.default_backend() != "tpu"
    st = timeit_stats(lambda: stream_read_sum(flat, interpret=interp),
                      min(budget, 6.0))
    roof_gbps = flat.size * itemsize / st["best_s"] / 1e9
    log(f"ivf sweep roofline (stream_read_sum): {roof_gbps:.1f} GB/s")

    # probed-union size for the list-major bytes model
    qf = queries.astype(jnp.float32)
    ip = qf @ index.centers.T
    score = -(index.center_norms[None, :] - 2.0 * ip)
    probes = jax.lax.top_k(score, n_probes)[1].astype(jnp.int32)
    n_union = int((np.asarray(unique_lists(probes, n_lists))
                   < n_lists).sum())

    slot_bytes = D * itemsize + 8          # data row + norm + id
    cases = []
    for engine in ("pallas", "xla", "rank"):
        resolved = resolve_scan_engine(engine, data=index.data, k=K)
        p = ivf_flat.IvfFlatSearchParams(n_probes=n_probes,
                                         scan_engine=engine)
        ex = SearchExecutor()
        ex.warmup(index, buckets=(ex.bucket_for(BATCH),), k=K, params=p)
        stats = timeit_stats(
            lambda: ex.search(index, queries, K, params=p), budget)
        dt = stats["best_s"]
        bytes_model = (BATCH * n_probes * m * slot_bytes
                       if resolved == "rank"
                       else n_union * m * slot_bytes)
        gbps = bytes_model / dt / 1e9
        cases.append({
            "engine": engine, "resolved": resolved,
            "best_s": round(dt, 6), "qps": round(BATCH / dt, 2),
            "model_bytes": bytes_model,
            "achieved_gbps": round(gbps, 2),
            "vs_roofline": round(gbps / roof_gbps, 4),
        })
        log(f"ivf sweep {engine}->{resolved}: {dt * 1e3:.2f} ms/iter, "
            f"{gbps:.1f} GB/s ({gbps / roof_gbps:.3f} of roofline)")
    return {"n": n, "dim": D, "n_lists": n_lists, "n_probes": n_probes,
            "batch": BATCH, "max_list_size": m, "union_lists": n_union,
            "roofline_gbps": round(roof_gbps, 2), "cases": cases}


def _multichip_rider():
    """BENCH_MULTICHIP=1 rider: the mesh-native serving path — a
    list-sharded IVF-Flat index over EVERY visible chip, searched
    through the mesh-aware ``SearchExecutor``. Emits per-chip and
    aggregate QPS per scan engine, compile counts (executor bookkeeping
    + jax's backend-compile ground truth, so a recompiling steady state
    is machine-visible), and the modeled lean collective payloads
    (O(q · n_probes) probe candidates, O(q · k) merge, per wire_dtype)
    next to the dense coarse-block baseline they replaced.

    graftwire adds two sub-blocks: ``kmeans_wire`` (quantized-vs-f32
    distributed k-means build A/B — per-iteration wall clock, modeled
    wire bytes, inertia delta per reduce wire) and ``grid2d`` (the 2-D
    query×list grid under mixed-size load, with the
    compiles-during-load column that pins the zero-recompile steady
    state). Env knobs: BENCH_MC_N / BENCH_MC_LISTS / BENCH_MC_PROBES /
    BENCH_MC_SECONDS (per-case budget) / BENCH_MC_KMEANS_ITERS /
    BENCH_MC_KMEANS_ROWS."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu import SearchExecutor
    from raft_tpu.bench.prims import timeit_stats
    from raft_tpu.comms import local_comms
    from raft_tpu.core import tracing
    from raft_tpu.distributed import ivf as dist_ivf
    from raft_tpu.neighbors import ivf_flat

    n = int(os.environ.get("BENCH_MC_N", 200_000))
    n_lists = int(os.environ.get("BENCH_MC_LISTS", 512))
    n_probes = int(os.environ.get("BENCH_MC_PROBES", 20))
    budget = float(os.environ.get("BENCH_MC_SECONDS", 8))
    n_dev = len(jax.devices())
    comms = local_comms()
    tracing.install_xla_compile_listener()

    kd, kq = jax.random.split(jax.random.key(2))
    x = jax.random.normal(kd, (n, D), jnp.float32)
    queries = jax.random.normal(kq, (BATCH, D), jnp.float32)
    log(f"multichip: building sharded index ({n}x{D}, {n_lists} lists, "
        f"{n_dev} chips)")
    tracing.reset_counters("distributed.build.")
    index = dist_ivf.build(None, comms, ivf_flat.IvfFlatIndexParams(
        n_lists=n_lists, kmeans_n_iters=10), x)
    build_peak = tracing.get_counter(
        "distributed.build.peak_deal_block_bytes")

    cases = []
    for engine, wire in (("auto", "f32"), ("auto", "bf16"),
                         ("rank", "f32")):
        from raft_tpu.ops.ivf_scan import resolve_scan_engine

        resolved = resolve_scan_engine(engine, data=index.data, k=K)
        p = ivf_flat.IvfFlatSearchParams(n_probes=n_probes,
                                         scan_engine=engine)
        ex = SearchExecutor()
        ex.warmup(index, buckets=(ex.bucket_for(BATCH),), k=K, params=p,
                  wire_dtype=wire)
        # one primer call so the per-batch-size pad/place micro-programs
        # compile outside the measured (and counted) window
        ex.search(index, queries, K, params=p, wire_dtype=wire)
        backend0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        stats = timeit_stats(
            lambda: ex.search(index, queries, K, params=p,
                              wire_dtype=wire), budget)
        dt = stats["best_s"]
        model = dist_ivf.collective_payload_model(
            BATCH, K, n_probes, index.n_lists, comms.size, wire)
        cases.append({
            "engine": engine, "resolved": resolved, "wire_dtype": wire,
            "best_s": round(dt, 6),
            "qps": round(BATCH / dt, 2),
            "qps_per_chip": round(BATCH / dt / n_dev, 2),
            "compile_count": ex.stats.compile_count,
            "backend_compiles_during_measure": (
                tracing.get_counter(tracing.XLA_COMPILE_COUNT) - backend0),
            "modeled_collective_bytes": model,
        })
        log(f"multichip {engine}/{wire}->{resolved}: "
            f"{dt * 1e3:.2f} ms/iter, {BATCH / dt / n_dev:.1f} QPS/chip, "
            f"coarse {model['coarse_bytes']}B vs dense "
            f"{model['dense_coarse_bytes']}B, merge "
            f"{model['merge_bytes']}B")
    # graftwire rider: quantized-vs-f32 distributed k-means build A/B —
    # per-iteration wall clock, the payload model's per-iteration wire
    # bytes, and the inertia delta the narrow wire costs
    from raft_tpu.distributed import kmeans as dist_kmeans

    km_iters = int(os.environ.get("BENCH_MC_KMEANS_ITERS", 10))
    km_clusters = min(n_lists, 256)
    km_rows = int(os.environ.get("BENCH_MC_KMEANS_ROWS", 32_768))
    km_rows = -(-km_rows // comms.size) * comms.size
    kx = jax.random.normal(jax.random.key(5), (km_rows, D),
                           jnp.float32)
    kmeans_cases = {}
    inertia_f32 = None
    for wire in ("f32", "bf16", "int8"):
        def _fit(wire=wire):
            c, i = dist_kmeans.fit(comms, kx, km_clusters,
                                   n_iters=km_iters, wire_dtype=wire)
            jax.block_until_ready(c)
            return i
        inertia = float(_fit())  # warm the compile, capture inertia
        stats = timeit_stats(_fit, budget / 2)
        per_iter = stats["best_s"] / km_iters
        if wire == "f32":
            inertia_f32 = inertia
        model = dist_kmeans.collective_payload_model(km_clusters, D,
                                                     wire)
        # dict keyed by wire (not a list) so the CI gate's dotted
        # tolerance paths reach the columns
        kmeans_cases[wire] = {
            "per_iter_s": round(per_iter, 6),
            "modeled_iter_wire_bytes": model["iter_bytes"],
            "inertia": round(inertia, 2),
            "inertia_vs_f32": round(inertia / inertia_f32, 6),
        }
        log(f"multichip kmeans {wire}: {per_iter * 1e3:.2f} ms/iter, "
            f"{model['iter_bytes']}B/iter wire, inertia x"
            f"{inertia / inertia_f32:.4f}")

    # graftwire rider: the 2-D query×list grid serves bucketed with
    # ZERO steady-state compiles — the compiles-during-load column is
    # the acceptance gate (it used to recompile per batch size)
    grid2d = None
    if n_dev >= 4 and n_dev % 2 == 0:
        from jax.sharding import Mesh

        from raft_tpu.comms.comms import Comms

        devs = np.array(jax.devices()).reshape(n_dev // 2, 2)
        comms2 = Comms(Mesh(devs, ("lists", "queries")), "lists")
        index2 = dist_ivf.build(None, comms2, ivf_flat.IvfFlatIndexParams(
            n_lists=n_lists, kmeans_n_iters=4), x)
        p2 = ivf_flat.IvfFlatSearchParams(n_probes=n_probes,
                                          scan_engine="auto")
        ex2 = SearchExecutor()
        ex2.warmup(index2, buckets=(ex2.bucket_for(BATCH),), k=K,
                   params=p2, query_axis="queries")
        qs = np.asarray(queries)
        # primer sweep compiles the per-size pad micro-programs
        sizes = tuple(sorted({BATCH, max(1, BATCH - 3),
                              BATCH // 2 + 1}))
        for m in sizes:
            ex2.search(index2, qs[:m], K, params=p2,
                       query_axis="queries")
        backend0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        t0 = time.perf_counter()
        rounds = 0
        while time.perf_counter() - t0 < budget / 2:
            for m in sizes:
                jax.block_until_ready(ex2.search(
                    index2, qs[:m], K, params=p2,
                    query_axis="queries")[0])
            rounds += 1
        dt = (time.perf_counter() - t0) / max(rounds * len(sizes), 1)
        grid2d = {
            "mesh_shape": [n_dev // 2, 2],
            "best_s": round(dt, 6),
            "qps": round(BATCH / dt, 2),
            "compiles_during_load": (
                tracing.get_counter(tracing.XLA_COMPILE_COUNT)
                - backend0),
        }
        log(f"multichip 2-D grid {n_dev // 2}x2: {dt * 1e3:.2f} ms/iter"
            f", {grid2d['compiles_during_load']:.0f} compiles under "
            "mixed-size load")

    return {"n": n, "dim": D, "n_lists": n_lists, "n_probes": n_probes,
            "batch": BATCH, "n_chips": n_dev,
            "build_peak_deal_block_bytes": int(build_peak),
            "cases": cases,
            "kmeans_wire": {"n_rows": int(kx.shape[0]),
                            "n_clusters": km_clusters,
                            "n_iters": km_iters,
                            "cases": kmeans_cases},
            "grid2d": grid2d}


def _bq_rider():
    """BENCH_BQ=1 rider: the RaBitQ IVF-BQ A/B — the fused
    estimate-then-rerank scan (exact distances, one list-major
    stream) against the legacy estimate+refine two-pass path at equal
    over-fetch, with the byte accounting the acceptance criterion is
    about:

    - ``bytes_per_vector_codes`` vs ``bytes_per_vector_raw``: the scan
      stream's compression (packed sign words + correction scalars vs
      f32 rows);
    - ``fused_model_bytes``: ONE stream of codes + corrections + the
      raw vectors of *survivor blocks only* (the prune decisions are
      replayed host-side with the engines' own margin rule), next to
      ``two_pass_model_bytes`` (estimate stream + an unconditional
      exact pass over every probed block). ``survivor_row_fraction``
      is the prune rule's deterministic CI signal — block-level
      skips (`one_stream_fraction` < 1) only bite at scale, where a
      block's every probing query has a tight running k-th;
    - achieved GB/s of the fused search against a ``stream_read_sum``
      roofline of the raw-vector tensor.

    Env knobs: BENCH_BQ_N / BENCH_BQ_LISTS / BENCH_BQ_PROBES /
    BENCH_BQ_BITS / BENCH_BQ_SECONDS."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu import SearchExecutor
    from raft_tpu.bench.prims import timeit_stats
    from raft_tpu.neighbors import brute_force, ivf_bq
    from raft_tpu.neighbors.ivf_bq import (
        _unpack_pm1,
        estimator_margin,
        overfetch_budget,
    )
    from raft_tpu.neighbors.refine import refine
    from raft_tpu.ops.bq_scan import resolve_bq_engine
    from raft_tpu.ops.fused_topk import stream_read_sum
    from raft_tpu.ops.ivf_scan import unique_lists

    n = int(os.environ.get("BENCH_BQ_N", 100_000))
    n_lists = int(os.environ.get("BENCH_BQ_LISTS", 128))
    n_probes = int(os.environ.get("BENCH_BQ_PROBES", 16))
    bits = int(os.environ.get("BENCH_BQ_BITS", 1))
    budget = float(os.environ.get("BENCH_BQ_SECONDS", 8))
    kd, kq = jax.random.split(jax.random.key(7))
    x = jax.random.normal(kd, (n, D), jnp.float32)
    queries = jax.random.normal(kq, (BATCH, D), jnp.float32)
    log(f"bq rider: building RaBitQ index ({n}x{D}, {n_lists} lists, "
        f"{bits} bit/dim + rerank plane)")
    index = ivf_bq.build(None, ivf_bq.IvfBqIndexParams(
        n_lists=n_lists, bits=bits, kmeans_n_iters=10), x)
    m = index.max_list_size
    de = index.dim_ext
    words = index.codes.shape[2]
    jax.block_until_ready(index.data)
    _, gt = brute_force.knn(None, x, queries, K)
    gt = np.asarray(gt)

    def recall(ids):
        ids = np.asarray(ids)
        return float(np.mean([len(set(ids[r]) & set(gt[r])) / K
                              for r in range(ids.shape[0])]))

    # roofline: a pure streamed read of the raw-vector plane — the
    # ceiling the rerank stream is judged against
    flat = index.data.reshape(n_lists * m, D)
    interp = jax.default_backend() != "tpu"
    st = timeit_stats(lambda: stream_read_sum(flat, interpret=interp),
                      min(budget, 6.0))
    roof_gbps = flat.size * 4 / st["best_s"] / 1e9
    log(f"bq roofline (stream_read_sum raw vectors): "
        f"{roof_gbps:.1f} GB/s")

    # per-vector scan-stream bytes: packed sign words + the three
    # correction scalars (+ per-level scales) + the id slot
    code_slot = words * 4 + (bits + 2) * 4 + 4
    raw_slot = D * 4 + 8                    # f32 row + norm + id

    # probed-union + host-side replay of the fused prune (the
    # engines' margin rule) -> survivor blocks for the byte model
    qf = np.asarray(queries, np.float32)
    centers = np.asarray(index.centers)
    qc2_all = (np.sum(qf * qf, 1)[:, None]
               + np.sum(centers * centers, 1)[None, :]
               - 2.0 * qf @ centers.T)
    probes = jnp.asarray(np.argsort(qc2_all, axis=1)[:, :n_probes],
                         jnp.int32)
    uniq = np.asarray(unique_lists(probes, n_lists))
    uniq = uniq[uniq < n_lists]
    rot = np.asarray(index.rotation)
    qrot = qf @ rot.T
    crot = centers @ rot.T
    rnorm = np.asarray(index.rnorm)
    cfac = np.asarray(index.cfac)
    errw = np.asarray(index.errw)
    ids_plane = np.asarray(index.indices)
    pm1 = np.asarray(_unpack_pm1(index.codes, jnp.float32)).reshape(
        n_lists, m, bits, de)
    recon = ((rnorm[..., None] * cfac)[..., None] * pm1).sum(axis=2)
    xnorms = np.asarray(index.data_norms)
    xplane = np.asarray(index.data)
    probed = np.zeros((BATCH, n_lists), bool)
    np.put_along_axis(probed, np.asarray(probes), True, axis=1)
    kth = np.full((BATCH,), np.inf, np.float32)
    topk = [[] for _ in range(BATCH)]
    survivor_blocks = 0
    survivor_rows = 0
    probed_rows = 0
    for lid in uniq:
        qt = qrot - crot[lid]
        qc2 = np.sum(qt * qt, 1, keepdims=True)
        delta = ((qt.max(1, keepdims=True) - qt.min(1, keepdims=True))
                 / 15.0)
        est = qc2 + np.square(rnorm[lid])[None, :] \
            - 2.0 * qt @ recon[lid].T
        margin = np.asarray(estimator_margin(
            jnp.asarray(np.sqrt(qc2)), jnp.asarray(rnorm[lid])[None],
            jnp.asarray(errw[lid])[None], jnp.asarray(delta), de, 3.0))
        ok = (ids_plane[lid][None, :] >= 0) & probed[:, lid : lid + 1]
        cand = ((est - margin) < kth[:, None]) & ok
        survivor_rows += int(cand.sum())
        probed_rows += int(ok.sum())
        if not cand.any():
            continue
        survivor_blocks += 1
        exact = (np.sum(qf * qf, 1, keepdims=True) + xnorms[lid][None]
                 - 2.0 * qf @ xplane[lid].T)
        for r in range(BATCH):
            if cand[r].any():
                topk[r].extend(exact[r][cand[r]].tolist())
                topk[r] = sorted(topk[r])[:K]
                if len(topk[r]) == K:
                    kth[r] = topk[r][-1]
    # the byte models: fused = ONE list-major stream (codes +
    # corrections for every probed block, raw vectors only for blocks
    # the prune left survivors in — per-block DMA granularity, the
    # kernel's actual unit); two-pass = reading each probed block
    # TWICE (an estimate pass then a full exact pass — the roofline
    # antipattern the fusion removes). Both are replays of the
    # engines' own margin rule, deterministic under the pinned seeds:
    # the gate pins survivor_row_fraction (margin/prune-math
    # regressions move it), while block-level pruning only bites at
    # scale — many blocks, tight kth — and on the real chip.
    est_stream = len(uniq) * m * code_slot
    fused_model_bytes = est_stream + survivor_blocks * m * raw_slot
    two_pass_model_bytes = est_stream + len(uniq) * m * raw_slot
    row_frac = survivor_rows / max(probed_rows, 1)
    log(f"bq prune replay: {survivor_blocks}/{len(uniq)} blocks, "
        f"{row_frac:.3f} of probed rows kept for exact re-rank")

    engine = resolve_bq_engine("auto", data=index.data, k=K,
                               dim_ext=de, bits=bits)
    p = ivf_bq.IvfBqSearchParams(n_probes=n_probes)
    ex = SearchExecutor()
    ex.warmup(index, buckets=(ex.bucket_for(BATCH),), k=K, params=p)
    stats = timeit_stats(
        lambda: ex.search(index, queries, K, params=p), budget)
    dt = stats["best_s"]
    d_f, i_f = ex.search(index, queries, K, params=p)
    fused_recall = recall(i_f)
    gbps = fused_model_bytes / dt / 1e9
    log(f"bq fused ({engine}): {dt * 1e3:.2f} ms/iter, recall@{K} "
        f"{fused_recall:.4f}, {gbps:.1f} GB/s modeled "
        f"({gbps / roof_gbps:.3f} of roofline)")

    # legacy estimate+refine at the bound-derived over-fetch
    est_index = _dc.replace(index, data=None, data_norms=None)
    fetch = overfetch_budget(est_index, K)
    pe = ivf_bq.IvfBqSearchParams(n_probes=n_probes,
                                  scan_engine="rank")

    def est_refine():
        _, cand = ivf_bq.search(None, pe, est_index, queries, fetch)
        return refine(None, x, queries, cand, K)

    est_stats = timeit_stats(lambda: jax.block_until_ready(
        est_refine()[0]), budget)
    _, i_e = est_refine()
    est_recall = recall(i_e)
    _, i_ek = ivf_bq.search(None, pe, est_index, queries, K)
    log(f"bq estimate+refine (fetch {fetch}): "
        f"{est_stats['best_s'] * 1e3:.2f} ms/iter, recall@{K} "
        f"{est_recall:.4f}; raw estimate@{K} {recall(i_ek):.4f}")

    return {
        "n": n, "dim": D, "dim_ext": de, "n_lists": n_lists,
        "n_probes": n_probes, "bits": bits, "batch": BATCH, "k": K,
        "engine": engine, "max_list_size": m,
        "union_lists": int(len(uniq)),
        "survivor_blocks": int(survivor_blocks),
        "survivor_row_fraction": round(row_frac, 4),
        "bytes_per_vector_codes": code_slot,
        "bytes_per_vector_raw": raw_slot,
        "fused_model_bytes": int(fused_model_bytes),
        "two_pass_model_bytes": int(two_pass_model_bytes),
        "one_stream_fraction": round(
            fused_model_bytes / max(two_pass_model_bytes, 1), 4),
        "roofline_gbps": round(roof_gbps, 2),
        "fused_best_s": round(dt, 6),
        "fused_qps": round(BATCH / dt, 2),
        "fused_recall": round(fused_recall, 4),
        "achieved_gbps": round(gbps, 2),
        "vs_roofline": round(gbps / roof_gbps, 4),
        "estimate_fetch": int(fetch),
        "estimate_refine_best_s": round(est_stats["best_s"], 6),
        "estimate_refine_recall": round(est_recall, 4),
        "estimate_at_k_recall": round(recall(i_ek), 4),
    }


def _cagra_rider():
    """BENCH_CAGRA=1 rider: the graftbeam A/B — three arms of the
    rebuilt CAGRA serving path on ONE index (seed plane + BQ record
    plane built once):

    - ``pool``: the legacy query-aware strided seed pool at a big
      ``seed_pool`` budget;
    - ``coarse``: IVF-coarse seeding from the build-time k-means seed
      plane at an 8x smaller ``seed_pool`` — the frontier-shift claim
      is ``pool_shrink_factor`` next to the two recall columns;
    - ``coarse_bq``: coarse seeding + BQ-coded traversal — graph
      neighbors scored by the packed-record XOR+popcount estimate,
      exact distances DMA'd only for estimate-survivors.

    Each arm reports recall@K, QPS, and a deterministic modeled
    gather-byte account (seed-stage rows + per-iteration candidate
    gathers; the BQ arm charges the record plane ONCE — its tile
    loads are VMEM-resident — plus the survivor fraction of raw-row
    DMAs, where the survivor fraction is a host-side replay of the
    shared estimator margin rule against each query's TRUE k-th
    distance) against a ``stream_read_sum`` roofline. ``compiles_during_measure`` must stay 0 — every arm
    serves AOT through the executor — and ``raggable`` records that
    the default-params CAGRA plan joins the ragged family (the PR 15
    fallback pin retired).

    Env knobs: BENCH_CAGRA_N / BENCH_CAGRA_DEG / BENCH_CAGRA_BITS /
    BENCH_CAGRA_POOL / BENCH_CAGRA_COARSE_POOL / BENCH_CAGRA_SECONDS.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu import SearchExecutor
    from raft_tpu.bench.prims import timeit_stats
    from raft_tpu.core import tracing
    from raft_tpu.neighbors import brute_force, cagra
    from raft_tpu.ops.bq_scan import (
        _block_estimate,
        auto_query_bits,
        unpack_bq_records,
    )
    from raft_tpu.ops.fused_topk import stream_read_sum

    n = int(os.environ.get("BENCH_CAGRA_N", 100_000))
    deg = int(os.environ.get("BENCH_CAGRA_DEG", 32))
    bits = int(os.environ.get("BENCH_CAGRA_BITS", 2))
    pool_big = int(os.environ.get("BENCH_CAGRA_POOL", 8192))
    pool_small = int(os.environ.get("BENCH_CAGRA_COARSE_POOL", 1024))
    budget = float(os.environ.get("BENCH_CAGRA_SECONDS", 8))
    kd, kq = jax.random.split(jax.random.key(11))
    x = jax.random.normal(kd, (n, D), jnp.float32)
    queries = jax.random.normal(kq, (BATCH, D), jnp.float32)
    log(f"cagra rider: building graph index ({n}x{D}, degree {deg}, "
        f"seed plane + {bits}-bit BQ record plane)")
    index = cagra.build(None, cagra.CagraIndexParams(
        graph_degree=deg, bq_bits=bits), x)
    jax.block_until_ready(index.graph)
    _, gt = brute_force.knn(None, x, queries, K)
    gt = np.asarray(gt)

    def recall(ids):
        ids = np.asarray(ids)
        return float(np.mean([len(set(ids[r]) & set(gt[r])) / K
                              for r in range(ids.shape[0])]))

    itemsize = jnp.dtype(index.dataset.dtype).itemsize
    interp = jax.default_backend() != "tpu"
    st = timeit_stats(
        lambda: stream_read_sum(index.dataset, interpret=interp),
        min(budget, 6.0))
    roof_gbps = index.dataset.size * itemsize / st["best_s"] / 1e9
    log(f"cagra roofline (stream_read_sum dataset): "
        f"{roof_gbps:.1f} GB/s")

    # survivor fraction for the BQ arm: replay the SHARED estimator
    # (the exact _block_estimate math both engines run) on a strided
    # row sample against each query's TRUE k-th exact distance — a
    # deterministic margin/prune-math signal, like the bq rider's
    words = bits * ((D + 31) // 32)
    de = ((D + 31) // 32) * 32
    codes, rnorm, cfac, errw = unpack_bq_records(
        index.bq_records, n, words, bits)
    samp = jnp.arange(0, n, max(1, n // 4096))[:4096]
    qrot = cagra._rotate_queries(queries, index.bq_rotation)
    est, margin = _block_estimate(
        qrot, index.bq_center_rot,
        rnorm[samp][None, :], errw[samp][None, :],
        jnp.transpose(cfac[samp]), codes[samp],
        dim_ext=de, bits=bits, query_bits=auto_query_bits(bits),
        epsilon=cagra.CagraSearchParams().bq_epsilon, ip_metric=False)
    qf = np.asarray(queries, np.float32)
    xf = np.asarray(index.dataset, np.float32)
    d_all = (np.sum(qf * qf, 1)[:, None] + np.sum(xf * xf, 1)[None, :]
             - 2.0 * qf @ xf.T)
    kth = np.partition(d_all, K - 1, axis=1)[:, K - 1:K]
    surv_frac = float(np.mean(
        (np.asarray(est) - np.asarray(margin)) < kth))
    log(f"cagra bq estimator replay: survivor fraction "
        f"{surv_frac:.4f} over {int(samp.shape[0])} sampled rows")

    cap = int(index.seed_members.shape[1])
    n_lists = int(index.seed_centers.shape[0])
    arms = {
        "pool": cagra.CagraSearchParams(
            seed_mode="pool", seed_pool=pool_big),
        "coarse": cagra.CagraSearchParams(
            seed_mode="coarse", seed_pool=pool_small),
        "coarse_bq": cagra.CagraSearchParams(
            seed_mode="coarse", seed_pool=pool_small,
            bq_traversal="on"),
    }
    tracing.install_xla_compile_listener()
    out = {"n": n, "dim": D, "degree": deg, "bits": bits, "k": K,
           "batch": BATCH, "roofline_gbps": round(roof_gbps, 2),
           "survivor_row_fraction": round(surv_frac, 4),
           "pool_shrink_factor": round(pool_big / pool_small, 2)}
    compiles_total = 0
    for name, p in arms.items():
        ex = SearchExecutor()
        bucket = ex.bucket_for(BATCH)
        ex.warmup(index, buckets=(bucket,), k=K, params=p)
        b0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        stats = timeit_stats(
            lambda: ex.search(index, queries, K, params=p), budget)
        compiles = int(tracing.get_counter(tracing.XLA_COMPILE_COUNT)
                       - b0)
        compiles_total += compiles
        d_a, i_a = ex.search(index, queries, K, params=p)
        cfg = cagra.derive_search_config(p, index, K)
        c_width = cfg["w"] * deg
        # seed stage: pool arm scores `seed_pool` strided raw rows per
        # query; coarse scores the center plane (f32) once per query
        # plus the probed lists' member rows
        if name == "pool":
            seed_bytes = BATCH * min(pool_big, n) * D * itemsize
        else:
            probes = max(1, min(-(-pool_small // cap), n_lists))
            seed_bytes = BATCH * (n_lists * D * 4
                                  + probes * cap * D * itemsize)
        # traversal: C candidate gathers per iteration per query. The
        # BQ arm's record-tile loads are VMEM-resident (the plane
        # streams into VMEM ONCE — charged here), so its HBM side is
        # only the survivor fraction of exact-row DMAs
        hops = BATCH * cfg["max_iters"] * c_width
        if name == "coarse_bq":
            trav_bytes = (index.bq_records.size * 4
                          + surv_frac * hops * D * itemsize)
        else:
            trav_bytes = hops * D * itemsize
        model_bytes = int(seed_bytes + trav_bytes)
        dt = stats["best_s"]
        gbps = model_bytes / dt / 1e9
        raggable = ex.ragged_key(index, K, params=p) is not None
        log(f"cagra {name}: {dt * 1e3:.2f} ms/iter, recall@{K} "
            f"{recall(i_a):.4f}, {gbps:.1f} GB/s modeled "
            f"({gbps / roof_gbps:.3f} of roofline), "
            f"{compiles} compiles during measure")
        out[name] = {
            "seed_pool": int(p.seed_pool),
            "recall": round(recall(i_a), 4),
            "best_s": round(dt, 6),
            "qps": round(BATCH / dt, 2),
            "model_bytes": model_bytes,
            "model_gbps": round(gbps, 2),
            "vs_roofline": round(gbps / roof_gbps, 4),
            "compiles_during_measure": compiles,
            "raggable": bool(raggable),
        }
    out["compiles_during_measure"] = compiles_total
    out["raggable"] = int(all(out[a]["raggable"] for a in arms))
    out["bq_byte_reduction"] = round(
        out["coarse"]["model_bytes"]
        / max(out["coarse_bq"]["model_bytes"], 1), 4)
    # pad waste of the bucketed front at this batch size (the ragged
    # family's pad behavior is gated by the serving rider's legs)
    bucket = SearchExecutor().bucket_for(BATCH)
    out["bucket"] = int(bucket)
    out["pad_fraction"] = round(1.0 - BATCH / bucket, 4)
    return out


def _tiered_rider():
    """BENCH_TIERED=1 rider: grafttier's billion-scale tiered storage
    under the TPU-KNN DUAL-roofline accounting. Half the lists go
    cold (host-resident where the backend supports memory kinds; the
    honest device fallback elsewhere — ``host_resident`` says which),
    and the record carries:

    - the hot stream's achieved GB/s next to an HBM roofline
      (``stream_read_sum`` over the hot plane) and the cold stream's
      achieved GB/s next to a HOST-link roofline (a timed
      host→device transfer of one cold-tier-sized buffer — the
      ceiling the manual-DMA pipeline is judged against);
    - ``bit_identical`` (tiered executor results vs the all-HBM
      index — the correctness gate column);
    - two LIVE placement epochs under a manual clock:
      ``compiles_during_epochs`` (must stay 0 — re-placement only
      permutes the fixed hot slots) and the per-epoch swap bytes
      (deterministic at the pinned config: targeted traffic promotes
      the same lists every run).

    Env knobs: BENCH_TIER_N / BENCH_TIER_LISTS / BENCH_TIER_PROBES /
    BENCH_TIER_SECONDS."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu import SearchExecutor
    from raft_tpu.bench.prims import timeit_stats
    from raft_tpu.core import tracing
    from raft_tpu.neighbors import ivf_flat, tiered
    from raft_tpu.ops.fused_topk import stream_read_sum
    from raft_tpu.ops.ivf_scan import unique_lists
    from raft_tpu.serving.harness import ManualClock
    from raft_tpu.serving.placement import PlacementConfig, TierManager

    n = int(os.environ.get("BENCH_TIER_N", 200_000))
    n_lists = int(os.environ.get("BENCH_TIER_LISTS", 256))
    n_probes = int(os.environ.get("BENCH_TIER_PROBES", 20))
    budget = float(os.environ.get("BENCH_TIER_SECONDS", 8))

    kd, kq = jax.random.split(jax.random.key(7))
    x = jax.random.normal(kd, (n, D), jnp.float32)
    queries = jax.random.normal(kq, (BATCH, D), jnp.float32)
    log(f"tiered rider: building index ({n}x{D}, {n_lists} lists)")
    index = ivf_flat.build(None, ivf_flat.IvfFlatIndexParams(
        n_lists=n_lists, kmeans_n_iters=10), x)
    t = tiered.build_tiered(index, hot_fraction=0.5)
    m = t.max_list_size
    itemsize = 4
    interp = jax.default_backend() != "tpu"

    # --- dual rooflines. HBM: a pure streamed read of the hot plane.
    # Host link: a timed host→device transfer of one cold-tier-sized
    # buffer — the ceiling the cold manual-DMA stream is judged
    # against (on CPU both pools are the same memory; the on-chip
    # numbers are what the evidence debt item collects).
    hot_flat = t.hot_data.reshape(t.n_hot * m, D)
    st = timeit_stats(lambda: stream_read_sum(hot_flat,
                                              interpret=interp),
                      min(budget, 6.0))
    hbm_roof_gbps = hot_flat.size * itemsize / st["best_s"] / 1e9
    cold_host = np.zeros((t.n_cold * m, D), np.float32)
    st = timeit_stats(
        lambda: jax.block_until_ready(jax.device_put(cold_host)),
        min(budget, 6.0))
    host_roof_gbps = cold_host.nbytes / st["best_s"] / 1e9
    log(f"tiered rooflines: HBM {hbm_roof_gbps:.1f} GB/s, host link "
        f"{host_roof_gbps:.1f} GB/s")

    # --- probed-union split for the per-tier byte models (host-side
    # replay of the engines' own coarse selection — deterministic
    # under the pinned seeds)
    qf = queries.astype(jnp.float32)
    ip = qf @ t.centers.T
    score = -(t.center_norms[None, :] - 2.0 * ip)
    probes = jax.lax.top_k(score, n_probes)[1].astype(jnp.int32)
    uniq = np.asarray(unique_lists(probes, n_lists))
    uniq = uniq[uniq < n_lists]
    cold_map = np.asarray(t.cold_slot_map)
    union_cold = int((cold_map[uniq] >= 0).sum())
    union_hot = int(len(uniq) - union_cold)
    # hot stream reads data+norms+ids from HBM; a cold list's data
    # crosses the host link while its norm/id planes stay HBM
    hot_model_bytes = (union_hot * m * (D * itemsize + 8)
                       + union_cold * m * 8)
    cold_model_bytes = union_cold * m * D * itemsize

    # --- serving: tiered executor vs the all-HBM index
    p = tiered.TieredSearchParams(n_probes=n_probes)
    ex = SearchExecutor(probe_accounting=True)
    ex.warmup(t, buckets=(ex.bucket_for(BATCH),), k=K, params=p)
    stats = timeit_stats(
        lambda: ex.search(t, queries, K, params=p), budget)
    dt = stats["best_s"]
    d_t, i_t = ex.search(t, queries, K, params=p)
    pf = ivf_flat.IvfFlatSearchParams(n_probes=n_probes)
    d_f, i_f = ivf_flat.search(None, pf, index, queries, K)
    bit_identical = bool(
        (np.asarray(d_t) == np.asarray(d_f)).all()
        and (np.asarray(i_t) == np.asarray(i_f)).all())
    hot_gbps = hot_model_bytes / dt / 1e9
    cold_gbps = cold_model_bytes / dt / 1e9
    log(f"tiered serving: {dt * 1e3:.2f} ms/iter, bit_identical="
        f"{bit_identical}, hot {hot_gbps:.1f} GB/s "
        f"({hot_gbps / hbm_roof_gbps:.3f} of HBM roofline), cold "
        f"{cold_gbps:.1f} GB/s "
        f"({cold_gbps / host_roof_gbps:.3f} of host roofline)")

    # --- live placement epochs: targeted traffic at two cold lists,
    # one warm epoch (the fixed-width swap programs specialize once),
    # then two gated epochs — zero backend compiles, deterministic
    # swap bytes
    clock = ManualClock()
    mgr = TierManager(t, ex, clock=clock, config=PlacementConfig(
        epoch_every_s=1.0, max_swaps_per_epoch=4))
    centers_np = np.asarray(t.centers)

    def targeted(lid, seed):
        rng = np.random.default_rng(seed)
        return (np.tile(centers_np[lid], (BATCH, 1))
                + 0.01 * rng.standard_normal((BATCH, D))
                ).astype(np.float32)

    ex.search(t, targeted(int(t.cold_lists[0]), 0), K, params=p)
    mgr.epoch()                      # warm the swap programs
    tracing.install_xla_compile_listener()
    c0 = tracing.counters().get(tracing.XLA_COMPILE_COUNT, 0)
    swap_bytes = []
    for step in (1, 2):
        for _ in range(2):
            ex.search(t, targeted(int(t.cold_lists[0]), step), K,
                      params=p)
        b0 = tracing.get_counter("tier.swap_bytes")
        mgr.epoch()
        swap_bytes.append(
            int(tracing.get_counter("tier.swap_bytes") - b0))
        ex.search(t, queries, K, params=p)
    compiles = int(tracing.counters().get(tracing.XLA_COMPILE_COUNT, 0)
                   - c0)
    d_t2, i_t2 = ex.search(t, queries, K, params=p)
    post_identical = bool(
        (np.asarray(d_t2) == np.asarray(d_f)).all()
        and (np.asarray(i_t2) == np.asarray(i_f)).all())
    log(f"tiered epochs: swap bytes {swap_bytes}, compiles during "
        f"epochs {compiles}, post-epoch bit_identical={post_identical}")

    # --- prefetch A/B (PR 18 graftcast): the SAME seeded drifting
    # hot set served twice — reactive epochs vs the forecast-driven
    # prefetcher. A forecast hit moved its block at stage time, so
    # the epoch path's cold-stream bytes (tier.promote_cold_bytes)
    # must STRICTLY drop with the prefetcher on; and after one warm
    # drift cycle (the stage/mix programs specialize once, like the
    # warm epoch above) the measured window must add ZERO backend
    # compiles. Both legs replay identical traffic (pinned rng), so
    # their epochs run identical plans — the bytes column isolates
    # the prefetcher.
    from raft_tpu.serving.prefetch import HITS, ISSUED, MISSES
    from raft_tpu.serving.prefetch import PrefetchConfig

    def _prefetch_leg(with_prefetch):
        t2 = tiered.build_tiered(index, hot_fraction=0.5)
        ex2 = SearchExecutor(probe_accounting=True)
        clk = ManualClock()
        mgr2 = TierManager(t2, ex2, clock=clk, config=PlacementConfig(
            epoch_every_s=60.0, max_swaps_per_epoch=4,
            prefetch_lead_s=10.0))
        if with_prefetch:
            mgr2.enable_prefetch(config=PrefetchConfig(alpha=0.5))
        hot0 = [int(lid) for lid in t2.hot_lists[:8]]
        cold0 = [int(lid) for lid in t2.cold_lists[:8]]
        ex2.warmup(t2, buckets=(ex2.bucket_for(BATCH),), k=K, params=p)
        lat = []

        def drive(lists, ticks, measure=False):
            rng = np.random.default_rng(11)
            lists = np.asarray(lists)
            for _ in range(ticks):
                lids = lists[rng.integers(0, len(lists), BATCH)]
                q2 = (centers_np[lids]
                      + 0.01 * rng.standard_normal((BATCH, D))
                      ).astype(np.float32)
                t0 = time.perf_counter()
                jax.block_until_ready(
                    ex2.search(t2, q2, K, params=p)[0])
                if measure:
                    lat.append(time.perf_counter() - t0)
                clk.advance(11.0)
                mgr2.tick()

        drive(hot0, 12)              # settle on hot0
        drive(cold0, 14)             # warm drift cycle (specialize)
        c0 = dict(tracing.counters())
        drive(hot0, 14, measure=True)   # measured drift-back
        c1 = dict(tracing.counters())

        def delta(name):
            return float(c1.get(name, 0) - c0.get(name, 0))

        lat.sort()
        return {
            "promotions": delta("tier.promotions"),
            "promote_cold_bytes": delta("tier.promote_cold_bytes"),
            "prefetch_issued": delta(ISSUED),
            "prefetch_hits": delta(HITS),
            "prefetch_misses": delta(MISSES),
            "compiles_during_load": delta(tracing.XLA_COMPILE_COUNT),
            "p99_ms": round(
                lat[min(len(lat) - 1, int(0.99 * len(lat)))] * 1e3, 3),
        }

    log("tiered prefetch A/B: reactive leg")
    reactive = _prefetch_leg(False)
    log("tiered prefetch A/B: prefetch-on leg")
    on = _prefetch_leg(True)
    pf_total = on["prefetch_hits"] + on["prefetch_misses"]
    prefetch_ab = {
        "reactive": reactive,
        "on": on,
        "hit_rate": round(on["prefetch_hits"] / pf_total, 4)
        if pf_total else 0.0,
        "cold_bytes_saved": reactive["promote_cold_bytes"]
        - on["promote_cold_bytes"],
        "reduces_cold_bytes": int(
            on["promote_cold_bytes"] < reactive["promote_cold_bytes"]),
    }
    log(f"tiered prefetch A/B: hits {on['prefetch_hits']:.0f}/"
        f"{on['prefetch_issued']:.0f} issued, cold bytes "
        f"{reactive['promote_cold_bytes']:.0f} -> "
        f"{on['promote_cold_bytes']:.0f}, compiles during load "
        f"{on['compiles_during_load']:.0f}")

    return {
        "n": n, "dim": D, "n_lists": n_lists, "n_probes": n_probes,
        "batch": BATCH, "k": K, "max_list_size": m,
        "hot_lists": t.n_hot, "cold_lists": t.n_cold,
        "host_resident": int(t.host_resident),
        "union_lists": int(len(uniq)),
        "union_hot": union_hot, "union_cold": union_cold,
        "hot_model_bytes": int(hot_model_bytes),
        "cold_model_bytes": int(cold_model_bytes),
        "best_s": round(dt, 6), "qps": round(BATCH / dt, 2),
        "bit_identical": int(bit_identical and post_identical),
        "hot_gbps": round(hot_gbps, 2),
        "cold_gbps": round(cold_gbps, 2),
        "hbm_roofline_gbps": round(hbm_roof_gbps, 2),
        "host_roofline_gbps": round(host_roof_gbps, 2),
        "vs_hbm_roofline": round(hot_gbps / hbm_roof_gbps, 4),
        "vs_host_roofline": round(cold_gbps / host_roof_gbps, 4),
        "epochs": 2,
        "swap_bytes_per_epoch": swap_bytes,
        "swap_bytes_total": int(sum(swap_bytes)),
        "compiles_during_epochs": compiles,
        "prefetch": prefetch_ab,
    }


def _fleet_rider():
    """BENCH_FLEET=1 rider: graftroute's fleet router through the
    device-free N-replica harness (deterministic hash engine — the
    numbers gate ROUTING structure, not scan kernels). The planner
    places a skewed traffic plane (hot head replicated fleet-wide,
    long tail owned once), then three routed legs run against the
    solo-replica oracle:

    - ``steer``: head-covered batches steered whole to one hot
      replica — must be bit-identical to solo;
    - ``fanout_f32``: tail batches partitioned owner-wise, merged on
      the f32 wire — must also be bit-identical (the exact-merge
      contract);
    - ``fanout_bf16``: the same legs on the opt-in bf16 distance
      wire (ids stay exact int32) — half the merge payload, recall
      pinned >= 0.99 and deterministic at the seeded config.

    The merge-bytes columns come from ``route_payload_model`` (the
    ``collective_payload_model`` convention), so the bf16 < f32
    payload ordering is encoded exactly; coverage/fan-out fractions
    come off the router's own gauge view. Env knobs:
    BENCH_FLEET_REPLICAS / BENCH_FLEET_LISTS / BENCH_FLEET_SECONDS.
    """
    import numpy as np

    from raft_tpu.bench.prims import timeit_stats
    from raft_tpu.fleet import (
        FleetPlanConfig,
        QueryRouter,
        RouterConfig,
        make_fleet,
        plan_fleet,
        route_payload_model,
    )

    n_replicas = int(os.environ.get("BENCH_FLEET_REPLICAS", 4))
    n_lists = int(os.environ.get("BENCH_FLEET_LISTS", 64))
    budget = float(os.environ.get("BENCH_FLEET_SECONDS", 2))

    h = make_fleet(n_replicas, n_lists=n_lists)
    # skewed plane: the head half is hot enough to replicate onto
    # every replica (hot_share_ratio 0.5 → copies saturate at fleet
    # size), the tail is owned exactly once
    counts = np.ones(n_lists, np.int64)
    counts[: n_lists // 2] = 10_000
    table = plan_fleet(
        counts, {n: None for n in h.replicas}, label="ivf:0",
        version=1, config=FleetPlanConfig(hot_share_ratio=0.5))
    log(f"fleet rider: {n_replicas} replicas, {n_lists} lists, "
        f"{table.replicated_lists()} replicated")

    def _router(wire):
        r = QueryRouter(h.replicas, resolve_probes=h.resolve_probes,
                        clock=h.clock,
                        config=RouterConfig(merge_wire_dtype=wire))
        assert r.apply_table(table)
        return r

    r32 = _router("f32")
    # head batch: every probed list inside the replicated head →
    # steered whole; tail batch: probes cross the singleton tail →
    # owner-wise fan-out
    q_head = h.make_queries(BATCH, 0)
    q_tail = h.make_queries(BATCH, n_lists // 2)

    legs = []
    for name, q in (("steer", q_head), ("fanout_f32", q_tail)):
        ref_d, ref_i = h.solo(q, K)
        d, i, dec = r32.route(q, K)
        bit = bool(np.array_equal(np.asarray(d), ref_d)
                   and np.array_equal(np.asarray(i), ref_i))
        st = timeit_stats(lambda: r32.route(q, K),
                          min(budget, 3.0))
        legs.append((name, {
            "mode": dec.mode, "legs": dec.legs,
            "bit_identical": int(bit),
            "best_s": round(st["best_s"], 6),
            "qps": round(BATCH / st["best_s"], 2),
        }))
        log(f"fleet {name}: mode={dec.mode} legs={dec.legs} "
            f"bit_identical={bit} {st['best_s'] * 1e3:.3f} ms/iter")

    # bf16 wire: same fan-out legs, half-width distance payload;
    # recall vs the solo oracle (ids exact int32 on any wire)
    rb = _router("bf16")
    ref_d, ref_i = h.solo(q_tail, K)
    d, i, dec = rb.route(q_tail, K)
    ib = np.asarray(i)
    hits = sum(
        len(set(ib[row].tolist()) & set(ref_i[row].tolist()))
        for row in range(ref_i.shape[0]))
    recall = hits / float(ref_i.size)
    st = timeit_stats(lambda: rb.route(q_tail, K), min(budget, 3.0))
    pay32 = route_payload_model(BATCH, K, dec.legs, "f32")
    pay16 = route_payload_model(BATCH, K, dec.legs, "bf16")
    log(f"fleet fanout_bf16: recall={recall:.4f} merge bytes "
        f"{pay32['merge_bytes']} -> {pay16['merge_bytes']}")
    legs.append(("fanout_bf16", {
        "mode": dec.mode, "legs": dec.legs,
        "recall": round(recall, 4),
        "best_s": round(st["best_s"], 6),
        "qps": round(BATCH / st["best_s"], 2),
    }))

    # coverage split on a FRESH router under a fixed 12-head /
    # 4-tail batch schedule — the timed routers above saw a host-
    # speed-dependent number of iterations, this column must be
    # exact at the pinned geometry
    rc = _router("f32")
    for b in range(16):
        start = 0 if b % 4 else n_lists // 2
        rc.route(h.make_queries(BATCH, start), K)
    snap = rc.snapshot()["router"]
    req = snap["requests"]
    rec = {
        "replicas": n_replicas, "n_lists": n_lists,
        "batch": BATCH, "k": K,
        "table_version": table.version,
        "replicated_lists": table.replicated_lists(),
        "cold_owned": len(table.cold_owned),
        "requests": req,
        "coverage_rate": round(snap["steered"] / req, 4),
        "fanout_fraction": round(snap["fanout"] / req, 4),
        "merge_bytes_f32": pay32["merge_bytes"],
        "merge_bytes_bf16": pay16["merge_bytes"],
        "wire_bytes_saved_frac": round(
            1.0 - pay16["merge_bytes"] / pay32["merge_bytes"], 4),
    }
    rec.update(legs)
    return rec


def _serving_rider():
    """BENCH_SERVING=1 rider: the request frontend under bursty
    open-loop load. A DynamicBatcher in front of a warmed
    ``SearchExecutor`` takes bursts of small (1-4 row) requests on a
    fixed schedule (open loop — submission does not wait for
    completions) and the rider emits p50/p95/p99 end-to-end latency,
    the shed/reject rates, and the measured batch occupancy
    (requests per executor call — the coalescing win) next to the
    one-request-per-call baseline's QPS over the same request stream.

    PR 6 (graftscope): the record also carries the cost-analysis-
    derived achieved-vs-roofline columns — modeled bytes/flops from
    each executable's compile-time ``cost_analysis()`` divided by the
    measured execute-latency histogram, next to a ``stream_read_sum``
    roofline probe of the packed list tensor. These are the SAME
    counters the live ``serving.execute.*`` metrics and the exporter's
    ``derived`` block read, so BENCH JSONs and a running scrape agree
    by construction.

    PR 9 (ragged continuous batching): the record carries a
    ``ragged`` A/B block — the SAME request stream driven through the
    packed-batch plan family (``BatcherConfig(ragged=True)``, one
    executable at ``BENCH_SV_RAGGED_TILE`` rows) next to the bucketed
    leg, with the columns the acceptance criteria gate on: pad-waste
    fraction (bucketed pow2 rounding wastes up to ~50%; the packed
    tile only pads timer-fired partials), executables compiled (one
    vs the ladder), backend compiles during load, and p99 at the same
    offered load.

    PR 12 (graftfleet): a ``continuous`` A/B block — the SAME
    bucketed stream with a ``ContinuousCapture`` armed (REAL
    ``jax.profiler`` windows ticked from the open-loop pump hook), so
    the gated ``p99_ratio`` column prices steady-state attribution
    against the capture-free leg, next to the capture/window/duty
    accounting.

    Env knobs: BENCH_SV_N / BENCH_SV_LISTS / BENCH_SV_BURSTS /
    BENCH_SV_BURST (requests per burst) / BENCH_SV_MAX_ROWS (request
    sizes draw 1..max — the size variance the pad-waste A/B regime is
    defined over) / BENCH_SV_PERIOD_MS / BENCH_SV_WAIT_MS (batcher
    max-wait) / BENCH_SV_TIMEOUT_MS (per-request deadline) /
    BENCH_SV_RAGGED_TILE (packed tile rows) / BENCH_SV_RAGGED_SMALL
    (dual small tile, 0 = off) / BENCH_SV_FAMILIES (=1: PQ + BQ +
    mesh ragged legs) / BENCH_SV_MESH_SHARDS (mesh-leg device floor)
    / BENCH_SV_CONT (=1, continuous A/B on) / BENCH_SV_CONT_PERIOD_MS
    / BENCH_SV_CONT_CAPTURE_MS (scheduler cadence for the A/B).

    PR 15 (graftragged): ``ragged_families`` legs drive the SAME
    stream through the PQ, BQ, and mesh ragged fronts — the unified
    ragged plan family across the index zoo — each gated on the
    structural acceptance columns (≤ 2 executables via the dual
    tile, tight compiles-during-load, pad waste ≤ 0.05 band)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu import SearchExecutor
    from raft_tpu.core import tracing
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.serving import BatcherConfig, DynamicBatcher
    from raft_tpu.serving import metrics as sv_metrics
    from raft_tpu.serving.harness import burst_schedule, drive_open_loop

    n = int(os.environ.get("BENCH_SV_N", 200_000))
    n_lists = int(os.environ.get("BENCH_SV_LISTS", 256))
    n_bursts = int(os.environ.get("BENCH_SV_BURSTS", 50))
    burst = int(os.environ.get("BENCH_SV_BURST", 16))
    max_rows = int(os.environ.get("BENCH_SV_MAX_ROWS", 4))
    period_s = float(os.environ.get("BENCH_SV_PERIOD_MS", 10)) / 1e3
    max_wait_s = float(os.environ.get("BENCH_SV_WAIT_MS", 2)) / 1e3
    timeout_s = float(os.environ.get("BENCH_SV_TIMEOUT_MS", 250)) / 1e3

    kd, kq = jax.random.split(jax.random.key(5))
    x = np.asarray(jax.random.normal(kd, (n, D), jnp.float32))
    rng = np.random.default_rng(9)
    log(f"serving rider: building index ({n}x{D}, {n_lists} lists)")
    index = ivf_flat.build(None, ivf_flat.IvfFlatIndexParams(
        n_lists=n_lists, kmeans_n_iters=10), x)
    p = ivf_flat.IvfFlatSearchParams(n_probes=20)
    ex = SearchExecutor()
    ex.warmup(index, k=K, params=p)
    tracing.install_xla_compile_listener()

    # pre-draw the request stream: bursts of mixed-size blocks
    # (1..BENCH_SV_MAX_ROWS rows). Size variance is what makes the
    # pad-waste A/B honest: whole-request assembly stops mid-bucket
    # when the next request does not fit, while the ragged path splits
    # at tile boundaries and keeps every tile full.
    blocks = [rng.standard_normal(
        (int(rng.integers(1, max_rows + 1)), D)).astype(np.float32)
        for _ in range(n_bursts * burst)]

    # baseline: the same stream, one executor call per request — also
    # the honest measurement of the raw bucket ladder's pad waste
    # (every request pow2-rounds alone; coalescing hides most of it,
    # splitting kills it)
    sv_metrics.reset()
    t0 = time.perf_counter()
    for b in blocks:
        jax.block_until_ready(ex.search(index, b, K, params=p))
    base_dt = time.perf_counter() - t0
    base_qps = len(blocks) / base_dt
    base_pad_waste = sv_metrics.derived()["pad_waste_fraction"]
    log(f"serving rider baseline: {base_qps:.1f} req/s "
        f"(one call per request, pad waste {base_pad_waste:.3f})")

    sv_metrics.reset()
    b = DynamicBatcher(ex, BatcherConfig(max_wait_s=max_wait_s,
                                         full_batch_rows=256))
    clock = b._clock
    backend0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)

    def submit(ordinal, _t):
        return b.submit(index, blocks[ordinal], K, params=p,
                        timeout_s=timeout_s)

    t0 = time.perf_counter()
    handles = drive_open_loop(
        submit, burst_schedule(n_bursts, burst, period_s,
                               start_s=clock.now()), clock)
    done = sum(1 for h in handles if h.exception(timeout=30.0) is None)
    dt = time.perf_counter() - t0
    b.close()

    snap = sv_metrics.snapshot()
    occ = snap["occupancy"]
    e2e = snap["histograms"].get(sv_metrics.E2E, {})
    shed = snap["counters"].get("serving.batcher.shed_deadline", 0)
    rej = snap["counters"].get("serving.admission.rejected", 0)
    slo_ok = snap["counters"].get(sv_metrics.SLO_ATTAINED, 0)
    slo_miss = snap["counters"].get(sv_metrics.SLO_MISSED, 0)
    der = snap["derived"]

    # roofline: a pure streamed read of the packed list tensor — the
    # same ceiling the IVF sweep judges engines against, here next to
    # the achieved number derived from cost_analysis + execute latency
    roof_gbps = 0.0
    try:
        from raft_tpu.bench.prims import timeit_stats
        from raft_tpu.ops.fused_topk import stream_read_sum

        flat = jnp.asarray(index.data).reshape(-1, D)
        interp = jax.default_backend() != "tpu"
        st = timeit_stats(lambda: stream_read_sum(flat, interpret=interp),
                          2.0)
        roof_gbps = (flat.size * index.data.dtype.itemsize
                     / st["best_s"] / 1e9)
    except Exception as e:  # noqa: BLE001 — roofline probe is best-effort
        log(f"serving rider roofline probe failed ({e})")
    # ---- ragged A/B leg: the SAME stream through the packed-batch
    # plan family — continuous admission with tile-boundary splits,
    # one executable per tile (BENCH_SV_RAGGED_TILE rows, plus the
    # optional BENCH_SV_RAGGED_SMALL dual tile — ≤ 2 total)
    ragged_tile = int(os.environ.get("BENCH_SV_RAGGED_TILE", 64))
    ragged_small = int(os.environ.get("BENCH_SV_RAGGED_SMALL", 0))

    def _ragged_executor():
        return SearchExecutor(
            ragged_tile=ragged_tile,
            ragged_tile_small=ragged_small or None)

    def _drive_ragged(idx, params, legs_bursts, **sub_kw):
        """One ragged A/B leg: warm the packed executable(s), drive
        the SAME mixed-size stream through BatcherConfig(ragged=True),
        and report the acceptance columns (pad waste, executables,
        compiles during load, p99 at the offered load)."""
        ex_f = _ragged_executor()
        ex_f.warmup_ragged(idx, k=K, params=params, **sub_kw)
        sv_metrics.reset()
        bf = DynamicBatcher(ex_f, BatcherConfig(max_wait_s=max_wait_s,
                                                full_batch_rows=256,
                                                ragged=True))
        backend0_f = tracing.get_counter(tracing.XLA_COMPILE_COUNT)

        def submit_f(ordinal, _t):
            return bf.submit(idx, blocks[ordinal], K, params=params,
                             timeout_s=timeout_s, **sub_kw)

        t0 = time.perf_counter()
        handles_f = drive_open_loop(
            submit_f, burst_schedule(legs_bursts, burst, period_s,
                                     start_s=bf._clock.now()),
            bf._clock)
        done_f = sum(1 for h in handles_f
                     if h.exception(timeout=30.0) is None)
        dt_f = time.perf_counter() - t0
        bf.close()
        snap_f = sv_metrics.snapshot()
        e2e_f = snap_f["histograms"].get(sv_metrics.E2E, {})
        occ_f = snap_f["occupancy"]
        return {
            "tile_rows": ragged_tile,
            "tile_rows_small": ragged_small,
            "requests": len(handles_f), "completed": done_f,
            "qps": round(done_f / dt_f, 2),
            "p50_ms": round(e2e_f.get("p50", 0) * 1e3, 3),
            "p95_ms": round(e2e_f.get("p95", 0) * 1e3, 3),
            "p99_ms": round(e2e_f.get("p99", 0) * 1e3, 3),
            "requests_per_batch": round(occ_f["requests_per_batch"], 2),
            "rows_per_batch": round(occ_f["rows_per_batch"], 2),
            "pad_waste_fraction": round(
                snap_f["derived"]["pad_waste_fraction"], 4),
            "pad_waste_by_class":
                snap_f["derived"]["pad_waste_by_class"],
            "backend_compiles_during_load": (
                tracing.get_counter(tracing.XLA_COMPILE_COUNT)
                - backend0_f),
            "executables": ex_f.ragged_executables(),
        }

    ragged_out = _drive_ragged(index, p, n_bursts)

    # ---- ragged family legs (graftragged): the SAME mixed-size
    # stream through the PQ, BQ, and mesh ragged fronts — the whole
    # index zoo serving from the one ragged plan family. Each leg
    # gates the structural acceptance columns (≤ 2 executables, tight
    # compiles-during-load, pad waste ≤ baseline + 0.05); the mesh
    # leg needs >= BENCH_SV_MESH_SHARDS local devices (the pinned CI
    # config forces virtual CPU devices via XLA_FLAGS) and is
    # reported absent otherwise.
    fam_out = {}
    if os.environ.get("BENCH_SV_FAMILIES", "1") == "1":
        from raft_tpu.neighbors import ivf_bq, ivf_pq

        fam_bursts = max(2, n_bursts // 2)
        log("serving rider: building PQ/BQ family-leg indexes")
        pq_index = ivf_pq.build(None, ivf_pq.IvfPqIndexParams(
            n_lists=n_lists, pq_dim=max(4, D // 8),
            kmeans_n_iters=10), x)
        # the list-major union engine is the raggable one (auto
        # resolves to rank-major on CPU, which has no membership mask)
        fam_out["pq"] = _drive_ragged(
            pq_index, ivf_pq.IvfPqSearchParams(
                n_probes=20, scan_engine="xla"), fam_bursts)
        bq_index = ivf_bq.build(None, ivf_bq.IvfBqIndexParams(
            n_lists=n_lists, bits=2, kmeans_n_iters=10), x)
        fam_out["bq"] = _drive_ragged(
            bq_index, ivf_bq.IvfBqSearchParams(
                n_probes=20, scan_engine="xla"), fam_bursts)
        mesh_shards = int(os.environ.get("BENCH_SV_MESH_SHARDS", 4))
        if jax.device_count() >= mesh_shards:
            from raft_tpu.comms import local_comms
            from raft_tpu.distributed import ivf as dist_ivf

            comms = local_comms(
                shape=(jax.device_count(),))
            log(f"serving rider: building {comms.size}-shard mesh "
                "family-leg index")
            mesh_index = dist_ivf.build(None, comms, ivf_flat.
                                        IvfFlatIndexParams(
                                            n_lists=n_lists,
                                            kmeans_n_iters=10), x)
            fam_out["mesh"] = dict(_drive_ragged(
                mesh_index, ivf_flat.IvfFlatSearchParams(
                    n_probes=20, scan_engine="xla"), fam_bursts),
                shards=comms.size)
        else:
            log(f"serving rider: mesh family leg skipped — "
                f"{jax.device_count()} device(s) < {mesh_shards}")

    # ---- continuous-capture overhead A/B (PR 12 graftfleet): the
    # SAME bucketed stream with a ContinuousCapture armed (REAL
    # jax.profiler windows, driven from the open-loop pump hook) —
    # the p99 delta vs the capture-free leg above is the price of
    # steady-state attribution, gated tight in ci/bench_compare.py.
    # The first tick always captures (the budget admits it), so every
    # run pays at least one real profiler window; the default 1%
    # budget then gates the rest — the honest deployment cadence.
    cont_out = {}
    if os.environ.get("BENCH_SV_CONT", "1") == "1":
        import tempfile

        from raft_tpu.serving import ContinuousCapture, ContinuousConfig
        from raft_tpu.serving import continuous as cont_mod

        cont_period = float(
            os.environ.get("BENCH_SV_CONT_PERIOD_MS", 50)) / 1e3
        cont_cap = float(
            os.environ.get("BENCH_SV_CONT_CAPTURE_MS", 20)) / 1e3
        p99_off_ms = round(e2e.get("p99", 0) * 1e3, 3)
        sv_metrics.reset()
        bc = DynamicBatcher(ex, BatcherConfig(max_wait_s=max_wait_s,
                                              full_batch_rows=256))
        cc = ContinuousCapture(
            executor=ex, clock=bc._clock,
            config=ContinuousConfig(period_s=cont_period,
                                    capture_seconds=cont_cap),
            profile_dir=tempfile.mkdtemp(prefix="bench_cont_prof_"))
        counters0 = {name: tracing.get_counter(name) for name in (
            cont_mod.CAPTURES, cont_mod.EMPTY, cont_mod.ERRORS)}

        def submit_c(ordinal, _t):
            return bc.submit(index, blocks[ordinal], K, params=p,
                             timeout_s=timeout_s)

        t0 = time.perf_counter()
        handles_c = drive_open_loop(
            submit_c, burst_schedule(n_bursts, burst, period_s,
                                     start_s=bc._clock.now()),
            bc._clock, pump=cc.tick)
        done_c = sum(1 for h in handles_c
                     if h.exception(timeout=30.0) is None)
        dt_c = time.perf_counter() - t0
        cc.tick()             # one more chance past the load window
        bc.close()
        e2e_c = sv_metrics.snapshot()["histograms"].get(
            sv_metrics.E2E, {})
        p99_on_ms = round(e2e_c.get("p99", 0) * 1e3, 3)
        deltas = {name: tracing.get_counter(name) - v0
                  for name, v0 in counters0.items()}
        cont_out = {
            "period_ms": cont_period * 1e3,
            "capture_ms": cont_cap * 1e3,
            "requests": len(handles_c), "completed": done_c,
            "qps": round(done_c / dt_c, 2),
            "p99_ms": p99_on_ms,
            "p99_off_ms": p99_off_ms,
            # the gated overhead signal: on/off tail ratio over the
            # identical stream (CI hosts are noisy on absolutes)
            "p99_ratio": round(p99_on_ms / max(p99_off_ms, 1e-9), 4),
            # attempts = captured + empty + failed windows: whether a
            # 20 ms window caught a dispatch is thread-timing luck,
            # paying for real profiler windows is not
            "captures": int(deltas[cont_mod.CAPTURES]),
            "capture_attempts": int(sum(deltas.values())),
            "rolling_windows": int(tracing.get_gauge(
                "serving.attribution.rolling.windows")),
            "duty_cycle": round(cc.duty_cycle(), 5),
        }
        log(f"serving rider continuous A/B: p99 {p99_on_ms} ms with "
            f"duty cycle on vs {p99_off_ms} ms off (ratio "
            f"{cont_out['p99_ratio']}), "
            f"{cont_out['capture_attempts']} capture window(s), "
            f"{cont_out['rolling_windows']} attributed")

    out = {
        "n": n, "dim": D, "n_lists": n_lists, "k": K,
        "bursts": n_bursts, "burst_size": burst,
        "period_ms": period_s * 1e3, "max_wait_ms": max_wait_s * 1e3,
        "requests": len(handles), "completed": done,
        "qps": round(done / dt, 2),
        "baseline_one_per_call_qps": round(base_qps, 2),
        "baseline_pad_waste_fraction": round(base_pad_waste, 4),
        "p50_ms": round(e2e.get("p50", 0) * 1e3, 3),
        "p95_ms": round(e2e.get("p95", 0) * 1e3, 3),
        "p99_ms": round(e2e.get("p99", 0) * 1e3, 3),
        "shed_rate": round(shed / max(len(handles), 1), 4),
        "reject_rate": round(rej / max(len(handles), 1), 4),
        # graftscope v2: deadline-SLO attainment over the same stream
        "slo_attained": int(slo_ok),
        "slo_missed": int(slo_miss),
        "slo_burn_rate": round(
            tracing.get_gauge(sv_metrics.SLO_BURN_RATE), 4),
        "requests_per_batch": round(occ["requests_per_batch"], 2),
        "rows_per_batch": round(occ["rows_per_batch"], 2),
        "backend_compiles_during_load": (
            tracing.get_counter(tracing.XLA_COMPILE_COUNT) - backend0),
        # graftscope: live-metric accounting reproduced in the JSON
        "modeled_exec_bytes": int(der["modeled_bytes_total"]),
        "modeled_exec_flops": int(der["modeled_flops_total"]),
        "execute_seconds_total": round(der["execute_seconds_total"], 6),
        "achieved_gbps": round(der["achieved_gbps"], 3),
        "achieved_gflops": round(der["achieved_gflops"], 3),
        "roofline_gbps": round(roof_gbps, 3),
        "vs_roofline": (round(der["achieved_gbps"] / roof_gbps, 4)
                        if roof_gbps else 0.0),
        "cache_hit_rate": round(der["cache_hit_rate"], 4),
        "executables": len(ex.executable_costs()),
        "pad_waste_fraction": round(der["pad_waste_fraction"], 4),
        "ragged": ragged_out,
        "ragged_families": fam_out,
        "continuous": cont_out,
    }
    log(f"serving rider: {out['qps']} req/s through the batcher "
        f"(occupancy {out['requests_per_batch']} req/call, "
        f"p99 {out['p99_ms']} ms, shed {out['shed_rate']}, "
        f"scan {out['achieved_gbps']} GB/s = {out['vs_roofline']} of "
        f"roofline)")
    log(f"serving rider ragged A/B: {ragged_out['qps']} req/s, p99 "
        f"{ragged_out['p99_ms']} ms, pad waste "
        f"{ragged_out['pad_waste_fraction']} (bucketed "
        f"{out['pad_waste_fraction']}), "
        f"{ragged_out['executables']} executable(s) vs "
        f"{out['executables']}, compiles during load "
        f"{ragged_out['backend_compiles_during_load']}")
    for fam, rec in fam_out.items():
        log(f"serving rider ragged {fam}: {rec['qps']} req/s, p99 "
            f"{rec['p99_ms']} ms, pad waste "
            f"{rec['pad_waste_fraction']}, {rec['executables']} "
            f"executable(s), compiles during load "
            f"{rec['backend_compiles_during_load']}")
    return out


def _list_cpu_hogs():
    """Print matching pids (no signals) — the shell plans reuse THIS
    matcher for their window-wide pause instead of a pgrep substring
    scan that could freeze a process merely mentioning these names.
    Already-stopped pids are excluded (the shared walk's ownership
    rule), so a plan's later blanket SIGCONT can't resume a pause some
    other guard still owns."""
    for pid_s, _ in _iter_cpu_hog_pids():
        print(pid_s)


if __name__ == "__main__":
    if "--list-cpu-hogs" in sys.argv[1:]:
        _list_cpu_hogs()
    elif os.environ.get("BENCH_CHILD"):
        child_main()
    else:
        parent_main()
