#!/usr/bin/env python
"""Headline benchmark — one JSON line on stdout for the driver.

Flagship config: exact brute-force kNN on SIFT-shaped synthetic data
(1M × 128 float32, k=10, query batch 10 — the reference's "batch size
10" headline regime, ``docs/source/raft_ann_benchmarks.md``). Exact
search ⇒ recall@10 is 1.0 by construction; the figure of merit is QPS.

``vs_baseline`` normalizes QPS by the single-chip HBM roofline for this
config: each batch must stream the whole dataset (512 MB) from HBM, so
roofline QPS = batch · BW / bytes = 10 · 819e9 / 512e6 ≈ 16k QPS on
TPU v5e. A value of 1.0 means memory-bound optimal. (The reference
repo publishes no numeric tables to compare against — see BASELINE.md.)

Timing is pipelined (dispatch a run of iterations, fetch once):
``block_until_ready`` does not block on relayed backends, and a
per-iteration host fetch would pay the relay round-trip every call.
Measured note: through the axon relay the achievable HBM stream rate is
~200 GB/s (XLA rowsum over the same array measures slower than this
kernel), so vs_baseline ≈ 0.25 is the practical ceiling there.

Progress goes to stderr so a slow run is diagnosable; stdout carries
exactly one JSON line. Env knobs: BENCH_N / BENCH_DIM / BENCH_BATCH /
BENCH_K / BENCH_SECONDS (measurement budget, default 45) /
BENCH_DTYPE (float32|bfloat16 dataset storage) /
RAFT_TPU_DISABLE_FUSED=1 (force the XLA tile-scan path).
"""

import json
import os
import sys
import time

T0 = time.perf_counter()

N = int(os.environ.get("BENCH_N", 1_000_000))
D = int(os.environ.get("BENCH_DIM", 128))
BATCH = int(os.environ.get("BENCH_BATCH", 10))
K = int(os.environ.get("BENCH_K", 10))
BUDGET_S = float(os.environ.get("BENCH_SECONDS", 45))
V5E_HBM_BYTES_PER_S = 819e9
ROOFLINE_QPS = BATCH * V5E_HBM_BYTES_PER_S / (N * D * 4)


def log(msg):
    print(f"[bench +{time.perf_counter() - T0:.1f}s] {msg}",
          file=sys.stderr, flush=True)


def _backend_healthy(timeout_s: float) -> bool:
    """Probe backend init in a subprocess: a wedged TPU relay blocks
    ~25 min before erroring, which would eat the whole bench budget."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print('OK')"],
            capture_output=True, timeout=timeout_s, text=True,
        )
        return "OK" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def main():
    init_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", 300))
    suffix = ""
    if not _backend_healthy(init_timeout):
        log(f"default backend failed/hung (> {init_timeout:.0f}s probe); "
            "falling back to CPU — metric annotated accordingly")
        suffix = "_cpu_fallback"
        import jax

        jax.config.update("jax_platforms", "cpu")

    log(f"importing jax (config {N}x{D}, batch {BATCH}, k {K})")
    import jax
    import jax.numpy as jnp

    from raft_tpu.neighbors import brute_force

    log(f"backend: {jax.default_backend()}")
    key = jax.random.key(0)
    kd, kq = jax.random.split(key)
    dataset = jax.random.normal(kd, (N, D), jnp.float32)
    queries = jax.random.normal(kq, (BATCH, D), jnp.float32)
    jax.block_until_ready((dataset, queries))
    log("data generated")
    storage = (jnp.bfloat16 if os.environ.get("BENCH_DTYPE") == "bfloat16"
               else None)
    index = brute_force.build(None, dataset, storage_dtype=storage)
    jax.block_until_ready(index.norms)
    log(f"index built (storage {index.dataset.dtype}, norms cached)")

    import numpy as np

    def run():
        return brute_force.search(None, index, queries, K, db_tile=262144)

    def sync(out):
        # force completion by fetching a few result elements:
        # block_until_ready does NOT block on relayed backends (axon),
        # so wall-clock timing must be anchored on a host fetch
        np.asarray(out[0][0, :1])

    sync(run())  # compile + warm
    t1 = time.perf_counter()
    sync(run())
    est = time.perf_counter() - t1  # one synced iter (incl. relay RTT)
    log(f"compiled + warmed; single-iter estimate {est * 1e3:.1f} ms")

    # pipelined measurement: dispatch a batch of iterations and sync once
    # at the end — executions run back-to-back on device, so the per-call
    # host->device round-trip latency is amortized out and the figure is
    # steady-state throughput. Batch length is sized so one batch fits in
    # ~half the budget; repeat batches within the time budget.
    PIPE = max(3, min(50, int(BUDGET_S / 2 / max(est, 1e-4))))
    rates = []
    t_meas = time.perf_counter()
    while len(rates) < 6 and (
        not rates or time.perf_counter() - t_meas < BUDGET_S
    ):
        t0 = time.perf_counter()
        for _ in range(PIPE):
            out = run()
        sync(out)
        rates.append((time.perf_counter() - t0) / PIPE)
    dt = min(rates)  # best batch: steady-state throughput
    qps = BATCH / dt
    log(f"{len(rates)} batches of {PIPE}, best {dt * 1e3:.2f} ms/iter, "
        f"median {sorted(rates)[len(rates) // 2] * 1e3:.2f} ms/iter")

    tag = os.environ.get("BENCH_TAG", "")
    tag = f"_{tag}" if tag else ""
    print(json.dumps({
        "metric": f"brute_force_knn_qps_sift1m_shape_b{BATCH}_k{K}{tag}{suffix}",
        "value": round(qps, 2),
        "unit": "QPS",
        "vs_baseline": round(qps / ROOFLINE_QPS, 4),
    }))


if __name__ == "__main__":
    main()
