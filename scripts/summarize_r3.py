#!/usr/bin/env python
"""Collect round-3 hardware evidence into one markdown report.

Reads whatever exists of:
  ci/tpu_smoke_kernels_r3.json        kernel parity smoke
  ci/tpu_profile6_r3.jsonl            committed profile pieces
  results/tpu_profile6_r3.jsonl       this-session profile pieces
  results/tpu_profile6_r3_v96.jsonl   VMEM-96 fknn legs
  results/bench_headline.json         bench.py output (if saved)
  results/sweep-1M/results.jsonl      pareto sweep rows
  results/scale_*.jsonl / *.log       100M streaming build records
  results/prims_full_r3.jsonl         per-primitive table

Writes RESULTS_r3.md (repo root). Purely host-side — safe anytime.

Run: python scripts/summarize_r3.py
"""

import json
import pathlib

ROOT = pathlib.Path(__file__).resolve().parent.parent


def read_jsonl(path):
    rows = []
    p = ROOT / path
    if not p.exists():
        return rows
    for line in p.read_text().splitlines():
        line = line.strip()
        if not line or not line.startswith("{"):
            continue
        try:
            rows.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return rows


def dedupe_last(rows, key_fields):
    """Keep the LAST record per key — reruns append, newest wins."""
    out = {}
    for r in rows:
        out[tuple(r.get(k) for k in key_fields)] = r
    return list(out.values())


def fmt_table(rows, cols, header=None):
    if not rows:
        return "_no data captured_\n"
    head = header or cols
    lines = ["| " + " | ".join(head) + " |",
             "|" + "|".join("---" for _ in head) + "|"]
    for r in rows:
        lines.append("| " + " | ".join(
            "" if r.get(c) is None else str(r.get(c)) for c in cols) + " |")
    return "\n".join(lines) + "\n"


def main():
    out = ["# Round-3 hardware evidence (TPU v5e via relay)", ""]

    smoke = read_jsonl("ci/tpu_smoke_kernels_r3.json")  # JSON lines
    if smoke:
        lines, used = [], 0
        for r in smoke:  # whole records only; never cut JSON mid-object
            s = json.dumps(r)
            if used + len(s) > 2000:
                lines.append(f"... {len(smoke) - len(lines)} more records "
                             "truncated")
                break
            lines.append(s)
            used += len(s)
        out += ["## Pallas kernel parity smoke (compiled Mosaic)",
                "", "```json", "\n".join(lines), "```", ""]

    prof = dedupe_last(
        read_jsonl("ci/tpu_profile6_r3.jsonl")
        + read_jsonl("results/tpu_profile6_r3.jsonl"), ("piece",))
    prof96 = read_jsonl("results/tpu_profile6_r3_v96.jsonl")
    if prof:
        out += ["## Profile pieces (slope-timed; per-dtype spreads)", "",
                fmt_table(prof, ["piece", "iter_ms", "gbps", "ms", "qps",
                                 "recall", "error"])]
    if prof96:
        out += ["### fknn at RAFT_TPU_VMEM_MB=96 (auto tiles)", "",
                fmt_table(prof96, ["piece", "iter_ms", "gbps", "error"])]

    bench = read_jsonl("results/bench_headline.json")
    if bench:
        out += ["## Headline bench (driver format)", "",
                "```json", "\n".join(json.dumps(b) for b in bench), "```",
                ""]

    sweep = read_jsonl("results/sweep-1M/results.jsonl")
    if sweep:
        for r in sweep:
            r["build"] = json.dumps(r.get("build_params"))
            r["search"] = json.dumps(r.get("search_params"))
        out += ["## Recall-vs-QPS sweep, blobs-1M-128 (batch = full query "
                "set unless noted)", "",
                fmt_table(sweep, ["algo", "build", "search", "qps",
                                  "recall", "build_seconds",
                                  "build_cached"])]

    scale = read_jsonl("results/scale_tpu_r3.jsonl")
    scale_note = ""
    if not scale:
        # fall back to the newest CPU rehearsal, clearly labeled
        logs = list(ROOT.glob("results/scale_rehearsal*.log"))
        if logs:
            newest = max(logs, key=lambda p: p.stat().st_mtime)
            scale = read_jsonl(newest.relative_to(ROOT))
            scale_note = (" — **CPU rehearsal only** (no TPU run "
                          "captured)")
    if scale:
        out += [f"## Streaming scale build (IVF-PQ over fbin > HBM)"
                f"{scale_note}", "",
                fmt_table(scale, ["piece", "backend", "rows", "dim",
                                  "pq_bits", "s", "vectors_per_s", "ms",
                                  "qps", "recall"])]

    prims = read_jsonl("results/prims_full_r3.jsonl")
    if prims:
        out += ["## Per-primitive micro-bench (--size full)", "",
                fmt_table(prims, ["prim", "shape", "ms", "gbps", "bw_frac",
                                  "mfu"])]

    (ROOT / "RESULTS_r3.md").write_text("\n".join(out) + "\n")
    print(f"wrote {ROOT / 'RESULTS_r3.md'} "
          f"({len(prof)} profile rows, {len(sweep)} sweep rows, "
          f"{len(scale)} scale rows, {len(prims)} prim rows)")


if __name__ == "__main__":
    main()
