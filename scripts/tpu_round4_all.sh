#!/bin/bash
# Forwarding shim (was the round-4 plan; see git history): any stale
# launcher hitting this path must run the CURRENT plan so captured
# evidence is stamped with the right round.
exec bash "$(cd "$(dirname "$0")" && pwd)/tpu_round5_all.sh" "$@"
