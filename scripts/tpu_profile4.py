#!/usr/bin/env python
"""Fourth-round TPU probes: TRUE on-chip rates via in-program iteration.

Round-3 finding: every single-dispatch measurement bottoms out at
~4.2 ms regardless of bytes/steps — the relay serializes dispatches
with a ~4 ms gap, so per-dispatch timing cannot see anything faster.
Fix: iterate the kernel INSIDE one jitted program and fit a slope:
per-iter cost = (T(M2) - T(M1)) / (M2 - M1), which cancels both the
dispatch overhead and the compile-cached constant term.

- Pure read bandwidth: one pallas_call whose grid revisits the same
  array M times (index_map i -> (i % steps, 0)) — M full dataset
  streams in a single dispatch.
- Search kernels: lax.fori_loop whose carried query tile is perturbed
  by a data-dependent epsilon each iteration, so XLA can neither hoist
  nor CSE the body.

Run serially on a healthy relay; pipelined fetch-anchored timing.
"""

import functools
import json
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def wall(fn):
    out = fn()
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(5):
        out = fn()
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    return (time.perf_counter() - t0) / 5


def slope(tag, make_fn, m1, m2, payload_per_iter=None, extra=None):
    """Per-iteration time from two loop lengths."""
    try:
        f1, f2 = make_fn(m1), make_fn(m2)
        t1, t2 = wall(f1), wall(f2)
        dt = (t2 - t1) / (m2 - m1)
    except Exception as e:  # noqa: BLE001
        print(json.dumps({"piece": tag, "error": str(e)[:200]}), flush=True)
        return None
    rec = {"piece": tag, "iter_ms": round(dt * 1e3, 4),
           "t1_ms": round(t1 * 1e3, 2), "t2_ms": round(t2 * 1e3, 2)}
    if payload_per_iter and dt > 0:
        rec["gbps"] = round(payload_per_iter / dt / 1e9, 1)
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)
    return dt


# ---- repeated-read kernel: grid revisits the array M times ---------------


def _mread_kernel(x_ref, o_ref, acc):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)

    acc[:] += jnp.sum(x_ref[:].astype(jnp.float32), axis=0, keepdims=True)

    @pl.when(step == pl.num_programs(0) - 1)
    def _():
        o_ref[:] = acc[:]


@functools.partial(jax.jit, static_argnames=("tile", "m", "vmem_mb"))
def multi_read(x, tile: int, m: int, vmem_mb: int = 72):
    n, d = x.shape
    assert n % tile == 0
    steps = n // tile
    return pl.pallas_call(
        _mread_kernel,
        grid=(steps * m,),
        in_specs=[pl.BlockSpec((tile, d), lambda i, s=steps: (i % s, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, d), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((1, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=vmem_mb * 1024 * 1024),
    )(x)


# the in-program loop + perturbation trick lives in the shared
# methodology module — one copy, shared with bench.py
from raft_tpu.bench.prims import loop_queries  # noqa: E402


def main():
    print(json.dumps({"prof": "round4", "backend": jax.default_backend()}),
          flush=True)

    big = jax.random.normal(jax.random.key(0), (1 << 20, 128), jnp.float32)
    bigb = big.astype(jnp.bfloat16)

    # ---- 1. true sustained read bandwidth
    for tag, x, payload in (("f32", big, 512e6), ("bf16", bigb, 256e6)):
        for tile in (4096, 16384):
            slope(f"mread_{tag}_t{tile}",
                  lambda m, x=x, t=tile: (lambda: multi_read(x, t, m)),
                  2, 10, payload_per_iter=payload,
                  extra={"steps_per_iter": (1 << 20) // tile})

    # ---- 2. fused_knn true per-iter cost
    from raft_tpu.distance.types import DistanceType
    from raft_tpu.ops.fused_topk import fused_knn
    qs = jax.random.normal(jax.random.key(2), (10, 128), jnp.float32)
    norms = jnp.sum(jnp.square(big), axis=1)
    for tag, ds, payload in (("f32", big, 512e6), ("bf16", bigb, 256e6)):
        for tile in (8192, 16384, 32768):
            fn = lambda q, ds=ds, t=tile: fused_knn(  # noqa: E731
                q.astype(ds.dtype), ds, 10, DistanceType.L2Expanded,
                dataset_norms=norms, tile=t)
            slope(f"fknn_{tag}_t{tile}",
                  lambda m, fn=fn: loop_queries(fn, qs, m),
                  2, 8, payload_per_iter=payload)

    # ---- 3. IVF-Flat / IVF-PQ search true per-iter cost
    from raft_tpu.neighbors import ivf_flat, ivf_pq
    rng = np.random.default_rng(0)
    x = rng.standard_normal((200_000, 128)).astype(np.float32)
    q100 = jnp.asarray(rng.standard_normal((100, 128)), jnp.float32)

    fi = ivf_flat.build(None, ivf_flat.IvfFlatIndexParams(n_lists=1024), x)
    for p in (32, 64):
        sp = ivf_flat.IvfFlatSearchParams(n_probes=p)
        fn = lambda q, sp=sp: ivf_flat.search(None, sp, fi, q, 10)  # noqa: E731
        slope(f"ivf_flat_p{p}", lambda m, fn=fn: loop_queries(fn, q100, m),
              1, 5, payload_per_iter=100 * p * 200 * 128 * 4)

    pi4 = ivf_pq.build(None, ivf_pq.IvfPqIndexParams(
        n_lists=1024, pq_dim=128, pq_bits=4), x)
    for mode in ("select", "onehot"):
        sp = ivf_pq.IvfPqSearchParams(n_probes=32, score_mode=mode)
        fn = lambda q, sp=sp: ivf_pq.search(None, sp, pi4, q, 10)  # noqa: E731
        slope(f"ivf_pq_b4_{mode}_p32",
              lambda m, fn=fn: loop_queries(fn, q100, m), 1, 5)

    pi8 = ivf_pq.build(None, ivf_pq.IvfPqIndexParams(
        n_lists=1024, pq_dim=64, pq_bits=8), x)
    sp = ivf_pq.IvfPqSearchParams(n_probes=32)
    fn = lambda q, sp=sp: ivf_pq.search(None, sp, pi8, q, 10)  # noqa: E731
    slope("ivf_pq_b8_onehot_p32",
          lambda m, fn=fn: loop_queries(fn, q100, m), 1, 3)

    # ---- 4. brute-force XLA scan path (RAFT_TPU_DISABLE_FUSED analog)
    from raft_tpu.neighbors.brute_force import _knn_scan
    fn = lambda q: _knn_scan(q, big, 10, DistanceType.L2Expanded,  # noqa: E731
                             2.0, 262144, "highest", False)
    slope("bf_xla_scan_t262144", lambda m: loop_queries(fn, qs, m),
          2, 6, payload_per_iter=512e6)


if __name__ == "__main__":
    main()
