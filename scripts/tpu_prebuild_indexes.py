#!/usr/bin/env python
"""Pre-build the CAGRA indexes the TPU profile needs, ON CPU, and save
them to disk. Rationale: round-2 AND round-3 relay deaths both struck
during large multi-compile build phases; prebuilding on CPU means the
hardware window only pays for (a) search-leg compiles, which are small
and known-good from the kernel smoke, and (b) the one optional
cluster_join-on-TPU timing leg, run last.

The dataset is regenerated deterministically (default_rng(0)) so the
profile script's queries/ground-truth match the saved index.

Run: python scripts/tpu_prebuild_indexes.py   (CPU-only; safe anytime)
"""

import os
import sys
import time

import numpy as np

os.environ.setdefault("RAFT_TPU_VMEM_MB", "64")

import jax

# the axon plugin forces jax_platforms via jax.config at import; override
# back to CPU before any backend initializes (same trick as tests/conftest)
jax.config.update("jax_platforms", "cpu")



def main():
    assert jax.devices()[0].platform == "cpu"
    from raft_tpu.neighbors import cagra

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    # single source of truth for cache filenames, build params, AND the
    # dataset itself — the profile pieces evaluate recall against
    # make_data's vectors, so the indexes must be built from them too
    from tpu_profile6 import (CACHE_DIR, PROFILE_N, cache_path,
                              ivf_prebuild_specs, make_data, size_tag)

    profile_n = PROFILE_N
    _, x, _ = make_data()

    os.makedirs(CACHE_DIR, exist_ok=True)

    def save_atomic(save, path):
        # a prebuild killed mid-save (the relay-death scenario this
        # cache defends against) must not leave a truncated file that
        # later loads inside the TPU window
        tmp = path + ".tmp"
        save(tmp)
        os.replace(tmp, path)

    for n in (profile_n, profile_n // 2):
        tag = size_tag(n)
        path = cache_path(f"cagra_cluster_join_{tag}.bin")
        if os.path.exists(path):
            print(f"{tag}: cached at {path}", flush=True)
            continue
        t0 = time.perf_counter()
        ci = cagra.build(None, cagra.CagraIndexParams(
            graph_degree=32, intermediate_graph_degree=64,
            build_algo=cagra.BuildAlgo.CLUSTER_JOIN), x[:n])
        np.asarray(ci.graph[:1])
        dt = time.perf_counter() - t0
        save_atomic(lambda p: cagra.save(ci, p, include_dataset=False),
                    path)
        print(f"{tag}: built in {dt:.0f}s (CPU) -> {path}", flush=True)

    for fname, mod, build in ivf_prebuild_specs().values():
        path = cache_path(fname)
        if os.path.exists(path):
            print(f"cached: {path}", flush=True)
            continue
        t0 = time.perf_counter()
        idx = build(x)
        jax.block_until_ready(idx)
        dt = time.perf_counter() - t0
        save_atomic(lambda p: mod.save(idx, p), path)
        print(f"built {fname} in {dt:.0f}s (CPU)", flush=True)


if __name__ == "__main__":
    main()
