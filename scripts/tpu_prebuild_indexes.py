#!/usr/bin/env python
"""Pre-build the CAGRA indexes the TPU profile needs, ON CPU, and save
them to disk. Rationale: round-2 AND round-3 relay deaths both struck
during large multi-compile build phases; prebuilding on CPU means the
hardware window only pays for (a) search-leg compiles, which are small
and known-good from the kernel smoke, and (b) the one optional
cluster_join-on-TPU timing leg, run last.

The dataset is regenerated deterministically (default_rng(0)) so the
profile script's queries/ground-truth match the saved index.

Run: python scripts/tpu_prebuild_indexes.py   (CPU-only; safe anytime)
"""

import os
import sys
import time

import numpy as np

os.environ.setdefault("RAFT_TPU_VMEM_MB", "64")

import jax

# the axon plugin forces jax_platforms via jax.config at import; override
# back to CPU before any backend initializes (same trick as tests/conftest)
jax.config.update("jax_platforms", "cpu")

CACHE = os.path.join(os.path.dirname(__file__), "..", "results", "cache")


def main():
    os.makedirs(CACHE, exist_ok=True)
    assert jax.devices()[0].platform == "cpu"
    from raft_tpu.neighbors import cagra

    profile_n = int(os.environ.get("RAFT_TPU_PROFILE_N", 200_000))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((profile_n, 128)).astype(np.float32)

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tpu_profile6 import size_tag

    for n in (profile_n, profile_n // 2):
        tag = size_tag(n)
        path = os.path.join(CACHE, f"cagra_cluster_join_{tag}.bin")
        if os.path.exists(path):
            print(f"{tag}: cached at {path}", flush=True)
            continue
        t0 = time.perf_counter()
        ci = cagra.build(None, cagra.CagraIndexParams(
            graph_degree=32, intermediate_graph_degree=64,
            build_algo=cagra.BuildAlgo.CLUSTER_JOIN), x[:n])
        np.asarray(ci.graph[:1])
        dt = time.perf_counter() - t0
        cagra.save(ci, path, include_dataset=False)
        print(f"{tag}: built in {dt:.0f}s (CPU) -> {path}", flush=True)


if __name__ == "__main__":
    main()
