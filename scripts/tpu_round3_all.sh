#!/bin/bash
# Forwarding shim: the round-3 relay watcher (scripts/relay_watch.sh)
# may still be running detached and launches THIS path when the relay
# returns; the current hardware plan lives in tpu_round5_all.sh.
exec bash "$(cd "$(dirname "$0")" && pwd)/tpu_round5_all.sh" "$@"
