#!/bin/bash
# Master round-5 hardware plan: run EVERYTHING in value order with a
# relay port check between steps, so however short the relay window is,
# the highest-value evidence lands first. Each step is its own process
# (never two TPU processes at once); a relay death stops the chain
# cleanly instead of wedging.
#
# Round-5 value order (VERDICT r4 "next round" list):
#   smoke -> bench headline (#1: the driver artifact must not be a CPU
#   fallback) -> 1M pareto sweep, IVF-Flat and IVF-PQ FIRST (#1's Done
#   criterion: backend=tpu rows at recall >= 0.95) -> ivf/bq profile ->
#   cagra profile incl. the HBM-engine block_q legs (#4) -> fknn slopes
#   (honest bf16 2-vs-32, #5) -> prims -> 10M+ streaming scale (#6) ->
#   cjoin-last (the leg that killed the r3 relay).
#
# Usage: bash scripts/tpu_round5_all.sh   (logs under results/)
set -u
SCRIPT_DIR=$(cd "$(dirname "$0")" && pwd)
cd "$SCRIPT_DIR/.."
export PYTHONPATH=/root/repo:/root/.axon_site
export RAFT_TPU_VMEM_MB=64
# cross-process persistent compile cache (the pieces/steps are separate
# processes; compiles are the relay's highest-risk phase)
export JAX_COMPILATION_CACHE_DIR="$PWD/results/jaxcache"
TS=$(date +%H%M%S)
LOG=results/round5_all_$TS.log
echo "round5_all start $(date)" | tee -a "$LOG"

. "$SCRIPT_DIR/relay_lib.sh"

# Single-core host: pause any CPU-heavy background job for the duration
# of the hardware window — it would otherwise contend with TPU backend
# init/compile on the one core (a background 1M hnswlib sweep halved
# the round-4 driver capture). bench.py now pauses the same set itself
# (and skips pids already in state T, so this window-wide stop and the
# per-bench stop compose). The match is bench.py's token-based
# _is_cpu_hog (via --list-cpu-hogs), NOT a pgrep substring scan: only
# CPU-only-by-construction jobs qualify — a substring match could
# freeze a process that merely MENTIONS these names, or an abandoned
# in-flight TPU process, the mid-transaction freeze the relay rules
# forbid. Resumed by the traps.
PAUSED_PIDS=$(python bench.py --list-cpu-hogs | tr '\n' ' ' || true)
if [ -n "$PAUSED_PIDS" ]; then
  echo "pausing background bench pids: $PAUSED_PIDS" | tee -a "$LOG"
  kill -STOP $PAUSED_PIDS 2>/dev/null
fi
resume_paused() {
  [ -n "$PAUSED_PIDS" ] && kill -CONT $PAUSED_PIDS 2>/dev/null
}

# Archive whatever evidence landed — runs on EVERY exit (a relay death
# mid-chain aborts with exit 2; the captured pieces must still be
# summarized and committed, or a later workspace reset loses them).
archive_evidence() {
  # record streams (JSONL) APPEND into ci/ so a partial session can
  # never clobber a prior session's committed rows (summarize_round
  # dedupes by record key, newest wins); whole-artifact files
  # (csv/png) are regenerated complete each run and may overwrite
  while read -r mode src dst; do
    if [ -s "$src" ]; then
      case "$mode" in
        # order-preserving exact-duplicate drop: summarize_round's
        # newest-wins dedupe needs chronological order kept
        append) cat "$src" >> "ci/$dst" \
                  && awk '!seen[$0]++' "ci/$dst" > "ci/$dst.tmp" \
                  && mv "ci/$dst.tmp" "ci/$dst" ;;
        copy)   cp "$src" "ci/$dst" ;;
      esac
    fi
  done <<'EOF'
append results/tpu_smoke_r5.jsonl tpu_smoke_kernels_r5.json
append results/tpu_profile6_r5.jsonl tpu_profile6_r5.jsonl
append results/tpu_profile6_r5_v96.jsonl tpu_profile6_r5_v96.jsonl
append results/bench_headline.json bench_headline_r5.json
append results/scale_tpu_r5.jsonl scale_tpu_r5.jsonl
append results/prims_full_r5.jsonl prims_full_r5.jsonl
append results/sweep-1M/results.jsonl sweep1m_results_r5.jsonl
copy results/sweep-1M/export.csv sweep1m_export_r5.csv
copy results/sweep-1M/pareto.png pareto_r5.png
copy results/compare_hnsw.png compare_hnsw_r5.png
EOF
  # summarize AFTER archiving so the report reads the ci/ copies too
  python scripts/summarize_round.py --round 5 >> "$LOG" 2>&1
  git add ci/ 2>>"$LOG"
  [ -s RESULTS_r5.md ] && git add RESULTS_r5.md 2>>"$LOG"
  git diff --cached --quiet -- ci/ RESULTS_r5.md 2>/dev/null || \
    git commit -q -m "Round-5 hardware evidence (auto-archived by tpu_round5_all.sh)" \
      -- ci/ RESULTS_r5.md
  resume_paused
}
trap archive_evidence EXIT
# EXIT traps don't run on untrapped fatal signals — without these a
# SIGTERM/HUP (session drop) would leave the background bench frozen
trap 'exit 129' HUP
trap 'exit 130' INT
trap 'exit 143' TERM

step() {  # step <name> <cmd...>
  local name=$1; shift
  if ! relay_gate; then  # inter-process gap + checks: relay_lib.sh
    echo "RELAY DOWN before step $name — stopping $(date)" | tee -a "$LOG"
    exit 2
  fi
  echo "=== step $name start $(date) ===" | tee -a "$LOG"
  "$@" >> "$LOG" 2>&1
  echo "=== step $name rc=$? end $(date) ===" | tee -a "$LOG"
}

# 1. kernel smoke (fast; proves the window is healthy AND compiles the
#    HBM/int8 beam legs on real Mosaic — VERDICT r4 #4); teed so the
#    parity records reach the archive, not just the log
step smoke bash -c 'set -o pipefail
  python scripts/tpu_smoke_kernels.py | tee -a results/tpu_smoke_r5.jsonl'

# 2. THE headline bench (driver-format JSON line -> committed evidence;
#    teed to the file scripts/summarize_round.py collects)
step bench bash -c 'set -o pipefail
  BENCH_SECONDS=45 python bench.py | tee -a results/bench_headline.json'

# 3. recall-vs-QPS pareto sweep on blobs-1M (the reference's headline
#    artifact form; VERDICT r4 #1's Done criterion is backend=tpu rows
#    for IVF-Flat and IVF-PQ at recall >= 0.95, so those families go
#    FIRST), piece-wise: one process per family with --resume, so a
#    relay death loses one family, not the sweep.
#    --require-cached-index: a config entry whose index isn't
#    CPU-prebuilt fails fast host-side instead of running its 1M build
#    ON TPU — the exact multi-compile leg that killed the relay.
#    (brute_force has no index file and is exempt by design.)
sweep_family() {  # sweep_family <step-name> <algo>
  # host-side pre-gate (CPU, no relay risk): skip a family whose
  # indexes aren't all prebuilt instead of burning an inter-process
  # gap + TPU launch on a run that --require-cached-index would kill.
  # Output IS captured ($LOG) and the exit cause distinguished — an
  # import error or missing dataset must abort loudly, not masquerade
  # as "not prebuilt" (ADVICE r3).
  if [ "$2" != raft_brute_force ]; then
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      python scripts/prebuild_sweep_indexes.py --check --algos "$2" \
      >> "$LOG" 2>&1
    local rc=$?
    if [ $rc -eq 10 ]; then  # the check's "missing index" exit code
      echo "SKIP $1: family $2 not fully prebuilt" \
        "(run scripts/prebuild_sweep_indexes.py first)" | tee -a "$LOG"
      return
    elif [ $rc -ne 0 ]; then
      echo "ABORT $1: prebuild --check failed rc=$rc (NOT a missing" \
        "index — see $LOG for the real error)" | tee -a "$LOG"
      exit 3
    fi
  fi
  step "$1" python -m raft_tpu.bench run \
    --dataset datasets/blobs-1000000-128 --config blobs-1M-128 \
    --out-dir results/sweep-1M --resume --algos "$2" \
    --require-cached-index
}
sweep_family sweep_flat  raft_ivf_flat
sweep_family sweep_pq    raft_ivf_pq
sweep_family sweep_bf    raft_brute_force
sweep_family sweep_bq    raft_ivf_bq
sweep_family sweep_cagra raft_cagra

# export/plot are CPU-only and cannot wedge the relay — no gap, no
# relay gate, so harvested results always get exported even if the
# relay died right after the sweep
cpustep() {  # cpustep <name> <cmd...>
  local name=$1; shift
  echo "=== cpustep $name start $(date) ===" | tee -a "$LOG"
  "$@" >> "$LOG" 2>&1
  echo "=== cpustep $name rc=$? end $(date) ===" | tee -a "$LOG"
}
cpustep sweep_export python -m raft_tpu.bench data-export \
  --results results/sweep-1M --out results/sweep-1M/export.csv
cpustep sweep_plot python -m raft_tpu.bench plot \
  --results results/sweep-1M --out results/sweep-1M/pareto.png

# 4. the previously-zero-TPU-evidence index families' profile legs:
#    IVF-Flat probe scan + IVF-PQ scoring-mode A/B + LUT ladder, then BQ
step profile_ivf python scripts/tpu_profile6.py --piece ivf --out results/tpu_profile6_r5.jsonl
step profile_bq  python scripts/tpu_profile6.py --piece bq  --out results/tpu_profile6_r5.jsonl

# 5. CAGRA engines A/B on the prebuilt index — batch-10 legs (the
#    reference's headline regime) + the HBM-engine block_q/placement
#    sweep (VERDICT r4 #4: hbm vs vmem vs XLA on real Mosaic)
step profile_cagra python scripts/tpu_profile6.py --piece cagra --out results/tpu_profile6_r5.jsonl

# 6. fknn slope legs — honest bf16 at the 2-vs-32 spread with in-run
#    f32-exact recall validation (VERDICT r4 #5)
step profile_fknn  python scripts/tpu_profile6.py --piece fknn  --out results/tpu_profile6_r5.jsonl
step profile_fknn96 env RAFT_TPU_VMEM_MB=96 RAFT_TPU_FKNN_TILES=0 \
  python scripts/tpu_profile6.py --piece fknn --out results/tpu_profile6_r5_v96.jsonl

# 7. per-primitive table
step prims python -m raft_tpu.bench.prims --size full --out results/prims_full_r5.jsonl

# 8. streaming scale build (long; VERDICT r4 #6 wants >= 10M rows on
#    chip). Params pinned explicitly so a rerun after a default change
#    stays comparable with recorded rows (8-bit codes: the
#    >=0.95-recall@10 regime, 0.988 refined in the 2M CPU rehearsal)
step scale bash -c 'set -o pipefail
  python scripts/tpu_scale_build.py --pq-bits 8 | tee -a results/scale_tpu_r5.jsonl'

# 9. cluster_join build timing — the leg that killed the r3 relay; LAST
step profile_cjoin python scripts/tpu_profile6.py --piece cjoin --out results/tpu_profile6_r5.jsonl

echo "round5_all COMPLETE $(date)" | tee -a "$LOG"
