#!/usr/bin/env python
"""Fifth-round TPU probes — only known-safe compositions (no fori_loop
around pallas_call; that wedged the remote-compile helper in round 4).

- fused_knn true per-pass cost via its `passes` grid-wrap mode (same
  compile shape family as the multi-read kernel that compiled fine).
- CAGRA search after the argsort-free merge rewrite (single dispatch —
  its internal while_loop makes the number real, not dispatch-bound).
- cluster_join full build wall time at 200k (vs 838 s for the old
  IVF-PQ-path CAGRA build and ~92 s for NN-descent at 50k).
- IVF-Flat / IVF-PQ single-dispatch timings for continuity with the
  round-2 numbers (both include the session's dispatch floor).
"""

import json
import os
import time

import numpy as np

# the beam/fused kernels derive their VMEM budget from device_kind;
# pin it here so an unrecognized relayed kind string can't silently
# disable every pallas leg (v5e measured safe at 64 MB)
os.environ.setdefault("RAFT_TPU_VMEM_MB", "64")

import jax
import jax.numpy as jnp


def wall(fn, iters=5):
    out = fn()
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    return (time.perf_counter() - t0) / iters


def emit(piece, **kw):
    print(json.dumps({"piece": piece, **kw}), flush=True)


def main():
    emit("config", backend=jax.default_backend(),
         device=jax.devices()[0].device_kind,
         vmem_mb=os.environ.get("RAFT_TPU_VMEM_MB"))

    from raft_tpu.distance.types import DistanceType
    from raft_tpu.ops.fused_topk import fused_knn

    big = jax.random.normal(jax.random.key(0), (1 << 20, 128), jnp.float32)
    bigb = big.astype(jnp.bfloat16)
    qs = jax.random.normal(jax.random.key(2), (10, 128), jnp.float32)
    norms = jnp.sum(jnp.square(big), axis=1)

    # ---- 1. fused_knn per-pass slope via the passes mode
    for tag, ds, payload in (("f32", big, 512e6), ("bf16", bigb, 256e6)):
        for tile in (0, 16384):  # 0 = auto (VMEM-budget sized)
            try:
                t2 = wall(lambda: fused_knn(qs, ds, 10,
                                            DistanceType.L2Expanded,
                                            dataset_norms=norms, tile=tile,
                                            passes=2))
                t8 = wall(lambda: fused_knn(qs, ds, 10,
                                            DistanceType.L2Expanded,
                                            dataset_norms=norms, tile=tile,
                                            passes=8))
                dt = (t8 - t2) / 6
                emit(f"fknn_{tag}_tile{tile}_slope",
                     iter_ms=round(dt * 1e3, 3),
                     gbps=round(payload / dt / 1e9, 1) if dt > 0 else -1,
                     t2_ms=round(t2 * 1e3, 2), t8_ms=round(t8 * 1e3, 2))
            except Exception as e:  # noqa: BLE001
                emit(f"fknn_{tag}_tile{tile}_slope", error=str(e)[:160])

    # ---- 2. datasets for the ANN pieces
    from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq
    from raft_tpu.utils import eval_recall

    rng = np.random.default_rng(0)
    x = rng.standard_normal((200_000, 128)).astype(np.float32)
    q = rng.standard_normal((100, 128)).astype(np.float32)
    _, gt_i = brute_force.knn(None, x, q, 10)
    gt = np.asarray(gt_i)

    # ---- 3. cluster_join graph build + CAGRA end-to-end
    t0 = time.perf_counter()
    ci = cagra.build(None, cagra.CagraIndexParams(
        graph_degree=32, intermediate_graph_degree=64,
        build_algo=cagra.BuildAlgo.CLUSTER_JOIN), x)
    np.asarray(ci.graph[:1])
    emit("cagra_build_cluster_join_200k",
         s=round(time.perf_counter() - t0, 1))

    # engines: XLA while_loop at f32, and the Pallas VMEM-resident
    # kernel on the bf16 index (200k x 128 f32 = 102 MB exceeds VMEM;
    # bf16 = 51 MB fits the 64 MB budget — the kernel's design point)
    ci16 = cagra.CagraIndex(dataset=ci.dataset.astype(jnp.bfloat16),
                            graph=ci.graph, metric=ci.metric)
    legs = [("xla_f32", ci, "xla"), ("pallas_bf16", ci16, "pallas"),
            ("xla_bf16", ci16, "xla")]
    for it in (64, 128):
        for tag, idx, algo in legs:
            sp = cagra.CagraSearchParams(itopk_size=it, search_width=4,
                                         algo=algo)
            try:
                dt = wall(lambda sp=sp, idx=idx:
                          cagra.search(None, sp, idx, q, 10), iters=10)
                _, i = cagra.search(None, sp, idx, q, 10)
                r, _, _ = eval_recall(gt, np.asarray(i))
                emit(f"cagra_search_itopk{it}_{tag}",
                     ms=round(dt * 1e3, 2),
                     qps=round(100 / dt, 1), recall=round(float(r), 4))
            except Exception as e:  # noqa: BLE001
                emit(f"cagra_search_itopk{it}_{tag}", error=str(e)[:200])

    # kernel block_q sweep (queries per grid step): the VMEM-resident
    # design's main tunable — pin the default from this
    try:
        from raft_tpu.ops.beam_search import beam_search, pad_graph

        seeds = jnp.asarray(
            rng.integers(0, len(x), (100, 4 * 32)).astype(np.int32))
        x16 = ci16.dataset
        # pad outside the timed loop, as cagra.search does — the sweep
        # must time the kernel, not a per-call graph pad
        pg = pad_graph(ci.graph)
        deg = ci.graph.shape[1]
        for bq in (4, 8, 16):
            dt = wall(lambda bq=bq: beam_search(
                jnp.asarray(q), x16, pg, seeds, 10, 64, 4, 40,
                ci.metric, block_q=bq, deg=deg), iters=10)
            emit(f"beam_blockq{bq}", ms=round(dt * 1e3, 2),
                 qps=round(100 / dt, 1))
    except Exception as e:  # noqa: BLE001
        emit("beam_blockq", error=str(e)[:200])

    # a 100k f32 slice fits VMEM — the f32 kernel datapoint
    try:
        ci100 = cagra.build(None, cagra.CagraIndexParams(
            graph_degree=32, intermediate_graph_degree=64,
            build_algo=cagra.BuildAlgo.CLUSTER_JOIN), x[:100_000])
        for algo in ("xla", "pallas"):
            sp = cagra.CagraSearchParams(itopk_size=64, search_width=4,
                                         algo=algo)
            dt = wall(lambda sp=sp: cagra.search(None, sp, ci100, q, 10),
                      iters=10)
            emit(f"cagra_search_100k_f32_{algo}", ms=round(dt * 1e3, 2),
                 qps=round(100 / dt, 1))
    except Exception as e:  # noqa: BLE001
        emit("cagra_search_100k_f32", error=str(e)[:200])

    # seed_pool variant (query-aware seeding)
    sp = cagra.CagraSearchParams(itopk_size=64, search_width=4,
                                 seed_pool=4096)
    dt = wall(lambda: cagra.search(None, sp, ci, q, 10), iters=10)
    _, i = cagra.search(None, sp, ci, q, 10)
    r, _, _ = eval_recall(gt, np.asarray(i))
    emit("cagra_search_itopk64_pool", ms=round(dt * 1e3, 2),
         qps=round(100 / dt, 1), recall=round(float(r), 4))

    # ---- 4. IVF continuity numbers (dispatch-floor inflated)
    fi = ivf_flat.build(None, ivf_flat.IvfFlatIndexParams(n_lists=1024), x)
    for p in (32, 64):
        sp = ivf_flat.IvfFlatSearchParams(n_probes=p)
        dt = wall(lambda sp=sp: ivf_flat.search(None, sp, fi, q, 10),
                  iters=10)
        emit(f"ivf_flat_p{p}", ms=round(dt * 1e3, 2),
             qps=round(100 / dt, 1))

    pi = ivf_pq.build(None, ivf_pq.IvfPqIndexParams(
        n_lists=1024, pq_dim=128, pq_bits=4), x)
    sp = ivf_pq.IvfPqSearchParams(n_probes=32)
    dt = wall(lambda: ivf_pq.search(None, sp, pi, q, 10), iters=10)
    _, i = ivf_pq.search(None, sp, pi, q, 10)
    r, _, _ = eval_recall(gt, np.asarray(i))
    emit("ivf_pq_b4_d128_p32", ms=round(dt * 1e3, 2),
         qps=round(100 / dt, 1), recall=round(float(r), 4))

    # ---- 5. IVF-BQ: the pure-MXU 1-bit index vs the PQ paths
    from raft_tpu.neighbors import ivf_bq
    from raft_tpu.neighbors.refine import refine as refine_fn

    bi = ivf_bq.build(None, ivf_bq.IvfBqIndexParams(n_lists=1024), x)
    xd = jnp.asarray(x)

    def bq_full(sp):
        # the end-to-end pipeline BOTH the ms and the recall describe:
        # estimate search (over-fetch 40) + exact refine to k=10
        _, cand = ivf_bq.search(None, sp, bi, q, 40)
        return refine_fn(None, xd, q, cand, 10)

    for p in (32, 64):
        sp = ivf_bq.IvfBqSearchParams(n_probes=p)
        dt = wall(lambda sp=sp: bq_full(sp), iters=10)
        _, i = bq_full(sp)
        r, _, _ = eval_recall(gt, np.asarray(i))
        emit(f"ivf_bq_p{p}_refined", ms=round(dt * 1e3, 2),
             qps=round(100 / dt, 1), recall=round(float(r), 4))

    # bits=2 (32 B/vec) — the multi-bit path added after round 2's
    # relay death; A/B against 4-bit PQ at equal bytes
    bi2 = ivf_bq.build(None, ivf_bq.IvfBqIndexParams(n_lists=1024, bits=2), x)

    def bq2_full(sp):
        _, cand = ivf_bq.search(None, sp, bi2, q, 40)
        return refine_fn(None, xd, q, cand, 10)

    for p in (32, 64):
        sp = ivf_bq.IvfBqSearchParams(n_probes=p)
        dt = wall(lambda sp=sp: bq2_full(sp), iters=10)
        _, i = bq2_full(sp)
        r, _, _ = eval_recall(gt, np.asarray(i))
        emit(f"ivf_bq2_p{p}_refined", ms=round(dt * 1e3, 2),
             qps=round(100 / dt, 1), recall=round(float(r), 4))

    # ---- 6. fp8 vs bf16 vs f32 LUT A/B at fixed probes
    for dt_name in ("float32", "bfloat16", "float8_e4m3fn"):
        lut_dt = getattr(jnp, dt_name)
        sp = ivf_pq.IvfPqSearchParams(n_probes=32, lut_dtype=lut_dt,
                                      score_mode="onehot")
        try:
            t = wall(lambda sp=sp: ivf_pq.search(None, sp, pi, q, 10),
                     iters=10)
            _, i = ivf_pq.search(None, sp, pi, q, 10)
            r, _, _ = eval_recall(gt, np.asarray(i))
            emit(f"ivf_pq_lut_{dt_name}", ms=round(t * 1e3, 2),
                 recall=round(float(r), 4))
        except Exception as e:  # noqa: BLE001
            emit(f"ivf_pq_lut_{dt_name}", error=str(e)[:160])


if __name__ == "__main__":
    main()
