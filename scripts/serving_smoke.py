#!/usr/bin/env python
"""Serving-frontend smoke on the real backend: a DynamicBatcher in
front of a warmed SearchExecutor takes bursty open-loop traffic and
the script asserts the PR-5 acceptance criteria end-to-end on chip —
results bit-identical to direct executor calls under coalescing and
re-splitting, batch occupancy >= 2x one-request-per-call — and
reports steady-state backend compiles (the warmed search executable
never recompiles; pad/slice micro-programs per NEW coalesced batch
size are the executor's documented small print). One JSON line per
piece (commit the output as hardware evidence, like
tpu_smoke_kernels.py).

graftragged (PR 15) pieces: ``ragged`` (IVF-flat packed-batch
acceptance), ``ragged_bq`` (the fused BQ engine through the same
ragged plan family), and ``ragged_mesh`` (the list-sharded index
serving packed replicated tiles on the REAL mesh) — each asserts
bit-parity vs the bucketed executor path and a zero-recompile steady
state, with the dual-tile executable count (≤ 2) and pad-waste split
reported as evidence.

graftbeam (PR 16) pieces: ``ragged_cagra`` and ``ragged_cagra_bq``
— the rebuilt CAGRA (content-pure coarse seeds, per-request
iteration budgets on the packed tile mask, BQ-coded traversal in the
bq piece) through the same ragged family, same assertions.

graftcast (PR 18) piece: ``prefetch_overlap`` — a seeded drifting
hot set with the forecast-driven prefetcher armed: lead-time stage
DMAs overlap live serving, the measured drift cycle shows prefetch
hits with ZERO backend compiles, and every dispatch stays
bit-identical to the all-HBM index.

Run: PYTHONPATH=/root/repo:/root/.axon_site python scripts/serving_smoke.py
"""

import json
import os

import numpy as np

os.environ.setdefault("RAFT_TPU_VMEM_MB", "64")
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                 "results", "jaxcache"))

import jax  # noqa: E402


def emit(piece, **kw):
    print(json.dumps({"piece": piece, **kw}), flush=True)


def main():
    emit("config", backend=jax.default_backend(),
         device=jax.devices()[0].device_kind)
    from raft_tpu import SearchExecutor
    from raft_tpu.core import tracing
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.serving import BatcherConfig, DynamicBatcher
    from raft_tpu.serving import metrics as sv_metrics
    from raft_tpu.serving.harness import burst_schedule, drive_open_loop

    rng = np.random.default_rng(0)
    x = rng.standard_normal((50_000, 128)).astype(np.float32)
    index = ivf_flat.build(
        None, ivf_flat.IvfFlatIndexParams(n_lists=64), x)
    p = ivf_flat.IvfFlatSearchParams(n_probes=8)
    ex = SearchExecutor()
    warm_s = ex.warmup(index, k=10, params=p)
    tracing.install_xla_compile_listener()

    # bit-identity under coalescing + re-split
    q = rng.standard_normal((48, 128)).astype(np.float32)
    want_d, want_i = (np.asarray(a)
                      for a in ex.search(index, q, 10, params=p))
    with DynamicBatcher(ex, BatcherConfig(max_wait_s=0.005)) as b:
        hs = [b.submit(index, q[at:at + m], 10, params=p)
              for at, m in ((0, 17), (17, 3), (20, 28))]
        got_d = np.concatenate(
            [np.asarray(h.result(timeout=60)[0]) for h in hs])
        got_i = np.concatenate(
            [np.asarray(h.result(timeout=60)[1]) for h in hs])
    bit_identical = (np.array_equal(got_i, want_i)
                     and np.array_equal(got_d, want_d))
    emit("bit_identity", ok=bool(bit_identical),
         warmup_seconds=round(warm_s, 3))
    assert bit_identical

    # bursty open-loop load: occupancy + zero-recompile steady state
    sv_metrics.reset()
    blocks = [rng.standard_normal(
        (int(rng.integers(1, 5)), 128)).astype(np.float32)
        for _ in range(200)]
    b = DynamicBatcher(ex, BatcherConfig(max_wait_s=0.002))
    # primer burst so per-batch-size pad programs land pre-measurement
    for h in [b.submit(index, blk, 10, params=p)
              for blk in blocks[:40]]:
        h.result(timeout=60)
    backend0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
    handles = drive_open_loop(
        lambda o, _t: b.submit(index, blocks[40 + o], 10, params=p),
        burst_schedule(n_bursts=10, burst_size=16, period_s=0.01,
                       start_s=b._clock.now()),
        b._clock)
    failures = sum(1 for h in handles
                   if h.exception(timeout=60) is not None)
    b.close()
    compiles = tracing.get_counter(tracing.XLA_COMPILE_COUNT) - backend0
    occ = sv_metrics.occupancy()
    emit("open_loop", requests=len(handles), failures=failures,
         requests_per_batch=round(occ["requests_per_batch"], 2),
         rows_per_batch=round(occ["rows_per_batch"], 2),
         backend_compiles_steady_state=int(compiles),
         e2e=sv_metrics.snapshot()["histograms"].get(
             sv_metrics.E2E, {}))
    assert failures == 0
    assert occ["requests_per_batch"] >= 2.0

    # PR 9 ragged acceptance on chip: ONE packed executable serves
    # mixed shapes zero-recompile, bit-identical to the bucketed path,
    # pad waste = final partial tiles only
    ex_r = SearchExecutor(ragged_tile=128)
    warm_r = ex_r.warmup_ragged(index, k=8, params=p)
    sv_metrics.reset()
    # mixed n_probes AND k inside ONE pow2 params class (n_probes
    # {5,8} -> class 8, k {7,8} -> class 8): one executable packs both
    p2 = ivf_flat.IvfFlatSearchParams(n_probes=5)
    with DynamicBatcher(ex_r, BatcherConfig(max_wait_s=0.002,
                                            ragged=True)) as br:
        # primer pass (transfer programs for the packed shapes)
        for h in [br.submit(index, blk, 8, params=p)
                  for blk in blocks[:20]]:
            h.result(timeout=60)
        backend0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        hs = [br.submit(index, blk, 8 if j % 2 else 7,
                        params=p if j % 2 else p2)
              for j, blk in enumerate(blocks[20:120])]
        ragged_failures = sum(1 for h in hs
                              if h.exception(timeout=60) is not None)
        ragged_compiles = (
            tracing.get_counter(tracing.XLA_COMPILE_COUNT) - backend0)
        j, ragged_bits = 0, True
        for h, blk in zip(hs, blocks[20:120]):
            k_j, p_j = (8, p) if j % 2 else (7, p2)
            want = ex_r.search(index, blk, k_j, params=p_j)
            got = h.result(timeout=60)
            ragged_bits = ragged_bits and np.array_equal(
                np.asarray(got[1]), np.asarray(want[1]))
            j += 1
    emit("ragged",
         ok=bool(ragged_bits and ragged_failures == 0),
         warmup_seconds=round(warm_r, 3),
         executables=ex_r.ragged_executables(),
         backend_compiles_steady_state=int(ragged_compiles),
         pad_waste_fraction=round(
             sv_metrics.derived()["pad_waste_fraction"], 4))
    assert ragged_bits and ragged_failures == 0
    assert ex_r.ragged_executables() == 1

    # graftragged acceptance on chip: the BQ fused engine and the
    # real mesh serve the SAME ragged plan family — bit-parity vs the
    # bucketed path and zero-recompile steady state, per family
    def ragged_family_piece(piece, idx, params, make_params, **sub_kw):
        """Drive mixed-k/mixed-n_probes traffic through one family's
        ragged front (dual tile) and assert bit-parity vs that
        family's bucketed executor path + zero steady-state backend
        compiles."""
        ex_f = SearchExecutor(ragged_tile=128, ragged_tile_small=32)
        warm_f = ex_f.warmup_ragged(idx, k=8, params=params, **sub_kw)
        sv_metrics.reset()
        with DynamicBatcher(ex_f, BatcherConfig(max_wait_s=0.002,
                                                ragged=True)) as bf:
            for h in [bf.submit(idx, blk, 8, params=params, **sub_kw)
                      for blk in blocks[:20]]:
                h.result(timeout=120)        # primer (plane creation)
            b0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
            hs, wants = [], []
            for j, blk in enumerate(blocks[20:100]):
                k_j, p_j = (8, params) if j % 2 else (7, make_params())
                hs.append(bf.submit(idx, blk, k_j, params=p_j,
                                    **sub_kw))
                wants.append((blk, k_j, p_j))
            fails = sum(1 for h in hs
                        if h.exception(timeout=120) is not None)
            compiles = (tracing.get_counter(tracing.XLA_COMPILE_COUNT)
                        - b0)
            bits = True
            for h, (blk, k_j, p_j) in zip(hs, wants):
                want = ex_f.search(idx, blk, k_j, params=p_j, **sub_kw)
                got = h.result(timeout=120)
                bits = bits and np.array_equal(
                    np.asarray(got[1]), np.asarray(want[1]))
        emit(piece, ok=bool(bits and fails == 0),
             warmup_seconds=round(warm_f, 3),
             executables=ex_f.ragged_executables(),
             backend_compiles_steady_state=int(compiles),
             pad_waste_fraction=round(
                 sv_metrics.derived()["pad_waste_fraction"], 4),
             pad_waste_by_class=sv_metrics.derived()
             ["pad_waste_by_class"])
        assert bits and fails == 0
        assert ex_f.ragged_executables() <= 2

    from raft_tpu.neighbors import ivf_bq

    bq_index = ivf_bq.build(
        None, ivf_bq.IvfBqIndexParams(n_lists=64, bits=2), x)
    ragged_family_piece(
        "ragged_bq", bq_index, ivf_bq.IvfBqSearchParams(n_probes=8),
        lambda: ivf_bq.IvfBqSearchParams(n_probes=5))

    # graftbeam acceptance on chip: CAGRA — coarse seeds are a pure
    # function of query content, so its blocks concatenate and it
    # serves through the SAME ragged plan family (the per-block
    # dispatch exemption is deleted, not bypassed). Evidence debt the
    # two pieces retire on real silicon: per-request iteration
    # budgets riding the packed tile mask keep bit-parity with the
    # bucketed path, and (bq piece) the packed record plane's
    # bitcast_convert_type lanes + non-128-lane record window selects
    # survive Mosaic compilation inside the serving executable.
    from raft_tpu.neighbors import cagra

    g_index = cagra.build(None, cagra.CagraIndexParams(
        graph_degree=32, bq_bits=2), x)
    ragged_family_piece(
        "ragged_cagra", g_index, cagra.CagraSearchParams(),
        lambda: cagra.CagraSearchParams(max_iterations=100))
    ragged_family_piece(
        "ragged_cagra_bq", g_index,
        cagra.CagraSearchParams(bq_traversal="on"),
        lambda: cagra.CagraSearchParams(bq_traversal="on",
                                        max_iterations=100))

    # graftcast acceptance on chip (PR 18): forecast-driven prefetch
    # overlapping live serving — a seeded drifting hot set drives
    # lead-time stage DMAs; after one warm drift cycle the measured
    # drift must show prefetch HITS (the epoch consumed staged
    # blocks), ZERO backend compiles, and per-dispatch bit-identity
    # to the all-HBM index throughout. Evidence CI cannot collect:
    # whether the stage DMA truly overlaps the serving stream on the
    # real chip (CPU serializes host work), and the ICI/host-link
    # contention a concurrent stage creates — the on-chip numbers
    # this piece records.
    try:
        from raft_tpu.neighbors import tiered as tiered_mod
        from raft_tpu.serving.harness import ManualClock
        from raft_tpu.serving.placement import (PlacementConfig,
                                                TierManager)
        from raft_tpu.serving.prefetch import PrefetchConfig

        t_idx = tiered_mod.build_tiered(index, hot_fraction=0.5)
        tp = tiered_mod.TieredSearchParams(n_probes=8)
        ex_t = SearchExecutor(probe_accounting=True)
        ex_t.warmup(t_idx, buckets=(ex_t.bucket_for(16),), k=10,
                    params=tp)
        clk = ManualClock()
        mgr = TierManager(t_idx, ex_t, clock=clk,
                          config=PlacementConfig(
                              epoch_every_s=60.0,
                              max_swaps_per_epoch=4,
                              prefetch_lead_s=10.0))
        mgr.enable_prefetch(config=PrefetchConfig(alpha=0.5))
        centers_np = np.asarray(t_idx.centers)
        hot0 = [int(lid) for lid in t_idx.hot_lists[:8]]
        cold0 = [int(lid) for lid in t_idx.cold_lists[:8]]

        def drift(lists, ticks):
            bits = True
            rng2 = np.random.default_rng(11)
            lists = np.asarray(lists)
            for _ in range(ticks):
                lids = lists[rng2.integers(0, len(lists), 16)]
                qd = (centers_np[lids] + 0.01 * rng2.standard_normal(
                    (16, 128))).astype(np.float32)
                dt_, it_ = ex_t.search(t_idx, qd, 10, params=tp)
                df_, if_ = ex_t.search(index, qd, 10, params=p)
                bits = bits and np.array_equal(
                    np.asarray(it_), np.asarray(if_))
                clk.advance(11.0)
                mgr.tick()
            return bits

        ok_bits = drift(hot0, 12)
        ok_bits = drift(cold0, 14) and ok_bits   # warm drift cycle
        pc0 = dict(tracing.counters())
        ok_bits = drift(hot0, 14) and ok_bits    # measured drift
        pc1 = dict(tracing.counters())

        def pdelta(name):
            return float(pc1.get(name, 0) - pc0.get(name, 0))

        hits = pdelta("tier.prefetch.hits")
        emit("prefetch_overlap", ok=bool(ok_bits and hits > 0),
             bit_identical=bool(ok_bits),
             prefetch_issued=pdelta("tier.prefetch.issued"),
             prefetch_hits=hits,
             prefetch_misses=pdelta("tier.prefetch.misses"),
             promote_cold_bytes=pdelta("tier.promote_cold_bytes"),
             backend_compiles_steady_state=int(
                 pdelta(tracing.XLA_COMPILE_COUNT)))
        assert ok_bits and hits > 0
        assert pdelta(tracing.XLA_COMPILE_COUNT) == 0
    except AssertionError:
        raise
    except Exception as e:  # noqa: BLE001
        emit("prefetch_overlap", error=str(e)[:300])

    if jax.device_count() >= 2:
        from raft_tpu.comms import local_comms
        from raft_tpu.distributed import ivf as dist_ivf

        comms = local_comms()
        mesh_index = dist_ivf.build(
            None, comms, ivf_flat.IvfFlatIndexParams(n_lists=64), x)
        ragged_family_piece(
            "ragged_mesh", mesh_index,
            ivf_flat.IvfFlatSearchParams(n_probes=8),
            lambda: ivf_flat.IvfFlatSearchParams(n_probes=5),
            probe_mode="global")
        emit("ragged_mesh_info", shards=comms.size)
    else:
        emit("ragged_mesh", skipped="single-device host — mesh ragged "
             "needs a real mesh")
    emit("done", ok=True)


if __name__ == "__main__":
    main()
