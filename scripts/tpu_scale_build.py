#!/usr/bin/env python
"""Single-chip scale proof: streaming IVF-PQ build at 100M+ rows —
VERDICT r2 item #4. Exercises the billion-row plumbing (2-D slot
indexing, native IO prefetch) at a dataset size many times HBM
(100M × 96 f32 = 38.4 GB vs 16 GB HBM on v5e); the role of the
reference's managed-memory spill (``ivf_pq_build.cuh:1542-1554``).

Stages (each timed, JSON lines on stdout):
  1. generate the fbin on disk in chunks (skipped if present)
  2. ivf_pq.build_streaming over the file
  3. search QPS at n_probes in {32, 64}
  4. recall@10 against a streamed exact ground truth (chunked
     brute-force scan + knn_merge_parts)

Usage: python scripts/tpu_scale_build.py [--rows 100000000] [--dim 96]
       [--path /tmp/scale.fbin] [--queries 100] [--rehearsal]
(--rehearsal = 2M rows; the CPU-sized dry run of the same code path.)
"""

import argparse
import json
import os

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                 "results", "jaxcache"))
import time

import numpy as np


def emit(piece, **kw):
    print(json.dumps({"piece": piece, **kw}), flush=True)


def gen_fbin(path: str, rows: int, dim: int, chunk: int = 1 << 20,
             n_clusters: int = 4096, seed: int = 7):
    """Clustered synthetic data (IVF's target regime), written chunkwise
    so host memory stays at one chunk."""
    want_bytes = 8 + rows * dim * 4
    if os.path.exists(path):
        with open(path, "rb") as f:
            hdr = np.fromfile(f, np.int32, 2)
        # header AND size must match — a crashed prior run leaves a
        # truncated file with a valid header
        if (len(hdr) == 2 and hdr[0] == rows and hdr[1] == dim
                and os.path.getsize(path) == want_bytes):
            emit("gen", skipped=True)
            return
    rng = np.random.default_rng(seed)
    centers = (rng.standard_normal((n_clusters, dim)) * 4).astype(np.float32)
    t0 = time.perf_counter()
    with open(path, "wb") as f:
        np.asarray([rows, dim], np.int32).tofile(f)
        for start in range(0, rows, chunk):
            n = min(chunk, rows - start)
            labels = rng.integers(0, n_clusters, n)
            block = centers[labels] + rng.standard_normal(
                (n, dim)).astype(np.float32)
            block.astype(np.float32).tofile(f)
    emit("gen", s=round(time.perf_counter() - t0, 1),
         gb=round(rows * dim * 4 / 1e9, 1))


def exact_gt(ds, q, k: int, chunk: int = 1 << 20):
    """Streamed exact ground truth: chunked fused/brute scan + merge."""
    import jax.numpy as jnp
    from raft_tpu.neighbors import brute_force
    from raft_tpu.neighbors.brute_force import knn_merge_parts

    parts_d, parts_i = [], []
    for start in range(0, ds.n_rows, chunk):
        n = min(chunk, ds.n_rows - start)
        block = ds.read(start, n)
        d, i = brute_force.knn(None, block, q, k)
        parts_d.append(jnp.asarray(d))
        parts_i.append(jnp.asarray(i) + start)
    all_d = jnp.stack(parts_d)                  # (P, q, k)
    all_i = jnp.stack(parts_i)
    return knn_merge_parts(all_d, all_i, True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=100_000_000)
    ap.add_argument("--dim", type=int, default=96)
    ap.add_argument("--path", default="/tmp/scale.fbin")
    ap.add_argument("--queries", type=int, default=100)
    ap.add_argument("--n-lists", type=int, default=0,
                    help="0 = auto (~sqrt(n) rounded to 1k)")
    ap.add_argument("--pq-bits", type=int, default=8,
                    help="codebook bits (8 = the reference's high-"
                         "recall regime; 4 halves the code bytes)")
    ap.add_argument("--pq-dim", type=int, default=0,
                    help="0 = dim/2 (codes dim/2 bytes/vector at 8 "
                         "bits)")
    ap.add_argument("--rehearsal", action="store_true",
                    help="2M rows — the CPU dry run of the same path")
    args = ap.parse_args()
    if args.rehearsal:
        args.rows = min(args.rows, 2_000_000)

    import jax
    pq_dim = args.pq_dim or args.dim // 2
    emit("config", backend=jax.default_backend(), rows=args.rows,
         dim=args.dim, pq_dim=pq_dim, pq_bits=args.pq_bits)

    from raft_tpu.io import BinDataset
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.utils import eval_recall

    gen_fbin(args.path, args.rows, args.dim)
    ds = BinDataset(args.path)
    rng = np.random.default_rng(1)
    qpos = rng.integers(0, ds.n_rows, args.queries)
    q = np.stack([ds.read(int(p), 1)[0] for p in qpos])
    q = q + rng.standard_normal(q.shape).astype(np.float32)

    n_lists = args.n_lists or max(1024,
                                  int(round((args.rows ** 0.5) / 1024)) * 1024)
    params = ivf_pq.IvfPqIndexParams(
        n_lists=n_lists, pq_dim=pq_dim, pq_bits=args.pq_bits,
        kmeans_n_iters=10)
    t0 = time.perf_counter()
    index = ivf_pq.build_streaming(None, params, ds)
    np.asarray(index.list_sizes[:1])
    build_s = time.perf_counter() - t0
    # stored bytes/vector, not logical: codes are one uint8 per
    # sub-dim except the packed 4-bit/even-pq_dim layout (ivf_pq.py)
    packed = args.pq_bits == 4 and pq_dim % 2 == 0
    emit("build_streaming", s=round(build_s, 1),
         vectors_per_s=round(args.rows / build_s),
         n_lists=n_lists,
         pq_stored_bytes=pq_dim // 2 if packed else pq_dim)

    gt_t0 = time.perf_counter()
    _, gt_i = exact_gt(ds, q, 10)
    gt = np.asarray(gt_i)
    emit("exact_gt", s=round(time.perf_counter() - gt_t0, 1))

    def disk_refine(cand, k):
        """Exact re-rank of over-fetched candidates with rows read
        straight off the fbin (the dataset exceeds HBM by design, so
        refinement gathers from disk — the role of the reference's
        host-memory refinement pass)."""
        cand = np.asarray(cand)
        out = np.empty((cand.shape[0], k), np.int64)
        for qi in range(cand.shape[0]):
            ids = cand[qi][cand[qi] >= 0]
            rows = np.stack([ds.read(int(r), 1)[0] for r in ids])
            dd = np.sum((rows - q[qi]) ** 2, axis=1)
            out[qi] = ids[np.argsort(dd, kind="stable")[:k]]
        return out

    for p in (32, 64):
        sp = ivf_pq.IvfPqSearchParams(n_probes=p)
        d, i = ivf_pq.search(None, sp, index, q, 10)   # compile
        np.asarray(i[:1])
        t0 = time.perf_counter()
        iters = 5
        for _ in range(iters):
            d, i = ivf_pq.search(None, sp, index, q, 10)
        np.asarray(i[:1])
        dt = (time.perf_counter() - t0) / iters
        r, _, _ = eval_recall(gt, np.asarray(i))
        emit(f"search_p{p}", ms=round(dt * 1e3, 2),
             qps=round(args.queries / dt, 1), recall=round(float(r), 4))

        # over-fetch 4x + exact disk refine (recall as the reference
        # reports it: refine_ratio 4, raft_ann_benchmarks.md)
        _, cand = ivf_pq.search(None, sp, index, q, 40)
        ref_ids = disk_refine(cand, 10)
        r4, _, _ = eval_recall(gt, ref_ids)
        emit(f"search_p{p}_refined4x", recall=round(float(r4), 4))


if __name__ == "__main__":
    main()
