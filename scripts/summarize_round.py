#!/usr/bin/env python
"""Collect hardware evidence into one markdown report (round-aware).

For every evidence stream this reads BOTH the committed ci/ archives
(from this round and prior rounds) AND the live results/ files, then
dedupes newest-wins — so a workspace reset can never regress the report
to fewer rows than what is already committed (ADVICE r3, medium).

Every row is stamped with the round it was CAPTURED in (VERDICT r4
weak #3: a results file must never present old hardware data as new):
a record's provenance is the earliest source file it appears in with
identical content; the source files are round-named (ci/..._rN...), so
carried-forward evidence keeps its original round label even after
being re-archived, and only genuinely new records get this round's.

Streams (any subset may exist):
  smoke    ci/tpu_smoke_kernels_r{3..N}.json + results/tpu_smoke_rN.jsonl
  profile  ci/tpu_profile6_r{3..N}.jsonl + results/tpu_profile6_rN.jsonl
  bench    ci/bench_headline_r{3..N}.json + results/bench_headline.json
  sweep    ci/sweep1m_results_r{3..N}.jsonl + results/sweep-1M/results.jsonl
  scale    ci/scale_tpu_r{3..N}.jsonl + results/scale_tpu_rN.jsonl
  prims    ci/prims_full_r{3..N}.jsonl + results/prims_full_rN.jsonl

Writes RESULTS_r{N}.md (repo root). Purely host-side — safe anytime.

Run: python scripts/summarize_round.py [--round 5]
"""

import argparse
import json
import pathlib
import re

ROOT = pathlib.Path(__file__).resolve().parent.parent

# rows from a live (round-unnamed) file can only have been captured
# this round or — for files that predate the current round's first
# archive pass — an earlier one; the caller passes the label to use
_SRC_KEY = "_captured"


def round_of_path(path: str, live_label: str) -> str:
    m = re.search(r"_r(\d+)", pathlib.Path(path).name)
    return f"r{m.group(1)}" if m else live_label


def read_jsonl(path):
    rows = []
    p = ROOT / path
    if not p.exists():
        return rows
    for line in p.read_text().splitlines():
        line = line.strip()
        if not line or not line.startswith("{"):
            continue
        try:
            rows.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return rows


def dedupe_last(rows, key_fields):
    """Keep the LAST record per key — reruns append, newest wins.
    Provenance: when the newer record's content is identical to the
    one it replaces, the original capture label is kept (the record
    was merely re-archived); only a content change re-stamps it."""
    out = {}
    for r in rows:
        key = tuple(str(r.get(k)) for k in key_fields)
        prev = out.get(key)
        if prev is not None:
            same = {k: v for k, v in prev.items() if k != _SRC_KEY} == \
                   {k: v for k, v in r.items() if k != _SRC_KEY}
            if same:
                continue  # identical re-archive: keep first-seen stamp
        out[key] = r
    return list(out.values())


def read_all(paths, key_fields=None, live_label="live"):
    """Concatenate sources oldest-first, stamping each row with the
    round its source file encodes, and (optionally) dedupe so the
    newest record per key wins (identical re-archives keep their
    original stamp)."""
    rows = []
    for p in paths:
        label = round_of_path(p, live_label)
        for r in read_jsonl(p):
            r.setdefault(_SRC_KEY, label)
            rows.append(r)
    if key_fields:
        rows = dedupe_last(rows, key_fields)
    return rows


def fmt_table(rows, cols, header=None):
    if not rows:
        return "_no data captured_\n"
    cols = list(cols) + [_SRC_KEY]
    head = (list(header) if header else list(cols[:-1])) + ["captured"]
    lines = ["| " + " | ".join(head) + " |",
             "|" + "|".join("---" for _ in head) + "|"]
    for r in rows:
        lines.append("| " + " | ".join(
            "" if r.get(c) is None else str(r.get(c)) for c in cols) + " |")
    return "\n".join(lines) + "\n"


def sources(rnd, ci_tmpl, live):
    """Paths for one stream: prior-round ci archives (oldest first),
    this round's ci archive, then the live results file (newest)."""
    out = [ci_tmpl.format(r) for r in range(3, rnd + 1)]
    out += [live] if isinstance(live, str) else list(live)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, default=5)
    args = ap.parse_args()
    rnd = args.round
    live = f"r{rnd}"  # rows in round-unnamed live files are this round's

    out = [f"# Round-{rnd} hardware evidence (TPU v5e via relay)", "",
           "Every row/record carries a `captured` stamp: the round whose "
           "capture produced it. `r{N}` for N < " + str(rnd) +
           " is carried-forward evidence (committed in that round, "
           "re-read from its ci/ archive); only rows stamped "
           f"`r{rnd}` are new this round.", ""]

    smoke = read_all(
        sources(rnd, "ci/tpu_smoke_kernels_r{}.json",
                f"results/tpu_smoke_r{rnd}.jsonl"), ("piece",),
        live_label=live)
    if smoke:
        lines, used = [], 0
        for r in smoke:  # whole records only; never cut JSON mid-object
            s = json.dumps(r)
            if used + len(s) > 3000:
                lines.append(f"... {len(smoke) - len(lines)} more records "
                             "truncated")
                break
            lines.append(s)
            used += len(s)
        out += ["## Pallas kernel parity smoke (compiled Mosaic)",
                "", "```json", "\n".join(lines), "```", ""]

    prof = read_all(
        sources(rnd, "ci/tpu_profile6_r{}.jsonl",
                f"results/tpu_profile6_r{rnd}.jsonl"), ("piece",),
        live_label=live)
    prof96 = read_all(
        sources(rnd, "ci/tpu_profile6_r{}_v96.jsonl",
                ["results/tpu_profile6_r3_v96.jsonl",
                 f"results/tpu_profile6_r{rnd}_v96.jsonl"]), ("piece",),
        live_label=live)
    if prof:
        out += ["## Profile pieces (slope-timed; per-dtype spreads)", "",
                fmt_table(prof, ["piece", "iter_ms", "gbps", "ms", "qps",
                                 "recall", "error"])]
    if prof96:
        out += ["### fknn at RAFT_TPU_VMEM_MB=96 (auto tiles)", "",
                fmt_table(prof96, ["piece", "iter_ms", "gbps", "error"])]

    bench = read_all(
        sources(rnd, "ci/bench_headline_r{}.json",
                "results/bench_headline.json"), ("metric",),
        live_label=live)
    if bench:
        out += ["## Headline bench (driver format)", "",
                "```json", "\n".join(json.dumps(b) for b in bench), "```",
                ""]

    sweep = read_all(
        sources(rnd, "ci/sweep1m_results_r{}.jsonl",
                "results/sweep-1M/results.jsonl"), live_label=live)
    sweep = dedupe_last(
        [r for r in sweep if r.get("algo")],
        ("algo", "backend", "build_params", "search_params"))
    if sweep:
        for r in sweep:
            r["build"] = json.dumps(r.get("build_params"))
            r["search"] = json.dumps(r.get("search_params"))
        out += ["## Recall-vs-QPS sweep, blobs-1M-128 (batch = full query "
                "set unless noted)", "",
                fmt_table(sweep, ["algo", "backend", "build", "search",
                                  "qps", "recall", "build_seconds",
                                  "build_cached"])]

    scale = read_all(
        sources(rnd, "ci/scale_tpu_r{}.jsonl",
                f"results/scale_tpu_r{rnd}.jsonl"), ("piece", "backend"),
        live_label=live)
    scale_note = ""
    if not scale:
        # fall back to the newest CPU rehearsal, clearly labeled
        logs = list(ROOT.glob("results/scale_rehearsal*.log"))
        if logs:
            newest = max(logs, key=lambda p: p.stat().st_mtime)
            scale = read_jsonl(newest.relative_to(ROOT))
            # a rehearsal log's capture round is not derivable from its
            # name — stamp the source file instead of guessing a round
            for r in scale:
                r.setdefault(_SRC_KEY, f"cpu-rehearsal ({newest.name})")
            scale_note = (" — **CPU rehearsal only** (no TPU run "
                          "captured)")
    if scale:
        out += [f"## Streaming scale build (IVF-PQ over fbin > HBM)"
                f"{scale_note}", "",
                fmt_table(scale, ["piece", "backend", "rows", "dim",
                                  "pq_bits", "s", "vectors_per_s", "ms",
                                  "qps", "recall"])]

    prims = read_all(
        sources(rnd, "ci/prims_full_r{}.jsonl",
                f"results/prims_full_r{rnd}.jsonl"), ("prim", "shape"),
        live_label=live)
    if prims:
        out += ["## Per-primitive micro-bench (--size full)", "",
                fmt_table(prims, ["prim", "shape", "ms", "gbps", "bw_frac",
                                  "mfu"])]

    report = ROOT / f"RESULTS_r{rnd}.md"
    report.write_text("\n".join(out) + "\n")
    new_rows = sum(1 for r in prof + prof96 + sweep + scale + prims
                   + smoke + bench if r.get(_SRC_KEY) == live)
    print(f"wrote {report} "
          f"({len(prof)} profile rows, {len(sweep)} sweep rows, "
          f"{len(scale)} scale rows, {len(prims)} prim rows; "
          f"{new_rows} records captured this round)")


if __name__ == "__main__":
    main()
