#!/usr/bin/env python
"""Regenerate the committed device-free ``.xplane.pb`` fixture
(``tests/data/graftfleet_capture.xplane.pb``) — the protobuf twin of
the chrome-trace fixture's mesh module, written with a minimal wire
encoder so the test pins :mod:`raft_tpu.core.xplane` against bytes no
jax/profiler version can move underneath it.

Logical content mirrors ``graftflight_capture.trace.json``'s
``jit_rt_dist_ivf_flat_bbbb02bbbb02`` events exactly — two mesh
dispatches on two TPU device planes with the named-scope phase
markers in ``tf_op`` — so ``profiling.attribute`` over either fixture
yields the SAME pinned mesh attribution. One plane interns the module
name through ``ref_value`` stats, the other carries plain
``str_value`` stats: both resolution paths the reader supports are in
the committed bytes. A host plane with module-less python events
proves the skip path.

Run:  python scripts/make_xplane_fixture.py
"""

import os
import struct

OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "data",
    "graftfleet_capture.xplane.pb")

MODULE = "jit_rt_dist_ivf_flat_bbbb02bbbb02"

# (op name, tf_op scope, offset_us, dur_us) per device plane — the
# same timings the chrome fixture pins (line timestamp carries the
# 1000 us base, offsets are line-relative)
EVENTS = {
    "/device:TPU:0": [
        ("all-gather.3", "jit(rt)/coarse_select/all_gather", 0, 100),
        ("fusion.9", "jit(rt)/scan/while", 100, 400),
        ("sort.12", "jit(rt)/merge/sort", 500, 50),
        ("all-gather.3", "jit(rt)/coarse_select/all_gather", 1000, 100),
        ("fusion.9", "jit(rt)/scan/while", 1100, 400),
        ("sort.12", "jit(rt)/merge/sort", 1500, 50),
    ],
    "/device:TPU:1": [
        ("all-gather.3", "jit(rt)/coarse_select/all_gather", 0, 100),
        ("fusion.9", "jit(rt)/scan/while", 100, 600),
        ("sort.12", "jit(rt)/merge/sort", 700, 50),
        ("all-gather.3", "jit(rt)/coarse_select/all_gather", 1000, 100),
        ("fusion.9", "jit(rt)/scan/while", 1100, 600),
        ("sort.12", "jit(rt)/merge/sort", 1700, 50),
    ],
}
LINE_T0_NS = 1_000_000          # 1000 us — matches the chrome fixture


def varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | (0x80 if v else 0))
        if not v:
            return bytes(out)


def field(num: int, wtype: int, payload: bytes) -> bytes:
    return varint((num << 3) | wtype) + payload


def fv(num: int, v: int) -> bytes:                 # varint field
    return field(num, 0, varint(v))


def fs(num: int, s) -> bytes:                      # length-delimited
    b = s.encode() if isinstance(s, str) else s
    return field(num, 2, varint(len(b)) + b)


def stat_str(mid: int, s: str) -> bytes:           # XStat str_value
    return fv(1, mid) + fs(5, s)


def stat_ref(mid: int, ref: int) -> bytes:         # XStat ref_value
    return fv(1, mid) + fv(7, ref)


def stat_double(mid: int, v: float) -> bytes:      # XStat double_value
    return fv(1, mid) + field(2, 1, struct.pack("<d", v))


def event(md_id: int, offset_us: float, dur_us: float,
          stats) -> bytes:
    out = (fv(1, md_id) + fv(2, int(offset_us * 1e6))
           + fv(3, int(dur_us * 1e6)))
    for s in stats:
        out += fs(4, s)
    return out


def map_entry(key: int, name: str) -> bytes:
    md = fv(1, key) + fs(2, name)
    return fv(1, key) + fs(2, md)


def plane(name: str, events, *, intern_module: bool) -> bytes:
    """One XPlane: event metadata ids intern the op names; stat
    metadata ids 1/2 name the ``hlo_module``/``tf_op`` stats (and,
    when ``intern_module``, id 10 interns the module STRING so the
    module stat is a ``ref_value`` — the other resolution path)."""
    op_ids = {}
    for op, _, _, _ in events:
        op_ids.setdefault(op, len(op_ids) + 1)
    evs = []
    for op, scope, off, dur in events:
        if intern_module:
            stats = [stat_ref(1, 10), stat_str(2, scope)]
        else:
            stats = [stat_str(1, MODULE), stat_str(2, scope)]
        evs.append(event(op_ids[op], off, dur, stats))
    line = fs(2, "XLA Ops") + fv(3, LINE_T0_NS)
    for ev in evs:
        line += fs(4, ev)
    out = fs(2, name) + fs(3, line)
    for op, mid in op_ids.items():
        out += fs(4, map_entry(mid, op))
    out += fs(5, map_entry(1, "hlo_module"))
    out += fs(5, map_entry(2, "tf_op"))
    if intern_module:
        out += fs(5, map_entry(10, MODULE))
    return out


def host_plane() -> bytes:
    """Module-less python events the reader must skip — plus an
    unknown-kind stat (double) on one of them."""
    line = fs(2, "python") + fv(3, LINE_T0_NS)
    line += fs(4, event(1, 0, 500, [stat_double(2, 0.5)]))
    line += fs(4, event(2, 600, 80, []))
    out = fs(2, "/host:CPU") + fs(3, line)
    out += fs(4, map_entry(1, "$lax_numpy.py:6155 ones"))
    out += fs(4, map_entry(2, "ThreadpoolListener::StartRegion"))
    out += fs(5, map_entry(2, "tf_op"))
    return out


def main() -> None:
    space = (fs(1, plane("/device:TPU:0", EVENTS["/device:TPU:0"],
                         intern_module=False))
             + fs(1, plane("/device:TPU:1", EVENTS["/device:TPU:1"],
                           intern_module=True))
             + fs(1, host_plane()))
    with open(OUT, "wb") as f:
        f.write(space)
    print(f"wrote {OUT} ({len(space)} bytes)")


if __name__ == "__main__":
    main()
