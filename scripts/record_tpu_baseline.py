#!/usr/bin/env python
"""Record a TPU bench baseline for the CI perf-regression gate.

The committed ``ci/bench_baseline.json`` pins a small CPU config so
every CI run gates somewhere; the numbers that actually matter are
TPU numbers. Run this ON a TPU host to record
``ci/bench_baseline_tpu.json`` — the same record/tolerances shape,
plus ``"requires_backend": "tpu"`` so ``ci/bench_compare.py`` (which
gates every ``ci/bench_baseline*.json`` by default) skips it with a
note on CPU-only runners and gates it wherever a TPU is present.

Commit the output file to put TPU throughput under the same
regression bands as the CPU smoke::

    python scripts/record_tpu_baseline.py            # defaults
    BENCH_N=1000000 python scripts/record_tpu_baseline.py  # bigger pin

Any ``BENCH_*`` already in the environment overrides the default pin
(recorded into the baseline, so compare runs replay exactly what was
measured).
"""

from __future__ import annotations

import importlib.util
import json
import os
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent

# TPU pin: the CPU config's shape scaled to something a TPU core
# notices, serving rider on. Deliberately modest — the gate needs a
# stable signal, not a record run.
TPU_PINNED_ENV = {
    "BENCH_CHILD": "1",
    "BENCH_N": "200000",
    "BENCH_DIM": "128",
    "BENCH_BATCH": "64",
    "BENCH_K": "10",
    "BENCH_SECONDS": "5",
    "BENCH_DTYPE": "float32",
    "BENCH_SERVING": "1",
    "BENCH_SV_N": "200000",
    "BENCH_SV_LISTS": "256",
    "BENCH_SV_BURSTS": "40",
    "BENCH_SV_BURST": "16",
    "BENCH_SV_PERIOD_MS": "5",
    "BENCH_SV_WAIT_MS": "2",
    "BENCH_SV_TIMEOUT_MS": "2000",
}


def load_bench_compare():
    spec = importlib.util.spec_from_file_location(
        "bench_compare", REPO / "ci" / "bench_compare.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main() -> int:
    import jax

    if jax.default_backend() != "tpu":
        sys.stderr.write(
            "record_tpu_baseline: no TPU backend present "
            f"(default_backend={jax.default_backend()!r}) — run this "
            "on a TPU host\n")
        return 2
    bc = load_bench_compare()
    env = dict(TPU_PINNED_ENV)
    # operator overrides (larger corpus, different burst shape) are
    # recorded into the baseline so replays measure the same problem
    env.update({k: v for k, v in os.environ.items()
                if k.startswith("BENCH_")})
    env["BENCH_CHILD"] = "1"
    print(f"record_tpu_baseline: running pinned TPU config "
          f"({env['BENCH_N']}x{env['BENCH_DIM']})", flush=True)
    record = bc.run_bench(env)
    out_path = REPO / "ci" / "bench_baseline_tpu.json"
    out = {
        "env": env,
        "requires_backend": "tpu",
        "tolerances": bc.DEFAULT_TOLERANCES,
        "snapshot_floors": bc.SNAPSHOT_FLOORS,
        "record": record,
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"record_tpu_baseline: wrote {out_path} — commit it to gate "
          "TPU throughput in CI (skipped automatically off-TPU)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
