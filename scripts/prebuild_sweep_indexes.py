#!/usr/bin/env python
"""Pre-build the expensive (CAGRA) sweep indexes ON CPU into the sweep
run's index cache, using the runner's own cache-key function so the TPU
sweep reloads them instead of re-running the build leg that killed the
relay. Safe to run while the relay is down.

Usage: python scripts/prebuild_sweep_indexes.py \
    [--config blobs-1M-128] [--dataset datasets/blobs-1000000-128] \
    [--out-dir results/sweep-1M] [--algos raft_cagra]
"""

import argparse
import importlib.resources
import json
import pathlib
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")  # same trick as the conftest


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="blobs-1M-128")
    ap.add_argument("--dataset", default="datasets/blobs-1000000-128")
    ap.add_argument("--out-dir", default="results/sweep-1M")
    ap.add_argument("--algos", default="raft_cagra",
                    help="comma-separated algo names to prebuild")
    ap.add_argument("--check", action="store_true",
                    help="build nothing; exit 0 iff every index this "
                         "run would build is already cached (the "
                         "host-side pre-gate the TPU sweep runs before "
                         "burning an inter-process gap on a doomed "
                         "family)")
    args = ap.parse_args()

    assert jax.devices()[0].platform == "cpu"
    from raft_tpu.bench.datasets import METRICS
    from raft_tpu.bench.runner import (
        ALGO_REGISTRY,
        _index_cache_key,
        normalize_config,
        save_index_atomic,
    )
    from raft_tpu.io import read_bin

    cfg_path = pathlib.Path(args.config)
    if not cfg_path.exists():
        cfg_path = (importlib.resources.files("raft_tpu.bench") / "conf"
                    / f"{args.config}.json")
    config = normalize_config(json.loads(cfg_path.read_text()))

    dataset_dir = pathlib.Path(args.dataset)
    if args.check:
        # header only — the cache key needs just (rows, dim)
        with open(dataset_dir / "base.fbin", "rb") as f:
            import numpy as np
            shape = tuple(np.fromfile(f, np.int32, 2))
    else:
        base = read_bin(dataset_dir / "base.fbin")
        shape = base.shape
    metric_name = (dataset_dir / "metric.txt").read_text().strip() \
        if (dataset_dir / "metric.txt").exists() else "euclidean"
    metric = METRICS[metric_name]

    wanted = set(args.algos.split(","))
    index_dir = pathlib.Path(args.out_dir) / "indexes"
    missing = 0
    for algo_cfg in config["algos"]:
        if algo_cfg["name"] not in wanted:
            continue
        algo = ALGO_REGISTRY[algo_cfg["name"]]
        if algo.save is None:
            print(f"{algo_cfg['name']}: no save support, skipping")
            continue
        build_params = algo_cfg.get("build", {})
        key = _index_cache_key(algo.name, dataset_dir.name, shape[0],
                               shape[1], metric_name, build_params)
        path = index_dir / f"{key}.bin"
        if path.exists():
            print(f"cached: {path}", flush=True)
            continue
        if args.check:
            print(f"MISSING: {path}", flush=True)
            missing += 1
            continue
        t0 = time.perf_counter()
        index = algo.build(base, metric, **build_params)
        jax.block_until_ready(index)  # the whole tree, not leaves[0]
        dt = time.perf_counter() - t0
        save_index_atomic(algo, index, path)
        print(f"built {key} in {dt:.0f}s (CPU) -> {path}", flush=True)
    if args.check and missing:
        # 10, not 1: an unhandled exception (import error, missing
        # dataset, config typo) exits 1, and the sweep gate must be able
        # to tell "not prebuilt" (skip the family) from "broken" (abort
        # loudly) — ADVICE r3
        sys.exit(10)


if __name__ == "__main__":
    main()
