#!/usr/bin/env python
"""Round-3 staged TPU profile — resumable, piece-at-a-time.

Both relay deaths (r2, r3) struck during long multi-compile phases, so
this runner splits the measurement plan into pieces run as SEPARATE
processes, ordered safe -> risky, each appending JSON lines to one
output file. A relay death mid-plan loses only the current piece;
`scripts/tpu_profile6.sh` checks the relay ports between pieces and
stops cleanly when the tunnel is gone.

Pieces (safe -> risky):
  fknn   fused-kNN slope legs (known-good shapes; iters raised to kill
         the dispatch-jitter noise seen in the r3 partial run)
  cagra  search-engine A/B on the PREBUILT saved index
         (scripts/tpu_prebuild_indexes.py) — no build compiles at risk
  ivf    IVF-Flat/PQ continuity + fp32/bf16/fp8 LUT ladder
  bq     IVF-BQ bits 1/2, refined pipeline
  cjoin  cluster_join 200k build ON TPU — the leg that was in flight
         when the r3 relay died; run last, alone

Run one piece: PYTHONPATH=/root/repo:/root/.axon_site \
    python scripts/tpu_profile6.py --piece fknn --out results/p6.jsonl
"""

import argparse
import json
import os
import time

import numpy as np

os.environ.setdefault("RAFT_TPU_VMEM_MB", "64")
# persistent compile cache: each piece is its own process, so without
# this every piece re-pays its compiles — and long compile phases are
# what kills the relay. Unsupported-backend failures are non-fatal.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                 "results", "jaxcache"))

import jax
import jax.numpy as jnp

OUT = None


def emit(piece, **kw):
    line = json.dumps({"piece": piece, **kw})
    print(line, flush=True)
    if OUT:
        with open(OUT, "a") as f:
            f.write(line + "\n")


def wall(fn, iters=10):
    out = fn()
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    return (time.perf_counter() - t0) / iters


# RAFT_TPU_PROFILE_N scales every piece down for a CPU rehearsal of the
# exact code paths (the hardware window must not be spent on API typos);
# the index-cache tag tracks it so rehearsal and real runs never mix.
PROFILE_N = int(os.environ.get("RAFT_TPU_PROFILE_N", 200_000))


def size_tag(n):
    """Cache-file tag — exact row count so no two sizes ever share a
    file (shared with tpu_prebuild_indexes; keep a single copy)."""
    return str(n)


def make_data(n=None, nq=100):
    n = PROFILE_N if n is None else n
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, 128)).astype(np.float32)
    q = rng.standard_normal((nq, 128)).astype(np.float32)
    return rng, x, q


def ground_truth(x, q):
    from raft_tpu.neighbors import brute_force
    _, gt_i = brute_force.knn(None, x, q, 10)
    return np.asarray(gt_i)


# ---------------------------------------------------------------------------


def piece_fknn():
    from raft_tpu.bench.prims import slope_passes
    from raft_tpu.distance.types import DistanceType
    from raft_tpu.ops.fused_topk import fused_knn

    n_big = (1 << 20) if PROFILE_N >= 200_000 else (1 << 16)
    big = jax.random.normal(jax.random.key(0), (n_big, 128), jnp.float32)
    bigb = big.astype(jnp.bfloat16)
    qs = jax.random.normal(jax.random.key(2), (10, 128), jnp.float32)
    norms = jnp.sum(jnp.square(big), axis=1)
    payload_f32 = n_big * 128 * 4

    # pass spreads + calibration rationale: prims.SLOPE_PASSES (shared
    # with bench.py). RAFT_TPU_FKNN_TILES limits the tile legs — the
    # VMEM-sweep rerun only needs the auto-sized tile=0 legs, not a
    # recompile of the fixed-tile ones whose results can't change
    tiles = tuple(int(t) for t in os.environ.get(
        "RAFT_TPU_FKNN_TILES", "0,16384").split(","))
    for tag, ds, payload in (("f32", big, payload_f32),
                             ("bf16", bigb, payload_f32 / 2)):
        lo, hi = slope_passes(ds.dtype)
        for tile in tiles:
            try:
                tlo = wall(lambda: fused_knn(qs, ds, 10,
                                             DistanceType.L2Expanded,
                                             dataset_norms=norms, tile=tile,
                                             passes=lo))
                thi = wall(lambda: fused_knn(qs, ds, 10,
                                             DistanceType.L2Expanded,
                                             dataset_norms=norms, tile=tile,
                                             passes=hi))
                dt = (thi - tlo) / (hi - lo)
                emit(f"fknn_{tag}_tile{tile}_slope",
                     iter_ms=round(dt * 1e3, 3), lo_passes=lo,
                     hi_passes=hi,
                     gbps=round(payload / dt / 1e9, 1) if dt > 0 else -1,
                     tlo_ms=round(tlo * 1e3, 2),
                     thi_ms=round(thi * 1e3, 2))
            except Exception as e:  # noqa: BLE001
                emit(f"fknn_{tag}_tile{tile}_slope", error=str(e)[:160])


CACHE_DIR = os.path.join(os.path.dirname(__file__), "..", "results",
                         "cache")


def cache_path(fname):
    """Single definition of the prebuilt-index cache location — used by
    every piece here and imported by tpu_prebuild_indexes."""
    return os.path.join(CACHE_DIR, fname)


def ivf_prebuild_specs():
    """name -> (filename, module, build(x)) for every IVF-family index
    the profile pieces consume. tpu_prebuild_indexes imports this table
    (like size_tag), so filenames and build params cannot drift between
    the CPU prebuild and the TPU pieces."""
    from raft_tpu.neighbors import ivf_bq, ivf_flat, ivf_pq
    tag = size_tag(PROFILE_N)
    specs = {
        "ivf_flat": (f"ivf_flat_1024_{tag}.bin", ivf_flat,
                     lambda x: ivf_flat.build(
                         None, ivf_flat.IvfFlatIndexParams(n_lists=1024),
                         x)),
        "ivf_pq": (f"ivf_pq_1024_d128_b4_{tag}.bin", ivf_pq,
                   lambda x: ivf_pq.build(
                       None, ivf_pq.IvfPqIndexParams(
                           n_lists=1024, pq_dim=128, pq_bits=4), x)),
    }
    for bits in (1, 2):
        specs[f"ivf_bq{bits}"] = (
            f"ivf_bq_1024_b{bits}_{tag}.bin", ivf_bq,
            lambda x, bits=bits: ivf_bq.build(
                None, ivf_bq.IvfBqIndexParams(n_lists=1024, bits=bits), x))
    return specs


def load_index(tag):
    path = cache_path(f"cagra_cluster_join_{tag}.bin")
    if not os.path.exists(path):
        return None
    return path


def piece_cagra():
    from raft_tpu.neighbors import cagra
    from raft_tpu.utils import eval_recall

    rng, x, q = make_data()
    gt = ground_truth(x, q)
    tag_n = size_tag(PROFILE_N)
    path = load_index(tag_n)
    if path is None:
        emit("cagra", error="no prebuilt index; run tpu_prebuild_indexes")
        return
    ci = cagra.load(None, path, dataset=jnp.asarray(x))
    ci16 = cagra.CagraIndex(dataset=ci.dataset.astype(jnp.bfloat16),
                            graph=ci.graph, metric=ci.metric)
    # pallas legs: ds_mode auto picks placement from beam_search_fits,
    # so the leg labels compute the SAME decision — at PROFILE_N=200k
    # the f32 dataset (102 MB) streams from HBM and bf16 (51 MB) sits
    # in VMEM, but a RAFT_TPU_PROFILE_N rehearsal or a different VMEM
    # budget must not mislabel the engine measured
    from raft_tpu.ops.beam_search import beam_search_fits
    m32 = "vmem" if beam_search_fits(PROFILE_N, 128, 4) else "hbm"
    m16 = "vmem" if beam_search_fits(PROFILE_N, 128, 2) else "hbm"
    legs = [("xla_f32", ci, "xla"), (f"pallas_{m32}_f32", ci, "pallas"),
            (f"pallas_{m16}_bf16", ci16, "pallas"),
            ("xla_bf16", ci16, "xla")]

    def search_leg(name, idx, algo, it, qs, gts, **extra):
        sp = cagra.CagraSearchParams(itopk_size=it, search_width=4,
                                     algo=algo, **extra)
        try:
            dt = wall(lambda: cagra.search(None, sp, idx, qs, 10),
                      iters=10)
            _, i = cagra.search(None, sp, idx, qs, 10)
            r, _, _ = eval_recall(gts, np.asarray(i))
            emit(name, ms=round(dt * 1e3, 2),
                 qps=round(len(qs) / dt, 1), recall=round(float(r), 4))
        except Exception as e:  # noqa: BLE001
            emit(name, error=str(e)[:200])

    for it in (64, 128):
        for tag, idx, algo in legs:
            search_leg(f"cagra_search_itopk{it}_{tag}", idx, algo, it,
                       q, gt)

    # kernel block_q sweep on the bf16 index
    try:
        from raft_tpu.ops.beam_search import beam_search, pad_graph

        seeds = jnp.asarray(
            rng.integers(0, len(x), (100, 4 * 32)).astype(np.int32))
        pg = pad_graph(ci.graph)
        deg = ci.graph.shape[1]
        for bq in (4, 8, 16):
            dt = wall(lambda bq=bq: beam_search(
                jnp.asarray(q), ci16.dataset, pg, seeds, 10, 64, 4, 40,
                ci.metric, block_q=bq, deg=deg), iters=10)
            emit(f"beam_blockq{bq}", ms=round(dt * 1e3, 2),
                 qps=round(100 / dt, 1))
        # HBM-resident engine (double-buffered candidate-row DMA) on
        # the same bf16 dataset: vmem-vs-hbm cost of the any-size path
        for bq in (8, 16):
            dt = wall(lambda bq=bq: beam_search(
                jnp.asarray(q), ci16.dataset, pg, seeds, 10, 64, 4, 40,
                ci.metric, block_q=bq, deg=deg, ds_mode="hbm"), iters=10)
            emit(f"beam_hbm_blockq{bq}", ms=round(dt * 1e3, 2),
                 qps=round(100 / dt, 1))
    except Exception as e:  # noqa: BLE001
        emit("beam_blockq", error=str(e)[:200])

    # half-size f32 slice fits VMEM — the f32 kernel datapoint
    tag_h = size_tag(PROFILE_N // 2)
    path_h = load_index(tag_h)
    if path_h is not None:
        try:
            ci_h = cagra.load(None, path_h,
                              dataset=jnp.asarray(x[:PROFILE_N // 2]))
            for algo in ("xla", "pallas"):
                sp = cagra.CagraSearchParams(itopk_size=64, search_width=4,
                                             algo=algo)
                dt = wall(lambda sp=sp: cagra.search(None, sp, ci_h, q, 10),
                          iters=10)
                emit(f"cagra_search_{tag_h}_f32_{algo}",
                     ms=round(dt * 1e3, 2), qps=round(100 / dt, 1))
        except Exception as e:  # noqa: BLE001
            emit(f"cagra_search_{tag_h}_f32", error=str(e)[:200])

    # batch-10 legs — the reference's headline regime
    # (raft-vector-search-batch-10.png); q=100 above measures
    # throughput, this measures the small-batch latency point
    for tag, idx, algo in legs:
        search_leg(f"cagra_search_b10_itopk64_{tag}", idx, algo, 64,
                   q[:10], gt[:10])

    # seed_pool variants (query-aware seeding — on clustered data the
    # unseeded beam collapses; the routing GEMM is MXU-cheap, so these
    # legs measure what the 1M sweep's seeded combos should cost).
    # "cagra_search_itopk64_pool" keeps its historical semantics (algo
    # auto, same key as prior rounds' JSONL); the engine-pinned legs
    # carry the placement tag like every other pallas leg name.
    search_leg("cagra_search_itopk64_pool", ci, "auto", 64, q, gt,
               seed_pool=4096)
    search_leg("cagra_search_b10_itopk64_pool", ci, "auto", 64,
               q[:10], gt[:10], seed_pool=4096)
    search_leg(f"cagra_search_itopk64_pool_pallas_{m16}_bf16", ci16,
               "pallas", 64, q, gt, seed_pool=4096)


def cached_or_build(spec_name, x):
    """Load a prebuilt index from results/cache (tpu_prebuild_indexes
    writes them on CPU) so the TPU window never pays a build; fall back
    to building in-process when the cache is cold."""
    fname, mod, build = ivf_prebuild_specs()[spec_name]
    path = cache_path(fname)
    if os.path.exists(path):
        try:
            idx = mod.load(None, path)
            emit("cache_hit", file=fname)
            return idx
        except Exception as e:  # noqa: BLE001 — salvage the TPU window
            emit("cache_load_failed", file=fname, error=str(e)[:160])
    else:
        emit("cache_miss", file=fname)
    emit("building_in_process", file=fname)
    return build(x)


def piece_ivf():
    from raft_tpu.neighbors import ivf_flat, ivf_pq
    from raft_tpu.utils import eval_recall

    _, x, q = make_data()
    gt = ground_truth(x, q)

    fi = cached_or_build("ivf_flat", x)
    for p in (32, 64):
        sp = ivf_flat.IvfFlatSearchParams(n_probes=p)
        dt = wall(lambda sp=sp: ivf_flat.search(None, sp, fi, q, 10),
                  iters=10)
        emit(f"ivf_flat_p{p}", ms=round(dt * 1e3, 2), qps=round(100 / dt, 1))

    pi = cached_or_build("ivf_pq", x)
    sp = ivf_pq.IvfPqSearchParams(n_probes=32)
    dt = wall(lambda: ivf_pq.search(None, sp, pi, q, 10), iters=10)
    _, i = ivf_pq.search(None, sp, pi, q, 10)
    r, _, _ = eval_recall(gt, np.asarray(i))
    emit("ivf_pq_b4_d128_p32", ms=round(dt * 1e3, 2),
         qps=round(100 / dt, 1), recall=round(float(r), 4))

    for dt_name in ("float32", "bfloat16", "float8_e4m3fn"):
        lut_dt = getattr(jnp, dt_name)
        sp = ivf_pq.IvfPqSearchParams(n_probes=32, lut_dtype=lut_dt,
                                      score_mode="onehot")
        try:
            t = wall(lambda sp=sp: ivf_pq.search(None, sp, pi, q, 10),
                     iters=10)
            _, i = ivf_pq.search(None, sp, pi, q, 10)
            r, _, _ = eval_recall(gt, np.asarray(i))
            emit(f"ivf_pq_lut_{dt_name}", ms=round(t * 1e3, 2),
                 recall=round(float(r), 4))
        except Exception as e:  # noqa: BLE001
            emit(f"ivf_pq_lut_{dt_name}", error=str(e)[:160])

    # score-mode A/B on hardware (VERDICT r3 next #3: prove the XLA
    # scoring path adequate or justify a Pallas probe-scan kernel).
    # The prebuilt b4 index has J=16 books, so all three modes apply;
    # the b4 'onehot' leg above doubles as this A/B's onehot point at
    # 32 probes — these add select (the J<=32 VPU path) and gather
    # (the scalar-core baseline the auto mode avoids on TPU).
    for mode in ("select", "gather"):
        sp = ivf_pq.IvfPqSearchParams(n_probes=32, score_mode=mode)
        try:
            t = wall(lambda sp=sp: ivf_pq.search(None, sp, pi, q, 10),
                     iters=10)
            emit(f"ivf_pq_score_{mode}", ms=round(t * 1e3, 2),
                 qps=round(100 / t, 1))
        except Exception as e:  # noqa: BLE001
            emit(f"ivf_pq_score_{mode}", error=str(e)[:160])


def piece_bq():
    from raft_tpu.neighbors import ivf_bq
    from raft_tpu.neighbors.refine import refine as refine_fn
    from raft_tpu.utils import eval_recall

    _, x, q = make_data()
    gt = ground_truth(x, q)
    xd = jnp.asarray(x)

    for bits in (1, 2):
        bi = cached_or_build(f"ivf_bq{bits}", x)

        def full(sp, bi=bi):
            _, cand = ivf_bq.search(None, sp, bi, q, 40)
            return refine_fn(None, xd, q, cand, 10)

        for p in (32, 64):
            sp = ivf_bq.IvfBqSearchParams(n_probes=p)
            dt = wall(lambda sp=sp: full(sp), iters=10)
            _, i = full(sp)
            r, _, _ = eval_recall(gt, np.asarray(i))
            emit(f"ivf_bq{bits}_p{p}_refined", ms=round(dt * 1e3, 2),
                 qps=round(100 / dt, 1), recall=round(float(r), 4))


def piece_cjoin():
    from raft_tpu.neighbors import cagra
    from raft_tpu.core.logger import LogLevel, set_level

    # stage-level stderr logs: if the relay dies mid-build again, the
    # last line names the stage whose compile killed it
    set_level(LogLevel.INFO)
    _, x, _ = make_data()
    tag = size_tag(PROFILE_N)

    # stage 1 — cluster passes only (no NN-descent polish): fewer and
    # smaller XLA programs; its number lands in the file even if the
    # polish leg below takes the relay down
    from raft_tpu.neighbors import cluster_join

    t0 = time.perf_counter()
    ids = cluster_join.build(None, cluster_join.ClusterJoinParams(
        graph_degree=64, polish_rounds=0), x)
    np.asarray(ids[:1])
    emit(f"cluster_join_nopolish_{tag}",
         s=round(time.perf_counter() - t0, 1))

    # stage 2 — the full default build (polish + optimize), the leg in
    # flight when the r3 relay died
    t0 = time.perf_counter()
    ci = cagra.build(None, cagra.CagraIndexParams(
        graph_degree=32, intermediate_graph_degree=64,
        build_algo=cagra.BuildAlgo.CLUSTER_JOIN), x)
    np.asarray(ci.graph[:1])
    emit(f"cagra_build_cluster_join_{tag}",
         s=round(time.perf_counter() - t0, 1))


PIECES = {"fknn": piece_fknn, "cagra": piece_cagra, "ivf": piece_ivf,
          "bq": piece_bq, "cjoin": piece_cjoin}


def main():
    global OUT
    ap = argparse.ArgumentParser()
    ap.add_argument("--piece", required=True, choices=sorted(PIECES))
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    OUT = args.out
    emit(f"config_{args.piece}", backend=jax.default_backend(),
         device=jax.devices()[0].device_kind,
         vmem_mb=os.environ.get("RAFT_TPU_VMEM_MB"))
    PIECES[args.piece]()


if __name__ == "__main__":
    main()
