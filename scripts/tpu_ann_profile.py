#!/usr/bin/env python
"""ANN A/B profiling on the real TPU — ivf_flat / ivf_pq / cagra QPS+recall.

Timing is pipelined (dispatch ITERS, fetch once at the end):
``block_until_ready`` does not block on relayed backends, so wall-clock
is anchored on a host fetch of the last result. Run serially — never
two TPU processes at once. One JSON line per config on stdout.

Env: PROF_N (200000), PROF_DIM (128), PROF_Q (100), PROF_K (10),
PROF_ITERS (20).
"""

import json
import os
import time

import numpy as np

import jax

N = int(os.environ.get("PROF_N", 200_000))
D = int(os.environ.get("PROF_DIM", 128))
Q = int(os.environ.get("PROF_Q", 100))
K = int(os.environ.get("PROF_K", 10))
ITERS = int(os.environ.get("PROF_ITERS", 20))


def main():
    from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq
    from raft_tpu.utils import eval_recall

    print(json.dumps({"prof": "config", "backend": jax.default_backend(),
                      "n": N, "dim": D, "q": Q, "k": K}), flush=True)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((N, D)).astype(np.float32)
    q = rng.standard_normal((Q, D)).astype(np.float32)
    gt_d, gt_i = brute_force.knn(None, x, q, K)
    gt = np.asarray(gt_i)

    def bench(name, fn):
        out = fn()
        np.asarray(out[0][0, :1])              # compile + warm + drain
        t0 = time.perf_counter()
        for _ in range(ITERS):
            out = fn()
        np.asarray(out[0][0, :1])              # one fetch drains the queue
        dt = (time.perf_counter() - t0) / ITERS
        r, _, _ = eval_recall(gt, np.asarray(out[1]))
        print(json.dumps({"bench": name, "ms": round(dt * 1e3, 2),
                          "qps": round(Q / dt, 1),
                          "recall": round(float(r), 4)}), flush=True)

    fi = ivf_flat.build(None, ivf_flat.IvfFlatIndexParams(n_lists=1024), x)
    for p in (32, 64, 128):
        sp = ivf_flat.IvfFlatSearchParams(n_probes=p)
        bench(f"ivf_flat_p{p}",
              lambda sp=sp: ivf_flat.search(None, sp, fi, q, K))

    pi = ivf_pq.build(None, ivf_pq.IvfPqIndexParams(n_lists=1024, pq_dim=64),
                      x)
    for mode in ("gather", "onehot"):
        for p in (32, 64):
            sp = ivf_pq.IvfPqSearchParams(n_probes=p, score_mode=mode)
            bench(f"ivf_pq_{mode}_p{p}",
                  lambda sp=sp: ivf_pq.search(None, sp, pi, q, K))

    ci = cagra.build(None, cagra.CagraIndexParams(
        graph_degree=32, intermediate_graph_degree=64,
        build_algo=cagra.BuildAlgo.NN_DESCENT), x)
    for it in (64, 128):
        sp = cagra.CagraSearchParams(itopk_size=it, search_width=4)
        bench(f"cagra_itopk{it}",
              lambda sp=sp: cagra.search(None, sp, ci, q, K))


if __name__ == "__main__":
    main()
