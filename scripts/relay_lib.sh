# Shared relay helpers for the TPU scripts. Source, don't execute:
#   . "$(dirname "$0")/relay_lib.sh"
# One definition of the relay port set — tpu_profile6.sh and
# tpu_round3_all.sh must agree on what "relay up" means.
RELAY_PORTS=(8082 8083 8093)

relay_up() {
  local p
  for p in "${RELAY_PORTS[@]}"; do
    (echo > "/dev/tcp/127.0.0.1/$p") 2>/dev/null || return 1
  done
  return 0
}
