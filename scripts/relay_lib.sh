# Shared relay helpers for the TPU scripts. Source, don't execute:
#   . "$(dirname "$0")/relay_lib.sh"
# One definition of the relay port set — tpu_profile6.sh and
# tpu_round3_all.sh must agree on what "relay up" means.
RELAY_PORTS=(8082 8083 8093)

relay_up() {
  local p
  for p in "${RELAY_PORTS[@]}"; do
    (echo > "/dev/tcp/127.0.0.1/$p") 2>/dev/null || return 1
  done
  return 0
}

# relay_gate: call before launching each TPU process in a sequence.
# Returns 1 when the relay is down (check BEFORE sleeping, so a dead
# relay is reported instantly). From the second call on, inserts a
# ${RELAY_GAP_S:-150}s gap first — the r3s3 lesson: backend init racing
# the previous process's teardown can wedge the relay even with no
# compile in flight — then re-checks so the launch itself is fresh.
RELAY_GATE_FIRST=1
relay_gate() {
  relay_up || return 1
  if [ "$RELAY_GATE_FIRST" = 0 ]; then
    sleep "${RELAY_GAP_S:-150}"
    relay_up || return 1
  fi
  RELAY_GATE_FIRST=0
  return 0
}
