#!/bin/bash
# Watch the relay ports; when they come up (and stay up through a
# settle period), launch the full round-3 hardware plan exactly once.
# Run detached: nohup bash scripts/relay_watch.sh > results/relay_watch.log 2>&1 &
set -u
SCRIPT_DIR=$(cd "$(dirname "$0")" && pwd)
cd "$SCRIPT_DIR/.."
. "$SCRIPT_DIR/relay_lib.sh"

LOCK=results/round3_all.launched
if [ -e "$LOCK" ]; then
  echo "lock $LOCK exists — a plan already launched; refusing" >&2
  exit 1
fi

echo "watching relay ports ${RELAY_PORTS[*]} $(date)"
while true; do
  if relay_up; then
    echo "ports up $(date); settling 60s"
    sleep 60
    if relay_up; then
      break
    fi
    echo "ports dropped during settle; resuming watch"
  fi
  sleep 30
done

# atomic claim (noclobber): if two watchers raced through the wait
# loop, exactly one wins — two concurrent plans would mean two TPU
# processes at once, the relay-wedging condition
if ! { set -o noclobber; date > "$LOCK"; } 2>/dev/null; then
  echo "lost lock race to another watcher; exiting" >&2
  exit 1
fi
echo "launching tpu_round3_all.sh $(date)"
bash scripts/tpu_round3_all.sh
echo "plan finished rc=$? $(date)"
