#!/usr/bin/env python
"""Second-round TPU profiling: stream-BW ceiling, bf16 fused anomaly,
4-bit vs 8-bit PQ one-hot scoring, CAGRA search. Pipelined timing
(fetch-anchored). Run serially on a healthy relay.
"""

import functools
import json
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def timed(tag, fn, iters=20, payload=None):
    out = fn()
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    dt = (time.perf_counter() - t0) / iters
    rec = {"piece": tag, "ms": round(dt * 1e3, 3)}
    if payload:
        rec["gbps"] = round(payload / dt / 1e9, 1)
    print(json.dumps(rec), flush=True)
    return dt


def _read_kernel(x_ref, o_ref, acc):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)

    acc[:] += jnp.sum(x_ref[:], axis=0, keepdims=True)

    @pl.when(step == pl.num_programs(0) - 1)
    def _():
        o_ref[:] = acc[:]


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def pallas_read(x, tile: int = 4096, interpret: bool = False):
    n, d = x.shape
    # exact-tiling guard: a ragged tail would be silently dropped by
    # grid = n // tile, overstating the streamed payload
    if n % tile != 0:
        raise ValueError(f"n={n} must be a multiple of tile={tile}")
    grid = n // tile
    return pl.pallas_call(
        _read_kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((tile, d), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, d), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((1, d), jnp.float32)],
        interpret=interpret,
    )(x)


def main():
    print(json.dumps({"prof": "round2", "backend": jax.default_backend()}),
          flush=True)

    # ---- 1. pure-read stream BW ceiling (Pallas reduce over 512 MB)
    big = jax.random.normal(jax.random.key(0), (1 << 20, 128), jnp.float32)
    timed("pallas_read_512MB_f32", lambda: pallas_read(big), payload=512e6)
    bigb = big.astype(jnp.bfloat16)
    timed("pallas_read_256MB_bf16", lambda: pallas_read(bigb), payload=256e6)

    # ---- 2. fused kNN f32 vs bf16, and VPU-merge sensitivity via k
    from raft_tpu.ops.fused_topk import fused_knn
    from raft_tpu.distance.types import DistanceType

    qs = jax.random.normal(jax.random.key(2), (10, 128), jnp.float32)
    norms = jnp.sum(jnp.square(big), axis=1)
    for tag, ds in (("f32", big), ("bf16", bigb)):
        for k in (10, 64):
            timed(f"fused_knn_{tag}_k{k}",
                  lambda ds=ds, k=k: fused_knn(
                      qs, ds, k, DistanceType.L2Expanded,
                      dataset_norms=norms, tile=8192),
                  payload=(512e6 if tag == "f32" else 256e6))

    # ---- 3. PQ bits: 8-bit/pq64 vs 4-bit/pq128 (same bytes/row)
    from raft_tpu.neighbors import brute_force, ivf_pq
    from raft_tpu.utils import eval_recall

    rng = np.random.default_rng(0)
    x = rng.standard_normal((200_000, 128)).astype(np.float32)
    q = rng.standard_normal((100, 128)).astype(np.float32)
    _, gt_i = brute_force.knn(None, x, q, 10)
    gt = np.asarray(gt_i)
    for bits, pqd in ((8, 64), (4, 128), (4, 64)):
        pi = ivf_pq.build(None, ivf_pq.IvfPqIndexParams(
            n_lists=1024, pq_dim=pqd, pq_bits=bits), x)
        sp = ivf_pq.IvfPqSearchParams(n_probes=32)
        dt = timed(f"ivf_pq_b{bits}_d{pqd}_p32",
                   lambda: ivf_pq.search(None, sp, pi, q, 10), iters=10)
        _, i = ivf_pq.search(None, sp, pi, q, 10)
        r, _, _ = eval_recall(gt, np.asarray(i))
        print(json.dumps({"piece": f"ivf_pq_b{bits}_d{pqd}_recall",
                          "recall": round(float(r), 4),
                          "qps": round(100 / dt, 1)}), flush=True)

    # ---- 4. CAGRA: IVF-PQ-path build time + search QPS
    from raft_tpu.neighbors import cagra

    t0 = time.perf_counter()
    ci = cagra.build(None, cagra.CagraIndexParams(
        graph_degree=32, intermediate_graph_degree=64), x)
    np.asarray(ci.graph[:1])
    print(json.dumps({"piece": "cagra_build_ivfpq_200k",
                      "s": round(time.perf_counter() - t0, 1)}), flush=True)
    for it in (64, 128):
        sp = cagra.CagraSearchParams(itopk_size=it, search_width=4)
        dt = timed(f"cagra_search_itopk{it}",
                   lambda sp=sp: cagra.search(None, sp, ci, q, 10), iters=10)
        _, i = cagra.search(None, sp, ci, q, 10)
        r, _, _ = eval_recall(gt, np.asarray(i))
        print(json.dumps({"piece": f"cagra_itopk{it}_recall",
                          "recall": round(float(r), 4),
                          "qps": round(100 / dt, 1)}), flush=True)

    # ---- 5. NN-descent round cost after the scatter fix (50k)
    from raft_tpu.neighbors import nn_descent as nnd

    xs = jnp.asarray(x[:50_000])
    t0 = time.perf_counter()
    g = nnd.build(None, nnd.NNDescentParams(
        graph_degree=64, intermediate_graph_degree=96, max_iterations=5), xs)
    np.asarray(g[:1])
    print(json.dumps({"piece": "nnd_build_5it_50k",
                      "s": round(time.perf_counter() - t0, 1)}), flush=True)


if __name__ == "__main__":
    main()
