#!/bin/bash
# Staged round-3 profile: one process per piece, relay-checked between
# pieces so a relay death loses at most the in-flight piece.
# Usage: bash scripts/tpu_profile6.sh [out.jsonl] [pieces...]
set -u
SCRIPT_DIR=$(cd "$(dirname "$0")" && pwd)
cd "$SCRIPT_DIR/.."
OUT=${1:-results/tpu_profile6_r3.jsonl}
shift || true
PIECES=("$@")
[ ${#PIECES[@]} -eq 0 ] && PIECES=(fknn cagra ivf bq cjoin)

. "$SCRIPT_DIR/relay_lib.sh"

for piece in "${PIECES[@]}"; do
  if ! relay_gate; then
    echo "relay DOWN before piece $piece — stopping" >&2
    exit 2
  fi
  echo "=== piece $piece ===" >&2
  PYTHONPATH=/root/repo:/root/.axon_site RAFT_TPU_VMEM_MB=64 \
    JAX_COMPILATION_CACHE_DIR="$PWD/results/jaxcache" \
    python scripts/tpu_profile6.py --piece "$piece" --out "$OUT" \
    2>> "${OUT%.jsonl}.err"
  echo "=== piece $piece rc=$? ===" >&2
done
