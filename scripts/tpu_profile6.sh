#!/bin/bash
# Staged round-3 profile: one process per piece, relay-checked between
# pieces so a relay death loses at most the in-flight piece.
# Usage: bash scripts/tpu_profile6.sh [out.jsonl] [pieces...]
set -u
SCRIPT_DIR=$(cd "$(dirname "$0")" && pwd)
cd "$SCRIPT_DIR/.."
OUT=${1:-results/tpu_profile6_r3.jsonl}
shift || true
PIECES=("$@")
[ ${#PIECES[@]} -eq 0 ] && PIECES=(fknn cagra ivf bq cjoin)

. "$SCRIPT_DIR/relay_lib.sh"

FIRST=1
for piece in "${PIECES[@]}"; do
  if ! relay_up; then
    echo "relay DOWN before piece $piece — stopping" >&2
    exit 2
  fi
  # r3s3 lesson: backend init racing the previous process's teardown
  # can wedge the relay even with no compile in flight — leave a gap
  # (after the cheap check above so a dead relay exits immediately,
  # re-checked after the sleep so the launch itself is fresh)
  if [ "$FIRST" = 0 ]; then
    sleep 150
    if ! relay_up; then
      echo "relay DOWN before piece $piece — stopping" >&2
      exit 2
    fi
  fi
  FIRST=0
  echo "=== piece $piece ===" >&2
  PYTHONPATH=/root/repo:/root/.axon_site RAFT_TPU_VMEM_MB=64 \
    python scripts/tpu_profile6.py --piece "$piece" --out "$OUT" \
    2>> "${OUT%.jsonl}.err"
  echo "=== piece $piece rc=$? ===" >&2
done
