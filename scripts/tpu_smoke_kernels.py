#!/usr/bin/env python
"""Compiled-Mosaic smoke for every Pallas kernel — VERDICT r2 weak #4:
CI exercises the kernels in interpret mode only; this script runs each
one COMPILED on the real chip at small shapes and asserts parity —
exact kNN against a host float64 reference (an on-device XLA reference
would itself run at MXU default precision), beam search against the
XLA engine. Commit its JSON output as the hardware evidence.

Run: PYTHONPATH=/root/repo:/root/.axon_site python scripts/tpu_smoke_kernels.py
"""

import json
import os

import numpy as np

os.environ.setdefault("RAFT_TPU_VMEM_MB", "64")  # see tpu_profile5.py
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                 "results", "jaxcache"))

import jax
import jax.numpy as jnp


def emit(piece, **kw):
    print(json.dumps({"piece": piece, **kw}), flush=True)


def main():
    emit("config", backend=jax.default_backend(),
         device=jax.devices()[0].device_kind)
    from raft_tpu.distance.types import DistanceType
    from raft_tpu.matrix.select_k import merge_topk  # noqa: F401 (import check)
    from raft_tpu.ops.beam_search import beam_search
    from raft_tpu.ops.fused_topk import fused_knn, select_k_tiles, stream_read_sum

    rng = np.random.default_rng(0)
    x = rng.standard_normal((20_000, 128)).astype(np.float32)
    q = rng.standard_normal((16, 128)).astype(np.float32)
    xd, qd = jnp.asarray(x), jnp.asarray(q)

    # Host float64 reference for exact kNN (an on-device XLA reference
    # would itself run the matmul at MXU default precision and lose
    # the tie-breaks the f32-HIGHEST kernel gets right).
    x64, q64 = x.astype(np.float64), q.astype(np.float64)
    d_full64 = (np.sum(q64**2, 1)[:, None] + np.sum(x64**2, 1)[None, :]
                - 2.0 * q64 @ x64.T)
    ref_i = np.argsort(d_full64, axis=1, kind="stable")[:, :10]
    ref_d = np.take_along_axis(d_full64, ref_i, axis=1).astype(np.float32)

    # ---- fused_knn compiled
    try:
        kd, ki = fused_knn(qd, xd, 10, DistanceType.L2Expanded)
        ok = bool((np.asarray(ki) == ref_i).all())
        emit("fused_knn_f32", ids_exact=ok,
             max_d_err=float(np.abs(np.asarray(kd) - ref_d).max()))
    except Exception as e:  # noqa: BLE001
        emit("fused_knn_f32", error=str(e)[:300])

    try:
        kd, ki = fused_knn(qd, xd.astype(jnp.bfloat16), 10,
                           DistanceType.L2Expanded)
        r = (np.asarray(ki) == ref_i).mean()
        emit("fused_knn_bf16", id_agreement=float(r))
    except Exception as e:  # noqa: BLE001
        emit("fused_knn_bf16", error=str(e)[:300])

    # ---- select_k_tiles compiled
    try:
        mat = jnp.asarray(rng.standard_normal((16, 50_000)).astype(np.float32))
        sd, si = select_k_tiles(mat, 10)
        rd, ri = jax.lax.top_k(-mat, 10)
        ok = bool((np.asarray(si) == np.asarray(ri)).all())
        emit("select_k_tiles", ids_exact=ok,
             max_d_err=float(np.abs(np.asarray(sd) - np.asarray(-rd)).max()))
    except Exception as e:  # noqa: BLE001
        emit("select_k_tiles", error=str(e)[:300])

    # ---- stream_read_sum compiled (value parity vs jnp.sum)
    try:
        s = stream_read_sum(xd)
        want = float(jnp.sum(xd))
        emit("stream_read_sum",
             rel_err=float(abs(float(jnp.sum(s)) - want)
                           / max(abs(want), 1e-9)))
    except Exception as e:  # noqa: BLE001
        emit("stream_read_sum", error=str(e)[:300])

    # ---- ivf_scan compiled: list-major Pallas probe scan vs the
    # rank-major XLA scan on the same index (ids must agree exactly;
    # distances to dot-reassociation tolerance), plus the pallas-vs-
    # xla-list-major pair which shares one contraction and must match
    # bit-for-bit
    try:
        from raft_tpu.neighbors import ivf_flat

        xs = jnp.asarray(rng.standard_normal((20_000, 128), ).astype(
            np.float32))
        qs = jnp.asarray(rng.standard_normal((16, 128)).astype(np.float32))
        index = ivf_flat.build(
            None, ivf_flat.IvfFlatIndexParams(n_lists=64,
                                              kmeans_n_iters=5), xs)
        outs = {}
        for eng in ("rank", "xla", "pallas"):
            sp = ivf_flat.IvfFlatSearchParams(n_probes=8, scan_engine=eng)
            d, i = ivf_flat.search(None, sp, index, qs, 10)
            outs[eng] = (np.asarray(d), np.asarray(i))
        emit("ivf_scan",
             pallas_ids_vs_rank=float(
                 (outs["pallas"][1] == outs["rank"][1]).mean()),
             pallas_bits_vs_xla=bool(
                 (outs["pallas"][0] == outs["xla"][0]).all()
                 and (outs["pallas"][1] == outs["xla"][1]).all()),
             max_d_err_vs_rank=float(np.nanmax(np.abs(
                 np.where(np.isfinite(outs["pallas"][0]),
                          outs["pallas"][0], 0.0)
                 - np.where(np.isfinite(outs["rank"][0]),
                            outs["rank"][0], 0.0)))))
    except Exception as e:  # noqa: BLE001
        emit("ivf_scan", error=str(e)[:300])

    # ---- sharded list-major scan on the real mesh: the distributed
    # IVF search (probe_mode=global) must be bit-identical to the
    # single-device index for every engine — the PR-3 mesh contract,
    # proven on real silicon (a 1-chip "mesh" still exercises the
    # shard_map program + collectives end to end)
    try:
        from raft_tpu.comms import local_comms
        from raft_tpu.distributed import ivf as dist_ivf
        from raft_tpu.neighbors import ivf_flat

        comms = local_comms()
        xs = jnp.asarray(rng.standard_normal((20_000, 128)).astype(
            np.float32))
        qs = jnp.asarray(rng.standard_normal((16, 128)).astype(np.float32))
        params = ivf_flat.IvfFlatIndexParams(n_lists=64, kmeans_n_iters=5)
        single = ivf_flat.build(None, params, xs)
        sharded = dist_ivf.build(None, comms, params, xs)
        rep = {"n_chips": comms.size}
        for eng in ("rank", "xla", "pallas"):
            sp = ivf_flat.IvfFlatSearchParams(n_probes=8, scan_engine=eng)
            d0, i0 = ivf_flat.search(None, sp, single, qs, 10)
            d1, i1 = dist_ivf.search(None, sp, sharded, qs, 10)
            rep[f"{eng}_ids_exact"] = bool(
                (np.asarray(i0) == np.asarray(i1)).all())
            rep[f"{eng}_bits_exact"] = bool(
                (np.asarray(d0) == np.asarray(d1)).all()
                and (np.asarray(i0) == np.asarray(i1)).all())
        # wire-compressed merge stays rank-stable on well-separated data
        sp = ivf_flat.IvfFlatSearchParams(n_probes=8)
        _, iw = dist_ivf.search(None, sp, sharded, qs, 10,
                                wire_dtype="bf16")
        _, i0 = dist_ivf.search(None, sp, sharded, qs, 10)
        rep["bf16_wire_id_agreement"] = float(
            (np.asarray(iw) == np.asarray(i0)).mean())
        # mesh-aware executor: zero recompiles across batch sizes
        from raft_tpu import SearchExecutor
        from raft_tpu.core import tracing

        tracing.install_xla_compile_listener()
        ex = SearchExecutor()
        for nq in (16, 13, 9):
            ex.search(sharded, qs[:nq], 10, params=sp)
        b0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        for nq in (16, 13, 9, 13):
            ex.search(sharded, qs[:nq], 10, params=sp)
        rep["executor_zero_recompile"] = bool(
            tracing.get_counter(tracing.XLA_COMPILE_COUNT) == b0)
        rep["executor_compile_count"] = ex.stats.compile_count
        emit("dist_ivf_scan", **rep)
    except Exception as e:  # noqa: BLE001
        emit("dist_ivf_scan", error=str(e)[:300])

    # ---- graftwire quantized collectives on the real mesh: the
    # EQuARX-style block-scaled reduce wires (allreduce /
    # reducescatter) and the block-independent affine probe gather,
    # compiled through shard_map across every visible chip, plus the
    # quantized k-means EM's convergence vs the exact f32 wire — a
    # 1-chip "mesh" still compiles the full quantize → narrow
    # collective → dequantize program end to end
    try:
        from jax.sharding import PartitionSpec as P

        from raft_tpu.comms import local_comms
        from raft_tpu.comms.comms import (
            Op,
            allgather_quantized,
            allreduce_quantized,
            reducescatter_quantized,
        )
        from raft_tpu.distributed import kmeans as dist_kmeans

        comms = local_comms()
        axis, nd = comms.axis, comms.size
        rep = {"n_chips": nd}
        mat = jnp.asarray(
            rng.standard_normal((nd * 128, 256)).astype(np.float32))
        mat = mat.at[:, 128:192].multiply(100.0)  # stress the scales
        want = np.asarray(mat).reshape(nd, -1, 256).sum(0)
        ref = max(float(np.abs(want).max()), 1e-9)

        # check_vma=False on the replicated-out calls: the quantized
        # epilogs are replicated by construction but not statically
        # inferrable (same stance as the serving fns)
        def _run(fn):
            return np.asarray(comms.run(
                fn, mat, in_specs=(P(axis, None),), out_specs=P(),
                check_vma=False))

        for wd in ("bf16", "int8"):
            got = _run(lambda m, wd=wd: allreduce_quantized(
                m, Op.SUM, axis, wire_dtype=wd))
            rep[f"allreduce_{wd}_rel_err"] = float(
                np.abs(got - want).max() / ref)
        mi = (mat * 3.0).astype(jnp.int32)
        got_i = np.asarray(comms.run(
            lambda m: allreduce_quantized(m, Op.SUM, axis,
                                          wire_dtype="int8"),
            mi, in_specs=(P(axis, None),), out_specs=P(),
            check_vma=False))
        want_i = np.asarray(mi).reshape(nd, -1, 256).sum(0)
        rep["allreduce_int32_exact"] = bool((got_i == want_i).all())
        rs = np.asarray(comms.run(
            lambda m: reducescatter_quantized(m, Op.SUM, axis,
                                              wire_dtype="int8"),
            mat, in_specs=(P(axis, None),), out_specs=P(axis, None)))
        rep["reducescatter_int8_rel_err"] = float(
            np.abs(rs - want).max() / ref)
        gath = np.asarray(comms.run(
            lambda m: allgather_quantized(m, axis, "int8"),
            mat, in_specs=(P(axis, None),), out_specs=P(),
            check_vma=False))          # stacked (n_shards, rows, n)
        rep["allgather_int8_rel_err"] = float(
            np.abs(gath.reshape(-1, mat.shape[1])
                   - np.asarray(mat)).max()
            / max(float(np.abs(np.asarray(mat)).max()), 1e-9))
        kx2 = jnp.asarray(rng.standard_normal(
            (4096 - 4096 % nd, 64)).astype(np.float32))
        _, in_f = dist_kmeans.fit(comms, kx2, 32, n_iters=8)
        _, in_q = dist_kmeans.fit(comms, kx2, 32, n_iters=8,
                                  wire_dtype="int8")
        rep["kmeans_int8_inertia_vs_f32"] = float(in_q) / float(in_f)
        emit("quantized_wire", **rep)
    except Exception as e:  # noqa: BLE001
        emit("quantized_wire", error=str(e)[:300])

    # ---- fused BQ estimate-then-rerank compiled: on-chip pallas ≡
    # xla parity on ids + the one-stream byte check (the compiled
    # fused program's cost_analysis bytes must sit well under the
    # two-pass estimate + refine programs')
    try:
        from raft_tpu.neighbors import ivf_bq
        from raft_tpu.neighbors.ivf_bq import (
            IvfBqIndexParams,
            IvfBqSearchParams,
        )

        bq_index = ivf_bq.build(None, IvfBqIndexParams(n_lists=64), x)
        rep = {}

        def compiled_bytes(fn, *args, **kw):
            comp = jax.jit(fn, static_argnames=tuple(kw)).lower(
                *args, **kw).compile()
            ca = comp.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            return float(ca.get("bytes accessed", 0.0))

        sp_p = IvfBqSearchParams(n_probes=16, scan_engine="pallas")
        sp_x = IvfBqSearchParams(n_probes=16, scan_engine="xla")
        dp, ip_ = ivf_bq.search(None, sp_p, bq_index, qd, 10)
        dx, ix = ivf_bq.search(None, sp_x, bq_index, qd, 10)
        rep["pallas_ids_eq_xla"] = bool(
            (np.asarray(ip_) == np.asarray(ix)).all())
        rep["max_d_err_vs_xla"] = float(
            np.nanmax(np.abs(np.asarray(dp) - np.asarray(dx))))
        rep["recall_vs_exact"] = float(
            (np.asarray(ip_) == ref_i).mean())
        # stream-bytes: compiled fused (pallas) vs the two-pass
        # estimate-scan + exact-refine alternative
        from raft_tpu.neighbors.refine import refine as _refine

        fw = None
        fused_b = compiled_bytes(
            lambda qq: ivf_bq._search_impl_fn(
                qq, bq_index.centers, bq_index.rotation,
                bq_index.codes, bq_index.rnorm, bq_index.cfac,
                bq_index.errw, bq_index.indices, bq_index.data,
                bq_index.data_norms, fw, n_probes=16, k=10,
                metric=bq_index.metric, scan_engine="pallas"), qd)
        est_b = compiled_bytes(
            lambda qq: ivf_bq._search_impl_fn(
                qq, bq_index.centers, bq_index.rotation,
                bq_index.codes, bq_index.rnorm, bq_index.cfac,
                bq_index.errw, bq_index.indices, None, None, fw,
                n_probes=16, k=40, metric=bq_index.metric,
                scan_engine="rank"), qd)
        _, cand = ivf_bq.search(
            None, IvfBqSearchParams(n_probes=16, scan_engine="rank"),
            bq_index, qd, 40)
        refine_b = compiled_bytes(
            lambda qq, cc: _refine(None, xd, qq, cc, 10), qd, cand)
        rep["fused_bytes"] = fused_b
        rep["two_pass_bytes"] = est_b + refine_b
        rep["one_stream"] = bool(fused_b < est_b + refine_b)
        emit("bq_scan", **rep)
    except Exception as e:  # noqa: BLE001
        emit("bq_scan", error=str(e)[:300])

    # ---- grafttier tiered scan compiled (PR 14): on-chip pallas ≡
    # xla on ids AND distances with half the lists host-cold, swap
    # bit-stability through a placement epoch, and the compiled
    # hot-vs-cold stream split via cost_analysis — the dual-roofline
    # evidence: the tiered program's DEVICE bytes-accessed must sit
    # close to the hot tier's share, not re-read the whole index
    # (whether the cold operand truly stays host-resident on this
    # jaxlib is reported, not assumed: host_resident says what
    # host_put achieved)
    try:
        from raft_tpu.neighbors import ivf_flat, tiered

        xs = jnp.asarray(rng.standard_normal((20_000, 128)).astype(
            np.float32))
        qs = jnp.asarray(rng.standard_normal((16, 128)).astype(
            np.float32))
        params = ivf_flat.IvfFlatIndexParams(n_lists=64,
                                             kmeans_n_iters=5)
        single = ivf_flat.build(None, params, xs)
        t = tiered.build_tiered(single, hot_fraction=0.5)
        rep = {"n_hot": t.n_hot, "n_cold": t.n_cold,
               "host_resident": bool(t.host_resident)}
        outs = {}
        for eng in ("xla", "pallas"):
            sp = tiered.TieredSearchParams(n_probes=8, scan_engine=eng)
            d1, i1 = tiered.search(None, sp, t, qs, 10)
            outs[eng] = (np.asarray(d1), np.asarray(i1))
        sp0 = ivf_flat.IvfFlatSearchParams(n_probes=8,
                                           scan_engine="xla")
        d0, i0 = ivf_flat.search(None, sp0, single, qs, 10)
        rep["pallas_bits_eq_xla"] = bool(
            (outs["pallas"][0] == outs["xla"][0]).all()
            and (outs["pallas"][1] == outs["xla"][1]).all())
        rep["tiered_bits_eq_allhbm"] = bool(
            (outs["xla"][0] == np.asarray(d0)).all()
            and (outs["xla"][1] == np.asarray(i0)).all())
        # placement swap on-chip: promote/demote 4 pairs, results
        # must not move a bit
        tiered.apply_plan(t, [int(x_) for x_ in t.cold_lists[:4]],
                          [int(x_) for x_ in t.hot_lists[:4]],
                          width=8)
        sp = tiered.TieredSearchParams(n_probes=8,
                                       scan_engine="pallas")
        d2, i2 = tiered.search(None, sp, t, qs, 10)
        rep["post_swap_bits_exact"] = bool(
            (np.asarray(d2) == outs["pallas"][0]).all()
            and (np.asarray(i2) == outs["pallas"][1]).all())

        # compiled stream split: cost_analysis bytes of the tiered
        # pallas program vs the all-HBM list-major program — with
        # the cold plane host-side, device bytes-accessed should
        # drop toward the hot share
        def compiled_bytes(fn, *args, **kw):
            comp = jax.jit(fn, static_argnames=tuple(kw)).lower(
                *args, **kw).compile()
            ca = comp.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            return float(ca.get("bytes accessed", 0.0))

        fw = None
        tiered_b = compiled_bytes(
            lambda qq: tiered._tiered_search_fn(
                qq, t.centers, t.center_norms, t.hot_data,
                t.cold_data, t.hot_slot_map, t.cold_slot_map,
                t.data_norms, t.indices, fw, n_probes=8, k=10,
                metric=t.metric, scan_engine="pallas"), qs)
        allhbm_b = compiled_bytes(
            lambda qq: ivf_flat._search_impl_fn(
                qq, single.centers, single.center_norms, single.data,
                single.data_norms, single.indices, fw, n_probes=8,
                k=10, metric=single.metric, scan_engine="pallas"), qs)
        rep["tiered_compiled_bytes"] = tiered_b
        rep["allhbm_compiled_bytes"] = allhbm_b
        rep["hot_fraction_of_bytes"] = float(
            t.hot_bytes / (t.hot_bytes + t.cold_bytes))
        emit("tier_scan", **rep)
    except Exception as e:  # noqa: BLE001
        emit("tier_scan", error=str(e)[:300])

    # ---- graftcast tiered PQ/BQ compiled (PR 18): the compressed
    # planes tier the same way — codes plane (PQ) / 5-plane record
    # (BQ) half host-cold, results bit-identical to the all-HBM
    # index on-chip, and still bit-identical after a placement swap.
    # The on-chip questions CI cannot answer (dual-source BQ kernel,
    # sparse cold gather) ride the ROADMAP evidence list.
    try:
        from raft_tpu.neighbors import ivf_pq
        from raft_tpu.neighbors import tiered as tiered_mod

        pqp = ivf_pq.IvfPqIndexParams(n_lists=64, pq_dim=16,
                                      kmeans_n_iters=5)
        pq_idx = ivf_pq.build(None, pqp, xs)
        tpq = tiered_mod.build_tiered_pq(pq_idx, hot_fraction=0.5)
        spq = ivf_pq.IvfPqSearchParams(n_probes=8)
        d0, i0 = ivf_pq.search(None, spq, pq_idx, qs, 10)
        d1, i1 = tiered_mod.search_pq(None, spq, tpq, qs, 10)
        rep = {"n_hot": tpq.n_hot, "n_cold": tpq.n_cold,
               "host_resident": bool(tpq.host_resident),
               "bits_eq_allhbm": bool(
                   (np.asarray(d1) == np.asarray(d0)).all()
                   and (np.asarray(i1) == np.asarray(i0)).all())}
        tiered_mod.apply_plan(
            tpq, [int(x_) for x_ in tpq.cold_lists[:4]],
            [int(x_) for x_ in tpq.hot_lists[:4]], width=8)
        d2, i2 = tiered_mod.search_pq(None, spq, tpq, qs, 10)
        rep["post_swap_bits_exact"] = bool(
            (np.asarray(d2) == np.asarray(d1)).all()
            and (np.asarray(i2) == np.asarray(i1)).all())
        emit("tiered_pq", **rep)
    except Exception as e:  # noqa: BLE001
        emit("tiered_pq", error=str(e)[:300])

    try:
        from raft_tpu.neighbors import ivf_bq
        from raft_tpu.neighbors import tiered as tiered_mod

        bqp = ivf_bq.IvfBqIndexParams(n_lists=64, kmeans_n_iters=5)
        bq_idx = ivf_bq.build(None, bqp, xs)
        tbq = tiered_mod.build_tiered_bq(bq_idx, hot_fraction=0.5)
        sbq = ivf_bq.IvfBqSearchParams(n_probes=8)
        d0, i0 = ivf_bq.search(None, sbq, bq_idx, qs, 10)
        d1, i1 = tiered_mod.search_bq(None, sbq, tbq, qs, 10)
        rep = {"n_hot": tbq.n_hot, "n_cold": tbq.n_cold,
               "host_resident": bool(tbq.host_resident),
               "bits_eq_allhbm": bool(
                   (np.asarray(d1) == np.asarray(d0)).all()
                   and (np.asarray(i1) == np.asarray(i0)).all())}
        tiered_mod.apply_plan(
            tbq, [int(x_) for x_ in tbq.cold_lists[:4]],
            [int(x_) for x_ in tbq.hot_lists[:4]], width=8)
        d2, i2 = tiered_mod.search_bq(None, sbq, tbq, qs, 10)
        rep["post_swap_bits_exact"] = bool(
            (np.asarray(d2) == np.asarray(d1)).all()
            and (np.asarray(i2) == np.asarray(i1)).all())
        emit("tiered_bq", **rep)
    except Exception as e:  # noqa: BLE001
        emit("tiered_bq", error=str(e)[:300])

    # ---- beam_search compiled vs the XLA engine (same seeds)
    try:
        from raft_tpu.neighbors.cagra import _search_batch

        deg, w, L = 32, 4, 64
        dm = (jnp.sum(xd[:4000]**2, 1)[:, None]
              + jnp.sum(xd[:4000]**2, 1)[None, :]
              - 2.0 * xd[:4000] @ xd[:4000].T)
        dm = dm + jnp.diag(jnp.full((4000,), jnp.inf))
        _, g = jax.lax.top_k(-dm, deg)
        graph = jnp.asarray(g, jnp.int32)
        seeds = jnp.asarray(
            rng.integers(0, 4000, (16, w * deg)).astype(np.int32))
        bd, bi = beam_search(qd, xd[:4000], graph, seeds, 10, L, w, 24,
                             DistanceType.L2Expanded)
        xd2, xi2 = _search_batch(xd[:4000], graph, qd, seeds, None, k=10,
                                 L=L, w=w, max_iters=24,
                                 metric=DistanceType.L2Expanded)
        agree = float((np.asarray(bi) == np.asarray(xi2)).mean())
        emit("beam_search", id_agreement_vs_xla=agree,
             max_d_err=float(np.nanmax(np.abs(
                 np.asarray(bd) - np.asarray(xd2)))))
        # HBM-resident mode (the any-size engine: candidate rows DMA'd
        # from HBM, double-buffered) — must match the VMEM engine's ids
        try:
            hd, hi = beam_search(qd, xd[:4000], graph, seeds, 10, L, w,
                                 24, DistanceType.L2Expanded,
                                 ds_mode="hbm")
            emit("beam_search_hbm",
                 id_agreement_vs_vmem=float(
                     (np.asarray(hi) == np.asarray(bi)).mean()),
                 max_d_err=float(np.nanmax(np.abs(
                     np.asarray(hd) - np.asarray(bd)))))
        except Exception as e:  # noqa: BLE001
            emit("beam_search_hbm", error=str(e)[:300])
        # int8 (CAGRA-Q role): the (1, d) int8 HBM row DMA has its own
        # Mosaic tiling; prove it on real silicon, vmem vs hbm parity
        try:
            x8 = jnp.asarray(np.clip(x[:4000] * 30.0, -127, 127)
                             .astype(np.int8))
            vd8, vi8 = beam_search(qd, x8, graph, seeds, 10, L, w, 24,
                                   DistanceType.L2Expanded,
                                   ds_mode="vmem")
            hd8, hi8 = beam_search(qd, x8, graph, seeds, 10, L, w, 24,
                                   DistanceType.L2Expanded,
                                   ds_mode="hbm")
            emit("beam_search_hbm_int8",
                 id_agreement_vs_vmem=float(
                     (np.asarray(hi8) == np.asarray(vi8)).mean()))
        except Exception as e:  # noqa: BLE001
            emit("beam_search_hbm_int8", error=str(e)[:300])
    except Exception as e:  # noqa: BLE001
        emit("beam_search", error=str(e)[:300])

    # ---- graftbeam: the rebuilt CAGRA serving path compiled on chip
    # — coarse-plane seeding + BQ-coded traversal. On-chip evidence
    # debt this piece retires: the packed record plane's
    # bitcast_convert_type lanes and the rec_pad-lane (non-128) record
    # window selects must compile under Mosaic, and the kernel's
    # conditional exact-rerank DMA (estimate-survivors only) must keep
    # id parity with the XLA twin's dense replay of the same
    # _block_estimate math.
    try:
        import dataclasses as _dc

        from raft_tpu.neighbors import cagra

        gidx = cagra.build(None, cagra.CagraIndexParams(
            graph_degree=32, bq_bits=2), x)
        rep = {"seed_lists": int(gidx.seed_centers.shape[0])}
        d_full = (np.sum(q.astype(np.float64)**2, 1)[:, None]
                  + np.sum(x.astype(np.float64)**2, 1)[None, :]
                  - 2.0 * q.astype(np.float64) @ x.astype(np.float64).T)
        gt = np.argsort(d_full, axis=1, kind="stable")[:, :10]

        def rec10(ids):
            ids = np.asarray(ids)
            return float(np.mean([
                len(set(ids[r]) & set(gt[r])) / 10
                for r in range(ids.shape[0])]))

        for nm, p in {
            "pool": cagra.CagraSearchParams(seed_mode="pool",
                                            seed_pool=4096),
            "coarse": cagra.CagraSearchParams(seed_mode="coarse",
                                              seed_pool=512),
            "coarse_bq": cagra.CagraSearchParams(
                seed_mode="coarse", seed_pool=512,
                bq_traversal="on"),
        }.items():
            _, i_a = cagra.search(None, p, gidx, qd, 10)
            rep[f"{nm}_recall"] = rec10(i_a)
        # pallas-vs-xla bit parity with BQ pruning ON, compiled — the
        # riskiest Mosaic surface of the rewrite
        p_k = cagra.CagraSearchParams(seed_mode="coarse", seed_pool=512,
                                      bq_traversal="on", algo="pallas")
        p_x = _dc.replace(p_k, algo="xla")
        dk, ik = cagra.search(None, p_k, gidx, qd, 10)
        dx, ix = cagra.search(None, p_x, gidx, qd, 10)
        rep["bq_pallas_ids_vs_xla"] = float(
            (np.asarray(ik) == np.asarray(ix)).mean())
        rep["bq_pallas_max_d_err_vs_xla"] = float(np.nanmax(np.abs(
            np.asarray(dk) - np.asarray(dx))))
        emit("graftbeam_cagra", **rep)
    except Exception as e:  # noqa: BLE001
        emit("graftbeam_cagra", error=str(e)[:300])

    # ---- graftflight: capture-and-attribute on the real chip — a
    # jax.profiler capture around compiled executor dispatches must
    # correlate back to the digest-named modules, yielding MEASURED
    # device seconds next to the modeled cost-analysis bytes (the
    # on-chip evidence the measured-supersedes-modeled contract needs:
    # on TPU the xplane export also carries the named-scope phase
    # markers the CPU chrome export drops)
    try:
        import tempfile

        from raft_tpu.core import profiling, tracing
        from raft_tpu.core.executor import SearchExecutor
        from raft_tpu.neighbors import ivf_flat

        idx = ivf_flat.build(
            None, ivf_flat.IvfFlatIndexParams(n_lists=64), x)
        ex = SearchExecutor(min_bucket=16, max_bucket=16)
        sp = ivf_flat.IvfFlatSearchParams(n_probes=8)
        ex.search(idx, q[:16, :], 10, params=sp)     # compile + warm
        prof_dir = tempfile.mkdtemp(prefix="graftflight_")
        with tracing.capture(prof_dir):
            for _ in range(8):
                jax.block_until_ready(
                    ex.search(idx, q[:16, :], 10, params=sp))
        attr = profiling.attribute(prof_dir, ex.executable_costs())
        measured = profiling.publish(attr)
        from raft_tpu.serving import metrics as serving_metrics

        derived = serving_metrics.derived()
        emit("graftflight_attribution",
             matched_executables=len(attr.modules),
             unmatched_modules=len(attr.unmatched_modules),
             invocations=sum(m.invocations
                             for m in attr.modules.values()),
             measured_device_seconds=sum(
                 m.device_seconds for m in attr.modules.values()),
             measured_gbps={d: s["gbps"] for d, s in measured.items()},
             device_achieved_gbps=derived["device_achieved_gbps"],
             phase_seconds={d: m.phase_seconds
                            for d, m in attr.modules.items()})
    except Exception as e:  # noqa: BLE001
        emit("graftflight_attribution", error=str(e)[:300])


if __name__ == "__main__":
    main()
