#!/usr/bin/env python
"""Third-round TPU probes: grid-step overhead vs HBM bandwidth.

Round-2 finding: pallas_read at tile=4096 (256 steps over 512 MB) and
bf16 at the same step count take the SAME wall time (~4.2 ms) — the
stream is per-step-overhead bound (~16 us/step), not byte bound. This
probe sweeps block sizes (and the Mosaic vmem limit) to find the real
bandwidth ceiling and the knee, for f32 and bf16, then re-checks
fused_knn with the best tile. Also A/Bs PQ scoring modes standalone.

Pipelined fetch-anchored timing; run serially on a healthy relay.
"""

import functools
import json
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def timed(tag, fn, iters=20, payload=None, extra=None):
    try:
        out = fn()
        np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn()
        np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
        dt = (time.perf_counter() - t0) / iters
    except Exception as e:  # noqa: BLE001 — probe must survive OOMs
        print(json.dumps({"piece": tag, "error": str(e)[:200]}), flush=True)
        return None
    rec = {"piece": tag, "ms": round(dt * 1e3, 3)}
    if payload:
        rec["gbps"] = round(payload / dt / 1e9, 1)
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)
    return dt


def _read_kernel(x_ref, o_ref, acc):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)

    acc[:] += jnp.sum(x_ref[:].astype(jnp.float32), axis=0, keepdims=True)

    @pl.when(step == pl.num_programs(0) - 1)
    def _():
        o_ref[:] = acc[:]


@functools.partial(jax.jit, static_argnames=("tile", "vmem_mb"))
def pallas_read(x, tile: int, vmem_mb: int = 0):
    n, d = x.shape
    assert n % tile == 0
    params = {}
    if vmem_mb:
        params["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=vmem_mb * 1024 * 1024)
    return pl.pallas_call(
        _read_kernel,
        grid=(n // tile,),
        in_specs=[pl.BlockSpec((tile, d), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, d), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((1, d), jnp.float32)],
        **params,
    )(x)


def main():
    print(json.dumps({"prof": "round3", "backend": jax.default_backend()}),
          flush=True)

    big = jax.random.normal(jax.random.key(0), (1 << 20, 128), jnp.float32)
    bigb = big.astype(jnp.bfloat16)

    # ---- 1. tile sweep: is the stream step-bound or byte-bound?
    for tile in (2048, 4096, 8192):
        timed(f"read_f32_t{tile}", lambda t=tile: pallas_read(big, t),
              payload=512e6, extra={"steps": (1 << 20) // tile})
    for tile, mb in ((16384, 40), (32768, 72), (65536, 0)):
        # 65536: 32 MB blocks — needs ~68 MB; v5e physical VMEM is 128 MB
        timed(f"read_f32_t{tile}_v{mb or 128}",
              lambda t=tile, m=mb or 128: pallas_read(big, t, m),
              payload=512e6, extra={"steps": (1 << 20) // tile})
    for tile, mb in ((8192, 0), (16384, 0), (32768, 40), (65536, 72)):
        timed(f"read_bf16_t{tile}_v{mb or 16}",
              lambda t=tile, m=mb: pallas_read(bigb, t, m),
              payload=256e6, extra={"steps": (1 << 20) // tile})

    # ---- 2. XLA-native streams for reference
    js = jax.jit(lambda x: jnp.sum(x, axis=0))
    timed("xla_colsum_f32", lambda: js(big), payload=512e6)
    timed("xla_colsum_bf16", lambda: js(bigb), payload=256e6)

    # ---- 3. fused_knn with bigger tiles (needs the code's tile param)
    from raft_tpu.distance.types import DistanceType
    from raft_tpu.ops.fused_topk import fused_knn

    qs = jax.random.normal(jax.random.key(2), (10, 128), jnp.float32)
    norms = jnp.sum(jnp.square(big), axis=1)
    for tag, ds, tiles in (("f32", big, (8192, 16384, 32768)),
                           ("bf16", bigb, (8192, 16384, 32768, 65536))):
        for t in tiles:
            timed(f"fused_knn_{tag}_t{t}",
                  lambda ds=ds, t=t: fused_knn(
                      qs, ds, 10, DistanceType.L2Expanded,
                      dataset_norms=norms, tile=t),
                  payload=(512e6 if tag == "f32" else 256e6))

    # ---- 4. PQ scoring A/B standalone (q=100 m=256 s match profile cfg)
    from raft_tpu.neighbors.ivf_pq import score_fn

    kl, kr = jax.random.split(jax.random.key(4))
    for J, s in ((256, 64), (16, 128)):
        lut = jax.random.normal(kl, (100, s, J), jnp.float32)
        rows = jax.random.randint(kr, (100, 256, s), 0, J,
                                  jnp.int32).astype(jnp.uint8)
        jax.block_until_ready((lut, rows))
        modes = ("onehot", "select") if J <= 32 else ("onehot",)
        for mode in modes:
            f = jax.jit(score_fn(mode, J))
            timed(f"pq_score_{mode}_J{J}_s{s}", lambda f=f: f(lut, rows))


if __name__ == "__main__":
    main()
