#!/usr/bin/env bash
# Serial TPU A/B profiling session — run ONLY when the relay is healthy
# and nothing else is using the chip. Never run two of these at once;
# never kill one mid-flight (the relay wedges).
set -uo pipefail
cd "$(dirname "$0")/.."

N=${BENCH_N:-1000000}
SECS=${BENCH_SECONDS:-20}
# One child-deadline value drives both budgets: bench.py can run TWO
# children back to back (TPU child abandoned at the deadline, then a
# CPU fallback child with the same deadline), so the shell timeout must
# cover 2x the child deadline plus probe/startup margin — otherwise the
# fallback's JSON line is lost to the shell kill (ADVICE r3).
DEADLINE=${BENCH_CHILD_DEADLINE:-2400}
SHELL_TIMEOUT=$((2 * DEADLINE + 600))

run() {
  echo "=== $* ===" >&2
  env "$@" BENCH_N=$N BENCH_SECONDS=$SECS BENCH_CHILD_DEADLINE=$DEADLINE \
    timeout $SHELL_TIMEOUT python bench.py
}

# 1. f32 storage, fused Pallas kernel (bench.py now defaults to bf16
#    on TPU, so the f32 legs pin BENCH_DTYPE explicitly)
run BENCH_DTYPE=float32 BENCH_TAG=fused
# 2. f32 storage, XLA tile-scan path
run BENCH_DTYPE=float32 RAFT_TPU_DISABLE_FUSED=1 BENCH_TAG=scan
# 3. bf16 storage (half the HBM stream)
run BENCH_DTYPE=bfloat16 BENCH_TAG=bf16
# 4. bf16 + scan
run BENCH_DTYPE=bfloat16 RAFT_TPU_DISABLE_FUSED=1 BENCH_TAG=bf16scan

# 5. ANN mini-suite: ivf_flat / ivf_pq(gather|onehot) / cagra on 200k
timeout 3600 python - << 'EOF'
import json, time
import jax, jax.numpy as jnp
import numpy as np
from raft_tpu.neighbors import ivf_flat, ivf_pq, cagra
from raft_tpu.utils import eval_recall

N, D, Q, K = 200_000, 128, 100, 10
rng = np.random.default_rng(0)
x = rng.standard_normal((N, D)).astype(np.float32)
q = rng.standard_normal((Q, D)).astype(np.float32)
from raft_tpu.neighbors import brute_force
gt_d, gt_i = brute_force.knn(None, x, q, K)
gt = np.asarray(gt_i)

def bench(name, fn, iters=10):
    out = fn(); jax.block_until_ready(out)        # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
        jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    d, i = out
    r, _, _ = eval_recall(gt, np.asarray(i))
    print(json.dumps({"bench": name, "qps": round(Q / dt, 1),
                      "recall": round(float(r), 4)}), flush=True)

fi = ivf_flat.build(None, ivf_flat.IvfFlatIndexParams(n_lists=1024), x)
for p in (32, 64):
    sp = ivf_flat.IvfFlatSearchParams(n_probes=p)
    bench(f"ivf_flat_p{p}", lambda sp=sp: tuple(
        jax.block_until_ready(ivf_flat.search(None, sp, fi, q, K))))

pi = ivf_pq.build(None, ivf_pq.IvfPqIndexParams(n_lists=1024, pq_dim=64), x)
for mode in ("gather", "onehot"):
    sp = ivf_pq.IvfPqSearchParams(n_probes=64, score_mode=mode)
    bench(f"ivf_pq_{mode}", lambda sp=sp: tuple(
        jax.block_until_ready(ivf_pq.search(None, sp, pi, q, K))))

ci = cagra.build(None, cagra.CagraIndexParams(
    graph_degree=32, intermediate_graph_degree=64,
    build_algo=cagra.BuildAlgo.NN_DESCENT), x)
for it in (64, 128):
    sp = cagra.CagraSearchParams(itopk_size=it, search_width=4)
    bench(f"cagra_itopk{it}", lambda sp=sp: tuple(
        jax.block_until_ready(cagra.search(None, sp, ci, q, K))))
EOF
