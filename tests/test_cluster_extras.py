"""single-linkage / spectral / LAP tests (reference ``cpp/test/cluster``,
``cpp/test/sparse/spectral_matrix``, ``cpp/test/lap``)."""

import jax.numpy as jnp
import numpy as np
import pytest
import scipy.optimize
import sklearn.metrics as skm
from sklearn.datasets import make_blobs

from raft_tpu.cluster.single_linkage import single_linkage
from raft_tpu.solver import LinearAssignmentProblem, linear_assignment
from raft_tpu import spectral
from raft_tpu.sparse.types import CSR


class TestSingleLinkage:
    def test_blobs_recovery(self, res):
        x, y = make_blobs(
            n_samples=200, centers=4, n_features=8, cluster_std=0.4, random_state=0
        )
        out = single_linkage(res, x.astype(np.float32), 4)
        assert out.labels.shape == (200,)
        assert len(np.unique(out.labels)) == 4
        assert skm.adjusted_rand_score(y, out.labels) > 0.95

    def test_matches_sklearn_moons(self, res):
        # non-convex shapes: exactly where single-linkage beats kmeans
        from sklearn.datasets import make_moons
        from sklearn.cluster import AgglomerativeClustering

        x, y = make_moons(n_samples=150, noise=0.04, random_state=0)
        out = single_linkage(res, x.astype(np.float32), 2, k=10)
        sk = AgglomerativeClustering(n_clusters=2, linkage="single").fit(x)
        assert skm.adjusted_rand_score(sk.labels_, out.labels) > 0.95

    def test_dendrogram_shape(self, res):
        x, _ = make_blobs(n_samples=40, centers=3, n_features=4, random_state=1)
        out = single_linkage(res, x.astype(np.float32), 3)
        assert out.children.shape == (39, 2)
        assert out.deltas.shape == (39,)
        # merge distances ascend (single linkage over sorted MST edges)
        assert np.all(np.diff(out.deltas) >= -1e-6)


def _two_cliques_csr(n_half=10, p_bridge=1):
    """Two dense cliques joined by a single bridge edge."""
    n = 2 * n_half
    a = np.zeros((n, n), np.float32)
    a[:n_half, :n_half] = 1.0
    a[n_half:, n_half:] = 1.0
    np.fill_diagonal(a, 0.0)
    a[0, n_half] = a[n_half, 0] = 1.0
    return CSR.from_dense(a), n


class TestSpectral:
    def test_partition_two_cliques(self, res):
        adj, n = _two_cliques_csr()
        labels, evals, emb = spectral.partition(res, adj, 2, seed=3)
        labels = np.asarray(labels)
        want = np.array([0] * 10 + [1] * 10)
        assert skm.adjusted_rand_score(want, labels) == 1.0

    def test_analyze_partition(self, res):
        adj, n = _two_cliques_csr()
        labels = jnp.asarray([0] * 10 + [1] * 10)
        edge_cut, cost = spectral.analyze_partition(res, adj, labels)
        np.testing.assert_allclose(float(edge_cut), 1.0, atol=1e-4)  # the bridge
        np.testing.assert_allclose(float(cost), 2 * 1.0 / 10, rtol=1e-4)

    def test_modularity_maximization(self, res):
        adj, n = _two_cliques_csr()
        labels, evals, emb = spectral.modularity_maximization(res, adj, 2, seed=0)
        want = np.array([0] * 10 + [1] * 10)
        assert skm.adjusted_rand_score(want, np.asarray(labels)) == 1.0
        q = spectral.modularity(res, adj, jnp.asarray(want))
        assert float(q) > 0.4  # two near-disconnected cliques

    def test_fit_embedding_fiedler_sign_structure(self, res):
        adj, n = _two_cliques_csr()
        evals, evecs = spectral.fit_embedding(res, adj, 1, seed=1)
        fiedler = np.asarray(evecs)[:, 0]
        # Fiedler vector separates the cliques by sign
        s1 = set(np.sign(fiedler[:10]))
        s2 = set(np.sign(fiedler[10:]))
        assert s1 == {1.0} and s2 == {-1.0} or s1 == {-1.0} and s2 == {1.0}


class TestLAP:
    @pytest.mark.parametrize("n", [5, 20, 64])
    def test_matches_scipy(self, rng_np, res, n):
        cost = rng_np.integers(0, 100, (n, n)).astype(np.float32)
        assign, total = linear_assignment(res, cost)
        assign = np.asarray(assign)
        # valid permutation
        assert sorted(assign.tolist()) == list(range(n))
        ri, ci = scipy.optimize.linear_sum_assignment(cost)
        np.testing.assert_allclose(float(total), cost[ri, ci].sum(), atol=1e-3)

    def test_float_costs_near_optimal(self, rng_np, res):
        n = 32
        cost = rng_np.random((n, n)).astype(np.float32)
        assign, total = linear_assignment(res, cost)
        ri, ci = scipy.optimize.linear_sum_assignment(cost)
        opt = cost[ri, ci].sum()
        # auction with eps-scaling: within n*eps_final of optimum
        assert float(total) <= opt + n * (1.0 / (n + 1)) + 1e-3

    def test_maximize(self, rng_np, res):
        n = 10
        cost = rng_np.integers(0, 50, (n, n)).astype(np.float32)
        assign, total = linear_assignment(res, cost, maximize=True)
        ri, ci = scipy.optimize.linear_sum_assignment(cost, maximize=True)
        np.testing.assert_allclose(float(total), cost[ri, ci].sum(), atol=1e-3)

    def test_batched_object_api(self, rng_np, res):
        n, b = 8, 3
        costs = rng_np.integers(0, 30, (b, n, n)).astype(np.float32)
        lap = LinearAssignmentProblem(res, n, b)
        assigns = np.asarray(lap.solve(costs))
        for i in range(b):
            ri, ci = scipy.optimize.linear_sum_assignment(costs[i])
            np.testing.assert_allclose(
                float(np.asarray(lap.objective_values)[i]),
                costs[i][ri, ci].sum(),
                atol=1e-3,
            )
