"""graftlint tests — the fixture corpus (one minimal violating + one
conforming sample per rule, so each rule is proven live: disable a
rule and its fixture test fails), the repo-wide "lint is clean" gate,
and the suppression-inventory snapshot (a new ``disable=`` pragma
anywhere in the tree must show up here, in review)."""

import pathlib

import pytest

from raft_tpu.analysis import RULES, lint_root, lint_texts
from raft_tpu.analysis.core import parse_pragma_items

ROOT = pathlib.Path(__file__).resolve().parents[1]


def rules_fired(report):
    return {f.rule for f in report.findings}


def lint_lib(src, rules, rel="raft_tpu/ops/sample.py"):
    return lint_texts({rel: src}, rules=rules)


# ---------------------------------------------------------------------------
# fixture corpus — VIOLATING / CONFORMING per rule
# ---------------------------------------------------------------------------

R0_VIOLATING = (
    "import os\n"          # unused import
    "x = 1 \n"             # trailing whitespace
)
R0_CONFORMING = "import os\n\nx = os.getpid()\n"

R1_VIOLATING = '''\
def _score_fn(queries, data, *, k: int):
    total = queries + data
    if total > 0:
        return total
    while queries:
        queries = queries - 1
    return total
'''
R1_CONFORMING = '''\
def _score_fn(queries, data, *, k: int):
    if queries.ndim == 2 and data is not None:
        return queries + data
    if k > 4:
        return data
    return queries
'''
R1_KEY_VIOLATING = '''\
def _plan(statics, arrays):
    key = ("ivf", [s for s in statics], float(arrays))
    return key
'''
R1_KEY_CONFORMING = '''\
def _plan(statics, arrays):
    key = ("ivf", tuple(sorted(statics)), len(arrays))
    return key
'''

R2_VIOLATING = '''\
import jax


def _step_fn(state):
    return state


def serve(state):
    step = jax.jit(_step_fn, donate_argnums=(0,))
    out = step(state)
    return out + state
'''
R2_CONFORMING = '''\
import jax


def _step_fn(state):
    return state


def serve(state):
    step = jax.jit(_step_fn, donate_argnums=(0,))
    state = step(state)
    return state
'''
R2_DECORATOR_VIOLATING = '''\
import functools

import jax


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter(buf, rows):
    return buf


def extend_all(buf, rows):
    out = _scatter(buf, rows)
    return out + buf
'''
R2_ARGNAMES_VIOLATING = '''\
import jax


def _step_fn(init_d, rows):
    return init_d


def serve(init_d, rows):
    step = jax.jit(_step_fn, donate_argnames=("init_d",))
    out = step(init_d, rows)
    return out + init_d
'''
R2_DONATE_KWARG = '''\
def extend(res, index, rows, donate=False):
    return index


def grow(res, index, rows):
    index = extend(res, index, rows, donate=True)
    return index, rows  # rows stays caller-owned — NOT a finding
'''

R3_VIOLATING = '''\
import jax


def merge(x, axis):
    return jax.lax.psum(x, axis)
'''
R3_CONFORMING = '''\
from raft_tpu.comms.comms import allreduce


def merge(x, axis):
    return allreduce(x, axis=axis)
'''
R3_AXIS_VIOLATING = '''\
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import allgather


def merge(x):
    spec = P("data")
    return allgather(x, axis="dataa"), spec
'''
R3_AXIS_CONFORMING = '''\
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import allgather


def merge(x):
    spec = P("data")
    return allgather(x, axis="data"), spec
'''

R4_VIOLATING = '''\
import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def run(x, interpret=False):
    n = x.shape[1]
    blocks = n // 512
    return pl.pallas_call(
        kernel,
        grid=(blocks,),
        in_specs=[pl.BlockSpec((8, 512), lambda i: (0, i))],
        out_specs=pl.BlockSpec((8, 512), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((8, 512), x.dtype),
        interpret=interpret,
    )(x)
'''
R4_BUDGET_VIOLATING = '''\
import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_COMPILER_PARAMS = pltpu.CompilerParams


def kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def run(x, interpret=False):
    rows = 16384
    cols = 4096
    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((rows, cols), lambda i: (0, i))],
        out_specs=pl.BlockSpec((rows, cols), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((rows, cols), x.dtype),
        compiler_params=_COMPILER_PARAMS(vmem_limit_bytes=64 << 20),
        interpret=interpret,
    )(x)
'''
R4_CONFORMING = '''\
import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_COMPILER_PARAMS = pltpu.CompilerParams


def kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def run(x, n, interpret=False):
    npad = -(-n // 512) * 512
    blocks = npad // 512
    return pl.pallas_call(
        kernel,
        grid=(blocks,),
        in_specs=[pl.BlockSpec((8, 512), lambda i: (0, i))],
        out_specs=pl.BlockSpec((8, 512), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((8, 512), x.dtype),
        compiler_params=_COMPILER_PARAMS(vmem_limit_bytes=64 << 20),
        interpret=interpret,
    )(x)
'''

R4_SYMBOLIC_VIOLATING = '''\
import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_COMPILER_PARAMS = pltpu.CompilerParams


def kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def run(x, interpret=False):
    n = x.shape[0]
    rows = min(n, 65536)  # dynamic, but bounded by the cap
    cols = 4096
    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((rows, cols), lambda i: (0, i))],
        out_specs=pl.BlockSpec((rows, cols), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((rows, cols), x.dtype),
        compiler_params=_COMPILER_PARAMS(vmem_limit_bytes=64 << 20),
        interpret=interpret,
    )(x)
'''
R4_SYMBOLIC_CONFORMING = '''\
import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_COMPILER_PARAMS = pltpu.CompilerParams


def kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def run(x, interpret=False):
    n = x.shape[0]
    rows = min(n, 256)  # dynamic, bounded well inside the budget
    cols = 512
    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((rows, cols), lambda i: (0, i))],
        out_specs=pl.BlockSpec((rows, cols), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((rows, cols), x.dtype),
        compiler_params=_COMPILER_PARAMS(vmem_limit_bytes=64 << 20),
        interpret=interpret,
    )(x)
'''

R5_VIOLATING = '''\
import numpy as np


def _scan_fn(queries, data, *, k: int):
    hot = float(queries)
    host = np.asarray(data)
    return hot, host


def refresh(parts, dev):
    import jax

    out = []
    for p in parts:
        out.append(jax.device_put(p, dev))
    return out
'''
R5_CONFORMING = '''\
import numpy as np


def _scan_fn(queries, data, *, k: int):
    q = int(np.shape(queries)[0])
    return queries[:q] + data


def refresh(parts, dev):
    import jax

    return jax.device_put(list(parts), dev)
'''

# PR 5 scope extensions: R5 covers raft_tpu/serving/* and R1's
# cache-key discipline covers the batcher's coalescing keys
R5_SERVING_VIOLATING = '''\
def dispatch(batch):
    depth = batch.depth.item()
    return depth
'''
R1_SERVING_KEY_VIOLATING = '''\
def admit(executor, index, k, kw, handle):
    compat_key = (id(index), [k], float(kw))
    return SearchRequest(compat_key={"k": k}, handle=handle)
'''
R1_SERVING_KEY_CONFORMING = '''\
def admit(executor, index, k, kw, handle):
    compat_key = (id(index), k,
                  tuple(sorted((n, str(v)) for n, v in kw.items())))
    return SearchRequest(compat_key=compat_key, handle=handle)
'''

R7_SERVING_VIOLATING = '''\
import time


def pick_deadline(timeout_s):
    return time.monotonic() + timeout_s


def stamp():
    return time.time()
'''
R7_SERVING_CONFORMING = '''\
import time


class MonotonicClock:
    def now(self):
        return time.monotonic()


class WallClock:
    def now(self):
        return time.time()


def pick_deadline(clock, timeout_s):
    return clock.now() + timeout_s


def nap(delay_s):
    time.sleep(delay_s)    # sleeping reads no clock
'''
R7_BARE_IMPORT_VIOLATING = '''\
from time import monotonic


def stamp():
    return monotonic()
'''
R7_EVASION_VIOLATING = '''\
import time as t
from time import time
from time import perf_counter as pc


def three_ways():
    return t.monotonic() + time() + pc()
'''
R7_LOCAL_NAME_CONFORMING = '''\
def use_local(time, monotonic):
    return time() + monotonic()    # locals, not the time module
'''

# PR 7 scope extensions: datetime is a wall-clock read too (span /
# SLO call sites must stay in the injectable clock's domain), and the
# comms timed-dispatch shim joins R3's axis-literal discipline
R7_DATETIME_VIOLATING = '''\
import datetime
from datetime import datetime as dt


def stamp_span():
    return datetime.datetime.now().timestamp()


def stamp_bare():
    return dt.utcnow()


def day():
    return datetime.date.today()
'''
R7_DATETIME_CONFORMING = '''\
import datetime


def render(ts):
    # transforming an existing timestamp VALUE reads no clock
    return datetime.datetime.fromtimestamp(ts).isoformat()


def span_times(clock):
    t0 = clock.now()
    return t0, clock.now()
'''
R3_TIMED_DISPATCH_VIOLATING = '''\
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import timed_dispatch


def dispatch(thunk):
    spec = P("data")
    return timed_dispatch("knn", thunk, "dataa"), spec
'''
R3_TIMED_DISPATCH_CONFORMING = '''\
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import timed_dispatch


def dispatch(thunk):
    spec = P("data")
    return timed_dispatch("knn", thunk, "data"), spec
'''

# graftwire: the quantized-collective veneers join R3's axis-literal
# discipline at the same positional slots as their exact twins
R3_QUANTIZED_VIOLATING = '''\
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import (
    Op,
    allgather_quantized,
    allreduce_quantized,
    reducescatter_quantized,
)


def reduce_sums(sums, coarse):
    spec = P("data")
    s = allreduce_quantized(sums, Op.SUM, "dataa", wire_dtype="int8")
    m = reducescatter_quantized(sums, Op.SUM, axis="datb")
    g = allgather_quantized(coarse, "datc", "int8")
    return s, m, g, spec
'''
R3_QUANTIZED_CONFORMING = '''\
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import (
    Op,
    allgather_quantized,
    allreduce_quantized,
    reducescatter_quantized,
)


def reduce_sums(sums, coarse):
    spec = P("data")
    s = allreduce_quantized(sums, Op.SUM, "data", wire_dtype="int8")
    m = reducescatter_quantized(sums, Op.SUM, axis="data")
    g = allgather_quantized(coarse, "data", "int8")
    return s, m, g, spec
'''

# graftwire: R1's key discipline extends to mesh_key-spelled builders —
# the 2-D mesh identity tuple feeds every dist plan key
R1_MESH_KEY_VIOLATING = '''\
def _mesh_key(comms):
    mesh = comms.mesh
    return ("mesh", comms.axis, [d.id for d in mesh.devices.flat],
            int(mesh.devices.size))
'''
R1_MESH_KEY_CONFORMING = '''\
def _mesh_key(comms):
    mesh = comms.mesh
    return ("mesh", comms.axis, tuple(mesh.axis_names),
            tuple(mesh.devices.shape),
            tuple(d.id for d in mesh.devices.flat))
'''

R6_OPS_VIOLATING = '''\
from jax.experimental import pallas as pl


def my_kernel_entry(x, *, interpret: bool = False):
    return pl.pallas_call(lambda x_ref, o_ref: None)(x)
'''
R6_TEST_CONFORMING = '''\
def test_kernel():
    from raft_tpu.ops.sample import my_kernel_entry

    my_kernel_entry(None, interpret=True)
'''


class TestFixtureCorpus:
    """Each rule fires on its violating sample and stays quiet on the
    conforming one — delete a rule from the registry and the
    corresponding test fails."""

    def test_r0(self):
        bad = lint_lib(R0_VIOLATING, ["R0"])
        msgs = [f.message for f in bad.findings]
        assert any("unused import" in m for m in msgs), msgs
        assert any("trailing whitespace" in m for m in msgs), msgs
        assert lint_lib(R0_CONFORMING, ["R0"]).ok

    def test_r1_tracer_control_flow(self):
        bad = lint_lib(R1_VIOLATING, ["R1"])
        assert rules_fired(bad) == {"R1"}
        msgs = " ".join(f.message for f in bad.findings)
        assert "`if`" in msgs and "`while`" in msgs, msgs
        assert lint_lib(R1_CONFORMING, ["R1"]).ok

    def test_r1_cache_key(self):
        bad = lint_lib(R1_KEY_VIOLATING, ["R1"])
        msgs = " ".join(f.message for f in bad.findings)
        assert "unhashable" in msgs and "float()" in msgs, msgs
        assert lint_lib(R1_KEY_CONFORMING, ["R1"]).ok

    def test_r2(self):
        bad = lint_lib(R2_VIOLATING, ["R2"])
        assert rules_fired(bad) == {"R2"}
        assert "read after being donated" in bad.findings[0].message
        assert lint_lib(R2_CONFORMING, ["R2"]).ok

    def test_r2_decorator_and_argnames_forms(self):
        bad = lint_lib(R2_DECORATOR_VIOLATING, ["R2"])
        assert rules_fired(bad) == {"R2"}, [
            f.render() for f in bad.findings]
        bad = lint_lib(R2_ARGNAMES_VIOLATING, ["R2"])
        assert rules_fired(bad) == {"R2"}, [
            f.render() for f in bad.findings]

    def test_r2_donate_kwarg_donates_only_the_index(self):
        # second positional is donated; later args stay caller-owned
        assert lint_lib(R2_DONATE_KWARG, ["R2"]).ok
        bad = lint_lib(R2_DONATE_KWARG.replace(
            "return index, rows", "return index, index")
            .replace("index = extend", "out = extend"), ["R2"])
        assert rules_fired(bad) == {"R2"}
        # keyword spelling of the same bug is caught too
        bad = lint_lib(R2_DONATE_KWARG.replace(
            "return index, rows", "return index, index")
            .replace("index = extend(res, index, rows, donate=True)",
                     "out = extend(res, index=index, rows=rows, "
                     "donate=True)"), ["R2"])
        assert rules_fired(bad) == {"R2"}

    def test_r3_raw_collective(self):
        bad = lint_lib(R3_VIOLATING, ["R3"])
        assert rules_fired(bad) == {"R3"}
        assert "jax.lax.psum" in bad.findings[0].message
        assert lint_lib(R3_CONFORMING, ["R3"]).ok

    def test_r3_axis_name(self):
        bad = lint_lib(R3_AXIS_VIOLATING, ["R3"])
        assert rules_fired(bad) == {"R3"}
        assert "'dataa'" in bad.findings[0].message
        assert lint_lib(R3_AXIS_CONFORMING, ["R3"]).ok

    def test_r3_quantized_veneers(self):
        bad = lint_lib(R3_QUANTIZED_VIOLATING, ["R3"])
        assert rules_fired(bad) == {"R3"}
        msgs = " ".join(f.message for f in bad.findings)
        assert "allreduce_quantized" in msgs, msgs
        assert "reducescatter_quantized" in msgs, msgs
        assert "allgather_quantized" in msgs, msgs
        assert lint_lib(R3_QUANTIZED_CONFORMING, ["R3"]).ok

    def test_r1_mesh_key_discipline(self):
        bad = lint_lib(R1_MESH_KEY_VIOLATING, ["R1"])
        msgs = " ".join(f.message for f in bad.findings)
        assert "unhashable" in msgs and "int()" in msgs, msgs
        assert lint_lib(R1_MESH_KEY_CONFORMING, ["R1"]).ok

    def test_r4_missing_params_and_grid(self):
        bad = lint_lib(R4_VIOLATING, ["R4"])
        msgs = " ".join(f.message for f in bad.findings)
        assert "without compiler_params" in msgs, msgs
        assert "not padded up to the divisor" in msgs, msgs
        assert lint_lib(R4_CONFORMING, ["R4"]).ok

    def test_r4_static_vmem_budget(self):
        bad = lint_lib(R4_BUDGET_VIOLATING, ["R4"])
        msgs = " ".join(f.message for f in bad.findings)
        assert "exceeds" in msgs and "MiB" in msgs, msgs

    def test_r4_symbolic_upper_bound(self):
        # a dim that doesn't const-fold (min(n, CAP)) no longer
        # escapes the budget check — the CAP bounds it
        bad = lint_lib(R4_SYMBOLIC_VIOLATING, ["R4"])
        msgs = " ".join(f.message for f in bad.findings)
        assert "upper bound" in msgs and "exceeds" in msgs, msgs
        assert lint_lib(R4_SYMBOLIC_CONFORMING, ["R4"]).ok

    def test_r5(self):
        bad = lint_lib(R5_VIOLATING, ["R5"])
        assert rules_fired(bad) == {"R5"}
        msgs = " ".join(f.message for f in bad.findings)
        assert "float()" in msgs
        assert "np.asarray" in msgs
        assert "device_put inside a python loop" in msgs
        assert lint_lib(R5_CONFORMING, ["R5"]).ok

    def test_r5_covers_serving_modules(self):
        bad = lint_lib(R5_SERVING_VIOLATING, ["R5"],
                       rel="raft_tpu/serving/sample.py")
        assert rules_fired(bad) == {"R5"}
        assert ".item()" in bad.findings[0].message
        # the same source outside the hot set stays quiet
        assert lint_lib(R5_SERVING_VIOLATING, ["R5"],
                        rel="raft_tpu/io/sample.py").ok

    def test_r1_serving_compat_key(self):
        bad = lint_lib(R1_SERVING_KEY_VIOLATING, ["R1"],
                       rel="raft_tpu/serving/sample.py")
        msgs = " ".join(f.message for f in bad.findings)
        assert "unhashable" in msgs and "float()" in msgs, msgs
        assert lint_lib(R1_SERVING_KEY_CONFORMING, ["R1"],
                        rel="raft_tpu/serving/sample.py").ok

    def test_r7_clock_discipline(self):
        bad = lint_lib(R7_SERVING_VIOLATING, ["R7"],
                       rel="raft_tpu/serving/sample.py")
        assert rules_fired(bad) == {"R7"}
        msgs = " ".join(f.message for f in bad.findings)
        assert "time.monotonic" in msgs and "time.time" in msgs, msgs
        assert "injectable clock" in msgs
        assert lint_lib(R7_SERVING_CONFORMING, ["R7"],
                        rel="raft_tpu/serving/sample.py").ok
        # from-imports of clock functions are still clock reads
        bad = lint_lib(R7_BARE_IMPORT_VIOLATING, ["R7"],
                       rel="raft_tpu/serving/sample.py")
        assert rules_fired(bad) == {"R7"}
        # evasion routes: aliased module, `from time import time`,
        # aliased from-import — all three fire
        bad = lint_lib(R7_EVASION_VIOLATING, ["R7"],
                       rel="raft_tpu/serving/sample.py")
        assert len(bad.findings) == 3, [f.render() for f in bad.findings]
        # a local variable that happens to be named `time` stays exempt
        assert lint_lib(R7_LOCAL_NAME_CONFORMING, ["R7"],
                        rel="raft_tpu/serving/sample.py").ok
        # the same sources outside raft_tpu/serving/ stay quiet
        assert lint_lib(R7_SERVING_VIOLATING, ["R7"],
                        rel="raft_tpu/ops/sample.py").ok

    def test_r5_r7_cover_graftgauge_sampler_module(self):
        """PR 8 satellite: the hot scope reaches the new graftgauge
        sampler module by its real path — a host sync or a bare clock
        read landing in ``raft_tpu/serving/gauge.py`` is a finding,
        not a blind spot (the shipped module itself lints clean: its
        fetches are scrape-time by contract and its timestamps come
        from the batcher's injectable clock)."""
        sampler_sync = (
            "def pump(handles):\n"
            "    return [h.depth.item() for h in handles]\n"
        )
        bad = lint_lib(sampler_sync, ["R5"],
                       rel="raft_tpu/serving/gauge.py")
        assert rules_fired(bad) == {"R5"}
        sampler_clock = (
            "import time\n"
            "\n"
            "\n"
            "def shadow_stamp():\n"
            "    return time.monotonic()\n"
        )
        bad = lint_lib(sampler_clock, ["R7"],
                       rel="raft_tpu/serving/gauge.py")
        assert rules_fired(bad) == {"R7"}
        # and the conforming discipline the module actually uses
        ok = (
            "def shadow_stamp(clock):\n"
            "    return clock.now()\n"
        )
        assert lint_lib(ok, ["R5", "R7"],
                        rel="raft_tpu/serving/gauge.py").ok

    def test_r5_r7_cover_graftfleet_modules(self):
        """PR 12 satellite: the hot scope reaches BOTH new graftfleet
        serving modules by their real paths — a host sync landing in
        the continuous scheduler or a bare clock read in the
        federation aggregator is a finding, not a blind spot (the
        shipped modules lint clean: timestamps come from injected
        clocks, the capture's ``time.sleep`` is the documented
        duration exemption, and federation is urllib + dict work)."""
        cont_sync = (
            "def tick(planes):\n"
            "    return [p.total.item() for p in planes]\n"
        )
        bad = lint_lib(cont_sync, ["R5"],
                       rel="raft_tpu/serving/continuous.py")
        assert rules_fired(bad) == {"R5"}
        cont_clock = (
            "import time\n"
            "\n"
            "\n"
            "def next_tick_due():\n"
            "    return time.monotonic()\n"
        )
        bad = lint_lib(cont_clock, ["R7"],
                       rel="raft_tpu/serving/continuous.py")
        assert rules_fired(bad) == {"R7"}
        fed_clock = (
            "import time\n"
            "\n"
            "\n"
            "def replica_age(scraped_at):\n"
            "    return time.time() - scraped_at\n"
        )
        bad = lint_lib(fed_clock, ["R7"],
                       rel="raft_tpu/serving/federation.py")
        assert rules_fired(bad) == {"R7"}
        fed_sync = (
            "def merge_planes(planes):\n"
            "    return sum(p.sum().item() for p in planes)\n"
        )
        bad = lint_lib(fed_sync, ["R5"],
                       rel="raft_tpu/serving/federation.py")
        assert rules_fired(bad) == {"R5"}
        # the conforming discipline both modules actually use:
        # injected-clock stamps, durations slept not read
        ok = (
            "import time\n"
            "\n"
            "\n"
            "def tick(clock, seconds):\n"
            "    t = clock.now()\n"
            "    time.sleep(seconds)\n"
            "    return t\n"
        )
        assert lint_lib(ok, ["R5", "R7"],
                        rel="raft_tpu/serving/continuous.py").ok
        assert lint_lib(ok, ["R5", "R7"],
                        rel="raft_tpu/serving/federation.py").ok

    def test_r5_r7_cover_graftflight_module(self):
        """PR 11 satellite: the hot scope reaches the new graftflight
        flight-recorder module by its real path — a host sync or a
        bare clock read landing in ``raft_tpu/serving/flight.py`` is a
        finding, not a blind spot (the shipped module itself lints
        clean: its timestamps come from the injected clock, its only
        wall-time touch is the capture's exempt ``time.sleep``, and
        the bundle reads registries, never device arrays)."""
        flight_sync = (
            "def check(handles):\n"
            "    return [h.depth.item() for h in handles]\n"
        )
        bad = lint_lib(flight_sync, ["R5"],
                       rel="raft_tpu/serving/flight.py")
        assert rules_fired(bad) == {"R5"}
        flight_clock = (
            "import time\n"
            "\n"
            "\n"
            "def incident_stamp():\n"
            "    return time.monotonic()\n"
        )
        bad = lint_lib(flight_clock, ["R7"],
                       rel="raft_tpu/serving/flight.py")
        assert rules_fired(bad) == {"R7"}
        # the conforming discipline the module actually uses: clock
        # injection for stamps, time.sleep (a duration) for captures
        ok = (
            "import time\n"
            "\n"
            "\n"
            "def capture(clock, seconds):\n"
            "    t = clock.now()\n"
            "    time.sleep(seconds)\n"
            "    return t\n"
        )
        assert lint_lib(ok, ["R5", "R7"],
                        rel="raft_tpu/serving/flight.py").ok
        # core/profiling.py is OFFLINE host-side parsing — outside the
        # hot scopes by design (it must never run on a dispatch path);
        # prove the scope boundary sits where the docs say it does
        assert lint_lib(flight_clock, ["R7"],
                        rel="raft_tpu/core/profiling.py").ok

    def test_r5_r7_cover_graftledger_module(self):
        """PR 13 satellite: the hot scopes reach ``core/memwatch.py``
        by its real path — the watermark sample runs on the executor's
        dispatch path, so a host sync there taxes every search, and a
        bare clock read would split the scrape surface across time
        domains (the shipped module lints clean: it is shape/dtype
        arithmetic plus ``memory_stats()`` backend introspection, and
        keeps no timestamps at all)."""
        ledger_sync = (
            "def sample_dispatch(planes):\n"
            "    return sum(p.sum().item() for p in planes)\n"
        )
        bad = lint_lib(ledger_sync, ["R5"],
                       rel="raft_tpu/core/memwatch.py")
        assert rules_fired(bad) == {"R5"}
        ledger_clock = (
            "import time\n"
            "\n"
            "\n"
            "def sample_stamp():\n"
            "    return time.monotonic()\n"
        )
        bad = lint_lib(ledger_clock, ["R7"],
                       rel="raft_tpu/core/memwatch.py")
        assert rules_fired(bad) == {"R7"}
        # the conforming discipline the module actually uses: pure
        # metadata arithmetic, no clocks, no array fetches
        ok = (
            "def shard_bytes(shape, itemsize):\n"
            "    b = itemsize\n"
            "    for s in shape:\n"
            "        b *= s\n"
            "    return b\n"
        )
        assert lint_lib(ok, ["R5", "R7"],
                        rel="raft_tpu/core/memwatch.py").ok
        # the scope boundary: other core modules stay OUTSIDE both
        # rules (profiling.py's R7 boundary is proven above; prove
        # the R5 side the same way — memwatch is the one core file
        # beyond executor.py on the dispatch path)
        assert lint_lib(ledger_sync, ["R5"],
                        rel="raft_tpu/core/serialize.py").ok
        assert lint_lib(ledger_clock, ["R7"],
                        rel="raft_tpu/core/serialize.py").ok

    def test_r5_r7_cover_graftcast_prefetch_module(self):
        """PR 18 satellite: the hot scopes reach the new graftcast
        prefetcher module by its real path — a bare clock read there
        would re-couple the lead-time pacing to the wall clock (the
        forecast must replay deterministically under the ManualClock
        fault suite), and a device-array fetch would stall the stage
        DMA behind serving's dispatch stream (the shipped module
        lints clean: pacing lives in the TierManager's injected
        clock, slot truth comes from the host-side list mirrors, and
        eviction recency is a logical sequence number)."""
        prefetch_clock = (
            "import time\n"
            "\n"
            "\n"
            "def lead_due(last_epoch_at, lead_s):\n"
            "    return time.monotonic() - last_epoch_at >= lead_s\n"
        )
        bad = lint_lib(prefetch_clock, ["R7"],
                       rel="raft_tpu/serving/prefetch.py")
        assert rules_fired(bad) == {"R7"}
        prefetch_sync = (
            "def staged_rows(planes):\n"
            "    return [p.sum().item() for p in planes]\n"
        )
        bad = lint_lib(prefetch_sync, ["R5"],
                       rel="raft_tpu/serving/prefetch.py")
        assert rules_fired(bad) == {"R5"}
        # the conforming discipline the module actually uses: logical
        # recency, injected pacing, host-side slot mirrors
        ok = (
            "def evict_candidate(row_age, active):\n"
            "    best = None\n"
            "    for row in active:\n"
            "        if best is None or row_age[row] < row_age[best]:\n"
            "            best = row\n"
            "    return best\n"
        )
        assert lint_lib(ok, ["R5", "R7"],
                        rel="raft_tpu/serving/prefetch.py").ok

    def test_r5_covers_tier_scan_cold_engines(self):
        """PR 18 satellite: the R5 hot scope reaches the tiered cold
        engines by their real path — the list-major cold scan runs
        per dispatch, so one stray ``.item()`` (say, reading a cold
        slot id off the device map instead of the host mirror) taxes
        every tiered search exactly like an executor-side sync."""
        cold_sync = (
            "def cold_slot_of(cold_slot_map, lid):\n"
            "    return cold_slot_map[lid].item()\n"
        )
        bad = lint_lib(cold_sync, ["R5"],
                       rel="raft_tpu/ops/tier_scan.py")
        assert rules_fired(bad) == {"R5"}
        # the conforming discipline the engines actually use: slot
        # arithmetic on host mirrors, device work stays traced
        ok = (
            "def cold_slot_of(cold_lists, lid):\n"
            "    for slot, cl in enumerate(cold_lists):\n"
            "        if cl == lid:\n"
            "            return slot\n"
            "    return -1\n"
        )
        assert lint_lib(ok, ["R5"],
                        rel="raft_tpu/ops/tier_scan.py").ok

    def test_r7_datetime_clock_reads(self):
        """PR 7: datetime.now()/utcnow()/date.today() are wall-clock
        reads — module-dotted and from-import spellings both fire;
        fromtimestamp (a value transform) stays exempt."""
        bad = lint_lib(R7_DATETIME_VIOLATING, ["R7"],
                       rel="raft_tpu/serving/sample.py")
        assert rules_fired(bad) == {"R7"}
        assert len(bad.findings) == 3, [f.render() for f in bad.findings]
        assert lint_lib(R7_DATETIME_CONFORMING, ["R7"],
                        rel="raft_tpu/serving/sample.py").ok
        # outside the serving scope: quiet, like the time-module rule
        assert lint_lib(R7_DATETIME_VIOLATING, ["R7"],
                        rel="raft_tpu/ops/sample.py").ok

    def test_r3_timed_dispatch_axis_literal(self):
        """PR 7: the comms timed-dispatch shim is on R3's veneer
        allowlist — a typo'd axis literal at its call site is the same
        latent multi-chip bug as one inside a collective."""
        bad = lint_lib(R3_TIMED_DISPATCH_VIOLATING, ["R3"])
        assert rules_fired(bad) == {"R3"}
        assert "'dataa'" in bad.findings[0].message
        assert lint_lib(R3_TIMED_DISPATCH_CONFORMING, ["R3"]).ok

    def test_r6(self):
        bad = lint_texts({"raft_tpu/ops/sample.py": R6_OPS_VIOLATING},
                         rules=["R6"])
        assert rules_fired(bad) == {"R6"}
        assert "no interpret=True call" in bad.findings[0].message
        ok = lint_texts({"raft_tpu/ops/sample.py": R6_OPS_VIOLATING,
                         "tests/test_sample.py": R6_TEST_CONFORMING},
                        rules=["R6"])
        assert ok.ok


class TestDataflow:
    """The traced-name machinery R1/R5 stand on."""

    @staticmethod
    def _traced(src):
        import ast

        from raft_tpu.analysis import astutil

        fn = ast.parse(src).body[0]
        return astutil.traced_names(fn)

    def test_seed_convention(self):
        traced = self._traced(
            "def _f(queries, data, init_d=None, *, k: int, metric): pass")
        assert traced == {"queries", "data", "init_d"}

    def test_annotated_positionals_are_static(self):
        # annotated params, 'res', and 'self' are never tracers
        traced = self._traced(
            "def _f(mode: str, queries, res, self=None): pass")
        assert traced == {"queries"}

    def test_metadata_launders(self):
        traced = self._traced(
            "def _f(q):\n"
            "    n = q.shape[0]\n"
            "    d = len(q)\n"
            "    v = q + 1\n"
            "    pass\n")
        assert "n" not in traced and "d" not in traced
        assert "v" in traced and "q" in traced

    def test_rebind_to_static_clears(self):
        traced = self._traced(
            "def _f(q):\n"
            "    x = q * 2\n"
            "    x = 3\n"
            "    pass\n")
        assert "x" not in traced

    def test_value_names_identity_checks_exempt(self):
        import ast

        from raft_tpu.analysis import astutil

        expr = ast.parse("x is None or y.ndim == 2", mode="eval").body
        assert astutil.value_names(expr) == set()
        expr = ast.parse("x > 0", mode="eval").body
        assert astutil.value_names(expr) == {"x"}

    def test_jit_decorator_statics(self):
        import ast

        from raft_tpu.analysis import astutil

        fn = ast.parse(
            "@partial(jax.jit, static_argnames=('k',))\n"
            "def _f(q, k): pass").body[0]
        statics = astutil.jit_static_names(fn)
        assert statics == {"k"}
        assert astutil.traced_names(fn, statics) == {"q"}


class TestSuppressions:
    def test_pragma_silences_with_reason(self):
        src = R3_VIOLATING.replace(
            "return jax.lax.psum(x, axis)",
            "return jax.lax.psum(x, axis)"
            "  # graftlint: disable=R3(fixture: exercising suppression)")
        rep = lint_lib(src, ["R3"])
        assert rep.ok
        assert len(rep.suppressed) == 1
        assert rep.suppressed[0][1] == "fixture: exercising suppression"

    def test_pragma_without_reason_is_a_finding(self):
        src = R3_VIOLATING.replace(
            "return jax.lax.psum(x, axis)",
            "return jax.lax.psum(x, axis)  # graftlint: disable=R3")
        rep = lint_lib(src, ["R0", "R3"])
        assert any("carries no reason" in f.message for f in rep.findings)

    def test_unused_pragma_is_a_finding(self):
        src = R3_CONFORMING.replace(
            "return allreduce(x, axis=axis)",
            "return allreduce(x, axis=axis)"
            "  # graftlint: disable=R3(stale)")
        rep = lint_lib(src, ["R0", "R3"])
        assert any("unused suppression" in f.message for f in rep.findings)

    def test_pragma_in_docstring_is_not_a_pragma(self):
        src = ('def f():\n'
               '    """Example: # graftlint: disable=R3(quoted)."""\n'
               '    return 0\n')
        rep = lint_lib(src, ["R0"])
        assert rep.ok and not rep.suppressions

    def test_trailing_pragma_on_continuation_line(self):
        """A pragma trailing the *second* physical line of a multi-line
        statement must still suppress the finding (which anchors to the
        statement's first line)."""
        src = (
            "import jax\n"
            "\n"
            "\n"
            "def merge(x, axis):\n"
            "    return jax.lax.psum(\n"
            "        x, axis)"
            "  # graftlint: disable=R3(fixture: continuation line)\n")
        rep = lint_lib(src, ["R0", "R3"])
        assert rep.ok, [f.render() for f in rep.findings]
        assert len(rep.suppressed) == 1

    def test_unknown_rule_id_is_a_finding(self):
        src = ("x = 1"
               "  # graftlint: disable=R77(typo for a real rule)\n")
        rep = lint_lib(src, ["R0"])
        assert any("unknown rule 'R77'" in f.message
                   for f in rep.findings), [
            f.render() for f in rep.findings]

    def test_rule_filtered_run_has_no_pragma_hygiene_leak(self):
        """ops-guard style runs (rules=[R6]) must not surface R0
        pragma-hygiene findings from unrelated files."""
        src = "x = 1  # graftlint: disable=R77\n"
        rep = lint_lib(src, ["R6"])
        assert rep.ok
        rep = lint_lib(src, ["R0"])
        assert any("carries no reason" in f.message for f in rep.findings)

    def test_parser_handles_parens_and_lists(self):
        items, bad = parse_pragma_items(
            "R1(keys are O(1) hashable), R5(bounded to O(block))")
        assert not bad
        assert items == [("R1", "keys are O(1) hashable"),
                         ("R5", "bounded to O(block)")]


class TestRepoWide:
    """The CI gate, in-process: the live tree must lint clean, and the
    suppression inventory is snapshot — adding a pragma anywhere means
    updating this list in the same diff."""

    # (path, rule, reason) for every pragma in the tree — KEEP SORTED
    EXPECTED_SUPPRESSIONS = [
        # PR 9: the ragged split fetches once per packed tile instead
        # of dispatching per-(offset, rows, k) device slices whose
        # micro-programs would recompile per load shape
        ("raft_tpu/core/executor.py", "R5",
         "ragged split is host-side by design: one batched fetch per "
         "packed tile replaces per-shape device-slice micro-programs; "
         "the serving caller blocks on results immediately"),
        # second site, same design: the stateless-engine fetch happens
        # OUTSIDE the executor lock (nothing aliases those outputs)
        ("raft_tpu/core/executor.py", "R5",
         "ragged split is host-side by design: one batched fetch per "
         "packed tile replaces per-shape device-slice micro-programs; "
         "the serving caller blocks on results immediately"),
        ("raft_tpu/distributed/ivf.py", "R5",
         "streaming deal: per-block puts bound build staging to "
         "O(block)"),
        ("raft_tpu/serving/harness.py", "R5",
         "device-free test shim: inputs are host arrays by contract"),
        # PR 9: FakeExecutor grew the ragged dispatch entry — same
        # device-free shim, second suppression with the same reason
        ("raft_tpu/serving/harness.py", "R5",
         "device-free test shim: inputs are host arrays by contract"),
        # PR 19: R8 guarded-by seeding — two benign races kept by
        # design, each with the reason the race is safe
        ("raft_tpu/core/tracing.py", "R8",
         "deque reference never rebinds; maxlen is immutable"),
        ("raft_tpu/serving/batcher.py", "R8",
         "benign racy fast-fail; the authoritative check re-runs "
         "under _cond before enqueue"),
    ]

    @pytest.fixture(scope="class")
    def report(self):
        return lint_root(ROOT)

    def test_registry_is_complete(self):
        assert sorted(RULES) == ["R0", "R1", "R2", "R3", "R4", "R5",
                                 "R6", "R7", "R8", "R9"]

    def test_repo_lints_clean(self, report):
        assert report.ok, "\n" + "\n".join(
            f.render() for f in report.findings)

    def test_suppression_inventory_snapshot(self, report):
        got = sorted((s.path, s.rule, s.reason)
                     for s in report.suppressions)
        assert got == sorted(self.EXPECTED_SUPPRESSIONS), (
            "suppression inventory changed — review the new/removed "
            f"pragmas and update the snapshot:\n{got}")

    def test_every_suppression_is_used(self, report):
        stale = [s for s in report.suppressions if not s.used]
        assert not stale, stale

    def test_suppression_inventory_json_shape(self, report):
        """``--list-suppressions --format=json`` and the
        ``ci/graftlint_report.json`` artifact expose the same
        ``[path, rule, reason]`` rows this snapshot pins."""
        rows = report.suppression_inventory()
        assert rows == sorted(list(t)
                              for t in self.EXPECTED_SUPPRESSIONS)
        assert report.to_dict()["suppression_inventory"] == rows


# PR 9 scope proofs: the ragged plan/kernel code paths are inside
# R1/R4/R5's reach — a hazard landing in the new code is a finding,
# not a blind spot (the shipped modules themselves lint clean).

R1_RAGGED_FN_VIOLATING = '''\
def _search_ragged_fn(queries, row_probes, centers, *, n_probes: int,
                      k: int):
    probes = queries + centers
    if row_probes > 0:
        probes = probes + 1
    return probes
'''
R1_RAGGED_KEY_VIOLATING = '''\
def _plan_ragged(statics, specs):
    ragged_key = ("ivf_flat_ragged", [s for s in specs],
                  float(statics))
    return ragged_key
'''
R1_RAGGED_KEY_CONFORMING = '''\
def _plan_ragged(statics, specs):
    ragged_key = ("ivf_flat_ragged", tuple(sorted(specs)),
                  len(statics))
    return ragged_key
'''
R4_RAGGED_KERNEL_VIOLATING = '''\
import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _ragged_scan_kernel(u_ref, q_ref, o_ref):
    o_ref[:] = q_ref[:]


def scan_ragged(uniq, q, interpret=False):
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, 128), lambda i, u: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i, u: (i, 0)),
    )
    return pl.pallas_call(
        _ragged_scan_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((32, 128), q.dtype),
        interpret=interpret,
    )(uniq, q)
'''
R5_RAGGED_PACKING_VIOLATING = '''\
def search_ragged(self, index, blocks, ks):
    sizes = [int(b.sum().item()) for b in blocks]
    return sizes
'''


class TestRaggedScopeProofs:
    """PR 9 satellite: R1/R4/R5 fire on ragged-plan/kernel-shaped
    hazards at the real module paths the ragged path lives in."""

    def test_r1_traced_branch_in_ragged_body(self):
        bad = lint_lib(R1_RAGGED_FN_VIOLATING, ["R1"],
                       rel="raft_tpu/neighbors/ivf_flat.py")
        assert rules_fired(bad) == {"R1"}
        assert "row_probes" in " ".join(
            f.message for f in bad.findings)

    def test_r1_ragged_packing_key_discipline(self):
        bad = lint_lib(R1_RAGGED_KEY_VIOLATING, ["R1"],
                       rel="raft_tpu/core/executor.py")
        msgs = " ".join(f.message for f in bad.findings)
        assert "unhashable" in msgs and "float()" in msgs, msgs
        assert lint_lib(R1_RAGGED_KEY_CONFORMING, ["R1"],
                        rel="raft_tpu/core/executor.py").ok

    def test_r4_ragged_kernel_needs_budget(self):
        bad = lint_lib(R4_RAGGED_KERNEL_VIOLATING, ["R4"],
                       rel="raft_tpu/ops/ivf_scan.py")
        assert "R4" in rules_fired(bad)
        assert any("vmem" in f.message.lower()
                   for f in bad.findings), [
            f.render() for f in bad.findings]

    def test_r5_host_sync_in_ragged_packing(self):
        bad = lint_lib(R5_RAGGED_PACKING_VIOLATING, ["R5"],
                       rel="raft_tpu/core/executor.py")
        assert rules_fired(bad) == {"R5"}
        assert ".item()" in bad.findings[0].message
        # the same source outside the hot set stays quiet
        assert lint_lib(R5_RAGGED_PACKING_VIOLATING, ["R5"],
                        rel="raft_tpu/label/sample.py").ok


# PR 10 scope proof: the fused BQ kernel (conditional-DMA pallas_call
# with an ANY-space operand — ops/bq_scan.py) is inside R4's reach: an
# undeclared VMEM budget on a bq_scan-shaped kernel is a finding, not
# a blind spot (the shipped module itself lints clean, suppression
# snapshot unchanged).

R4_BQ_KERNEL_VIOLATING = '''\
import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _bq_kernel(u_ref, q_ref, data_ref, o_ref, vec, sem):
    o_ref[:] = q_ref[:]


def bq_scan(uniq, q, data, interpret=False):
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(4,),
        in_specs=[
            pl.BlockSpec((8, 128), lambda i, u: (i, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((8, 128), lambda i, u: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, 512, 128), jax.numpy.float32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    return pl.pallas_call(
        _bq_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((32, 128), q.dtype),
        interpret=interpret,
    )(uniq, q, data)
'''


class TestBqScanScopeProof:
    def test_r4_bq_kernel_needs_budget(self):
        bad = lint_lib(R4_BQ_KERNEL_VIOLATING, ["R4"],
                       rel="raft_tpu/ops/bq_scan.py")
        assert "R4" in rules_fired(bad)
        assert any("vmem" in f.message.lower()
                   for f in bad.findings), [
            f.render() for f in bad.findings]


class TestGrafttierScopeProofs:
    """PR 14 satellite: the lint scopes reach BOTH new grafttier
    modules by their real paths — a budget-less pallas_call in the
    tiered scan, a host sync in either module, or a bare clock read
    in the placement policy is a finding, not a blind spot (the
    shipped modules lint clean: the kernel declares its VMEM budget
    from the shared footprint model, the scan is pure device work,
    and the manager's epochs fire from an injected clock)."""

    def test_r4_covers_tier_scan(self):
        bad = lint_lib(R4_VIOLATING, ["R4"],
                       rel="raft_tpu/ops/tier_scan.py")
        msgs = " ".join(f.message for f in bad.findings)
        assert "without compiler_params" in msgs, msgs
        assert lint_lib(R4_CONFORMING, ["R4"],
                        rel="raft_tpu/ops/tier_scan.py").ok

    def test_r5_covers_tier_scan_and_placement(self):
        tier_sync = (
            "def search_tiered(handles):\n"
            "    return [h.best.item() for h in handles]\n"
        )
        bad = lint_lib(tier_sync, ["R5"],
                       rel="raft_tpu/ops/tier_scan.py")
        assert rules_fired(bad) == {"R5"}
        bad = lint_lib(tier_sync, ["R5"],
                       rel="raft_tpu/serving/placement.py")
        assert rules_fired(bad) == {"R5"}
        # device_put in a python loop — the per-swap antipattern the
        # fixed-width batched swap exists to avoid
        swap_loop = (
            "import jax\n"
            "\n"
            "\n"
            "def search_swap(blocks, devs):\n"
            "    out = []\n"
            "    for b in blocks:\n"
            "        out.append(jax.device_put(b, devs[0]))\n"
            "    return out\n"
        )
        bad = lint_lib(swap_loop, ["R5"],
                       rel="raft_tpu/serving/placement.py")
        assert rules_fired(bad) == {"R5"}

    def test_r5_covers_fleet(self):
        """PR 20: graftroute modules are serving-hot — a host fetch
        inside a fleet search path (or a traced body) must fire R5
        exactly as it would in raft_tpu/serving/."""
        fleet_sync = (
            "import numpy as np\n"
            "\n"
            "\n"
            "def search_fanout(handles):\n"
            "    return [np.asarray(h.result()) for h in handles]\n"
        )
        bad = lint_lib(fleet_sync, ["R5"],
                       rel="raft_tpu/fleet/router.py")
        assert rules_fired(bad) == {"R5"}
        # the router's actual discipline: no search*-named host
        # functions, merges stay in jnp
        ok = (
            "import jax.numpy as jnp\n"
            "\n"
            "\n"
            "def merge_legs(parts, k):\n"
            "    return jnp.concatenate(parts, axis=1)[:, :k]\n"
        )
        assert lint_lib(ok, ["R5"],
                        rel="raft_tpu/fleet/router.py").ok

    def test_r7_covers_fleet(self):
        """PR 20: the router measures table age — only against the
        injected clock, same discipline as the serving frontend."""
        table_age = (
            "import time\n"
            "\n"
            "\n"
            "def table_age(applied_at):\n"
            "    return time.monotonic() - applied_at\n"
        )
        bad = lint_lib(table_age, ["R7"],
                       rel="raft_tpu/fleet/router.py")
        assert rules_fired(bad) == {"R7"}
        ok = (
            "def table_age(clock, applied_at):\n"
            "    return clock.now() - applied_at\n"
        )
        assert lint_lib(ok, ["R5", "R7"],
                        rel="raft_tpu/fleet/router.py").ok

    def test_r7_covers_placement(self):
        epoch_clock = (
            "import time\n"
            "\n"
            "\n"
            "def epoch_due(last):\n"
            "    return time.monotonic() - last > 60.0\n"
        )
        bad = lint_lib(epoch_clock, ["R7"],
                       rel="raft_tpu/serving/placement.py")
        assert rules_fired(bad) == {"R7"}
        # the conforming discipline the module actually uses
        ok = (
            "def epoch_due(clock, last):\n"
            "    return clock.now() - last > 60.0\n"
        )
        assert lint_lib(ok, ["R5", "R7"],
                        rel="raft_tpu/serving/placement.py").ok


# graftragged scope proof: the MESH ragged plan keys fold mesh devices
# and params-class tuples into RETURN position of ragged_key — R1's
# key discipline covers that construction (the shipped executor's
# ragged_key/coalesce_key lint clean, suppression snapshot unchanged).

R1_MESH_RAGGED_KEY_VIOLATING = '''\
def ragged_key(self, index, k, params=None, **kw):
    return ("dist_ivf_flat_ragged",
            [d.id for d in index.mesh_devices],
            float(index.probe_budget),
            {"wire": kw.get("wire_dtype")})
'''
R1_MESH_RAGGED_KEY_CONFORMING = '''\
def ragged_key(self, index, k, params=None, **kw):
    return ("dist_ivf_flat_ragged", index.mesh_key,
            tuple(sorted((n, str(v)) for n, v in kw.items())),
            k)
'''


class TestMeshRaggedKeyProofs:
    """graftragged satellite: R1 key discipline reaches the mesh
    ragged plan keys — device-id lists, runtime-data scalars, and
    bare dict displays in a key-returning function's RETURN are
    findings; the tuple-wrapped mesh-device + params-class + wire-kw
    construction conforms."""

    def test_mesh_ragged_key_violating(self):
        bad = lint_lib(R1_MESH_RAGGED_KEY_VIOLATING, ["R1"],
                       rel="raft_tpu/core/executor.py")
        assert rules_fired(bad) == {"R1"}
        msgs = " ".join(f.message for f in bad.findings)
        assert "unhashable list" in msgs
        assert "float() of runtime data" in msgs
        assert "unhashable dict" in msgs

    def test_mesh_ragged_key_conforming(self):
        assert lint_lib(R1_MESH_RAGGED_KEY_CONFORMING, ["R1"],
                        rel="raft_tpu/core/executor.py").ok


# ---------------------------------------------------------------------------
# PR 19: graftlint v3 — R8 lock discipline, R2v2 interprocedural
# donation escape, R9 metric-inventory conformance, the program graph
# they stand on, and the incremental cache
# ---------------------------------------------------------------------------

R8_VIOLATING = '''\
import threading


class Depot:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # guarded-by: _lock

    def bump(self):
        with self._lock:
            self._n += 1

    def peek(self):
        return self._n
'''
R8_CONFORMING = '''\
import threading


class Depot:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # guarded-by: _lock

    def bump(self):
        with self._lock:
            self._n += 1

    def peek(self):
        with self._lock:
            return self._n
'''
R8_HELPER_CONFORMING = '''\
import threading


class Depot:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # guarded-by: _lock

    def bump(self):
        with self._lock:
            self._bump_locked()

    def _bump_locked(self):
        self._n += 1
'''
R8_HELPER_ESCAPE_VIOLATING = R8_HELPER_CONFORMING + '''\

    def leak(self):
        self._bump_locked()
'''
R8_CALLBACK_VIOLATING = '''\
import threading


class Poller:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # guarded-by: _lock

    def arm(self, loop):
        loop.call(self._on_tick)

    def _on_tick(self):
        self._n += 1
'''
R8_UNKNOWN_LOCK = '''\
import threading


class Depot:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # guarded-by: _missing
'''
R8_GLOBAL_VIOLATING = '''\
import threading

_lock = threading.Lock()
_total = 0  # guarded-by: _lock


def bump(n):
    global _total
    with _lock:
        _total += n


def peek():
    return _total
'''
R8_CYCLE_VIOLATING = '''\
import threading

_a = threading.Lock()
_b = threading.Lock()


def left():
    with _a:
        with _b:
            pass


def right():
    with _b:
        with _a:
            pass
'''
R8_CYCLE_CONFORMING = '''\
import threading

_a = threading.Lock()
_b = threading.Lock()


def left():
    with _a:
        with _b:
            pass


def right():
    with _a:
        with _b:
            pass
'''
R8_SELF_DEADLOCK_VIOLATING = '''\
import threading

_m = threading.Lock()


def outer():
    with _m:
        inner()


def inner():
    with _m:
        pass
'''
R8_SELF_DEADLOCK_CONFORMING = \
    R8_SELF_DEADLOCK_VIOLATING.replace("threading.Lock()",
                                       "threading.RLock()")


class TestLockDiscipline:
    """R8 fixture corpus: guarded-by accesses checked lexically and
    through private-helper call sites, annotation hygiene, and the
    static lock graph's cycle / self-deadlock findings."""

    def test_unguarded_read_fires(self):
        bad = lint_lib(R8_VIOLATING, ["R8"])
        assert rules_fired(bad) == {"R8"}
        msg = bad.findings[0].message
        assert "read of 'self._n'" in msg and "Depot.peek" in msg, msg
        assert lint_lib(R8_CONFORMING, ["R8"]).ok

    def test_private_helper_inherits_callers_lock(self):
        assert lint_lib(R8_HELPER_CONFORMING, ["R8"]).ok
        # one unlocked call site and the helper's guarantee is gone
        bad = lint_lib(R8_HELPER_ESCAPE_VIOLATING, ["R8"])
        assert rules_fired(bad) == {"R8"}
        assert "_bump_locked" in bad.findings[0].message

    def test_callback_reference_never_inherits(self):
        bad = lint_lib(R8_CALLBACK_VIOLATING, ["R8"])
        assert rules_fired(bad) == {"R8"}
        assert "_on_tick" in bad.findings[0].message

    def test_annotation_must_name_a_real_lock(self):
        bad = lint_lib(R8_UNKNOWN_LOCK, ["R8"])
        assert rules_fired(bad) == {"R8"}
        assert "no lock of that name exists" in bad.findings[0].message

    def test_module_globals_are_covered(self):
        bad = lint_lib(R8_GLOBAL_VIOLATING, ["R8"])
        assert rules_fired(bad) == {"R8"}
        assert "read of '_total'" in bad.findings[0].message

    def test_lock_order_cycle(self):
        bad = lint_lib(R8_CYCLE_VIOLATING, ["R8"])
        assert rules_fired(bad) == {"R8"}
        msgs = " ".join(f.message for f in bad.findings)
        assert "lock-order cycle" in msgs, msgs
        assert "_a" in msgs and "_b" in msgs
        assert lint_lib(R8_CYCLE_CONFORMING, ["R8"]).ok

    def test_interprocedural_self_deadlock(self):
        bad = lint_lib(R8_SELF_DEADLOCK_VIOLATING, ["R8"])
        assert rules_fired(bad) == {"R8"}
        assert "self-deadlock" in bad.findings[0].message
        assert lint_lib(R8_SELF_DEADLOCK_CONFORMING, ["R8"]).ok

    def test_lockgraph_artifact_shape(self):
        from raft_tpu.analysis.core import Project
        from raft_tpu.analysis.rules_locks import build_lock_graph

        project = Project.from_texts(
            {"raft_tpu/ops/sample.py": R8_CYCLE_VIOLATING})
        d = build_lock_graph(project).to_dict()
        assert sorted(d) == ["cycles", "edges", "locks",
                             "self_deadlocks"]
        assert len(d["locks"]) == 2
        assert d["cycles"], d
        assert not d["self_deadlocks"]


R2_INTERPROC_VIOLATING = '''\
import jax


def _step_fn(state):
    return state


def _advance(state):
    step = jax.jit(_step_fn, donate_argnums=(0,))
    return step(state)


def serve(state):
    out = _advance(state)
    return out + state
'''
R2_INTERPROC_CONFORMING = '''\
import jax


def _step_fn(state):
    return state


def _advance(state):
    step = jax.jit(_step_fn, donate_argnums=(0,))
    return step(state)


def serve(state):
    state = _advance(state)
    return state
'''
R2_FIELD_ESCAPE_VIOLATING = '''\
import jax


def _step_fn(plane):
    return plane


def _consume(entry):
    step = jax.jit(_step_fn, donate_argnums=(0,))
    return step(entry.plane)


def refresh(entry):
    out = _consume(entry)
    return out + entry.plane
'''
R2_METHOD_ESCAPE_VIOLATING = '''\
import jax


def _step_fn(state):
    return state


class Entry:
    def claim(self):
        step = jax.jit(_step_fn, donate_argnums=(0,))
        return step(self.state)


def roll():
    entry = Entry()
    out = entry.claim()
    return out + entry.state
'''


class TestDonationEscape:
    """R2v2 fixture corpus: donation summaries flow across function
    boundaries — a helper that donates its argument taints every
    caller, fields included, while result-threading stays blessed."""

    def test_escape_through_helper(self):
        bad = lint_lib(R2_INTERPROC_VIOLATING, ["R2"])
        assert rules_fired(bad) == {"R2"}
        msg = bad.findings[0].message
        assert "donation escaping through '_advance'" in msg, msg
        assert lint_lib(R2_INTERPROC_CONFORMING, ["R2"]).ok

    def test_field_path_escape(self):
        bad = lint_lib(R2_FIELD_ESCAPE_VIOLATING, ["R2"])
        assert rules_fired(bad) == {"R2"}
        assert "'entry.plane'" in bad.findings[0].message
        # an un-donated sibling field stays readable
        ok = R2_FIELD_ESCAPE_VIOLATING.replace(
            "return out + entry.plane", "return out + entry.meta")
        assert lint_lib(ok, ["R2"]).ok

    def test_method_receiver_escape(self):
        bad = lint_lib(R2_METHOD_ESCAPE_VIOLATING, ["R2"])
        assert rules_fired(bad) == {"R2"}
        assert "'entry.state'" in bad.findings[0].message


R9_LIB = '''\
from raft_tpu.core import tracing


def record(n, split):
    tracing.inc_counter("serving.sample.calls", n)
    tracing.inc_counter(f"serving.sample.{split}.rows", n)
    tracing.set_gauge("serving.sample.depth", n)
'''
R9_ARCH_OK = (
    "## Metric inventory\n"
    "\n"
    "| name | type | meaning |\n"
    "| --- | --- | --- |\n"
    "| `serving.sample.calls` | counter | total calls |\n"
    "| `serving.sample.<split>.rows` | counter | rows per split |\n"
    "| `serving.sample.depth` | gauge | queue depth |\n"
)
R9_ARCH_MISSING_GAUGE = R9_ARCH_OK.replace(
    "| `serving.sample.depth` | gauge | queue depth |\n", "")
R9_FLOORS_OK = (
    "SNAPSHOT_FLOORS = {\n"
    '    "serving.sample.calls": 10,\n'
    "}\n"
)
R9_FLOORS_DEAD = (
    "SNAPSHOT_FLOORS = {\n"
    '    "serving.sample.calls": 10,\n'
    '    "serving.sample.ghost": 1,\n'
    "}\n"
)
R9_EXPORTER_OK = (
    "_HELP_PREFIXES = (\n"
    '    ("serving.sample", "sample family"),\n'
    ")\n"
)
R9_EXPORTER_DEAD = (
    "_HELP_PREFIXES = (\n"
    '    ("serving.sample", "sample family"),\n'
    '    ("serving.ghostly", "nothing registers this"),\n'
    ")\n"
)


class TestMetricInventory:
    """R9 fixture corpus: the registered-pattern inventory against the
    ARCHITECTURE.md tables, SNAPSHOT_FLOORS, and _HELP_PREFIXES — each
    drift direction is one finding, and the rule is quiet when a
    fixture project supplies no aux evidence."""

    def test_documented_inventory_conforms(self):
        rep = lint_texts({"raft_tpu/serving/sample.py": R9_LIB},
                         rules=["R9"],
                         aux={"ARCHITECTURE.md": R9_ARCH_OK})
        assert rep.ok, [f.render() for f in rep.findings]

    def test_undocumented_gauge_fires(self):
        rep = lint_texts({"raft_tpu/serving/sample.py": R9_LIB},
                         rules=["R9"],
                         aux={"ARCHITECTURE.md": R9_ARCH_MISSING_GAUGE})
        assert rules_fired(rep) == {"R9"}
        msg = rep.findings[0].message
        assert "gauge 'serving.sample.depth'" in msg, msg
        assert "ARCHITECTURE.md" in msg

    def test_dead_floor_fires(self):
        rep = lint_texts({"raft_tpu/serving/sample.py": R9_LIB},
                         rules=["R9"],
                         aux={"ARCHITECTURE.md": R9_ARCH_OK,
                              "ci/bench_compare.py": R9_FLOORS_DEAD})
        assert rules_fired(rep) == {"R9"}
        msg = rep.findings[0].message
        assert "serving.sample.ghost" in msg and "floor" in msg, msg
        assert rep.findings[0].path == "ci/bench_compare.py"
        rep = lint_texts({"raft_tpu/serving/sample.py": R9_LIB},
                         rules=["R9"],
                         aux={"ARCHITECTURE.md": R9_ARCH_OK,
                              "ci/bench_compare.py": R9_FLOORS_OK})
        assert rep.ok

    def test_dead_help_prefix_fires(self):
        texts = {"raft_tpu/serving/sample.py": R9_LIB,
                 "raft_tpu/serving/exporter.py": R9_EXPORTER_DEAD}
        rep = lint_texts(texts, rules=["R9"],
                         aux={"ARCHITECTURE.md": R9_ARCH_OK})
        assert rules_fired(rep) == {"R9"}
        assert "serving.ghostly" in rep.findings[0].message
        texts["raft_tpu/serving/exporter.py"] = R9_EXPORTER_OK
        assert lint_texts(texts, rules=["R9"],
                          aux={"ARCHITECTURE.md": R9_ARCH_OK}).ok

    def test_quiet_without_aux(self):
        assert lint_texts({"raft_tpu/serving/sample.py": R9_LIB},
                          rules=["R9"]).ok


class TestProgGraph:
    """The cross-module program graph R8/R9/R2v2 stand on."""

    def test_guarded_fields_and_lock_kinds(self):
        from raft_tpu.analysis import proggraph
        from raft_tpu.analysis.core import Project

        src = (
            "import threading\n"
            "import dataclasses\n"
            "from dataclasses import field\n"
            "\n"
            "\n"
            "@dataclasses.dataclass\n"
            "class Plane:\n"
            "    rows: int = 0  # guarded-by: _swap_lock\n"
            "    _swap_lock: object = field(\n"
            "        default_factory=threading.Lock)\n"
            "\n"
            "\n"
            "class Depot:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.RLock()\n"
            "        self._n = 0  # guarded-by: _lock\n"
        )
        project = Project.from_texts({"raft_tpu/core/sample.py": src})
        graph = proggraph.get_graph(project)
        mod = graph.modules["raft_tpu/core/sample.py"]
        plane = mod.classes["Plane"]
        assert plane.fields["rows"].guarded_by == "_swap_lock"
        assert plane.fields["_swap_lock"].is_lock
        depot = mod.classes["Depot"]
        assert depot.fields["_n"].guarded_by == "_lock"
        assert depot.fields["_lock"].is_lock

    def test_cross_module_call_resolution(self):
        from raft_tpu.analysis import proggraph
        from raft_tpu.analysis.core import Project

        project = Project.from_texts({
            "raft_tpu/core/util.py": (
                "def helper(x):\n"
                "    return x\n"),
            "raft_tpu/core/main.py": (
                "from raft_tpu.core.util import helper\n"
                "\n"
                "\n"
                "def caller(x):\n"
                "    return helper(x)\n")})
        graph = proggraph.get_graph(project)
        fn = graph.modules["raft_tpu/core/main.py"].functions["caller"]
        callees = [c.name for c, _call in graph.callees(fn)]
        assert callees == ["helper"]


class TestLintCache:
    """The incremental content-hash cache: per-file keys for
    file-scope rules, one project digest for program-scope rules, and
    version-stamped invalidation."""

    TEXTS = {"raft_tpu/ops/a.py": "x = 1\n",
             "raft_tpu/ops/b.py": "y = 2\n"}

    def _run(self, path, texts, rules, version="v1"):
        from raft_tpu.analysis import LintCache
        from raft_tpu.analysis.core import Project, run

        cache = LintCache(path, version)
        rep = run(Project.from_texts(texts), rules=rules, cache=cache)
        cache.save()
        return rep

    def test_second_run_is_all_hits(self, tmp_path):
        path = tmp_path / "cache.json"
        r1 = self._run(path, self.TEXTS, ["R0"])
        assert r1.cache_misses == 2 and r1.cache_hits == 0
        r2 = self._run(path, self.TEXTS, ["R0"])
        assert r2.cache_hits == 2 and r2.cache_misses == 0
        assert r2.ok == r1.ok

    def test_edit_invalidates_only_that_file(self, tmp_path):
        path = tmp_path / "cache.json"
        self._run(path, self.TEXTS, ["R0"])
        edited = dict(self.TEXTS)
        edited["raft_tpu/ops/b.py"] = "y = 3\n"
        r = self._run(path, edited, ["R0"])
        assert r.cache_hits == 1 and r.cache_misses == 1

    def test_program_scope_keys_on_project_digest(self, tmp_path):
        path = tmp_path / "cache.json"
        r1 = self._run(path, self.TEXTS, ["R8"])
        assert (r1.cache_hits, r1.cache_misses) == (0, 1)
        r2 = self._run(path, self.TEXTS, ["R8"])
        assert (r2.cache_hits, r2.cache_misses) == (1, 0)
        # ANY file edit re-runs a whole-program rule
        edited = dict(self.TEXTS)
        edited["raft_tpu/ops/b.py"] = "y = 3\n"
        r3 = self._run(path, edited, ["R8"])
        assert (r3.cache_hits, r3.cache_misses) == (0, 1)

    def test_ruleset_version_change_invalidates(self, tmp_path):
        path = tmp_path / "cache.json"
        self._run(path, self.TEXTS, ["R0"])
        r = self._run(path, self.TEXTS, ["R0"], version="v2")
        assert r.cache_hits == 0 and r.cache_misses == 2

    def test_cached_findings_match_fresh(self, tmp_path):
        path = tmp_path / "cache.json"
        texts = {"raft_tpu/ops/a.py": R0_VIOLATING}
        r1 = self._run(path, texts, ["R0"])
        r2 = self._run(path, texts, ["R0"])
        assert r2.cache_hits > 0
        assert ([f.render() for f in r1.findings]
                == [f.render() for f in r2.findings])
