"""Mesh-native serving tests — the PR-3 acceptance suite.

- Engine parity: the list-sharded IVF search (probe_mode=global) is
  bit-identical (ids AND distances) to the single-device index for
  every ``scan_engine``, on the 8-virtual-CPU-device mesh.
- Lean collectives: the lean probe-candidate exchange selects the same
  probe set as the dense coarse-block gather; ``wire_dtype="bf16"``
  result compression keeps ids exact-ranked (smallest-id ties) and
  shard-count deterministic.
- Mesh-aware SearchExecutor: bucketing invariance (bit-identity with
  the direct distributed search at batch sizes that do and do not fill
  their bucket) and the zero-recompile steady-state guarantee, asserted
  against jax's backend-compile monitoring events.
- Streamed build deal: the per-shard placement produces the same index
  as the dealt layout contract requires, and the peak build-device
  staging counter stays at one block.
"""

import numpy as np
import pytest

from raft_tpu import SearchExecutor
from raft_tpu.comms import local_comms
from raft_tpu.core import tracing
from raft_tpu.distributed import bq as dist_bq, ivf as dist_ivf
from raft_tpu.neighbors import ivf_bq, ivf_flat, ivf_pq
from raft_tpu.neighbors.ivf_flat import (
    IvfFlatIndexParams,
    IvfFlatSearchParams,
)
from raft_tpu.neighbors.ivf_pq import IvfPqIndexParams, IvfPqSearchParams

N_DEV = 8


@pytest.fixture(scope="module")
def comms():
    return local_comms()


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(11)
    x = rng.standard_normal((4096, 32)).astype(np.float32)
    q = rng.standard_normal((16, 32)).astype(np.float32)
    return x, q


@pytest.fixture(scope="module")
def flat_pair(comms, data):
    """The same dataset built as a single-device index and as the
    list-sharded distributed index (same params/resources, so the
    quantizer and packed lists are identical — only the deal differs)."""
    x, _ = data
    params = IvfFlatIndexParams(n_lists=32)
    return (ivf_flat.build(None, params, x),
            dist_ivf.build(None, comms, params, x))


class TestEngineParity:
    """probe_mode=global must be bit-identical to the single-device
    index for every scan engine — the tentpole acceptance criterion."""

    @pytest.mark.parametrize("engine", ["rank", "xla", "pallas", "auto"])
    @pytest.mark.parametrize("n_probes", [4, 12, 32])
    def test_flat_bit_identical(self, data, flat_pair, engine, n_probes):
        _, q = data
        single, dist = flat_pair
        sp = IvfFlatSearchParams(n_probes=n_probes, scan_engine=engine)
        d0, i0 = ivf_flat.search(None, sp, single, q, 10)
        d1, i1 = dist_ivf.search(None, sp, dist, q, 10)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    def test_flat_inner_product(self, comms, data):
        x, q = data
        from raft_tpu.distance.types import DistanceType

        params = IvfFlatIndexParams(n_lists=32,
                                    metric=DistanceType.InnerProduct)
        single = ivf_flat.build(None, params, x)
        dist = dist_ivf.build(None, comms, params, x)
        for engine in ("rank", "xla"):
            sp = IvfFlatSearchParams(n_probes=8, scan_engine=engine)
            d0, i0 = ivf_flat.search(None, sp, single, q, 10)
            d1, i1 = dist_ivf.search(None, sp, dist, q, 10)
            np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
            np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    @pytest.mark.parametrize("engine", ["xla", "rank"])
    def test_pq_engines(self, comms, data, engine):
        """PQ union scan per shard: the xla engine must match the
        single-chip xla engine bit-for-bit (shared smallest-id ADC
        tie-break); the rank engine tracks it on id sets (positional
        ties may legitimately order differently across layouts)."""
        x, q = data
        params = IvfPqIndexParams(n_lists=16, pq_dim=16)
        single = ivf_pq.build(None, params, x)
        dist = dist_ivf.build_pq(None, comms, params, x)
        sp = IvfPqSearchParams(n_probes=8, scan_engine=engine)
        d0, i0 = ivf_pq.search(None, sp, single, q, 10)
        d1, i1 = dist_ivf.search_pq(None, sp, dist, q, 10)
        if engine == "xla":
            np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
            np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
        else:
            for a, b in zip(np.asarray(i0), np.asarray(i1)):
                assert set(a.tolist()) == set(b.tolist())

    def test_full_probes_equal_brute_force(self, comms, data):
        x, q = data
        dist = dist_ivf.build(None, comms, IvfFlatIndexParams(n_lists=16),
                              x)
        d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        gt = np.argsort(d2, axis=1, kind="stable")[:, :5]
        for engine in ("rank", "xla", "pallas"):
            sp = IvfFlatSearchParams(n_probes=16, scan_engine=engine)
            _, i = dist_ivf.search(None, sp, dist, q, 5)
            np.testing.assert_array_equal(np.asarray(i), gt)


class TestLeanCollectives:
    def test_lean_probe_select_matches_dense(self, data, flat_pair):
        """n_probes small enough to take the lean candidate exchange
        (2·local_k < n_local) must return the same results as the
        single-device probe set — the lean path is exact."""
        _, q = data
        single, dist = flat_pair
        # n_local = 32/8 = 4 -> lean needs local_k < 2: n_probes=1
        sp = IvfFlatSearchParams(n_probes=1, scan_engine="xla")
        d0, i0 = ivf_flat.search(None, sp, single, q, 5)
        d1, i1 = dist_ivf.search(None, sp, dist, q, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    def test_lean_vs_dense_larger_mesh_budget(self, comms, data):
        """With more lists per shard, a mid-size probe budget rides the
        lean branch; it must match the dense branch bit-for-bit
        (synthesized by a probe budget that forces the dense path on
        the same index)."""
        x, q = data
        dist = dist_ivf.build(None, comms, IvfFlatIndexParams(n_lists=128),
                              x)
        # n_local = 16: n_probes=4 -> local_k=4, lean; compare against
        # the single-device search (the exactness oracle)
        single = ivf_flat.build(None, IvfFlatIndexParams(n_lists=128), x)
        sp = IvfFlatSearchParams(n_probes=4, scan_engine="xla")
        d0, i0 = ivf_flat.search(None, sp, single, q, 5)
        d1, i1 = dist_ivf.search(None, sp, dist, q, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    def test_wire_dtype_bf16(self, data, flat_pair):
        """bf16 wire compression: ids stay int32-exact and the result
        ranking follows the compressed distances with smallest-id
        ties; against the f32 wire the id sets stay near-identical on
        well-separated data."""
        _, q = data
        _, dist = flat_pair
        sp = IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        d32, i32 = dist_ivf.search(None, sp, dist, q, 10)
        d16, i16 = dist_ivf.search(None, sp, dist, q, 10,
                                   wire_dtype="bf16")
        assert np.asarray(i16).dtype == np.int32
        agree = (np.asarray(i32) == np.asarray(i16)).mean()
        assert agree >= 0.9, agree
        # compressed distances within bf16 relative tolerance
        np.testing.assert_allclose(np.asarray(d16), np.asarray(d32),
                                   rtol=1e-2, atol=1e-2)

    def test_wire_dtype_validates(self, data, flat_pair):
        _, q = data
        _, dist = flat_pair
        with pytest.raises(ValueError, match="wire_dtype"):
            dist_ivf.search(None, IvfFlatSearchParams(n_probes=4), dist,
                            q, 5, wire_dtype="f16")

    def test_payload_model_is_lean(self):
        """Acceptance: global-mode probe selection and result merge move
        O(q · n_probes) and O(q · k) payloads, not the O(q · n_lists/R)
        coarse block."""
        m = dist_ivf.collective_payload_model(
            q=64, k=10, n_probes=32, n_lists=4096, r=8,
            wire_dtype="bf16")
        assert m["coarse_bytes"] == 64 * 32 * 8      # (d, id) candidates
        assert m["coarse_bytes"] < m["dense_coarse_bytes"]
        assert m["merge_bytes"] == 64 * 10 * (2 + 4)  # bf16 wire + ids


def _sharded_flat(n_shards):
    """A flat index list-sharded over ``n_shards`` devices + queries —
    the quantized-wire recall study's fixture builder."""
    import jax

    from raft_tpu.comms.comms import Comms
    from raft_tpu.comms.bootstrap import make_mesh

    rng = np.random.default_rng(23)
    x = rng.standard_normal((4096, 32)).astype(np.float32)
    q = rng.standard_normal((64, 32)).astype(np.float32)
    comms = Comms(make_mesh(("data",),
                            devices=jax.devices()[:n_shards]), "data")
    dist = dist_ivf.build(None, comms, IvfFlatIndexParams(n_lists=64), x)
    return dist, q


class TestQuantizedProbeExchange:
    """ROADMAP item: the probe-candidate exchange rides the
    ``probe_wire_dtype`` quantized wire (bf16, and int8 with
    block-independent per-row affine scales) — recall swept vs shard
    count against the exact f32 exchange (graftwire satellite: the
    16-shard point is the slow-marked tail of the study)."""

    @pytest.fixture(scope="class")
    def four_shard(self):
        return _sharded_flat(4)

    @pytest.mark.parametrize("probe_wire", ["bf16", "int8"])
    @pytest.mark.parametrize("n_shards", [
        4, 8,
        pytest.param(16, marks=pytest.mark.slow),
    ])
    def test_recall_vs_shards(self, n_shards, probe_wire):
        import jax

        if len(jax.devices()) < n_shards:
            pytest.skip(f"needs {n_shards} devices")
        dist, q = _sharded_flat(n_shards)
        # n_local = 64 / n_shards, n_probes = 4 -> the exchange goes
        # lean at 4/8 shards and dense at 16 (2*4 >= 64/16) — the
        # sweep covers both wire layouts
        sp = IvfFlatSearchParams(n_probes=4, scan_engine="xla")
        _, i_exact = dist_ivf.search(None, sp, dist, q, 10)
        _, i_q = dist_ivf.search(None, sp, dist, q, 10,
                                 probe_wire_dtype=probe_wire)
        exact = np.asarray(i_exact)
        got = np.asarray(i_q)
        recall = np.mean([
            len(set(got[r]) & set(exact[r])) / 10
            for r in range(exact.shape[0])])
        floor = 0.99 if probe_wire == "bf16" else 0.95
        assert recall >= floor, (n_shards, probe_wire, recall)

    def test_dense_fallback_also_quantizes(self, four_shard):
        """Probing most of the index takes the dense coarse-block
        gather; the quantized wire applies there too and recall holds
        (at a probe budget this wide the probe sets barely move)."""
        dist, q = four_shard
        sp = IvfFlatSearchParams(n_probes=48, scan_engine="xla")
        _, i_exact = dist_ivf.search(None, sp, dist, q, 10)
        _, i_q = dist_ivf.search(None, sp, dist, q, 10,
                                 probe_wire_dtype="int8")
        exact, got = np.asarray(i_exact), np.asarray(i_q)
        recall = np.mean([
            len(set(got[r]) & set(exact[r])) / 10
            for r in range(exact.shape[0])])
        assert recall >= 0.99, recall

    def test_executor_serves_quantized_probe_wire(self, four_shard):
        """The mesh-aware executor plans the quantized exchange as a
        distinct static (own AOT executable) and matches the direct
        entry bit-for-bit."""
        dist, q = four_shard
        sp = IvfFlatSearchParams(n_probes=4, scan_engine="xla")
        d0, i0 = dist_ivf.search(None, sp, dist, q, 10,
                                 probe_wire_dtype="int8")
        ex = SearchExecutor()
        d1, i1 = ex.search(dist, q, 10, params=sp,
                           probe_wire_dtype="int8")
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    def test_probe_wire_validates(self, data, flat_pair):
        _, q = data
        _, dist = flat_pair
        with pytest.raises(ValueError, match="probe wire_dtype"):
            dist_ivf.search(None, IvfFlatSearchParams(n_probes=4), dist,
                            q, 5, probe_wire_dtype="f16")

    def test_payload_model_prices_quantized_probes(self):
        f32 = dist_ivf.collective_payload_model(
            q=64, k=10, n_probes=32, n_lists=4096, r=8)
        bf16 = dist_ivf.collective_payload_model(
            q=64, k=10, n_probes=32, n_lists=4096, r=8,
            probe_wire_dtype="bf16")
        i8 = dist_ivf.collective_payload_model(
            q=64, k=10, n_probes=32, n_lists=4096, r=8,
            probe_wire_dtype="int8")
        assert f32["coarse_bytes"] == 64 * 32 * 8
        assert bf16["coarse_bytes"] == 64 * 32 * 6
        # + per-row (min, range) f32 affine scale pair — the
        # block-independent scheme that lets int8 ride ragged
        assert i8["coarse_bytes"] == 64 * (32 * 5 + 8)
        assert i8["coarse_bytes"] < bf16["coarse_bytes"] \
            < f32["coarse_bytes"]


class TestMeshExecutor:
    """Mesh-aware SearchExecutor: bucketing invariance + the
    zero-recompile steady state, per engine."""

    @pytest.mark.parametrize("engine", ["rank", "xla", "pallas"])
    @pytest.mark.parametrize("q_rows", [3, 11, 16])
    def test_bucketing_invariance(self, data, flat_pair, engine, q_rows):
        _, q = data
        _, dist = flat_pair
        sp = IvfFlatSearchParams(n_probes=8, scan_engine=engine)
        ex = SearchExecutor()
        d0, i0 = dist_ivf.search(None, sp, dist, q[:q_rows], 5)
        d1, i1 = ex.search(dist, q[:q_rows], 5, params=sp)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    def test_zero_recompiles_within_bucket(self, data, flat_pair):
        _, q = data
        _, dist = flat_pair
        tracing.install_xla_compile_listener()
        sp = IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        ex = SearchExecutor()
        # prime each batch size (search compiles once per bucket; the
        # tiny pad/place programs compile per distinct size)
        for n in (16, 13, 9):
            ex.search(dist, q[:n], 5, params=sp)
        compiles0 = ex.stats.compile_count
        assert compiles0 == 1
        backend0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        for n in (16, 13, 9, 13, 16, 9):
            ex.search(dist, q[:n], 5, params=sp)
        assert ex.stats.compile_count == compiles0
        assert tracing.get_counter(tracing.XLA_COMPILE_COUNT) == backend0
        assert ex.stats.cache_hits >= 8

    def test_engine_switch_is_distinct_executable(self, data, flat_pair):
        _, q = data
        _, dist = flat_pair
        ex = SearchExecutor()
        ex.search(dist, q, 5,
                  params=IvfFlatSearchParams(n_probes=8, scan_engine="xla"))
        c0 = ex.stats.compile_count
        ex.search(dist, q, 5,
                  params=IvfFlatSearchParams(n_probes=8,
                                             scan_engine="rank"))
        assert ex.stats.compile_count == c0 + 1

    def test_warmup_then_serve(self, data, flat_pair):
        _, q = data
        _, dist = flat_pair
        sp = IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        ex = SearchExecutor()
        secs = ex.warmup(dist, buckets=(16,), k=5, params=sp)
        assert secs > 0 and ex.stats.compile_count == 1
        d, i = ex.search(dist, q, 5, params=sp)
        assert ex.stats.compile_count == 1
        assert ex.stats.cache_hits == 1
        d0, i0 = dist_ivf.search(None, sp, dist, q, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i))

    def test_donated_state_keeps_results_valid(self, data, flat_pair):
        import warnings

        _, q = data
        _, dist = flat_pair
        sp = IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # cpu ignores donation
            ex = SearchExecutor(donate=True)
            d1, i1 = ex.search(dist, q[:16], 5, params=sp)
            d1c = np.asarray(d1).copy()
            ex.search(dist, q[:9], 5, params=sp)
            np.testing.assert_array_equal(np.asarray(d1), d1c)

    def test_pq_and_bq_through_executor(self, comms, data):
        x, q = data
        pqi = dist_ivf.build_pq(
            None, comms, IvfPqIndexParams(n_lists=16, pq_dim=16), x)
        sp = IvfPqSearchParams(n_probes=8)
        ex = SearchExecutor()
        d0, i0 = dist_ivf.search_pq(None, sp, pqi, q[:9], 5)
        d1, i1 = ex.search(pqi, q[:9], 5, params=sp)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

        bqi = dist_bq.build_bq(
            None, comms, ivf_bq.IvfBqIndexParams(n_lists=16), x)
        spb = ivf_bq.IvfBqSearchParams(n_probes=8)
        d0, i0 = dist_bq.search_bq(None, spb, bqi, q[:9], 10)
        d1, i1 = ex.search(bqi, q[:9], 10, params=spb)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    def test_rejects_filter_and_bad_query_axis(self, data, flat_pair):
        from raft_tpu.core.bitset import Bitset
        from raft_tpu.core.validation import RaftError
        from raft_tpu.neighbors.filters import BitsetFilter

        x, q = data
        _, dist = flat_pair
        ex = SearchExecutor()
        bs = Bitset.from_mask(np.ones(x.shape[0], bool))
        with pytest.raises(RaftError, match="sample_filter"):
            ex.search(dist, q, 5, params=IvfFlatSearchParams(n_probes=4),
                      sample_filter=BitsetFilter(bs))
        # query_axis must name ANOTHER axis of the index's mesh — a
        # 1-D mesh has none to offer
        with pytest.raises(RaftError, match="query_axis"):
            ex.search(dist, q, 5, params=IvfFlatSearchParams(n_probes=4),
                      query_axis="queries")


def _grid_pair(data):
    """The same dataset list-sharded over a 1-D 4-device mesh and over
    the lists axis of a 4×2 (lists × queries) grid — built with the
    same params so the quantizer and deal are identical."""
    import jax
    from jax.sharding import Mesh

    from raft_tpu.comms.comms import Comms

    x, _ = data
    params = IvfFlatIndexParams(n_lists=32)
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    c2 = Comms(Mesh(devs, ("lists", "queries")), "lists")
    c1 = Comms(Mesh(np.array(jax.devices()[:4]), ("data",)), "data")
    return (dist_ivf.build(None, c1, params, x),
            dist_ivf.build(None, c2, params, x))


class Test2DMeshExecutor:
    """graftwire: 2-D query×list grids join the zero-recompile world —
    the executor's bucketed plans shard the padded query block over
    ``query_axis``, scatter-merge within the list axis, and key the
    AOT cache on the full 2-D mesh identity."""

    @pytest.fixture(scope="class")
    def grid_pair(self, data):
        return _grid_pair(data)

    @pytest.mark.parametrize("engine", ["rank", "xla", "pallas"])
    def test_bit_identical_to_1d(self, data, grid_pair, engine):
        _, q = data
        d1, d2 = grid_pair
        sp = IvfFlatSearchParams(n_probes=8, scan_engine=engine)
        ex = SearchExecutor()
        a_d, a_i = ex.search(d1, q, 5, params=sp)
        b_d, b_i = ex.search(d2, q, 5, params=sp,
                             query_axis="queries")
        np.testing.assert_array_equal(np.asarray(a_i), np.asarray(b_i))
        np.testing.assert_array_equal(np.asarray(a_d), np.asarray(b_d))

    def test_quantized_wires_bit_identical_to_1d(self, data, grid_pair):
        _, q = data
        d1, d2 = grid_pair
        sp = IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        ex = SearchExecutor()
        kw = dict(wire_dtype="bf16", probe_wire_dtype="int8")
        a_d, a_i = ex.search(d1, q, 5, params=sp, **kw)
        b_d, b_i = ex.search(d2, q, 5, params=sp,
                             query_axis="queries", **kw)
        np.testing.assert_array_equal(np.asarray(a_i), np.asarray(b_i))
        np.testing.assert_array_equal(np.asarray(a_d), np.asarray(b_d))

    def test_zero_recompiles_under_load(self, data, grid_pair):
        """Warm the ladder once, then prime-sized batches serve with
        ZERO backend compiles — the recompile hole the 2-D mesh used
        to have (the per-query-shard block is bucketed and the plan
        key carries the 2-D mesh)."""
        rng = np.random.default_rng(31)
        _, d2 = grid_pair
        sp = IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        dist = d2
        ex = SearchExecutor(min_bucket=16, max_bucket=64)
        ex.warmup(dist, k=5, params=sp, query_axis="queries")
        # primer: one dispatch per bucket compiles nothing new but
        # creates the tiny per-size pad programs
        for n in (16, 13, 9, 64, 33):
            ex.search(dist, rng.standard_normal(
                (n, 32)).astype(np.float32), 5, params=sp,
                query_axis="queries")
        tracing.install_xla_compile_listener()
        c0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        compiles0 = ex.stats.compile_count
        for n in (16, 13, 9, 64, 33, 9, 13):
            ex.search(dist, rng.standard_normal(
                (n, 32)).astype(np.float32), 5, params=sp,
                query_axis="queries")
        assert ex.stats.compile_count == compiles0
        assert tracing.get_counter(tracing.XLA_COMPILE_COUNT) == c0

    def test_auto_wire_selection(self, data, grid_pair):
        """``wire_dtype="auto"``/``probe_wire_dtype="auto"`` close the
        loop on the payload model: the resolved plan serves, and the
        model's argmin picks the narrowest wire at this shape."""
        _, q = data
        d1, d2 = grid_pair
        sp = IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        ex = SearchExecutor()
        b_d, b_i = ex.search(d2, q, 5, params=sp, query_axis="queries",
                             wire_dtype="auto", probe_wire_dtype="auto")
        assert np.asarray(b_d).shape == (16, 5)
        # at this tiny grid int8's scale plane ties bf16's dense block
        # — the tie prefers the wider (less lossy) wire
        wd, pwd = dist_ivf.resolve_auto_wires(
            16, 5, 8, 32, 4, "auto", "global", "auto")
        assert wd == "bf16" and pwd == "bf16"
        # at a serving-scale candidate shape the int8 codes dwarf
        # their scale plane and the argmin flips to int8
        _, pwd_big = dist_ivf.resolve_auto_wires(
            64, 10, 32, 4096, 8, "auto", "global", "auto")
        assert pwd_big == "int8"
        # concrete dtypes pass through untouched
        assert dist_ivf.resolve_auto_wires(
            16, 5, 8, 32, 4, "f32", "global", "bf16") == ("f32", "bf16")


class TestStreamedBuildDeal:
    def test_peak_staging_is_one_block(self, comms, data):
        x, _ = data
        tracing.reset_counters("distributed.build.")
        index = dist_ivf.build(None, comms, IvfFlatIndexParams(n_lists=32),
                               x)
        peak = tracing.get_counter(
            "distributed.build.peak_deal_block_bytes")
        total = tracing.get_counter("distributed.build.deal_bytes_total")
        data_bytes = index.data.size * index.data.dtype.itemsize
        assert 0 < peak <= data_bytes // N_DEV + 1
        assert total >= data_bytes
        # and the dealt index still searches exactly
        q = x[:4]
        sp = IvfFlatSearchParams(n_probes=32)
        _, i = dist_ivf.search(None, sp, index, q, 1)
        np.testing.assert_array_equal(np.asarray(i)[:, 0],
                                      np.arange(4))


class TestPayloadGauges:
    """graftscope (PR 6): compiling a mesh executable publishes its
    modeled collective payload as live gauges — the same accounting
    the bench rider emits, scrapeable while serving."""

    def test_mesh_compile_publishes_collective_gauges(self, data,
                                                      flat_pair):
        _, q = data
        _, dist = flat_pair
        tracing.reset_gauges("serving.collective.")
        sp = IvfFlatSearchParams(n_probes=8)
        ex = SearchExecutor()
        ex.search(dist, q, 5, params=sp, wire_dtype="bf16")
        got = tracing.gauges("serving.collective.dist_ivf_flat.bf16.f32.")
        assert set(n.rsplit(".", 1)[1] for n in got) == {
            "coarse_bytes", "dense_coarse_bytes", "merge_bytes"}
        model = dist_ivf.collective_payload_model(
            16, 5, 8, dist.n_lists, N_DEV, "bf16")
        base = "serving.collective.dist_ivf_flat.bf16.f32."
        assert got[base + "merge_bytes"] == model["merge_bytes"]
        assert got[base + "coarse_bytes"] == model["coarse_bytes"]
        # the executor's cost table carries the same model per entry
        (info,) = ex.executable_costs().values()
        assert info["collective_payload"]["merge_bytes"] == (
            model["merge_bytes"])
        # a gauge wipe (metrics.reset) heals at scrape time: the
        # resident mesh entry re-publishes its collective gauges too
        tracing.reset_gauges("serving.")
        assert tracing.gauges(base) == {}
        ex.publish_cost_gauges()
        assert tracing.gauges(base)[base + "merge_bytes"] == (
            model["merge_bytes"])


class TestMeshSpans:
    """graftscope v2: trace_id propagation into the distributed search
    — phase spans with modeled wire bytes, per-shard straggler spans,
    and the regressions (bit-identity + zero-recompile) re-asserted
    with mesh tracing fully enabled."""

    def test_executor_mesh_span_tree(self, data, flat_pair):
        _, q = data
        single, dist = flat_pair
        tracing.reset_spans()
        tracing.reset_gauges("serving.mesh.")
        sp = IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        ex = SearchExecutor(mesh_trace=True)
        tid = tracing.new_trace_id()
        d1, i1 = ex.search(dist, q, 5, params=sp, trace_ids=(tid,))
        # tracing changes nothing about the results
        d0, i0 = ivf_flat.search(None, sp, single, q, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
        rec = tracing.span_recorder()
        # the three mesh phases, each carrying the trace id AND the
        # modeled wire bytes of the entry's collective_payload_model
        model = dist_ivf.collective_payload_model(
            16, 5, 8, dist.n_lists, N_DEV)
        (cs,) = rec.spans(trace_id=tid,
                          name="serving.mesh.coarse_select")
        assert cs.attrs["wire_bytes"] == model["coarse_bytes"]
        (mg,) = rec.spans(trace_id=tid, name="serving.mesh.merge")
        assert mg.attrs["wire_bytes"] == model["merge_bytes"]
        assert rec.spans(trace_id=tid, name="serving.mesh.scan")
        # one readiness span per shard of the 8-device mesh, and the
        # straggler gauges reduced from those timings
        shards = rec.spans(trace_id=tid, name="serving.mesh.shard")
        assert [s.attrs["shard"] for s in shards] == list(range(N_DEV))
        assert all(s.attrs["family"] == "dist_ivf_flat" for s in shards)
        slowest = tracing.get_gauge(tracing.MESH_SLOWEST_SHARD)
        times = [s.duration for s in shards]
        assert times[int(slowest)] == max(times)
        assert tracing.get_gauge(
            tracing.MESH_SHARD_SKEW) == pytest.approx(
                max(times) - min(times))

    def test_zero_recompiles_with_mesh_tracing_enabled(self, data,
                                                       flat_pair):
        _, q = data
        _, dist = flat_pair
        tracing.install_xla_compile_listener()
        sp = IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        ex = SearchExecutor(mesh_trace=True)
        tid = tracing.new_trace_id()
        for n in (16, 13, 9):
            ex.search(dist, q[:n], 5, params=sp, trace_ids=(tid,))
        compiles0 = ex.stats.compile_count
        assert compiles0 == 1
        backend0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        for n in (16, 13, 9, 13, 16):
            ex.search(dist, q[:n], 5, params=sp, trace_ids=(tid,))
        assert ex.stats.compile_count == compiles0
        assert tracing.get_counter(tracing.XLA_COMPILE_COUNT) == backend0

    def test_direct_search_trace_id(self, data, flat_pair):
        _, q = data
        single, dist = flat_pair
        tracing.reset_spans()
        sp = IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        tid = tracing.new_trace_id()
        d1, i1 = dist_ivf.search(None, sp, dist, q, 5, trace_id=tid)
        d0, i0 = ivf_flat.search(None, sp, single, q, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
        rec = tracing.span_recorder()
        # the timed-dispatch wrapper's span + the three phase spans
        (disp,) = rec.spans(trace_id=tid,
                            name="comms.dispatch.dist_ivf_flat")
        assert disp.duration > 0
        assert disp.attrs["modeled_bytes"] > 0
        assert rec.spans(trace_id=tid, name="serving.mesh.merge")
        assert tracing.get_counter(
            "comms.dispatch.dist_ivf_flat.calls") >= 1.0
        # untraced calls record nothing new (opt-in contract)
        n0 = len(rec.spans())
        dist_ivf.search(None, sp, dist, q, 5)
        assert len(rec.spans()) == n0

    def test_bq_direct_search_trace_id(self, comms, data):
        x, q = data
        from raft_tpu.neighbors.ivf_bq import (
            IvfBqIndexParams,
            IvfBqSearchParams,
        )

        tracing.reset_spans()
        dist = dist_bq.build_bq(None, comms,
                                IvfBqIndexParams(n_lists=16), x)
        tid = tracing.new_trace_id()
        dist_bq.search_bq(None, IvfBqSearchParams(n_probes=8), dist,
                          q, 5, trace_id=tid)
        rec = tracing.span_recorder()
        assert rec.spans(trace_id=tid,
                         name="comms.dispatch.dist_ivf_bq")
        assert rec.spans(trace_id=tid, name="serving.mesh.merge")

    def test_collective_trace_counters_inventory(self, data, flat_pair):
        """The comms veneer's trace-time accounting: tracing a mesh
        program bumps per-family calls/bytes counters, and repeat
        dispatches of the compiled program add nothing."""
        _, q = data
        _, dist = flat_pair
        sp = IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        ex = SearchExecutor()
        ex.warmup(dist, buckets=(16,), k=5, params=sp)
        calls0 = tracing.get_counter("comms.allgather.calls")
        assert calls0 >= 1.0            # the id gather traced at least once
        assert tracing.get_counter("comms.allgather.modeled_bytes") > 0
        ex.search(dist, q, 5, params=sp)
        ex.search(dist, q, 5, params=sp)
        # steady state: no re-traces, so the inventory is unchanged
        assert tracing.get_counter("comms.allgather.calls") == calls0


class TestShardedAnnStraggler:
    """The per-shard-dispatch path measures REAL per-shard readiness:
    a trace_id-carrying search feeds the straggler detector (opt-in —
    untraced steady traffic must not fill the span ring)."""

    def test_sharded_search_records_shard_timings(self):
        import jax

        from raft_tpu.distributed import sharded_ann
        from raft_tpu.neighbors import brute_force

        rng = np.random.default_rng(7)
        x = rng.standard_normal((512, 16)).astype(np.float32)
        q = rng.standard_normal((8, 16)).astype(np.float32)
        idx = sharded_ann.build_sharded(
            None,
            lambda res, part: brute_force.build(res, part),
            lambda res, ix, qs, k: brute_force.search(res, ix, qs, k),
            x, devices=jax.devices()[:4])
        tracing.reset_spans()
        tracing.reset_gauges("serving.mesh.")
        tid = tracing.new_trace_id()
        d, i = idx.search(None, q, 5, trace_id=tid)
        assert np.asarray(i).shape == (8, 5)
        rec = tracing.span_recorder()
        shards = rec.spans(trace_id=tid, name="serving.mesh.shard")
        assert len(shards) == 4
        assert tracing.get_gauge(tracing.MESH_SHARD_TIME_MAX) > 0
        assert 0 <= tracing.get_gauge(tracing.MESH_SLOWEST_SHARD) < 4
        # opt-in: an untraced search records NO shard spans — steady
        # traffic must not churn the bounded span ring
        n_before = len(rec.spans(name="serving.mesh.shard"))
        idx.search(None, q, 5)
        assert len(rec.spans(name="serving.mesh.shard")) == n_before


class TestMeshProbeAccounting:
    """graftgauge (PR 8): the sharded IVF families scatter-add their
    selected probes into a LIST-SHARDED donated counter plane — each
    shard counts only the probes it owns, so a probe lands exactly
    once mesh-wide and the gathered plane is the same global histogram
    the single-chip index would have recorded."""

    def test_mesh_bit_identity_and_exact_counts(self, data, flat_pair):
        _, q = data
        single, dist = flat_pair
        sp = IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        ex = SearchExecutor(probe_accounting=True)
        d1, i1 = ex.search(dist, q, 5, params=sp)
        d0, i0 = dist_ivf.search(None, sp, dist, q, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
        (plane,) = ex.probe_frequencies().values()
        assert plane.shape == (dist.n_lists,)
        assert plane.sum() == q.shape[0] * 8

    def test_mesh_histogram_in_own_list_id_space(self, data, flat_pair):
        """The gathered mesh plane must be the exact probe histogram
        in the DIST index's own list-id space (the deal permutes list
        ids, so that space — the one ``dist.list_sizes`` and the drift
        baseline live in — is the meaningful one): bin for bin equal
        to a host-side bincount of the coarse selection over the dist
        quantizer, and a permutation of nothing lost."""
        _, q = data
        _, dist = flat_pair
        import jax.numpy as jnp
        from raft_tpu.neighbors._batching import coarse_select

        ex = SearchExecutor(probe_accounting=True)
        ex.search(dist, q, 5,
                  params=IvfFlatSearchParams(n_probes=8,
                                             scan_engine="xla"))
        (p_mesh,) = ex.probe_frequencies().values()
        c = jnp.asarray(np.asarray(dist.centers))
        ip = jnp.asarray(q) @ c.T
        score = -(jnp.sum(jnp.square(c), axis=1)[None, :] - 2.0 * ip)
        probes = np.asarray(coarse_select(score, 8, "exact"))
        expected = np.bincount(probes.reshape(-1),
                               minlength=dist.n_lists)
        np.testing.assert_array_equal(expected, p_mesh)

    def test_mesh_zero_recompile_with_accounting(self, data, flat_pair):
        _, q = data
        _, dist = flat_pair
        tracing.install_xla_compile_listener()
        sp = IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        ex = SearchExecutor(probe_accounting=True)
        for n in (16, 13, 9):
            ex.search(dist, q[:n], 5, params=sp)
        compiles0 = ex.stats.compile_count
        backend0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        for n in (16, 13, 9, 13, 16, 9):
            ex.search(dist, q[:n], 5, params=sp)
        assert ex.stats.compile_count == compiles0
        assert tracing.get_counter(tracing.XLA_COMPILE_COUNT) == backend0
        # pad rows masked on the mesh too: 6 + 3 dispatches of
        # (16+13+9)=38 rows x 8 probes each plane-wide
        (plane,) = ex.probe_frequencies().values()
        assert plane.sum() == 3 * (16 + 13 + 9) * 8

    def test_mesh_pq_and_bq_accounting(self, comms, data):
        x, q = data
        ex = SearchExecutor(probe_accounting=True)
        pq = dist_ivf.build_pq(
            None, comms, IvfPqIndexParams(n_lists=32, pq_dim=8), x)
        sp = IvfPqSearchParams(n_probes=8)
        d1, i1 = ex.search(pq, q, 5, params=sp)
        d0, i0 = dist_ivf.search_pq(None, sp, pq, q, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
        bq = dist_bq.build_bq(
            None, comms, ivf_bq.IvfBqIndexParams(n_lists=32), x)
        bp = ivf_bq.IvfBqSearchParams(n_probes=8)
        d3, i3 = ex.search(bq, q, 5, params=bp)
        d2, i2 = dist_bq.search_bq(None, bp, bq, q, 5)
        np.testing.assert_array_equal(np.asarray(i2), np.asarray(i3))
        np.testing.assert_array_equal(np.asarray(d2), np.asarray(d3))
        planes = ex.probe_frequencies()
        assert len(planes) == 2
        for plane in planes.values():
            assert plane.sum() == q.shape[0] * 8


class TestBqFusedMesh:
    """RaBitQ IVF-BQ on the mesh (this PR's tentpole): the fused
    estimate-then-rerank engines run shard-locally, probe_mode=global
    stays bit-identical to the single-chip index per engine, and the
    variance-corrected merge hits the 0.99 recall bar at (or under)
    the budget the flat 2x over-fetch used to burn."""

    @pytest.fixture(scope="class")
    def bq_pair(self, comms, data):
        x, _ = data
        params = ivf_bq.IvfBqIndexParams(n_lists=32)
        return (ivf_bq.build(None, params, x),
                dist_bq.build_bq(None, comms, params, x))

    @pytest.mark.parametrize("engine", ["rank", "xla", "pallas", "auto"])
    def test_bq_bit_identical(self, data, bq_pair, engine):
        _, q = data
        single, dist = bq_pair
        sp = ivf_bq.IvfBqSearchParams(n_probes=8, scan_engine=engine)
        d0, i0 = ivf_bq.search(None, sp, single, q, 10)
        d1, i1 = dist_bq.search_bq(None, sp, dist, q, 10)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    def test_four_shard_recall_at_old_half_budget(self):
        """Acceptance: sharded recall >= 0.99 at <= the old 2x merge
        over-fetch budget on the 4-shard config. The fused engines
        exchange EXACT distances, so merge_k collapses to k — half
        the old 2x wire depth — and recall is limited only by the
        probe set."""
        import jax

        from raft_tpu.comms.bootstrap import make_mesh
        from raft_tpu.comms.comms import Comms
        from raft_tpu.distributed.bq import merge_overfetch
        from raft_tpu.neighbors import brute_force
        from raft_tpu.utils import eval_recall

        rng = np.random.default_rng(23)
        x = rng.standard_normal((4096, 32)).astype(np.float32)
        q = rng.standard_normal((64, 32)).astype(np.float32)
        comms4 = Comms(make_mesh(("data",),
                                 devices=jax.devices()[:4]), "data")
        dist = dist_bq.build_bq(
            None, comms4, ivf_bq.IvfBqIndexParams(n_lists=64), x)
        merge_k = merge_overfetch(dist, 10)
        assert merge_k <= 20, merge_k          # old budget was 2x k
        sp = ivf_bq.IvfBqSearchParams(n_probes=48)
        _, i = dist_bq.search_bq(None, sp, dist, q, 10)
        _, gt = brute_force.knn(None, x, q, 10)
        r, _, _ = eval_recall(np.asarray(gt), np.asarray(i))
        assert r >= 0.99, r

    def test_estimate_only_variance_corrected_merge(self, comms, data):
        """A codes-only mesh index over-fetches the merge by the
        MEASURED per-shard estimator variance (not a flat 2x): the
        derived depth is recorded per shard at build, and the merged
        estimate candidates rescue the exact top-k through refine."""
        from raft_tpu.distributed.bq import merge_overfetch
        from raft_tpu.neighbors import brute_force
        from raft_tpu.neighbors.refine import refine
        from raft_tpu.utils import eval_recall

        x, q = data
        dist = dist_bq.build_bq(
            None, comms, ivf_bq.IvfBqIndexParams(
                n_lists=32, store_vectors=False), x)
        assert len(dist.shard_rel_err) == N_DEV
        assert all(v > 0 for v in dist.shard_rel_err)
        merge_k = merge_overfetch(dist, 10)
        assert 10 < merge_k <= 240    # bound-derived, not hand-tuned
        # exhaustive probes isolate the merge budget: recall measures
        # the candidate depth, not the probe set
        sp = ivf_bq.IvfBqSearchParams(n_probes=32)
        _, gt = brute_force.knn(None, x, q, 10)

        def recall_at(depth):
            _, cand = dist_bq.search_bq(None, sp, dist, q, depth)
            assert np.asarray(cand).shape[1] == depth
            _, i = refine(None, x, q, cand, 10)
            r, _, _ = eval_recall(np.asarray(gt), np.asarray(i))
            return float(r)

        r_derived = recall_at(merge_k)
        r_flat2x = recall_at(20)
        # the measured-variance depth beats the flat 2x it replaced by
        # a wide margin on the estimator's hardest case (1-bit codes,
        # unclustered gaussians — residual ≈ the whole vector)
        assert r_derived >= r_flat2x + 0.2, (r_derived, r_flat2x)
        assert r_derived >= 0.7, r_derived

    @pytest.mark.parametrize("engine", ["xla", "pallas"])
    @pytest.mark.parametrize("q_rows", [3, 11, 16])
    def test_executor_bucketing_invariance(self, data, bq_pair, engine,
                                           q_rows):
        _, q = data
        _, dist = bq_pair
        sp = ivf_bq.IvfBqSearchParams(n_probes=8, scan_engine=engine)
        ex = SearchExecutor()
        d0, i0 = dist_bq.search_bq(None, sp, dist, q[:q_rows], 5)
        d1, i1 = ex.search(dist, q[:q_rows], 5, params=sp)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    def test_executor_engine_in_cache_key_zero_recompile(self, data,
                                                         bq_pair):
        """Engine switch = distinct executable; steady state on one
        engine = zero recompiles (the new engine static is in the AOT
        cache key)."""
        _, q = data
        _, dist = bq_pair
        tracing.install_xla_compile_listener()
        ex = SearchExecutor()
        sp_x = ivf_bq.IvfBqSearchParams(n_probes=8, scan_engine="xla")
        for n in (16, 13, 9):
            ex.search(dist, q[:n], 5, params=sp_x)
        c0 = ex.stats.compile_count
        assert c0 == 1
        backend0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        for n in (16, 13, 9, 13):
            ex.search(dist, q[:n], 5, params=sp_x)
        assert ex.stats.compile_count == c0
        assert tracing.get_counter(tracing.XLA_COMPILE_COUNT) == backend0
        sp_p = ivf_bq.IvfBqSearchParams(n_probes=8,
                                        scan_engine="pallas")
        ex.search(dist, q, 5, params=sp_p)
        assert ex.stats.compile_count == c0 + 1


class TestMeshRagged:
    """graftragged: the list-sharded families serve through the SAME
    ragged plan family — one replicated-tile executable per (mesh,
    params class) replaces the distributed bucket ladder. Bit-identity
    per request vs the bucketed mesh dispatch, zero-recompile mixed
    load, probe accounting exact, and the mesh-specific residue
    (query_axis grids) falls back with an explicit reason; the int8
    probe wire rides ragged since graftwire's block-independent
    scales."""

    @pytest.fixture(scope="class")
    def mesh_indexes(self, comms, data):
        x, _ = data
        return {
            "flat": dist_ivf.build(
                None, comms, IvfFlatIndexParams(n_lists=32), x),
            "pq": dist_ivf.build_pq(
                None, comms,
                IvfPqIndexParams(n_lists=32, pq_dim=8), x),
            "bq": dist_bq.build_bq(
                None, comms,
                ivf_bq.IvfBqIndexParams(n_lists=32, bits=2), x),
        }

    def _blocks(self, seed=3):
        rng = np.random.default_rng(seed)
        return [rng.standard_normal((m, 32)).astype(np.float32)
                for m in (3, 5, 2, 9)]

    # three combos cover both axes (engine × probe mode) without the
    # fourth's near-duplicate compile cost — tier-1 wall-time budget
    @pytest.mark.parametrize("probe_mode,engine", [
        ("global", "pallas"), ("global", "xla"), ("local", "xla")])
    def test_flat_bit_identical_per_engine(self, mesh_indexes, engine,
                                           probe_mode):
        index = mesh_indexes["flat"]
        ex = SearchExecutor(ragged_tile=16)
        p1 = IvfFlatSearchParams(n_probes=5, scan_engine=engine)
        p2 = IvfFlatSearchParams(n_probes=8, scan_engine=engine)
        assert (ex.ragged_key(index, 4, params=p1,
                              probe_mode=probe_mode)
                == ex.ragged_key(index, 7, params=p2,
                                 probe_mode=probe_mode))
        blocks = self._blocks()
        res = ex.search_ragged(index, blocks, [4, 7, 6, 5],
                               params_list=[p1, p2, p1, p2],
                               probe_mode=probe_mode)
        for b, (d, i), kj, pj in zip(blocks, res, [4, 7, 6, 5],
                                     [p1, p2, p1, p2]):
            sd, si = ex.search(index, b, kj, params=pj,
                               probe_mode=probe_mode)
            np.testing.assert_array_equal(i, np.asarray(si))
            np.testing.assert_array_equal(d, np.asarray(sd))
        assert ex.ragged_executables("dist_ivf_flat") == 1

    @pytest.mark.parametrize("fam", ["pq", "bq"])
    def test_pq_bq_bit_identical(self, mesh_indexes, fam):
        index = mesh_indexes[fam]
        mk = (IvfPqSearchParams if fam == "pq"
              else ivf_bq.IvfBqSearchParams)
        p1 = mk(n_probes=5, scan_engine="xla")
        p2 = mk(n_probes=8, scan_engine="xla")
        ex = SearchExecutor(ragged_tile=16)
        blocks = self._blocks(seed=5)
        res = ex.search_ragged(index, blocks, [4, 7, 6, 5],
                               params_list=[p1, p2, p1, p2])
        for b, (d, i), kj, pj in zip(blocks, res, [4, 7, 6, 5],
                                     [p1, p2, p1, p2]):
            sd, si = ex.search(index, b, kj, params=pj)
            np.testing.assert_array_equal(i, np.asarray(si))
            np.testing.assert_array_equal(d, np.asarray(sd))
        assert ex.ragged_executables("dist_ivf_" + fam) == 1

    def test_zero_recompile_mixed_load(self, mesh_indexes):
        """Warm the one executable, then mixed per-request n_probes/k
        load — with probe accounting ON — serves with ZERO backend
        compiles (after the one-time lazily-created probe plane)."""
        index = mesh_indexes["flat"]
        ex = SearchExecutor(ragged_tile=16, probe_accounting=True)
        p1 = IvfFlatSearchParams(n_probes=5, scan_engine="xla")
        p2 = IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        ex.warmup_ragged(index, k=7, params=p1)
        blocks = self._blocks(seed=7)
        # primer dispatch creates the donated probe plane (one jnp
        # broadcast compile, same one-time cost as the bucketed path)
        ex.search_ragged(index, blocks[:1], 4, params_list=p1)
        tracing.install_xla_compile_listener()
        c0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        for _ in range(3):
            ex.search_ragged(index, blocks, [4, 7, 6, 5],
                             params_list=[p1, p2, p1, p2])
        assert (tracing.get_counter(tracing.XLA_COMPILE_COUNT)
                - c0 == 0)
        assert ex.ragged_executables() == 1
        # probe accounting: every dispatched row lands exactly its own
        # budget, counted once mesh-wide on the owning shard
        planes = ex.probe_frequencies()
        (label,) = planes.keys()
        assert label.startswith("dist_ivf_flat-")
        # primer: 3 rows at n_probes=5; then 3 rounds of the mixed
        # stream (rows x that request's OWN budget)
        rows_by_budget = 3 * 5 + 3 * (3 * 5 + 5 * 8 + 2 * 5 + 9 * 8)
        assert planes[label].sum() == rows_by_budget

    def test_mesh_residue_reasons(self, mesh_indexes):
        index = mesh_indexes["flat"]
        ex = SearchExecutor()
        p = IvfFlatSearchParams(n_probes=5, scan_engine="xla")
        # graftwire: the int8 probe wire went block-independent
        # (per-row affine scales over the FULL local coarse block), so
        # its ragged pin is retired — int8 is raggable now
        assert ex.ragged_key(index, 4, params=p,
                             probe_wire_dtype="int8") is not None
        assert ex.ragged_fallback_reason(
            index, 4, params=p, probe_wire_dtype="int8") is None
        assert ex.ragged_key(index, 4, params=p,
                             query_axis="q") is None
        assert "query_axis" in ex.ragged_fallback_reason(
            index, 4, params=p, query_axis="q")
        # bf16 wires stay raggable (per-element rounding keeps the
        # budget-prefix property)
        assert ex.ragged_key(index, 4, params=p, wire_dtype="bf16",
                             probe_wire_dtype="bf16") is not None

    def test_int8_probe_wire_bit_identical(self, mesh_indexes):
        """The retired pin's acceptance: an int8-probe-wire ragged
        dispatch is bit-identical to the solo bucketed search — the
        block-independent scales make codes independent of what else
        shares the tile (cap-vs-solo)."""
        index = mesh_indexes["flat"]
        ex = SearchExecutor(ragged_tile=16)
        p1 = IvfFlatSearchParams(n_probes=5, scan_engine="xla")
        p2 = IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        blocks = self._blocks(seed=13)[:2]
        res = ex.search_ragged(index, blocks, 4, params_list=[p1, p2],
                               probe_wire_dtype="int8")
        for b, (d, i), pj in zip(blocks, res, [p1, p2]):
            sd, si = ex.search(index, b, 4, params=pj,
                               probe_wire_dtype="int8")
            np.testing.assert_array_equal(i, np.asarray(si))
            np.testing.assert_array_equal(d, np.asarray(sd))

    def test_bf16_wire_bit_identical(self, mesh_indexes):
        index = mesh_indexes["flat"]
        ex = SearchExecutor(ragged_tile=16)
        p1 = IvfFlatSearchParams(n_probes=5, scan_engine="xla")
        p2 = IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        blocks = self._blocks(seed=9)[:2]
        res = ex.search_ragged(index, blocks, 4, params_list=[p1, p2],
                               wire_dtype="bf16",
                               probe_wire_dtype="bf16")
        for b, (d, i), pj in zip(blocks, res, [p1, p2]):
            sd, si = ex.search(index, b, 4, params=pj,
                               wire_dtype="bf16",
                               probe_wire_dtype="bf16")
            np.testing.assert_array_equal(i, np.asarray(si))
            np.testing.assert_array_equal(d, np.asarray(sd))

    def test_batcher_serves_mesh_ragged(self, mesh_indexes):
        """BatcherConfig(ragged=True) covers the mesh families in
        continuous admission: submissions group by the mesh ragged
        key and complete bit-identical to the executor path."""
        from raft_tpu.serving import BatcherConfig, DynamicBatcher

        index = mesh_indexes["flat"]
        ex = SearchExecutor(ragged_tile=16)
        p = IvfFlatSearchParams(n_probes=5, scan_engine="xla")
        blocks = self._blocks(seed=13)
        with DynamicBatcher(ex, BatcherConfig(max_wait_s=0.002,
                                              ragged=True)) as b:
            hs = [b.submit(index, blk, 5, params=p,
                           probe_mode="global") for blk in blocks]
            for h, blk in zip(hs, blocks):
                got = h.result(timeout=120)
                want = ex.search(index, blk, 5, params=p,
                                 probe_mode="global")
                np.testing.assert_array_equal(
                    np.asarray(got[1]), np.asarray(want[1]))
        assert ex.ragged_executables("dist_ivf_flat") == 1
