"""graftflight (PR 11) tests — device-truth attribution and incident
capture.

- Trace parser + correlation pinned DETERMINISTICALLY by the committed
  device-free capture fixture (``tests/data/graftflight_capture
  .trace.json`` — anonymized CPU-backend structure with mesh-device
  pids grafted in the same event format).
- Measured-supersedes-modeled: with a capture attributed, mesh
  phase/shard spans re-emit ``modeled: False`` with device-measured
  windows, the straggler gauges recompute from device timings, and
  ``metrics.derived()`` carries per-executable achieved GB/s divided
  by MEASURED device seconds — all pinned by the fixture.
- Real-executor round trip on a live CPU capture: the digest-named
  HLO modules correlate back to the resident executables, and
  zero-recompile + bit-identity stay green with attribution applied
  (single-chip and mesh).
- FlightRecorder: the multiburn-alert and latency-anomaly triggers,
  the cooldown rate limit, and the incident bundle surface
  (``/incident.json``) — ManualClock-pinned.
- Exporter hardening: ``/profile`` returns the capture's trace-file
  path; ``/incident.json`` and ``/profile`` responses parse-checked
  field by field; per-params-class latency histograms render as
  labeled Prometheus families.

graftfleet (PR 12) additions:

- Per-dispatch invocation windows: gap-clustering determinism and
  edge cases (single dispatch, overlapping devices, empty capture,
  back-to-back fallback to the op-count floor), per-dispatch skew
  distribution gauges — fixture-pinned.
- xplane-pb ingestion: the committed ``.xplane.pb`` twin of the
  chrome fixture's mesh module must yield the SAME attribution;
  auto-selection only without a chrome sidecar.
- The live round trip now drives ``ContinuousCapture`` over TWO real
  profiler windows at the default duty cycle — rolling gauges from
  two distinct windows, zero-recompile + bit-identity intact.
"""

import json
import os
import urllib.request

import numpy as np
import pytest

from raft_tpu import SearchExecutor
from raft_tpu.core import profiling, tracing
from raft_tpu.neighbors import ivf_flat
from raft_tpu.serving import (
    BatcherConfig,
    DynamicBatcher,
    FlightConfig,
    FlightRecorder,
    LatencyAnomaly,
    MetricsExporter,
    MultiBurnConfig,
    SloConfig,
)
from raft_tpu.serving import flight as flight_mod
from raft_tpu.serving import metrics
from raft_tpu.serving.harness import FakeExecutor, ManualClock

FIXTURE = os.path.join(os.path.dirname(__file__), "data",
                       "graftflight_capture.trace.json")

# the cost table the fixture's modules correlate against — the shape
# SearchExecutor.executable_costs() produces, with round numbers so
# the measured achieved GB/s pins exactly:
#   single-chip: 270 kB/call x 3 invocations / 810 us = 1.0 GB/s
#   mesh:        1.3 MB/call x 2 invocations / 2600 us = 1.0 GB/s
FIXTURE_COSTS = {
    "aaaa01aaaa01": {
        "hlo_module": "jit_rt_ivf_flat_aaaa01aaaa01",
        "family": "ivf_flat", "bucket": 8, "k": 5,
        "bytes_accessed": 270_000.0, "flops": 540_000.0,
    },
    "bbbb02bbbb02": {
        "hlo_module": "jit_rt_dist_ivf_flat_bbbb02bbbb02",
        "family": "dist_ivf_flat", "bucket": 16, "k": 5,
        "bytes_accessed": 1_300_000.0, "flops": 2_600_000.0,
        "collective_payload": {
            "coarse_bytes": 2048, "dense_coarse_bytes": 8192,
            "merge_bytes": 512, "wire_dtype": "f32",
            "probe_wire_dtype": "f32"},
    },
}


def fixture_attr():
    return profiling.attribute(FIXTURE, FIXTURE_COSTS)


class TestTraceParser:
    def test_fixture_parses_device_ops_only(self):
        ops = profiling.parse_chrome_trace(profiling.load_trace(FIXTURE))
        # python host-thread events and ThreadpoolListener markers
        # carry no hlo_module and are skipped; every parsed op carries
        # the module it executes in
        assert len(ops) == 25
        assert all(op.module for op in ops)
        devices = {op.device for op in ops}
        assert devices == {"/host:CPU", "/device:TPU:0",
                           "/device:TPU:1"}
        # scope extraction: the mesh ops carry tf_op paths, the CPU
        # module's ops carry none (the CPU chrome export drops scopes)
        mesh = [op for op in ops if op.module.endswith("bbbb02bbbb02")]
        assert all(op.scope for op in mesh)
        assert {op.phase for op in mesh} == set(profiling.PHASE_MARKERS)
        cpu = [op for op in ops if op.module.endswith("aaaa01aaaa01")]
        assert {op.phase for op in cpu} == {profiling.UNATTRIBUTED}

    def test_load_trace_variants(self, tmp_path):
        import gzip
        import shutil

        data = profiling.load_trace(FIXTURE)
        # dict passthrough
        assert profiling.load_trace(data) is data
        # profiler-layout directory + gz, resolved via latest_trace_file
        run = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
        run.mkdir(parents=True)
        gz = run / "host.trace.json.gz"
        with open(FIXTURE, "rb") as src, gzip.open(gz, "wb") as dst:
            shutil.copyfileobj(src, dst)
        assert profiling.latest_trace_file(str(tmp_path)) == str(gz)
        parsed = profiling.load_trace(str(tmp_path))
        assert parsed["traceEvents"] == data["traceEvents"]
        # an empty capture dir is an explicit error, not a silent {}
        empty = tmp_path / "empty"
        empty.mkdir()
        with pytest.raises(FileNotFoundError):
            profiling.load_trace(str(empty))


class TestCorrelation:
    def test_fixture_pinned(self):
        attr = fixture_attr()
        assert set(attr.modules) == set(FIXTURE_COSTS)
        a = attr.modules["aaaa01aaaa01"]
        # 100+200+300 (dot) + 3x50 (fusion) + 6x10 (loop-body) us
        assert a.device_seconds == pytest.approx(810e-6, rel=1e-9)
        # min per-(device, op) count: the loop-body op appears 6x but
        # the module ran 3x — max would read 6 and inflate GB/s
        assert a.invocations == 3
        assert a.shard_seconds == {
            "/host:CPU": pytest.approx(810e-6, rel=1e-9)}
        assert not a.mesh
        b = attr.modules["bbbb02bbbb02"]
        assert b.invocations == 2
        assert b.mesh
        assert b.phase_seconds == {
            "coarse_select": pytest.approx(400e-6, rel=1e-9),
            "scan": pytest.approx(2000e-6, rel=1e-9),
            "merge": pytest.approx(200e-6, rel=1e-9),
        }
        assert b.shard_seconds == {
            "/device:TPU:0": pytest.approx(1100e-6, rel=1e-9),
            "/device:TPU:1": pytest.approx(1500e-6, rel=1e-9),
        }
        assert b.window == (pytest.approx(1000e-6), pytest.approx(2750e-6))
        # measured roofline: modeled bytes x invocations / device time
        assert a.measured_gbps() == pytest.approx(1.0, rel=1e-6)
        assert a.measured_gflops() == pytest.approx(2.0, rel=1e-6)
        assert b.measured_gbps() == pytest.approx(1.0, rel=1e-6)
        # the result-slice micro-program matched nothing and says so
        assert attr.unmatched_modules == {
            "jit_dynamic_slice": pytest.approx(5e-6, rel=1e-9)}
        # per-dispatch invocation windows (PR 12): the gap-clustering
        # found exactly one window per dispatch, and each mesh
        # window's per-device busy time yields a PER-DISPATCH skew
        assert len(a.windows) == 3 and len(b.windows) == 2
        assert a.skew_samples() == []          # single-device module
        assert b.skew_samples() == [
            pytest.approx(200e-6, rel=1e-9)] * 2

    def test_attribute_bumps_ingestion_counters(self):
        before = tracing.get_counter(profiling.CAPTURES)
        ops_before = tracing.get_counter(profiling.DEVICE_OPS)
        fixture_attr()
        assert tracing.get_counter(profiling.CAPTURES) == before + 1
        assert tracing.get_counter(profiling.DEVICE_OPS) == \
            ops_before + 25

    def test_trace_file_recorded_from_path_source(self):
        attr = fixture_attr()
        assert attr.trace_file == FIXTURE
        assert attr.to_dict()["trace_file"] == FIXTURE


class TestInvocationWindows:
    """graftfleet (PR 12): gap-clustering determinism + edge cases.
    The fixture pins the real shapes; the synthetic cases pin the
    boundary rules."""

    def ops(self, module="aaaa01aaaa01"):
        all_ops = profiling.parse_chrome_trace(
            profiling.load_trace(FIXTURE))
        return [o for o in all_ops if o.module.endswith(module)]

    def test_fixture_windows_pinned(self):
        wins = profiling.invocation_windows(self.ops())
        assert len(wins) == 3
        assert [w.start_s for w in wins] == [
            pytest.approx(t, rel=1e-9)
            for t in (3000e-6, 4000e-6, 5000e-6)]
        # per-window phase/device totals partition the capture totals
        assert sum(w.device_seconds for w in wins) == \
            pytest.approx(810e-6, rel=1e-9)
        assert all(w.ops == 4 for w in wins)

    def test_overlapping_devices_merge_into_shared_windows(self):
        # the mesh module's two devices overlap in time: one device's
        # intra-dispatch idle is covered by the other's ops, so the
        # merged timeline yields exactly one window per DISPATCH
        wins = profiling.invocation_windows(self.ops("bbbb02bbbb02"))
        assert len(wins) == 2
        for w in wins:
            assert set(w.shard_seconds) == {"/device:TPU:0",
                                            "/device:TPU:1"}
            assert w.shard_seconds["/device:TPU:0"] == \
                pytest.approx(550e-6, rel=1e-9)
            assert w.shard_seconds["/device:TPU:1"] == \
                pytest.approx(750e-6, rel=1e-9)
            assert w.skew == pytest.approx(200e-6, rel=1e-9)
            assert w.phase_seconds["scan"] == pytest.approx(
                1000e-6, rel=1e-9)

    def test_single_dispatch_yields_one_window(self):
        # every op ran once (n_min == n_max == 1): whatever idle gaps
        # the events carry, nothing may split
        ops = [profiling.DeviceOp("d", "m", f"op{i}", "",
                                  i * 1e-3, 1e-5)
               for i in range(4)]
        wins = profiling.invocation_windows(ops)
        assert len(wins) == 1
        assert wins[0].ops == 4

    def test_empty_capture(self):
        assert profiling.invocation_windows([]) == []

    def test_explicit_gap_threshold(self):
        wins = profiling.invocation_windows(self.ops(), gap_s=300e-6)
        assert len(wins) == 3
        # an explicit threshold above every gap keeps one window
        wins = profiling.invocation_windows(self.ops(), gap_s=1.0)
        assert len(wins) == 1

    def test_deterministic(self):
        a = profiling.invocation_windows(self.ops("bbbb02bbbb02"))
        b = profiling.invocation_windows(self.ops("bbbb02bbbb02"))
        assert [w.to_dict() for w in a] == [w.to_dict() for w in b]

    def test_back_to_back_dispatches_fall_back_to_count_floor(self):
        # two dispatches with ZERO idle between them: clustering
        # cannot separate, correlate() falls back to the op-count
        # floor for the invocation count
        ops = []
        for k in range(2):
            t = k * 200e-6
            ops.append(profiling.DeviceOp("d", "m", "dot", "",
                                          t, 100e-6))
            ops.append(profiling.DeviceOp("d", "m", "sort", "",
                                          t + 100e-6, 100e-6))
        assert len(profiling.invocation_windows(ops)) == 1
        attr = profiling.correlate(ops, {
            "x1": {"hlo_module": "m", "family": "f"}})
        assert attr.modules["x1"].invocations == 2


class TestMeasuredSupersedesModeled:
    """The acceptance criterion: with a capture present, mesh
    phase/shard spans re-emit ``modeled: False`` with device-measured
    windows, straggler gauges recompute from device timings, and
    ``metrics.derived()`` divides per-executable achieved GB/s by
    measured device time — pinned by the committed fixture."""

    def publish_fixture(self):
        metrics.reset()
        tracing.reset_gauges("serving.mesh.")
        return profiling.publish(fixture_attr())

    def test_mesh_spans_reemit_measured(self):
        self.publish_fixture()
        rec = tracing.span_recorder()
        (cs,) = rec.spans(name="serving.mesh.coarse_select")
        assert cs.attrs["modeled"] is False
        assert cs.attrs["source"] == "profiler"
        assert cs.attrs["family"] == "dist_ivf_flat"
        # device-measured window: mean per-invocation phase duration,
        # laid out from the capture window's start
        assert cs.start == pytest.approx(1000e-6, rel=1e-9)
        assert cs.duration == pytest.approx(200e-6, rel=1e-9)
        # the modeled wire bytes still ride along, over MEASURED time
        assert cs.attrs["wire_bytes"] == 2048
        (sc,) = rec.spans(name="serving.mesh.scan")
        assert sc.attrs["modeled"] is False
        assert sc.duration == pytest.approx(1000e-6, rel=1e-9)
        (mg,) = rec.spans(name="serving.mesh.merge")
        assert mg.attrs["wire_bytes"] == 512
        assert mg.duration == pytest.approx(100e-6, rel=1e-9)

    def test_shard_spans_and_straggler_gauges_from_device_time(self):
        dispatches = tracing.get_counter("serving.mesh.dispatches")
        self.publish_fixture()
        rec = tracing.span_recorder()
        shards = rec.spans(name="serving.mesh.shard")
        assert len(shards) == 2
        assert all(s.attrs["modeled"] is False for s in shards)
        assert all(s.attrs["source"] == "profiler" for s in shards)
        # mean per-invocation busy seconds per device: 550 / 750 us
        assert shards[0].duration == pytest.approx(550e-6, rel=1e-9)
        assert shards[1].duration == pytest.approx(750e-6, rel=1e-9)
        assert tracing.get_gauge(
            tracing.MESH_SHARD_SKEW) == pytest.approx(200e-6, rel=1e-9)
        assert tracing.get_gauge(tracing.MESH_SLOWEST_SHARD) == 1.0
        assert tracing.get_gauge(
            tracing.MESH_SHARD_TIME_MAX) == pytest.approx(750e-6,
                                                          rel=1e-9)
        # a re-attribution is not a new dispatch
        assert tracing.get_counter(
            "serving.mesh.dispatches") == dispatches
        # per-dispatch skew distribution (PR 12): both fixture
        # dispatches skew by exactly 200 us, so p50 == p99 == 200 us
        assert tracing.get_gauge(
            tracing.MESH_SHARD_SKEW_P99) == pytest.approx(200e-6,
                                                          rel=1e-9)
        assert tracing.get_gauge(
            tracing.MESH_SHARD_SKEW_P50) == pytest.approx(200e-6,
                                                          rel=1e-9)

    def test_derived_measured_columns(self):
        self.publish_fixture()
        d = metrics.derived()
        # totals: 810 us + 2600 us device time; 810 kB + 2.6 MB
        # modeled bytes over it -> exactly 1.0 GB/s device-truth
        assert d["measured_device_seconds_total"] == pytest.approx(
            3410e-6, rel=1e-9)
        assert d["device_achieved_gbps"] == pytest.approx(1.0, rel=1e-6)
        assert d["device_achieved_gflops"] == pytest.approx(2.0,
                                                            rel=1e-6)
        # per-executable measured view: achieved GB/s divides by THIS
        # executable's measured device seconds
        me = d["measured_executables"]
        assert me["aaaa01aaaa01"]["gbps"] == pytest.approx(1.0,
                                                           rel=1e-6)
        assert me["aaaa01aaaa01"]["device_seconds"] == pytest.approx(
            810e-6, rel=1e-9)
        assert me["aaaa01aaaa01"]["invocations"] == 3.0
        assert me["bbbb02bbbb02"]["gflops"] == pytest.approx(2.0,
                                                             rel=1e-6)
        # the wall-clock-derived numbers still sit next to them (zero
        # here — no execute histogram observations in this test), so
        # the two accountings are visibly separate surfaces
        assert "achieved_gbps" in d

    def test_publish_returns_stats_and_gauges(self):
        out = self.publish_fixture()
        assert out["bbbb02bbbb02"]["invocations"] == 2
        g = tracing.gauges("serving.executable.aaaa01aaaa01.")
        assert g["serving.executable.aaaa01aaaa01.measured_gbps"] == \
            pytest.approx(1.0, rel=1e-6)
        assert g["serving.executable.aaaa01aaaa01"
                 ".measured_device_seconds"] == pytest.approx(810e-6,
                                                              rel=1e-9)


XPLANE_FIXTURE = os.path.join(os.path.dirname(__file__), "data",
                              "graftfleet_capture.xplane.pb")


class TestXplaneIngestion:
    """graftfleet satellite: the stdlib protobuf wire-format reader
    for the XSpace subset, pinned by the committed device-free
    ``.xplane.pb`` sample (regenerate with
    ``scripts/make_xplane_fixture.py``) — whose logical content
    mirrors the chrome fixture's mesh module, so BOTH ingestion paths
    must produce the same attribution."""

    def test_fixture_parses_device_ops_only(self):
        ops = profiling.parse_xplane(XPLANE_FIXTURE)
        # the host plane's module-less python events are skipped;
        # both TPU planes' events parse (one plane interns the module
        # name through ref_value stats, the other carries str_value —
        # both resolution paths are in the committed bytes)
        assert len(ops) == 12
        assert {op.device for op in ops} == {"/device:TPU:0",
                                             "/device:TPU:1"}
        assert all(op.module == "jit_rt_dist_ivf_flat_bbbb02bbbb02"
                   for op in ops)
        assert {op.phase for op in ops} == set(profiling.PHASE_MARKERS)

    def test_xplane_attribution_matches_chrome(self):
        """The protobuf twin yields the SAME pinned mesh attribution
        as the chrome fixture — parse format must not leak into the
        numbers."""
        chrome = fixture_attr().modules["bbbb02bbbb02"]
        attr = profiling.attribute(
            XPLANE_FIXTURE,
            {"bbbb02bbbb02": FIXTURE_COSTS["bbbb02bbbb02"]})
        assert attr.trace_file == XPLANE_FIXTURE
        x = attr.modules["bbbb02bbbb02"]
        assert x.device_seconds == pytest.approx(chrome.device_seconds,
                                                 rel=1e-9)
        assert x.invocations == chrome.invocations == 2
        for marker in profiling.PHASE_MARKERS:
            assert x.phase_seconds[marker] == pytest.approx(
                chrome.phase_seconds[marker], rel=1e-9)
        assert x.shard_seconds == {
            d: pytest.approx(s, rel=1e-9)
            for d, s in chrome.shard_seconds.items()}
        assert x.measured_gbps() == pytest.approx(1.0, rel=1e-6)
        assert [w.skew for w in x.windows] == [
            pytest.approx(200e-6, rel=1e-9)] * 2

    def test_auto_selected_only_without_chrome_sidecar(self, tmp_path):
        import shutil

        # a capture dir holding ONLY an xplane file: auto-selected
        run = tmp_path / "plugins" / "profile" / "r1"
        run.mkdir(parents=True)
        shutil.copyfile(XPLANE_FIXTURE, str(run / "h.xplane.pb"))
        ops, path = profiling.load_ops(str(tmp_path))
        assert path == str(run / "h.xplane.pb")
        assert len(ops) == 12
        # the chrome path stays primary: once a sidecar exists, it
        # wins regardless of mtime order
        shutil.copyfile(FIXTURE, str(run / "h.trace.json"))
        os.utime(str(run / "h.xplane.pb"))     # xplane now newest
        ops, path = profiling.load_ops(str(tmp_path))
        assert path == str(run / "h.trace.json")
        assert len(ops) == 25
        # fresh_trace_file obeys the same preference
        before = profiling.trace_snapshot(str(tmp_path))
        os.utime(str(run / "h.xplane.pb"))
        assert profiling.fresh_trace_file(
            str(tmp_path), before) == str(run / "h.xplane.pb")

    def test_load_trace_stays_chrome_only(self, tmp_path):
        import shutil

        # load_trace must NEVER feed protobuf bytes to json.load: an
        # xplane-only directory stays the explicit "no chrome
        # capture" failure it always was, and an explicit .xplane.pb
        # path is rejected with a pointer at load_ops
        run = tmp_path / "plugins" / "profile" / "r1"
        run.mkdir(parents=True)
        shutil.copyfile(XPLANE_FIXTURE, str(run / "h.xplane.pb"))
        with pytest.raises(FileNotFoundError, match="load_ops"):
            profiling.load_trace(str(tmp_path))
        with pytest.raises(ValueError, match="load_ops"):
            profiling.load_trace(str(run / "h.xplane.pb"))

    def test_truncated_pb_is_an_error(self):
        with open(XPLANE_FIXTURE, "rb") as f:
            data = f.read()
        with pytest.raises(ValueError):
            profiling.parse_xplane(data[:len(data) // 2])

    def test_empty_dir_still_an_explicit_error(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            profiling.load_ops(str(tmp_path))


@pytest.fixture(scope="module")
def real_setup():
    rng = np.random.default_rng(7)
    x = rng.standard_normal((2048, 16)).astype(np.float32)
    q = rng.standard_normal((8, 16)).astype(np.float32)
    return {"x": x, "q": q,
            "ivf": ivf_flat.build(
                None, ivf_flat.IvfFlatIndexParams(n_lists=8), x)}


class TestRealExecutorAttribution:
    """Live-capture round trip: the digest-named modules correlate,
    and the zero-recompile / bit-identity regressions stay green with
    profiling armed and attribution enabled."""

    def test_module_names_unique_and_captured(self, real_setup):
        ex = SearchExecutor()
        p4 = ivf_flat.IvfFlatSearchParams(n_probes=4)
        p8 = ivf_flat.IvfFlatSearchParams(n_probes=8)
        q = real_setup["q"]
        ex.search(real_setup["ivf"], q, 5, params=p4)
        ex.search(real_setup["ivf"], q, 5, params=p8)
        costs = ex.executable_costs()
        mods = [info["hlo_module"] for info in costs.values()]
        # one distinct module name per executable — the correlation
        # identity graftflight stands on
        assert len(mods) == len(set(mods)) == 2
        for digest, info in costs.items():
            assert info["hlo_module"] == f"jit_rt_ivf_flat_{digest}"

    def test_capture_attribute_zero_recompile_bit_identity(
            self, real_setup, tmp_path):
        """The live round trip, driven through the graftfleet
        continuous scheduler at its DEFAULT duty cycle (PR 12): TWO
        real ``jax.profiler`` windows — each covering both a
        single-chip and a mesh executable — tick through
        ``ContinuousCapture``, so the digest-named modules correlate,
        the mesh entry re-emits measured ``modeled: False`` spans,
        the ``serving.attribution.rolling.*`` gauges populate from
        two distinct capture windows, and the zero-recompile +
        bit-identity regressions hold with mesh_trace, attribution,
        AND continuous capture enabled. (jax.profiler's stop_trace
        serializes session-accumulated state, so every in-suite
        capture costs real wall time — these two windows are the
        suite's real-capture budget.)"""
        import jax

        from raft_tpu.comms import local_comms
        from raft_tpu.distributed import ivf as dist_ivf
        from raft_tpu.serving import ContinuousCapture
        from raft_tpu.serving.harness import ManualClock

        tracing.install_xla_compile_listener()
        comms = local_comms()
        params = ivf_flat.IvfFlatIndexParams(n_lists=16)
        single = ivf_flat.build(None, params, real_setup["x"])
        dist = dist_ivf.build(None, comms, params, real_setup["x"])
        p = ivf_flat.IvfFlatSearchParams(n_probes=4)
        sp = ivf_flat.IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        ex = SearchExecutor(mesh_trace=True)
        q = real_setup["q"]
        d0, i0 = ex.search(real_setup["ivf"], q, 5, params=p)
        dm0, im0 = ivf_flat.search(None, sp, single, q, 5)
        dm1, im1 = ex.search(dist, q, 5, params=sp)

        def traffic_under_capture():
            # a real capture window with real traffic inside it — the
            # injected capture_fn stands in for the wall-clock sleep
            # (ManualClock owns the schedule; the capture is genuine)
            before = profiling.trace_snapshot(str(tmp_path))
            with tracing.capture(str(tmp_path)):
                for _ in range(2):
                    jax.block_until_ready(
                        ex.search(real_setup["ivf"], q, 5, params=p))
                    jax.block_until_ready(
                        ex.search(dist, q, 5, params=sp))
            return profiling.fresh_trace_file(str(tmp_path), before)

        clock = ManualClock()
        cc = ContinuousCapture(executor=ex, clock=clock,
                               capture_fn=traffic_under_capture)
        assert cc.config.capture_seconds / cc.config.period_s <= \
            cc.config.duty_cycle_budget      # the DEFAULT duty cycle
        tracing.reset_spans()
        snap1 = cc.tick()
        assert snap1 is not None and snap1["windows"] == 1
        clock.advance(cc.config.period_s)
        snap2 = cc.tick()
        assert snap2 is not None and snap2["windows"] == 2
        # both live windows correlated to BOTH resident executables
        digests = set(snap2["executables"])
        costs = ex.executable_costs()
        families = {costs[d]["family"] for d in digests}
        assert families == {"ivf_flat", "dist_ivf_flat"}
        for stats in snap2["executables"].values():
            assert stats["device_seconds"] > 0
            assert stats["invocations"] >= 1
        # the rolling gauges populated from >= 2 distinct windows
        assert tracing.get_gauge(
            profiling.ROLLING_PREFIX + "windows") == 2.0
        assert tracing.get_gauge(
            profiling.ROLLING_PREFIX + "device_seconds") > 0
        assert tracing.get_gauge(
            profiling.ROLLING_PREFIX + "gbps") > 0
        d = metrics.derived()
        assert d["rolling_windows"] == 2.0
        assert d["rolling_gbps"] > 0
        # measured mesh spans re-emitted modeled: False per window
        # (the CPU chrome export drops op scopes, so the measured
        # time lands in the honest "unattributed" phase — a TPU
        # capture's xplane carries the coarse_select/scan/merge
        # markers the distributed bodies plant via jax.named_scope)
        rec = tracing.span_recorder()
        meshspans = [s for s in rec.spans()
                     if s.name.startswith("serving.mesh.")
                     and s.attrs.get("modeled") is False]
        assert meshspans, "no measured mesh spans re-emitted"
        # continuous capture enabled changes nothing downstream: no
        # new compiles, bit-identical results — single-chip AND mesh
        before = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        d1, i1 = ex.search(real_setup["ivf"], q, 5, params=p)
        dm2, im2 = ex.search(dist, q, 5, params=sp)
        assert tracing.get_counter(tracing.XLA_COMPILE_COUNT) == before
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
        np.testing.assert_array_equal(np.asarray(im0), np.asarray(im2))
        np.testing.assert_array_equal(np.asarray(dm0), np.asarray(dm2))
        np.testing.assert_array_equal(np.asarray(im1), np.asarray(im2))


def burning_alert(clock, windows=(10.0, 100.0)):
    """A MultiBurnAlert driven into the firing state at the clock's
    now (all misses in both windows -> serving.slo.alert = 1)."""
    alert = metrics.MultiBurnAlert(MultiBurnConfig(
        short=SloConfig(window_s=windows[0]),
        long=SloConfig(window_s=windows[1])))
    for _ in range(5):
        alert.record(clock.now(), False)
    return alert


class TestFlightRecorder:
    def setup_method(self):
        metrics.reset()

    def test_multiburn_produces_exactly_one_rate_limited_bundle(self):
        clock = ManualClock()
        alert = burning_alert(clock)
        assert tracing.get_gauge(metrics.SLO_ALERT) == 1.0
        bundles0 = tracing.get_counter(flight_mod.INCIDENT_BUNDLES)
        fr = FlightRecorder(
            config=FlightConfig(cooldown_s=60.0, latency=None),
            clock=clock, capture_fn=lambda: None)
        b1 = fr.check()
        assert b1 is not None
        assert b1["triggers"] == ["multiburn_alert"]
        # still firing, but inside the cooldown: suppressed, counted
        sup0 = tracing.get_counter(flight_mod.INCIDENT_SUPPRESSED)
        clock.advance(1.0)
        assert fr.check() is None
        assert fr.check() is None
        assert tracing.get_counter(
            flight_mod.INCIDENT_SUPPRESSED) == sup0 + 2
        assert tracing.get_counter(
            flight_mod.INCIDENT_BUNDLES) == bundles0 + 1
        assert fr.latest() is b1
        # past the cooldown, with the outage still burning (fresh
        # misses keep both windows over budget), a second incident
        # may capture
        clock.advance(60.0)
        for _ in range(5):
            alert.record(clock.now(), False)
        assert tracing.get_gauge(metrics.SLO_ALERT) == 1.0
        b2 = fr.check()
        assert b2 is not None and b2["incident"] == 2
        assert len(fr.bundles()) == 2

    def test_quiet_service_never_triggers(self):
        clock = ManualClock()
        fr = FlightRecorder(config=FlightConfig(latency=None),
                            clock=clock, capture_fn=lambda: None)
        assert fr.check() is None
        assert fr.latest() is None

    def test_latency_anomaly_windowed(self):
        clock = ManualClock()
        cfg = FlightConfig(
            cooldown_s=30.0,
            latency=LatencyAnomaly(p99_threshold_s=0.5, min_count=4))
        # histogram history BEFORE the recorder attaches must not be
        # re-judged: the baseline primes at construction
        for _ in range(10):
            metrics.observe_stage(metrics.E2E, 2.0)
        fr = FlightRecorder(config=cfg, clock=clock,
                            capture_fn=lambda: None)
        assert fr.check() is None
        # a fresh stall: 6 slow observations in the window
        for _ in range(6):
            metrics.observe_stage(metrics.E2E, 1.0)
        b = fr.check()
        assert b is not None and b["triggers"] == ["latency_anomaly"]
        # the window advanced: the SAME observations are judged once
        clock.advance(31.0)
        assert fr.check() is None
        # below min_count: a lone slow request is noise, not a page
        metrics.observe_stage(metrics.E2E, 5.0)
        assert fr.check() is None
        # fast traffic dominating the window keeps p99 low
        for _ in range(100):
            metrics.observe_stage(metrics.E2E, 0.001)
        assert fr.check() is None

    def test_window_quantile_pure(self):
        bounds = [0.001, 0.01, 0.1]
        # 10 obs in bucket 0, 0, 0 -> all mass at/below 1 ms
        assert flight_mod.window_quantile(
            bounds, [10, 10, 10, 10], 0.99) <= 0.001
        # all mass in the overflow bucket -> estimated in (0.1, 0.2]
        v = flight_mod.window_quantile(bounds, [0, 0, 0, 5], 0.99)
        assert 0.1 < v <= 0.2
        assert flight_mod.window_quantile(bounds, [0, 0, 0, 0],
                                          0.99) == 0.0

    def test_bundle_contents_and_disk_persistence(self, tmp_path):
        clock = ManualClock()
        burning_alert(clock)
        fake = FakeExecutor()
        b = DynamicBatcher(fake, BatcherConfig(max_wait_s=0.0),
                           clock=clock, start=False)
        ex = SearchExecutor()
        fr = FlightRecorder(
            executor=ex, batcher=b,
            config=FlightConfig(cooldown_s=60.0, latency=None,
                                bundle_dir=str(tmp_path)),
            clock=clock,
            capture_fn=lambda: profiling.load_trace(FIXTURE))
        bundle = fr.check()
        b.close()
        assert bundle is not None
        # the bundle carries everything the post-mortem needs
        for key in ("incident", "time", "triggers", "slo", "metrics",
                    "spans", "span_ring", "attribution", "executables",
                    "shed_level", "queue_depth"):
            assert key in bundle, key
        assert bundle["time"] == clock.now()
        assert bundle["shed_level"] == 0
        # the injected fixture capture was parsed; no resident
        # executable matches it, so modules is empty but the unmatched
        # accounting says what the trace held
        assert bundle["attribution"] is not None
        assert bundle["attribution"]["unmatched_modules"]
        # persisted to disk as JSON, path recorded in the bundle
        path = bundle["bundle_path"]
        assert os.path.dirname(path) == str(tmp_path)
        with open(path) as f:
            on_disk = json.load(f)
        assert on_disk["incident"] == bundle["incident"]
        assert on_disk["triggers"] == ["multiburn_alert"]

    def test_busy_profiler_defers_without_burning_cooldown(self):
        import threading

        clock = ManualClock()
        burning_alert(clock)
        fr = FlightRecorder(
            config=FlightConfig(cooldown_s=60.0, latency=None),
            clock=clock, capture_fn=lambda: None)
        # an operator's /profile capture owns the profiler (the
        # exporter wires its _profile_lock into the recorder)
        fr.profile_lock = threading.Lock()
        deferred0 = tracing.get_counter("incident.trigger"
                                        ".multiburn_alert")
        with fr.profile_lock:
            assert fr.check() is None          # deferred, not burned
        assert tracing.get_counter("incident.deferred") >= 1
        # the cooldown was NOT consumed: the very next check captures
        bundle = fr.check()
        assert bundle is not None and bundle["incident"] == 1
        assert deferred0 >= 0  # trigger counters kept counting

    def test_capture_without_fresh_trace_yields_no_source(
            self, tmp_path, monkeypatch):
        import contextlib
        import shutil

        # a STALE capture already sits in profile_dir; the incident's
        # own capture writes nothing — the recorder must not attribute
        # the stale file as current evidence
        run = tmp_path / "plugins" / "profile" / "old"
        run.mkdir(parents=True)
        shutil.copyfile(FIXTURE, str(run / "host.trace.json"))

        @contextlib.contextmanager
        def empty_capture(log_dir):
            yield                              # no trace written

        monkeypatch.setattr(tracing, "capture", empty_capture)
        clock = ManualClock()
        burning_alert(clock)
        fr = FlightRecorder(
            executor=SearchExecutor(),
            config=FlightConfig(cooldown_s=60.0, latency=None),
            clock=clock, profile_dir=str(tmp_path))
        bundle = fr.check()
        assert bundle is not None
        assert bundle["attribution"] is None
        assert bundle["trace_file"] is None

    def test_capture_failure_still_bundles(self):
        clock = ManualClock()
        burning_alert(clock)

        def bad_capture():
            raise RuntimeError("profiler unavailable")

        fr = FlightRecorder(
            config=FlightConfig(cooldown_s=60.0, latency=None),
            clock=clock, capture_fn=bad_capture)
        bundle = fr.check()
        assert bundle is not None
        assert bundle["attribution"] is None
        assert "profiler unavailable" in bundle["capture_error"]


class TestExporterGraftflight:
    """Exporter hardening satellite: /incident.json + /profile
    responses parse-checked, and the scrape refresh drives the
    recorder's triggers."""

    def setup_method(self):
        metrics.reset()

    def _get(self, url):
        # generous timeout: /profile runs a real jax.profiler capture,
        # and stop_trace serializes every thread's python events —
        # tens of seconds under a loaded full-suite session
        try:
            with urllib.request.urlopen(url, timeout=120) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    def test_incident_endpoint_404_then_bundle(self):
        clock = ManualClock()
        fr = FlightRecorder(
            config=FlightConfig(cooldown_s=60.0, latency=None),
            clock=clock, capture_fn=lambda: None)
        with MetricsExporter(flight=fr) as exp:
            code, _ = self._get(exp.url("/incident.json"))
            assert code == 404
            burning_alert(clock)
            # the scrape refresh evaluates the triggers: one /metrics
            # pull arms and captures the incident...
            code, _ = self._get(exp.url("/metrics"))
            assert code == 200
            code, body = self._get(exp.url("/incident.json"))
            assert code == 200
            bundle = json.loads(body)
            # ...and the response parses field by field
            assert bundle["incident"] == 1
            assert bundle["triggers"] == ["multiburn_alert"]
            assert isinstance(bundle["metrics"], dict)
            assert "counters" in bundle["metrics"]
            assert isinstance(bundle["spans"], dict)
            assert "traceEvents" in bundle["spans"]
            assert bundle["span_ring"]["capacity"] > 0
            # exactly ONE bundle however many scrapes raced the alert
            self._get(exp.url("/metrics"))
            code, body2 = self._get(exp.url("/incident.json"))
            assert json.loads(body2)["incident"] == 1

    def test_no_flight_attached_404(self):
        with MetricsExporter() as exp:
            code, _ = self._get(exp.url("/incident.json"))
            assert code == 404

    def test_profile_returns_trace_file(self, tmp_path, monkeypatch):
        import contextlib
        import shutil

        # a layout-faithful fake capture: jax.profiler's stop_trace
        # serializes session-accumulated state, which costs ~a minute
        # late in a full test session — the REAL capture is proven by
        # TestRealExecutorAttribution (direct) and the core capture
        # smoke; this test pins OUR plumbing (trace-file resolution +
        # the response contract) against the profiler's disk layout
        @contextlib.contextmanager
        def fake_capture(log_dir):
            run = os.path.join(log_dir, "plugins", "profile", "r1")
            os.makedirs(run, exist_ok=True)
            shutil.copyfile(FIXTURE,
                            os.path.join(run, "host.trace.json"))
            yield

        monkeypatch.setattr(tracing, "capture", fake_capture)
        with MetricsExporter(profile_dir=str(tmp_path)) as exp:
            code, body = self._get(exp.url("/profile?seconds=0.05"))
            assert code == 200
            out = json.loads(body)
            assert set(out) == {"log_dir", "seconds", "trace_file"}
            assert out["log_dir"] == str(tmp_path)
            assert out["seconds"] == 0.05
            # the path points at the capture that was just written,
            # inside profile_dir — exactly what graftflight ingests
            assert out["trace_file"] is not None
            assert os.path.exists(out["trace_file"])
            assert out["trace_file"].startswith(str(tmp_path))
            assert profiling.parse_chrome_trace(
                profiling.load_trace(out["trace_file"]))


class TestParamsClassLatency:
    """Per-params-class latency labels (graftgauge carried follow-on):
    serving.execute histograms gain a params-class label pairing the
    params-sweep recall gauges with a latency axis."""

    def setup_method(self):
        metrics.reset()

    def test_params_class_label(self):
        assert metrics.params_class(
            ivf_flat.IvfFlatSearchParams(n_probes=12)) == "p12"
        assert metrics.params_class(None) is None
        assert metrics.params_class(object()) is None

    def test_dispatch_observes_class_histogram(self):
        clock = ManualClock()
        fake = FakeExecutor()
        b = DynamicBatcher(fake, BatcherConfig(max_wait_s=0.0),
                           clock=clock, start=False)
        idx = object()
        p = ivf_flat.IvfFlatSearchParams(n_probes=6)
        qb = np.zeros((2, 4), np.float32)
        b.submit(idx, qb, 3, params=p)
        b.pump()
        b.submit(idx, qb, 3)            # no params -> unlabeled only
        b.pump()
        b.close()
        h = tracing.histograms(metrics.EXECUTE)
        assert h[metrics.EXECUTE]["count"] == 2
        assert h[f"{metrics.EXECUTE}.p6"]["count"] == 1

    def test_class_label_cardinality_capped(self):
        # n_probes is client-supplied: past the cap a NEW label lands
        # only in the unlabeled aggregate (counted), so an autotuner
        # sweeping arbitrary values cannot grow /metrics unboundedly
        for i in range(metrics.EXECUTE_CLASS_CAP + 5):
            metrics.observe_execute_class(f"p{i + 1}", 0.001)
        h = tracing.histograms(metrics.EXECUTE + ".")
        assert len(h) == metrics.EXECUTE_CLASS_CAP
        assert tracing.get_counter(
            metrics.PREFIX + "execute_class_dropped") == 5.0
        # known labels keep observing past the cap
        metrics.observe_execute_class("p1", 0.002)
        assert tracing.get_histogram(
            f"{metrics.EXECUTE}.p1").snapshot()["count"] == 2
        # reset() clears the cap set along with the histograms
        metrics.reset()
        metrics.observe_execute_class("p99", 0.001)
        assert tracing.get_histogram(
            f"{metrics.EXECUTE}.p99").snapshot()["count"] == 1

    def test_ragged_dispatch_observes_each_class_once(self):
        clock = ManualClock()
        fake = FakeExecutor(ragged_tile=8)
        b = DynamicBatcher(fake, BatcherConfig(max_wait_s=0.0,
                                               ragged=True),
                           clock=clock, start=False)
        idx = object()
        qb = np.zeros((2, 4), np.float32)
        # two requests with DIFFERENT n_probes pack into one tile;
        # the shared execute latency lands once per distinct class
        b.submit(idx, qb, 3,
                 params=ivf_flat.IvfFlatSearchParams(n_probes=4))
        b.submit(idx, qb, 3,
                 params=ivf_flat.IvfFlatSearchParams(n_probes=8))
        b.pump()
        b.close()
        h = tracing.histograms(metrics.EXECUTE)
        assert h[f"{metrics.EXECUTE}.p4"]["count"] == 1
        assert h[f"{metrics.EXECUTE}.p8"]["count"] == 1

    def test_exposition_renders_labeled_histogram_family(self):
        import re

        clock = ManualClock()
        fake = FakeExecutor()
        b = DynamicBatcher(fake, BatcherConfig(max_wait_s=0.0),
                           clock=clock, start=False)
        b.submit(object(), np.zeros((2, 4), np.float32), 3,
                 params=ivf_flat.IvfFlatSearchParams(n_probes=6))
        b.pump()
        exp = MetricsExporter(batcher=b)
        text = exp.prometheus_text()
        b.close()
        # ONE family declaration, labeled AND unlabeled samples in it
        assert text.count(
            "# TYPE serving_batcher_execute_seconds histogram") == 1
        assert re.search(
            r'serving_batcher_execute_seconds_bucket'
            r'\{params_class="p6",le="[^"]+"\} \d+', text)
        assert ('serving_batcher_execute_seconds_count'
                '{params_class="p6"} 1') in text
        assert re.search(
            r"^serving_batcher_execute_seconds_count 1$", text,
            flags=re.M)
        # every line still parses against the exposition grammar
        sample_re = re.compile(
            r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
            r'(\{[a-zA-Z_]+="[^"]*"(,[a-zA-Z_]+="[^"]*")*\})? '
            r"[-+0-9.e]+$")
        for line in text.strip().splitlines():
            if not line.startswith("#"):
                assert sample_re.match(line), line
