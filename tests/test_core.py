"""Core runtime tests (analog of reference cpp/test/core/)."""

import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import Resources
from raft_tpu.core import (
    Bitset,
    deserialize_array,
    deserialize_scalar,
    serialize_array,
    serialize_scalar,
)
from raft_tpu.core import interruptible
from raft_tpu.core.serialize import check_version
from raft_tpu.core.validation import RaftError, check_matrix, expect


class TestResources:
    def test_next_key_unique(self):
        res = Resources(seed=1)
        k1, k2 = res.next_key(), res.next_key()
        assert not np.array_equal(jax.random.key_data(k1), jax.random.key_data(k2))

    def test_next_key_batch(self):
        res = Resources(seed=1)
        keys = res.next_key(4)
        assert keys.shape[0] == 4

    def test_reproducible(self):
        a = Resources(seed=7).next_key()
        b = Resources(seed=7).next_key()
        assert np.array_equal(jax.random.key_data(a), jax.random.key_data(b))

    def test_sync(self):
        res = Resources()
        x = jnp.ones((8,))
        res.sync(x)
        res.sync()

    def test_subcomm(self):
        res = Resources()
        res.set_subcomm("row", "fake")
        assert res.get_subcomm("row") == "fake"


class TestSerialize:
    def test_array_roundtrip(self, rng_np):
        buf = io.BytesIO()
        arr = rng_np.standard_normal((5, 3)).astype(np.float32)
        serialize_array(buf, jnp.asarray(arr))
        buf.seek(0)
        out = deserialize_array(buf)
        np.testing.assert_array_equal(out, arr)

    @pytest.mark.parametrize("dtype_name", ["bfloat16", "float8_e4m3fn"])
    def test_extension_dtype_roundtrip(self, rng_np, dtype_name):
        """ml_dtypes arrays (bf16 datasets, fp8) have no .npy descr —
        they ride as a marker record + uint view and come back typed."""
        dtype = getattr(jnp, dtype_name)
        buf = io.BytesIO()
        arr = jnp.asarray(rng_np.standard_normal((6, 4)), dtype)
        serialize_array(buf, arr)
        buf.seek(0)
        out = deserialize_array(buf)
        assert out.dtype == np.dtype(dtype_name)
        np.testing.assert_array_equal(out, np.asarray(arr))

    def test_bf16_brute_force_index_roundtrip(self, rng_np):
        """The end-to-end case that was broken: a bf16-storage index
        must save/load (previously died with 'Dtype |V2')."""
        from raft_tpu.neighbors import brute_force

        x = rng_np.standard_normal((64, 16)).astype(np.float32)
        idx = brute_force.build(None, x, storage_dtype=jnp.bfloat16)
        buf = io.BytesIO()
        brute_force.save(idx, buf)
        buf.seek(0)
        idx2 = brute_force.load(None, buf)
        assert idx2.dataset.dtype == jnp.bfloat16
        np.testing.assert_array_equal(np.asarray(idx2.dataset),
                                      np.asarray(idx.dataset))

    def test_scalar_roundtrip(self):
        buf = io.BytesIO()
        serialize_scalar(buf, 42, np.int64)
        serialize_scalar(buf, 2.5, np.float32)
        buf.seek(0)
        assert deserialize_scalar(buf) == 42
        assert deserialize_scalar(buf) == np.float32(2.5)

    def test_stream_of_records(self, rng_np):
        buf = io.BytesIO()
        serialize_scalar(buf, 4, np.int32)  # version
        a = rng_np.random((4, 4)).astype(np.float32)
        serialize_array(buf, a)
        buf.seek(0)
        assert deserialize_scalar(buf) == 4
        np.testing.assert_array_equal(deserialize_array(buf), a)

    def test_check_version(self):
        check_version(3, 3, "x")
        with pytest.raises(ValueError):
            check_version(2, 3, "x")


class TestBitset:
    def test_default_all_set(self):
        bs = Bitset.create(70)
        assert int(bs.count()) == 70
        assert bool(bs.test(69))

    def test_from_mask_roundtrip(self, rng_np):
        mask = rng_np.random(100) < 0.5
        bs = Bitset.from_mask(mask)
        np.testing.assert_array_equal(np.asarray(bs.to_mask()), mask)
        assert int(bs.count()) == mask.sum()

    def test_vectorized_test(self, rng_np):
        mask = rng_np.random(64) < 0.5
        bs = Bitset.from_mask(mask)
        idx = jnp.array([0, 5, 63])
        np.testing.assert_array_equal(np.asarray(bs.test(idx)), mask[[0, 5, 63]])

    def test_set_flip(self):
        bs = Bitset.create(40, default=False)
        bs = bs.set(jnp.array([1, 3]))
        assert int(bs.count()) == 2
        flipped = bs.flip()
        assert int(flipped.count()) == 38

    def test_jit_through(self):
        bs = Bitset.from_mask(jnp.array([True, False, True]))

        @jax.jit
        def f(b):
            return b.count()

        assert int(f(bs)) == 2


class TestValidation:
    def test_expect(self):
        expect(True, "ok")
        with pytest.raises(RaftError):
            expect(False, "bad")

    def test_check_matrix(self):
        check_matrix(jnp.ones((3, 4)), cols=4)
        with pytest.raises(RaftError):
            check_matrix(jnp.ones((3,)))


class TestInterruptible:
    def test_yield_no_flag(self):
        interruptible.yield_()  # no-op

    def test_cancel_then_yield(self):
        interruptible.cancel()
        with pytest.raises(interruptible.InterruptedException):
            interruptible.yield_()
        interruptible.yield_()  # flag cleared

    def test_synchronize(self):
        interruptible.synchronize(jnp.ones((4,)))


class TestOperators:
    """core/operators.hpp functor vocabulary."""

    def test_basic_ops(self):
        import jax.numpy as jnp

        from raft_tpu.core import operators as op

        x = jnp.float32(-3.0)
        assert float(op.sq_op(x)) == 9.0
        assert float(op.abs_op(x)) == 3.0
        assert float(op.nz_op(jnp.float32(0.0))) == 0.0
        assert float(op.compose_op(op.sqrt_op, op.sq_op)(x)) == 3.0
        assert float(op.div_checkzero_op(jnp.float32(4), jnp.float32(0))) == 0
        assert float(op.plug_const_op(2.0, op.pow_op)(jnp.float32(3))) == 9.0
        assert op.key_op((1, 2.5)) == 1 and op.value_op((1, 2.5)) == 2.5
        add3 = op.map_args_op(op.add_op, op.sq_op, op.identity_op)
        assert float(add3(jnp.float32(2), jnp.float32(1))) == 5.0


class TestSpatialAlias:
    def test_deprecated_forwarding(self):
        import warnings

        import numpy as np

        from raft_tpu.spatial import knn as spatial_knn

        x = np.random.default_rng(0).standard_normal((50, 8)).astype(np.float32)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            d, i = spatial_knn.brute_force_knn(None, x, x[:4], 3)
            assert any(issubclass(x.category, DeprecationWarning) for x in w)
        assert np.asarray(i)[:, 0].tolist() == [0, 1, 2, 3]


class TestTracingCapture:
    def test_capture_writes_trace(self, tmp_path):
        import jax.numpy as jnp

        from raft_tpu.core import tracing

        with tracing.capture(str(tmp_path)):
            with tracing.range("test.block"):
                jnp.square(jnp.arange(16.0)).block_until_ready()
        # a plugins/profile dir with at least one artifact appears
        found = list(tmp_path.rglob("*"))
        assert any(p.is_file() for p in found), found


class TestInterop:
    """Array-interop parity with pylibraft.common's cai/ai wrappers
    (``common/cai_wrapper.py:21,43``): any ``__array_interface__`` /
    dlpack producer — numpy, torch (cpu) — is accepted by the public
    APIs without copies being forced on the caller."""

    def test_torch_tensor_inputs(self):
        torch = pytest.importorskip("torch")
        import numpy as np

        from raft_tpu.neighbors import brute_force

        t = torch.randn(64, 8, dtype=torch.float32)
        q = t[:4]
        d, i = brute_force.knn(None, t, q, 3)
        assert np.asarray(i)[:, 0].tolist() == [0, 1, 2, 3]

    def test_numpy_and_jax_mixed(self):
        import numpy as np
        import jax.numpy as jnp

        from raft_tpu.distance import pairwise_distance

        x = np.random.default_rng(0).standard_normal((8, 4)).astype(np.float32)
        out = pairwise_distance(None, x, jnp.asarray(x))
        assert np.allclose(np.asarray(out).diagonal(), 0.0, atol=1e-4)
