"""CI guard for the Pallas kernel library: every ``pallas_call`` kernel
under ``raft_tpu/ops/`` must keep an interpret-mode test reference, so
CPU CI always validates kernel numerics even though Mosaic only
compiles on real TPUs (the convention every existing kernel follows:
a public entry point with an ``interpret`` keyword, exercised by some
test with ``interpret=True``)."""

import ast
import pathlib

import raft_tpu.ops

OPS_DIR = pathlib.Path(raft_tpu.ops.__file__).parent
TESTS_DIR = pathlib.Path(__file__).parent


def _public_kernel_entries(src: str):
    """Public module-level functions exposing an ``interpret`` knob —
    the kernel-entry convention of this package."""
    tree = ast.parse(src)
    out = []
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        if node.name.startswith("_"):
            continue
        args = node.args
        names = {a.arg for a in args.args + args.kwonlyargs}
        if "interpret" in names:
            out.append(node.name)
    return out


def _interpret_true_calls(src: str):
    """Names called with a literal ``interpret=True`` keyword — a
    docstring or comment mention cannot satisfy the guard, only an
    actual interpret-mode call site."""
    out = set()
    for node in ast.walk(ast.parse(src)):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = (fn.id if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute) else None)
        if name is None:
            continue
        for kw in node.keywords:
            if (kw.arg == "interpret"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True):
                out.add(name)
    return out


def test_every_pallas_kernel_has_interpret_reference():
    covered = set()
    for p in sorted(TESTS_DIR.glob("test_*.py")):
        if p.name == pathlib.Path(__file__).name:
            continue
        covered |= _interpret_true_calls(p.read_text())
    missing = []
    for mod in sorted(OPS_DIR.glob("*.py")):
        src = mod.read_text()
        if "pl.pallas_call(" not in src:
            continue
        entries = _public_kernel_entries(src)
        if not entries:
            missing.append(
                f"{mod.name}: contains pallas_call but exposes no public "
                "entry with an `interpret` parameter")
            continue
        for name in entries:
            if name not in covered:
                missing.append(
                    f"{mod.name}:{name}: no test calls it with "
                    "interpret=True — add an interpret-mode parity test")
    assert not missing, (
        "Pallas kernels without interpret-mode CPU coverage:\n  "
        + "\n  ".join(missing))


def test_known_kernels_are_detected():
    """The walker itself must see the kernels we know exist — if the
    entry convention drifts, this fails before the guard silently
    stops guarding."""
    found = set()
    for mod in sorted(OPS_DIR.glob("*.py")):
        src = mod.read_text()
        if "pl.pallas_call(" in src:
            found.update(_public_kernel_entries(src))
    for expected in ("fused_knn", "select_k_tiles", "stream_read_sum",
                     "beam_search", "list_major_scan"):
        assert expected in found, expected
