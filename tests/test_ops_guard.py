"""CI guard for the Pallas kernel library — thin pytest wrapper over
graftlint rule R6 (``raft_tpu.analysis.rules_pallas``), so ops guarding
and linting share one traversal: every ``pallas_call`` kernel under
``raft_tpu/ops/`` must keep an interpret-mode test reference, and CPU
CI always validates kernel numerics even though Mosaic only compiles
on real TPUs."""

import pathlib

from raft_tpu.analysis import Project, run
from raft_tpu.analysis.rules_pallas import public_kernel_entries

ROOT = pathlib.Path(__file__).resolve().parents[1]


def test_every_pallas_kernel_has_interpret_reference():
    report = run(Project.from_root(ROOT), rules=["R6"])
    assert report.ok, (
        "Pallas kernels without interpret-mode CPU coverage:\n  "
        + "\n  ".join(f.render() for f in report.findings))


def test_known_kernels_are_detected():
    """The walker itself must see the kernels we know exist — if the
    entry convention drifts, this fails before the guard silently
    stops guarding."""
    entries = public_kernel_entries(Project.from_root(ROOT))
    found = {name for names in entries.values() for name in names}
    for expected in ("fused_knn", "select_k_tiles", "stream_read_sum",
                     "beam_search", "list_major_scan"):
        assert expected in found, expected
