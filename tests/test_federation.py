"""graftfleet federation tests (PR 12) — multi-replica merge
semantics, pinned byte-exactly by the committed 3-replica snapshot
fixtures (``tests/data/fleet_replica_r{0,1,2}.json``).

The acceptance criteria this file carries: the aggregator reproduces
the fixture fleet sums, the pooled-trials Wilson CI, and the fleet
probe-coverage exactly, serves them at ``/fleet.json``, and renders a
``replica=``-labeled + fleet-aggregate Prometheus exposition; a
mid-scrape counter reset can NEVER make a fleet counter go backwards
(lifetime ledger + high-water monotonicity assertion); a stale
replica drops from windowed surfaces while its cumulative
contributions are retained.
"""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from raft_tpu.core import tracing
from raft_tpu.serving import (
    DriftDetector,
    FleetAggregator,
    FleetConfig,
    IndexGauge,
    MetricsExporter,
)
from raft_tpu.serving import federation as fed_mod
from raft_tpu.serving import metrics
from raft_tpu.serving.gauge import wilson_interval
from raft_tpu.serving.harness import ManualClock

DATA = os.path.join(os.path.dirname(__file__), "data")


def load_replica(name):
    with open(os.path.join(DATA, f"fleet_replica_{name}.json")) as f:
        return json.load(f)


def fixture_fetch(url, timeout):
    for name in ("r0", "r1", "r2"):
        if f"//{name}/" in url:
            return load_replica(name)
    raise ValueError(f"unknown fixture url {url!r}")


def fixture_aggregator(clock=None, **kw):
    return FleetAggregator(
        {n: f"http://{n}/snapshot.json" for n in ("r0", "r1", "r2")},
        clock=clock or ManualClock(), fetch=fixture_fetch, **kw)


class TestFixturePinnedMerge:
    def setup_method(self):
        metrics.reset()
        tracing.reset_gauges("fleet.")

    def merged(self):
        agg = fixture_aggregator()
        scrapes0 = tracing.get_counter(fed_mod.SCRAPES)
        out = agg.fleet_snapshot()
        assert tracing.get_counter(fed_mod.SCRAPES) == scrapes0 + 1
        return out

    def test_replica_health(self):
        out = self.merged()
        assert out["size"] == 3 and out["healthy"] == 3
        for name in ("r0", "r1", "r2"):
            r = out["replicas"][name]
            assert r["healthy"] and r["errors"] == 0
            assert r["age_s"] == 0.0

    def test_fleet_counter_sums_from_lifetime_ledger(self):
        out = self.merged()
        c = out["counters"]
        # the LIFETIME values sum (r0's live view says 10 — a
        # mid-session reset folded 90 into its ledger; the fleet
        # number must be the reset-proof 100 + 200 + 50)
        assert c["serving.execute.calls"] == 350.0
        assert c["serving.slo.missed"] == 6.0
        assert c["index.probe.dispatches"] == 60.0

    def test_histograms_merge_bucket_wise(self):
        h = self.merged()["histograms"]["serving.batcher.e2e_seconds"]
        assert h["count"] == 9
        assert h["sum"] == pytest.approx(0.5105)
        assert h["bucket_counts"] == [3, 6, 8, 9]
        assert h["replicas"] == 3
        # quantiles recompute from the MERGED distribution — never
        # averaged per-replica quantiles
        assert h["p50"] == pytest.approx(0.0055)
        assert h["p95"] == pytest.approx(0.155)
        assert h["p99"] == pytest.approx(0.191)

    def test_fleet_probe_coverage_exact(self):
        pf = self.merged()["probe_freq"]["ivf:0"]
        # summed plane [100, 5, 5, 0, 10, 0, 0, 5]
        assert pf["total"] == 125
        assert pf["probed_fraction"] == pytest.approx(5 / 8)
        assert pf["coverage_p01"] == pytest.approx(0.8)
        assert pf["coverage_p10"] == pytest.approx(0.8)
        assert pf["top"][0] == (0, 100)
        assert tracing.get_gauge(
            "fleet.probe_freq.ivf:0.coverage_p01") == \
            pytest.approx(0.8)

    def test_recall_pools_trials_before_wilson(self):
        rec = self.merged()["recall"]
        live = rec["live"]
        assert (live["hits"], live["trials"], live["pairs"]) == \
            (315, 350, 32)
        assert live["estimate"] == pytest.approx(0.9)
        lo, hi = wilson_interval(315, 350)
        assert live["ci_low"] == pytest.approx(lo)
        assert live["ci_high"] == pytest.approx(hi)
        # pooling strictly tightens: the fleet CI is narrower than
        # the smallest replica's own window could support
        lo2, hi2 = wilson_interval(45, 50)
        assert hi - lo < hi2 - lo2
        # a sweep leg present on one replica still federates
        assert rec["sweep.p8"]["trials"] == 10
        assert tracing.get_gauge("fleet.recall.estimate") == \
            pytest.approx(0.9)

    def test_drift_rescores_pooled_histogram(self):
        drift = self.merged()["drift"]
        # traffic-weighted pooled live (40x + 20x uniform) is EXACTLY
        # proportional to the pooled baseline [30,30,30,30]: zero
        # drift however each replica's own window wiggled
        assert drift["main"]["score"] == pytest.approx(0.0)
        assert drift["main"]["replicas"] == 3
        # every replica skewed the same way: whatever the traffic
        # weights, the pooled distribution is [1, 0] and the score is
        # the JSD of it vs the pooled baseline [30, 30]
        expect = tracing.js_divergence([24.0, 0.0], [30.0, 30.0])
        assert drift["skew"]["score"] == pytest.approx(expect)
        assert drift["skew"]["score"] == pytest.approx(0.311278,
                                                       abs=1e-6)

    def test_drift_pooling_weighs_by_traffic_share(self):
        # a drifted replica carrying 99% of fleet traffic must NOT be
        # averaged away by an idle healthy peer: each replica's live
        # histogram is normalized, so the pool scales by ``traffic``
        def snap(live, traffic):
            return {"federation": {"drift": {"ix": {
                "baseline": [50, 50], "live": live,
                "traffic": traffic, "score": 0.0, "updates": 1}}}}

        payload = {"http://busy/snapshot.json": snap([1.0, 0.0], 99.0),
                   "http://idle/snapshot.json": snap([0.5, 0.5], 1.0)}
        agg = FleetAggregator({"busy": "http://busy/",
                               "idle": "http://idle/"},
                              clock=ManualClock(),
                              fetch=lambda url, t: payload[url])
        score = agg.fleet_snapshot()["drift"]["ix"]["score"]
        assert score == pytest.approx(
            tracing.js_divergence([99.5, 0.5], [100.0, 100.0]))
        # equal-weight fallback for payloads predating the weight
        for s in payload.values():
            del s["federation"]["drift"]["ix"]["traffic"]
        agg2 = FleetAggregator({"busy": "http://busy/",
                                "idle": "http://idle/"},
                               clock=ManualClock(),
                               fetch=lambda url, t: payload[url])
        score2 = agg2.fleet_snapshot()["drift"]["ix"]["score"]
        assert score2 == pytest.approx(
            tracing.js_divergence([1.5, 0.5], [100.0, 100.0]))
        # traffic weighting makes the busy drifted replica dominate:
        # MORE fleet drift detected than the averaged-away pool shows
        assert score > score2

    def test_admission_rollup(self):
        adm = self.merged()["admission"]
        assert adm["queue_depth"] == 5.0
        assert adm["arrival_rate_hz"] == 15.0
        assert adm["max_shed_level"] == 1


class TestMonotonicity:
    def setup_method(self):
        metrics.reset()

    def test_mid_scrape_reset_cannot_regress_fleet_counter(self):
        payloads = [
            {"counters_lifetime": {"serving.execute.calls": 100.0}},
            # a replica restart zeroed its ledger mid-scrape
            {"counters_lifetime": {"serving.execute.calls": 40.0}},
            {"counters_lifetime": {"serving.execute.calls": 60.0}},
        ]
        seq = iter(payloads)
        agg = FleetAggregator(["http://a"], clock=ManualClock(),
                              fetch=lambda url, t: next(seq))
        v0 = tracing.get_counter(fed_mod.MONOTONICITY_VIOLATIONS)
        assert agg.fleet_snapshot()["counters"][
            "serving.execute.calls"] == 100.0
        out = agg.fleet_snapshot()
        # clamped to the high-water mark — asserted monotone — and
        # the violation is counted, not silent
        assert out["counters"]["serving.execute.calls"] == 100.0
        assert tracing.get_counter(
            fed_mod.MONOTONICITY_VIOLATIONS) == v0 + 1
        # recovery below the mark still cannot move the fleet down
        assert agg.fleet_snapshot()["counters"][
            "serving.execute.calls"] == 100.0

    def test_live_counters_fallback_for_old_payloads(self):
        agg = FleetAggregator(
            ["http://a"], clock=ManualClock(),
            fetch=lambda url, t: {"counters": {"x": 7.0}})
        assert agg.fleet_snapshot()["counters"]["x"] == 7.0


class TestStaleness:
    def setup_method(self):
        metrics.reset()
        tracing.reset_gauges("fleet.")

    def test_stale_replica_drops_from_windowed_surfaces(self):
        clock = ManualClock()
        alive = {"r0": True, "r1": True}

        def fetch(url, timeout):
            name = "r0" if "//r0/" in url else "r1"
            if not alive[name]:
                raise urllib.error.URLError("connection refused")
            return load_replica(name)

        agg = FleetAggregator(
            {"r0": "http://r0/", "r1": "http://r1/"},
            config=FleetConfig(staleness_s=30.0), clock=clock,
            fetch=fetch)
        out = agg.fleet_snapshot()
        assert out["healthy"] == 2
        h2 = out["histograms"]["serving.batcher.e2e_seconds"]
        assert h2["count"] == 8                  # r0 (4) + r1 (4)
        alive["r1"] = False
        # within the staleness bound the last snapshot still counts
        clock.advance(10.0)
        out = agg.fleet_snapshot()
        assert out["healthy"] == 2
        assert out["replicas"]["r1"]["errors"] == 1
        # past it the replica drops unhealthy: windowed surfaces
        # (histograms, recall) exclude it...
        clock.advance(30.0)
        errs0 = tracing.get_counter(fed_mod.SCRAPE_ERRORS)
        out = agg.fleet_snapshot()
        assert out["healthy"] == 1
        assert not out["replicas"]["r1"]["healthy"]
        assert tracing.get_counter(fed_mod.SCRAPE_ERRORS) == errs0 + 1
        assert out["histograms"][
            "serving.batcher.e2e_seconds"]["count"] == 4
        assert out["recall"]["live"]["trials"] == 100   # r0 only
        # ...while CUMULATIVE surfaces retain its last-known (monotone
        # lower-bound) contribution — fleet counters cannot regress
        assert out["counters"]["serving.execute.calls"] == 300.0
        plane_total = out["probe_freq"]["ivf:0"]["total"]
        assert plane_total == 100                # 60 (r0) + 40 (r1)
        assert tracing.get_gauge("fleet.replica.r1.healthy") == 0.0
        assert tracing.get_gauge("fleet.replicas_healthy") == 1.0


class TestFleetHTTP:
    """The served surface: /fleet.json and the replica=-labeled +
    fleet-aggregate exposition, over real HTTP."""

    def setup_method(self):
        metrics.reset()
        tracing.reset_gauges("fleet.")

    def _get(self, url):
        try:
            with urllib.request.urlopen(url, timeout=30) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    def test_fleet_json_serves_merged_view(self):
        agg = fixture_aggregator()
        with MetricsExporter(fleet=agg) as exp:
            code, body = self._get(exp.url("/fleet.json"))
            assert code == 200
            out = json.loads(body)
            assert out["healthy"] == 3
            assert out["counters"]["serving.execute.calls"] == 350.0
            assert out["recall"]["live"]["estimate"] == \
                pytest.approx(0.9)
            assert out["probe_freq"]["ivf:0"]["coverage_p01"] == \
                pytest.approx(0.8)

    def test_fleet_json_404_without_aggregator(self):
        with MetricsExporter() as exp:
            code, _ = self._get(exp.url("/fleet.json"))
            assert code == 404

    def test_labeled_exposition(self):
        import re

        agg = fixture_aggregator()
        with MetricsExporter(fleet=agg) as exp:
            code, text = self._get(exp.url("/metrics"))
        assert code == 200
        # per-replica lifetime samples + the fleet aggregate, in ONE
        # fleet_-prefixed family (no collision with local families)
        assert ('fleet_serving_execute_calls{replica="r0"} 100'
                in text)
        assert ('fleet_serving_execute_calls{replica="r1"} 200'
                in text)
        assert ('fleet_serving_execute_calls{replica="fleet"} 350'
                in text)
        assert text.count(
            "# TYPE fleet_serving_execute_calls counter") == 1
        # the merged histogram renders per replica and fleet-wide
        assert re.search(
            r'fleet_serving_batcher_e2e_seconds_bucket'
            r'\{replica="fleet",le="[^"]+"\} \d+', text)
        assert ('fleet_serving_batcher_e2e_seconds_count'
                '{replica="fleet"} 9') in text
        # the aggregator's own health gauges render as labeled
        # families through the normal registry path
        assert 'fleet_replica_healthy{replica="r0"} 1' in text
        assert re.search(
            r'fleet_probe_freq_coverage_p01\{index="ivf:0"\}', text)
        # every non-comment line still parses against the exposition
        # grammar (label values may carry ':' — quoted, so legal)
        sample_re = re.compile(
            r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
            r'(\{[a-zA-Z_]+="[^"]*"(,[a-zA-Z_]+="[^"]*")*\})? '
            r"[-+0-9.e]+$")
        for line in text.strip().splitlines():
            if not line.startswith("#"):
                assert sample_re.match(line), line


class FakePlaneExecutor:
    def probe_frequencies(self):
        return {"ivf:0": np.array([3, 0, 2, 0], dtype=np.int64)}

    def publish_probe_gauges(self, top_n=8, planes=None):
        return {}


class TestSnapshotFederationPayload:
    """The replica side: /snapshot.json must carry the merge inputs —
    the lifetime ledger and (with an IndexGauge) the federation
    block."""

    def setup_method(self):
        metrics.reset()

    def test_snapshot_carries_lifetime_ledger(self):
        tracing.inc_counter("serving.execute.calls", 5.0)
        tracing.reset_counters("serving.")     # mid-scrape reset
        tracing.inc_counter("serving.execute.calls", 2.0)
        exp = MetricsExporter()
        snap = exp.snapshot()
        # the live view regressed to 2; the ledger the fleet sums
        # from still carries the full 7
        assert snap["counters"]["serving.execute.calls"] == 2.0
        assert snap["counters_lifetime"][
            "serving.execute.calls"] >= 7.0

    def test_federation_block_with_index_gauge(self):
        det = DriftDetector(np.array([1.0, 2.0, 3.0, 4.0]))
        det.update(np.array([1, 0, 1, 0]))
        gauge = IndexGauge(executor=FakePlaneExecutor(),
                           drift={"main": det})
        exp = MetricsExporter(index_gauge=gauge)
        fed = exp.snapshot()["federation"]
        assert fed["probe_planes"]["ivf:0"] == [3, 0, 2, 0]
        assert fed["drift"]["main"]["baseline"] == [1.0, 2.0, 3.0, 4.0]
        assert fed["drift"]["main"]["live"] is not None
        # the pooling weight: an EWMA (alpha=0.2) of per-window
        # traffic — first window seeds, the second folds
        assert fed["drift"]["main"]["traffic"] == pytest.approx(2.0)
        det.update(np.array([7, 2, 1, 0]))      # delta sum 8
        assert det.state()["traffic"] == pytest.approx(
            0.2 * 8.0 + 0.8 * 2.0)
        # JSON-serializable end to end (the payload ships over HTTP)
        json.dumps(fed)

    def test_recall_raw_pools(self):
        from raft_tpu.serving import RecallWindow

        w = RecallWindow(window_s=60.0)
        w.record(0.0, 8, 10)
        w.record(10.0, 9, 10)
        assert w.raw(10.0) == {"hits": 17, "trials": 20, "pairs": 2}
        # pruned pairs leave the raw counts with the window
        assert w.raw(65.0) == {"hits": 9, "trials": 10, "pairs": 1}
