"""graftfleet federation tests (PR 12) — multi-replica merge
semantics, pinned byte-exactly by the committed 3-replica snapshot
fixtures (``tests/data/fleet_replica_r{0,1,2}.json``).

The acceptance criteria this file carries: the aggregator reproduces
the fixture fleet sums, the pooled-trials Wilson CI, and the fleet
probe-coverage exactly, serves them at ``/fleet.json``, and renders a
``replica=``-labeled + fleet-aggregate Prometheus exposition; a
mid-scrape counter reset can NEVER make a fleet counter go backwards
(lifetime ledger + high-water monotonicity assertion); a stale
replica drops from windowed surfaces while its cumulative
contributions are retained.
"""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from raft_tpu.core import tracing
from raft_tpu.serving import (
    DriftDetector,
    FleetAggregator,
    FleetConfig,
    IndexGauge,
    MetricsExporter,
)
from raft_tpu.serving import federation as fed_mod
from raft_tpu.serving import metrics
from raft_tpu.serving.gauge import wilson_interval
from raft_tpu.serving.harness import ManualClock

DATA = os.path.join(os.path.dirname(__file__), "data")


def load_replica(name):
    with open(os.path.join(DATA, f"fleet_replica_{name}.json")) as f:
        return json.load(f)


def fixture_fetch(url, timeout):
    for name in ("r0", "r1", "r2"):
        if f"//{name}/" in url:
            return load_replica(name)
    raise ValueError(f"unknown fixture url {url!r}")


def fixture_aggregator(clock=None, **kw):
    return FleetAggregator(
        {n: f"http://{n}/snapshot.json" for n in ("r0", "r1", "r2")},
        clock=clock or ManualClock(), fetch=fixture_fetch, **kw)


class TestFixturePinnedMerge:
    def setup_method(self):
        metrics.reset()
        tracing.reset_gauges("fleet.")

    def merged(self):
        agg = fixture_aggregator()
        scrapes0 = tracing.get_counter(fed_mod.SCRAPES)
        out = agg.fleet_snapshot()
        assert tracing.get_counter(fed_mod.SCRAPES) == scrapes0 + 1
        return out

    def test_replica_health(self):
        out = self.merged()
        assert out["size"] == 3 and out["healthy"] == 3
        for name in ("r0", "r1", "r2"):
            r = out["replicas"][name]
            assert r["healthy"] and r["errors"] == 0
            assert r["age_s"] == 0.0

    def test_fleet_counter_sums_from_lifetime_ledger(self):
        out = self.merged()
        c = out["counters"]
        # the LIFETIME values sum (r0's live view says 10 — a
        # mid-session reset folded 90 into its ledger; the fleet
        # number must be the reset-proof 100 + 200 + 50)
        assert c["serving.execute.calls"] == 350.0
        assert c["serving.slo.missed"] == 6.0
        assert c["index.probe.dispatches"] == 60.0

    def test_tier_block_sums_and_hit_rate(self):
        """graftcast: the tier placement + prefetch counters merge
        like every lifetime counter (monotone clamped sums) and
        restate as the structured ``tier`` block — r2 predates
        tiering and contributes zeros, never an error."""
        out = self.merged()
        t = out["tier"]
        assert t["epochs"] == 7.0          # 4 + 3 + 0
        assert t["promotions"] == 14.0
        assert t["demotions"] == 14.0
        pf = t["prefetch"]
        assert pf["issued"] == 11.0        # 6 + 5
        assert pf["hits"] == 7.0 and pf["misses"] == 4.0
        assert pf["cancelled"] == 1.0
        assert pf["hit_rate"] == pytest.approx(7.0 / 11.0)
        assert tracing.get_gauge("fleet.tier.epochs") == 7.0
        assert tracing.get_gauge(
            "fleet.tier.prefetch.hits") == 7.0
        assert tracing.get_gauge(
            "fleet.tier.prefetch.hit_rate") == \
            pytest.approx(7.0 / 11.0)

    def test_histograms_merge_bucket_wise(self):
        h = self.merged()["histograms"]["serving.batcher.e2e_seconds"]
        assert h["count"] == 9
        assert h["sum"] == pytest.approx(0.5105)
        assert h["bucket_counts"] == [3, 6, 8, 9]
        assert h["replicas"] == 3
        # quantiles recompute from the MERGED distribution — never
        # averaged per-replica quantiles
        assert h["p50"] == pytest.approx(0.0055)
        assert h["p95"] == pytest.approx(0.155)
        assert h["p99"] == pytest.approx(0.191)

    def test_fleet_probe_coverage_exact(self):
        pf = self.merged()["probe_freq"]["ivf:0"]
        # summed plane [100, 5, 5, 0, 10, 0, 0, 5]
        assert pf["total"] == 125
        assert pf["probed_fraction"] == pytest.approx(5 / 8)
        assert pf["coverage_p01"] == pytest.approx(0.8)
        assert pf["coverage_p10"] == pytest.approx(0.8)
        assert pf["top"][0] == (0, 100)
        assert tracing.get_gauge(
            "fleet.probe_freq.ivf:0.coverage_p01") == \
            pytest.approx(0.8)

    def test_recall_pools_trials_before_wilson(self):
        rec = self.merged()["recall"]
        live = rec["live"]
        assert (live["hits"], live["trials"], live["pairs"]) == \
            (315, 350, 32)
        assert live["estimate"] == pytest.approx(0.9)
        lo, hi = wilson_interval(315, 350)
        assert live["ci_low"] == pytest.approx(lo)
        assert live["ci_high"] == pytest.approx(hi)
        # pooling strictly tightens: the fleet CI is narrower than
        # the smallest replica's own window could support
        lo2, hi2 = wilson_interval(45, 50)
        assert hi - lo < hi2 - lo2
        # a sweep leg present on one replica still federates
        assert rec["sweep.p8"]["trials"] == 10
        assert tracing.get_gauge("fleet.recall.estimate") == \
            pytest.approx(0.9)

    def test_drift_rescores_pooled_histogram(self):
        drift = self.merged()["drift"]
        # traffic-weighted pooled live (40x + 20x uniform) is EXACTLY
        # proportional to the pooled baseline [30,30,30,30]: zero
        # drift however each replica's own window wiggled
        assert drift["main"]["score"] == pytest.approx(0.0)
        assert drift["main"]["replicas"] == 3
        # every replica skewed the same way: whatever the traffic
        # weights, the pooled distribution is [1, 0] and the score is
        # the JSD of it vs the pooled baseline [30, 30]
        expect = tracing.js_divergence([24.0, 0.0], [30.0, 30.0])
        assert drift["skew"]["score"] == pytest.approx(expect)
        assert drift["skew"]["score"] == pytest.approx(0.311278,
                                                       abs=1e-6)

    def test_drift_pooling_weighs_by_traffic_share(self):
        # a drifted replica carrying 99% of fleet traffic must NOT be
        # averaged away by an idle healthy peer: each replica's live
        # histogram is normalized, so the pool scales by ``traffic``
        def snap(live, traffic):
            return {"federation": {"drift": {"ix": {
                "baseline": [50, 50], "live": live,
                "traffic": traffic, "score": 0.0, "updates": 1}}}}

        payload = {"http://busy/snapshot.json": snap([1.0, 0.0], 99.0),
                   "http://idle/snapshot.json": snap([0.5, 0.5], 1.0)}
        agg = FleetAggregator({"busy": "http://busy/",
                               "idle": "http://idle/"},
                              clock=ManualClock(),
                              fetch=lambda url, t: payload[url])
        score = agg.fleet_snapshot()["drift"]["ix"]["score"]
        assert score == pytest.approx(
            tracing.js_divergence([99.5, 0.5], [100.0, 100.0]))
        # equal-weight fallback for payloads predating the weight
        for s in payload.values():
            del s["federation"]["drift"]["ix"]["traffic"]
        agg2 = FleetAggregator({"busy": "http://busy/",
                                "idle": "http://idle/"},
                               clock=ManualClock(),
                               fetch=lambda url, t: payload[url])
        score2 = agg2.fleet_snapshot()["drift"]["ix"]["score"]
        assert score2 == pytest.approx(
            tracing.js_divergence([1.5, 0.5], [100.0, 100.0]))
        # traffic weighting makes the busy drifted replica dominate:
        # MORE fleet drift detected than the averaged-away pool shows
        assert score > score2

    def test_admission_rollup(self):
        adm = self.merged()["admission"]
        assert adm["queue_depth"] == 5.0
        assert adm["arrival_rate_hz"] == 15.0
        assert adm["max_shed_level"] == 1


class TestMonotonicity:
    def setup_method(self):
        metrics.reset()

    def test_mid_scrape_reset_cannot_regress_fleet_counter(self):
        payloads = [
            {"counters_lifetime": {"serving.execute.calls": 100.0}},
            # a replica restart zeroed its ledger mid-scrape
            {"counters_lifetime": {"serving.execute.calls": 40.0}},
            {"counters_lifetime": {"serving.execute.calls": 60.0}},
        ]
        seq = iter(payloads)
        agg = FleetAggregator(["http://a"], clock=ManualClock(),
                              fetch=lambda url, t: next(seq))
        v0 = tracing.get_counter(fed_mod.MONOTONICITY_VIOLATIONS)
        assert agg.fleet_snapshot()["counters"][
            "serving.execute.calls"] == 100.0
        out = agg.fleet_snapshot()
        # clamped to the high-water mark — asserted monotone — and
        # the violation is counted, not silent
        assert out["counters"]["serving.execute.calls"] == 100.0
        assert tracing.get_counter(
            fed_mod.MONOTONICITY_VIOLATIONS) == v0 + 1
        # recovery below the mark still cannot move the fleet down
        assert agg.fleet_snapshot()["counters"][
            "serving.execute.calls"] == 100.0

    def test_live_counters_fallback_for_old_payloads(self):
        agg = FleetAggregator(
            ["http://a"], clock=ManualClock(),
            fetch=lambda url, t: {"counters": {"x": 7.0}})
        assert agg.fleet_snapshot()["counters"]["x"] == 7.0


class TestStaleness:
    def setup_method(self):
        metrics.reset()
        tracing.reset_gauges("fleet.")

    def test_stale_replica_drops_from_windowed_surfaces(self):
        clock = ManualClock()
        alive = {"r0": True, "r1": True}

        def fetch(url, timeout):
            name = "r0" if "//r0/" in url else "r1"
            if not alive[name]:
                raise urllib.error.URLError("connection refused")
            return load_replica(name)

        agg = FleetAggregator(
            {"r0": "http://r0/", "r1": "http://r1/"},
            config=FleetConfig(staleness_s=30.0), clock=clock,
            fetch=fetch)
        out = agg.fleet_snapshot()
        assert out["healthy"] == 2
        h2 = out["histograms"]["serving.batcher.e2e_seconds"]
        assert h2["count"] == 8                  # r0 (4) + r1 (4)
        alive["r1"] = False
        # within the staleness bound the last snapshot still counts
        clock.advance(10.0)
        out = agg.fleet_snapshot()
        assert out["healthy"] == 2
        assert out["replicas"]["r1"]["errors"] == 1
        # past it the replica drops unhealthy: windowed surfaces
        # (histograms, recall) exclude it...
        clock.advance(30.0)
        errs0 = tracing.get_counter(fed_mod.SCRAPE_ERRORS)
        out = agg.fleet_snapshot()
        assert out["healthy"] == 1
        assert not out["replicas"]["r1"]["healthy"]
        assert tracing.get_counter(fed_mod.SCRAPE_ERRORS) == errs0 + 1
        assert out["histograms"][
            "serving.batcher.e2e_seconds"]["count"] == 4
        assert out["recall"]["live"]["trials"] == 100   # r0 only
        # ...while CUMULATIVE surfaces retain its last-known (monotone
        # lower-bound) contribution — fleet counters cannot regress
        assert out["counters"]["serving.execute.calls"] == 300.0
        plane_total = out["probe_freq"]["ivf:0"]["total"]
        assert plane_total == 100                # 60 (r0) + 40 (r1)
        assert tracing.get_gauge("fleet.replica.r1.healthy") == 0.0
        assert tracing.get_gauge("fleet.replicas_healthy") == 1.0


class TestFleetHTTP:
    """The served surface: /fleet.json and the replica=-labeled +
    fleet-aggregate exposition, over real HTTP."""

    def setup_method(self):
        metrics.reset()
        tracing.reset_gauges("fleet.")

    def _get(self, url):
        try:
            with urllib.request.urlopen(url, timeout=30) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    def test_fleet_json_serves_merged_view(self):
        agg = fixture_aggregator()
        with MetricsExporter(fleet=agg) as exp:
            code, body = self._get(exp.url("/fleet.json"))
            assert code == 200
            out = json.loads(body)
            assert out["healthy"] == 3
            assert out["counters"]["serving.execute.calls"] == 350.0
            assert out["recall"]["live"]["estimate"] == \
                pytest.approx(0.9)
            assert out["probe_freq"]["ivf:0"]["coverage_p01"] == \
                pytest.approx(0.8)

    def test_fleet_json_404_without_aggregator(self):
        with MetricsExporter() as exp:
            code, _ = self._get(exp.url("/fleet.json"))
            assert code == 404

    def test_labeled_exposition(self):
        import re

        agg = fixture_aggregator()
        with MetricsExporter(fleet=agg) as exp:
            code, text = self._get(exp.url("/metrics"))
        assert code == 200
        # per-replica lifetime samples + the fleet aggregate, in ONE
        # fleet_-prefixed family (no collision with local families)
        assert ('fleet_serving_execute_calls{replica="r0"} 100'
                in text)
        assert ('fleet_serving_execute_calls{replica="r1"} 200'
                in text)
        assert ('fleet_serving_execute_calls{replica="fleet"} 350'
                in text)
        assert text.count(
            "# TYPE fleet_serving_execute_calls counter") == 1
        # the merged histogram renders per replica and fleet-wide
        assert re.search(
            r'fleet_serving_batcher_e2e_seconds_bucket'
            r'\{replica="fleet",le="[^"]+"\} \d+', text)
        assert ('fleet_serving_batcher_e2e_seconds_count'
                '{replica="fleet"} 9') in text
        # the aggregator's own health gauges render as labeled
        # families through the normal registry path
        assert 'fleet_replica_healthy{replica="r0"} 1' in text
        assert re.search(
            r'fleet_probe_freq_coverage_p01\{index="ivf:0"\}', text)
        # every non-comment line still parses against the exposition
        # grammar (label values may carry ':' — quoted, so legal)
        sample_re = re.compile(
            r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
            r'(\{[a-zA-Z_]+="[^"]*"(,[a-zA-Z_]+="[^"]*")*\})? '
            r"[-+0-9.e]+$")
        for line in text.strip().splitlines():
            if not line.startswith("#"):
                assert sample_re.match(line), line


class FakePlaneExecutor:
    def probe_frequencies(self):
        return {"ivf:0": np.array([3, 0, 2, 0], dtype=np.int64)}

    def publish_probe_gauges(self, top_n=8, planes=None):
        return {}


class TestSnapshotFederationPayload:
    """The replica side: /snapshot.json must carry the merge inputs —
    the lifetime ledger and (with an IndexGauge) the federation
    block."""

    def setup_method(self):
        metrics.reset()

    def test_snapshot_carries_lifetime_ledger(self):
        tracing.inc_counter("serving.execute.calls", 5.0)
        tracing.reset_counters("serving.")     # mid-scrape reset
        tracing.inc_counter("serving.execute.calls", 2.0)
        exp = MetricsExporter()
        snap = exp.snapshot()
        # the live view regressed to 2; the ledger the fleet sums
        # from still carries the full 7
        assert snap["counters"]["serving.execute.calls"] == 2.0
        assert snap["counters_lifetime"][
            "serving.execute.calls"] >= 7.0

    def test_federation_block_with_index_gauge(self):
        det = DriftDetector(np.array([1.0, 2.0, 3.0, 4.0]))
        det.update(np.array([1, 0, 1, 0]))
        gauge = IndexGauge(executor=FakePlaneExecutor(),
                           drift={"main": det})
        exp = MetricsExporter(index_gauge=gauge)
        fed = exp.snapshot()["federation"]
        assert fed["probe_planes"]["ivf:0"] == [3, 0, 2, 0]
        assert fed["drift"]["main"]["baseline"] == [1.0, 2.0, 3.0, 4.0]
        assert fed["drift"]["main"]["live"] is not None
        # the pooling weight: an EWMA (alpha=0.2) of per-window
        # traffic — first window seeds, the second folds
        assert fed["drift"]["main"]["traffic"] == pytest.approx(2.0)
        det.update(np.array([7, 2, 1, 0]))      # delta sum 8
        assert det.state()["traffic"] == pytest.approx(
            0.2 * 8.0 + 0.8 * 2.0)
        # JSON-serializable end to end (the payload ships over HTTP)
        json.dumps(fed)

    def test_recall_raw_pools(self):
        from raft_tpu.serving import RecallWindow

        w = RecallWindow(window_s=60.0)
        w.record(0.0, 8, 10)
        w.record(10.0, 9, 10)
        assert w.raw(10.0) == {"hits": 17, "trials": 20, "pairs": 2}
        # pruned pairs leave the raw counts with the window
        assert w.raw(65.0) == {"hits": 9, "trials": 10, "pairs": 1}


class TestMemoryMerge:
    """graftledger federation (PR 13): per-replica memory blocks merge
    as resident SUM + headroom MIN; a replica missing the block (r2 —
    older build, no ledger) is skipped and counted, never guessed at."""

    def setup_method(self):
        metrics.reset()
        tracing.reset_gauges("fleet.")

    def merged(self):
        return fixture_aggregator().fleet_snapshot()

    def test_resident_sum_and_headroom_min(self):
        mem = self.merged()["memory"]
        # r0 and r1 report; r2 has no memory block
        assert mem["replicas_reporting"] == 2
        assert mem["resident_bytes"] == 9_000_000.0
        # per-index resident sums across replicas holding a copy
        assert mem["resident"]["ivf:0"] == 8_000_000.0
        assert mem["resident"]["pq:0"] == 1_000_000.0
        # headroom is the MIN over measured replicas — r1's 1.5 MB,
        # not an average, and r2's absence is not infinite room
        assert mem["headroom_min_bytes"] == 1_500_000.0
        assert mem["headroom_min_replica"] == "r1"
        assert mem["forecast_peak_max_bytes"] == 6_000_000.0

    def test_memory_gauges_published(self):
        self.merged()
        assert tracing.get_gauge(
            "fleet.memory.resident_bytes") == 9_000_000.0
        assert tracing.get_gauge(
            "fleet.memory.headroom_min_bytes") == 1_500_000.0
        assert tracing.get_gauge(
            "fleet.memory.replicas_reporting") == 2.0
        assert tracing.get_gauge(
            "fleet.replica.r0.headroom_bytes") == 2_000_000.0
        assert tracing.get_gauge(
            "fleet.memory.index.ivf:0.resident_bytes") == 8_000_000.0

    def test_no_replica_reporting(self):
        """A fleet of memory-block-free replicas merges to an honest
        zero-reporting block — no gauges invented."""
        agg = FleetAggregator({"r2": "http://r2/snapshot.json"},
                              clock=ManualClock(), fetch=fixture_fetch)
        mem = agg.fleet_snapshot()["memory"]
        assert mem["replicas_reporting"] == 0
        assert mem["headroom_min_bytes"] is None
        assert tracing.get_gauge(
            "fleet.memory.resident_bytes", -1.0) == -1.0

    def test_stale_replica_drops_from_memory(self):
        """Memory is instantaneous state: a stale replica's block
        leaves the merge (unlike its cumulative counters)."""
        clock = ManualClock()
        agg = fixture_aggregator(clock=clock)
        agg.fleet_snapshot()
        # r0 keeps scraping; r1 and r2 go dark past staleness
        working = dict(agg._states)
        def flaky(url, timeout):
            if "//r0/" in url:
                return load_replica("r0")
            raise OSError("down")
        agg._fetch = flaky
        clock.advance(agg.config.staleness_s + 1.0)
        mem = agg.fleet_snapshot()["memory"]
        assert mem["replicas_reporting"] == 1
        assert mem["headroom_min_bytes"] == 2_000_000.0
        assert mem["headroom_min_replica"] == "r0"

    def test_labeled_memory_exposition(self):
        """fleet_memory_index_resident_bytes renders {index=}-labeled
        through the exporter (the exposition parse-check satellite)."""
        agg = fixture_aggregator()
        exp = MetricsExporter(fleet=agg)
        text = exp.prometheus_text()
        assert ('fleet_memory_index_resident_bytes{index="ivf:0"} '
                "8000000") in text
        assert "# TYPE fleet_memory_index_resident_bytes gauge" in text
        assert 'fleet_replica_headroom_bytes{replica="r1"} 1500000' \
            in text


class TestPushMode:
    """Federation push mode (PR 13): replicas behind NAT POST their
    /snapshot.json body; it enters the SAME type-correct merge path."""

    def setup_method(self):
        metrics.reset()
        tracing.reset_gauges("fleet.")

    def test_push_auto_registers_and_merges(self):
        clock = ManualClock()
        agg = FleetAggregator({}, clock=clock, fetch=fixture_fetch)
        agg.push("nat0", load_replica("r0"))
        out = agg.merge()
        assert out["size"] == 1 and out["healthy"] == 1
        assert out["replicas"]["nat0"]["healthy"]
        # the merge path is the shared one: lifetime-ledger counters
        assert out["counters"]["serving.execute.calls"] == 100.0
        assert out["memory"]["replicas_reporting"] == 1

    def test_push_replicas_are_never_fetched(self):
        fetched = []
        def spy(url, timeout):
            fetched.append(url)
            return fixture_fetch(url, timeout)
        agg = FleetAggregator({"r0": "http://r0/snapshot.json"},
                              clock=ManualClock(), fetch=spy)
        pushes0 = tracing.get_counter(fed_mod.PUSHES)
        agg.push("nat0", load_replica("r1"))
        agg.fleet_snapshot()
        assert fetched == ["http://r0/snapshot.json"]
        assert tracing.get_counter(fed_mod.PUSHES) == pushes0 + 1.0

    def test_pushed_counters_stay_monotone(self):
        """A pushed restart (ledger regression) clamps exactly like a
        scraped one — one merge path, one monotonicity contract."""
        clock = ManualClock()
        agg = FleetAggregator({}, clock=clock, fetch=fixture_fetch)
        agg.push("nat0", {"counters_lifetime":
                          {"serving.execute.calls": 100.0}})
        assert agg.merge()["counters"][
            "serving.execute.calls"] == 100.0
        v0 = tracing.get_counter(fed_mod.MONOTONICITY_VIOLATIONS)
        agg.push("nat0", {"counters_lifetime":
                          {"serving.execute.calls": 10.0}})
        assert agg.merge()["counters"][
            "serving.execute.calls"] == 100.0     # clamped
        assert tracing.get_counter(
            fed_mod.MONOTONICITY_VIOLATIONS) == v0 + 1

    def test_push_goes_stale_without_refresh(self):
        clock = ManualClock()
        agg = FleetAggregator({}, clock=clock, fetch=fixture_fetch)
        agg.push("nat0", load_replica("r0"))
        assert agg.merge()["healthy"] == 1
        clock.advance(agg.config.staleness_s + 1.0)
        out = agg.merge()
        assert out["healthy"] == 0
        # cumulative surfaces retain the stale lower bound
        assert out["counters"]["serving.execute.calls"] == 100.0

    def test_http_push_endpoint(self):
        import urllib.request as ur

        agg = FleetAggregator({}, clock=ManualClock(),
                              fetch=fixture_fetch)
        with MetricsExporter(fleet=agg) as exp:
            body = json.dumps(load_replica("r0")).encode()
            req = ur.Request(exp.url("/push?replica=nat0"), data=body,
                             method="POST")
            out = json.loads(ur.urlopen(req, timeout=10).read())
            assert out == {"accepted": "nat0"}
            # 400: no replica name
            req = ur.Request(exp.url("/push"), data=body,
                             method="POST")
            with pytest.raises(urllib.error.HTTPError) as e:
                ur.urlopen(req, timeout=10)
            assert e.value.code == 400
            # 400: body not a JSON object
            req = ur.Request(exp.url("/push?replica=nat0"),
                             data=b"[1,2]", method="POST")
            with pytest.raises(urllib.error.HTTPError) as e:
                ur.urlopen(req, timeout=10)
            assert e.value.code == 400
        assert agg.merge()["replicas"]["nat0"]["scrapes"] == 1

    def test_malformed_push_memory_block_costs_only_that_replica(self):
        """Review hardening: a pushed snapshot with garbage memory
        fields (null totals, list-typed resident map) must not poison
        the fleet merge for the staleness window — the bad replica's
        contribution drops, everyone else's survives."""
        clock = ManualClock()
        agg = FleetAggregator({}, clock=clock, fetch=fixture_fetch)
        agg.push("good", load_replica("r0"))
        agg.push("bad", {"counters_lifetime": {},
                         "memory": {"resident_total_bytes": None,
                                    "resident": [1, 2],
                                    "forecast_peak_bytes": "nan?",
                                    "headroom_bytes": "x"}})
        mem = agg.merge()["memory"]          # must not raise
        assert mem["replicas_reporting"] == 2
        assert mem["resident_bytes"] == 4_000_000.0   # r0 only
        assert mem["headroom_min_replica"] == "good"

    def test_push_registry_is_bounded(self):
        """Review hardening: the network-reachable push endpoint
        cannot grow the replica registry without bound."""
        import urllib.request as ur

        cfg = fed_mod.FleetConfig(max_push_replicas=2)
        agg = FleetAggregator({}, clock=ManualClock(),
                              fetch=fixture_fetch, config=cfg)
        agg.push("a", {"counters_lifetime": {}})
        agg.push("b", {"counters_lifetime": {}})
        agg.push("a", {"counters_lifetime": {}})    # re-push is fine
        with pytest.raises(ValueError, match="limit"):
            agg.push("c", {"counters_lifetime": {}})
        assert set(agg._states) == {"a", "b"}
        # over HTTP the refusal is a 429, telling the replica to back
        # off rather than silently dropping its snapshot
        with MetricsExporter(fleet=agg) as exp:
            req = ur.Request(exp.url("/push?replica=c"), data=b"{}",
                             method="POST")
            with pytest.raises(urllib.error.HTTPError) as e:
                ur.urlopen(req, timeout=10)
            assert e.value.code == 429

    def test_push_cannot_impersonate_scrape_replica(self):
        """Review hardening: an unauthenticated push must never
        overwrite a configured scrape replica's snapshot — that would
        ratchet its monotone high-water counters with whatever the
        pusher claims."""
        agg = fixture_aggregator()
        agg.fleet_snapshot()
        with pytest.raises(ValueError, match="scrape-mode"):
            agg.push("r0", {"counters_lifetime":
                            {"serving.slo.missed": 1e15}})
        # the real replica's clamped counters are untouched
        assert agg.merge()["counters"]["serving.slo.missed"] == 6.0

    def test_push_names_and_labels_sanitized(self):
        """Review hardening: network-supplied names/labels reach
        gauge registry names and Prometheus label values — quotes and
        newlines must never survive into the exposition."""
        agg = FleetAggregator({}, clock=ManualClock(),
                              fetch=fixture_fetch)
        agg.push('evil"}x\nup 1', {
            "counters_lifetime": {},
            "memory": {"resident_total_bytes": 10,
                       "resident": {'bad"label\n': 10},
                       "headroom_bytes": 5.0}})
        out = agg.merge()
        assert list(out["replicas"]) == ["evil--x-up-1"]
        assert list(out["memory"]["resident"]) == ["bad-label-"]
        text = MetricsExporter(fleet=agg).prometheus_text()
        for line in text.splitlines():
            assert '"}x' not in line and "up 1\"" not in line

    def test_memory_label_cardinality_bounded(self):
        """Review hardening: one replica's snapshot cannot mint
        unbounded per-index fleet gauges — top-N largest publish,
        stale labels retire."""
        agg = FleetAggregator({}, clock=ManualClock(),
                              fetch=fixture_fetch)
        resident = {f"idx{i}": float(i) for i in range(100)}
        agg.push("a", {"counters_lifetime": {},
                       "memory": {"resident_total_bytes": 1,
                                  "resident": resident}})
        agg.merge()
        published = tracing.gauges("fleet.memory.index.")
        assert len(published) == fed_mod.MEMORY_LABEL_CAP
        # largest residents won
        assert "fleet.memory.index.idx99.resident_bytes" in published
        assert "fleet.memory.index.idx0.resident_bytes" not in published
        # a later merge with fewer labels retires the stale ones
        agg.push("a", {"counters_lifetime": {},
                       "memory": {"resident_total_bytes": 1,
                                  "resident": {"only": 5.0}}})
        agg.merge()
        assert list(tracing.gauges("fleet.memory.index.")) == \
            ["fleet.memory.index.only.resident_bytes"]

    def test_nonfinite_pushed_values_are_garbage_not_measurements(self):
        """Review hardening: JSON ``1e999`` parses to inf — a pushed
        infinity must neither ratchet the monotone counter marks
        (which would crash the multiburn int() delta on every later
        merge) nor poison the fleet memory sums."""
        from raft_tpu.serving import MultiBurnConfig
        from raft_tpu.serving.metrics import SloConfig

        clock = ManualClock()
        agg = FleetAggregator(
            {}, clock=clock, fetch=fixture_fetch,
            config=fed_mod.FleetConfig(multiburn=MultiBurnConfig(
                short=SloConfig(window_s=300.0),
                long=SloConfig(window_s=3600.0))))
        agg.push("x", json.loads(
            '{"counters_lifetime": {"serving.slo.attained": 1e999,'
            ' "serving.execute.calls": 7.0},'
            ' "memory": {"resident_total_bytes": 1e999,'
            ' "resident": {"a": 1e999, "b": 5.0},'
            ' "headroom_bytes": 1e999}}'))
        out = agg.merge()                      # must not raise
        assert "serving.slo.attained" not in out["counters"]
        assert out["counters"]["serving.execute.calls"] == 7.0
        mem = out["memory"]
        assert mem["resident_bytes"] == 0.0    # inf dropped, honest 0
        assert mem["resident"] == {"a": 0.0, "b": 5.0} or \
            mem["resident"] == {"b": 5.0}
        assert mem["headroom_min_bytes"] is None
        # merges keep working afterwards (the poison would have been
        # permanent)
        clock.advance(1.0)
        agg.merge()

    def test_stale_memory_and_replica_gauges_retire(self):
        """Review hardening: a replica that stops reporting memory
        (or drops entirely) must not keep advertising its last
        headroom — stale room is what an operator would place the hot
        tier on."""
        clock = ManualClock()
        agg = FleetAggregator({}, clock=clock, fetch=fixture_fetch)
        agg.push("a", {"counters_lifetime": {},
                       "memory": {"resident_total_bytes": 10,
                                  "resident": {"i": 10},
                                  "headroom_bytes": 8e9}})
        agg.merge()
        assert tracing.get_gauge(
            "fleet.replica.a.headroom_bytes") == 8e9
        # the replica goes stale -> memory and headroom gauges retire
        clock.advance(agg.config.staleness_s + 1.0)
        agg.merge()
        assert tracing.get_gauge(
            "fleet.replica.a.headroom_bytes", -1.0) == -1.0
        assert tracing.get_gauge(
            "fleet.memory.resident_bytes", -1.0) == -1.0
        assert tracing.gauges("fleet.memory.index.") == {}
        # the replica itself is still listed (unhealthy), so its
        # health gauge re-publishes
        assert tracing.get_gauge("fleet.replica.a.healthy") == 0.0

    def test_http_push_404_without_aggregator(self):
        import urllib.request as ur

        with MetricsExporter() as exp:
            req = ur.Request(exp.url("/push?replica=x"), data=b"{}",
                             method="POST")
            with pytest.raises(urllib.error.HTTPError) as e:
                ur.urlopen(req, timeout=10)
            assert e.value.code == 404


class TestFleetBurnAlert:
    """Fleet-level multiburn alerting (PR 13, the PR 12 named
    follow-on): per-merge deltas of the summed attained/missed fleet
    counters fold into a 5m+1h MultiBurnAlert pair under fleet.slo.*,
    ManualClock-pinned."""

    def setup_method(self):
        metrics.reset()
        tracing.reset_gauges("fleet.")

    def make(self, clock, short_s=300.0, long_s=3600.0):
        from raft_tpu.serving import MultiBurnConfig
        from raft_tpu.serving.metrics import SloConfig

        return FleetAggregator(
            {}, clock=clock, fetch=fixture_fetch,
            config=fed_mod.FleetConfig(multiburn=MultiBurnConfig(
                short=SloConfig(window_s=short_s, target=0.9),
                long=SloConfig(window_s=long_s, target=0.9))))

    def push_counts(self, agg, attained, missed):
        agg.push("a", {"counters_lifetime": {
            "serving.slo.attained": float(attained),
            "serving.slo.missed": float(missed)}})

    def test_first_merge_primes_baseline(self):
        """History predating the aggregator is not re-judged: the
        first merge seeds the delta baseline and records nothing."""
        clock = ManualClock()
        agg = self.make(clock)
        self.push_counts(agg, 1000, 500)
        out = agg.merge()
        assert out["slo"]["burn_rates"] == {"5m": 0.0, "1h": 0.0}
        assert out["slo"]["alert"] is False
        assert tracing.get_gauge("fleet.slo.alert") == 0.0

    def test_alert_fires_when_both_windows_burn(self):
        clock = ManualClock()
        agg = self.make(clock)
        self.push_counts(agg, 100, 0)
        agg.merge()
        # 50% misses over the next merge window: burn 0.5/0.1 = 5.0
        clock.advance(10.0)
        self.push_counts(agg, 110, 10)
        out = agg.merge()
        assert out["slo"]["burn_rates"]["5m"] == pytest.approx(5.0)
        assert out["slo"]["burn_rates"]["1h"] == pytest.approx(5.0)
        assert out["slo"]["alert"] is True
        assert tracing.get_gauge("fleet.slo.alert") == 1.0
        assert tracing.get_gauge(
            "fleet.slo.burn_rate.5m") == pytest.approx(5.0)

    def test_short_window_recovery_clears_alert(self):
        """The multiburn pattern at fleet scope: after the misses age
        out of the SHORT window, healthy merges clear the alert even
        while the long window still burns."""
        clock = ManualClock()
        agg = self.make(clock, short_s=60.0, long_s=3600.0)
        self.push_counts(agg, 100, 0)
        agg.merge()
        clock.advance(10.0)
        self.push_counts(agg, 100, 20)       # a burst of misses
        assert agg.merge()["slo"]["alert"] is True
        # an hour of healthy traffic later: short window clean, long
        # window still carries the burst
        clock.advance(120.0)
        self.push_counts(agg, 400, 20)
        out = agg.merge()
        assert out["slo"]["burn_rates"]["5m"] == 0.0
        assert out["slo"]["burn_rates"]["1h"] > 0.0
        assert out["slo"]["alert"] is False

    def test_no_multiburn_config_no_slo_block(self):
        agg = fixture_aggregator()
        assert "slo" not in agg.fleet_snapshot()
