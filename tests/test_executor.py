"""Serving-path tests: SearchExecutor bucketing, AOT executable cache,
compile-count regression (the steady-state-never-compiles guarantee),
donated top-k state, and bit-identity with the direct search paths."""

import warnings

import numpy as np
import pytest

from raft_tpu import SearchExecutor
from raft_tpu.core import tracing
from raft_tpu.neighbors import brute_force, cagra, ivf_bq, ivf_flat, ivf_pq
from raft_tpu.neighbors.filters import BitmapFilter, BitsetFilter
from raft_tpu.core.bitset import Bitset


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    x = rng.standard_normal((600, 16)).astype(np.float32)
    q = rng.standard_normal((16, 16)).astype(np.float32)
    return x, q


@pytest.fixture(scope="module")
def indexes(data):
    x, _ = data
    return {
        "brute_force": brute_force.build(None, x),
        "ivf_flat": ivf_flat.build(
            None, ivf_flat.IvfFlatIndexParams(n_lists=8), x),
        "ivf_pq": ivf_pq.build(
            None, ivf_pq.IvfPqIndexParams(n_lists=8, pq_dim=8), x),
        "ivf_bq": ivf_bq.build(
            None, ivf_bq.IvfBqIndexParams(n_lists=8), x),
        "cagra": cagra.build(None, cagra.CagraIndexParams(
            graph_degree=8, intermediate_graph_degree=16,
            build_algo=cagra.BuildAlgo.NN_DESCENT), x),
    }


def _direct(name, index, q, k):
    if name == "brute_force":
        return brute_force.search(None, index, q, k)
    if name == "ivf_flat":
        return ivf_flat.search(
            None, ivf_flat.IvfFlatSearchParams(n_probes=8), index, q, k)
    if name == "ivf_pq":
        return ivf_pq.search(
            None, ivf_pq.IvfPqSearchParams(n_probes=8), index, q, k)
    if name == "ivf_bq":
        return ivf_bq.search(
            None, ivf_bq.IvfBqSearchParams(n_probes=8), index, q, k)
    return cagra.search(
        None, cagra.CagraSearchParams(itopk_size=16), index, q, k)


def _params(name):
    return {
        "brute_force": None,
        "ivf_flat": ivf_flat.IvfFlatSearchParams(n_probes=8),
        "ivf_pq": ivf_pq.IvfPqSearchParams(n_probes=8),
        "ivf_bq": ivf_bq.IvfBqSearchParams(n_probes=8),
        "cagra": cagra.CagraSearchParams(itopk_size=16),
    }[name]


class TestBitIdentity:
    """Acceptance: bucketed serving results are bit-identical to the
    direct search path for every index family, at batch sizes that do
    and do not fill their bucket."""

    @pytest.mark.parametrize(
        "name", ["brute_force", "ivf_flat", "ivf_pq", "ivf_bq", "cagra"])
    @pytest.mark.parametrize("q_rows", [3, 11, 16])
    def test_matches_direct(self, data, indexes, name, q_rows):
        _, q = data
        ex = SearchExecutor()
        d0, i0 = _direct(name, indexes[name], q[:q_rows], 5)
        d1, i1 = ex.search(indexes[name], q[:q_rows], 5,
                           params=_params(name))
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    def test_oversized_batch_tiles(self, data, indexes):
        x, _ = data
        rng = np.random.default_rng(3)
        big = rng.standard_normal((70, 16)).astype(np.float32)
        ex = SearchExecutor(min_bucket=8, max_bucket=32)
        d0, i0 = brute_force.search(None, indexes["brute_force"], big, 5)
        d1, i1 = ex.search(indexes["brute_force"], big, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    def test_oversized_batch_cagra_seeds_stay_aligned(self, data, indexes):
        """CAGRA seeds are a pure function of query content
        (graftbeam), so oversized batches tile through the shared
        bucketed executable with rows bit-identical to the direct
        path — no row-offset plumbing."""
        rng = np.random.default_rng(4)
        big = rng.standard_normal((70, 16)).astype(np.float32)
        p = cagra.CagraSearchParams(itopk_size=16)
        ex = SearchExecutor(min_bucket=8, max_bucket=32)
        d0, i0 = cagra.search(None, p, indexes["cagra"], big, 5)
        d1, i1 = ex.search(indexes["cagra"], big, 5, params=p)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    def test_filtered_search(self, data, indexes):
        x, q = data
        # shared bitset filter: ban the first half of the ids
        bs = Bitset.from_mask(
            np.arange(x.shape[0]) >= x.shape[0] // 2)
        ex = SearchExecutor()
        p = ivf_flat.IvfFlatSearchParams(n_probes=8)
        d0, i0 = ivf_flat.search(None, p, indexes["ivf_flat"], q[:9], 5,
                                 sample_filter=BitsetFilter(bs))
        d1, i1 = ex.search(indexes["ivf_flat"], q[:9], 5, params=p,
                           sample_filter=BitsetFilter(bs))
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
        # per-query bitmap filter: pad rows get all-zero words
        mask = np.ones((9, x.shape[0]), bool)
        mask[:, ::3] = False
        bm = BitmapFilter.from_mask(mask)
        d0, i0 = ivf_flat.search(None, p, indexes["ivf_flat"], q[:9], 5,
                                 sample_filter=bm)
        d1, i1 = ex.search(indexes["ivf_flat"], q[:9], 5, params=p,
                           sample_filter=bm)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))


class TestCompileRegression:
    """Tier-1 guarantee: within one bucket, steady-state serving
    triggers ZERO new XLA compilations — asserted against jax's own
    backend-compile monitoring events, not just the executor's
    bookkeeping."""

    def test_zero_recompiles_within_bucket(self, data, indexes):
        _, q = data
        tracing.install_xla_compile_listener()
        ex = SearchExecutor()
        # prime: each batch size once (the search executable compiles
        # once per bucket; tiny pad/slice programs compile per size)
        for n in (16, 13, 9):
            ex.search(indexes["brute_force"], q[:n], 5)
        compiles0 = ex.stats.compile_count
        assert compiles0 == 1  # one bucket -> one search executable
        backend0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        # steady state: repeats at varying batch sizes within the bucket
        for n in (16, 13, 9, 13, 16, 9):
            d, i = ex.search(indexes["brute_force"], q[:n], 5)
        assert ex.stats.compile_count == compiles0
        assert tracing.get_counter(tracing.XLA_COMPILE_COUNT) == backend0
        assert ex.stats.cache_hits >= 8

    def test_counters_exported_via_tracing(self, data, indexes):
        _, q = data
        base = tracing.get_counter("serving.compile_count")
        ex = SearchExecutor()
        ex.search(indexes["ivf_bq"], q, 5)
        assert tracing.get_counter("serving.compile_count") >= base + 1


class TestWarmup:
    def test_warmup_precompiles(self, data, indexes):
        _, q = data
        ex = SearchExecutor()
        secs = ex.warmup(indexes["ivf_flat"], buckets=(8, 16), k=5,
                         params=ivf_flat.IvfFlatSearchParams(n_probes=8))
        assert secs > 0 and ex.stats.warmup_seconds == secs
        assert ex.stats.compile_count == 2
        # first real traffic is a cache hit, not a compile
        d, i = ex.search(indexes["ivf_flat"], q[:5], 5,
                         params=ivf_flat.IvfFlatSearchParams(n_probes=8))
        assert ex.stats.compile_count == 2
        assert ex.stats.cache_hits == 1
        assert np.isfinite(np.asarray(d)).all()

    def test_warmup_rejects_unknown_bucket(self, indexes):
        from raft_tpu.core.validation import RaftError

        ex = SearchExecutor(min_bucket=8, max_bucket=32)
        with pytest.raises(RaftError):
            ex.warmup(indexes["brute_force"], buckets=(7,), k=5)


class TestCacheAndState:
    def test_lru_eviction(self, data, indexes):
        _, q = data
        ex = SearchExecutor(max_entries=1)
        ex.search(indexes["brute_force"], q[:4], 5)
        ex.search(indexes["ivf_flat"], q[:4], 5,
                  params=ivf_flat.IvfFlatSearchParams(n_probes=8))
        assert ex.stats.evictions == 1
        # the evicted brute-force entry recompiles on return
        ex.search(indexes["brute_force"], q[:4], 5)
        assert ex.stats.compile_count == 3

    def test_donated_state_keeps_results_valid(self, data, indexes):
        """With donation forced on, results returned from call N must
        survive call N+1 reusing the state storage."""
        _, q = data
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # cpu ignores donation
            ex = SearchExecutor(donate=True)
            d1, i1 = ex.search(indexes["brute_force"], q[:16], 5)
            d1c, i1c = np.asarray(d1).copy(), np.asarray(i1).copy()
            d2, i2 = ex.search(indexes["brute_force"], q[:9], 5)
            np.testing.assert_array_equal(np.asarray(d1), d1c)
            np.testing.assert_array_equal(np.asarray(i1), i1c)
            d0, i0 = brute_force.search(None, indexes["brute_force"],
                                        q[:9], 5)
            np.testing.assert_array_equal(np.asarray(i0), np.asarray(i2))
            np.testing.assert_array_equal(np.asarray(d0), np.asarray(d2))

    def test_empty_batch(self, indexes):
        ex = SearchExecutor()
        d, i = ex.search(indexes["brute_force"], np.zeros((0, 16),
                                                          np.float32), 5)
        assert d.shape == (0, 5) and i.shape == (0, 5)

    def test_unsupported_index_type(self):
        ex = SearchExecutor()
        with pytest.raises(TypeError):
            ex.search(object(), np.zeros((2, 4), np.float32), 1)


class TestCostIntrospection:
    """graftscope (PR 6): compile-time cost_analysis/memory_analysis
    capture, per-executable gauges, and the modeled-work counters the
    live achieved-GB/s derivation stands on."""

    def test_cost_table_and_gauges_after_compile(self, data, indexes):
        _, q = data
        ex = SearchExecutor()
        ex.search(indexes["ivf_flat"], q, 5,
                  params=ivf_flat.IvfFlatSearchParams(n_probes=4))
        costs = ex.executable_costs()
        assert len(costs) == 1
        digest, info = next(iter(costs.items()))
        assert info["family"] == "ivf_flat"
        assert info["bucket"] == 16 and info["k"] == 5
        assert info["bytes_accessed"] > 0
        assert info["peak_hbm_bytes"] > 0
        assert info["compile_seconds"] > 0
        base = f"serving.executable.{digest}."
        assert tracing.get_gauge(base + "bytes_accessed") == (
            info["bytes_accessed"])
        assert tracing.get_gauge(base + "peak_hbm_bytes") == (
            info["peak_hbm_bytes"])
        assert tracing.get_gauge(
            "serving.executor.cached_executables") == 1.0

    def test_modeled_counters_advance_per_call_not_per_compile(
            self, data, indexes):
        _, q = data
        ex = SearchExecutor()
        tracing.reset_counters("serving.execute.")
        ex.warmup(indexes["brute_force"], buckets=(16,), k=5)
        # warmup compiles but dispatches nothing
        assert tracing.get_counter("serving.execute.calls") == 0
        ex.search(indexes["brute_force"], q, 5)
        ex.search(indexes["brute_force"], q, 5)
        assert tracing.get_counter("serving.execute.calls") == 2
        per_call = ex.executable_costs()
        (info,) = per_call.values()
        assert tracing.get_counter(
            "serving.execute.modeled_bytes") == pytest.approx(
                2 * info["bytes_accessed"])
        assert tracing.get_counter(
            "serving.execute.rows") == 2 * q.shape[0]

    def test_publish_cost_gauges_survives_gauge_reset(self, data, indexes):
        """``metrics.reset()`` clears the serving gauge namespace while
        the AOT cache keeps its executables; ``publish_cost_gauges()``
        (the exporter's scrape-time refresh) restores the per-executable
        gauges so /metrics and executable_costs() agree again."""
        _, q = data
        ex = SearchExecutor()
        ex.search(indexes["brute_force"], q, 5)
        (digest,) = ex.executable_costs()
        base = f"serving.executable.{digest}."
        tracing.reset_gauges("serving.")
        assert tracing.gauges(base) == {}
        ex.publish_cost_gauges()
        info = ex.executable_costs()[digest]
        assert tracing.get_gauge(base + "bytes_accessed") == (
            info["bytes_accessed"])
        assert tracing.get_gauge(
            "serving.executor.cached_executables") == 1.0

    def test_eviction_retires_cost_gauges(self, data, indexes):
        _, q = data
        ex = SearchExecutor(max_entries=1)
        ex.search(indexes["brute_force"], q, 5)
        first = set(ex.executable_costs())
        ex.search(indexes["brute_force"], q, 7)   # evicts k=5 entry
        second = set(ex.executable_costs())
        assert len(second) == 1 and first != second
        gone = first.pop()
        assert tracing.gauges(f"serving.executable.{gone}.") == {}
        assert tracing.get_gauge(
            f"serving.executable.{second.pop()}.bytes_accessed") > 0


class TestProbeAccounting:
    """graftgauge (PR 8): device-side probe-frequency accounting —
    a donated int32 counter plane scatter-added inside the jitted IVF
    search bodies. Acceptance: bit-identity and zero-recompile stay
    green with accounting ON, counts are exact (inert bucket-pad rows
    masked), and the counters surface only at scrape time."""

    IVF = ("ivf_flat", "ivf_pq", "ivf_bq")

    @pytest.mark.parametrize("name", IVF)
    def test_bit_identity_with_accounting_on(self, data, indexes, name):
        _, q = data
        ex = SearchExecutor(probe_accounting=True)
        d1, i1 = ex.search(indexes[name], q, 5, params=_params(name))
        d0, i0 = _direct(name, indexes[name], q, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    @pytest.mark.parametrize("name", IVF)
    def test_exact_counts_and_pad_masking(self, data, indexes, name):
        """Every dispatch adds exactly rows * n_probes to the plane —
        13 rows pad to the 16-bucket, and the 3 phantom rows' probe
        selections must NOT pollute the histogram."""
        _, q = data
        ex = SearchExecutor(probe_accounting=True)
        for _ in range(3):
            ex.search(indexes[name], q[:13], 5, params=_params(name))
        (plane,) = ex.probe_frequencies().values()
        assert plane.shape == (8,)          # n_lists
        assert plane.sum() == 3 * 13 * 8    # calls * rows * n_probes
        assert (plane >= 0).all()

    def test_zero_recompile_steady_state(self, data, indexes):
        _, q = data
        tracing.install_xla_compile_listener()
        ex = SearchExecutor(probe_accounting=True)
        sp = ivf_flat.IvfFlatSearchParams(n_probes=4)
        for n in (16, 13, 9):
            ex.search(indexes["ivf_flat"], q[:n], 5, params=sp)
        compiles0 = ex.stats.compile_count
        backend0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        for n in (16, 13, 9, 13, 16, 9):
            ex.search(indexes["ivf_flat"], q[:n], 5, params=sp)
        assert ex.stats.compile_count == compiles0
        assert tracing.get_counter(tracing.XLA_COMPILE_COUNT) == backend0

    def test_accounting_is_a_distinct_executable(self, data, indexes):
        """The counter plane changes the compiled signature, so the
        flag joins the cache key — an accounting executor and a plain
        one must not collide in the persistent compile cache."""
        _, q = data
        ex_on = SearchExecutor(probe_accounting=True)
        ex_off = SearchExecutor(probe_accounting=False)
        sp = ivf_flat.IvfFlatSearchParams(n_probes=4)
        kon = ex_on._plan(indexes["ivf_flat"], sp, 5, 16, None, {}).key
        koff = ex_off._plan(indexes["ivf_flat"], sp, 5, 16, None, {}).key
        assert kon != koff
        assert "probe_accounting" in kon

    def test_off_by_default_no_planes(self, data, indexes):
        _, q = data
        ex = SearchExecutor()
        ex.search(indexes["ivf_flat"], q, 5,
                  params=ivf_flat.IvfFlatSearchParams(n_probes=4))
        assert ex.probe_frequencies() == {}
        assert ex.probe_label(indexes["ivf_flat"]) is None

    def test_non_ivf_families_unaffected(self, data, indexes):
        _, q = data
        ex = SearchExecutor(probe_accounting=True)
        d1, i1 = ex.search(indexes["brute_force"], q, 5)
        d0, i0 = _direct("brute_force", indexes["brute_force"], q, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        assert ex.probe_frequencies() == {}

    def test_publish_probe_gauges_and_lifetime_counter(
            self, data, indexes):
        _, q = data
        tracing.reset_counters("index.")
        tracing.reset_gauges("index.")
        ex = SearchExecutor(probe_accounting=True)
        sp = ivf_flat.IvfFlatSearchParams(n_probes=4)
        ex.search(indexes["ivf_flat"], q, 5, params=sp)
        label = ex.probe_label(indexes["ivf_flat"])
        assert label is not None and "." not in label
        stats = ex.publish_probe_gauges(top_n=3)[label]
        base = f"index.probe_freq.{label}."
        assert tracing.get_gauge(base + "total") == 16 * 4
        assert 0.0 < tracing.get_gauge(base + "probed_fraction") <= 1.0
        assert (tracing.get_gauge(base + "coverage_p01")
                <= tracing.get_gauge(base + "coverage_p10"))
        assert len(stats["top"]) <= 3
        for lid, c in stats["top"]:
            assert tracing.get_gauge(f"{base}list.{lid}") == float(c)
        # the monotone counter mirror — what the CI snapshot floors
        # check — reflects exactly what came off the device
        assert tracing.get_counter(
            "index.probe_freq.accounted") == 16 * 4
        ex.search(indexes["ivf_flat"], q, 5, params=sp)
        ex.publish_probe_gauges(top_n=3)
        assert tracing.get_counter(
            "index.probe_freq.accounted") == 2 * 16 * 4
        # the per-dispatch host heartbeat
        assert tracing.get_counter("index.probe.dispatches") == 2
        assert tracing.get_counter("index.probe.rows") == 32

    def test_stale_topn_samples_retire(self, data, indexes):
        _, q = data
        ex = SearchExecutor(probe_accounting=True)
        ex.search(indexes["ivf_flat"], q, 5,
                  params=ivf_flat.IvfFlatSearchParams(n_probes=4))
        label = ex.probe_label(indexes["ivf_flat"])
        base = f"index.probe_freq.{label}.list."
        ex.publish_probe_gauges(top_n=8)
        # fake a stale sample, then republishing must retire it
        tracing.set_gauge(base + "9999", 123.0)
        ex.publish_probe_gauges(top_n=8)
        assert base + "9999" not in tracing.gauges(base)


class TestRaggedPlans:
    """The ragged packed-batch plan family: one AOT entry per (index
    shapes, params class, tile) serves every load shape — bit-identical
    per request to the bucketed path, zero-recompile steady state."""

    @pytest.fixture(scope="class")
    def ragged_setup(self):
        rng = np.random.default_rng(11)
        x = rng.standard_normal((2000, 24)).astype(np.float32)
        index = ivf_flat.build(
            None, ivf_flat.IvfFlatIndexParams(n_lists=16), x)
        return x, index, rng

    @pytest.mark.parametrize("engine", ["pallas", "xla"])
    def test_bit_identical_to_bucketed_per_engine(self, ragged_setup,
                                                  engine):
        """pallas ≡ xla ≡ bucketed per packed request, with mixed
        per-request n_probes AND k in one params class."""
        _, index, rng = ragged_setup
        ex = SearchExecutor(ragged_tile=16)
        blocks = [rng.standard_normal((m, 24)).astype(np.float32)
                  for m in (3, 2, 4, 1)]
        nps, ks = [5, 8, 2, 7], [3, 7, 5, 8]
        ps = [ivf_flat.IvfFlatSearchParams(n_probes=n,
                                           scan_engine=engine)
              for n in nps]
        keys = {ex.ragged_key(index, k, params=p)
                for k, p in zip(ks, ps)}
        assert len(keys) == 1 and None not in keys
        res = ex.search_ragged(index, blocks, ks, params_list=ps)
        for b, k, p, (d, i) in zip(blocks, ks, ps, res):
            dd, ii = ex.search(index, b, k, params=p)
            np.testing.assert_array_equal(np.asarray(i), np.asarray(ii))
            np.testing.assert_array_equal(np.asarray(d), np.asarray(dd))

    def test_single_request_batch(self, ragged_setup):
        _, index, rng = ragged_setup
        ex = SearchExecutor(ragged_tile=16)
        p = ivf_flat.IvfFlatSearchParams(n_probes=5, scan_engine="xla")
        b = rng.standard_normal((5, 24)).astype(np.float32)
        (d, i), = ex.search_ragged(index, [b], 4, params_list=p)
        dd, ii = ex.search(index, b, 4, params=p)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(ii))
        np.testing.assert_array_equal(np.asarray(d), np.asarray(dd))
        assert d.shape == (5, 4)

    def test_one_executable_zero_recompile(self, ragged_setup):
        """warmup_ragged compiles the ONE executable; mixed load
        shapes then never compile again (asserted against the XLA
        backend counter) and the cache holds exactly one ragged
        entry."""
        _, index, rng = ragged_setup
        ex = SearchExecutor(ragged_tile=16)
        p = ivf_flat.IvfFlatSearchParams(n_probes=6, scan_engine="xla")
        ex.warmup_ragged(index, k=8, params=p)
        assert ex.ragged_executables() == 1
        tracing.install_xla_compile_listener()
        # first call set: each distinct total-row count pays its tiny
        # pad/concat program once (the bucketed small print), so churn
        # the shapes once before measuring
        shapes = [(1,), (3, 2), (5, 7, 4), (16,), (2, 2, 2)]
        for sizes in shapes:
            blocks = [rng.standard_normal((m, 24)).astype(np.float32)
                      for m in sizes]
            ex.search_ragged(index, blocks, 8, params_list=p)
        before = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        for sizes in shapes:
            blocks = [rng.standard_normal((m, 24)).astype(np.float32)
                      for m in sizes]
            ex.search_ragged(index, blocks, 8, params_list=p)
        assert tracing.get_counter(tracing.XLA_COMPILE_COUNT) == before
        assert ex.ragged_executables() == 1

    def test_distinct_params_class_distinct_key(self, ragged_setup):
        _, index, _ = ragged_setup
        ex = SearchExecutor(ragged_tile=16)
        p_small = ivf_flat.IvfFlatSearchParams(n_probes=5,
                                               scan_engine="xla")
        p_big = ivf_flat.IvfFlatSearchParams(n_probes=16,
                                             scan_engine="xla")
        k1 = ex.ragged_key(index, 4, params=p_small)
        k2 = ex.ragged_key(index, 4, params=p_big)     # np class 8 vs 16
        k3 = ex.ragged_key(index, 40, params=p_small)  # k class 8 vs 64
        assert k1 != k2 and k1 != k3

    def test_not_raggable_falls_back(self, ragged_setup, indexes):
        _, index, _ = ragged_setup
        ex = SearchExecutor()
        # rank engine / approx coarse / other families: bucketed only
        assert ex.ragged_key(index, 4, params=ivf_flat.IvfFlatSearchParams(
            n_probes=5, scan_engine="rank")) is None
        assert ex.ragged_key(index, 4, params=ivf_flat.IvfFlatSearchParams(
            n_probes=5, coarse_algo="approx")) is None
        # CAGRA packs since graftbeam (content-pure seeds) — only a k
        # class cap past itopk_size refuses
        assert ex.ragged_key(indexes["cagra"], 4,
                             params=cagra.CagraSearchParams()) is not None
        assert ex.ragged_key(
            indexes["cagra"], 40,
            params=cagra.CagraSearchParams(itopk_size=16)) is None
        assert ex.ragged_key(indexes["brute_force"], 4) is None

    def test_tile_overflow_streams_chunks(self, ragged_setup):
        """Totals past one tile stream through the SAME executable —
        results identical, no second specialization."""
        _, index, rng = ragged_setup
        ex = SearchExecutor(ragged_tile=8)
        p = ivf_flat.IvfFlatSearchParams(n_probes=5, scan_engine="xla")
        blocks = [rng.standard_normal((m, 24)).astype(np.float32)
                  for m in (6, 7, 9)]           # 22 rows -> 3 chunks
        res = ex.search_ragged(index, blocks, 6, params_list=p)
        assert ex.ragged_executables() == 1
        for b, (d, i) in zip(blocks, res):
            dd, ii = ex.search(index, b, 6, params=p)
            np.testing.assert_array_equal(np.asarray(i), np.asarray(ii))
            np.testing.assert_array_equal(np.asarray(d), np.asarray(dd))

    def test_empty_total_returns_empties(self, ragged_setup):
        _, index, _ = ragged_setup
        ex = SearchExecutor(ragged_tile=8)
        p = ivf_flat.IvfFlatSearchParams(n_probes=5, scan_engine="xla")
        res = ex.search_ragged(
            index, [np.zeros((0, 24), np.float32)], 4, params_list=p)
        assert res[0][0].shape == (0, 4)

    def test_2d_filter_rows_pack_adjacently(self, ragged_setup):
        """Per-request 2-D filter rows concatenate to the packed rows
        and mask exactly as the bucketed path does per request."""
        x, index, rng = ragged_setup
        ex = SearchExecutor(ragged_tile=16)
        p = ivf_flat.IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        sizes = (3, 5)
        blocks = [rng.standard_normal((m, 24)).astype(np.float32)
                  for m in sizes]
        mask = rng.random((sum(sizes), len(x))) < 0.5
        bm = BitmapFilter.from_mask(mask)
        res = ex.search_ragged(index, blocks, 6, params_list=p,
                               sample_filter=bm)
        row = 0
        for b, m, (d, i) in zip(blocks, sizes, res):
            bm_j = BitmapFilter.from_mask(mask[row:row + m])
            dd, ii = ex.search(index, b, 6, params=p, sample_filter=bm_j)
            np.testing.assert_array_equal(np.asarray(i), np.asarray(ii))
            np.testing.assert_array_equal(np.asarray(d), np.asarray(dd))
            ids = np.asarray(i)
            valid = ids >= 0
            rows_of = np.repeat(np.arange(m), 6).reshape(m, 6)
            assert mask[row:row + m][rows_of[valid], ids[valid]].all()
            row += m

    def test_shared_1d_filter(self, ragged_setup):
        x, index, rng = ragged_setup
        ex = SearchExecutor(ragged_tile=16)
        p = ivf_flat.IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        filt = BitsetFilter(Bitset.from_mask(np.arange(len(x)) % 2 == 0))
        blocks = [rng.standard_normal((m, 24)).astype(np.float32)
                  for m in (4, 3)]
        res = ex.search_ragged(index, blocks, 5, params_list=p,
                               sample_filter=filt)
        for b, (d, i) in zip(blocks, res):
            dd, ii = ex.search(index, b, 5, params=p, sample_filter=filt)
            np.testing.assert_array_equal(np.asarray(i), np.asarray(ii))
            ids = np.asarray(i)
            assert (ids[ids >= 0] % 2 == 0).all()

    def test_probe_accounting_counts_exactly(self, ragged_setup):
        """The donated probe plane counts each packed request's OWN
        n_probes per row — pad rows and masked slots contribute
        nothing, and the plane is shared with the bucketed plans."""
        _, index, rng = ragged_setup
        ex = SearchExecutor(ragged_tile=16, probe_accounting=True)
        p1 = ivf_flat.IvfFlatSearchParams(n_probes=5, scan_engine="xla")
        p2 = ivf_flat.IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        blocks = [rng.standard_normal((m, 24)).astype(np.float32)
                  for m in (3, 2)]
        ex.search_ragged(index, blocks, 4, params_list=[p1, p2])
        planes = ex.probe_frequencies()
        total = sum(int(v.sum()) for v in planes.values())
        assert total == 3 * 5 + 2 * 8
        # bucketed dispatch folds into the SAME plane
        ex.search(index, blocks[0], 4, params=p1)
        planes = ex.probe_frequencies()
        assert sum(int(v.sum()) for v in planes.values()) == \
            total + 3 * 5


class TestBqEngines:
    """RaBitQ IVF-BQ through the executor: the resolved scan engine
    is in the AOT cache key (engine switch = distinct executable),
    each fused engine is bit-identical to the direct search at every
    bucket occupancy, and steady state stays zero-recompile."""

    @pytest.mark.parametrize("engine", ["pallas", "xla", "rank"])
    @pytest.mark.parametrize("rows", [16, 13, 9])
    def test_bit_identity_per_engine(self, data, indexes, engine, rows):
        _, q = data
        sp = ivf_bq.IvfBqSearchParams(n_probes=8, scan_engine=engine)
        ex = SearchExecutor()
        d1, i1 = ex.search(indexes["ivf_bq"], q[:rows], 5, params=sp)
        d0, i0 = ivf_bq.search(None, sp, indexes["ivf_bq"], q[:rows], 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))

    def test_engine_in_cache_key_and_zero_recompile(self, data, indexes):
        _, q = data
        tracing.install_xla_compile_listener()
        ex = SearchExecutor()
        sp_x = ivf_bq.IvfBqSearchParams(n_probes=8, scan_engine="xla")
        for n in (16, 13, 9):
            ex.search(indexes["ivf_bq"], q[:n], 5, params=sp_x)
        assert ex.stats.compile_count == 1
        backend0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        for n in (16, 13, 9, 13, 16):
            ex.search(indexes["ivf_bq"], q[:n], 5, params=sp_x)
        assert ex.stats.compile_count == 1
        assert tracing.get_counter(tracing.XLA_COMPILE_COUNT) == backend0
        # engine switch compiles a DISTINCT executable (engine is in
        # the key); epsilon is a static too — both fork deliberately
        ex.search(indexes["ivf_bq"], q, 5,
                  params=ivf_bq.IvfBqSearchParams(n_probes=8,
                                                  scan_engine="pallas"))
        assert ex.stats.compile_count == 2
        ex.search(indexes["ivf_bq"], q, 5,
                  params=ivf_bq.IvfBqSearchParams(n_probes=8,
                                                  scan_engine="rank"))
        assert ex.stats.compile_count == 3

    def test_codes_only_index_degrades_to_rank(self, data):
        """An index without the rerank plane serves through the
        executor on the estimate-only path (auto resolves to rank),
        bit-identical to the direct search."""
        x, q = data
        idx = ivf_bq.build(None, ivf_bq.IvfBqIndexParams(
            n_lists=8, store_vectors=False), x)
        sp = ivf_bq.IvfBqSearchParams(n_probes=8)
        ex = SearchExecutor()
        d1, i1 = ex.search(idx, q[:9], 5, params=sp)
        d0, i0 = ivf_bq.search(None, sp, idx, q[:9], 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))


class TestRaggedFamilies:
    """graftragged: the PQ and BQ fronts serve through the SAME
    ragged plan family — bit-identical per request to the bucketed
    path, one executable per (index shapes, params class, tile), and
    the documented non-raggable residue falls back with an explicit
    reason."""

    @pytest.fixture(scope="class")
    def fam_setup(self):
        rng = np.random.default_rng(23)
        x = rng.standard_normal((2000, 32)).astype(np.float32)
        return (
            ivf_pq.build(None, ivf_pq.IvfPqIndexParams(
                n_lists=16, pq_dim=8), x),
            ivf_bq.build(None, ivf_bq.IvfBqIndexParams(
                n_lists=16, bits=2), x),
            rng,
        )

    def test_pq_bit_identical_and_zero_recompile(self, fam_setup):
        pq_index, _, rng = fam_setup
        ex = SearchExecutor(ragged_tile=16)
        p1 = ivf_pq.IvfPqSearchParams(n_probes=5, scan_engine="xla")
        p2 = ivf_pq.IvfPqSearchParams(n_probes=8, scan_engine="xla")
        # mixed n_probes AND k inside one pow2 class share the key
        assert (ex.ragged_key(pq_index, 4, params=p1)
                == ex.ragged_key(pq_index, 7, params=p2))
        ex.warmup_ragged(pq_index, k=7, params=p1)
        assert ex.ragged_executables("ivf_pq") == 1
        tracing.install_xla_compile_listener()
        blocks = [rng.standard_normal((m, 32)).astype(np.float32)
                  for m in (3, 5, 2, 9)]
        c0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        res = ex.search_ragged(pq_index, blocks, [4, 7, 6, 5],
                               params_list=[p1, p2, p1, p2])
        assert (tracing.get_counter(tracing.XLA_COMPILE_COUNT)
                - c0 == 0)
        assert ex.ragged_executables("ivf_pq") == 1
        for b, (d, i), kj, pj in zip(blocks, res, [4, 7, 6, 5],
                                     [p1, p2, p1, p2]):
            sd, si = ex.search(pq_index, b, kj, params=pj)
            np.testing.assert_array_equal(i, np.asarray(si))
            np.testing.assert_array_equal(d, np.asarray(sd))

    def test_bq_bit_identical_and_zero_recompile(self, fam_setup):
        _, bq_index, rng = fam_setup
        ex = SearchExecutor(ragged_tile=16)
        pb = ivf_bq.IvfBqSearchParams(n_probes=6, scan_engine="xla")
        pb2 = ivf_bq.IvfBqSearchParams(n_probes=3, scan_engine="xla")
        ex.warmup_ragged(bq_index, k=5, params=pb)
        assert ex.ragged_executables("ivf_bq") == 1
        tracing.install_xla_compile_listener()
        blocks = [rng.standard_normal((m, 32)).astype(np.float32)
                  for m in (4, 2, 7)]
        c0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        res = ex.search_ragged(bq_index, blocks, [5, 3, 4],
                               params_list=[pb, pb2, pb])
        assert (tracing.get_counter(tracing.XLA_COMPILE_COUNT)
                - c0 == 0)
        for b, (d, i), kj, pj in zip(blocks, res, [5, 3, 4],
                                     [pb, pb2, pb]):
            sd, si = ex.search(bq_index, b, kj, params=pj)
            np.testing.assert_array_equal(i, np.asarray(si))
            np.testing.assert_array_equal(d, np.asarray(sd))

    def test_probe_accounting_shared_with_bucketed(self, fam_setup):
        """The PQ ragged plan threads the SAME donated probe plane as
        the bucketed plans — one cumulative histogram per index,
        exact across the path split."""
        pq_index, _, rng = fam_setup
        ex = SearchExecutor(ragged_tile=16, probe_accounting=True)
        p1 = ivf_pq.IvfPqSearchParams(n_probes=4, scan_engine="xla")
        b1 = rng.standard_normal((3, 32)).astype(np.float32)
        b2 = rng.standard_normal((2, 32)).astype(np.float32)
        ex.search_ragged(pq_index, [b1, b2], 4, params_list=p1)
        ex.search(pq_index, b1, 4, params=p1)        # bucketed leg
        planes = ex.probe_frequencies()
        (label,) = planes.keys()
        assert label.startswith("ivf_pq-")
        # every dispatched row probed exactly n_probes=4 lists
        assert planes[label].sum() == (3 + 2 + 3) * 4

    def test_residue_reasons(self, fam_setup, indexes, data):
        pq_index, bq_index, _ = fam_setup
        x, _ = data
        ex = SearchExecutor()
        assert ex.ragged_fallback_reason(
            pq_index, 4, params=ivf_pq.IvfPqSearchParams(
                scan_engine="rank")).startswith("scan_engine")
        assert ex.ragged_fallback_reason(
            pq_index, 4, params=ivf_pq.IvfPqSearchParams(
                coarse_algo="approx")).startswith("coarse_algo")
        # CAGRA's only residue since graftbeam: a k class cap the beam
        # buffer cannot carry
        assert ex.ragged_fallback_reason(
            indexes["cagra"], 20,
            params=cagra.CagraSearchParams(
                itopk_size=16)).startswith("cagra")
        assert ex.ragged_fallback_reason(
            indexes["cagra"], 4,
            params=cagra.CagraSearchParams(itopk_size=16)) is None
        assert ex.ragged_fallback_reason(
            indexes["brute_force"], 4).startswith("brute_force")
        # codes-only BQ resolves to the rank estimate scan
        codes_only = ivf_bq.build(None, ivf_bq.IvfBqIndexParams(
            n_lists=8, store_vectors=False), x)
        assert ex.ragged_key(codes_only, 4) is None
        assert "rank" in ex.ragged_fallback_reason(codes_only, 4)
        # raggable combinations report no reason
        assert ex.ragged_fallback_reason(
            bq_index, 4, params=ivf_bq.IvfBqSearchParams(
                scan_engine="xla")) is None


class TestRaggedDualTile:
    """The opt-in small/large tile pair: tile selection happens at
    dispatch by packed-row count, the packing key never forks, and
    steady state holds at ≤ 2 executables per params class."""

    @pytest.fixture(scope="class")
    def dual_setup(self):
        rng = np.random.default_rng(29)
        x = rng.standard_normal((1500, 24)).astype(np.float32)
        index = ivf_flat.build(
            None, ivf_flat.IvfFlatIndexParams(n_lists=16), x)
        return x, index, rng

    def test_warmup_compiles_both_tiles(self, dual_setup):
        _, index, _ = dual_setup
        p = ivf_flat.IvfFlatSearchParams(n_probes=6)
        ex = SearchExecutor(ragged_tile=32, ragged_tile_small=8)
        ex.warmup_ragged(index, k=4, params=p)
        assert ex.ragged_executables() == 2
        assert ex.ragged_executables("ivf_flat") == 2

    def test_dispatch_selects_tile_and_stays_compiled(self, dual_setup):
        _, index, rng = dual_setup
        p = ivf_flat.IvfFlatSearchParams(n_probes=6)
        ex = SearchExecutor(ragged_tile=32, ragged_tile_small=8)
        ex.warmup_ragged(index, k=4, params=p)
        tracing.install_xla_compile_listener()
        small = [rng.standard_normal((3, 24)).astype(np.float32)]
        big = [rng.standard_normal((9, 24)).astype(np.float32)
               for _ in range(5)]
        c0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        tracing.reset_counters("serving.execute.")
        res_s = ex.search_ragged(index, small, 4, params_list=p)
        res_b = ex.search_ragged(index, big, 4, params_list=p)
        assert (tracing.get_counter(tracing.XLA_COMPILE_COUNT)
                - c0 == 0)
        assert ex.ragged_executables() == 2
        # the split counters attribute the dispatches per tile
        assert tracing.get_counter(
            "serving.execute.padded_rows.p8.t8") == 8.0
        assert tracing.get_counter(
            "serving.execute.padded_rows.p8.t32") == 64.0
        from raft_tpu.serving import metrics as sv_metrics

        by_class = sv_metrics.derived()["pad_waste_by_class"]
        assert set(by_class) >= {"p8.t8", "p8.t32"}
        # both tiles are bit-identical to the bucketed path
        sd, si = ex.search(index, small[0], 4, params=p)
        np.testing.assert_array_equal(res_s[0][1], np.asarray(si))
        for b, (d, i) in zip(big, res_b):
            _, si = ex.search(index, b, 4, params=p)
            np.testing.assert_array_equal(i, np.asarray(si))

    def test_tile_never_joins_the_key(self, dual_setup):
        _, index, _ = dual_setup
        p = ivf_flat.IvfFlatSearchParams(n_probes=6)
        ex1 = SearchExecutor(ragged_tile=32)
        ex2 = SearchExecutor(ragged_tile=32, ragged_tile_small=8)
        assert (ex1.ragged_key(index, 4, params=p)
                == ex2.ragged_key(index, 4, params=p))
