"""Direct smoke coverage for public API entries that were only
exercised indirectly (found by diffing docs/api.md against the test
corpus) — each asserts real semantics, not just 'does not throw'."""

import numpy as np

import jax
import jax.numpy as jnp


class TestLinalgExtras:
    def test_eig_jacobi_eigen_property(self, rng_np):
        from raft_tpu.linalg import eig_jacobi

        a = rng_np.standard_normal((12, 12)).astype(np.float32)
        a = a @ a.T
        # eig_jacobi currently delegates to eig_dc (kept for API
        # parity), so the signal here is the eigen-property itself:
        # A v = w v with ascending w, (vectors, values) return order
        vj, wj = eig_jacobi(None, a)
        assert (np.diff(np.asarray(wj)) >= -1e-4).all()
        av = a @ np.asarray(vj)
        np.testing.assert_allclose(av, np.asarray(vj) * np.asarray(wj),
                                   rtol=1e-2, atol=1e-2)

    def test_map_reduce(self, rng_np):
        from raft_tpu.linalg import map_reduce

        x = rng_np.standard_normal((100,)).astype(np.float32)
        got = map_reduce(None, jnp.asarray(x), jnp.square)
        np.testing.assert_allclose(float(got), float((x ** 2).sum()),
                                   rtol=1e-5)


class TestFusedL2NNPrecomputed:
    def test_matches_plain_variant(self, rng_np):
        from raft_tpu.distance.fused_l2_nn import (
            fused_l2_nn_argmin,
            fused_l2_nn_argmin_precomputed,
        )

        x = rng_np.standard_normal((40, 16)).astype(np.float32)
        y = rng_np.standard_normal((30, 16)).astype(np.float32)
        d0, i0 = fused_l2_nn_argmin(None, x, y)
        yn = (y.astype(np.float32) ** 2).sum(1)
        d1, i1 = fused_l2_nn_argmin_precomputed(x, y, yn)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_allclose(np.asarray(d0), np.asarray(d1),
                                   rtol=1e-5, atol=1e-5)


class TestSparseOpsExtras:
    def test_coo_sort_orders_and_pads_last(self):
        from raft_tpu.sparse.ops import coo_sort
        from raft_tpu.sparse.types import COO

        coo = COO(jnp.asarray([2, 0, -1, 1], jnp.int32),
                  jnp.asarray([1, 3, 0, 2], jnp.int32),
                  jnp.asarray([1.0, 2.0, 0.0, 3.0]), (3, 4))
        out = coo_sort(coo)
        assert np.asarray(out.rows).tolist() == [0, 1, 2, -1]
        assert np.asarray(out.cols).tolist() == [3, 2, 1, 0]

    def test_csr_row_op(self, rng_np):
        import scipy.sparse as sp

        from raft_tpu.sparse.ops import csr_row_op
        from raft_tpu.sparse.types import CSR

        m = sp.random(6, 8, density=0.4, random_state=0,
                      format="csr", dtype=np.float32)
        csr = CSR.from_scipy(m)
        out = csr_row_op(csr, lambda r, v: v * (r + 1).astype(v.dtype))
        want = m.toarray() * (np.arange(6) + 1)[:, None]
        np.testing.assert_allclose(np.asarray(out.to_dense()), want,
                                   rtol=1e-6)

    def test_coo_dense_roundtrip(self, rng_np):
        from raft_tpu.sparse.convert import coo_to_dense, dense_to_coo

        d = rng_np.standard_normal((5, 7)).astype(np.float32)
        d[d < 0.5] = 0
        coo = dense_to_coo(d)
        np.testing.assert_allclose(np.asarray(coo_to_dense(coo)), d)


class TestMatrixPrint:
    def test_prints_shape_and_values(self, capsys):
        from raft_tpu.matrix.ops import matrix_print

        matrix_print(jnp.arange(12.0).reshape(3, 4), name="m")
        out = capsys.readouterr().out
        assert "m shape=(3, 4)" in out
        assert "0." in out


class TestKmeansFitPredict:
    def test_labels_match_predict(self, rng_np):
        from raft_tpu.cluster import kmeans

        c = rng_np.standard_normal((4, 8)) * 6
        x = (c[rng_np.integers(0, 4, 400)]
             + rng_np.standard_normal((400, 8))).astype(np.float32)
        params = kmeans.KMeansParams(n_clusters=4, max_iter=20, seed=0)
        centers, labels, inertia, n_iter = kmeans.fit_predict(None, params, x)
        labels2, _ = kmeans.predict(None, params, centers, x)
        np.testing.assert_array_equal(np.asarray(labels),
                                      np.asarray(labels2))


class TestCommsSendrecv:
    def test_rotation(self):
        from jax.sharding import PartitionSpec as P

        from raft_tpu.comms.bootstrap import local_comms
        from raft_tpu.comms.comms import device_sendrecv

        comms = local_comms()
        r = comms.size
        x = jax.device_put(
            jnp.arange(r, dtype=jnp.float32)[:, None],
            comms.row_sharded())
        perm = [(i, (i + 1) % r) for i in range(r)]
        out = comms.run(lambda xl: device_sendrecv(xl, perm, "data"),
                        x, in_specs=(P("data", None),),
                        out_specs=P("data", None), check_vma=False)
        got = np.asarray(out).ravel()
        want = np.roll(np.arange(r, dtype=np.float32), 1)
        np.testing.assert_array_equal(got, want)


class TestApiReference:
    def test_gen_api_covers_all_modules(self, tmp_path, monkeypatch):
        """docs/gen_api.py must import every listed public module and
        document a non-trivial surface (the generated docs/api.md is a
        committed artifact; an import break here means the committed
        reference silently goes stale)."""
        import importlib.util
        import pathlib

        root = pathlib.Path(__file__).resolve().parent.parent
        spec = importlib.util.spec_from_file_location(
            "gen_api", root / "docs" / "gen_api.py")
        gen = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(gen)
        for name in gen.MODULES:  # every module imports cleanly
            importlib.import_module(name)
        # and the committed api.md was generated from this module list
        committed = (root / "docs" / "api.md").read_text()
        for name in gen.MODULES:
            assert f"## `{name}`" in committed or not gen.public_symbols(
                importlib.import_module(name), name), \
                f"{name} missing from committed docs/api.md — rerun " \
                "python docs/gen_api.py"
