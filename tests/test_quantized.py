"""Scalar-quantized kNN tests — the ann_quantized wrapper role
(spatial/knn/detail/ann_quantized.cuh): recall against exact brute
force stays high because int8 quantization error is small relative to
neighbor distance gaps."""

import io

import numpy as np
import pytest
import scipy.spatial.distance as spd

from raft_tpu.core.resources import resources_manager
from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import quantized
from raft_tpu.utils import eval_recall


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(11)
    x = rng.standard_normal((5000, 32)).astype(np.float32)
    q = rng.standard_normal((64, 32)).astype(np.float32)
    return x, q


class TestQuantized:
    def test_l2_recall(self, dataset):
        x, q = dataset
        d, i = quantized.knn(None, x, q, 10)
        gt = np.argsort(spd.cdist(q, x, "sqeuclidean"), axis=1,
                        kind="stable")[:, :10]
        r, _, _ = eval_recall(gt, np.asarray(i))
        assert r >= 0.95, r
        # distances close to exact after de-quantization
        ref = np.take_along_axis(spd.cdist(q, x, "sqeuclidean"),
                                 np.asarray(i), axis=1)
        np.testing.assert_allclose(np.asarray(d), ref, rtol=0.05, atol=0.5)
        # sorted ascending
        assert (np.diff(np.asarray(d), axis=1) >= -1e-3).all()

    def test_inner_product(self, dataset):
        x, q = dataset
        d, i = quantized.knn(None, x, q, 10, DistanceType.InnerProduct)
        gt = np.argsort(-(q @ x.T), axis=1, kind="stable")[:, :10]
        r, _, _ = eval_recall(gt, np.asarray(i))
        assert r >= 0.9, r
        assert (np.diff(np.asarray(d), axis=1) <= 1e-3).all()

    def test_l2sqrt(self, dataset):
        x, q = dataset
        index = quantized.build(None, x, DistanceType.L2SqrtExpanded)
        d, i = quantized.search(None, index, q, 5)
        ref = np.take_along_axis(spd.cdist(q, x), np.asarray(i), axis=1)
        np.testing.assert_allclose(np.asarray(d), ref, rtol=0.05, atol=0.1)

    def test_serialization_roundtrip(self, dataset):
        x, q = dataset
        index = quantized.build(None, x)
        buf = io.BytesIO()
        quantized.save(index, buf)
        buf.seek(0)
        index2 = quantized.load(None, buf)
        d1, i1 = quantized.search(None, index, q, 10)
        d2, i2 = quantized.search(None, index2, q, 10)
        assert np.array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2))

    def test_unsupported_metric(self, dataset):
        x, _ = dataset
        with pytest.raises(Exception):
            quantized.build(None, x, DistanceType.Canberra)


class TestResourcesManager:
    def test_per_device_pooling(self):
        import jax

        r0 = resources_manager.get_device_resources(0)
        assert r0 is resources_manager.get_device_resources(jax.devices()[0])
        assert r0 is not resources_manager.get_device_resources(1)
        assert resources_manager.get_device_resources(None) is \
            resources_manager.get_device_resources(None)


class TestAsymmetricData:
    """Regression: encode is q = x/s - zero, decode must be s*(q + zero).
    A sign slip cancels on L2 but destroys InnerProduct rankings on data
    not centered at zero (e.g. SIFT's all-positive range)."""

    def test_inner_product_positive_data(self, rng_np):
        x = rng_np.uniform(0.0, 10.0, (3000, 24)).astype(np.float32)
        q = rng_np.uniform(0.0, 10.0, (32, 24)).astype(np.float32)
        d, i = quantized.knn(None, x, q, 10, DistanceType.InnerProduct)
        gt = np.argsort(-(q @ x.T), axis=1, kind="stable")[:, :10]
        r, _, _ = eval_recall(gt, np.asarray(i))
        assert r >= 0.9, r
        # similarities close to exact
        ref = np.take_along_axis(q @ x.T, np.asarray(i), axis=1)
        np.testing.assert_allclose(np.asarray(d), ref, rtol=0.02, atol=1.0)

    def test_l2_positive_data(self, rng_np):
        x = rng_np.uniform(0.0, 10.0, (3000, 24)).astype(np.float32)
        q = rng_np.uniform(0.0, 10.0, (16, 24)).astype(np.float32)
        d, i = quantized.knn(None, x, q, 10)
        from scipy.spatial.distance import cdist

        gt = np.argsort(cdist(q, x, "sqeuclidean"), axis=1,
                        kind="stable")[:, :10]
        r, _, _ = eval_recall(gt, np.asarray(i))
        assert r >= 0.9, r
