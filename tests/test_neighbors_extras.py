"""ball cover / epsilon neighborhood / masked NN tests
(reference ``cpp/test/neighbors/ball_cover.cu``,
``epsilon_neighborhood.cu``, ``cpp/test/distance/masked_nn.cu``)."""

import jax.numpy as jnp
import numpy as np

from raft_tpu.distance.masked_nn import compress_to_bits, masked_l2_nn
from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import ball_cover, brute_force
from raft_tpu.neighbors.epsilon_neighborhood import eps_neighbors


class TestEpsNeighborhood:
    def test_against_naive(self, rng_np, res):
        x = rng_np.standard_normal((50, 4)).astype(np.float32)
        y = rng_np.standard_normal((70, 4)).astype(np.float32)
        eps = 1.5
        adj, vd = eps_neighbors(res, x, y, eps)
        d2 = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
        want = d2 <= eps * eps
        np.testing.assert_array_equal(np.asarray(adj), want)
        np.testing.assert_array_equal(np.asarray(vd), want.sum(axis=1))

    def test_tiled_matches(self, rng_np, res):
        x = rng_np.standard_normal((33, 3)).astype(np.float32)
        adj1, _ = eps_neighbors(res, x, x, 1.0)
        adj2, _ = eps_neighbors(res, x, x, 1.0, tile=7)
        np.testing.assert_array_equal(np.asarray(adj1), np.asarray(adj2))


class TestMaskedNN:
    def test_compress_to_bits(self, res):
        mask = jnp.asarray([[True, False, True] + [False] * 30 + [True]])
        words = np.asarray(compress_to_bits(res, mask))
        assert words.shape == (1, 2)
        assert words[0, 0] == 0b101
        assert words[0, 1] == 0b10  # bit 33 → bit 1 of word 1

    def test_masked_l2_nn(self, rng_np, res):
        m, n, d, g = 40, 60, 5, 3
        x = rng_np.standard_normal((m, d)).astype(np.float32)
        y = rng_np.standard_normal((n, d)).astype(np.float32)
        # groups: y rows [0,20), [20,45), [45,60)
        group_idxs = jnp.asarray([20, 45, 60])
        groups = np.zeros(n, np.int64)
        groups[20:45] = 1
        groups[45:] = 2
        adj = rng_np.random((m, g)) < 0.6
        adj[0] = [True, False, False]  # deterministic row
        md, mi = masked_l2_nn(res, x, y, jnp.asarray(adj), group_idxs)
        md, mi = np.asarray(md), np.asarray(mi)
        d2 = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
        allowed = adj[:, groups]
        d2m = np.where(allowed, d2, np.inf)
        want_i = d2m.argmin(axis=1)
        want_d = d2m.min(axis=1)
        has = np.isfinite(want_d)
        np.testing.assert_allclose(md[has], want_d[has], rtol=1e-3, atol=1e-4)
        np.testing.assert_array_equal(mi[has], want_i[has])
        assert np.all(mi[~has] == -1)

    def test_no_enabled_groups(self, rng_np, res):
        x = rng_np.standard_normal((4, 3)).astype(np.float32)
        y = rng_np.standard_normal((6, 3)).astype(np.float32)
        adj = jnp.zeros((4, 2), bool)
        md, mi = masked_l2_nn(res, x, y, adj, jnp.asarray([3, 6]))
        assert np.all(np.isinf(np.asarray(md)))
        assert np.all(np.asarray(mi) == -1)


class TestBallCover:
    def test_exact_small_2d(self, rng_np, res):
        # probing all landmarks must equal brute force exactly
        x = rng_np.standard_normal((500, 2)).astype(np.float32)
        q = rng_np.standard_normal((32, 2)).astype(np.float32)
        idx = ball_cover.build_index(res, x)
        d, i = ball_cover.knn_query(res, idx, q, 5, n_probes=idx.n_landmarks)
        bd, bi = brute_force.knn(res, x, q, 5, DistanceType.L2SqrtExpanded)
        np.testing.assert_allclose(np.asarray(d), np.asarray(bd), rtol=1e-3, atol=1e-4)
        # indices may differ on ties; distances must match
        recall = np.mean([
            len(set(np.asarray(i)[r]) & set(np.asarray(bi)[r])) / 5
            for r in range(32)
        ])
        assert recall > 0.99

    def test_default_probes_high_recall(self, rng_np, res):
        x = rng_np.standard_normal((2000, 3)).astype(np.float32)
        q = rng_np.standard_normal((64, 3)).astype(np.float32)
        idx = ball_cover.build_index(res, x)
        d, i = ball_cover.knn_query(res, idx, q, 10)
        bd, bi = brute_force.knn(res, x, q, 10, DistanceType.L2SqrtExpanded)
        recall = np.mean([
            len(set(np.asarray(i)[r]) & set(np.asarray(bi)[r])) / 10
            for r in range(64)
        ])
        assert recall >= 0.95  # reference's statistical-recall pattern

    def test_haversine(self, rng_np, res):
        # lat/lon in radians
        pts = np.stack([
            rng_np.uniform(-np.pi / 2, np.pi / 2, 300),
            rng_np.uniform(-np.pi, np.pi, 300),
        ], axis=1).astype(np.float32)
        qs = pts[:8] + 0.001
        idx = ball_cover.build_index(res, pts, DistanceType.Haversine)
        d, i = ball_cover.knn_query(res, idx, qs, 3, n_probes=idx.n_landmarks)
        bd, bi = brute_force.knn(res, pts, qs, 3, DistanceType.Haversine)
        np.testing.assert_allclose(np.asarray(d), np.asarray(bd), rtol=1e-2, atol=1e-4)

    def test_eps_query(self, rng_np, res):
        x = rng_np.standard_normal((200, 2)).astype(np.float32)
        idx = ball_cover.build_index(res, x)
        adj, vd = ball_cover.eps_nn_query(res, idx, x[:10], 0.5)
        d2 = ((x[:10, None, :] - x[None, :, :]) ** 2).sum(-1)
        np.testing.assert_array_equal(np.asarray(adj), d2 <= 0.25)


class TestIvfHelpers:
    """ivf_flat_helpers / ivf_pq_helpers analogs."""

    def test_flat_pack_unpack(self, rng_np):
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu.neighbors.ivf_helpers import (
            flat_pack_list_data,
            flat_unpack_list_data,
        )

        x = rng_np.standard_normal((500, 8)).astype(np.float32)
        index = ivf_flat.build(
            None, ivf_flat.IvfFlatIndexParams(n_lists=8), x)
        vecs, ids = flat_unpack_list_data(index, 0)
        assert vecs.shape[0] == int(index.list_sizes[0])
        assert (np.asarray(ids) >= 0).all()
        # round-trip: packing the same data back changes nothing
        index2 = flat_pack_list_data(index, 0, vecs, ids)
        np.testing.assert_array_equal(np.asarray(index2.data),
                                      np.asarray(index.data))
        np.testing.assert_array_equal(np.asarray(index2.indices),
                                      np.asarray(index.indices))
        # original rows recoverable
        np.testing.assert_allclose(np.asarray(vecs), x[np.asarray(ids)])

    def test_pq_reconstruct(self, rng_np):
        from raft_tpu.neighbors import ivf_pq
        from raft_tpu.neighbors.ivf_helpers import (
            pq_extract_centers,
            pq_reconstruct_list_data,
            pq_unpack_list_data,
        )

        x = rng_np.standard_normal((2000, 32)).astype(np.float32)
        index = ivf_pq.build(
            None, ivf_pq.IvfPqIndexParams(n_lists=8, pq_dim=16), x)
        codes, ids = pq_unpack_list_data(index, 3)
        assert codes.shape == (int(index.list_sizes[3]), 16)
        recon = pq_reconstruct_list_data(index, 3)
        orig = x[np.asarray(ids)]
        # PQ reconstruction error well below data norm
        rel = (np.linalg.norm(np.asarray(recon) - orig, axis=1)
               / np.linalg.norm(orig, axis=1))
        assert np.median(rel) < 0.65, np.median(rel)
        assert pq_extract_centers(index).shape == (8, 32)


class TestOddDims:
    """dim not a multiple of 8/128 exercises padding and the PQ
    rotation's dim→dim_ext extension (reference supports arbitrary dims)."""

    def test_dim17_all_families(self, rng_np):
        from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq

        x = rng_np.standard_normal((1500, 17)).astype(np.float32)
        q = x[:6]
        _, i = brute_force.knn(None, x, q, 5)
        assert (np.asarray(i)[:, 0] == np.arange(6)).all()
        fidx = ivf_flat.build(None, ivf_flat.IvfFlatIndexParams(n_lists=8), x)
        _, i = ivf_flat.search(None, ivf_flat.IvfFlatSearchParams(n_probes=8),
                               fidx, q, 5)
        assert (np.asarray(i)[:, 0] == np.arange(6)).all()
        pidx = ivf_pq.build(None, ivf_pq.IvfPqIndexParams(n_lists=8, pq_dim=5),
                            x)
        assert pidx.dim_ext == 20 and pidx.pq_len == 4
        _, i = ivf_pq.search(None, ivf_pq.IvfPqSearchParams(n_probes=8),
                             pidx, q, 5)
        assert (np.asarray(i)[:, 0] == np.arange(6)).all()


class TestDegenerateData:
    """Duplicate-heavy and all-zero inputs must not produce NaN/inf
    results or invalid ids in any index family (real-world datasets
    contain exact duplicates and zero rows)."""

    def test_all_families_finite(self):
        import numpy as np

        from raft_tpu.neighbors import (
            brute_force,
            cagra,
            ivf_bq,
            ivf_flat,
            ivf_pq,
        )

        rng = np.random.default_rng(0)
        base = rng.standard_normal((10, 16)).astype(np.float32)
        x = np.concatenate([np.repeat(base, 90, axis=0),
                            np.zeros((100, 16), np.float32)])
        q = np.concatenate([base[:3],
                            np.zeros((1, 16), np.float32)]).astype(np.float32)

        cases = [
            lambda: brute_force.knn(None, x, q, 5),
            lambda: ivf_flat.search(
                None, ivf_flat.IvfFlatSearchParams(n_probes=8),
                ivf_flat.build(None, ivf_flat.IvfFlatIndexParams(n_lists=8),
                               x), q, 5),
            lambda: ivf_pq.search(
                None, ivf_pq.IvfPqSearchParams(n_probes=8),
                ivf_pq.build(None, ivf_pq.IvfPqIndexParams(n_lists=8,
                                                           pq_dim=8), x),
                q, 5),
            lambda: ivf_bq.search(
                None, ivf_bq.IvfBqSearchParams(n_probes=8),
                ivf_bq.build(None, ivf_bq.IvfBqIndexParams(n_lists=8), x),
                q, 5),
        ]
        for fn in cases:
            d, i = fn()
            assert np.isfinite(np.asarray(d)).all()
            assert (np.asarray(i) >= 0).all()

        ci = cagra.build(None, cagra.CagraIndexParams(
            graph_degree=8, intermediate_graph_degree=16,
            build_algo=cagra.BuildAlgo.NN_DESCENT), x)
        d, _ = cagra.search(None, cagra.CagraSearchParams(itopk_size=16),
                            ci, q, 5)
        assert np.isfinite(np.asarray(d)).all()
