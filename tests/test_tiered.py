"""grafttier (PR 14): tiered hot/cold IVF storage.

The serving contracts under test:

- **Bit-identity**: with tiering enabled (half the lists cold), search
  results are bit-identical to the all-HBM index per engine — direct
  and through the executor, before and after placement swaps, with
  shared and per-row filters, for L2/sqrt-L2/IP.
- **Zero-recompile across epochs**: placement only permutes which
  lists occupy the fixed hot slots (fixed-width drop-mode swaps), so
  steady-state serving runs zero backend compiles across ≥2
  promote/demote epochs.
- **Determinism**: the epoch function is pure (ties to the smaller
  list id), so scripted traffic under a ManualClock reproduces the
  exact same swap sequence run-to-run.
- **Probe-plane exactness**: graftgauge's accounting stays exact with
  tiering on (the plane threads the tiered plan like any IVF plan).
"""

import json
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.core import memwatch, tracing
from raft_tpu.core.executor import SearchExecutor
from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import ivf_flat, tiered
from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors.tiered import TieredSearchParams, build_tiered
from raft_tpu.ops.tier_scan import (
    resolve_tier_engine,
    tier_fetch_plan,
    tiered_list_major_scan,
)
from raft_tpu.serving.harness import ManualClock
from raft_tpu.serving.placement import (
    PlacementConfig,
    TierManager,
    plan_epoch,
)

ENGINES = ("xla", "pallas")


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(21)
    x = rng.standard_normal((4096, 32)).astype(np.float32)
    q = rng.standard_normal((24, 32)).astype(np.float32)
    return x, q


@pytest.fixture(scope="module")
def flat_index(data):
    x, _ = data
    return ivf_flat.build(
        None, ivf_flat.IvfFlatIndexParams(n_lists=32,
                                          kmeans_n_iters=6), x)


@pytest.fixture()
def tiered_index(flat_index):
    # fresh split per test (the container is mutable — placement
    # tests would otherwise leak layout into each other)
    return build_tiered(flat_index, hot_fraction=0.5)


@pytest.fixture(autouse=True)
def clean_gate():
    yield
    memwatch.remove_gate()


def _search_pair(flat_index, t, q, k=10, engine="xla", n_probes=8,
                 flt=None, metric_params=None):
    pf = ivf_flat.IvfFlatSearchParams(n_probes=n_probes,
                                      scan_engine=engine)
    pt = TieredSearchParams(n_probes=n_probes, scan_engine=engine)
    d0, i0 = ivf_flat.search(None, pf, flat_index, q, k,
                             sample_filter=flt)
    d1, i1 = tiered.search(None, pt, t, q, k, sample_filter=flt)
    return (np.asarray(d0), np.asarray(i0),
            np.asarray(d1), np.asarray(i1))


class TestBitIdentity:
    """Tiered results ≡ all-HBM results, bit for bit, per engine."""

    @pytest.mark.parametrize("engine", ENGINES)
    def test_half_cold_bit_identical(self, data, flat_index,
                                     tiered_index, engine):
        _, q = data
        assert tiered_index.n_cold >= tiered_index.n_lists // 2
        d0, i0, d1, i1 = _search_pair(flat_index, tiered_index, q,
                                      engine=engine)
        assert (d0 == d1).all() and (i0 == i1).all()

    @pytest.mark.parametrize("engine", ENGINES)
    def test_bit_identical_after_swaps(self, data, flat_index,
                                       tiered_index, engine):
        _, q = data
        promo = [int(x) for x in tiered_index.cold_lists[:3]]
        demo = [int(x) for x in tiered_index.hot_lists[:3]]
        moved = tiered.apply_plan(tiered_index, promo, demo, width=8)
        assert moved == 2 * 3 * tiered_index.block_bytes
        d0, i0, d1, i1 = _search_pair(flat_index, tiered_index, q,
                                      engine=engine)
        assert (d0 == d1).all() and (i0 == i1).all()

    @pytest.mark.parametrize("engine", ENGINES)
    def test_shared_filter_through_cold_blocks(self, data, flat_index,
                                               tiered_index, engine):
        """A 1-D shared bitset that knocks out rows living in COLD
        lists must filter identically — the id-fold rides the
        resident id plane, so the cold tier needs no filter
        plumbing of its own."""
        x, q = data
        # forbid every odd id — guaranteed to hit rows in both tiers
        mask = np.ones(x.shape[0], bool)
        mask[1::2] = False
        bs = Bitset.from_mask(mask)
        d0, i0, d1, i1 = _search_pair(flat_index, tiered_index, q,
                                      engine=engine, flt=bs)
        assert (d0 == d1).all() and (i0 == i1).all()
        assert (i1[i1 >= 0] % 2 == 0).all()

    def test_per_row_filter_through_cold_blocks(self, data, flat_index,
                                                tiered_index):
        """2-D per-query filters degrade pallas→xla (same contract as
        ivf_scan) and stay bit-identical through cold blocks."""
        x, q = data
        rng = np.random.default_rng(3)
        words = x.shape[0] // 32 + 1
        fw = jnp.asarray(
            rng.integers(0, 2**31, size=(q.shape[0], words),
                         dtype=np.int32).astype(np.uint32))
        assert resolve_tier_engine(
            "pallas", hot_data=tiered_index.hot_data,
            filter_words=fw, k=10) == "xla"
        d0, i0, d1, i1 = _search_pair(flat_index, tiered_index, q,
                                      flt=fw)
        assert (d0 == d1).all() and (i0 == i1).all()

    @pytest.mark.parametrize("engine", ENGINES)
    def test_inner_product_and_sqrt_metrics(self, data, engine):
        x, q = data
        for metric in (DistanceType.InnerProduct,
                       DistanceType.L2SqrtExpanded):
            idx = ivf_flat.build(
                None, ivf_flat.IvfFlatIndexParams(
                    n_lists=16, kmeans_n_iters=4, metric=metric), x)
            t = build_tiered(idx, hot_fraction=0.5)
            d0, i0, d1, i1 = _search_pair(idx, t, q, engine=engine,
                                          n_probes=6)
            assert (d0 == d1).all() and (i0 == i1).all()

    def test_interpret_mode_kernel_reference(self, data, flat_index,
                                             tiered_index):
        """The R6 interpret-coverage reference: the tiered Pallas
        kernel itself, driven directly with interpret=True, matches
        the XLA twin bit-for-bit (the ops-guard contract every
        pallas_call in ops/ must keep)."""
        x, q = data
        t = tiered_index
        qf = jnp.asarray(q)
        ip = qf @ np.asarray(t.centers).T
        score = -(np.asarray(t.center_norms)[None, :] - 2.0 * ip)
        probes = jnp.asarray(
            np.argsort(-np.asarray(score), axis=1)[:, :8]
            .astype(np.int32))
        outs = {}
        for eng in ENGINES:
            outs[eng] = tiered_list_major_scan(
                qf, t.hot_data, t.cold_data, t.hot_slot_map,
                t.cold_slot_map, t.data_norms, t.indices, probes,
                k=10, metric=t.metric, engine=eng, interpret=True)
        assert (np.asarray(outs["pallas"][0])
                == np.asarray(outs["xla"][0])).all()
        assert (np.asarray(outs["pallas"][1])
                == np.asarray(outs["xla"][1])).all()


class TestFetchPlan:
    """tier_fetch_plan: the per-step dual-tier fetch descriptor."""

    def test_hot_hold_and_cold_sequence(self):
        # lists: 0 hot(slot 0), 1 cold(slot 0), 2 hot(slot 1),
        # 3 cold(slot 1), 4 cold(slot 2)
        hot_map = jnp.asarray([0, -1, 1, -1, -1], jnp.int32)
        cold_map = jnp.asarray([-1, 0, -1, 1, 2], jnp.int32)
        uniq = jnp.asarray([0, 1, 2, 3, 4, 5], jnp.int32)  # 5 = sentinel
        hf, cf, cs = tier_fetch_plan(uniq, hot_map, cold_map, 5)
        # hot fetch holds across cold + sentinel steps
        assert list(np.asarray(hf)) == [0, 0, 1, 1, 1, 1]
        assert list(np.asarray(cf)) == [-1, 0, -1, 1, 2, -1]
        # exclusive cold count -> alternating buffer slots 0,1,0
        assert list(np.asarray(cs)) == [0, 0, 1, 1, 2, 3]

    def test_leading_cold_clamps_to_slot_zero(self):
        hot_map = jnp.asarray([-1, 0], jnp.int32)
        cold_map = jnp.asarray([0, -1], jnp.int32)
        hf, cf, _ = tier_fetch_plan(
            jnp.asarray([0, 1], jnp.int32), hot_map, cold_map, 2)
        assert list(np.asarray(hf)) == [0, 0]
        assert list(np.asarray(cf)) == [0, -1]


class TestResolveEngine:
    def test_auto_is_xla_off_tpu(self, tiered_index):
        assert resolve_tier_engine(
            "auto", hot_data=tiered_index.hot_data, k=10) == "xla"

    def test_big_k_degrades(self, tiered_index):
        assert resolve_tier_engine(
            "pallas", hot_data=tiered_index.hot_data, k=256) == "xla"

    def test_non_f32_degrades(self, tiered_index):
        bf = tiered_index.hot_data.astype(jnp.bfloat16)
        assert resolve_tier_engine("pallas", hot_data=bf,
                                   k=10) == "xla"

    def test_bad_engine_rejected(self):
        with pytest.raises(Exception, match="tiered scan_engine"):
            resolve_tier_engine("rank")


class TestHotSizing:
    """resolve_hot_slots: the graftledger byte half of placement."""

    def test_ledger_headroom_sizes_the_hot_tier(self, flat_index):
        block = (flat_index.max_list_size * flat_index.dim * 4)
        # capacity for exactly 5 blocks after the 10% safety reserve
        ledger = memwatch.MemoryLedger(
            capacity_bytes=block * 5 / 0.9 + 1)
        h = tiered.resolve_hot_slots(flat_index, ledger=ledger)
        assert h == 5
        t = build_tiered(flat_index, ledger=ledger)
        assert t.n_hot == 5 and t.n_cold == flat_index.n_lists - 5

    def test_unknown_headroom_falls_back_to_fraction(self, flat_index):
        ledger = memwatch.MemoryLedger()   # no stats, no capacity
        h = tiered.resolve_hot_slots(flat_index, ledger=ledger,
                                     hot_fraction=0.25)
        assert h == flat_index.n_lists // 4

    def test_clamped_to_a_real_split(self, flat_index):
        assert tiered.resolve_hot_slots(flat_index,
                                        hot_slots=10**9) \
            == flat_index.n_lists - 1
        assert tiered.resolve_hot_slots(flat_index, hot_slots=0) == 1

    def test_probe_counts_seed_the_initial_placement(self, flat_index):
        counts = np.zeros((flat_index.n_lists,), np.int64)
        hot_lids = [3, 7, 11, 20]
        counts[hot_lids] = [40, 30, 20, 10]
        t = build_tiered(flat_index, hot_slots=4, probe_counts=counts)
        assert sorted(t.hot_lists.tolist()) == hot_lids


class TestPlanEpoch:
    """The pure epoch function: deterministic, hysteretic, bounded."""

    def test_promotes_hot_cold_pairs(self):
        counts = np.asarray([0, 100, 5, 50, 2, 0])
        plan = plan_epoch(counts, hot_lists=[0, 2], cold_lists=[1, 3, 4, 5],
                          max_swaps=8, min_heat_ratio=1.5)
        # cold 1 (100) beats hot 0 (0); cold 3 (50) beats hot 2 (5)
        assert plan.promotions == (1, 3)
        assert plan.demotions == (0, 2)
        assert plan.window_total == 157
        assert plan.hot_window_fraction == pytest.approx(5 / 157)

    def test_hysteresis_blocks_border_swaps(self):
        counts = np.asarray([10, 14, 0, 0])
        plan = plan_epoch(counts, hot_lists=[0], cold_lists=[1, 2, 3],
                          min_heat_ratio=1.5)
        assert plan.promotions == ()        # 14 < 1.5 * 10
        plan = plan_epoch(counts, hot_lists=[0], cold_lists=[1, 2, 3],
                          min_heat_ratio=1.2)
        assert plan.promotions == (1,) and plan.demotions == (0,)

    def test_zero_traffic_cold_never_promotes(self):
        plan = plan_epoch(np.zeros(4, np.int64), hot_lists=[0, 1],
                          cold_lists=[2, 3])
        assert plan.promotions == ()

    def test_max_swaps_bounds_the_plan(self):
        counts = np.asarray([0, 0, 0, 9, 9, 9])
        plan = plan_epoch(counts, hot_lists=[0, 1, 2],
                          cold_lists=[3, 4, 5], max_swaps=2)
        assert len(plan.promotions) == 2

    def test_ties_break_to_smaller_lid(self):
        counts = np.asarray([0, 0, 7, 7])
        plan = plan_epoch(counts, hot_lists=[0, 1], cold_lists=[2, 3],
                          max_swaps=1)
        assert plan.promotions == (2,) and plan.demotions == (0,)

    def test_pure_function_determinism(self):
        rng = np.random.default_rng(5)
        counts = rng.integers(0, 100, size=32)
        hot, cold = list(range(16)), list(range(16, 32))
        a = plan_epoch(counts, hot, cold)
        b = plan_epoch(counts.copy(), list(hot), list(cold))
        assert a == b


class TestApplyPlan:
    def test_layout_mirrors_and_maps_agree(self, tiered_index):
        t = tiered_index
        promo = [int(t.cold_lists[1])]
        demo = [int(t.hot_lists[2])]
        tiered.apply_plan(t, promo, demo, width=4)
        assert promo[0] in t.hot_lists and demo[0] in t.cold_lists
        hot_map = np.asarray(t.hot_slot_map)
        cold_map = np.asarray(t.cold_slot_map)
        # every list in exactly one tier; maps mirror the host truth
        assert ((hot_map >= 0) ^ (cold_map >= 0)).all()
        for slot, lid in enumerate(t.hot_lists):
            assert hot_map[lid] == slot
        for slot, lid in enumerate(t.cold_lists):
            assert cold_map[lid] == slot

    def test_rejects_wrong_tier_pairs(self, tiered_index):
        t = tiered_index
        with pytest.raises(Exception, match="currently-cold"):
            tiered.apply_plan(t, [int(t.hot_lists[0])],
                              [int(t.hot_lists[1])], width=4)
        with pytest.raises(Exception, match="currently-hot"):
            tiered.apply_plan(t, [int(t.cold_lists[0])],
                              [int(t.cold_lists[1])], width=4)

    def test_empty_plan_is_a_noop(self, tiered_index):
        before = tiered_index.hot_lists.copy()
        assert tiered.apply_plan(tiered_index, [], [], width=4) == 0
        assert (tiered_index.hot_lists == before).all()


class TestServingEpochs:
    """The executor contract: zero backend compiles across epochs,
    probe-plane exactness, deterministic ManualClock placement."""

    def _targeted_queries(self, flat_index, lid, rows=16, seed=7):
        rng = np.random.default_rng(seed)
        c = np.asarray(flat_index.centers)[lid]
        return (np.tile(c, (rows, 1))
                + 0.01 * rng.standard_normal((rows, c.size))
                ).astype(np.float32)

    def test_zero_recompile_across_epochs(self, data, flat_index):
        _, q = data
        t = build_tiered(flat_index, hot_fraction=0.5)
        p = TieredSearchParams(n_probes=8)
        ex = SearchExecutor(probe_accounting=True)
        ex.warmup(t, buckets=(32,), k=10, params=p)
        clock = ManualClock()
        mgr = TierManager(t, ex, clock=clock, config=PlacementConfig(
            epoch_every_s=10.0, max_swaps_per_epoch=4))
        qh = self._targeted_queries(flat_index, int(t.cold_lists[0]))
        d_ref, i_ref = ex.search(t, qh, 10, params=p)
        d_ref, i_ref = np.asarray(d_ref), np.asarray(i_ref)
        # warm everything the epoch path compiles (the fixed-width
        # swap programs specialize once), then demand silence
        mgr.epoch()
        ex.search(t, qh, 10, params=p)
        tracing.install_xla_compile_listener()
        c0 = tracing.counters().get(tracing.XLA_COMPILE_COUNT, 0)
        for _ in range(2):
            ex.search(t, qh, 10, params=p)
            plan = mgr.epoch()
            d2, i2 = ex.search(t, qh, 10, params=p)
        c1 = tracing.counters().get(tracing.XLA_COMPILE_COUNT, 0)
        assert c1 - c0 == 0, "re-placement must not recompile"
        # and the results stayed bit-identical through re-placement
        assert (np.asarray(d2) == d_ref).all()
        assert (np.asarray(i2) == i_ref).all()
        del plan

    def test_epoch_promotes_hot_traffic(self, flat_index):
        t = build_tiered(flat_index, hot_fraction=0.5)
        p = TieredSearchParams(n_probes=4)
        ex = SearchExecutor(probe_accounting=True)
        mgr = TierManager(t, ex, clock=ManualClock())
        lid = int(t.cold_lists[0])
        qh = self._targeted_queries(flat_index, lid)
        for _ in range(3):
            ex.search(t, qh, 10, params=p)
        plan = mgr.epoch()
        assert lid in plan.promotions
        assert lid in t.hot_lists

    def test_epoch_determinism_under_manual_clock(self, flat_index):
        """Two identical runs — same traffic script, same clock
        script — produce the exact same swap sequence."""
        def run():
            t = build_tiered(flat_index, hot_fraction=0.5)
            p = TieredSearchParams(n_probes=4)
            ex = SearchExecutor(probe_accounting=True)
            clock = ManualClock()
            mgr = TierManager(t, ex, clock=clock, config=PlacementConfig(
                epoch_every_s=5.0, max_swaps_per_epoch=2))
            plans = []
            for step, lid_pos in enumerate((0, 3, 5)):
                lid = int(build_tiered(flat_index,
                                       hot_fraction=0.5)
                          .cold_lists[lid_pos])
                qh = self._targeted_queries(flat_index, lid,
                                            seed=step)
                for _ in range(2):
                    ex.search(t, qh, 10, params=p)
                plans.append(mgr.epoch())
            return [(pl.promotions, pl.demotions) for pl in plans]

        assert run() == run()

    def test_tick_pacing(self, flat_index):
        t = build_tiered(flat_index, hot_fraction=0.5)
        ex = SearchExecutor(probe_accounting=True)
        clock = ManualClock()
        mgr = TierManager(t, ex, clock=clock, config=PlacementConfig(
            epoch_every_s=10.0))
        assert mgr.tick() is None          # first tick stamps only
        clock.advance(9.0)
        assert mgr.tick() is None          # not due yet
        clock.advance(2.0)
        assert mgr.tick() is not None      # due
        # elapsed multiples never stack into more than one epoch
        clock.advance(100.0)
        assert mgr.tick() is not None
        assert mgr.tick() is None

    def test_probe_plane_exact_with_tiering_on(self, data, flat_index):
        _, q = data
        t = build_tiered(flat_index, hot_fraction=0.5)
        p = TieredSearchParams(n_probes=8)
        ex = SearchExecutor(probe_accounting=True)
        n_dispatch = 3
        for _ in range(n_dispatch):
            ex.search(t, q, 10, params=p)
        planes = ex.probe_frequencies()
        label = ex.probe_label(t)
        assert label is not None and label.startswith("tiered_ivf-")
        total = int(planes[label].sum())
        assert total == n_dispatch * q.shape[0] * 8
        # and the plane matches the all-HBM index's own accounting
        # (same coarse selection -> identical histograms)
        ex2 = SearchExecutor(probe_accounting=True)
        for _ in range(n_dispatch):
            ex2.search(flat_index, q, 10,
                       params=ivf_flat.IvfFlatSearchParams(n_probes=8))
        ref = ex2.probe_frequencies()[ex2.probe_label(flat_index)]
        assert (planes[label] == ref).all()

    def test_executor_bit_identity_both_engines(self, data, flat_index):
        _, q = data
        t = build_tiered(flat_index, hot_fraction=0.5)
        ex = SearchExecutor()
        for eng in ENGINES:
            p = TieredSearchParams(n_probes=8, scan_engine=eng)
            d1, i1 = ex.search(t, q, 10, params=p)
            d0, i0 = ivf_flat.search(
                None, ivf_flat.IvfFlatSearchParams(n_probes=8,
                                                   scan_engine=eng),
                flat_index, q, 10)
            assert (np.asarray(d0) == np.asarray(d1)).all()
            assert (np.asarray(i0) == np.asarray(i1)).all()
        # the resolved engine keys distinct executables
        fams = [key for key in ex._cache if key[0] == "tiered_ivf"]
        assert len(fams) == 2

    def test_manager_requires_probe_accounting(self, tiered_index):
        with pytest.raises(Exception, match="probe-accounting"):
            TierManager(tiered_index, SearchExecutor(),
                        clock=ManualClock())


class TestTierSurface:
    """/tier.json + gauges + host-tier memory accounting."""

    def test_tier_json_and_gauges(self, data, flat_index):
        from raft_tpu.serving import MetricsExporter

        _, q = data
        t = build_tiered(flat_index, hot_fraction=0.5)
        p = TieredSearchParams(n_probes=8)
        ex = SearchExecutor(probe_accounting=True)
        clock = ManualClock()
        mgr = TierManager(t, ex, clock=clock, config=PlacementConfig(
            epoch_every_s=5.0))
        for _ in range(2):
            ex.search(t, q, 10, params=p)
        exp = MetricsExporter(executor=ex, tier=mgr)
        port = exp.start()
        try:
            body = urllib.request.urlopen(
                exp.url("/tier.json")).read()
            snap = json.loads(body)
            assert snap["layout"]["n_hot"] == t.n_hot
            assert snap["layout"]["n_cold"] == t.n_cold
            assert snap["layout"]["host_resident"] is t.host_resident
            assert snap["epochs"] == 0
            # two scrapes with the clock advanced drive one epoch
            urllib.request.urlopen(exp.url("/metrics")).read()
            clock.advance(6.0)
            urllib.request.urlopen(exp.url("/metrics")).read()
            snap = json.loads(urllib.request.urlopen(
                exp.url("/tier.json")).read())
            assert snap["epochs"] == 1
            assert snap["last_plan"] is not None
            g = tracing.gauges()
            assert g["tier.hot_lists"] == float(t.n_hot)
            assert g["tier.hot_bytes"] == float(t.hot_bytes)
            assert g["tier.cold_bytes"] == float(t.cold_bytes)
            assert "tier.hot_window_fraction" in g
            text = urllib.request.urlopen(
                exp.url("/metrics")).read().decode()
            assert "tier_hot_bytes" in text
        finally:
            exp.close()

    def test_tier_json_404_unattached(self):
        from raft_tpu.serving import MetricsExporter

        exp = MetricsExporter()
        port = exp.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(exp.url("/tier.json"))
            assert e.value.code == 404
        finally:
            exp.close()
        del port

    def test_memwatch_models_the_tiers(self, tiered_index):
        """The resident model accounts the hot plane as device bytes;
        on CPU the cold plane honestly stays device (host and device
        are one pool — host_resident is False), while the numpy
        layout mirrors count host."""
        m = memwatch.index_memory_model(tiered_index)
        comps = m["components"]
        assert comps["hot_data"]["tier"] == "device"
        assert comps["hot_data"]["bytes"] == tiered_index.hot_bytes
        assert comps["cold_data"]["bytes"] == tiered_index.cold_bytes
        if tiered_index.host_resident:
            assert comps["cold_data"]["tier"] == "host"
            assert m["host_resident_bytes"] >= tiered_index.cold_bytes
        else:
            assert comps["cold_data"]["tier"] == "device"
        assert comps["hot_lists"]["tier"] == "host"

    def test_host_put_fallback_is_honest(self):
        arr, resident = tiered.host_put(np.zeros((4, 4), np.float32))
        if jax.default_backend() == "cpu":
            assert resident is False
        assert arr.shape == (4, 4)


class TestMultiTileKernel:
    """The Pallas kernel's cold-DMA discipline across QUERY TILES:
    cbuf/semaphore state persists across grid steps, and each tile's
    j==0 warm-up must re-fetch its own first cold block — force a
    small q_tile via the VMEM budget so several tiles actually run,
    and demand bit-parity with the XLA twin."""

    def test_multi_tile_bit_parity(self, flat_index, tiered_index):
        from raft_tpu.ops.tier_scan import (
            _tier_scan_pallas,
            _tier_scan_xla,
            _tier_vmem_plan,
        )

        t = tiered_index
        rng = np.random.default_rng(9)
        q = rng.standard_normal((192, t.dim)).astype(np.float32)
        qf = jnp.asarray(q)
        ip = qf @ np.asarray(t.centers).T
        score = -(np.asarray(t.center_norms)[None, :] - 2.0 * ip)
        probes = jnp.asarray(
            np.argsort(-np.asarray(score), axis=1)[:, :8]
            .astype(np.int32))
        # size the budget so the tile is a fraction of the batch —
        # the SAME arithmetic the kernel uses, so the tile count
        # assertion below can't silently degrade to one tile
        m_pad = -(-t.max_list_size // 8) * 8
        d_pad = -(-t.dim // 128) * 128
        fixed, per_q = _tier_vmem_plan(m_pad, d_pad, 10)
        vmem_mb = -(-int(fixed + 48 * per_q) // (1 << 20))
        budget = (vmem_mb << 20) - fixed
        q_tile = min(max(8, (budget // per_q) // 8 * 8), 192)
        assert 192 // q_tile >= 2, "budget did not force multiple tiles"
        pd, pi = _tier_scan_pallas(
            qf, t.hot_data, t.cold_data, t.hot_slot_map,
            t.cold_slot_map, t.data_norms, t.indices, probes, None,
            k=10, metric=t.metric, interpret=True, vmem_mb=vmem_mb)
        xd, xi = _tier_scan_xla(
            qf, t.hot_data, t.cold_data, t.hot_slot_map,
            t.cold_slot_map, t.data_norms, t.indices, probes, None,
            k=10, metric=t.metric)
        assert (np.asarray(pd) == np.asarray(xd)).all()
        assert (np.asarray(pi) == np.asarray(xi)).all()


class TestLivePlacementRace:
    """The donation race the verify drive surfaced: an epoch swap
    donates the old hot plane while a concurrent search thread holds
    the pre-swap generation — the executor must absorb it with one
    rebuild-and-retry (jax spells the deleted-buffer error as
    RuntimeError OR ValueError), never surface it to the caller."""

    def test_concurrent_epochs_and_searches(self, data, flat_index):
        import threading

        _, q = data
        t = build_tiered(flat_index, hot_fraction=0.5)
        p = TieredSearchParams(n_probes=8)
        ex = SearchExecutor(probe_accounting=True)
        ex.warmup(t, buckets=(32,), k=10, params=p)
        d_ref, i_ref = np.asarray(ivf_flat.search(
            None, ivf_flat.IvfFlatSearchParams(n_probes=8),
            flat_index, q, 10)[0]), None
        errors = []
        stop = threading.Event()

        def pump():
            while not stop.is_set():
                try:
                    d, i = ex.search(t, q, 10, params=p)
                    np.asarray(d)
                except Exception as e:  # noqa: BLE001 — the assertion
                    errors.append(e)
                    return

        th = threading.Thread(target=pump, daemon=True)
        th.start()
        try:
            for step in range(20):
                promo = [int(t.cold_lists[step % t.n_cold])]
                demo = [int(t.hot_lists[step % t.n_hot])]
                tiered.apply_plan(t, promo, demo, width=4,
                                  executor=ex)
        finally:
            stop.set()
            th.join(timeout=30)
        assert not errors, errors[:1]
        d2, i2 = ex.search(t, q, 10, params=p)
        assert (np.asarray(d2) == d_ref).all()
        del i_ref, i2


class TestRaggedTiered:
    """graftcast retires the tiered ragged refusal (PR 15 pinned it;
    this is the flip that pin documented): the tiered plan key
    carries only shapes + statics — the placement generation never
    enters it — so an epoch swap can't invalidate the one packed
    ragged executable, and tiered serving rides the same packed-tile
    path as every other IVF family, bit-identical to its bucketed
    dispatch."""

    def test_fallback_pin_retired(self, tiered_index):
        ex = SearchExecutor()
        p = TieredSearchParams(n_probes=8)
        assert ex.ragged_key(tiered_index, 5, params=p) is not None
        assert ex.ragged_fallback_reason(tiered_index, 5,
                                         params=p) is None

    def test_ragged_batcher_serves_tiered(self, data, tiered_index):
        from raft_tpu.serving import BatcherConfig, DynamicBatcher

        _, q = data
        ex = SearchExecutor()
        p = TieredSearchParams(n_probes=8)
        want_d, want_i = ex.search(tiered_index, q[:7], 5, params=p)
        with DynamicBatcher(ex, BatcherConfig(max_wait_s=0.002,
                                              ragged=True)) as b:
            h = b.submit(tiered_index, q[:7], 5, params=p)
            got_d, got_i = h.result(timeout=120)
        np.testing.assert_array_equal(np.asarray(got_i),
                                      np.asarray(want_i))
        np.testing.assert_array_equal(np.asarray(got_d),
                                      np.asarray(want_d))
        assert ex.ragged_executables() == 1

    def test_ragged_stable_across_epochs(self, data, flat_index):
        """The generation-stable packing contract: ONE ragged
        executable serves across a placement swap, and its results
        track the (bit-identical) bucketed path on both sides."""
        _, q = data
        t = build_tiered(flat_index, hot_fraction=0.5)
        ex = SearchExecutor(probe_accounting=True)
        p = TieredSearchParams(n_probes=8)
        for _ in range(2):
            want_d, want_i = ex.search(t, q[:7], 5, params=p)
            (got_d, got_i), = ex.search_ragged(t, [q[:7]], 5,
                                               params_list=p)
            np.testing.assert_array_equal(np.asarray(got_i),
                                          np.asarray(want_i))
            np.testing.assert_array_equal(np.asarray(got_d),
                                          np.asarray(want_d))
            tiered.apply_plan(t, [int(t.cold_lists[0])],
                              [int(t.hot_lists[0])], width=4,
                              executor=ex)
        assert ex.ragged_executables() == 1


# ---------------------------------------------------------------------------
# graftcast (PR 18): tiered PQ/BQ planes
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def pq_index(data):
    from raft_tpu.neighbors import ivf_pq

    x, _ = data
    return ivf_pq.build(None, ivf_pq.IvfPqIndexParams(
        n_lists=32, pq_dim=8, kmeans_n_iters=6), x)


@pytest.fixture(scope="module")
def bq_index(data):
    from raft_tpu.neighbors import ivf_bq

    x, _ = data
    return ivf_bq.build(None, ivf_bq.IvfBqIndexParams(
        n_lists=32, kmeans_n_iters=6), x)


class TestTieredCompressed:
    """Tiered PQ codes plane and BQ record planes: bit-identical to
    the all-HBM index with half the lists cold — direct, through the
    executor, through the ragged tile, and across placement swaps
    (the ONE shared scan body guarantees it by construction; these
    pin that the tier steering doesn't perturb it)."""

    def _pq_pair(self, pq_index, t, q, k=10, n_probes=8):
        from raft_tpu.neighbors import ivf_pq

        p = ivf_pq.IvfPqSearchParams(n_probes=n_probes,
                                     scan_engine="xla")
        d0, i0 = ivf_pq.search(None, p, pq_index, q, k)
        d1, i1 = tiered.search_pq(None, p, t, q, k)
        return (np.asarray(d0), np.asarray(i0),
                np.asarray(d1), np.asarray(i1))

    def _bq_pair(self, bq_index, t, q, k=10, n_probes=8):
        from raft_tpu.neighbors import ivf_bq

        p = ivf_bq.IvfBqSearchParams(n_probes=n_probes,
                                     scan_engine="xla")
        d0, i0 = ivf_bq.search(None, p, bq_index, q, k)
        d1, i1 = tiered.search_bq(None, p, t, q, k)
        return (np.asarray(d0), np.asarray(i0),
                np.asarray(d1), np.asarray(i1))

    def test_pq_half_cold_bit_identical(self, data, pq_index):
        _, q = data
        t = tiered.build_tiered_pq(pq_index, hot_fraction=0.5)
        d0, i0, d1, i1 = self._pq_pair(pq_index, t, q)
        np.testing.assert_array_equal(i0, i1)
        np.testing.assert_array_equal(d0, d1)

    def test_bq_half_cold_bit_identical(self, data, bq_index):
        _, q = data
        t = tiered.build_tiered_bq(bq_index, hot_fraction=0.5)
        d0, i0, d1, i1 = self._bq_pair(bq_index, t, q)
        np.testing.assert_array_equal(i0, i1)
        np.testing.assert_array_equal(d0, d1)

    def test_pq_packed_half_cold_bit_identical(self, data):
        from raft_tpu.neighbors import ivf_pq

        x, q = data
        idx = ivf_pq.build(None, ivf_pq.IvfPqIndexParams(
            n_lists=16, pq_dim=16, pq_bits=4, kmeans_n_iters=4), x)
        t = tiered.build_tiered_pq(idx, hot_fraction=0.5)
        d0, i0, d1, i1 = self._pq_pair(idx, t, q)
        np.testing.assert_array_equal(i0, i1)
        np.testing.assert_array_equal(d0, d1)

    def test_bit_identical_after_swaps(self, data, pq_index, bq_index):
        _, q = data
        tpq = tiered.build_tiered_pq(pq_index, hot_fraction=0.5)
        tbq = tiered.build_tiered_bq(bq_index, hot_fraction=0.5)
        for t in (tpq, tbq):
            promo = [int(t.cold_lists[0]), int(t.cold_lists[1])]
            demo = [int(t.hot_lists[0]), int(t.hot_lists[1])]
            tiered.apply_plan(t, promo, demo, width=4)
            assert t.generation == 1
        d0, i0, d1, i1 = self._pq_pair(pq_index, tpq, q)
        np.testing.assert_array_equal(i0, i1)
        np.testing.assert_array_equal(d0, d1)
        d0, i0, d1, i1 = self._bq_pair(bq_index, tbq, q)
        np.testing.assert_array_equal(i0, i1)
        np.testing.assert_array_equal(d0, d1)

    def test_executor_and_ragged_paths(self, data, pq_index, bq_index):
        from raft_tpu.neighbors import ivf_bq, ivf_pq

        _, q = data
        tpq = tiered.build_tiered_pq(pq_index, hot_fraction=0.5)
        tbq = tiered.build_tiered_bq(bq_index, hot_fraction=0.5)
        ex = SearchExecutor()
        for t, p in ((tpq, ivf_pq.IvfPqSearchParams(n_probes=8)),
                     (tbq, ivf_bq.IvfBqSearchParams(n_probes=8))):
            assert ex.ragged_key(t, 5, params=p) is not None
            assert ex.ragged_fallback_reason(t, 5, params=p) is None
            want_d, want_i = ex.search(t, q[:7], 5, params=p)
            (got_d, got_i), = ex.search_ragged(t, [q[:7]], 5,
                                               params_list=p)
            np.testing.assert_array_equal(np.asarray(got_i),
                                          np.asarray(want_i))
            np.testing.assert_array_equal(np.asarray(got_d),
                                          np.asarray(want_d))

    def test_rank_engine_rejected_for_tiered_pq(self, pq_index):
        from raft_tpu.ops.tier_scan import resolve_tier_pq_engine

        with pytest.raises(Exception):
            resolve_tier_pq_engine("rank")

    def test_block_bytes_prices_all_planes(self, pq_index, bq_index):
        tpq = tiered.build_tiered_pq(pq_index, hot_fraction=0.5)
        tbq = tiered.build_tiered_bq(bq_index, hot_fraction=0.5)
        assert tpq.block_bytes == (
            int(np.prod(tpq.hot_codes.shape[1:]))
            * tpq.hot_codes.dtype.itemsize)
        per_plane = sum(
            int(np.prod(getattr(tbq, h).shape[1:]))
            * getattr(tbq, h).dtype.itemsize
            for h, _ in type(tbq)._PLANE_PAIRS)
        assert tbq.block_bytes == per_plane


# ---------------------------------------------------------------------------
# graftcast (PR 18): forecast-driven prefetch
# ---------------------------------------------------------------------------


class TestPrefetch:
    """The predictive tiering loop: forecast = the epoch policy over
    (rolling window + EWMA prior); staged promotions hit at the
    epoch; stale stages are refused by the generation check; the miss
    cache respects the ledger's capacity gate and shrinking
    headroom."""

    def _manager(self, flat_index, clock, capacity=1 << 30,
                 lead=10.0, **pf_kw):
        from raft_tpu.serving.prefetch import PrefetchConfig

        t = build_tiered(flat_index, hot_fraction=0.5)
        ex = SearchExecutor(probe_accounting=True)
        ledger = memwatch.MemoryLedger(executor=ex,
                                       capacity_bytes=capacity)
        mgr = TierManager(t, ex, config=PlacementConfig(
            epoch_every_s=60.0, max_swaps_per_epoch=4,
            prefetch_lead_s=lead), clock=clock)
        pf = mgr.enable_prefetch(
            config=PrefetchConfig(alpha=0.5, **pf_kw), ledger=ledger)
        return t, ex, mgr, pf, ledger

    def _drive(self, ex, t, mgr, clock, q, ticks, flat_index=None):
        p = TieredSearchParams(n_probes=4)
        for _ in range(ticks):
            d, i = ex.search(t, q, 10, params=p)
            if flat_index is not None:
                d2, i2 = ex.search(
                    flat_index, q, 10,
                    params=ivf_flat.IvfFlatSearchParams(n_probes=4))
                np.testing.assert_array_equal(np.asarray(i),
                                              np.asarray(i2))
                np.testing.assert_array_equal(np.asarray(d),
                                              np.asarray(d2))
            clock.advance(11.0)
            mgr.tick()

    @staticmethod
    def _near(flat_index, lids, n=64, seed=7):
        rng = np.random.default_rng(seed)
        centers = np.asarray(jax.device_get(flat_index.centers))
        qs = centers[np.asarray(lids)[rng.integers(0, len(lids), n)]]
        qs = qs + 0.01 * rng.standard_normal(qs.shape)
        return qs.astype(np.float32)

    def test_forecast_is_the_epoch_policy(self):
        from raft_tpu.serving.prefetch import forecast_plan

        window = np.array([0, 50, 1, 40, 2, 3], np.int64)
        hot = np.array([0, 1, 2])
        cold = np.array([3, 4, 5])
        want = plan_epoch(window, hot, cold, max_swaps=2)
        got = forecast_plan(np.zeros(6), hot, cold, max_swaps=2,
                            window=window)
        assert got.promotions == want.promotions
        assert got.demotions == want.demotions

    def test_prefetch_hits_and_zero_recompile(self, flat_index):
        """Drifting hot set under a ManualClock: the lead-time stage
        hits at the epoch, cold bytes leave the epoch path, and —
        after one warm drift cycle — further epochs with the
        prefetcher on add ZERO backend compiles (bit-identity to the
        flat index asserted on every dispatch)."""
        clock = ManualClock()
        t, ex, mgr, pf, _ = self._manager(flat_index, clock)
        assert pf.enabled
        hot0 = [int(lid) for lid in t.hot_lists[:8]]
        cold0 = [int(lid) for lid in t.cold_lists[:8]]
        tracing.install_xla_compile_listener()
        # warm: settle on hot0, then one full drift cycle compiles
        # the stage/mix executables exactly once
        self._drive(ex, t, mgr, clock, self._near(flat_index, hot0),
                    12, flat_index)
        self._drive(ex, t, mgr, clock, self._near(flat_index, cold0),
                    14, flat_index)
        base = dict(tracing.counters())
        n0 = base.get(tracing.XLA_COMPILE_COUNT, 0)
        # measured: drift BACK — prefetch stages ahead, zero compiles
        self._drive(ex, t, mgr, clock, self._near(flat_index, hot0),
                    14, flat_index)
        c = tracing.counters()
        assert c.get(tracing.XLA_COMPILE_COUNT, 0) - n0 == 0
        assert c.get("tier.prefetch.issued", 0) > base.get(
            "tier.prefetch.issued", 0)
        assert c.get("tier.prefetch.hits", 0) > base.get(
            "tier.prefetch.hits", 0)
        # a hit's bytes moved at stage time: the epoch path charged
        # fewer cold bytes than its promotions would cost reactively
        promoted = (c.get("tier.promotions", 0)
                    - base.get("tier.promotions", 0))
        cold_bytes = (c.get("tier.promote_cold_bytes", 0)
                      - base.get("tier.promote_cold_bytes", 0))
        assert cold_bytes < promoted * t.block_bytes

    def test_stale_promotion_cancelled(self, flat_index):
        """A prefetch that lands after the placement moved under it
        (the list was promoted/demoted by a racing epoch) is refused
        by the generation check and counted cancelled — never mixed
        into a swap."""
        clock = ManualClock()
        t, ex, mgr, pf, _ = self._manager(flat_index, clock)
        lid = int(t.cold_lists[0])
        window = np.zeros((t.n_lists,), np.int64)
        window[lid] = 100
        assert pf.prefetch(max_swaps=4, window=window) == 1
        gen0 = t.generation
        # racing epoch: promote lid reactively, then demote it again
        tiered.apply_plan(t, [lid], [int(t.hot_lists[0])], width=4)
        tiered.apply_plan(t, [int(t.cold_lists[0])], [lid], width=4)
        assert t.generation == gen0 + 2
        base = dict(tracing.counters())
        staged = pf.take([lid], t.generation)
        assert staged is None
        c = tracing.counters()
        assert (c.get("tier.prefetch.cancelled", 0)
                == base.get("tier.prefetch.cancelled", 0) + 1)
        assert (c.get("tier.prefetch.hits", 0)
                == base.get("tier.prefetch.hits", 0))

    def test_epoch_mid_prefetch_generation_wins(self, data,
                                                flat_index):
        """Epoch fires between stage and take: the stale row is
        cancelled, the epoch streams reactively, and serving stays
        bit-identical to the flat index across the whole exchange."""
        _, q = data
        clock = ManualClock()
        t, ex, mgr, pf, _ = self._manager(flat_index, clock)
        p = TieredSearchParams(n_probes=8)
        lid = int(t.cold_lists[0])
        window = np.zeros((t.n_lists,), np.int64)
        window[lid] = 100
        assert pf.prefetch(max_swaps=4, window=window) == 1
        # the mid-prefetch epoch (another list's traffic wins)
        tiered.apply_plan(t, [int(t.cold_lists[1])],
                          [int(t.hot_lists[0])], width=4, executor=ex)
        staged = pf.take([lid], t.generation)
        assert staged is None                 # stale: refused
        tiered.apply_plan(t, [int(t.cold_lists[0])],
                          [int(t.hot_lists[1])], width=4, executor=ex)
        d1, i1 = ex.search(t, q, 10, params=p)
        d0, i0 = ivf_flat.search(
            None, ivf_flat.IvfFlatSearchParams(n_probes=8),
            flat_index, q, 10)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))

    def test_miss_cache_evicts_under_shrinking_headroom(self,
                                                        flat_index):
        clock = ManualClock()
        t, ex, mgr, pf, ledger = self._manager(flat_index, clock)
        window = np.zeros((t.n_lists,), np.int64)
        lids = [int(lid) for lid in t.cold_lists[:3]]
        window[lids] = (300, 200, 100)
        assert pf.prefetch(max_swaps=4, window=window) == 3
        assert pf.snapshot()["staged"] == 3
        assert ledger.reserved_bytes() == 3 * t.block_bytes
        # headroom collapses: everything but one block's worth goes
        ledger.capacity_bytes = (
            ledger.forecast()["peak_bytes"] + ledger.reserved_bytes()
            - 1.5 * t.block_bytes)
        before = tracing.counters().get("tier.prefetch.cancelled", 0)
        evicted = pf.maintain()
        assert evicted >= 2
        assert pf.snapshot()["staged"] == 3 - evicted
        assert tracing.counters().get("tier.prefetch.cancelled",
                                      0) == before + evicted
        assert ledger.reserved_bytes() == (
            (3 - evicted) * t.block_bytes)

    def test_capacity_exceeded_degrades_to_reactive(self, data,
                                                    flat_index):
        """The gate refusing a stage never surfaces: the prefetcher
        cancels, the epoch promotes reactively, searches succeed."""
        _, q = data
        clock = ManualClock()
        t, ex, mgr, pf, ledger = self._manager(flat_index, clock)
        # collapse headroom BEFORE any stage: every reserve refuses
        ledger.capacity_bytes = ledger.forecast()["peak_bytes"] + 1.0
        window = np.zeros((t.n_lists,), np.int64)
        window[int(t.cold_lists[0])] = 100
        before = tracing.counters().get("tier.prefetch.cancelled", 0)
        assert pf.prefetch(max_swaps=4, window=window) == 0
        assert tracing.counters().get("tier.prefetch.cancelled",
                                      0) == before + 1
        # serving and the reactive epoch are untouched
        p = TieredSearchParams(n_probes=8)
        d, i = ex.search(t, q, 10, params=p)
        plan = mgr.epoch()
        assert plan is not None
        d, i = ex.search(t, q, 10, params=p)
        assert np.asarray(d).shape == (q.shape[0], 10)

    def test_window_claimed_once_per_epoch(self, flat_index):
        """The satellite-6 lock fix: one epoch claims the probe
        window EXACTLY once, and the same single claim feeds both the
        plan and the prefetcher's EWMA — a racing scrape can't
        double-fold (the DriftDetector.update locking model)."""
        import threading

        clock = ManualClock()
        t, ex, mgr, pf, _ = self._manager(flat_index, clock)
        calls = []
        orig = ex.probe_frequencies

        def counting():
            calls.append(threading.get_ident())
            return orig()

        ex.probe_frequencies = counting
        p = TieredSearchParams(n_probes=4)
        ex.search(t, self._near(flat_index, [0, 1, 2]), 10, params=p)
        mgr.tick()                            # baseline stamp: no claim
        base_calls = len(calls)
        clock.advance(61.0)
        n_threads = 4
        barrier = threading.Barrier(n_threads)
        plans = []

        def racer():
            barrier.wait()
            plans.append(mgr.tick())

        threads = [threading.Thread(target=racer)
                   for _ in range(n_threads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60)
        ran = [pl for pl in plans if pl is not None]
        assert len(ran) == 1                  # one epoch, n racers
        # exactly one ledger claim for that epoch (the lead-time
        # peek is read-only and did not run here)
        assert len(calls) == base_calls + 1
        assert pf._epochs_observed == 1

    def test_disabled_prefetcher_is_reactive(self, flat_index):
        from raft_tpu.serving.prefetch import PrefetchConfig

        clock = ManualClock()
        t = build_tiered(flat_index, hot_fraction=0.5)
        ex = SearchExecutor(probe_accounting=True)
        mgr = TierManager(t, ex, clock=clock)
        pf = mgr.enable_prefetch(config=PrefetchConfig(capacity=0))
        assert not pf.enabled
        assert pf.prefetch(max_swaps=4) == 0
        assert pf.take([1], t.generation) is None
        assert mgr.epoch() is not None
