"""IVF-Flat tests — statistical recall pattern of the reference
(cpp/test/neighbors/ann_ivf_flat.cuh): random data → brute-force ground
truth → build/search → recall >= threshold; plus exhaustive-probe
exactness, extend, filters, serialization."""

import numpy as np
import pytest
import scipy.spatial.distance as spd

from raft_tpu.core.bitset import Bitset
from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import ivf_flat
from raft_tpu.neighbors.ivf_flat import IvfFlatIndexParams, IvfFlatSearchParams
from raft_tpu.utils import eval_neighbours


@pytest.fixture(scope="module")
def dataset(request):
    rng = np.random.default_rng(7)
    x = rng.standard_normal((4000, 24)).astype(np.float32)
    q = rng.standard_normal((50, 24)).astype(np.float32)
    return x, q


def _gt(x, q, k, metric="sqeuclidean"):
    d = -(q @ x.T) if metric == "ip" else spd.cdist(q, x, metric)
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=1), idx


class TestIvfFlat:
    def test_recall_l2(self, dataset):
        x, q = dataset
        params = IvfFlatIndexParams(n_lists=32, kmeans_n_iters=10)
        index = ivf_flat.build(None, params, x)
        assert index.size == len(x)
        # unstructured gaussian data is the worst case for IVF; probing
        # 25% of lists lands ~0.73, 50% ~0.92 (the reference's statistical
        # thresholds are likewise per-config, ann_ivf_flat.cuh)
        dist, idx = ivf_flat.search(None, IvfFlatSearchParams(n_probes=8),
                                    index, q, 10)
        gt_d, gt_i = _gt(x, q, 10)
        eval_neighbours(gt_i, np.asarray(idx), gt_d, np.asarray(dist),
                        min_recall=0.65)
        dist16, idx16 = ivf_flat.search(None, IvfFlatSearchParams(n_probes=16),
                                        index, q, 10)
        eval_neighbours(gt_i, np.asarray(idx16), gt_d, np.asarray(dist16),
                        min_recall=0.85)

    def test_exhaustive_probes_exact(self, dataset):
        """n_probes == n_lists must reproduce brute force exactly."""
        x, q = dataset
        params = IvfFlatIndexParams(n_lists=16, kmeans_n_iters=5)
        index = ivf_flat.build(None, params, x)
        dist, idx = ivf_flat.search(None, IvfFlatSearchParams(n_probes=16),
                                    index, q, 10)
        gt_d, gt_i = _gt(x, q, 10)
        recall = eval_neighbours(gt_i, np.asarray(idx), gt_d, np.asarray(dist),
                                 min_recall=0.999)
        np.testing.assert_allclose(np.asarray(dist), gt_d, rtol=1e-3, atol=1e-2)

    def test_sqrt_metric(self, dataset):
        x, q = dataset
        params = IvfFlatIndexParams(n_lists=16, metric=DistanceType.L2SqrtExpanded)
        index = ivf_flat.build(None, params, x)
        dist, idx = ivf_flat.search(None, IvfFlatSearchParams(n_probes=16),
                                    index, q, 5)
        gt_d, gt_i = _gt(x, q, 5, "euclidean")
        np.testing.assert_allclose(np.asarray(dist), gt_d, rtol=1e-3, atol=1e-2)

    def test_inner_product(self, dataset):
        x, q = dataset
        params = IvfFlatIndexParams(n_lists=16, metric=DistanceType.InnerProduct)
        index = ivf_flat.build(None, params, x)
        sims, idx = ivf_flat.search(None, IvfFlatSearchParams(n_probes=16),
                                    index, q, 10)
        want = -_gt(x, q, 10, "ip")[0]
        np.testing.assert_allclose(np.sort(np.asarray(sims), 1),
                                   np.sort(want, 1), rtol=1e-3, atol=1e-2)

    def test_build_then_extend_matches(self, dataset):
        """Building on half then extending with the rest must cover all ids."""
        x, q = dataset
        params = IvfFlatIndexParams(n_lists=16, add_data_on_build=False)
        index = ivf_flat.build(None, params, x)
        assert index.size == 0
        index = ivf_flat.extend(None, index, x[:2000],
                                np.arange(2000, dtype=np.int32))
        index = ivf_flat.extend(None, index, x[2000:],
                                np.arange(2000, 4000, dtype=np.int32))
        assert index.size == 4000
        dist, idx = ivf_flat.search(None, IvfFlatSearchParams(n_probes=16),
                                    index, q, 10)
        gt_d, gt_i = _gt(x, q, 10)
        eval_neighbours(gt_i, np.asarray(idx), gt_d, np.asarray(dist),
                        min_recall=0.999)

    def test_sample_filter(self, dataset):
        """Filtered-out ids must never appear in results."""
        x, q = dataset
        params = IvfFlatIndexParams(n_lists=16)
        index = ivf_flat.build(None, params, x)
        mask = np.ones(len(x), bool)
        mask[::2] = False  # filter out even ids
        filt = Bitset.from_mask(mask)
        _, idx = ivf_flat.search(None, IvfFlatSearchParams(n_probes=16),
                                 index, q, 10, sample_filter=filt)
        idx = np.asarray(idx)
        valid = idx[idx >= 0]
        assert (valid % 2 == 1).all()

    def test_int8_dataset(self):
        rng = np.random.default_rng(0)
        x = rng.integers(-100, 100, (1000, 16)).astype(np.int8)
        q = x[:10].astype(np.float32)
        params = IvfFlatIndexParams(n_lists=8)
        index = ivf_flat.build(None, params, x)
        assert index.data.dtype == np.int8
        _, idx = ivf_flat.search(None, IvfFlatSearchParams(n_probes=8),
                                 index, q, 1)
        np.testing.assert_array_equal(np.asarray(idx)[:, 0], np.arange(10))

    def test_serialization_roundtrip(self, dataset, tmp_path):
        x, q = dataset
        params = IvfFlatIndexParams(n_lists=16)
        index = ivf_flat.build(None, params, x)
        path = tmp_path / "ivf.bin"
        ivf_flat.save(index, path)
        loaded = ivf_flat.load(None, path)
        d1, i1 = ivf_flat.search(None, IvfFlatSearchParams(n_probes=4), index, q, 5)
        d2, i2 = ivf_flat.search(None, IvfFlatSearchParams(n_probes=4), loaded, q, 5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-5)

    def test_bf16_serialization_roundtrip(self, dataset, tmp_path):
        """bf16-stored lists survive save/load (regression: ml_dtypes
        arrays previously wrote as untyped '|V2' npy records)."""
        import jax.numpy as jnp

        x, q = dataset
        index = ivf_flat.build(None, IvfFlatIndexParams(n_lists=16),
                               jnp.asarray(x, jnp.bfloat16))
        assert index.data.dtype == jnp.bfloat16
        path = tmp_path / "ivf_bf16.bin"
        ivf_flat.save(index, path)
        loaded = ivf_flat.load(None, path)
        assert loaded.data.dtype == jnp.bfloat16
        _, i1 = ivf_flat.search(None, IvfFlatSearchParams(n_probes=4),
                                index, q, 5)
        _, i2 = ivf_flat.search(None, IvfFlatSearchParams(n_probes=4),
                                loaded, q, 5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_k_larger_than_probed(self, dataset):
        """k bigger than candidates in probed lists → -1 padding."""
        x, q = dataset
        params = IvfFlatIndexParams(n_lists=64)
        index = ivf_flat.build(None, params, x)
        dist, idx = ivf_flat.search(None, IvfFlatSearchParams(n_probes=1),
                                    index, q[:2], 500)
        idx = np.asarray(idx)
        assert (idx == -1).any()  # one small list can't fill k=500


class TestFilterTypes:
    def test_bitmap_per_query_filter(self, dataset):
        """Per-query bitmap: each query greenlights a different id set."""
        from raft_tpu.neighbors.filters import BitmapFilter, BitsetFilter, NoneSampleFilter

        x, q = dataset
        q = q[:6]
        params = IvfFlatIndexParams(n_lists=16)
        index = ivf_flat.build(None, params, x)
        n = len(x)
        mask = np.ones((6, n), bool)
        for r in range(6):
            mask[r, r::3] = False  # query r forbids ids ≡ r (mod 3)
        filt = BitmapFilter.from_mask(mask)
        _, idx = ivf_flat.search(None, IvfFlatSearchParams(n_probes=16),
                                 index, q, 10, sample_filter=filt)
        idx = np.asarray(idx)
        for r in range(6):
            valid = idx[r][idx[r] >= 0]
            assert valid.size > 0
            assert (valid % 3 != r % 3).all() or not np.any(valid % 3 == r % 3)
            assert mask[r, valid].all()

        # NoneSampleFilter == no filter
        _, i_none = ivf_flat.search(None, IvfFlatSearchParams(n_probes=16),
                                    index, q, 10,
                                    sample_filter=NoneSampleFilter())
        _, i_raw = ivf_flat.search(None, IvfFlatSearchParams(n_probes=16),
                                   index, q, 10)
        assert np.array_equal(np.asarray(i_none), np.asarray(i_raw))

        # BitsetFilter wrapper == raw Bitset
        m1 = np.ones(n, bool); m1[::2] = False
        b = Bitset.from_mask(m1)
        _, i_a = ivf_flat.search(None, IvfFlatSearchParams(n_probes=16),
                                 index, q, 10, sample_filter=b)
        _, i_b = ivf_flat.search(None, IvfFlatSearchParams(n_probes=16),
                                 index, q, 10,
                                 sample_filter=BitsetFilter(b))
        assert np.array_equal(np.asarray(i_a), np.asarray(i_b))


class TestQueryTiling:
    def test_tiled_matches_untiled(self, dataset):
        x, _ = dataset
        rng = np.random.default_rng(1)
        q = rng.standard_normal((50, x.shape[1])).astype(np.float32)
        index = ivf_flat.build(None, IvfFlatIndexParams(n_lists=16), x)
        sp = IvfFlatSearchParams(n_probes=16)
        d1, i1 = ivf_flat.search(None, sp, index, q, 10)
        d2, i2 = ivf_flat.search(None, sp, index, q, 10, query_tile=16)
        assert np.array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2))


class TestApproxCoarse:
    def test_approx_coarse_recall(self, dataset):
        x, q = dataset
        index = ivf_flat.build(None, IvfFlatIndexParams(n_lists=32), x)
        _, i1 = ivf_flat.search(None, IvfFlatSearchParams(n_probes=16),
                                index, q, 10)
        _, i2 = ivf_flat.search(
            None, IvfFlatSearchParams(n_probes=16, coarse_algo="approx"),
            index, q, 10)
        overlap = np.mean([
            len(set(np.asarray(i1)[r]) & set(np.asarray(i2)[r])) / 10
            for r in range(len(q))
        ])
        assert overlap >= 0.9, overlap


class TestBf16Storage:
    def test_bf16_dataset_recall(self, rng_np):
        """bf16 list storage (the reference's fp16 dataset analog): the
        padded lists keep the storage dtype, norms/scan run f32, and
        recall matches the f32 index on well-separated data."""
        import jax.numpy as jnp
        from raft_tpu.neighbors import brute_force
        from raft_tpu.utils import eval_recall

        centers = rng_np.standard_normal((8, 32)) * 6
        x = (centers[rng_np.integers(0, 8, 4000)]
             + rng_np.standard_normal((4000, 32))).astype(np.float32)
        q = (centers[rng_np.integers(0, 8, 16)]
             + rng_np.standard_normal((16, 32))).astype(np.float32)
        _, gt = brute_force.knn(None, x, q, 10)

        idx = ivf_flat.build(None, IvfFlatIndexParams(n_lists=32),
                             jnp.asarray(x, jnp.bfloat16))
        assert idx.data.dtype == jnp.bfloat16
        _, i = ivf_flat.search(None, IvfFlatSearchParams(n_probes=16),
                               idx, q, 10)
        r, _, _ = eval_recall(np.asarray(gt), np.asarray(i))
        assert r >= 0.95, r
