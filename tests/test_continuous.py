"""graftfleet continuous-attribution tests (PR 12) — the low-duty-
cycle capture scheduler and the rolling EWMA attribution.

Everything here is ManualClock-pinned and device-free: the capture is
injected (the committed graftflight chrome fixture), so the duty-cycle
budget, defer-vs-skip accounting, period gating, and the EWMA fold
arithmetic are exact. The REAL-capture proof lives in
``tests/test_profiling.py``'s live round trip, which drives this
scheduler over two genuine ``jax.profiler`` windows.
"""

import os
import threading

import pytest

from raft_tpu.core import profiling, tracing
from raft_tpu.serving import (
    ContinuousCapture,
    ContinuousConfig,
    MetricsExporter,
)
from raft_tpu.serving import continuous as cont_mod
from raft_tpu.serving import metrics
from raft_tpu.serving.harness import ManualClock

FIXTURE = os.path.join(os.path.dirname(__file__), "data",
                       "graftflight_capture.trace.json")

COSTS = {
    "aaaa01aaaa01": {
        "hlo_module": "jit_rt_ivf_flat_aaaa01aaaa01",
        "family": "ivf_flat", "bucket": 8, "k": 5,
        "bytes_accessed": 270_000.0, "flops": 540_000.0,
    },
    "bbbb02bbbb02": {
        "hlo_module": "jit_rt_dist_ivf_flat_bbbb02bbbb02",
        "family": "dist_ivf_flat", "bucket": 16, "k": 5,
        "bytes_accessed": 1_300_000.0, "flops": 2_600_000.0,
        "collective_payload": {"coarse_bytes": 2048,
                               "merge_bytes": 512},
    },
}


class StubExecutor:
    def executable_costs(self):
        return dict(COSTS)


def counting_capture(calls):
    def capture():
        calls.append(1)
        return profiling.load_trace(FIXTURE)
    return capture


def make_cc(clock, config=None, capture=None, executor=None):
    return ContinuousCapture(
        executor=executor or StubExecutor(),
        config=config or ContinuousConfig(),
        clock=clock,
        capture_fn=capture or (lambda: profiling.load_trace(FIXTURE)))


class TestDutyCycleSchedule:
    def setup_method(self):
        metrics.reset()

    def test_first_tick_captures_then_period_gates(self):
        clock = ManualClock()
        calls = []
        cc = make_cc(clock, ContinuousConfig(period_s=10.0,
                                             capture_seconds=0.05),
                     capture=counting_capture(calls))
        caps0 = tracing.get_counter(cont_mod.CAPTURES)
        assert cc.tick() is not None          # first tick captures
        clock.advance(5.0)
        assert cc.tick() is None              # mid-period: quiet
        assert len(calls) == 1
        clock.advance(5.0)
        assert cc.tick() is not None          # period elapsed
        assert len(calls) == 2
        assert tracing.get_counter(cont_mod.CAPTURES) == caps0 + 2
        assert tracing.get_counter(cont_mod.TICKS) >= 3

    def test_elapsed_periods_never_stack(self):
        clock = ManualClock()
        calls = []
        cc = make_cc(clock, ContinuousConfig(period_s=10.0,
                                             capture_seconds=0.05),
                     capture=counting_capture(calls))
        cc.tick()
        # a long quiet stretch (scrapes stopped, service idle): ten
        # periods elapsed, but the next tick runs exactly ONE capture
        clock.advance(100.0)
        assert cc.tick() is not None
        assert cc.tick() is None
        assert len(calls) == 2

    def test_budget_skips_due_ticks(self):
        # a misconfigured cadence (10% duty asked, 1% budget): the
        # budget is a hard ceiling — due ticks SKIP (counted) until
        # the cumulative duty cycle re-enters the budget
        clock = ManualClock()
        calls = []
        cc = make_cc(clock, ContinuousConfig(
            period_s=1.0, capture_seconds=0.1,
            duty_cycle_budget=0.01), capture=counting_capture(calls))
        skipped0 = tracing.get_counter(cont_mod.SKIPPED)
        assert cc.tick() is not None          # first capture admits
        for _ in range(9):
            clock.advance(1.0)
            cc.tick()
        # 0.1 s spent amortizes back under the 1% budget only at
        # t = 10 s: every due tick before that SKIPS, counted
        assert len(calls) == 1
        assert tracing.get_counter(cont_mod.SKIPPED) == skipped0 + 9
        clock.advance(1.0)                    # t = 10 = 0.1 / 0.01
        assert cc.tick() is not None
        assert len(calls) == 2
        assert cc.duty_cycle() == pytest.approx(0.2 / 10.0)
        # the long-run cadence settles at capture_seconds / budget —
        # the budget's own period — not the misconfigured 1 s one
        skipped1 = tracing.get_counter(cont_mod.SKIPPED)
        for _ in range(9):
            clock.advance(1.0)
            cc.tick()
        assert len(calls) == 2
        assert tracing.get_counter(cont_mod.SKIPPED) == skipped1 + 9
        clock.advance(1.0)                    # t = 20 = 0.2 / 0.01
        assert cc.tick() is not None
        assert len(calls) == 3

    def test_default_config_respects_one_percent(self):
        cfg = ContinuousConfig()
        assert cfg.capture_seconds / cfg.period_s <= \
            cfg.duty_cycle_budget
        clock = ManualClock()
        calls = []
        cc = make_cc(clock, cfg, capture=counting_capture(calls))
        skipped0 = tracing.get_counter(cont_mod.SKIPPED)
        for _ in range(20):
            cc.tick()
            clock.advance(cfg.period_s)
        # the default cadence never trips the budget guard
        assert tracing.get_counter(cont_mod.SKIPPED) == skipped0
        assert len(calls) == 20
        assert cc.duty_cycle() <= cfg.duty_cycle_budget + 1e-12

    def test_busy_profiler_defers_without_consuming_period(self):
        clock = ManualClock()
        calls = []
        cc = make_cc(clock, ContinuousConfig(period_s=10.0),
                     capture=counting_capture(calls))
        # an operator /profile (or incident) capture owns the lock
        cc.profile_lock = threading.Lock()
        def0 = tracing.get_counter(cont_mod.DEFERRED)
        with cc.profile_lock:
            assert cc.tick() is None
            assert cc.tick() is None
        assert tracing.get_counter(cont_mod.DEFERRED) == def0 + 2
        assert not calls
        # the period stamp was NOT advanced: the freed lock lets the
        # very next tick capture without waiting another period
        assert cc.tick() is not None
        assert len(calls) == 1

    def test_capture_error_counted_not_raised(self):
        clock = ManualClock()

        def bad():
            raise RuntimeError("profiler unavailable")

        cc = make_cc(clock, ContinuousConfig(period_s=1.0,
                                             capture_seconds=0.001),
                     capture=bad)
        err0 = tracing.get_counter(cont_mod.ERRORS)
        assert cc.tick() is None
        assert tracing.get_counter(cont_mod.ERRORS) == err0 + 1
        # the scheduler survives: a later (working) tick captures
        cc.capture_fn = lambda: profiling.load_trace(FIXTURE)
        clock.advance(1.0)
        assert cc.tick() is not None

    def test_empty_capture_counted(self):
        clock = ManualClock()
        cc = make_cc(clock, ContinuousConfig(period_s=1.0,
                                             capture_seconds=0.001),
                     capture=lambda: None)
        empty0 = tracing.get_counter(cont_mod.EMPTY)
        assert cc.tick() is None
        assert tracing.get_counter(cont_mod.EMPTY) == empty0 + 1


def scripted_attr(secs, bytes_, flops=0.0, digest="dddd01",
                  skews=()):
    """A minimal Attribution with one module — the EWMA fold's input."""
    windows = [profiling.InvocationWindow(
        start_s=0.0, end_s=secs, ops=1, device_seconds=secs,
        phase_seconds={}, shard_seconds={"a": 0.0, "b": sk})
        for sk in skews]
    mod = profiling.ModuleAttribution(
        digest=digest, module=f"jit_rt_x_{digest}", family="x",
        device_seconds=secs, invocations=1,
        phase_seconds={"scan": secs}, shard_seconds={},
        window=(0.0, secs), modeled_bytes_per_call=bytes_,
        modeled_flops_per_call=flops, windows=windows)
    return profiling.Attribution(modules={digest: mod},
                                 unmatched_modules={})


class TestRollingAttribution:
    def setup_method(self):
        metrics.reset()

    def test_ewma_fold_pinned(self):
        r = profiling.RollingAttribution(alpha=0.5)
        folds0 = tracing.get_counter(profiling.ROLLING_FOLDS)
        s1 = r.fold(scripted_attr(1.0, 10e9))
        assert s1["windows"] == 1
        assert s1["gbps"] == pytest.approx(10.0)
        s2 = r.fold(scripted_attr(1.0, 20e9))
        # bytes EWMA 0.5*20 + 0.5*10 = 15 GB over seconds EWMA 1.0
        assert s2["windows"] == 2
        assert s2["device_seconds"] == pytest.approx(1.0)
        assert s2["gbps"] == pytest.approx(15.0)
        assert s2["phase_seconds"]["scan"] == pytest.approx(1.0)
        assert tracing.get_counter(profiling.ROLLING_FOLDS) == \
            folds0 + 2
        g = tracing.gauges(profiling.ROLLING_PREFIX)
        assert g[profiling.ROLLING_PREFIX + "windows"] == 2.0
        assert g[profiling.ROLLING_PREFIX + "gbps"] == \
            pytest.approx(15.0)
        # the per-executable labeled family rides along
        assert tracing.get_gauge(
            "serving.executable.dddd01.rolling_gbps") == \
            pytest.approx(15.0)

    def test_absent_executable_holds_its_value(self):
        r = profiling.RollingAttribution(alpha=0.5)
        r.fold(scripted_attr(1.0, 10e9, digest="aaaa01"))
        r.fold(scripted_attr(2.0, 30e9, digest="cccc02"))
        snap = r.snapshot()
        # a window that did not overlap aaaa01's traffic is no
        # evidence it changed: its per-exec state holds
        assert snap["executables"]["aaaa01"]["gbps"] == \
            pytest.approx(10.0)
        assert snap["executables"]["cccc02"]["gbps"] == \
            pytest.approx(15.0)
        # totals fold what each window measured
        assert snap["device_seconds"] == pytest.approx(
            0.5 * 2.0 + 0.5 * 1.0)

    def test_empty_attribution_is_not_evidence(self):
        r = profiling.RollingAttribution()
        assert r.fold(profiling.Attribution(modules={},
                                            unmatched_modules={})) \
            is None
        assert r.snapshot()["windows"] == 0

    def test_skew_p99_folds(self):
        r = profiling.RollingAttribution(alpha=0.5)
        s1 = r.fold(scripted_attr(1.0, 1e9, skews=(100e-6, 300e-6)))
        assert s1["shard_skew_p99"] == pytest.approx(298e-6)
        s2 = r.fold(scripted_attr(1.0, 1e9, skews=(100e-6,)))
        assert s2["shard_skew_p99"] == pytest.approx(
            0.5 * 100e-6 + 0.5 * 298e-6)

    def test_derived_carries_rolling_columns(self):
        r = profiling.RollingAttribution(alpha=0.5)
        r.fold(scripted_attr(1.0, 10e9, flops=5e9))
        d = metrics.derived()
        assert d["rolling_windows"] == 1.0
        assert d["rolling_gbps"] == pytest.approx(10.0)
        assert d["rolling_gflops"] == pytest.approx(5.0)
        assert d["rolling_device_seconds"] == pytest.approx(1.0)

    def test_publish_restores_gauges_after_reset(self):
        r = profiling.RollingAttribution()
        r.fold(scripted_attr(1.0, 10e9))
        metrics.reset()
        assert tracing.get_gauge(
            profiling.ROLLING_PREFIX + "gbps") == 0.0
        r.publish()
        assert tracing.get_gauge(
            profiling.ROLLING_PREFIX + "gbps") == pytest.approx(10.0)


class TestSchedulerFeedsRolling:
    def setup_method(self):
        metrics.reset()

    def test_two_windows_populate_rolling_gauges(self):
        clock = ManualClock()
        cc = make_cc(clock, ContinuousConfig(period_s=15.0))
        assert cc.tick() is not None
        clock.advance(15.0)
        snap = cc.tick()
        assert snap["windows"] == 2
        # the fixture's round numbers: both executables at 1.0 GB/s
        assert snap["gbps"] == pytest.approx(1.0, rel=1e-6)
        g = tracing.gauges(profiling.ROLLING_PREFIX)
        assert g[profiling.ROLLING_PREFIX + "windows"] == 2.0
        assert g[profiling.ROLLING_PREFIX + "gbps"] == \
            pytest.approx(1.0, rel=1e-6)
        # measured-supersedes-modeled ran per window too (publish):
        # the per-capture measured gauges are fresh
        assert tracing.get_gauge(
            "serving.executable.aaaa01aaaa01.measured_gbps") == \
            pytest.approx(1.0, rel=1e-6)
        assert tracing.get_gauge(
            cont_mod.GAUGE_PREFIX + "windows") == 2.0
        # two 0.1 s windows over 15 s elapsed: the measured duty cycle
        # transiently overshoots 1% right after a capture and
        # amortizes back under it — the gauge reports honestly
        assert tracing.get_gauge(
            cont_mod.GAUGE_PREFIX + "duty_cycle") == \
            pytest.approx(0.2 / 15.0)

    def test_exporter_scrape_drives_tick_and_wires_lock(self):
        clock = ManualClock()
        cc = make_cc(clock, ContinuousConfig(period_s=15.0))
        exp = MetricsExporter(continuous=cc)
        # the shared one-capture-at-a-time lock is wired at attach
        assert cc.profile_lock is exp._profile_lock
        ticks0 = tracing.get_counter(cont_mod.TICKS)
        caps0 = tracing.get_counter(cont_mod.CAPTURES)
        text = exp.prometheus_text()
        assert tracing.get_counter(cont_mod.TICKS) == ticks0 + 1
        assert tracing.get_counter(cont_mod.CAPTURES) == caps0 + 1
        assert "serving_attribution_rolling_gbps" in text
        # while /profile holds the lock, the scrape's tick defers
        clock.advance(15.0)
        def0 = tracing.get_counter(cont_mod.DEFERRED)
        with exp._profile_lock:
            exp.prometheus_text()
        assert tracing.get_counter(cont_mod.DEFERRED) == def0 + 1
