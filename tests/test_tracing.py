"""The graftscope observability core (``core/tracing.py``): histogram
thread safety + cumulative buckets, gauges, and the span flight
recorder with its Chrome trace-event round trip."""

import json
import threading

import pytest

from raft_tpu.core import tracing


class TestHistogramConcurrency:
    def test_concurrent_observe_loses_nothing(self):
        """PR 5's ``get_histogram`` handed out live objects whose
        ``observe`` ran unlocked — racing increments could drop
        counts. Hammer one instance from many threads and assert
        exact totals."""
        h = tracing.Histogram()
        n_threads, per_thread = 8, 5000
        start = threading.Barrier(n_threads)

        def worker(seed):
            start.wait()
            for i in range(per_thread):
                h.observe(1e-6 * ((seed + i) % 50 + 1))

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = h.snapshot()
        assert snap["count"] == n_threads * per_thread
        assert sum(h.counts) == n_threads * per_thread
        assert snap["bucket_counts"][-1] == n_threads * per_thread

    def test_concurrent_snapshot_is_consistent(self):
        """A snapshot taken mid-storm must be internally consistent:
        its cumulative bucket total equals its count."""
        h = tracing.Histogram()
        stop = threading.Event()

        def writer():
            i = 0
            while not stop.is_set():
                h.observe(1e-6 * (i % 30 + 1))
                i += 1

        w = threading.Thread(target=writer)
        w.start()
        try:
            for _ in range(200):
                snap = h.snapshot()
                assert snap["bucket_counts"][-1] == snap["count"]
        finally:
            stop.set()
            w.join()


class TestHistogramBuckets:
    def test_cumulative_buckets_shape_and_monotonicity(self):
        h = tracing.Histogram()
        for v in (0.5e-6, 3e-6, 3e-6, 1.0):
            h.observe(v)
        snap = h.snapshot()
        bounds, cum = snap["bucket_bounds"], snap["bucket_counts"]
        assert len(cum) == len(bounds) + 1      # +Inf overflow bucket
        assert cum == sorted(cum)               # cumulative => monotone
        assert cum[-1] == snap["count"] == 4
        # first bucket (le 1e-6) holds exactly the 0.5 µs observation
        assert cum[0] == 1

    def test_empty_histogram(self):
        h = tracing.Histogram()
        snap = h.snapshot()
        assert snap["count"] == 0 and snap["sum"] == 0.0
        assert h.quantile(0.5) == 0.0
        assert snap["p50"] == snap["p95"] == snap["p99"] == 0.0
        assert snap["bucket_counts"][-1] == 0

    def test_single_observation_quantile(self):
        """Every quantile of a single observation lands inside that
        observation's bucket (linear interpolation within it)."""
        h = tracing.Histogram()
        h.observe(5e-6)                          # bucket (4e-6, 8e-6]
        for q in (0.01, 0.5, 0.99):
            assert 4e-6 < h.quantile(q) <= 8e-6, q

    def test_overflow_bucket_estimate(self):
        """Observations past the last bound interpolate inside the
        synthetic overflow bucket (last bound, 2 × last bound] — a
        bounded estimate, not garbage — and q→1 hits the 2× cap."""
        h = tracing.Histogram()
        h.observe(1e9)
        top = h.bounds[-1]
        assert top < h.quantile(0.5) <= 2.0 * top
        assert h.quantile(1.0) == pytest.approx(2.0 * top)
        snap = h.snapshot()
        assert snap["bucket_counts"][-2] == 0    # nothing below +Inf
        assert snap["bucket_counts"][-1] == 1


class TestGauges:
    def test_set_get_prefix_reset(self):
        tracing.reset_gauges("t_gauge.")
        tracing.set_gauge("t_gauge.a", 3.0)
        tracing.set_gauge("t_gauge.a", 1.5)      # last write wins
        tracing.set_gauges({"t_gauge.b": 2.0, "other.c": 7.0})
        try:
            assert tracing.get_gauge("t_gauge.a") == 1.5
            assert tracing.get_gauge("t_gauge.missing", -1.0) == -1.0
            assert tracing.gauges("t_gauge.") == {"t_gauge.a": 1.5,
                                                  "t_gauge.b": 2.0}
            tracing.reset_gauges("t_gauge.")
            assert tracing.gauges("t_gauge.") == {}
            assert tracing.get_gauge("other.c") == 7.0
        finally:
            tracing.reset_gauges("t_gauge.")
            tracing.reset_gauges("other.c")

    def test_inc_counters_batch(self):
        tracing.reset_counters("t_batch.")
        try:
            tracing.inc_counters({"t_batch.x": 2.0, "t_batch.y": 1.0})
            tracing.inc_counters({"t_batch.x": 3.0})
            assert tracing.get_counter("t_batch.x") == 5.0
            assert tracing.get_counter("t_batch.y") == 1.0
        finally:
            tracing.reset_counters("t_batch.")

    def test_reset_folds_into_lifetime_ledger(self):
        """``reset_counters`` moves counts into the process-lifetime
        ledger instead of discarding them: the session-end CI snapshot
        floors read :func:`lifetime_counters`, so a mid-session test
        reset must not blank the session's accounting."""
        tracing.reset_counters("t_life.")
        base = tracing.lifetime_counters("t_life.")
        tracing.inc_counter("t_life.a", 2.0)
        tracing.reset_counters("t_life.")        # folds, not discards
        tracing.inc_counter("t_life.a", 3.0)     # live again
        life = tracing.lifetime_counters("t_life.")
        assert life["t_life.a"] - base.get("t_life.a", 0.0) == 5.0
        # the LIVE view only sees what ran after the reset
        assert tracing.get_counter("t_life.a") == 3.0
        tracing.reset_counters("t_life.")


class TestSpanRecorder:
    def test_record_filter_and_trace_ids(self):
        r = tracing.SpanRecorder(capacity=16)
        a, b = tracing.new_trace_id(), tracing.new_trace_id()
        assert a != b
        r.record("stage.one", 0.0, 1.0, trace_ids=(a,))
        r.record("stage.two", 1.0, 2.0, trace_ids=(a, b))
        r.event("mark", 1.5, trace_ids=(b,), attrs={"reason": "x"})
        assert len(r) == 3
        assert [s.name for s in r.spans(trace_id=a)] == ["stage.one",
                                                         "stage.two"]
        only_b = r.spans(trace_id=b)
        assert [s.name for s in only_b] == ["stage.two", "mark"]
        assert r.spans(name="mark")[0].duration == 0.0
        assert r.spans(name="mark")[0].attrs["reason"] == "x"

    def test_ring_bounds_and_drop_accounting(self):
        """The flight recorder is bounded: old spans fall off, and the
        overwrite count is visible (a post-mortem must know whether it
        sees the whole story)."""
        r = tracing.SpanRecorder(capacity=4)
        for i in range(10):
            r.record(f"s{i}", float(i), float(i) + 0.5)
        assert len(r) == 4
        assert r.dropped == 6
        assert [s.name for s in r.spans()] == ["s6", "s7", "s8", "s9"]
        r.clear()
        assert len(r) == 0 and r.dropped == 0

    def test_chrome_trace_round_trip(self):
        """Export → json.dumps → json.loads → import reproduces the
        exact span list (timestamps ride in args as float seconds, so
        µs conversion lossiness cannot corrupt a post-mortem)."""
        r = tracing.SpanRecorder(capacity=8)
        tid = tracing.new_trace_id()
        r.record("serving.execute", 0.1, 0.25, trace_ids=(tid,),
                 attrs={"rows": 17},
                 events=((0.2, "failed", {"error": "ValueError"}),))
        r.event("serving.shed", 0.3, trace_ids=(tid,),
                attrs={"reason": "deadline"})
        data = json.loads(json.dumps(r.to_chrome_trace()))
        assert data["traceEvents"], data
        xs = [e for e in data["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in xs} == {"serving.execute",
                                           "serving.shed"}
        # event marks surface as instant events for Perfetto
        instants = [e for e in data["traceEvents"] if e["ph"] == "i"]
        assert any(e["name"] == "serving.execute.failed"
                   for e in instants)
        # zero-duration spans (shed/cancel/reject reasons) surface as
        # clickable instant marks too, not just invisible dur=0 slices
        shed_marks = [e for e in instants if e["name"] == "serving.shed"]
        assert shed_marks and shed_marks[0]["args"]["reason"] == "deadline"
        back = tracing.SpanRecorder.from_chrome_trace(data)
        assert back == r.spans()

    def test_chrome_trace_reserved_keys_win_over_attrs(self):
        """A span attr named like a reserved arg key (``t0_s`` etc.)
        must not corrupt the export: the reserved keys win, so the
        rebuilt span keeps exact timing/ids and only the colliding
        attr itself is shadowed."""
        r = tracing.SpanRecorder(capacity=4)
        r.record("x", 1.0, 2.0, trace_ids=(7,),
                 attrs={"t0_s": "label", "trace_ids": "oops", "rows": 3})
        (back,) = tracing.SpanRecorder.from_chrome_trace(
            json.loads(json.dumps(r.to_chrome_trace())))
        assert (back.start, back.end) == (1.0, 2.0)
        assert back.trace_ids == (7,)
        assert back.attrs == {"rows": 3}

    def test_process_ring_helpers(self):
        tracing.reset_spans()
        try:
            tid = tracing.new_trace_id()
            tracing.record_span("stage", 1.0, 2.0, trace_ids=(tid,))
            tracing.span_event("mark", 1.5, trace_ids=(tid,))
            assert len(tracing.span_recorder().spans(trace_id=tid)) == 2
        finally:
            tracing.reset_spans()

    def test_host_span_context_manager(self):
        tracing.reset_spans()
        try:
            with tracing.host_span("build.extend", attrs={"n": 3}):
                pass
            (s,) = tracing.span_recorder().spans(name="build.extend")
            assert s.end >= s.start
            assert s.attrs == {"n": 3}
        finally:
            tracing.reset_spans()


class TestStragglerDetector:
    """graftscope v2: per-shard timings reduce into exact straggler
    attribution — gauges, phase spans, and the trace_id-filtered
    Chrome export."""

    def test_straggler_stats_exact(self):
        stats = tracing.straggler_stats([0.010, 0.004, 0.025, 0.007])
        assert stats["shards"] == 4
        assert stats["slowest_shard"] == 2
        assert stats["shard_skew"] == pytest.approx(0.021)
        assert stats["max_s"] == 0.025
        assert stats["mean_s"] == pytest.approx(0.0115)
        empty = tracing.straggler_stats([])
        assert empty["slowest_shard"] == -1
        assert empty["shard_skew"] == 0.0

    def test_record_mesh_spans_spans_and_gauges(self):
        tracing.reset_spans()
        tracing.reset_gauges("serving.mesh.")
        tracing.reset_counters("serving.mesh.")
        try:
            tid = tracing.new_trace_id()
            stats = tracing.record_mesh_spans(
                "dist_ivf_flat", 10.0, 10.5, trace_ids=(tid,),
                phases={"coarse_select": {"wire_bytes": 256},
                        "merge": {"wire_bytes": 1280}},
                shard_timings=[0.1, 0.5, 0.2])
            rec = tracing.span_recorder()
            (cs,) = rec.spans(trace_id=tid,
                              name="serving.mesh.coarse_select")
            assert cs.attrs["wire_bytes"] == 256
            assert cs.attrs["family"] == "dist_ivf_flat"
            assert (cs.start, cs.end) == (10.0, 10.5)
            shards = rec.spans(trace_id=tid, name="serving.mesh.shard")
            assert [s.attrs["shard"] for s in shards] == [0, 1, 2]
            assert shards[1].end == pytest.approx(10.5)
            # gauges pin to the scripted timings exactly
            assert tracing.get_gauge(
                tracing.MESH_SHARD_SKEW) == pytest.approx(0.4)
            assert tracing.get_gauge(tracing.MESH_SLOWEST_SHARD) == 1.0
            assert tracing.get_gauge(
                tracing.MESH_SHARD_TIME_MAX) == pytest.approx(0.5)
            assert tracing.get_counter("serving.mesh.dispatches") == 1.0
            assert stats["shard_skew"] == pytest.approx(0.4)
        finally:
            tracing.reset_spans()
            tracing.reset_gauges("serving.mesh.")
            tracing.reset_counters("serving.mesh.")

    def test_chrome_trace_trace_id_filter(self):
        tracing.reset_spans()
        try:
            t1, t2 = tracing.new_trace_id(), tracing.new_trace_id()
            tracing.record_span("a", 1.0, 2.0, trace_ids=(t1,))
            tracing.record_span("b", 1.0, 2.0, trace_ids=(t2,))
            tracing.record_span("both", 2.0, 3.0, trace_ids=(t1, t2))
            rec = tracing.span_recorder()
            names = {e["name"]
                     for e in rec.to_chrome_trace(
                         trace_id=t1)["traceEvents"]}
            assert names == {"a", "both"}
            # unknown id: empty but VALID trace, not an error
            empty = rec.to_chrome_trace(trace_id=10**9)
            assert empty["traceEvents"] == []
            # the unfiltered export is unchanged
            assert len(rec.to_chrome_trace()["traceEvents"]) == 3
        finally:
            tracing.reset_spans()


class TestSpanRecorderConcurrentOverflow:
    """PR 8 satellite: the ring's overwrite accounting stays exact
    with MULTIPLE recorders overflowing under concurrent writers —
    recorders share nothing (each has its own lock, deque, and drop
    counter), so parallel flight recorders (per-test rings next to the
    process ring) cannot cross-pollute each other's story."""

    def test_concurrent_recorders_exact_drop_accounting(self):
        recorders = [tracing.SpanRecorder(capacity=32)
                     for _ in range(3)]
        threads_per = 4
        spans_per = 500
        errs = []

        def writer(r, tid):
            try:
                for i in range(spans_per):
                    r.record(f"t{tid}.s{i}", float(i), float(i) + 0.1)
            except Exception as e:          # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=writer,
                                    args=(r, t), daemon=True)
                   for r in recorders for t in range(threads_per)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        for r in recorders:
            # every record either resides in the ring or was counted
            # dropped — nothing vanishes silently
            assert len(r) == 32
            assert r.dropped == threads_per * spans_per - 32
            # the ring holds whole spans (no torn writes)
            for s in r.spans():
                assert s.end == pytest.approx(s.start + 0.1)

    def test_process_ring_isolated_from_local_recorders(self):
        tracing.reset_spans()
        local = tracing.SpanRecorder(capacity=2)
        for i in range(5):
            local.record(f"local{i}", float(i), float(i) + 1)
        assert tracing.span_recorder().dropped == 0
        tracing.record_span("process.one", 0.0, 1.0)
        assert local.dropped == 3
        assert len(tracing.span_recorder()) == 1


class TestGraftgaugeReducers:
    """graftgauge (PR 8): the pure index-health / probe-frequency /
    drift reducers — host-array functions whose every output a
    scripted test pins exactly."""

    def test_index_health_exact(self):
        sizes = [0, 10, 10, 10, 10, 40, 0, 10]
        h = tracing.index_health(sizes, max_list_size=40, shards=2)
        assert h["n_lists"] == 8
        assert h["rows"] == 90
        assert h["max_list_size"] == 40
        assert h["mean_list_size"] == pytest.approx(90 / 8)
        assert h["dead_lists"] == 2
        assert h["overflow_lists"] == 1
        assert h["fill_fraction"] == pytest.approx(90 / (8 * 40))
        # shards: [0,10,10,10]=30 vs [10,40,0,10]=60 -> max/mean
        assert h["shard_imbalance"] == pytest.approx(60 / 45)
        assert 0.0 < h["gini"] < 1.0

    def test_index_health_gini_edges(self):
        even = tracing.index_health([5, 5, 5, 5])
        assert even["gini"] == pytest.approx(0.0)
        skewed = tracing.index_health([0, 0, 0, 20])
        # all rows in one of n lists -> (n-1)/n
        assert skewed["gini"] == pytest.approx(3 / 4)
        assert tracing.index_health([])["gini"] == 0.0
        assert tracing.index_health([0, 0])["rows"] == 0

    def test_probe_freq_stats_exact(self):
        # 100 lists: list 0 takes 90 probes, list 1 takes 6, 4 lists
        # take 1 each -> total 100
        counts = [0] * 100
        counts[0] = 90
        counts[1] = 6
        for lid in (10, 20, 30, 40):
            counts[lid] = 1
        s = tracing.probe_freq_stats(counts, top_n=3)
        assert s["total"] == 100
        assert s["probed_fraction"] == pytest.approx(6 / 100)
        # hottest 1% (1 list) absorbs 90%; hottest 10% everything
        assert s["coverage_p01"] == pytest.approx(0.90)
        assert s["coverage_p10"] == pytest.approx(1.0)
        assert s["top"] == [(0, 90), (1, 6), (10, 1)]

    def test_probe_freq_stats_empty(self):
        s = tracing.probe_freq_stats([0, 0, 0])
        assert s["total"] == 0 and s["top"] == []
        assert s["coverage_p01"] == 0.0
        assert tracing.probe_freq_stats([])["n_lists"] == 0

    def test_js_divergence_properties(self):
        assert tracing.js_divergence([1, 2, 3], [1, 2, 3]) == (
            pytest.approx(0.0))
        assert tracing.js_divergence([2, 4, 6], [1, 2, 3]) == (
            pytest.approx(0.0))      # scale-invariant
        # disjoint support is maximal drift (base-2 JSD bound)
        assert tracing.js_divergence([1, 0], [0, 1]) == (
            pytest.approx(1.0))
        a, b = [5, 1, 1], [1, 1, 5]
        assert tracing.js_divergence(a, b) == pytest.approx(
            tracing.js_divergence(b, a))   # symmetric
        assert 0.0 < tracing.js_divergence(a, b) < 1.0
        # zero-mass edges
        assert tracing.js_divergence([0, 0], [0, 0]) == 0.0
        assert tracing.js_divergence([0, 0], [1, 1]) == 1.0
