"""graftroute (PR 20): fleet placement planning, content-aware
routing, shared-nothing scale-out.

The contracts under test:

- **Planner purity** — :func:`plan_fleet` is a pure function of
  (merged probe plane, headroom): same inputs ⇒ BYTE-identical
  routing table; input dict order never matters.
- **Bit-identity** — per engine, steered requests and f32-wire
  fan-out+merge return exactly a solo replica's answer; the bf16
  distance wire keeps ids exact int32 and holds a pinned recall
  floor ≥0.99 at fleet size 4.
- **Typed failover** — a replica dying during an in-flight request
  raises the typed :class:`ReplicaUnavailable`; the router retries
  the affected lists on survivors and the caller still gets the
  solo-identical answer.
- **Zero-recompile rebalance** — planner placement deltas execute
  through the existing ``apply_plan`` fixed-width donated swaps
  with zero backend compiles under live traffic
  (``xla.backend_compile_count``).

Everything runs in the device-free fleet harness (ManualClock,
deterministic hash engine) — no wall clocks, no RNG in any assert.
"""

import dataclasses
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from raft_tpu.core import tracing
from raft_tpu.core.executor import SearchExecutor
from raft_tpu.core.validation import RaftError
from raft_tpu.fleet import (
    FleetPlanConfig,
    FleetPlanner,
    QueryRouter,
    ReplicaUnavailable,
    RouterConfig,
    RoutingTable,
    make_fleet,
    merge_fanout,
    placement_deltas,
    plan_fleet,
    route_payload_model,
)
from raft_tpu.fleet import planner as planner_mod
from raft_tpu.fleet import router as router_mod
from raft_tpu.neighbors import ivf_flat, tiered
from raft_tpu.neighbors.tiered import TieredSearchParams, build_tiered
from raft_tpu.serving.harness import ManualClock


def reset_fleet_metrics():
    for prefix in ("fleet.route.", "fleet.plan."):
        tracing.reset_counters(prefix)
        tracing.reset_gauges(prefix)


def full_table(replicas, n_lists, version=1, owners_alternate=True,
               generations=()):
    """Every replica hot for every list (owners round-robin) — the
    all-covered steering scenario."""
    names = sorted(replicas)
    assigns = []
    for lid in range(n_lists):
        order = names[lid % len(names):] + names[:lid % len(names)] \
            if owners_alternate else names
        assigns.append(tuple(order))
    return RoutingTable(version=version, label="ivf:0",
                        assignments=tuple(assigns),
                        counts=tuple([1] * n_lists),
                        generations=tuple(generations))


class TestRoutingTable:
    def test_round_trip_and_canonical_bytes(self):
        t = full_table(["r0", "r1"], 8, generations=(("r0", 3),))
        doc = json.loads(t.to_bytes().decode())
        back = RoutingTable.from_json(doc)
        assert back == t
        assert back.to_bytes() == t.to_bytes()
        assert t.generation_of("r0") == 3
        assert t.generation_of("r1") is None

    def test_unknown_format_refused(self):
        with pytest.raises(RaftError, match="format"):
            RoutingTable.from_json({"format": "bogus/9"})

    def test_covering_and_owners(self):
        t = RoutingTable(
            version=1, label="ivf:0",
            assignments=(("r0", "r1"), ("r1",), ("r0",)),
            counts=(5, 3, 1))
        assert t.owners() == ("r0", "r1", "r0")
        assert t.covering([0]) == ("r0", "r1")
        assert t.covering([0, 1]) == ("r1",)
        assert t.covering([0, 1, 2]) == ()
        assert t.covering([0], healthy=lambda n: n == "r0") == ("r0",)
        assert t.hot_lists("r1").tolist() == [0, 1]

    def test_cold_owned_is_not_hot_and_never_covered(self):
        t = RoutingTable(
            version=1, label="ivf:0",
            assignments=(("r0",), ("r0",)), counts=(9, 1),
            cold_owned=(1,))
        assert t.hot_lists("r0").tolist() == [0]
        assert t.covering([1]) == ()
        assert t.owner(1) == "r0"  # fan-out still has an owner
        assert RoutingTable.from_json(t.to_json()) == t

    def test_diff(self):
        a = RoutingTable(version=1, label="ivf:0",
                         assignments=(("r0",), ("r0",), ("r1",)),
                         counts=(1, 1, 1))
        b = RoutingTable(version=2, label="ivf:0",
                         assignments=(("r0",), ("r1",), ("r1",)),
                         counts=(1, 1, 1))
        assert b.diff(a) == {"r0": {"gain": [], "lose": [1]},
                             "r1": {"gain": [1], "lose": []}}
        assert b.diff(None)["r1"] == {"gain": [1, 2], "lose": []}


class TestPlanner:
    def headroom(self, n=4, room=1e6):
        return {f"r{i}": room for i in range(n)}

    def test_pure_and_byte_identical(self):
        counts = (np.arange(32)[::-1] ** 3).astype(np.int64)
        cfg = FleetPlanConfig(fallback_slots=12)
        a = plan_fleet(counts, self.headroom(), label="ivf:0",
                       version=5, config=cfg)
        # same inputs, different dict insertion order
        rev = dict(reversed(list(self.headroom().items())))
        b = plan_fleet(list(counts), rev, label="ivf:0",
                       version=5, config=cfg)
        assert a.to_bytes() == b.to_bytes()

    def test_long_tail_owned_exactly_once(self):
        counts = np.ones(32, np.int64)
        t = plan_fleet(counts, self.headroom(),
                       config=FleetPlanConfig(fallback_slots=8))
        assert all(len(names) == 1 for names in t.assignments)
        assert t.replicated_lists() == 0
        # ownership balances over the fleet
        sizes = [t.hot_lists(f"r{i}").size for i in range(4)]
        assert sizes == [8, 8, 8, 8]

    def test_hot_lists_replicate_by_traffic(self):
        counts = np.ones(32, np.int64)
        counts[3] = 10_000  # way past hot_share_ratio x uniform
        t = plan_fleet(counts, self.headroom(),
                       config=FleetPlanConfig(fallback_slots=16))
        assert len(t.assignments[3]) == 4  # capped at fleet size
        assert t.replicated_lists() == 1
        tail = [lid for lid in range(32) if lid != 3]
        assert all(len(t.assignments[l]) == 1 for l in tail)

    def test_headroom_caps_capacity(self):
        counts = np.arange(16, 0, -1).astype(np.int64)
        # r1 reports half the headroom -> half the hot slots
        t = plan_fleet(counts, {"r0": 8e6, "r1": 4e6},
                       config=FleetPlanConfig(list_bytes=10 ** 6,
                                              safety_fraction=0.0))
        assert t.hot_lists("r0").size == 8
        assert t.hot_lists("r1").size == 4
        # capacity exhausted -> the 4 coldest lists are cold-owned,
        # still owned exactly once
        assert len(t.cold_owned) == 4
        assert all(len(t.assignments[l]) == 1 for l in t.cold_owned)

    def test_unreported_headroom_falls_back(self):
        counts = np.ones(8, np.int64)
        t = plan_fleet(counts, {"r0": None, "r1": None},
                       config=FleetPlanConfig(list_bytes=10 ** 6,
                                              fallback_slots=4))
        assert t.hot_lists("r0").size + t.hot_lists("r1").size == 8

    def test_placement_deltas_pair_and_stage(self):
        counts = np.zeros(8, np.int64)
        counts[[4, 5, 6]] = (30, 20, 10)
        t = plan_fleet(counts, {"r0": None},
                       config=FleetPlanConfig(fallback_slots=3))
        assert t.hot_lists("r0").tolist() == [4, 5, 6]
        deltas = placement_deltas(
            t, {"r0": [0, 1, 4]}, max_swaps=2)
        d = deltas["r0"]
        # gains hottest-first (5 before 6), losses coldest-first,
        # pairs truncated to max_swaps, stage carries the full gain
        assert d.promotions == (5, 6)
        assert d.demotions == (0, 1)
        assert d.stage == (5, 6)
        assert d.width == 2
        one = placement_deltas(t, {"r0": [0, 1, 4]}, max_swaps=1)
        assert one["r0"].promotions == (5,)
        assert one["r0"].stage == (5, 6)

    def test_planner_versions_only_on_change(self):
        from tests.test_federation import fixture_aggregator

        reset_fleet_metrics()
        agg = fixture_aggregator()
        agg.scrape()
        p = FleetPlanner(agg, label="ivf:0",
                         config=FleetPlanConfig(fallback_slots=4))
        t1 = p.plan()
        assert t1.version == 1
        t2 = p.plan()
        assert t2.version == 1  # steady fleet, no bump
        assert t2.to_bytes() == t1.to_bytes()
        assert tracing.get_counter(planner_mod.PLAN_BUILDS) == 2
        assert tracing.get_counter(planner_mod.PLAN_CHANGED) == 1
        # typed accessors, not dict parsing: the plane really is the
        # fixture sum (r0: 50/10 + r1 + r2 contributions)
        plane = agg.merged_probe_plane("ivf:0")
        assert sum(plane.counts) == sum(t1.counts)

    def test_plan_generations_pin(self):
        counts = np.ones(4, np.int64)
        t = plan_fleet(counts, {"r0": None}, generations={"r0": 7})
        assert t.generation_of("r0") == 7


class TestMergeWire:
    def test_f32_merge_of_disjoint_parts_is_exact(self):
        h = make_fleet(1)
        q = h.make_queries(6)
        lids = h.resolve_probes(q)
        ref_d, ref_i = h.solo(q, 10)
        half = len(lids) // 2
        parts = [h.executor.scan_lists(q, lids[:half], 10),
                 h.executor.scan_lists(q, lids[half:], 10)]
        d, i = merge_fanout(parts, 10, wire_dtype="f32")
        assert np.array_equal(np.asarray(d), ref_d)
        assert np.array_equal(np.asarray(i), ref_i)

    def test_payload_model_accounting(self):
        f32 = route_payload_model(64, 10, 4, "f32")
        bf16 = route_payload_model(64, 10, 4, "bf16")
        assert f32["merge_bytes"] == 4 * 64 * 10 * 8
        assert bf16["merge_bytes"] == 4 * 64 * 10 * 6
        assert bf16["per_leg_bytes"] == 64 * 10 * 6
        assert f32["wire_dtype"] == "f32"
        with pytest.raises(RaftError, match="wire_dtype"):
            route_payload_model(1, 1, 1, "f16")

    def test_bf16_recall_floor_at_four_replicas(self):
        h = make_fleet(4)
        t = plan_fleet(np.ones(h.executor.n_lists, np.int64),
                       {n: None for n in h.replicas}, label="ivf:0",
                       version=1)
        r = QueryRouter(h.replicas, resolve_probes=h.resolve_probes,
                        clock=h.clock,
                        config=RouterConfig(merge_wire_dtype="bf16"))
        assert r.apply_table(t)
        hits = total = 0
        for start in range(0, 512, 16):
            q = h.make_queries(16, start)
            ref_d, ref_i = h.solo(q, 10)
            d, i, dec = r.route(q, 10)
            assert dec.mode == "fanout"
            # ids stay exact int32 whatever the distance wire
            assert np.asarray(i).dtype == np.int32
            for row in range(q.shape[0]):
                hits += len(set(ref_i[row].tolist())
                            & set(np.asarray(i)[row].tolist()))
                total += 10
        assert hits / total >= 0.99


class TestRouter:
    def setup_method(self):
        reset_fleet_metrics()

    def test_steered_bit_identical_to_solo(self):
        h = make_fleet(3)
        r = QueryRouter(h.replicas, resolve_probes=h.resolve_probes,
                        clock=h.clock)
        assert r.apply_table(
            full_table(h.replicas, h.executor.n_lists))
        for start in (0, 40, 90):
            q = h.make_queries(8, start)
            ref_d, ref_i = h.solo(q, 10)
            d, i, dec = r.route(q, 10)
            assert dec.mode == "steer"
            assert np.array_equal(np.asarray(d), ref_d)
            assert np.array_equal(np.asarray(i), ref_i)
        # steer load-balances deterministically over coverage
        seen = {r.route(h.make_queries(4), 10)[2].replica
                for _ in range(3)}
        assert len(seen) == 3

    def test_fanout_f32_bit_identical_to_solo(self):
        h = make_fleet(4)
        t = plan_fleet(np.ones(h.executor.n_lists, np.int64),
                       {n: None for n in h.replicas}, label="ivf:0",
                       version=1)
        r = QueryRouter(h.replicas, resolve_probes=h.resolve_probes,
                        clock=h.clock)
        assert r.apply_table(t)
        for start in (0, 16, 200):
            for rep in h.replicas.values():
                rep.calls.clear()
            q = h.make_queries(12, start)
            ref_d, ref_i = h.solo(q, 10)
            d, i, dec = r.route(q, 10)
            assert dec.mode == "fanout"
            assert dec.fallback == "uncovered"
            assert dec.legs > 1
            assert np.array_equal(np.asarray(d), ref_d)
            assert np.array_equal(np.asarray(i), ref_i)
            # each probed list scanned exactly once across the legs
            scanned = []
            for rep in h.replicas.values():
                for _, lists in rep.calls:
                    scanned.extend(lists)
            assert len(scanned) == len(set(scanned))

    def test_single_replica_passthrough(self):
        h = make_fleet(1)
        r = QueryRouter(h.replicas, resolve_probes=h.resolve_probes,
                        clock=h.clock)
        q = h.make_queries(5)
        ref_d, ref_i = h.solo(q, 10)
        d, i, dec = r.route(q, 10)  # no table needed, no fan-out
        assert dec.mode == "passthrough"
        assert dec.replica == "r0"
        assert np.array_equal(np.asarray(d), ref_d)
        assert np.array_equal(np.asarray(i), ref_i)

    def test_no_table_fans_out_bit_identical(self):
        h = make_fleet(3)
        r = QueryRouter(h.replicas, resolve_probes=h.resolve_probes,
                        clock=h.clock)
        q = h.make_queries(6)
        ref_d, ref_i = h.solo(q, 10)
        d, i, dec = r.route(q, 10)
        assert dec.mode == "fanout" and dec.fallback == "no_table"
        assert np.array_equal(np.asarray(d), ref_d)
        assert np.array_equal(np.asarray(i), ref_i)

    def test_generation_skew_falls_back_bit_identical(self):
        h = make_fleet(2)
        # the table pins generations; r0 then rebalances (gen bump)
        t = full_table(h.replicas, h.executor.n_lists,
                       generations=(("r0", 0), ("r1", 0)))
        r = QueryRouter(h.replicas, resolve_probes=h.resolve_probes,
                        clock=h.clock)
        assert r.apply_table(t)
        h.replicas["r0"].generation = 1
        h.replicas["r1"].generation = 1
        q = h.make_queries(6)
        ref_d, ref_i = h.solo(q, 10)
        c0 = tracing.get_counter(router_mod.ROUTE_SKEW)
        d, i, dec = r.route(q, 10)
        assert dec.mode == "fanout"
        assert dec.fallback == "generation_skew"
        assert tracing.get_counter(router_mod.ROUTE_SKEW) == c0 + 1
        assert np.array_equal(np.asarray(d), ref_d)
        assert np.array_equal(np.asarray(i), ref_i)
        # matching generations steer again
        h.replicas["r0"].generation = 0
        h.replicas["r1"].generation = 0
        assert r.route(q, 10)[2].mode == "steer"

    def test_inflight_death_retries_on_survivor(self):
        h = make_fleet(2)
        r = QueryRouter(h.replicas, resolve_probes=h.resolve_probes,
                        clock=h.clock)
        assert r.apply_table(
            full_table(h.replicas, h.executor.n_lists))
        q = h.make_queries(6)
        ref_d, ref_i = h.solo(q, 10)
        first = r.route(q, 10)[2].replica  # deterministic pick
        other = "r1" if first == "r0" else "r0"
        h.replicas[other].fail_results(1)  # dies mid-flight next
        c0 = tracing.get_counter(router_mod.ROUTE_RETRIES)
        d, i, dec = r.route(q, 10)
        assert dec.mode == "fanout" and dec.fallback == "retry"
        assert tracing.get_counter(router_mod.ROUTE_RETRIES) == c0 + 1
        assert np.array_equal(np.asarray(d), ref_d)
        assert np.array_equal(np.asarray(i), ref_i)
        # the dead replica stays avoided until a fresh table arrives
        assert r.route(q, 10)[2].replica == first

    def test_fanout_leg_death_retries_on_survivor(self):
        h = make_fleet(3)
        t = plan_fleet(np.ones(h.executor.n_lists, np.int64),
                       {n: None for n in h.replicas}, label="ivf:0",
                       version=1)
        r = QueryRouter(h.replicas, resolve_probes=h.resolve_probes,
                        clock=h.clock)
        assert r.apply_table(t)
        q = h.make_queries(6)
        ref_d, ref_i = h.solo(q, 10)
        h.replicas["r1"].fail_results(1)
        d, i, dec = r.route(q, 10)
        assert dec.mode == "fanout"
        assert np.array_equal(np.asarray(d), ref_d)
        assert np.array_equal(np.asarray(i), ref_i)

    def test_whole_fleet_dead_is_typed(self):
        h = make_fleet(2)
        r = QueryRouter(h.replicas, resolve_probes=h.resolve_probes,
                        clock=h.clock)
        for rep in h.replicas.values():
            rep.kill()
        with pytest.raises(ReplicaUnavailable):
            r.route(h.make_queries(2), 5)

    def test_health_gate_excludes_stale_replicas(self):
        h = make_fleet(2)
        r = QueryRouter(h.replicas, resolve_probes=h.resolve_probes,
                        clock=h.clock,
                        health=lambda: {"r0": False})
        assert r.apply_table(
            full_table(h.replicas, h.executor.n_lists))
        q = h.make_queries(4)
        for _ in range(3):  # never steered to the unhealthy replica
            d, i, dec = r.route(q, 10)
            assert dec.replica == "r1"
        assert h.replicas["r0"].calls == []

    def test_stale_table_refused(self):
        h = make_fleet(2)
        r = QueryRouter(h.replicas, resolve_probes=h.resolve_probes,
                        clock=h.clock)
        t5 = full_table(h.replicas, h.executor.n_lists, version=5)
        assert r.apply_table(t5)
        assert not r.apply_table(
            full_table(h.replicas, h.executor.n_lists, version=5))
        assert not r.apply_table(
            full_table(h.replicas, h.executor.n_lists, version=4))
        assert r.table.version == 5
        assert r.apply_table(
            full_table(h.replicas, h.executor.n_lists, version=6))

    def test_gauges_publish(self):
        reset_fleet_metrics()
        tracing.reset_gauges("fleet.route.")
        h = make_fleet(2)
        r = QueryRouter(h.replicas, resolve_probes=h.resolve_probes,
                        clock=h.clock)
        assert r.apply_table(
            full_table(h.replicas, h.executor.n_lists, version=3))
        h.clock.advance(2.5)
        r.route(h.make_queries(4), 10)
        r.publish_gauges()
        g = tracing.gauges()
        assert g["fleet.route.coverage_rate"] == 1.0
        assert g["fleet.route.fanout_fraction"] == 0.0
        assert g["fleet.route.table_version"] == 3.0
        assert g["fleet.route.table_age_s"] == 2.5
        assert g["fleet.route.replica.r0.steered"] \
            + g["fleet.route.replica.r1.steered"] == 1.0


class TestRouteExporter:
    def test_route_json_push_and_metrics(self):
        from raft_tpu.serving import MetricsExporter

        h = make_fleet(2)
        r = QueryRouter(h.replicas, resolve_probes=h.resolve_probes,
                        clock=h.clock)
        exporter = MetricsExporter(route=r)
        port = exporter.start()
        base = f"http://127.0.0.1:{port}"
        try:
            # no table yet -> 404, like every unarmed endpoint
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(base + "/route.json")
            assert e.value.code == 404
            t = full_table(h.replicas, h.executor.n_lists, version=2)
            req = urllib.request.Request(
                base + "/push?route=1",
                data=t.to_bytes(), method="POST")
            with urllib.request.urlopen(req) as resp:
                assert json.load(resp) == {"applied": True}
            doc = json.load(
                urllib.request.urlopen(base + "/route.json"))
            assert doc["version"] == 2
            assert RoutingTable.from_json(doc) == t
            # duplicate push is stale -> 409 (idempotent channel)
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(urllib.request.Request(
                    base + "/push?route=1", data=t.to_bytes(),
                    method="POST"))
            assert e.value.code == 409
            # garbage -> 400, typed
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(urllib.request.Request(
                    base + "/push?route=1", data=b'{"format":"x"}',
                    method="POST"))
            assert e.value.code == 400
            r.route(h.make_queries(4), 10)
            text = urllib.request.urlopen(
                base + "/metrics").read().decode()
            assert "# HELP fleet_route_coverage_rate" in text
            assert 'fleet_route_replica_steered{replica="r0"}' \
                in text or \
                'fleet_route_replica_steered{replica="r1"}' in text
        finally:
            exporter.close()

    def test_route_push_without_router_404(self):
        from raft_tpu.serving import MetricsExporter

        exporter = MetricsExporter()
        port = exporter.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}/push?route=1",
                    data=b"{}", method="POST"))
            assert e.value.code == 404
        finally:
            exporter.close()


class TestRebalanceZeroRecompile:
    """Planner deltas ride the existing fixed-width donated swap
    contract: rebalancing a live tiered replica adds ZERO backend
    compiles under traffic, and serving results stay bit-identical
    through the move."""

    def test_deltas_apply_with_zero_compiles(self):
        rng = np.random.default_rng(21)
        x = rng.standard_normal((2048, 32)).astype(np.float32)
        q = rng.standard_normal((16, 32)).astype(np.float32)
        flat = ivf_flat.build(
            None, ivf_flat.IvfFlatIndexParams(n_lists=32,
                                              kmeans_n_iters=6), x)
        t = build_tiered(flat, hot_fraction=0.5)
        width = 4
        cfg = FleetPlanConfig(fallback_slots=int(t.hot_lists.size),
                              max_swaps=width)
        p = TieredSearchParams(n_probes=8)
        ex = SearchExecutor()
        ex.warmup(t, buckets=(16,), k=10, params=p)
        d_ref, i_ref = np.asarray(ex.search(t, q, 10, params=p)[0]), \
            np.asarray(ex.search(t, q, 10, params=p)[1])

        def epoch(counts):
            table = plan_fleet(counts, {"r0": None}, label="ivf:0",
                               version=1, config=cfg)
            delta = placement_deltas(
                table, {"r0": t.hot_lists.tolist()},
                max_swaps=width)["r0"]
            return tiered.apply_plan(
                t, list(delta.promotions), list(delta.demotions),
                width=width, executor=ex)

        # warm the one fixed-width swap program, then demand silence
        counts = np.zeros(32, np.int64)
        counts[np.asarray(t.cold_lists[:2])] = (100, 90)
        epoch(counts)
        ex.search(t, q, 10, params=p)
        tracing.install_xla_compile_listener()
        c0 = tracing.counters().get(tracing.XLA_COMPILE_COUNT, 0)
        for hot_lid in (3, 11, 27):
            counts = np.zeros(32, np.int64)
            counts[hot_lid] = 1000
            ex.search(t, q, 10, params=p)
            epoch(counts)
            d2, i2 = ex.search(t, q, 10, params=p)
            # the planner's target went hot on the replica
            assert hot_lid in t.hot_lists
        c1 = tracing.counters().get(tracing.XLA_COMPILE_COUNT, 0)
        assert c1 - c0 == 0, "fleet rebalance must not recompile"
        assert np.array_equal(np.asarray(d2), d_ref)
        assert np.array_equal(np.asarray(i2), i_ref)

    def test_stage_hints_feed_prefetcher_shape(self):
        """The delta's stage hint is promotions-compatible: ordered
        hottest-first, a superset of the paired promotions."""
        counts = np.zeros(16, np.int64)
        counts[[8, 9, 10, 11]] = (40, 30, 20, 10)
        table = plan_fleet(counts, {"r0": None},
                           config=FleetPlanConfig(fallback_slots=4))
        d = placement_deltas(table, {"r0": [0, 1, 2, 3]},
                             max_swaps=2)["r0"]
        assert d.stage[:len(d.promotions)] == d.promotions
        assert set(d.promotions) <= set(d.stage)
        assert d.stage == (8, 9, 10, 11)


class TestPlannerRouterLoop:
    """Planner -> table -> router, converging under skewed traffic:
    covered hot traffic steers, the tail fans out, and a re-plan
    under the same signals is a no-op (stable version)."""

    def test_skewed_traffic_steers_after_replan(self):
        h = make_fleet(2, n_probes=2)
        nl = h.executor.n_lists
        # traffic concentrated on the lists queries 0..1 probe
        hot = sorted(h.resolve_probes(h.make_queries(2)))
        counts = np.ones(nl, np.int64)
        counts[hot] = 50_000
        t = plan_fleet(counts, {n: None for n in h.replicas},
                       label="ivf:0", version=1,
                       config=FleetPlanConfig(fallback_slots=nl))
        # hot lists replicated fleet-wide -> hot queries covered
        r = QueryRouter(h.replicas, resolve_probes=h.resolve_probes,
                        clock=h.clock)
        assert r.apply_table(t)
        q_hot = h.make_queries(2)
        ref = h.solo(q_hot, 10)
        d, i, dec = r.route(q_hot, 10)
        assert dec.mode == "steer"
        assert np.array_equal(np.asarray(d), ref[0])
        assert np.array_equal(np.asarray(i), ref[1])
        # tail traffic fans out, still exact
        q_tail = h.make_queries(4, start=9)
        ref = h.solo(q_tail, 10)
        d, i, dec = r.route(q_tail, 10)
        assert np.array_equal(np.asarray(d), ref[0])
        assert np.array_equal(np.asarray(i), ref[1])
        # same signals -> byte-identical re-plan (no version bump
        # needed; stale push refused)
        t2 = plan_fleet(counts, {n: None for n in h.replicas},
                        label="ivf:0", version=1,
                        config=FleetPlanConfig(fallback_slots=nl))
        assert t2.to_bytes() == t.to_bytes()
        assert not r.apply_table(t2)


class TestTypedAccessors:
    """Satellite: the planner-facing FleetAggregator surface."""

    def test_merged_probe_plane_matches_fixture_sum(self):
        from tests.test_federation import fixture_aggregator, \
            load_replica

        reset_fleet_metrics()
        agg = fixture_aggregator()
        agg.scrape()
        view = agg.merged_probe_plane("ivf:0")
        want = None
        for name in ("r0", "r1", "r2"):
            plane = load_replica(name)["federation"][
                "probe_planes"].get("ivf:0")
            if plane is None:
                continue
            want = plane if want is None else \
                [a + b for a, b in zip(want, plane)]
        assert list(view.counts) == want
        assert view.stale_replicas == ()
        assert agg.probe_plane_labels() == ("ivf:0",)
        with pytest.raises(LookupError):
            agg.merged_probe_plane("nope:0")

    def test_staleness_metadata(self):
        from tests.test_federation import fixture_aggregator

        reset_fleet_metrics()
        clock = ManualClock()
        agg = fixture_aggregator(clock=clock)
        agg.scrape()
        assert all(h.healthy for h in agg.replica_headroom())
        clock.advance(agg.config.staleness_s + 1.0)
        views = agg.replica_headroom()
        assert all(not h.healthy for h in views)
        # stale -> no headroom evidence, but age is reported
        assert all(h.headroom_bytes is None for h in views)
        assert all(h.age_s > agg.config.staleness_s for h in views)
        # the plane keeps stale last-known contributions, flagged
        plane = agg.merged_probe_plane("ivf:0")
        assert set(plane.stale_replicas) == set(plane.replicas)
        assert agg.replica_health() == {
            "r0": False, "r1": False, "r2": False}

    def test_headroom_values_are_typed(self):
        from tests.test_federation import fixture_aggregator

        reset_fleet_metrics()
        agg = fixture_aggregator()
        agg.scrape()
        by_name = {h.name: h for h in agg.replica_headroom()}
        assert by_name["r0"].headroom_bytes == 2_000_000.0
        assert by_name["r0"].push is False
        assert sorted(by_name) == ["r0", "r1", "r2"]


class TestFleetHarness:
    def test_engine_is_deterministic_and_tie_ranked(self):
        h = make_fleet(1)
        q = h.make_queries(4)
        a = h.executor.scan_lists(q, [0, 1, 2], 6)
        b = h.executor.scan_lists(q, [0, 1, 2], 6)
        assert np.array_equal(a[0], b[0])
        assert np.array_equal(a[1], b[1])
        # distances ascend, ids valid, padding contract
        d, i = h.executor.scan_lists(q, [0], 10)
        assert (np.diff(d[:, :8], axis=1) >= 0).all()
        assert (i[:, 8:] == -1).all() and np.isinf(d[:, 8:]).all()

    def test_replica_scripting(self):
        h = make_fleet(2)
        rep = h.replicas["r0"]
        handle = rep.submit(h.make_queries(2), 5, lists=(0, 1))
        rep.kill()
        with pytest.raises(ReplicaUnavailable):
            handle.result()  # lazy: death lands on the in-flight leg
        rep.revive()
        d, i = rep.submit(h.make_queries(2), 5, lists=(0, 1)).result()
        assert d.shape == (2, 5)

    def test_router_rejects_empty_fleet(self):
        with pytest.raises(RaftError):
            QueryRouter({}, resolve_probes=lambda q: (0,))

    def test_decision_is_frozen_evidence(self):
        h = make_fleet(2)
        r = QueryRouter(h.replicas, resolve_probes=h.resolve_probes,
                        clock=h.clock)
        dec = r.route(h.make_queries(2), 5)[2]
        with pytest.raises(dataclasses.FrozenInstanceError):
            dec.mode = "steer"
