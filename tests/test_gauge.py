"""graftgauge tests (PR 8) — the index-health half of observability.

- Online recall estimation: the shadow-query sampler's windowed
  estimate is CALIBRATED against exact recall on a known corpus (the
  acceptance criterion: within ±0.02 while all shadows complete), and
  shadow work is the admission ladder's FIRST casualty under injected
  overload — live traffic never waits on a shadow.
- Query-drift detection: deterministic under a fixed shadow-sample
  seed (two identical runs → bit-equal score sequences), crafted
  traffic shifts drive the JS score up, quiet scrapes hold it.
- IndexGauge + exporter: one scrape refreshes health / probe-freq /
  recall / drift, ``/index.json`` serves the structured view, 404
  when unattached.

Everything deterministic: manual clock, seeded sampler, threadless
batcher (``start=False`` + ``pump()``).
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from raft_tpu import SearchExecutor
from raft_tpu.core import tracing
from raft_tpu.neighbors import brute_force, ivf_flat
from raft_tpu.serving import (
    BatcherConfig,
    DriftDetector,
    DynamicBatcher,
    IndexGauge,
    LoadShed,
    MetricsExporter,
    RecallWindow,
    ShadowConfig,
    ShadowSampler,
)
from raft_tpu.serving import metrics
from raft_tpu.serving.gauge import wilson_interval
from raft_tpu.serving.harness import FakeExecutor, ManualClock


@pytest.fixture(scope="module")
def corpus():
    """Calibration corpus: big enough that n_probes=2/8 visibly
    misses, with a brute-force twin as ground truth."""
    rng = np.random.default_rng(42)
    x = rng.standard_normal((2000, 24)).astype(np.float32)
    q = rng.standard_normal((48, 24)).astype(np.float32)
    return {
        "x": x, "q": q,
        "ivf": ivf_flat.build(
            None, ivf_flat.IvfFlatIndexParams(n_lists=16), x),
        "bf": brute_force.build(None, x),
    }


def exact_recall(ivf_index, bf_index, q, k, params):
    """Host-side ground truth: recall@k of the ANN result against the
    brute-force ids over the SAME query block."""
    _, ann = ivf_flat.search(None, params, ivf_index, q, k)
    _, truth = brute_force.search(None, bf_index, q, k)
    ann, truth = np.asarray(ann), np.asarray(truth)
    hits = sum(int(np.isin(ann[r], truth[r][truth[r] >= 0]).sum())
               for r in range(ann.shape[0]))
    return hits / (ann.shape[0] * k)


class TestWilsonInterval:
    def test_edges(self):
        assert wilson_interval(0, 0) == (0.0, 1.0)
        lo, hi = wilson_interval(10, 10)
        assert lo < 1.0 and hi == 1.0
        lo, hi = wilson_interval(0, 10)
        assert lo == 0.0 and hi > 0.0

    def test_narrows_with_trials(self):
        lo1, hi1 = wilson_interval(8, 10)
        lo2, hi2 = wilson_interval(800, 1000)
        assert (hi2 - lo2) < (hi1 - lo1)
        assert lo2 < 0.8 < hi2


class TestRecallWindow:
    def test_estimate_and_slide(self):
        metrics.reset()
        w = RecallWindow(window_s=10.0)
        w.record(0.0, hits=9, trials=10)
        w.record(5.0, hits=5, trials=10)
        e = w.estimate(5.0)
        assert e["estimate"] == pytest.approx(14 / 20)
        assert e["pairs"] == 2
        assert tracing.get_gauge(tracing.RECALL_ESTIMATE) == (
            pytest.approx(14 / 20))
        # the first pair ages out of the window
        e = w.estimate(10.5)
        assert e["estimate"] == pytest.approx(5 / 10)
        assert e["pairs"] == 1
        # empty window is maximally uncertain, not confidently zero
        e = w.estimate(100.0)
        assert e["estimate"] == 0.0
        assert (e["ci_low"], e["ci_high"]) == (0.0, 1.0)


class TestShadowSampler:
    def _serve(self, corpus, fraction, seed, n_probes=2, k=10,
               rounds=6, rows=8):
        """Drive the live+shadow loop threadless; returns the sampler
        after all pairs resolve."""
        ex = SearchExecutor()
        clock = ManualClock()
        b = DynamicBatcher(
            ex, BatcherConfig(max_wait_s=0.0,
                              shed=LoadShed(background_priority=100)),
            clock=clock, start=False)
        sampler = ShadowSampler(
            b, corpus["bf"],
            ShadowConfig(fraction=fraction, seed=seed, priority=100,
                         timeout_s=None, window_s=1e9))
        p = ivf_flat.IvfFlatSearchParams(n_probes=n_probes)
        q = corpus["q"]
        for r in range(rounds):
            block = q[(r * rows) % 40:(r * rows) % 40 + rows]
            sampler.submit(corpus["ivf"], block, k, params=p)
            while b.pump():
                pass
        sampler.pump()
        b.close()
        return sampler

    def test_estimate_calibrated_within_002(self, corpus):
        """Acceptance: with every shadow completing, the windowed
        estimate lands within ±0.02 of exact recall on the SAME
        queries (here it is exactly equal — same pairs, same
        arithmetic — so the band is pure safety margin)."""
        metrics.reset()
        p = ivf_flat.IvfFlatSearchParams(n_probes=2)
        sampler = self._serve(corpus, fraction=1.0, seed=3)
        e = sampler.window.estimate(sampler._clock.now())
        assert e["pairs"] == 6
        truth = exact_recall(corpus["ivf"], corpus["bf"],
                             corpus["q"][:40], 10, p)
        assert 0.2 < truth < 0.999      # the corpus really misses
        assert abs(e["estimate"] - truth) <= 0.02
        assert e["ci_low"] <= e["estimate"] <= e["ci_high"]
        assert tracing.get_counter(
            "index.recall.shadow_completed") == 6

    def test_sampled_subset_is_seed_deterministic(self, corpus):
        metrics.reset()
        s1 = self._serve(corpus, fraction=0.5, seed=11)
        n1 = tracing.get_counter("index.recall.shadow_submitted")
        e1 = s1.window.estimate(s1._clock.now())
        metrics.reset()
        s2 = self._serve(corpus, fraction=0.5, seed=11)
        n2 = tracing.get_counter("index.recall.shadow_submitted")
        e2 = s2.window.estimate(s2._clock.now())
        assert n1 == n2 > 0
        assert e1 == e2

    def test_shadow_sheds_first_under_overload(self):
        """Injected overload: occupancy >= background_reject_at makes
        the queue reject SHADOW submissions with typed Overloaded
        while the live path still admits — the recall estimator
        degrades (fewer samples) before any live request queues behind
        shadow work."""
        metrics.reset()
        ex = FakeExecutor()
        clock = ManualClock()
        b = DynamicBatcher(
            ex,
            BatcherConfig(max_wait_s=1.0, capacity=8,
                          shed=LoadShed(background_priority=100,
                                        background_reject_at=0.5)),
            clock=clock, start=False)

        class _Idx:
            pass

        live_idx, exact_idx = _Idx(), _Idx()
        sampler = ShadowSampler(b, exact_idx,
                                ShadowConfig(fraction=1.0, seed=0,
                                             priority=100))
        blk = np.zeros((1, 4), np.float32)
        # fill to occupancy 0.5 without pumping
        for _ in range(4):
            b.submit(live_idx, blk, 3)
        h = sampler.submit(live_idx, blk, 3)
        # live admitted (queue depth grew), shadow rejected + counted
        assert tracing.get_counter("index.recall.shadow_shed") == 1
        assert tracing.get_counter(
            "serving.admission.rejected_background") == 1
        assert tracing.get_counter(
            "index.recall.shadow_submitted") == 0
        clock.advance(1.0)
        while b.pump():
            pass
        assert h.result(timeout=0) is not None   # live unharmed
        b.close()

    def test_shadow_below_threshold_admits(self):
        metrics.reset()
        b = DynamicBatcher(
            FakeExecutor(),
            BatcherConfig(max_wait_s=1.0, capacity=8,
                          shed=LoadShed(background_priority=100,
                                        background_reject_at=0.5)),
            clock=ManualClock(), start=False)

        class _Idx:
            pass

        sampler = ShadowSampler(b, _Idx(),
                                ShadowConfig(fraction=1.0, seed=0,
                                             priority=100))
        sampler.submit(_Idx(), np.zeros((1, 4), np.float32), 3)
        assert tracing.get_counter(
            "index.recall.shadow_submitted") == 1
        assert tracing.get_counter("index.recall.shadow_shed") == 0
        b.close()


class TestDriftDetector:
    def test_score_rises_with_shifted_traffic_and_holds_quiet(self):
        baseline = np.full(16, 100.0)       # even build-time histogram
        det = DriftDetector(baseline, alpha=1.0, alert_threshold=0.3)
        assert det.score == 0.0 and not det.alert
        # live traffic matching the baseline: no drift
        cum = np.full(16, 5.0)
        assert det.update(cum) == pytest.approx(0.0)
        # traffic collapses onto 2 of 16 lists: strong drift
        cum2 = cum.copy()
        cum2[:2] += 500.0
        s = det.update(cum2)
        assert s > 0.3 and det.alert
        # a quiet scrape (no new probes) holds the score
        assert det.update(cum2) == s
        assert det.updates == 2

    def test_ewma_smooths_single_scrape_spike(self):
        baseline = np.full(8, 10.0)
        det = DriftDetector(baseline, alpha=0.2)
        even = np.full(8, 10.0)
        det.update(even)
        spike = even + np.eye(8)[0] * 1000.0
        s_smooth = det.update(spike)
        det2 = DriftDetector(baseline, alpha=1.0)
        det2.update(even)
        s_raw = det2.update(spike)
        assert 0.0 < s_smooth < s_raw

    def test_deterministic_sequence(self):
        rng = np.random.default_rng(5)
        baseline = rng.integers(1, 50, size=32)
        cums = np.cumsum(rng.integers(0, 9, size=(6, 32)), axis=0)
        runs = []
        for _ in range(2):
            det = DriftDetector(baseline)
            runs.append([det.update(c) for c in cums])
        assert runs[0] == runs[1]          # bit-equal, not approx


class TestIndexGauge:
    def test_publish_and_index_json(self, corpus):
        metrics.reset()
        ex = SearchExecutor(probe_accounting=True)
        clock = ManualClock()
        b = DynamicBatcher(ex, BatcherConfig(max_wait_s=0.0),
                           clock=clock, start=False)
        sampler = ShadowSampler(
            b, corpus["bf"], ShadowConfig(fraction=1.0, seed=1,
                                          timeout_s=None))
        p = ivf_flat.IvfFlatSearchParams(n_probes=4)
        sampler.submit(corpus["ivf"], corpus["q"][:8], 5, params=p)
        while b.pump():
            pass
        det = DriftDetector.from_index(corpus["ivf"])
        gauge = IndexGauge(executor=ex, indexes={"main": corpus["ivf"]},
                           sampler=sampler, drift={"main": det})
        with MetricsExporter(executor=ex, batcher=b,
                             index_gauge=gauge) as exp:
            body = json.loads(urllib.request.urlopen(
                exp.url("/index.json"), timeout=10).read())
            assert body["health"]["main"]["n_lists"] == 16
            assert body["health"]["main"]["rows"] == 2000
            assert body["recall"]["pairs"] == 1
            assert body["drift"]["main"]["updates"] == 1
            label = ex.probe_label(corpus["ivf"])
            assert body["probe_freq"][label]["total"] == 8 * 4
            # gauges landed for every surface
            assert tracing.get_gauge(
                "index.health.main.dead_lists") >= 0.0
            assert tracing.get_gauge(
                f"index.probe_freq.{label}.total") == 8 * 4
            assert tracing.gauges("index.drift.main.")
            # and the scrape exposes them as LABELED prom families
            text = urllib.request.urlopen(
                exp.url("/metrics"), timeout=10).read().decode()
            assert f'index_probe_freq_total{{index="{label}"}}' in text
            assert 'index_health_rows{index="main"} 2000' in text
            assert 'index_drift_score{index="main"}' in text
        b.close()

    def test_index_json_404_when_unattached(self):
        with MetricsExporter() as exp:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(exp.url("/index.json"),
                                       timeout=10)
            assert ei.value.code == 404

    def test_drift_pairs_with_live_plane_via_probe_label(self, corpus):
        """The detector watches the executor's REAL probe plane: live
        traffic matching the build distribution scores near zero;
        after the baseline is skewed away, the same traffic alerts."""
        metrics.reset()
        ex = SearchExecutor(probe_accounting=True)
        p = ivf_flat.IvfFlatSearchParams(n_probes=4)
        ex.search(corpus["ivf"], corpus["q"], 5, params=p)
        det = DriftDetector.from_index(corpus["ivf"],
                                       alert_threshold=0.5)
        gauge = IndexGauge(executor=ex,
                           indexes={"main": corpus["ivf"]},
                           drift={"main": det})
        gauge.publish()
        assert det.updates == 1
        low = det.score
        assert 0.0 <= low < 0.5
        skewed = np.zeros(16)
        skewed[0] = 1.0                      # everything in one list
        det2 = DriftDetector(skewed)
        gauge2 = IndexGauge(executor=ex,
                            indexes={"main": corpus["ivf"]},
                            drift={"main": det2})
        ex.search(corpus["ivf"], corpus["q"], 5, params=p)
        gauge2.publish()
        assert det2.score > low
        assert tracing.get_gauge(tracing.DRIFT_SCORE) == det2.score


class TestReviewHardening:
    """Regression tests for the PR 8 review findings."""

    def test_filtered_requests_are_never_shadowed(self, corpus):
        """Recall must compare ANN against exact truth over the SAME
        candidate set; the brute-force shadow leg has no filter
        support, so a filtered pair would score healthy traffic
        against the unfiltered truth and read permanently stale.
        Filtered submissions skip shadowing (counted) instead."""
        from raft_tpu.core.bitset import Bitset
        from raft_tpu.neighbors.filters import BitsetFilter

        metrics.reset()
        n = corpus["x"].shape[0]
        keep = np.zeros(n, bool)
        keep[: n // 2] = True              # exclude half the corpus
        filt = BitsetFilter(Bitset.from_mask(keep))
        ex = SearchExecutor()
        clock = ManualClock()
        b = DynamicBatcher(ex, BatcherConfig(max_wait_s=0.0),
                           clock=clock, start=False)
        sampler = ShadowSampler(
            b, corpus["bf"], ShadowConfig(fraction=1.0, seed=2,
                                          timeout_s=None))
        p = ivf_flat.IvfFlatSearchParams(n_probes=4)
        q = corpus["q"][:16]
        h = sampler.submit(corpus["ivf"], q, 10, params=p,
                           sample_filter=filt)
        while b.pump():
            pass
        # the live (filtered) leg served normally...
        _, ids = h.result(timeout=0)
        assert (np.asarray(ids) < n // 2).all()   # filter honored
        # ...but no pair formed: skipped, not mis-scored
        assert sampler.pump() == 0
        assert tracing.get_counter(
            "index.recall.shadow_skipped") == 1
        assert tracing.get_counter(
            "index.recall.shadow_submitted") == 0
        assert sampler.window.estimate(clock.now())["pairs"] == 0
        b.close()

    def test_live_failure_balances_shadow_ledger(self):
        """A pair whose LIVE leg was shed still resolves into the
        lifecycle ledger (dropped), so submitted == completed +
        shed-after-admission + dropped."""
        metrics.reset()
        clock = ManualClock()
        b = DynamicBatcher(FakeExecutor(),
                           BatcherConfig(max_wait_s=0.05),
                           clock=clock, start=False)

        class _Idx:
            pass

        sampler = ShadowSampler(b, _Idx(),
                                ShadowConfig(fraction=1.0, seed=0,
                                             timeout_s=None))
        # live expires in-queue; the (no-deadline) shadow completes
        sampler.submit(_Idx(), np.zeros((1, 4), np.float32), 3,
                       timeout_s=0.01)
        clock.advance(0.1)
        while b.pump():
            pass
        assert sampler.pump() == 0
        assert tracing.get_counter("index.recall.shadow_dropped") == 1
        submitted = tracing.get_counter("index.recall.shadow_submitted")
        resolved = (tracing.get_counter("index.recall.shadow_completed")
                    + tracing.get_counter("index.recall.shadow_shed")
                    + tracing.get_counter("index.recall.shadow_dropped"))
        assert submitted == resolved == 1
        b.close()

    def test_probe_window_reset_keeps_totals_monotone(self, corpus):
        """Each scrape claims its window (device plane resets to
        zero; totals accumulate host-side in int64) — repeated quiet
        scrapes change nothing and never double-count."""
        metrics.reset()
        ex = SearchExecutor(probe_accounting=True)
        p = ivf_flat.IvfFlatSearchParams(n_probes=4)
        ex.search(corpus["ivf"], corpus["q"][:16], 5, params=p)
        (t1,) = ex.probe_frequencies().values()
        assert t1.dtype == np.int64 and t1.sum() == 16 * 4
        assert tracing.get_counter(
            "index.probe_freq.accounted") == 16 * 4
        # quiet scrapes: totals identical, accounted unmoved
        (t2,) = ex.probe_frequencies().values()
        np.testing.assert_array_equal(t1, t2)
        assert tracing.get_counter(
            "index.probe_freq.accounted") == 16 * 4
        # more traffic accumulates on top
        ex.search(corpus["ivf"], corpus["q"][:16], 5, params=p)
        (t3,) = ex.probe_frequencies().values()
        assert t3.sum() == 2 * 16 * 4
        np.testing.assert_array_equal(t3, 2 * t1)

    def test_dead_index_plane_evicted(self):
        """A garbage-collected index's plane (and label) must not be
        inherited by a new index reusing its address."""
        import gc

        rng = np.random.default_rng(0)
        x = rng.standard_normal((400, 8)).astype(np.float32)
        q = rng.standard_normal((8, 8)).astype(np.float32)
        ex = SearchExecutor(probe_accounting=True)
        p = ivf_flat.IvfFlatSearchParams(n_probes=2)
        idx = ivf_flat.build(
            None, ivf_flat.IvfFlatIndexParams(n_lists=8), x)
        ex.search(idx, q, 5, params=p)
        assert len(ex.probe_frequencies()) == 1
        del idx
        gc.collect()
        assert ex.probe_frequencies() == {}


class TestRecallWindowDecay:
    """Exponential-decay weighting (PR 9): recent pairs dominate, so
    the estimate reacts to sudden staleness within a couple of
    half-lives; default (uniform) behavior unchanged."""

    def test_decay_weights_pinned(self):
        metrics.reset()
        w = RecallWindow(window_s=100.0, decay_half_life_s=10.0)
        w.record(0.0, hits=10, trials=10)   # perfect recall, old
        w.record(10.0, hits=0, trials=10)   # total miss, fresh
        # at t=10 the old pair weighs 0.5: est = 5 / 15
        e = w.estimate(10.0)
        assert e["estimate"] == pytest.approx(5.0 / 15.0)
        # uniform window would read 0.5 — decay reacts faster
        u = RecallWindow(window_s=100.0)
        u.record(0.0, hits=10, trials=10)
        u.record(10.0, hits=0, trials=10)
        assert u.estimate(10.0)["estimate"] == pytest.approx(0.5)
        # aging both pairs equally preserves their weight RATIO — the
        # estimate holds until newer evidence (or the window) moves it
        e = w.estimate(30.0)
        assert e["estimate"] == pytest.approx(5.0 / 15.0)

    def test_decay_widens_ci_as_evidence_ages(self):
        w = RecallWindow(window_s=1000.0, decay_half_life_s=10.0)
        w.record(0.0, hits=90, trials=100)
        fresh = w.estimate(0.0)
        old = w.estimate(50.0)
        assert old["estimate"] == pytest.approx(fresh["estimate"])
        assert (old["ci_high"] - old["ci_low"]) > (
            fresh["ci_high"] - fresh["ci_low"])

    def test_window_prune_still_applies(self):
        w = RecallWindow(window_s=10.0, decay_half_life_s=5.0)
        w.record(0.0, hits=10, trials=10)
        assert w.estimate(11.0)["pairs"] == 0


class TestDriftRebaseline:
    """extend()/rebuild shifts ``list_sizes`` — the detector must
    refresh its baseline when the watched index changes identity or
    shape, not score live traffic against the stale histogram."""

    def _corpus(self, n_lists=8, n=400, seed=0):
        rng = np.random.default_rng(seed)
        x = rng.standard_normal((n, 8)).astype(np.float32)
        q = rng.standard_normal((8, 8)).astype(np.float32)
        idx = ivf_flat.build(
            None, ivf_flat.IvfFlatIndexParams(n_lists=n_lists), x)
        return x, q, idx

    def test_matches_and_watch(self):
        _, _, idx = self._corpus()
        det = DriftDetector.from_index(idx)
        assert det.matches(idx)
        _, _, other = self._corpus(seed=1)
        assert not det.matches(other)      # different identity
        raw = DriftDetector(np.ones(8))
        assert raw.matches(idx)            # raw baseline adopts shape
        assert not raw.matches(
            ivf_flat.build(None, ivf_flat.IvfFlatIndexParams(
                n_lists=4), np.random.default_rng(2).standard_normal(
                    (64, 8)).astype(np.float32)))

    def test_extend_triggers_rebaseline_via_gauge(self):
        x, q, idx = self._corpus()
        ex = SearchExecutor(probe_accounting=True)
        p = ivf_flat.IvfFlatSearchParams(n_probes=2)
        det = DriftDetector.from_index(idx)
        gauge = IndexGauge(executor=ex, indexes={"main": idx},
                           drift={"main": det})
        ex.search(idx, q, 5, params=p)
        out = gauge.publish()
        assert out["drift"]["main"]["rebaselines"] == 0
        base0 = det.baseline.copy()
        # extend returns a NEW index object with shifted list_sizes
        rng = np.random.default_rng(3)
        new_rows = rng.standard_normal((150, 8)).astype(np.float32)
        idx2 = ivf_flat.extend(None, idx, new_rows)
        gauge.indexes["main"] = idx2
        ex.search(idx2, q, 5, params=p)
        out = gauge.publish()
        assert out["drift"]["main"]["rebaselines"] == 1
        assert det.matches(idx2)
        assert not np.array_equal(det.baseline, base0)
        np.testing.assert_array_equal(
            det.baseline, np.asarray(idx2.list_sizes, dtype=np.float64))
        # the same scrape then scores the NEW index's (fresh) plane
        # against the fresh baseline — only post-rebaseline traffic,
        # never the old index's history
        assert det.updates == 1
        assert tracing.get_gauge("index.drift.main.rebaselines") == 1.0
        # further scrapes with the SAME index do not rebaseline again
        ex.search(idx2, q, 5, params=p)
        out = gauge.publish()
        assert out["drift"]["main"]["rebaselines"] == 1

    def test_shape_change_rebaselines_and_scores_clean(self):
        """A rebuilt index with a different n_lists must swap baseline
        AND streaming state (stale planes would be the wrong
        length)."""
        x, q, idx = self._corpus(n_lists=8)
        det = DriftDetector.from_index(idx)
        det.update(np.arange(8, dtype=np.float64))   # some history
        x2 = np.random.default_rng(4).standard_normal(
            (400, 8)).astype(np.float32)
        idx2 = ivf_flat.build(
            None, ivf_flat.IvfFlatIndexParams(n_lists=16), x2)
        assert not det.matches(idx2)
        det.rebaseline(idx2)
        assert det.baseline.shape == (16,)
        assert det.score == 0.0 and det.rebaselines == 1
        # the next update scores against the fresh baseline cleanly
        det.update(np.asarray(idx2.list_sizes, dtype=np.float64))
        assert det.score == pytest.approx(0.0, abs=1e-9)


class TestParamsSweepShadow:
    """Params-sweep shadow sampling (PR 8 follow-on): sampled
    submissions re-run at alternative n_probes as extra background
    legs against the same exact truth, so the recall gauges map the
    live recall frontier, not just the operating point. ManualClock +
    seeded sampler + threadless batcher = fully deterministic."""

    def _serve_sweep(self, corpus, sweep, rounds=6, rows=8, k=10):
        ex = SearchExecutor()
        clock = ManualClock()
        b = DynamicBatcher(
            ex, BatcherConfig(max_wait_s=0.0,
                              shed=LoadShed(background_priority=100)),
            clock=clock, start=False)
        sampler = ShadowSampler(
            b, corpus["bf"],
            ShadowConfig(fraction=1.0, seed=3, priority=100,
                         timeout_s=None, window_s=1e9,
                         sweep_probes=sweep))
        p = ivf_flat.IvfFlatSearchParams(n_probes=2)
        q = corpus["q"]
        for r in range(rounds):
            block = q[(r * rows) % 40:(r * rows) % 40 + rows]
            sampler.submit(corpus["ivf"], block, k, params=p)
            while b.pump():
                pass
        sampler.publish()
        b.close()
        return sampler

    def test_sweep_windows_map_the_frontier(self, corpus):
        metrics.reset()
        sampler = self._serve_sweep(corpus, sweep=(4, 16))
        now = sampler._clock.now()
        # legs rotate round-robin: 6 sampled rounds -> 3 pairs each
        e4 = sampler.sweep_windows[4].estimate(now)
        e16 = sampler.sweep_windows[16].estimate(now)
        assert e4["pairs"] == 3 and e16["pairs"] == 3
        # deeper probes -> higher recall, and both bracket the
        # operating point (n_probes=2) from above
        e_op = sampler.window.estimate(now)
        assert e_op["pairs"] == 6
        assert e_op["estimate"] < e4["estimate"] <= e16["estimate"], (
            e_op["estimate"], e4["estimate"], e16["estimate"])
        # each sweep window's estimate matches exact recall at ITS
        # n_probes on ITS sampled blocks (same pairs, same arithmetic)
        q = corpus["q"]
        for probes, est, blocks in ((4, e4, (0, 2, 4)),
                                    (16, e16, (1, 3, 5))):
            hits = trials = 0
            pp = ivf_flat.IvfFlatSearchParams(n_probes=probes)
            for r in blocks:
                block = q[(r * 8) % 40:(r * 8) % 40 + 8]
                truth = exact_recall(corpus["ivf"], corpus["bf"],
                                     block, 10, pp)
                hits += truth * 8 * 10
                trials += 8 * 10
            assert abs(est["estimate"] - hits / trials) <= 0.02

    def test_sweep_gauges_published(self, corpus):
        metrics.reset()
        self._serve_sweep(corpus, sweep=(4,))
        assert tracing.get_gauge(
            "index.recall.sweep.p4.estimate") > 0.0
        # a single sweep value gets every sampled round: 6 pairs
        assert tracing.get_gauge(
            "index.recall.sweep.p4.window_pairs") == 6.0
        # the operating-point family is untouched by the sweep legs
        assert tracing.get_gauge("index.recall.window_pairs") == 6.0
        # lifecycle ledger still sums per PAIR (live + sweep legs)
        assert tracing.get_counter("index.recall.shadow_submitted") \
            == tracing.get_counter("index.recall.shadow_completed") == 12

    def test_sweep_is_deterministic(self, corpus):
        metrics.reset()
        s1 = self._serve_sweep(corpus, sweep=(4, 16))
        e1 = s1.sweep_windows[4].estimate(s1._clock.now())
        metrics.reset()
        s2 = self._serve_sweep(corpus, sweep=(4, 16))
        e2 = s2.sweep_windows[4].estimate(s2._clock.now())
        assert e1 == e2

    def test_paramsless_submission_sweeps_nothing(self, corpus):
        """A submission without an n_probes knob (params=None) takes
        the plain shadow path — no sweep leg, no crash."""
        metrics.reset()
        ex = SearchExecutor()
        b = DynamicBatcher(
            ex, BatcherConfig(max_wait_s=0.0,
                              shed=LoadShed(background_priority=100)),
            clock=ManualClock(), start=False)
        sampler = ShadowSampler(
            b, corpus["bf"],
            ShadowConfig(fraction=1.0, seed=3, priority=100,
                         timeout_s=None, sweep_probes=(4,)))
        sampler.submit(corpus["bf"], corpus["q"][:8], 10)
        while b.pump():
            pass
        sampler.pump()
        b.close()
        assert tracing.get_counter("index.recall.shadow_submitted") == 1
