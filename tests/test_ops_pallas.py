"""Pallas kernel tests (interpret mode on the CPU mesh; the driver's TPU
bench exercises the compiled path)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.distance.types import DistanceType
from raft_tpu.ops import fused_knn, select_k_tiles


def _naive_knn(q, x, k, metric):
    d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    if metric == DistanceType.InnerProduct:
        sim = q @ x.T
        idx = np.argsort(-sim, axis=1, kind="stable")[:, :k]
        return np.take_along_axis(sim, idx, 1), idx
    if metric == DistanceType.CosineExpanded:
        qn = np.linalg.norm(q, axis=1, keepdims=True)
        xn = np.linalg.norm(x, axis=1, keepdims=True)
        d = 1 - (q @ x.T) / (qn * xn.T)
    elif metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded):
        d = np.sqrt(d2)
    else:
        d = d2
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, 1), idx


class TestFusedKnn:
    @pytest.mark.parametrize(
        "metric",
        [
            DistanceType.L2Expanded,
            DistanceType.L2SqrtExpanded,
            DistanceType.InnerProduct,
            DistanceType.CosineExpanded,
        ],
    )
    def test_matches_naive(self, rng_np, metric):
        q = rng_np.standard_normal((9, 24)).astype(np.float32)
        x = rng_np.standard_normal((500, 24)).astype(np.float32)
        d, i = fused_knn(q, x, 7, metric, tile=128, interpret=True)
        wd, wi = _naive_knn(q, x, 7, metric)
        np.testing.assert_allclose(np.asarray(d), wd, rtol=1e-3, atol=1e-3)
        # indices can differ on ties; distance agreement is the contract
        same = (np.asarray(i) == wi).mean()
        assert same > 0.95

    def test_non_multiple_shapes(self, rng_np):
        # n not a tile multiple, q not 8-multiple, d not 128-multiple
        q = rng_np.standard_normal((3, 17)).astype(np.float32)
        x = rng_np.standard_normal((301, 17)).astype(np.float32)
        d, i = fused_knn(q, x, 5, tile=128, interpret=True)
        wd, wi = _naive_knn(q, x, 5, DistanceType.L2Expanded)
        np.testing.assert_allclose(np.asarray(d), wd, rtol=1e-3, atol=1e-3)

    def test_k_larger_than_tile_fraction(self, rng_np):
        q = rng_np.standard_normal((8, 16)).astype(np.float32)
        x = rng_np.standard_normal((256, 16)).astype(np.float32)
        d, i = fused_knn(q, x, 32, tile=128, interpret=True)
        wd, _ = _naive_knn(q, x, 32, DistanceType.L2Expanded)
        np.testing.assert_allclose(np.asarray(d), wd, rtol=1e-3, atol=1e-3)

    def test_multi_pass_identical(self, rng_np):
        """passes>1 (the slope-timing mode) repeats the stream in one
        dispatch and must return exactly the passes=1 result — incl.
        with a ragged tail block."""
        q = rng_np.standard_normal((4, 20)).astype(np.float32)
        x = rng_np.standard_normal((300, 20)).astype(np.float32)
        d1, i1 = fused_knn(q, x, 6, tile=128, interpret=True)
        d3, i3 = fused_knn(q, x, 6, tile=128, passes=3, interpret=True)
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d3))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i3))


class TestSelectKTiles:
    def test_matches_topk_min(self, rng_np):
        v = rng_np.standard_normal((5, 700)).astype(np.float32)
        d, i = select_k_tiles(v, 9, tile=256, interpret=True)
        want = np.sort(v, axis=1)[:, :9]
        np.testing.assert_allclose(np.asarray(d), want, rtol=1e-5)
        np.testing.assert_array_equal(
            np.take_along_axis(v, np.asarray(i), 1), np.asarray(d)
        )

    def test_matches_topk_max(self, rng_np):
        v = rng_np.standard_normal((4, 300)).astype(np.float32)
        d, i = select_k_tiles(v, 6, select_min=False, tile=128, interpret=True)
        want = -np.sort(-v, axis=1)[:, :6]
        np.testing.assert_allclose(np.asarray(d), want, rtol=1e-5)

    def test_duplicate_values_first_occurrence(self):
        v = jnp.asarray([[3.0, 1.0, 1.0, 2.0] * 64])
        d, i = select_k_tiles(v, 3, tile=128, interpret=True)
        np.testing.assert_allclose(np.asarray(d)[0], [1.0, 1.0, 1.0])
        # ids must be valid positions holding the value 1.0
        assert all(np.asarray(v)[0, j] == 1.0 for j in np.asarray(i)[0])


class TestBf16Kernel:
    def test_fused_knn_bf16(self):
        """bf16 dataset path: padding/alignment and dot dtype handling."""
        rng = np.random.default_rng(5)
        x = rng.standard_normal((700, 24)).astype(np.float32)
        q = rng.standard_normal((5, 24)).astype(np.float32)
        d, i = fused_knn(jnp.asarray(q, jnp.bfloat16),
                         jnp.asarray(x, jnp.bfloat16), 9,
                         tile=128, interpret=True)
        xb = np.asarray(jnp.asarray(x, jnp.bfloat16), np.float32)
        qb = np.asarray(jnp.asarray(q, jnp.bfloat16), np.float32)
        gt_d, gt_i = _naive_knn(qb, xb, 9, DistanceType.L2Expanded)
        assert np.array_equal(np.asarray(i), gt_i)
        np.testing.assert_allclose(np.asarray(d), gt_d, rtol=1e-2, atol=1e-2)


class TestStreamRead:
    def test_matches_column_sum(self, rng_np):
        import numpy as np

        from raft_tpu.ops.fused_topk import stream_read_sum

        x = rng_np.standard_normal((1000, 96)).astype(np.float32)
        got = np.asarray(stream_read_sum(x, tile=256, interpret=True))
        np.testing.assert_allclose(got[0], x.sum(axis=0), rtol=1e-4,
                                   atol=1e-3)

    def test_bf16_input(self, rng_np):
        import jax.numpy as jnp
        import numpy as np

        from raft_tpu.ops.fused_topk import stream_read_sum

        x = rng_np.standard_normal((512, 128)).astype(np.float32)
        got = np.asarray(stream_read_sum(jnp.asarray(x, jnp.bfloat16),
                                         tile=128, interpret=True))
        np.testing.assert_allclose(got[0], x.sum(axis=0), rtol=0.02,
                                   atol=0.5)
