"""Pallas kernel tests (interpret mode on the CPU mesh; the driver's TPU
bench exercises the compiled path)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.distance.types import DistanceType
from raft_tpu.ops import fused_knn, select_k_tiles


def _naive_knn(q, x, k, metric):
    d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    if metric == DistanceType.InnerProduct:
        sim = q @ x.T
        idx = np.argsort(-sim, axis=1, kind="stable")[:, :k]
        return np.take_along_axis(sim, idx, 1), idx
    if metric == DistanceType.CosineExpanded:
        qn = np.linalg.norm(q, axis=1, keepdims=True)
        xn = np.linalg.norm(x, axis=1, keepdims=True)
        d = 1 - (q @ x.T) / (qn * xn.T)
    elif metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded):
        d = np.sqrt(d2)
    else:
        d = d2
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, 1), idx


class TestFusedKnn:
    @pytest.mark.parametrize(
        "metric",
        [
            DistanceType.L2Expanded,
            DistanceType.L2SqrtExpanded,
            DistanceType.InnerProduct,
            DistanceType.CosineExpanded,
        ],
    )
    def test_matches_naive(self, rng_np, metric):
        q = rng_np.standard_normal((9, 24)).astype(np.float32)
        x = rng_np.standard_normal((500, 24)).astype(np.float32)
        d, i = fused_knn(q, x, 7, metric, tile=128, interpret=True)
        wd, wi = _naive_knn(q, x, 7, metric)
        np.testing.assert_allclose(np.asarray(d), wd, rtol=1e-3, atol=1e-3)
        # indices can differ on ties; distance agreement is the contract
        same = (np.asarray(i) == wi).mean()
        assert same > 0.95

    def test_non_multiple_shapes(self, rng_np):
        # n not a tile multiple, q not 8-multiple, d not 128-multiple
        q = rng_np.standard_normal((3, 17)).astype(np.float32)
        x = rng_np.standard_normal((301, 17)).astype(np.float32)
        d, i = fused_knn(q, x, 5, tile=128, interpret=True)
        wd, wi = _naive_knn(q, x, 5, DistanceType.L2Expanded)
        np.testing.assert_allclose(np.asarray(d), wd, rtol=1e-3, atol=1e-3)

    def test_k_larger_than_tile_fraction(self, rng_np):
        q = rng_np.standard_normal((8, 16)).astype(np.float32)
        x = rng_np.standard_normal((256, 16)).astype(np.float32)
        d, i = fused_knn(q, x, 32, tile=128, interpret=True)
        wd, _ = _naive_knn(q, x, 32, DistanceType.L2Expanded)
        np.testing.assert_allclose(np.asarray(d), wd, rtol=1e-3, atol=1e-3)

    def test_multi_pass_identical(self, rng_np):
        """passes>1 (the slope-timing mode) repeats the stream in one
        dispatch and must return exactly the passes=1 result — incl.
        with a ragged tail block."""
        q = rng_np.standard_normal((4, 20)).astype(np.float32)
        x = rng_np.standard_normal((300, 20)).astype(np.float32)
        d1, i1 = fused_knn(q, x, 6, tile=128, interpret=True)
        d3, i3 = fused_knn(q, x, 6, tile=128, passes=3, interpret=True)
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d3))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i3))


class TestSelectKTiles:
    def test_matches_topk_min(self, rng_np):
        v = rng_np.standard_normal((5, 700)).astype(np.float32)
        d, i = select_k_tiles(v, 9, tile=256, interpret=True)
        want = np.sort(v, axis=1)[:, :9]
        np.testing.assert_allclose(np.asarray(d), want, rtol=1e-5)
        np.testing.assert_array_equal(
            np.take_along_axis(v, np.asarray(i), 1), np.asarray(d)
        )

    def test_matches_topk_max(self, rng_np):
        v = rng_np.standard_normal((4, 300)).astype(np.float32)
        d, i = select_k_tiles(v, 6, select_min=False, tile=128, interpret=True)
        want = -np.sort(-v, axis=1)[:, :6]
        np.testing.assert_allclose(np.asarray(d), want, rtol=1e-5)

    def test_duplicate_values_first_occurrence(self):
        v = jnp.asarray([[3.0, 1.0, 1.0, 2.0] * 64])
        d, i = select_k_tiles(v, 3, tile=128, interpret=True)
        np.testing.assert_allclose(np.asarray(d)[0], [1.0, 1.0, 1.0])
        # ids must be valid positions holding the value 1.0
        assert all(np.asarray(v)[0, j] == 1.0 for j in np.asarray(i)[0])


class TestBf16Kernel:
    def test_fused_knn_bf16(self):
        """bf16 dataset path: padding/alignment and dot dtype handling."""
        rng = np.random.default_rng(5)
        x = rng.standard_normal((700, 24)).astype(np.float32)
        q = rng.standard_normal((5, 24)).astype(np.float32)
        d, i = fused_knn(jnp.asarray(q, jnp.bfloat16),
                         jnp.asarray(x, jnp.bfloat16), 9,
                         tile=128, interpret=True)
        xb = np.asarray(jnp.asarray(x, jnp.bfloat16), np.float32)
        qb = np.asarray(jnp.asarray(q, jnp.bfloat16), np.float32)
        gt_d, gt_i = _naive_knn(qb, xb, 9, DistanceType.L2Expanded)
        assert np.array_equal(np.asarray(i), gt_i)
        np.testing.assert_allclose(np.asarray(d), gt_d, rtol=1e-2, atol=1e-2)


class TestStreamRead:
    def test_matches_column_sum(self, rng_np):
        import numpy as np

        from raft_tpu.ops.fused_topk import stream_read_sum

        x = rng_np.standard_normal((1000, 96)).astype(np.float32)
        got = np.asarray(stream_read_sum(x, tile=256, interpret=True))
        np.testing.assert_allclose(got[0], x.sum(axis=0), rtol=1e-4,
                                   atol=1e-3)

    def test_bf16_input(self, rng_np):
        import jax.numpy as jnp
        import numpy as np

        from raft_tpu.ops.fused_topk import stream_read_sum

        x = rng_np.standard_normal((512, 128)).astype(np.float32)
        got = np.asarray(stream_read_sum(jnp.asarray(x, jnp.bfloat16),
                                         tile=128, interpret=True))
        np.testing.assert_allclose(got[0], x.sum(axis=0), rtol=0.02,
                                   atol=0.5)


class TestBeamSearchEdges:
    """Edge shapes for the one-dispatch beam kernel (interpret mode);
    the mainline parity tests live in test_cagra.TestBeamKernel."""

    def _setup(self, rng, n=600, d=128, deg=8):
        import scipy.spatial.distance as spd

        x = rng.standard_normal((n, d)).astype(np.float32)
        dm = spd.cdist(x, x, "sqeuclidean")
        np.fill_diagonal(dm, np.inf)
        graph = np.argsort(dm, 1)[:, :deg].astype(np.int32)
        return x, graph

    def test_query_padding_path(self, rng_np):
        """q not a multiple of block_q exercises the pad+slice path;
        results must match the same queries run in a full block."""
        import jax.numpy as jnp

        from raft_tpu.distance.types import DistanceType
        from raft_tpu.ops.beam_search import beam_search

        x, graph = self._setup(rng_np)
        q = rng_np.standard_normal((8, 128)).astype(np.float32)
        seeds = rng_np.integers(0, len(x), (8, 4 * 8)).astype(np.int32)
        d8, i8 = beam_search(jnp.asarray(q), jnp.asarray(x),
                             jnp.asarray(graph), jnp.asarray(seeds),
                             5, 16, 4, 10, DistanceType.L2Expanded,
                             interpret=True)
        d3, i3 = beam_search(jnp.asarray(q[:3]), jnp.asarray(x),
                             jnp.asarray(graph), jnp.asarray(seeds[:3]),
                             5, 16, 4, 10, DistanceType.L2Expanded,
                             interpret=True)
        assert i3.shape == (3, 5)
        np.testing.assert_array_equal(np.asarray(i3), np.asarray(i8)[:3])
        np.testing.assert_allclose(np.asarray(d3), np.asarray(d8)[:3],
                                   rtol=1e-5, atol=1e-5)

    def test_L_exceeds_candidate_width(self, rng_np):
        """itopk L > w*deg: the buffer starts partially empty (-1/inf
        rows) and must still converge to exact top-k on a full graph."""
        import jax.numpy as jnp
        from raft_tpu.distance.types import DistanceType
        from raft_tpu.ops.beam_search import beam_search

        x, graph = self._setup(rng_np, n=300, deg=4)   # C = 16 < L = 48
        q = rng_np.standard_normal((8, 128)).astype(np.float32)
        seeds = rng_np.integers(0, len(x), (8, 16)).astype(np.int32)
        d, i = beam_search(jnp.asarray(q), jnp.asarray(x),
                           jnp.asarray(graph), jnp.asarray(seeds),
                           10, 48, 4, 40, DistanceType.L2Expanded,
                           interpret=True)
        # parity with the XLA engine under the same partially-empty
        # buffer (recall itself is bounded by the degree-4 graph)
        from raft_tpu.neighbors.cagra import _search_batch

        dx, ix = _search_batch(jnp.asarray(x), jnp.asarray(graph),
                               jnp.asarray(q), jnp.asarray(seeds), None,
                               k=10, L=48, w=4, max_iters=40,
                               metric=DistanceType.L2Expanded)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(ix))
        np.testing.assert_allclose(np.asarray(d), np.asarray(dx),
                                   rtol=1e-5, atol=1e-5)
        # returned distances sorted ascending, ids valid
        dd = np.asarray(d)
        assert (np.diff(dd, axis=1) >= -1e-5).all()
        ii = np.asarray(i)
        assert ii.min() >= 0 and ii.max() < len(x)

    def test_hbm_mode_matches_vmem(self, rng_np):
        """ds_mode='hbm' (double-buffered candidate-row DMA from an
        HBM-resident dataset — the any-size engine) must be
        bit-identical to the VMEM-resident gather path on the same
        inputs, for f32 and bf16 datasets."""
        import jax.numpy as jnp

        from raft_tpu.distance.types import DistanceType
        from raft_tpu.ops.beam_search import beam_search

        x, graph = self._setup(rng_np)
        q = rng_np.standard_normal((8, 128)).astype(np.float32)
        seeds = rng_np.integers(0, len(x), (8, 4 * 8)).astype(np.int32)
        x8 = np.clip(x * 30.0, -127, 127).astype(np.int8)  # CAGRA-Q role
        for ds in (jnp.asarray(x), jnp.asarray(x).astype(jnp.bfloat16),
                   jnp.asarray(x8)):
            dv, iv = beam_search(jnp.asarray(q), ds,
                                 jnp.asarray(graph), jnp.asarray(seeds),
                                 5, 16, 4, 10, DistanceType.L2Expanded,
                                 interpret=True, ds_mode="vmem")
            dh, ih = beam_search(jnp.asarray(q), ds,
                                 jnp.asarray(graph), jnp.asarray(seeds),
                                 5, 16, 4, 10, DistanceType.L2Expanded,
                                 interpret=True, ds_mode="hbm")
            np.testing.assert_array_equal(np.asarray(iv), np.asarray(ih))
            # distances: allclose, not bit-equal — the two lowerings
            # (2-D scratch read vs 3-D double-buffer slice) may fuse /
            # reassociate the f32 dot reduction differently (1 ulp)
            np.testing.assert_allclose(np.asarray(dv), np.asarray(dh),
                                       rtol=1e-5, atol=1e-4)

    def test_vmem_mode_rejects_oversized_dataset(self, rng_np):
        import jax.numpy as jnp
        import pytest as _pytest

        from raft_tpu.core.validation import RaftError
        from raft_tpu.distance.types import DistanceType
        from raft_tpu.ops.beam_search import beam_search

        x, graph = self._setup(rng_np, n=300, deg=4)
        q = rng_np.standard_normal((4, 128)).astype(np.float32)
        seeds = rng_np.integers(0, 300, (4, 16)).astype(np.int32)
        with _pytest.raises(RaftError, match="VMEM budget"):
            beam_search(jnp.asarray(q), jnp.asarray(x),
                        jnp.asarray(graph), jnp.asarray(seeds),
                        5, 16, 4, 5, DistanceType.L2Expanded,
                        interpret=True, ds_mode="vmem", vmem_mb=8)

    def test_bad_args_rejected(self, rng_np):
        import jax.numpy as jnp
        import pytest as _pytest

        from raft_tpu.core.validation import RaftError
        from raft_tpu.distance.types import DistanceType
        from raft_tpu.ops.beam_search import beam_search

        x, graph = self._setup(rng_np, n=100, d=128, deg=4)
        q = rng_np.standard_normal((4, 128)).astype(np.float32)
        seeds = rng_np.integers(0, 100, (4, 16)).astype(np.int32)
        with _pytest.raises(RaftError, match="itopk"):
            beam_search(jnp.asarray(q), jnp.asarray(x),
                        jnp.asarray(graph), jnp.asarray(seeds),
                        20, 10, 4, 5, DistanceType.L2Expanded,
                        interpret=True)
        with _pytest.raises(RaftError, match="seeds"):
            beam_search(jnp.asarray(q), jnp.asarray(x),
                        jnp.asarray(graph), jnp.asarray(seeds[:, :8]),
                        5, 16, 4, 5, DistanceType.L2Expanded,
                        interpret=True)
