"""RNG + generator tests (analog of cpp/test/random/*)."""

import numpy as np
import pytest

from raft_tpu import random as rrandom


class TestRng:
    def test_reproducible(self):
        a = rrandom.uniform(rrandom.RngState(3), (100,))
        b = rrandom.uniform(rrandom.RngState(3), (100,))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_stream_advances(self):
        st = rrandom.RngState(3)
        a = rrandom.uniform(st, (100,))
        b = rrandom.uniform(st, (100,))
        assert not np.array_equal(np.asarray(a), np.asarray(b))

    def test_uniform_range(self):
        x = np.asarray(rrandom.uniform(rrandom.RngState(0), (5000,), low=-2, high=3))
        assert x.min() >= -2 and x.max() < 3
        assert abs(x.mean() - 0.5) < 0.1

    def test_normal_moments(self):
        x = np.asarray(rrandom.normal(rrandom.RngState(0), (20000,), mu=1.0, sigma=2.0))
        assert abs(x.mean() - 1.0) < 0.1
        assert abs(x.std() - 2.0) < 0.1

    @pytest.mark.parametrize("fn", ["lognormal", "gumbel", "logistic", "laplace",
                                    "exponential", "rayleigh"])
    def test_distributions_finite(self, fn):
        x = np.asarray(getattr(rrandom, fn)(rrandom.RngState(0), (1000,)))
        assert np.isfinite(x).all()

    def test_bernoulli(self):
        x = np.asarray(rrandom.bernoulli(rrandom.RngState(0), (10000,), prob=0.3))
        assert abs(x.mean() - 0.3) < 0.05

    def test_permute(self):
        p = np.asarray(rrandom.permute(rrandom.RngState(0), 100))
        np.testing.assert_array_equal(np.sort(p), np.arange(100))

    def test_sample_without_replacement(self):
        s = np.asarray(rrandom.sample_without_replacement(rrandom.RngState(0), 20, 100))
        assert len(set(s.tolist())) == 20

    def test_weighted_sample(self):
        w = np.zeros(50)
        w[:10] = 1.0
        s = np.asarray(
            rrandom.sample_without_replacement(rrandom.RngState(0), 10, 50, weights=w + 1e-9)
        )
        assert set(s.tolist()) == set(range(10))


class TestGenerators:
    def test_make_blobs_separable(self):
        x, labels, centers = rrandom.make_blobs(
            rrandom.RngState(0), 500, 8, n_clusters=4, cluster_std=0.1
        )
        x, labels, centers = np.asarray(x), np.asarray(labels), np.asarray(centers)
        assert x.shape == (500, 8) and labels.shape == (500,)
        # each point is closest to its own center
        d = ((x[:, None, :] - centers[None]) ** 2).sum(-1)
        assert (d.argmin(1) == labels).mean() > 0.99

    def test_make_regression_solvable(self):
        x, y, coef = rrandom.make_regression(rrandom.RngState(0), 200, 10, noise=0.0)
        x, y, coef = np.asarray(x), np.asarray(y), np.asarray(coef)
        fitted, *_ = np.linalg.lstsq(x, y, rcond=None)
        np.testing.assert_allclose(fitted, coef, rtol=1e-2, atol=1e-2)

    def test_rmat_shapes(self):
        e = np.asarray(rrandom.rmat(rrandom.RngState(0), 8, 8, 1000))
        assert e.shape == (1000, 2)
        assert e.min() >= 0 and e.max() < 256

    def test_rmat_skew(self):
        # default theta strongly favors quadrant a → low ids dominate
        e = np.asarray(rrandom.rmat(rrandom.RngState(0), 10, 10, 5000))
        assert (e[:, 0] < 512).mean() > 0.6

    def test_mvg(self):
        cov = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
        x = np.asarray(
            rrandom.multi_variable_gaussian(rrandom.RngState(0), np.zeros(2), cov, 20000)
        )
        np.testing.assert_allclose(np.cov(x.T), cov, atol=0.15)
