"""True multi-process distributed validation — the reference validates
MNMG logic with real NCCL over local worker processes
(raft_dask/test/test_comms.py LocalCUDACluster); the analog here is
``jax.distributed.initialize`` over local CPU processes: a 2-process
clique forms a global mesh and runs the comms collectives through the
same ``raft_tpu.comms`` code path multi-host TPU uses over DCN."""

import os
import pathlib
import socket
import subprocess
import sys
import textwrap

import pytest

# workers do `sys.path.insert(0, os.getcwd())`, so launch them with the
# repo root as cwd wherever this checkout lives
REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[1])

_WORKER = textwrap.dedent("""
    import os, sys
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    sys.path.insert(0, os.getcwd())   # launched with cwd = repo root
    from raft_tpu.comms import Comms, bootstrap
    from raft_tpu.comms.comms import allreduce, rank
    from jax.sharding import PartitionSpec as P
    import jax.numpy as jnp

    bootstrap.initialize(f"127.0.0.1:{port}", nproc, pid)
    assert len(jax.devices()) == nproc, jax.devices()
    assert jax.process_count() == nproc

    comms = Comms(bootstrap.make_mesh(), "data")
    assert comms.process_rank == pid

    x = jax.device_put(
        jnp.arange(nproc * 4, dtype=jnp.float32).reshape(nproc, 4),
        comms.row_sharded(),
    )

    def body(xl):
        return allreduce(xl, axis="data") + 0.0 * rank("data")

    out = comms.run(body, x, in_specs=(P("data", None),),
                    out_specs=P("data", None), check_vma=False)
    local = out.addressable_shards[0].data
    assert float(local.sum()) == float(
        jnp.arange(nproc * 4, dtype=jnp.float32).sum()
    ), local
    print(f"proc {pid} OK", flush=True)
""")


_WORKER_STACK = textwrap.dedent("""
    import os, sys
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    # 4 virtual devices per process -> 8-device global mesh
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=4")
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid, nproc, port, ckpt = (int(sys.argv[1]), int(sys.argv[2]),
                              sys.argv[3], sys.argv[4])
    sys.path.insert(0, os.getcwd())
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from raft_tpu.comms import Comms, bootstrap
    from raft_tpu.comms.comms import allreduce
    from raft_tpu.distributed import checkpoint as ckpt_mod
    from raft_tpu.distributed import ivf as dist_ivf
    from raft_tpu.neighbors import ivf_flat, ivf_pq
    from raft_tpu.neighbors.ivf_flat import (IvfFlatIndexParams,
                                             IvfFlatSearchParams)
    from raft_tpu.neighbors.ivf_pq import IvfPqIndexParams, IvfPqSearchParams

    bootstrap.initialize(f"127.0.0.1:{port}", nproc, pid)
    assert len(jax.devices()) == nproc * 4, jax.devices()
    comms = Comms(bootstrap.make_mesh(), "data")

    def fetch(a):
        return np.asarray(a.addressable_shards[0].data)

    def sync():
        # a fetched collective is a cross-process barrier: it cannot
        # complete until every process has reached (and enqueued) it
        out = comms.run(lambda x: allreduce(x, axis="data"),
                        jax.device_put(jnp.ones((comms.size, 1)),
                                       comms.row_sharded()),
                        in_specs=(P("data", None),),
                        out_specs=P("data", None), check_vma=False)
        fetch(out)

    # deterministic data, identical in both processes
    rng = np.random.default_rng(123)
    x = rng.standard_normal((2000, 32)).astype(np.float32)
    q = rng.standard_normal((16, 32)).astype(np.float32)

    # ---- IVF-Flat: distributed build + search vs single-chip parity
    fparams = IvfFlatIndexParams(n_lists=16, kmeans_n_iters=8)
    fsearch = IvfFlatSearchParams(n_probes=8)
    dist_index = dist_ivf.build(None, comms, fparams, x)
    dd, di = dist_ivf.search(None, fsearch, dist_index, q, 10,
                             probe_mode="global")
    dd, di = fetch(dd), fetch(di)

    ref_index = ivf_flat.build(None, fparams, x)
    rd, ri = ivf_flat.search(None, fsearch, ref_index, q, 10)
    np.testing.assert_array_equal(di, np.asarray(ri))
    np.testing.assert_allclose(dd, np.asarray(rd), rtol=1e-5, atol=1e-5)
    print(f"proc {pid} flat parity OK", flush=True)

    # ---- checkpoint: per-process save -> barrier -> reshard onto a
    #      4-device sub-mesh (2 devices from each process)
    ckpt_mod.save_flat_multihost(dist_index, ckpt)
    sync()
    by_proc = {}
    for d in jax.devices():
        by_proc.setdefault(d.process_index, []).append(d)
    half = [d for ds in by_proc.values()
            for d in sorted(ds, key=lambda d: d.id)[:2]]
    comms4 = Comms(bootstrap.make_mesh(devices=half), "data")
    loaded = ckpt_mod.load_flat_multihost(None, comms4, ckpt)
    assert loaded.centers.sharding.num_devices == 4
    ld, li = dist_ivf.search(None, fsearch, loaded, q, 10,
                             probe_mode="global")
    np.testing.assert_array_equal(fetch(li), di)
    np.testing.assert_allclose(fetch(ld), dd, rtol=1e-5, atol=1e-5)
    print(f"proc {pid} reshard OK", flush=True)

    # ---- IVF-PQ: distributed build + search + multihost round-trip
    pparams = IvfPqIndexParams(n_lists=16, pq_dim=8, pq_bits=8,
                               kmeans_n_iters=8)
    psearch = IvfPqSearchParams(n_probes=16)
    pq_dist = dist_ivf.build_pq(None, comms, pparams, x)
    pd, pi = dist_ivf.search_pq(None, psearch, pq_dist, q, 10,
                                probe_mode="global")
    pd, pi = fetch(pd), fetch(pi)
    pq_ref = ivf_pq.build(None, pparams, x)
    prd, pri = ivf_pq.search(None, psearch, pq_ref, q, 10)
    np.testing.assert_array_equal(pi, np.asarray(pri))

    pq_ckpt = ckpt + "_pq"
    ckpt_mod.save_pq_multihost(pq_dist, pq_ckpt)
    sync()
    pq_loaded = ckpt_mod.load_pq_multihost(None, comms4, pq_ckpt)
    p2d, p2i = dist_ivf.search_pq(None, psearch, pq_loaded, q, 10,
                                  probe_mode="global")
    np.testing.assert_array_equal(fetch(p2i), pi)
    np.testing.assert_allclose(fetch(p2d), pd, rtol=1e-5, atol=1e-5)
    print(f"proc {pid} OK", flush=True)
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# capability probe — some jaxlib/backend combinations accept
# jax.distributed.initialize but reject actually *running* a
# cross-process computation (jaxlib 0.4.37 CPU: "Multiprocess
# computations aren't implemented on the CPU backend"; see the
# ROADMAP "Known-environmental" note). That is an environment limit,
# not a repo bug, so these tests skip instead of failing. Re-check
# when the container's jax moves.
_CAPABILITY_ERRORS = (
    "Multiprocess computations aren't implemented",
    "non-addressable device",
)

_PROBE = textwrap.dedent("""
    import os, sys
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    sys.path.insert(0, os.getcwd())
    from raft_tpu.comms import Comms, bootstrap
    from raft_tpu.comms.comms import allreduce
    from jax.sharding import PartitionSpec as P
    import jax.numpy as jnp

    bootstrap.initialize(f"127.0.0.1:{port}", nproc, pid)
    comms = Comms(bootstrap.make_mesh(), "data")
    out = comms.run(lambda v: allreduce(v, axis="data"),
                    jax.device_put(jnp.ones((nproc, 1)),
                                   comms.row_sharded()),
                    in_specs=(P("data", None),),
                    out_specs=P("data", None), check_vma=False)
    assert float(out.addressable_shards[0].data.sum()) == nproc
    print("probe OK", flush=True)
""")

_probe_result = None


def _multiprocess_capability(tmp_path_factory) -> tuple:
    """(supported, detail) — cached for the session; one minimal
    2-process allreduce tells us whether the backend can run
    cross-process computations at all."""
    global _probe_result
    if _probe_result is not None:
        return _probe_result
    worker = tmp_path_factory.mktemp("mp_probe") / "probe.py"
    worker.write_text(_PROBE)
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "PALLAS_AXON_POOL_IPS",
                        "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            cwd=REPO_ROOT,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            out = b"probe timed out"
        outs.append(out.decode())
    ok = all(p.returncode == 0 and "probe OK" in o
             for p, o in zip(procs, outs))
    if ok:
        _probe_result = (True, "")
    else:
        combined = "\n".join(outs)
        known = [e for e in _CAPABILITY_ERRORS if e in combined]
        if known:
            _probe_result = (False, known[0])
        else:
            # an unknown failure is a real bug — do NOT mask it
            _probe_result = (True, "")
    return _probe_result


@pytest.fixture()
def multiprocess_backend(tmp_path_factory):
    supported, detail = _multiprocess_capability(tmp_path_factory)
    if not supported:
        pytest.skip(
            "backend rejects cross-process computations "
            f"({detail!r}) — known-environmental, see ROADMAP.md")


def test_two_process_clique(tmp_path, multiprocess_backend):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            cwd=REPO_ROOT,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process clique timed out")
        outs.append(out.decode())
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid} OK" in out


def test_two_process_distributed_stack(tmp_path, multiprocess_backend):
    """VERDICT r2 #5: the full distributed stack across process
    boundaries — dist IVF-Flat/PQ build + search (bit-parity with the
    single-chip result), per-process checkpoint save, and a reshard
    8 devices -> 4 (a 2x2 sub-mesh spanning both processes) on load."""
    worker = tmp_path / "worker_stack.py"
    worker.write_text(_WORKER_STACK)
    ckpt = tmp_path / "ckpt_flat"
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), "2", str(port),
             str(ckpt)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            cwd=REPO_ROOT,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=480)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process distributed stack timed out")
        outs.append(out.decode())
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid} flat parity OK" in out
        assert f"proc {pid} reshard OK" in out
        assert f"proc {pid} OK" in out
