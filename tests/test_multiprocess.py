"""True multi-process distributed validation — the reference validates
MNMG logic with real NCCL over local worker processes
(raft_dask/test/test_comms.py LocalCUDACluster); the analog here is
``jax.distributed.initialize`` over local CPU processes: a 2-process
clique forms a global mesh and runs the comms collectives through the
same ``raft_tpu.comms`` code path multi-host TPU uses over DCN."""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent("""
    import os, sys
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    sys.path.insert(0, os.getcwd())   # launched with cwd = repo root
    from raft_tpu.comms import Comms, bootstrap
    from raft_tpu.comms.comms import allreduce, rank
    from jax.sharding import PartitionSpec as P
    import jax.numpy as jnp

    bootstrap.initialize(f"127.0.0.1:{port}", nproc, pid)
    assert len(jax.devices()) == nproc, jax.devices()
    assert jax.process_count() == nproc

    comms = Comms(bootstrap.make_mesh(), "data")
    assert comms.process_rank == pid

    x = jax.device_put(
        jnp.arange(nproc * 4, dtype=jnp.float32).reshape(nproc, 4),
        comms.row_sharded(),
    )

    def body(xl):
        return allreduce(xl, axis="data") + 0.0 * rank("data")

    out = comms.run(body, x, in_specs=(P("data", None),),
                    out_specs=P("data", None), check_vma=False)
    local = out.addressable_shards[0].data
    assert float(local.sum()) == float(
        jnp.arange(nproc * 4, dtype=jnp.float32).sum()
    ), local
    print(f"proc {pid} OK", flush=True)
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_clique(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            cwd="/root/repo",
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process clique timed out")
        outs.append(out.decode())
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid} OK" in out
