"""Benchmark harness tests — dataset tree, runner, export, plot
(reference ``python/raft-ann-bench`` CLI behavior)."""

import json

import numpy as np
import pytest

from raft_tpu.bench.datasets import convert_hdf5, make_dataset
from raft_tpu.bench.runner import export_csv, plot_results, run_benchmark
from raft_tpu.io import read_bin


@pytest.fixture(scope="module")
def dataset_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("data")
    return make_dataset(out, "tiny", n=3000, dim=16, n_queries=50, k=20)


class TestDatasets:
    def test_tree_layout(self, dataset_dir):
        assert (dataset_dir / "base.fbin").exists()
        assert (dataset_dir / "query.fbin").exists()
        base = read_bin(dataset_dir / "base.fbin")
        gt = read_bin(dataset_dir / "groundtruth.neighbors.ibin")
        assert base.shape == (3000, 16)
        assert gt.shape == (50, 20)
        # groundtruth sanity: ids in range, first column is true NN
        assert gt.min() >= 0 and gt.max() < 3000

    def test_hdf5_conversion(self, tmp_path, rng_np):
        import h5py

        h5 = tmp_path / "toy.hdf5"
        train = rng_np.standard_normal((200, 8)).astype(np.float32)
        test = rng_np.standard_normal((10, 8)).astype(np.float32)
        with h5py.File(h5, "w") as f:
            f["train"] = train
            f["test"] = test
            f.attrs["distance"] = "euclidean"
        root = convert_hdf5(h5, tmp_path / "out")
        np.testing.assert_allclose(read_bin(root / "base.fbin"), train)
        assert (root / "metric.txt").read_text().strip() == "euclidean"


class TestRunner:
    def test_run_export_plot(self, dataset_dir, tmp_path):
        config = {
            "algos": [
                {"name": "raft_brute_force", "search": [{}]},
                {
                    "name": "raft_ivf_flat",
                    "build": {"n_lists": 32},
                    "search": [{"n_probes": 4}, {"n_probes": 32}],
                },
            ]
        }
        rows = run_benchmark(dataset_dir, config, tmp_path / "res",
                             k=10, search_iters=1)
        assert len(rows) == 3
        bf = rows[0]
        assert bf["algo"] == "raft_brute_force"
        assert bf["recall"] > 0.999          # exact search
        assert bf["qps"] > 0
        # sweeping n_probes to all lists reaches ~exact recall
        assert rows[2]["recall"] >= rows[1]["recall"]
        assert rows[2]["recall"] > 0.95

        csv_path = export_csv(tmp_path / "res")
        text = csv_path.read_text()
        assert "raft_ivf_flat" in text and "qps" in text

        png = plot_results(tmp_path / "res")
        assert png.exists() and png.stat().st_size > 1000

    def test_index_cache_round_trip(self, dataset_dir, tmp_path):
        """Second run on the same out_dir reloads the saved index
        (reference benchmark.hpp build/search phase separation) with
        identical search quality; --force-rebuild rebuilds."""
        config = {
            "algos": [
                {"name": "raft_ivf_flat", "build": {"n_lists": 32},
                 "search": [{"n_probes": 32}]},
                {"name": "raft_cagra",
                 "build": {"graph_degree": 16,
                           "intermediate_graph_degree": 24,
                           "build_algo": "cluster_join"},
                 "search": [{"itopk_size": 32}]},
            ]
        }
        out = tmp_path / "res"
        first = run_benchmark(dataset_dir, config, out, k=10,
                              search_iters=1)
        assert all(not r["build_cached"] for r in first)
        idx_files = sorted((out / "indexes").glob("*.bin"))
        assert len(idx_files) == 2, idx_files

        second = run_benchmark(dataset_dir, config, out, k=10,
                               search_iters=1)
        assert all(r["build_cached"] for r in second)
        for a, b in zip(first, second):
            assert a["recall"] == b["recall"], (a, b)

        third = run_benchmark(dataset_dir, config, out, k=10,
                              search_iters=1, force_rebuild=True)
        assert all(not r["build_cached"] for r in third)

    def test_resume_and_algo_filter(self, dataset_dir, tmp_path):
        """resume=True skips combinations already in results.jsonl and
        appends the rest (the interrupted-TPU-sweep recovery path);
        only_algos restricts the sweep to the named families."""
        config = {
            "algos": [
                {"name": "raft_brute_force", "search": [{}]},
                {"name": "raft_ivf_flat", "build": {"n_lists": 32},
                 "search": [{"n_probes": 4}, {"n_probes": 32}]},
            ]
        }
        out = tmp_path / "res"
        only = run_benchmark(dataset_dir, config, out, k=10,
                             search_iters=1,
                             only_algos=["raft_brute_force"])
        assert [r["algo"] for r in only] == ["raft_brute_force"]

        # simulate the interrupted sweep: results.jsonl holds only the
        # brute-force row; resume must keep it and add the ivf rows
        resumed = run_benchmark(dataset_dir, config, out, k=10,
                                search_iters=1, resume=True)
        assert [r["algo"] for r in resumed] == [
            "raft_brute_force", "raft_ivf_flat", "raft_ivf_flat"]
        lines = [json.loads(line) for line in
                 (out / "results.jsonl").read_text().splitlines()]
        assert len(lines) == 3

        # resuming a complete sweep is a no-op that reports every row
        again = run_benchmark(dataset_dir, config, out, k=10,
                              search_iters=1, resume=True)
        assert len(again) == 3
        assert len((out / "results.jsonl").read_text()
                   .splitlines()) == 3

        # a resumed per-family step returns only that family's rows
        one = run_benchmark(dataset_dir, config, out, k=10,
                            search_iters=1, resume=True,
                            only_algos=["raft_brute_force"])
        assert [r["algo"] for r in one] == ["raft_brute_force"]

        # rows measured at a different search_iters don't satisfy the
        # resume (they re-measure and append)
        deeper = run_benchmark(dataset_dir, config, out, k=10,
                               search_iters=2, resume=True,
                               only_algos=["raft_brute_force"])
        assert len(deeper) == 1
        assert len((out / "results.jsonl").read_text()
                   .splitlines()) == 4

    def test_resume_rewrites_legacy_backendless_rows(self, dataset_dir,
                                                     tmp_path):
        """A row written before the backend field existed cannot prove
        it was measured on THIS backend: resume re-measures the combo
        and drops the stale row from the file (keeping both would
        double up the export/plot)."""
        config = {"algos": [
            {"name": "raft_brute_force", "search": [{}]},
            {"name": "raft_ivf_flat", "build": {"n_lists": 16},
             "search": [{"n_probes": 4}]},
        ]}
        out = tmp_path / "res"
        first = run_benchmark(dataset_dir, config, out, k=10,
                              search_iters=1)
        out_file = out / "results.jsonl"
        bf_row, ivf_row = [json.loads(line) for line in
                           out_file.read_text().splitlines()]
        del bf_row["backend"]
        # a backend-less row from some OTHER dataset must survive
        foreign = dict(bf_row, dataset="other-ds")
        out_file.write_text("\n".join(json.dumps(r) for r in
                                      (bf_row, ivf_row, foreign)) + "\n")

        # a combo the resumed invocation will NOT re-measure (filtered
        # out by only_algos) must keep its legacy row: dropping without
        # replacing would lose measured data
        run_benchmark(dataset_dir, config, out, k=10, search_iters=1,
                      resume=True, only_algos=["raft_ivf_flat"])
        rows = [json.loads(line) for line in
                out_file.read_text().splitlines()]
        assert sum("backend" not in r for r in rows) == 2  # bf + foreign

        resumed = run_benchmark(dataset_dir, config, out, k=10,
                                search_iters=1, resume=True)
        assert len(resumed) == 2
        rows = [json.loads(line) for line in
                out_file.read_text().splitlines()]
        # this sweep's legacy brute-force row was replaced (with the
        # backend field); the foreign dataset's stayed as-is
        by_ds = {}
        for r in rows:
            by_ds.setdefault(r.get("dataset"), []).append(r)
        assert len(by_ds["other-ds"]) == 1
        assert "backend" not in by_ds["other-ds"][0]
        this_ds = by_ds[dataset_dir.name]
        assert len(this_ds) == 2
        assert all(r["backend"] == first[0]["backend"] for r in this_ds)

    def test_require_cached_index(self, dataset_dir, tmp_path):
        """require_cached_index fails fast (host-side) when a saveable
        algo's cache misses, instead of building on the measurement
        device; saveless brute_force is exempt; a cached family runs."""
        config = {
            "algos": [
                {"name": "raft_brute_force", "search": [{}]},
                {"name": "raft_ivf_flat", "build": {"n_lists": 32},
                 "search": [{"n_probes": 4}]},
            ]
        }
        out = tmp_path / "res"
        with pytest.raises(RuntimeError, match="require_cached_index"):
            run_benchmark(dataset_dir, config, out, k=10, search_iters=1,
                          require_cached_index=True)
        # brute force (no index file) ran and flushed before the raise
        lines = (out / "results.jsonl").read_text().splitlines()
        assert [json.loads(line)["algo"] for line in lines] == [
            "raft_brute_force"]

        # populate the cache, then the guarded run succeeds
        run_benchmark(dataset_dir, config, out, k=10, search_iters=1,
                      only_algos=["raft_ivf_flat"])
        rows = run_benchmark(dataset_dir, config, out, k=10,
                             search_iters=1, require_cached_index=True)
        assert [r["algo"] for r in rows] == [
            "raft_brute_force", "raft_ivf_flat"]
        assert rows[1]["build_cached"]

    def test_cli(self, dataset_dir, tmp_path):
        from raft_tpu.bench.__main__ import main

        cfg = tmp_path / "cfg.json"
        cfg.write_text(json.dumps(
            {"algos": [{"name": "raft_brute_force", "search": [{}]}]}
        ))
        rc = main([
            "run", "--dataset", str(dataset_dir), "--config", str(cfg),
            "--out-dir", str(tmp_path / "res2"), "-k", "5",
            "--search-iters", "1",
        ])
        assert rc == 0
        assert (tmp_path / "res2" / "results.jsonl").exists()


class TestReferenceConfigSchema:
    def test_normalize_reference_config(self):
        from raft_tpu.bench.runner import normalize_config

        ref = {
            "dataset": {"name": "x", "distance": "euclidean"},
            "index": [
                {"algo": "raft_bfknn", "build_param": {},
                 "search_params": [{"probe": 1}]},
                {"algo": "hnswlib", "build_param": {"M": 12},
                 "search_params": [{"ef": 10}]},
                {"algo": "raft_ivf_pq",
                 "build_param": {"niter": 25, "nlist": 1000, "pq_dim": 64,
                                 "pq_bits": 8, "ratio": 2},
                 "search_params": [{"nprobe": 20,
                                    "internalDistanceDtype": "float"}]},
                {"algo": "raft_cagra", "build_param": {"graph_degree": 32},
                 "search_params": [{"itopk": 32}, {"itopk": 64}]},
                # competitor with no wrapper here: must be dropped
                {"algo": "faiss_gpu_ivf_flat", "build_param": {"nlist": 64},
                 "search_params": [{"nprobe": 4}]},
            ],
        }
        cfg = normalize_config(ref)
        names = [a["name"] for a in cfg["algos"]]
        # hnswlib has a wrapper (the native C++ baseline), so a
        # reference conf naming it runs the competitor series; faiss/
        # ggnn wrap other libraries and are dropped.
        assert names == ["raft_brute_force", "hnswlib", "raft_ivf_pq",
                         "raft_cagra"]
        hnsw = cfg["algos"][1]
        assert hnsw["build"] == {"M": 12}
        assert hnsw["search"] == [{"ef": 10}]
        pq = cfg["algos"][2]
        assert pq["build"] == {"kmeans_n_iters": 25, "n_lists": 1000,
                               "pq_dim": 64, "pq_bits": 8,
                               "kmeans_trainset_fraction": 0.5}
        assert pq["search"] == [{"n_probes": 20}]
        assert cfg["algos"][3]["search"] == [{"itopk_size": 32},
                                             {"itopk_size": 64}]
        # native schema passes through untouched
        native = {"algos": [{"name": "raft_brute_force"}]}
        assert normalize_config(native) is native

    def test_runs_with_reference_schema(self, tmp_path):
        import json

        from raft_tpu.bench.datasets import make_dataset
        from raft_tpu.bench.runner import run_benchmark

        root = make_dataset(tmp_path, "tiny", n=2000, dim=16, n_queries=50,
                            k=10)
        ref_cfg = {"index": [
            {"algo": "raft_ivf_flat", "build_param": {"nlist": 16},
             "search_params": [{"nprobe": 8}, {"nprobe": 16}]},
        ]}
        rows = run_benchmark(root, ref_cfg, tmp_path / "out", k=10,
                             search_iters=1)
        assert len(rows) == 2
        assert rows[1]["recall"] >= 0.99


class TestPrims:
    def test_suite_runs_and_reports(self):
        from raft_tpu.bench.prims import run_prims

        recs = run_prims(size="tiny", name_filter="pairwise", budget_s=0.5)
        assert len(recs) == 1
        rec = recs[0]
        assert rec["prim"] == "pairwise_l2"
        for field in ("ms", "gbps", "bw_frac", "mfu", "shape", "backend"):
            assert field in rec
        assert rec["ms"] > 0 and rec["gbps"] > 0

    def test_out_jsonl(self, tmp_path):
        import json

        from raft_tpu.bench.prims import run_prims

        out = tmp_path / "prims.jsonl"
        run_prims(size="tiny", name_filter="select_k_xla", budget_s=0.5,
                  out_path=str(out))
        lines = out.read_text().strip().splitlines()
        assert len(lines) == 1
        assert json.loads(lines[0])["prim"] == "select_k_xla"


class TestCagraBundleRefine:
    def test_refine_uses_raw_base(self, rng_np):
        """Regression (review r3): with storage_dtype the index holds a
        quantized copy — refine must re-rank against the RAW f32 base,
        and refined distances must therefore be exact f32 L2."""
        import jax.numpy as jnp

        from raft_tpu.bench.runner import _cagra_build, _cagra_search
        from raft_tpu.distance.types import DistanceType

        c = rng_np.standard_normal((6, 128)) * 5
        x = (c[rng_np.integers(0, 6, 1200)]
             + rng_np.standard_normal((1200, 128))).astype(np.float32)
        q = (c[rng_np.integers(0, 6, 8)]
             + rng_np.standard_normal((8, 128))).astype(np.float32)
        bundle = _cagra_build(x, DistanceType.L2Expanded,
                              graph_degree=16,
                              intermediate_graph_degree=32,
                              build_algo="NN_DESCENT",
                              storage_dtype="bfloat16")
        assert bundle["index"].dataset.dtype == jnp.bfloat16
        assert np.asarray(bundle["base"]).dtype == np.float32
        d, i = _cagra_search(bundle, q, 5, itopk_size=32,
                             search_width=4, refine_ratio=2.0)
        ref = np.sum((q[:, None] - x[np.asarray(i)]) ** 2, axis=2)
        np.testing.assert_allclose(np.asarray(d), ref, rtol=1e-4,
                                   atol=1e-3)


class TestBenchCpuHogMatcher:
    """bench.py pauses CPU-only background jobs during the headline
    capture (the round-4 contention lesson); the matcher must be
    token-exact — freezing a process that merely MENTIONS these names
    (an agent driver's prompt, a bash -c script) froze the whole
    session once."""

    def _matcher(self):
        import importlib.util
        import pathlib

        root = pathlib.Path(__file__).resolve().parent.parent
        spec = importlib.util.spec_from_file_location(
            "bench_mod", root / "bench.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod._is_cpu_hog

    @pytest.mark.parametrize("argv,want", [
        (["python", "-m", "raft_tpu.bench", "run", "--algos",
          "hnswlib,ivf_flat_cpu"], True),
        (["python", "-m", "raft_tpu.bench", "run",
          "--algos=ivf_flat_cpu"], True),
        # a mixed list includes raft algos that may run on the TPU
        (["python", "-m", "raft_tpu.bench", "run", "--algos",
          "raft_ivf_flat,hnswlib"], False),
        (["python", "-m", "raft_tpu.bench", "run", "--algos",
          "raft_cagra"], False),
        (["python", "-m", "pytest", "tests/"], True),
        (["/usr/bin/pytest", "-q"], True),
        (["python", "scripts/prebuild_sweep_indexes.py", "--check"],
         True),
        (["python", "scripts/tpu_prebuild_indexes.py"], True),
        # argv that only MENTIONS the names must not match
        (["bash", "-c",
          "echo pytest hnswlib prebuild_sweep_indexes.py"], False),
        (["claude", "--append-system-prompt",
          "x" * 100 + " pytest hnswlib"], False),
        (["python", "-m", "raft_tpu.bench", "run", "--dataset", "x"],
         False),
    ])
    def test_is_cpu_hog(self, argv, want):
        assert self._matcher()(argv) is want

    def test_cpu_pinned_bench_by_environ(self):
        """A raft-family sweep pinned to CPU via its own environment
        (the rehearsal launch convention) is pausable even though its
        algo list names TPU families; the same argv without the pin is
        not."""
        import importlib.util
        import pathlib

        root = pathlib.Path(__file__).resolve().parent.parent
        spec = importlib.util.spec_from_file_location(
            "bench_mod2", root / "bench.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        argv = ["python", "-m", "raft_tpu.bench", "run", "--algos",
                "raft_ivf_flat,raft_ivf_pq"]
        assert mod._is_cpu_pinned_bench(
            argv, {"JAX_PLATFORMS": "cpu"}) is True
        assert mod._is_cpu_pinned_bench(
            argv, {"JAX_PLATFORMS": "cpu",
                   "PALLAS_AXON_POOL_IPS": "10.0.0.1"}) is False
        assert mod._is_cpu_pinned_bench(argv, {}) is False
        assert mod._is_cpu_pinned_bench(
            ["python", "x.py"], {"JAX_PLATFORMS": "cpu"}) is False


class TestHnswCpuBaseline:
    """The native C++ HNSW competitor wrapper (the reference's hnswlib
    comparison role, ``cpp/bench/ann/src/hnswlib/hnswlib_wrapper.h``)."""

    def test_build_search_recall(self, dataset_dir, tmp_path):
        pytest.importorskip("ctypes")
        from raft_tpu.bench import hnsw_cpu

        if not hnsw_cpu.available():
            pytest.skip("native HNSW library could not be built")
        config = {
            "algos": [{
                "name": "hnswlib",
                "build": {"M": 8, "ef_construction": 100},
                "search": [{"ef": 10}, {"ef": 100}],
            }]
        }
        rows = run_benchmark(dataset_dir, config, tmp_path / "res",
                             k=10, search_iters=1)
        assert len(rows) == 2
        assert all(r["algo"] == "hnswlib" for r in rows)
        # higher ef -> higher recall; ef=100 on a 3000-row set is ~exact
        assert rows[1]["recall"] >= rows[0]["recall"]
        assert rows[1]["recall"] > 0.9
        assert rows[1]["qps"] > 0

    def test_index_cache_round_trip(self, dataset_dir, tmp_path):
        from raft_tpu.bench import hnsw_cpu

        if not hnsw_cpu.available():
            pytest.skip("native HNSW library could not be built")
        config = {
            "algos": [{
                "name": "hnswlib",
                "build": {"M": 8, "ef_construction": 100},
                "search": [{"ef": 50}],
            }]
        }
        r1 = run_benchmark(dataset_dir, config, tmp_path / "res",
                           k=10, search_iters=1)
        assert not r1[0]["build_cached"]
        r2 = run_benchmark(dataset_dir, config, tmp_path / "res",
                           k=10, search_iters=1)
        assert r2[0]["build_cached"]
        assert abs(r2[0]["recall"] - r1[0]["recall"]) < 1e-6

    def test_ivf_flat_cpu_cache_round_trip(self, rng_np, tmp_path):
        """Second competitor's index cache: save -> load -> identical
        search; mismatched/corrupt caches are refused (the hnsw_cpu
        contract)."""
        from raft_tpu.bench import ivf_flat_cpu
        from raft_tpu.distance.types import DistanceType

        base = rng_np.standard_normal((500, 16)).astype(np.float32)
        q = rng_np.standard_normal((20, 16)).astype(np.float32)
        idx = ivf_flat_cpu.build(base, DistanceType.L2Expanded,
                                 n_lists=16, trainset_fraction=1.0)
        d1, i1 = ivf_flat_cpu.search(idx, q, 5, n_probes=4)
        path = tmp_path / "ivf.bin"
        ivf_flat_cpu.save(idx, path)
        idx2 = ivf_flat_cpu.load(path, 16, DistanceType.L2Expanded)
        d2, i2 = ivf_flat_cpu.search(idx2, q, 5, n_probes=4)
        assert np.array_equal(i1, i2) and np.allclose(d1, d2)
        with pytest.raises(ValueError, match="dim"):
            ivf_flat_cpu.load(path, 32, DistanceType.L2Expanded)
        with pytest.raises(ValueError, match="metric"):
            ivf_flat_cpu.load(path, 16, DistanceType.InnerProduct)
        raw = bytearray(path.read_bytes())
        raw[len(raw) // 2] ^= 0xFF  # flip a byte mid-payload
        bad = tmp_path / "bad.bin"
        bad.write_bytes(bytes(raw[:len(raw) // 2]))  # truncate too
        with pytest.raises((ValueError, OSError, EOFError)):
            ivf_flat_cpu.load(bad, 16, DistanceType.L2Expanded)

    def test_load_rejects_mismatched_cache(self, rng_np, tmp_path):
        """A cache file whose recorded dim/metric differ from the
        caller's must be refused — the native side strides queries by
        the FILE's dim, so accepting it reads past the query buffer."""
        from raft_tpu.bench import hnsw_cpu
        from raft_tpu.distance.types import DistanceType

        if not hnsw_cpu.available():
            pytest.skip("native HNSW library could not be built")
        base = rng_np.standard_normal((64, 16)).astype(np.float32)
        idx = hnsw_cpu.build(base, DistanceType.L2Expanded, M=8,
                             ef_construction=50)
        path = tmp_path / "idx.bin"
        hnsw_cpu.save(idx, path)
        with pytest.raises(RuntimeError, match="dim"):
            hnsw_cpu.load(path, 32, DistanceType.L2Expanded)
        with pytest.raises(RuntimeError, match="metric"):
            hnsw_cpu.load(path, 16, DistanceType.InnerProduct)
        ok = hnsw_cpu.load(path, 16, DistanceType.L2Expanded)
        assert ok.dim == 16

    def test_load_rejects_corrupt_max_level(self, rng_np, tmp_path):
        """max_level above the entry node's level list would index past
        upper[entry] at search time; the loader must reject it."""
        from raft_tpu.bench import hnsw_cpu
        from raft_tpu.distance.types import DistanceType

        if not hnsw_cpu.available():
            pytest.skip("native HNSW library could not be built")
        base = rng_np.standard_normal((64, 16)).astype(np.float32)
        idx = hnsw_cpu.build(base, DistanceType.L2Expanded, M=8,
                             ef_construction=50)
        path = tmp_path / "idx.bin"
        hnsw_cpu.save(idx, path)
        # header: magic u32, dim i64, M i64, ef_construction i64,
        # metric i32, n i64, max_level i32 — corrupt max_level
        raw = bytearray(path.read_bytes())
        off = 4 + 8 + 8 + 8 + 4 + 8
        raw[off:off + 4] = (10 ** 6).to_bytes(4, "little")
        path.write_bytes(bytes(raw))
        with pytest.raises(RuntimeError, match="corrupt"):
            hnsw_cpu.load(path, 16, DistanceType.L2Expanded)

    def test_reference_schema_spellings(self):
        from raft_tpu.bench.runner import normalize_config

        cfg = normalize_config({
            "index": [{
                "algo": "hnswlib",
                "build_param": {"M": 12, "efConstruction": 150},
                "search_params": [{"ef": 20}],
            }]
        })
        assert cfg["algos"][0]["name"] == "hnswlib"
        assert cfg["algos"][0]["build"] == {"M": 12,
                                            "ef_construction": 150}
        assert cfg["algos"][0]["search"] == [{"ef": 20}]

    def test_two_competitor_series(self, dataset_dir, tmp_path):
        """The pareto needs a second non-raft series (the reference
        benches FAISS beside hnswlib): both competitors must produce
        rows in one sweep."""
        from raft_tpu.bench import hnsw_cpu

        algos = [{"name": "ivf_flat_cpu",
                  "build": {"n_lists": 64, "trainset_fraction": 0.5},
                  "search": [{"n_probes": 4}, {"n_probes": 64}]}]
        if hnsw_cpu.available():
            algos.append({"name": "hnswlib", "build": {"M": 8},
                          "search": [{"ef": 50}]})
        rows = run_benchmark(dataset_dir, {"algos": algos},
                             tmp_path / "res", k=10, search_iters=1)
        by_algo = {}
        for r in rows:
            by_algo.setdefault(r["algo"], []).append(r)
        ivf = by_algo["ivf_flat_cpu"]
        assert len(ivf) == 2
        # more probes -> higher recall; n_probes=64 of 64 lists = exact
        assert ivf[1]["recall"] >= ivf[0]["recall"]
        assert ivf[1]["recall"] > 0.99
        assert all(r["qps"] > 0 for r in rows)

    def test_sweep_survives_missing_toolchain(self, dataset_dir, tmp_path,
                                              monkeypatch):
        """A host without g++ must lose the hnswlib comparison series,
        not the whole sweep (the raft algos still run)."""
        from raft_tpu.bench import hnsw_cpu

        monkeypatch.setattr(hnsw_cpu, "available", lambda: False)
        config = {
            "algos": [
                {"name": "raft_brute_force", "search": [{}]},
                {"name": "hnswlib", "build": {"M": 8},
                 "search": [{"ef": 10}]},
            ]
        }
        rows = run_benchmark(dataset_dir, config, tmp_path / "res",
                             k=10, search_iters=1)
        assert [r["algo"] for r in rows] == ["raft_brute_force"]


class TestBenchCompare:
    """The CI perf-regression gate (graftscope v2): ``ci/bench_compare``
    must pass a record against itself, exit nonzero on an injected
    throughput/latency regression beyond tolerance, and floor-check the
    metrics snapshot's modeled-throughput counters."""

    @pytest.fixture(scope="class")
    def bc(self):
        import importlib.util
        import pathlib

        path = (pathlib.Path(__file__).parent.parent / "ci"
                / "bench_compare.py")
        spec = importlib.util.spec_from_file_location(
            "bench_compare", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    @pytest.fixture
    def record(self):
        return {
            "value": 1000.0,
            "serving": {
                "qps": 800.0,
                "baseline_one_per_call_qps": 400.0,
                "p99_ms": 20.0,
                "requests_per_batch": 4.0,
                "completed": 96.0,
                "backend_compiles_during_load": 22.0,
                "modeled_exec_bytes": 7e6,
                "modeled_exec_flops": 3e7,
            },
        }

    def test_identical_records_pass(self, bc, record):
        assert bc.compare(record, record) == []

    def test_injected_throughput_regression_fails(self, bc, record):
        import copy

        slow = copy.deepcopy(record)
        slow["serving"]["qps"] = record["serving"]["qps"] * 0.1
        msgs = bc.compare(record, slow)
        assert any("serving.qps" in m for m in msgs)
        # within the band: a 2x slowdown on a 0.30 min_ratio passes
        ok = copy.deepcopy(record)
        ok["serving"]["qps"] = record["serving"]["qps"] * 0.5
        assert bc.compare(record, ok) == []

    def test_injected_latency_and_compile_regressions_fail(self, bc,
                                                           record):
        import copy

        bad = copy.deepcopy(record)
        bad["serving"]["p99_ms"] = 200.0        # > 4x and > base + 50
        msgs = bc.compare(record, bad)
        assert any("p99_ms" in m for m in msgs)
        rec = copy.deepcopy(record)
        rec["serving"]["backend_compiles_during_load"] = 100.0
        msgs = bc.compare(record, rec)
        assert any("backend_compiles_during_load" in m for m in msgs)

    def test_missing_fresh_column_is_a_regression(self, bc, record):
        import copy

        gone = copy.deepcopy(record)
        del gone["serving"]["modeled_exec_bytes"]
        msgs = bc.compare(record, gone)
        assert any("modeled_exec_bytes" in m and "missing" in m
                   for m in msgs)
        # the converse — a column only the FRESH run has — is fine
        # (old baselines must not fail new code)
        extra = copy.deepcopy(record)
        del extra["serving"]["modeled_exec_bytes"]
        assert bc.compare(extra, record) == []

    def test_ragged_family_bands_gate(self, bc, record):
        """graftragged: the per-family ragged legs gate structurally —
        an executable-count or pad-waste regression in any family leg
        fails the gate; identical records pass."""
        import copy

        base = copy.deepcopy(record)
        base["serving"]["ragged_families"] = {
            "pq": {"completed": 24.0, "qps": 5.0, "p99_ms": 20.0,
                   "pad_waste_fraction": 0.01,
                   "backend_compiles_during_load": 0.0,
                   "executables": 2.0},
            "mesh": {"completed": 24.0, "qps": 15.0, "p99_ms": 10.0,
                     "pad_waste_fraction": 0.01,
                     "backend_compiles_during_load": 0.0,
                     "executables": 2.0, "shards": 4.0},
        }
        assert bc.compare(base, base) == []
        worse = copy.deepcopy(base)
        worse["serving"]["ragged_families"]["pq"]["executables"] = 5.0
        msgs = bc.compare(base, worse)
        assert any("ragged_families.pq.executables" in m for m in msgs)
        padded = copy.deepcopy(base)
        padded["serving"]["ragged_families"]["mesh"][
            "pad_waste_fraction"] = 0.2
        msgs = bc.compare(base, padded)
        assert any("ragged_families.mesh.pad_waste_fraction" in m
                   for m in msgs)
        # a lost mesh shard is a measurement regression, not noise
        fewer = copy.deepcopy(base)
        fewer["serving"]["ragged_families"]["mesh"]["shards"] = 1.0
        msgs = bc.compare(base, fewer)
        assert any("ragged_families.mesh.shards" in m for m in msgs)

    def test_snapshot_floors(self, bc):
        ok = {"counters": {"serving.execute.calls": 5.0,
                           "serving.execute.modeled_bytes": 1e6,
                           "serving.execute.modeled_flops": 1e7,
                           "index.probe.dispatches": 2.0,
                           "index.probe_freq.accounted": 64.0,
                           "profiling.captures": 1.0,
                           "incident.bundles": 1.0,
                           "profiling.rolling.folds": 2.0,
                           "fleet.scrapes": 1.0,
                           "memory.samples": 8.0,
                           "tier.swaps": 2.0,
                           "tier.swap_bytes": 1e5,
                           "fleet.route.requests": 4.0,
                           "fleet.plan.builds": 2.0}}
        assert bc.check_snapshot(ok) == []
        dark = {"counters": {"serving.execute.calls": 5.0,
                             "serving.execute.modeled_bytes": 0.0}}
        msgs = bc.check_snapshot(dark)
        assert any("modeled_bytes" in m for m in msgs)
        assert any("modeled_flops" in m and "missing" in m
                   for m in msgs)

    def test_snapshot_floors_prefer_lifetime_ledger(self, bc):
        """The floors read ``counters_lifetime`` when present: the live
        ``counters`` view only holds what ran after the session's LAST
        ``reset_counters()`` — ordering-dependent — while the lifetime
        ledger accumulates across resets (conftest writes both)."""
        snap = {
            "counters": {},  # a late test reset the live registry
            "counters_lifetime": {
                "serving.execute.calls": 5.0,
                "serving.execute.modeled_bytes": 1e6,
                "serving.execute.modeled_flops": 1e7,
                "index.probe.dispatches": 2.0,
                "index.probe_freq.accounted": 64.0,
                "profiling.captures": 2.0,
                "incident.bundles": 1.0,
                "profiling.rolling.folds": 2.0,
                "fleet.scrapes": 1.0,
            "memory.samples": 8.0,
                "tier.swaps": 2.0,
                "tier.swap_bytes": 1e5,
                "fleet.route.requests": 4.0,
                "fleet.plan.builds": 2.0,
            },
        }
        assert bc.check_snapshot(snap) == []

    def test_main_exits_nonzero_on_injected_regression(self, bc, record,
                                                       tmp_path):
        """End-to-end through ``main()``: the gate's exit code is the
        CI contract — 0 within bands, 1 on regression."""
        import copy

        baseline = {"record": record,
                    "tolerances": bc.DEFAULT_TOLERANCES,
                    "snapshot_floors": bc.SNAPSHOT_FLOORS}
        bpath = tmp_path / "baseline.json"
        bpath.write_text(json.dumps(baseline))
        good = tmp_path / "fresh_ok.json"
        good.write_text(json.dumps(record))
        assert bc.main(["--baseline", str(bpath),
                        "--fresh", str(good)]) == 0
        slow = copy.deepcopy(record)
        slow["serving"]["qps"] = 1.0
        bad = tmp_path / "fresh_bad.json"
        bad.write_text(json.dumps(slow))
        assert bc.main(["--baseline", str(bpath),
                        "--fresh", str(bad)]) == 1
        # missing baseline without --update is a usage error, not a pass
        assert bc.main(["--baseline", str(tmp_path / "absent.json"),
                        "--fresh", str(good)]) == 2

    def test_update_writes_baseline(self, bc, record, tmp_path):
        bpath = tmp_path / "baseline.json"
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(record))
        assert bc.main(["--baseline", str(bpath), "--fresh", str(fresh),
                        "--update"]) == 0
        out = json.loads(bpath.read_text())
        assert out["record"] == record
        assert out["tolerances"] == bc.DEFAULT_TOLERANCES
        # and the freshly written baseline gates against itself
        assert bc.main(["--baseline", str(bpath),
                        "--fresh", str(fresh)]) == 0

    # -- PR 8: multi-baseline support + probe-accounting floors -------------

    def test_snapshot_floors_include_probe_accounting(self, bc):
        """graftgauge satellite: the gate floor-checks the device-side
        probe-frequency ledger — a refactor that disconnects the
        scatter-add (or the scrape fetch) zeroes these and fails."""
        assert "index.probe_freq.accounted" in bc.SNAPSHOT_FLOORS
        assert "index.probe.dispatches" in bc.SNAPSHOT_FLOORS
        dark = {"counters_lifetime": {
            "serving.execute.calls": 5.0,
            "serving.execute.modeled_bytes": 1e6,
            "serving.execute.modeled_flops": 1e7,
            "index.probe.dispatches": 3.0,
            "index.probe_freq.accounted": 0.0,     # went dark
            "profiling.captures": 1.0,
            "incident.bundles": 1.0,
            "profiling.rolling.folds": 2.0,
            "fleet.scrapes": 1.0,
            "memory.samples": 8.0,
            "tier.swaps": 2.0,
            "tier.swap_bytes": 1e5,
            "fleet.route.requests": 4.0,
            "fleet.plan.builds": 2.0,
        }}
        msgs = bc.check_snapshot(dark)
        assert any("index.probe_freq.accounted" in m for m in msgs)
        dark["counters_lifetime"]["index.probe_freq.accounted"] = 96.0
        assert bc.check_snapshot(dark) == []

    # -- PR 11: graftflight ingestion / incident-capture floors -------------

    def test_snapshot_floors_include_graftflight(self, bc):
        """graftflight satellite: the gate floor-checks trace
        ingestion and incident capture — a refactor that disconnects
        the parser pipeline or the flight-recorder triggers zeroes
        these and fails structurally."""
        assert "profiling.captures" in bc.SNAPSHOT_FLOORS
        assert "incident.bundles" in bc.SNAPSHOT_FLOORS
        dark = {"counters_lifetime": {
            "serving.execute.calls": 5.0,
            "serving.execute.modeled_bytes": 1e6,
            "serving.execute.modeled_flops": 1e7,
            "index.probe.dispatches": 3.0,
            "index.probe_freq.accounted": 96.0,
            "profiling.captures": 0.0,             # ingestion dark
            "incident.bundles": 1.0,
            "profiling.rolling.folds": 2.0,
            "fleet.scrapes": 1.0,
            "memory.samples": 8.0,
            "tier.swaps": 2.0,
            "tier.swap_bytes": 1e5,
            "fleet.route.requests": 4.0,
            "fleet.plan.builds": 2.0,
        }}
        msgs = bc.check_snapshot(dark)
        assert any("profiling.captures" in m for m in msgs)
        dark["counters_lifetime"]["profiling.captures"] = 3.0
        assert bc.check_snapshot(dark) == []
        # the committed baseline carries the new floors too
        import os

        base_path = os.path.join(os.path.dirname(bc.__file__),
                                 "bench_baseline.json")
        with open(base_path) as f:
            committed = json.load(f)
        assert "profiling.captures" in committed["snapshot_floors"]
        assert "incident.bundles" in committed["snapshot_floors"]

    # -- PR 12: graftfleet rolling-attribution / federation floors ----------

    def test_snapshot_floors_include_graftfleet(self, bc):
        """graftfleet satellite: the gate floor-checks the
        continuous-capture -> rolling-EWMA pipeline and the
        federation scrape loop — disconnecting either zeroes these
        and fails structurally — and carries the tight
        continuous-overhead tolerance bands."""
        assert "profiling.rolling.folds" in bc.SNAPSHOT_FLOORS
        assert "fleet.scrapes" in bc.SNAPSHOT_FLOORS
        dark = {"counters_lifetime": {
            "serving.execute.calls": 5.0,
            "serving.execute.modeled_bytes": 1e6,
            "serving.execute.modeled_flops": 1e7,
            "index.probe.dispatches": 3.0,
            "index.probe_freq.accounted": 96.0,
            "profiling.captures": 1.0,
            "incident.bundles": 1.0,
            "profiling.rolling.folds": 0.0,        # rolling dark
            "fleet.scrapes": 1.0,
            "memory.samples": 8.0,
            "tier.swaps": 2.0,
            "tier.swap_bytes": 1e5,
            "fleet.route.requests": 4.0,
            "fleet.plan.builds": 2.0,
        }}
        msgs = bc.check_snapshot(dark)
        assert any("profiling.rolling.folds" in m for m in msgs)
        dark["counters_lifetime"]["profiling.rolling.folds"] = 4.0
        assert bc.check_snapshot(dark) == []
        # the continuous-capture overhead bands are gated, ratio tight
        assert bc.DEFAULT_TOLERANCES[
            "serving.continuous.p99_ratio"] == {"max_increase": 1.0}
        assert "serving.continuous.capture_attempts" in \
            bc.DEFAULT_TOLERANCES
        import os

        base_path = os.path.join(os.path.dirname(bc.__file__),
                                 "bench_baseline.json")
        with open(base_path) as f:
            committed = json.load(f)
        assert "profiling.rolling.folds" in committed["snapshot_floors"]
        assert "fleet.scrapes" in committed["snapshot_floors"]

    # -- PR 13: graftledger watermark floor ---------------------------------

    def test_snapshot_floors_include_graftledger(self, bc):
        """graftledger satellite: the gate floor-checks the
        dispatch-time watermark heartbeat — a refactor that
        disconnects ``MemoryLedger.sample_dispatch()`` from the
        executor's dispatch core zeroes this and fails
        structurally."""
        assert "memory.samples" in bc.SNAPSHOT_FLOORS
        dark = {"counters_lifetime": {
            "serving.execute.calls": 5.0,
            "serving.execute.modeled_bytes": 1e6,
            "serving.execute.modeled_flops": 1e7,
            "index.probe.dispatches": 3.0,
            "index.probe_freq.accounted": 96.0,
            "profiling.captures": 1.0,
            "incident.bundles": 1.0,
            "profiling.rolling.folds": 2.0,
            "fleet.scrapes": 1.0,
            "memory.samples": 0.0,                 # watermark dark
            "tier.swaps": 2.0,
            "tier.swap_bytes": 1e5,
            "fleet.route.requests": 4.0,
            "fleet.plan.builds": 2.0,
        }}
        msgs = bc.check_snapshot(dark)
        assert any("memory.samples" in m for m in msgs)
        dark["counters_lifetime"]["memory.samples"] = 8.0
        assert bc.check_snapshot(dark) == []
        import os

        base_path = os.path.join(os.path.dirname(bc.__file__),
                                 "bench_baseline.json")
        with open(base_path) as f:
            committed = json.load(f)
        assert "memory.samples" in committed["snapshot_floors"]

    # -- PR 14: grafttier swap floor + tiered tolerance bands ---------------

    def test_snapshot_floors_include_grafttier(self, bc):
        """grafttier satellite: the gate floor-checks the placement
        swap executor — a refactor that disconnects apply_plan's
        block swaps (or their byte accounting) zeroes these and
        fails structurally — and carries the tight tiered bands."""
        assert "tier.swaps" in bc.SNAPSHOT_FLOORS
        assert "tier.swap_bytes" in bc.SNAPSHOT_FLOORS
        dark = {"counters_lifetime": {
            "serving.execute.calls": 5.0,
            "serving.execute.modeled_bytes": 1e6,
            "serving.execute.modeled_flops": 1e7,
            "index.probe.dispatches": 3.0,
            "index.probe_freq.accounted": 96.0,
            "profiling.captures": 1.0,
            "incident.bundles": 1.0,
            "profiling.rolling.folds": 2.0,
            "fleet.scrapes": 1.0,
            "memory.samples": 8.0,
            "tier.swaps": 0.0,                     # swaps dark
            "tier.swap_bytes": 1e5,
            "fleet.route.requests": 4.0,
            "fleet.plan.builds": 2.0,
        }}
        msgs = bc.check_snapshot(dark)
        assert any("tier.swaps" in m for m in msgs)
        dark["counters_lifetime"]["tier.swaps"] = 2.0
        assert bc.check_snapshot(dark) == []
        # the correctness + zero-recompile columns are gated TIGHT
        assert bc.DEFAULT_TOLERANCES["tiered.bit_identical"] == \
            {"min_ratio": 1.0}
        assert bc.DEFAULT_TOLERANCES[
            "tiered.compiles_during_epochs"] == {"max_increase": 0}
        assert "tiered.swap_bytes_total" in bc.DEFAULT_TOLERANCES
        import os

        base_path = os.path.join(os.path.dirname(bc.__file__),
                                 "bench_baseline.json")
        with open(base_path) as f:
            committed = json.load(f)
        assert "tier.swaps" in committed["snapshot_floors"]
        # the committed baseline's tiered record holds the contract
        # values the bands pin against
        tiered = committed["record"]["tiered"]
        assert tiered["bit_identical"] == 1
        assert tiered["compiles_during_epochs"] == 0

    def test_multi_baseline_gates_each(self, bc, record, tmp_path):
        import copy

        b1 = tmp_path / "bench_baseline.json"
        b2 = tmp_path / "bench_baseline_other.json"
        b1.write_text(json.dumps({"record": record}))
        tight = copy.deepcopy(record)
        tight["serving"]["qps"] = record["serving"]["qps"] * 4
        b2.write_text(json.dumps({"record": tight}))
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(record))
        # passes against itself, fails against the tighter second
        assert bc.main(["--baseline", str(b1),
                        "--fresh", str(fresh)]) == 0
        assert bc.main(["--baseline", str(b1), "--baseline", str(b2),
                        "--fresh", str(fresh)]) == 1

    def test_requires_backend_skips_when_absent(self, bc, record,
                                                tmp_path, capsys):
        import copy

        impossible = copy.deepcopy(record)
        impossible["serving"]["qps"] = record["serving"]["qps"] * 100
        tpu = tmp_path / "bench_baseline_tpu.json"
        tpu.write_text(json.dumps({"record": impossible,
                                   "requires_backend": "tpu"}))
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(record))
        # the (on CPU CI, unmeetable) TPU baseline is skipped with a
        # note instead of failing the gate
        assert bc.main(["--baseline", str(tpu),
                        "--fresh", str(fresh)]) == 0
        assert "SKIP" in capsys.readouterr().out
        cpu_spelled = tmp_path / "bench_baseline_cpu.json"
        cpu_spelled.write_text(json.dumps({"record": record,
                                           "requires_backend": "cpu"}))
        # a baseline whose backend IS present gates normally
        assert bc.main(["--baseline", str(cpu_spelled),
                        "--fresh", str(fresh)]) == 0

    def test_update_rejects_multiple_baselines(self, bc, record,
                                               tmp_path):
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(record))
        assert bc.main(["--baseline", str(tmp_path / "a.json"),
                        "--baseline", str(tmp_path / "b.json"),
                        "--fresh", str(fresh), "--update"]) == 2

    def test_default_baselines_glob(self, bc):
        """With no --baseline the gate picks up every committed
        ci/bench_baseline*.json — how a recorded TPU baseline joins
        CI without touching test.sh."""
        import os

        paths = bc.default_baselines()
        assert any(p.endswith("bench_baseline.json") for p in paths)
        assert all(os.path.basename(p).startswith("bench_baseline")
                   for p in paths)
