"""k-means tests vs sklearn-style expectations (analog of
cpp/test/cluster/kmeans*.cu, test_kmeans.py)."""

import numpy as np
import pytest

from raft_tpu import random as rrandom
from raft_tpu.cluster import kmeans, kmeans_balanced
from raft_tpu.cluster import KMeansParams, KMeansBalancedParams, InitMethod
from raft_tpu.distance.types import DistanceType


@pytest.fixture(scope="module")
def blobs():
    x, labels, centers = rrandom.make_blobs(
        rrandom.RngState(0), 2000, 10, n_clusters=5, cluster_std=0.5
    )
    return np.asarray(x), np.asarray(labels), np.asarray(centers)


class TestKMeans:
    def test_fit_recovers_blobs(self, blobs):
        x, true_labels, true_centers = blobs
        params = KMeansParams(n_clusters=5, max_iter=100, seed=0)
        centroids, inertia, n_iter = kmeans.fit(None, params, x)
        centroids = np.asarray(centroids)
        # every true center should have a learned centroid nearby
        d = ((true_centers[:, None, :] - centroids[None]) ** 2).sum(-1)
        assert d.min(axis=1).max() < 1.0
        assert float(inertia) < 2000 * 10 * 0.5**2 * 2.5
        assert int(n_iter) >= 1

    def test_random_init(self, blobs):
        x, _, _ = blobs
        params = KMeansParams(n_clusters=5, max_iter=100, init=InitMethod.Random, seed=3)
        centroids, inertia, _ = kmeans.fit(None, params, x)
        assert np.isfinite(np.asarray(centroids)).all()

    def test_array_init(self, blobs):
        x, _, true_centers = blobs
        params = KMeansParams(n_clusters=5, max_iter=50, init=InitMethod.Array)
        centroids, _, n_iter = kmeans.fit(None, params, x, init_centroids=true_centers)
        assert int(n_iter) <= 10  # should converge almost instantly

    def test_predict_consistent(self, blobs):
        x, true_labels, _ = blobs
        params = KMeansParams(n_clusters=5, max_iter=100, seed=0)
        centroids, _, _ = kmeans.fit(None, params, x)
        labels, _ = kmeans.predict(None, params, centroids, x)
        labels = np.asarray(labels)
        # cluster assignment should match blob structure up to permutation:
        # points sharing a true label share a predicted label
        from scipy.stats import mode
        agree = 0
        for c in range(5):
            sel = true_labels == c
            agree += (labels[sel] == mode(labels[sel]).mode).sum()
        assert agree / len(labels) > 0.95

    def test_transform_shape(self, blobs):
        x, _, _ = blobs
        params = KMeansParams(n_clusters=5)
        centroids, _, _ = kmeans.fit(None, params, x)
        t = kmeans.transform(None, params, centroids, x)
        assert t.shape == (2000, 5)

    def test_cluster_cost_matches_inertia(self, blobs):
        x, _, _ = blobs
        params = KMeansParams(n_clusters=5, max_iter=100, seed=0)
        centroids, inertia, _ = kmeans.fit(None, params, x)
        cost = kmeans.cluster_cost(None, centroids, x)
        np.testing.assert_allclose(float(cost), float(inertia), rtol=1e-3)

    def test_find_k(self):
        x, _, _ = rrandom.make_blobs(rrandom.RngState(1), 300, 4, n_clusters=3,
                                     cluster_std=0.2)
        best_k, _ = kmeans.find_k(None, np.asarray(x), k_max=6, k_min=2, max_iter=50)
        assert best_k == 3


class TestKMeansBalanced:
    def test_fit_quality_and_balance(self, blobs):
        x, _, _ = blobs
        params = KMeansBalancedParams(n_iters=20, seed=0)
        centers, labels, sizes = kmeans_balanced.build_clusters(None, params, x, 8)
        sizes = np.asarray(sizes)
        assert sizes.sum() == len(x)
        # balancing: no cluster should be tiny
        assert sizes.min() > 0.25 * len(x) / 8 * 0.5

    def test_predict(self, blobs):
        x, _, _ = blobs
        params = KMeansBalancedParams(n_iters=10, seed=0)
        centers = kmeans_balanced.fit(None, params, x, 6)
        labels = np.asarray(kmeans_balanced.predict(None, params, centers, x))
        d = ((x[:, None, :] - np.asarray(centers)[None]) ** 2).sum(-1)
        np.testing.assert_array_equal(labels, d.argmin(1))

    def test_inner_product_metric(self, blobs):
        x, _, _ = blobs
        xn = x / np.linalg.norm(x, axis=1, keepdims=True)
        params = KMeansBalancedParams(n_iters=10, seed=0,
                                      metric=DistanceType.InnerProduct)
        centers = np.asarray(kmeans_balanced.fit(None, params, xn, 4))
        # centers stay normalized for IP metric
        np.testing.assert_allclose(np.linalg.norm(centers, axis=1), 1.0, atol=1e-3)

    def test_calc_centers_and_sizes(self, rng_np):
        x = rng_np.standard_normal((50, 3)).astype(np.float32)
        labels = rng_np.integers(0, 4, 50).astype(np.int32)
        centers, sizes = kmeans_balanced.calc_centers_and_sizes(x, labels, 4)
        for c in range(4):
            if (labels == c).any():
                np.testing.assert_allclose(
                    np.asarray(centers)[c], x[labels == c].mean(0), rtol=1e-4, atol=1e-4
                )


class TestUpdateCentroids:
    def test_one_m_step(self, rng_np):
        from raft_tpu.cluster.kmeans import update_centroids

        x = rng_np.standard_normal((500, 8)).astype(np.float32)
        c0 = x[:4]
        new, labels = update_centroids(None, x, c0)
        labels = np.asarray(labels)
        ref = np.stack([
            x[labels == j].mean(0) if (labels == j).any() else np.asarray(c0[j])
            for j in range(4)
        ])
        np.testing.assert_allclose(np.asarray(new), ref, rtol=1e-5, atol=1e-5)

    def test_weighted(self, rng_np):
        from raft_tpu.cluster.kmeans import update_centroids

        x = rng_np.standard_normal((200, 4)).astype(np.float32)
        w = rng_np.uniform(0.1, 2.0, 200).astype(np.float32)
        c0 = x[:3]
        new, labels = update_centroids(None, x, c0, sample_weights=w)
        labels = np.asarray(labels)
        for j in range(3):
            m = labels == j
            if m.any():
                ref = (x[m] * w[m, None]).sum(0) / w[m].sum()
                np.testing.assert_allclose(np.asarray(new[j]), ref,
                                           rtol=1e-4, atol=1e-4)
