"""stats + label tests — sklearn/scipy cross-checks, the reference's
``python/pylibraft/pylibraft/test`` pattern (SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np
import pytest
import sklearn.metrics as skm

from raft_tpu import label, stats
from raft_tpu.stats.metrics import ICType


class TestSummary:
    def test_mean_var_stddev(self, rng_np, res):
        x = rng_np.standard_normal((50, 7)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(stats.mean(res, x)), x.mean(axis=0), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(stats.var(res, x)), x.var(axis=0, ddof=1), rtol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(stats.stddev(res, x)), x.std(axis=0, ddof=1), rtol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(stats.sum_stat(res, x, along_rows=True)),
            x.sum(axis=1),
            rtol=1e-4,
        )

    def test_cov(self, rng_np, res):
        x = rng_np.standard_normal((100, 5)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(stats.cov(res, x)), np.cov(x, rowvar=False), rtol=1e-3, atol=1e-4
        )

    def test_mean_center(self, rng_np, res):
        x = rng_np.standard_normal((20, 4)).astype(np.float32)
        out = np.asarray(stats.mean_center(res, x))
        np.testing.assert_allclose(out.mean(axis=0), 0.0, atol=1e-5)

    def test_histogram(self, rng_np, res):
        x = rng_np.uniform(0, 1, (200, 3)).astype(np.float32)
        h = np.asarray(stats.histogram(res, x, 10, lo=0.0, hi=1.0))
        assert h.shape == (10, 3)
        np.testing.assert_array_equal(h.sum(axis=0), 200)
        for c in range(3):
            want, _ = np.histogram(x[:, c], bins=10, range=(0, 1))
            np.testing.assert_array_equal(h[:, c], want)

    def test_minmax(self, rng_np, res):
        x = rng_np.standard_normal((30, 4)).astype(np.float32)
        mn, mx = stats.minmax(res, x)
        np.testing.assert_allclose(np.asarray(mn), x.min(axis=0))
        np.testing.assert_allclose(np.asarray(mx), x.max(axis=0))

    def test_weighted_mean(self, rng_np, res):
        x = rng_np.standard_normal((12, 6)).astype(np.float32)
        w = rng_np.uniform(0.1, 1.0, 6).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(stats.weighted_mean(res, x, w, along_rows=True)),
            (x * w).sum(axis=1) / w.sum(),
            rtol=1e-5,
        )
        w2 = rng_np.uniform(0.1, 1.0, 12).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(stats.weighted_mean(res, x, w2, along_rows=False)),
            (x * w2[:, None]).sum(axis=0) / w2.sum(),
            rtol=1e-5,
        )


class TestRegressionMetrics:
    def test_accuracy(self, rng_np, res):
        y = rng_np.integers(0, 3, 100)
        p = y.copy()
        p[:25] = (p[:25] + 1) % 3
        np.testing.assert_allclose(np.asarray(stats.accuracy(res, p, y)), 0.75)

    def test_r2(self, rng_np, res):
        y = rng_np.standard_normal(80).astype(np.float32)
        yh = y + 0.1 * rng_np.standard_normal(80).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(stats.r2_score(res, y, yh)),
            skm.r2_score(y, yh),
            rtol=1e-3,
        )


class TestClusteringMetrics:
    @pytest.fixture
    def two_labelings(self, rng_np):
        a = rng_np.integers(0, 4, 300)
        b = a.copy()
        flip = rng_np.random(300) < 0.2
        b[flip] = rng_np.integers(0, 4, int(flip.sum()))
        return a, b

    def test_contingency(self, two_labelings, res):
        a, b = two_labelings
        cm = np.asarray(stats.contingency_matrix(res, jnp.asarray(a), jnp.asarray(b)))
        want = skm.cluster.contingency_matrix(a, b)
        np.testing.assert_array_equal(cm, want)

    def test_rand_index(self, two_labelings, res):
        a, b = two_labelings
        # sklearn's rand_score is the same unadjusted RI
        np.testing.assert_allclose(
            np.asarray(stats.rand_index(res, jnp.asarray(a), jnp.asarray(b))),
            skm.rand_score(a, b),
            rtol=1e-5,
        )

    def test_adjusted_rand_index(self, two_labelings, res):
        a, b = two_labelings
        np.testing.assert_allclose(
            np.asarray(stats.adjusted_rand_index(res, jnp.asarray(a), jnp.asarray(b))),
            skm.adjusted_rand_score(a, b),
            rtol=1e-4,
        )

    def test_mutual_info(self, two_labelings, res):
        a, b = two_labelings
        np.testing.assert_allclose(
            np.asarray(stats.mutual_info_score(res, jnp.asarray(a), jnp.asarray(b))),
            skm.mutual_info_score(a, b),
            rtol=1e-4,
        )

    def test_homogeneity_completeness_v_measure(self, two_labelings, res):
        a, b = two_labelings
        h, c, v = skm.homogeneity_completeness_v_measure(a, b)
        np.testing.assert_allclose(
            np.asarray(stats.homogeneity_score(res, jnp.asarray(a), jnp.asarray(b))),
            h,
            rtol=1e-3,
        )
        np.testing.assert_allclose(
            np.asarray(stats.completeness_score(res, jnp.asarray(a), jnp.asarray(b))),
            c,
            rtol=1e-3,
        )
        np.testing.assert_allclose(
            np.asarray(stats.v_measure(res, jnp.asarray(a), jnp.asarray(b))),
            v,
            rtol=1e-3,
        )

    def test_entropy(self, res):
        labels = jnp.asarray([0, 0, 1, 1])
        np.testing.assert_allclose(
            np.asarray(stats.entropy(res, labels, 2)), np.log(2), rtol=1e-5
        )

    def test_kl(self, res):
        p = jnp.asarray([0.5, 0.5])
        q = jnp.asarray([0.9, 0.1])
        want = 0.5 * np.log(0.5 / 0.9) + 0.5 * np.log(0.5 / 0.1)
        np.testing.assert_allclose(np.asarray(stats.kl_divergence(res, p, q)), want, rtol=1e-5)

    def test_silhouette(self, rng_np, res):
        from sklearn.datasets import make_blobs

        x, y = make_blobs(n_samples=200, centers=4, n_features=8, random_state=0)
        x = x.astype(np.float32)
        got = np.asarray(stats.silhouette_score(res, x, jnp.asarray(y)))
        want = skm.silhouette_score(x, y)
        np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-3)

    def test_silhouette_batched_matches(self, rng_np, res):
        from sklearn.datasets import make_blobs

        x, y = make_blobs(n_samples=150, centers=3, n_features=5, random_state=1)
        x = x.astype(np.float32)
        full = np.asarray(stats.silhouette_score(res, x, jnp.asarray(y)))
        tiled = np.asarray(stats.silhouette_score(res, x, jnp.asarray(y), tile=37))
        np.testing.assert_allclose(tiled, full, rtol=1e-5)

    def test_trustworthiness(self, rng_np, res):
        from sklearn.manifold import trustworthiness as sk_trust

        x = rng_np.standard_normal((100, 10)).astype(np.float32)
        xe = x[:, :2] + 0.01 * rng_np.standard_normal((100, 2)).astype(np.float32)
        got = np.asarray(stats.trustworthiness(res, x, xe, 5))
        want = sk_trust(x, xe, n_neighbors=5)
        np.testing.assert_allclose(got, want, rtol=1e-2)

    def test_information_criterion(self, res):
        ll = jnp.asarray([-100.0, -200.0])
        aic = np.asarray(stats.information_criterion(res, ll, ICType.AIC, 3, 50))
        np.testing.assert_allclose(aic, [206.0, 406.0])
        bic = np.asarray(stats.information_criterion(res, ll, ICType.BIC, 3, 50))
        np.testing.assert_allclose(bic, -2 * np.asarray(ll) + 3 * np.log(50), rtol=1e-6)

    def test_dispersion(self, res):
        centroids = jnp.asarray([[0.0, 0.0], [2.0, 0.0]])
        sizes = jnp.asarray([10, 10])
        # global centroid (1,0); each center at distance 1 → sqrt(20)
        np.testing.assert_allclose(
            np.asarray(stats.dispersion(res, centroids, sizes)),
            np.sqrt(20.0),
            rtol=1e-5,
        )


class TestLabel:
    def test_unique_and_monotonic(self, res):
        labels = jnp.asarray([10, 20, 10, 99, 20])
        u = np.asarray(label.get_unique_labels(res, labels))
        np.testing.assert_array_equal(u, [10, 20, 99])
        m = np.asarray(label.make_monotonic(res, labels))
        np.testing.assert_array_equal(m, [0, 1, 0, 2, 1])

    def test_ovr(self, res):
        labels = jnp.asarray([1, 2, 1, 3])
        np.testing.assert_array_equal(
            np.asarray(label.ovr_labels(res, labels, 1)), [1, 0, 1, 0]
        )

    def test_merge_labels(self, res):
        # two batches of connected components: rows 0-2 labeled {0,0,2} in a,
        # rows 2-4 share group in b → all five rows should collapse to min
        la = jnp.asarray([0, 0, 2, 3, 3])
        lb = jnp.asarray([0, 1, 1, 1, 2])  # b links rows 1,2,3 together
        merged = np.asarray(label.merge_labels(res, la, lb))
        # rows 1,2,3 share b-group → min label 0 (via row1's a-label 0);
        # row 0 shares a-label with row 1 → 0; row 4 shares a-label 3 with row 3
        assert merged[0] == merged[1] == merged[2] == merged[3]
        # row 4 linked to row 3 only through a-label 3; merge_labels merges
        # via b-groups, a-continuity handled by chasing
        assert merged.min() == 0


class TestMeanVarRegression:
    def test_meanvar_matches_separate(self, rng_np):
        from raft_tpu import stats

        x = rng_np.standard_normal((50, 6)).astype(np.float32)
        mu, v = stats.meanvar(None, x)
        np.testing.assert_allclose(np.asarray(mu), x.mean(0), rtol=1e-5,
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(v), x.var(0, ddof=1),
                                   rtol=1e-4, atol=1e-5)

    def test_regression_metrics(self, rng_np):
        from raft_tpu import stats

        p = rng_np.standard_normal(64).astype(np.float32)
        r = rng_np.standard_normal(64).astype(np.float32)
        mae, mse, med = stats.regression_metrics(None, p, r)
        np.testing.assert_allclose(float(mae), np.abs(p - r).mean(),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(mse), ((p - r) ** 2).mean(),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(med), np.median(np.abs(p - r)),
                                   rtol=1e-5)

    def test_trustworthiness_alias(self):
        from raft_tpu import stats

        assert stats.trustworthiness_score is stats.trustworthiness
