"""Distance tests vs scipy references (analog of cpp/test/distance/*)."""

import numpy as np
import pytest
import scipy.spatial.distance as spd

from raft_tpu.distance import (
    DistanceType,
    KernelParams,
    KernelType,
    fused_l2_nn_argmin,
    gram_matrix,
    is_min_close,
    pairwise_distance,
    pairwise_distance_tiled,
)

M, N, D = 33, 47, 16


def _data(rng_np, positive=False, binary=False, d=D):
    x = rng_np.standard_normal((M, d)).astype(np.float32)
    y = rng_np.standard_normal((N, d)).astype(np.float32)
    if positive:
        x, y = np.abs(x) + 0.01, np.abs(y) + 0.01
    if binary:
        x, y = (x > 0).astype(np.float32), (y > 0).astype(np.float32)
    return x, y


SCIPY_METRICS = [
    (DistanceType.L2SqrtExpanded, "euclidean", {}, False, False),
    (DistanceType.L2Expanded, "sqeuclidean", {}, False, False),
    (DistanceType.L2SqrtUnexpanded, "euclidean", {}, False, False),
    (DistanceType.L2Unexpanded, "sqeuclidean", {}, False, False),
    (DistanceType.CosineExpanded, "cosine", {}, False, False),
    (DistanceType.L1, "cityblock", {}, False, False),
    (DistanceType.Linf, "chebyshev", {}, False, False),
    (DistanceType.Canberra, "canberra", {}, False, False),
    (DistanceType.CorrelationExpanded, "correlation", {}, False, False),
    (DistanceType.BrayCurtis, "braycurtis", {}, True, False),
    (DistanceType.JensenShannon, "jensenshannon", {}, True, False),
    (DistanceType.LpUnexpanded, "minkowski", {"p": 3.0}, False, False),
    (DistanceType.HammingUnexpanded, "hamming", {}, False, True),
    (DistanceType.RusselRaoExpanded, "russellrao", {}, False, True),
    (DistanceType.DiceExpanded, "dice", {}, False, True),
]


@pytest.mark.parametrize("metric,scipy_name,kwargs,positive,binary", SCIPY_METRICS)
def test_vs_scipy(rng_np, metric, scipy_name, kwargs, positive, binary):
    x, y = _data(rng_np, positive=positive, binary=binary)
    if metric == DistanceType.JensenShannon:
        # scipy normalizes to probability vectors internally; the reference
        # formula assumes already-normalized inputs
        x /= x.sum(1, keepdims=True)
        y /= y.sum(1, keepdims=True)
    got = np.asarray(
        pairwise_distance(None, x, y, metric, metric_arg=kwargs.get("p", 2.0))
    )
    want = spd.cdist(x.astype(np.float64), y.astype(np.float64), scipy_name, **kwargs)
    atol = 2e-3 if "sq" in scipy_name or metric == DistanceType.L2Expanded else 1e-3
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=atol)


def test_inner_product(rng_np):
    x, y = _data(rng_np)
    got = np.asarray(pairwise_distance(None, x, y, DistanceType.InnerProduct))
    np.testing.assert_allclose(got, x @ y.T, rtol=1e-4, atol=1e-4)
    assert not is_min_close(DistanceType.InnerProduct)
    assert is_min_close(DistanceType.L2Expanded)


def test_hellinger(rng_np):
    x, y = _data(rng_np, positive=True)
    # normalize to probability vectors
    x /= x.sum(1, keepdims=True)
    y /= y.sum(1, keepdims=True)
    got = np.asarray(pairwise_distance(None, x, y, DistanceType.HellingerExpanded))
    ip = np.sqrt(x) @ np.sqrt(y).T
    want = np.sqrt(np.maximum(1.0 - ip, 0.0))
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_kl_divergence(rng_np):
    x, y = _data(rng_np, positive=True)
    x /= x.sum(1, keepdims=True)
    y /= y.sum(1, keepdims=True)
    got = np.asarray(pairwise_distance(None, x, y, DistanceType.KLDivergence))
    want = np.array(
        [[np.sum(xi * (np.log(xi) - np.log(yj))) for yj in y] for xi in x]
    )
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_jaccard(rng_np):
    x, y = _data(rng_np, binary=True)
    got = np.asarray(pairwise_distance(None, x, y, DistanceType.JaccardExpanded))
    ip = x @ y.T
    denom = (x**2).sum(1)[:, None] + (y**2).sum(1)[None, :] - ip
    want = 1.0 - np.divide(ip, denom, out=np.zeros_like(ip), where=denom != 0)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_haversine(rng_np):
    x = rng_np.uniform(-1.0, 1.0, (10, 2)).astype(np.float32)
    y = rng_np.uniform(-1.0, 1.0, (12, 2)).astype(np.float32)
    got = np.asarray(pairwise_distance(None, x, y, DistanceType.Haversine))

    def hav(a, b):
        s1 = np.sin(0.5 * (a[0] - b[0])) ** 2
        s2 = np.sin(0.5 * (a[1] - b[1])) ** 2
        return 2 * np.arcsin(np.sqrt(s1 + np.cos(a[0]) * np.cos(b[0]) * s2))

    want = np.array([[hav(a, b) for b in y] for a in x])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_tiled_matches_full(rng_np):
    x = rng_np.standard_normal((300, 8)).astype(np.float32)
    y = rng_np.standard_normal((50, 8)).astype(np.float32)
    full = np.asarray(pairwise_distance(None, x, y, DistanceType.L2Expanded))
    tiled = np.asarray(
        pairwise_distance_tiled(None, x, y, DistanceType.L2Expanded, row_tile=128)
    )
    np.testing.assert_allclose(full, tiled, rtol=1e-5, atol=1e-5)


def test_self_distance_zero_diag(rng_np):
    x, _ = _data(rng_np)
    d = np.asarray(pairwise_distance(None, x, x, DistanceType.L2Expanded))
    np.testing.assert_allclose(np.diag(d), 0.0, atol=1e-3)


class TestFusedL2NN:
    def test_matches_bruteforce(self, rng_np):
        x = rng_np.standard_normal((100, 12)).astype(np.float32)
        y = rng_np.standard_normal((37, 12)).astype(np.float32)
        dist, idx = fused_l2_nn_argmin(None, x, y, tile=16)
        full = spd.cdist(x, y, "sqeuclidean")
        # tie tolerance (as in reference ann_utils.cuh eval_neighbours):
        # the distance at the chosen index must equal the true min
        chosen = full[np.arange(len(x)), np.asarray(idx)]
        np.testing.assert_allclose(chosen, full.min(1), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(dist), full.min(1), rtol=1e-3, atol=1e-3)

    def test_sqrt(self, rng_np):
        x = rng_np.standard_normal((20, 4)).astype(np.float32)
        y = rng_np.standard_normal((8, 4)).astype(np.float32)
        dist, _ = fused_l2_nn_argmin(None, x, y, sqrt=True)
        full = spd.cdist(x, y, "euclidean")
        np.testing.assert_allclose(np.asarray(dist), full.min(1), rtol=1e-3, atol=1e-3)


class TestGram:
    def test_linear(self, rng_np):
        x, y = _data(rng_np)
        k = np.asarray(gram_matrix(None, x, y, KernelParams(KernelType.LINEAR)))
        np.testing.assert_allclose(k, x @ y.T, rtol=1e-4, atol=1e-4)

    def test_rbf(self, rng_np):
        x, y = _data(rng_np)
        gamma = 0.5
        k = np.asarray(gram_matrix(None, x, y, KernelParams(KernelType.RBF, gamma=gamma)))
        want = np.exp(-gamma * spd.cdist(x, y, "sqeuclidean"))
        np.testing.assert_allclose(k, want, rtol=1e-3, atol=1e-3)

    def test_poly(self, rng_np):
        x, y = _data(rng_np)
        p = KernelParams(KernelType.POLYNOMIAL, degree=2, gamma=0.1, coef0=1.0)
        k = np.asarray(gram_matrix(None, x, y, p))
        np.testing.assert_allclose(k, (0.1 * (x @ y.T) + 1.0) ** 2, rtol=1e-3, atol=1e-3)

    def test_tanh(self, rng_np):
        x, y = _data(rng_np)
        p = KernelParams(KernelType.TANH, gamma=0.01, coef0=0.5)
        k = np.asarray(gram_matrix(None, x, y, p))
        np.testing.assert_allclose(k, np.tanh(0.01 * (x @ y.T) + 0.5), rtol=1e-3, atol=1e-3)
