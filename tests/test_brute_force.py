"""Brute-force kNN tests (analog of cpp/test/neighbors/knn.cu +
tiled_knn.cu): exact match vs numpy ground truth, tiling invariance,
merge_parts, serialization."""


import numpy as np
import pytest
import scipy.spatial.distance as spd

from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import brute_force
from raft_tpu.utils import eval_neighbours


def _groundtruth(x, q, k, metric="sqeuclidean"):
    d = spd.cdist(q, x, metric)
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=1), idx


class TestBruteForce:
    @pytest.mark.parametrize("metric", [DistanceType.L2Expanded,
                                        DistanceType.L2SqrtExpanded])
    def test_exact_recall_l2(self, rng_np, metric):
        x = rng_np.standard_normal((500, 16)).astype(np.float32)
        q = rng_np.standard_normal((40, 16)).astype(np.float32)
        dist, idx = brute_force.knn(None, x, q, 10, metric=metric)
        scipy_metric = "sqeuclidean" if metric == DistanceType.L2Expanded else "euclidean"
        gt_d, gt_i = _groundtruth(x, q, 10, scipy_metric)
        recall = eval_neighbours(gt_i, np.asarray(idx), gt_d, np.asarray(dist),
                                 min_recall=0.99)
        assert recall >= 0.99
        np.testing.assert_allclose(np.asarray(dist), gt_d, rtol=1e-3, atol=1e-3)

    def test_inner_product_direction(self, rng_np):
        x = rng_np.standard_normal((200, 8)).astype(np.float32)
        q = rng_np.standard_normal((10, 8)).astype(np.float32)
        dist, idx = brute_force.knn(None, x, q, 5, metric=DistanceType.InnerProduct)
        sims = q @ x.T
        gt_i = np.argsort(-sims, axis=1)[:, :5]
        gt_d = np.take_along_axis(sims, gt_i, axis=1)
        # descending similarities
        assert (np.diff(np.asarray(dist), axis=1) <= 1e-5).all()
        eval_neighbours(gt_i, np.asarray(idx), gt_d, np.asarray(dist), min_recall=0.99)

    def test_cosine(self, rng_np):
        x = rng_np.standard_normal((300, 12)).astype(np.float32)
        q = rng_np.standard_normal((20, 12)).astype(np.float32)
        dist, idx = brute_force.knn(None, x, q, 8, metric=DistanceType.CosineExpanded)
        gt_d, gt_i = _groundtruth(x, q, 8, "cosine")
        eval_neighbours(gt_i, np.asarray(idx), gt_d, np.asarray(dist), min_recall=0.98)

    def test_tiling_invariance(self, rng_np):
        """Small db_tile must give identical results to one big tile."""
        x = rng_np.standard_normal((1000, 8)).astype(np.float32)
        q = rng_np.standard_normal((16, 8)).astype(np.float32)
        index = brute_force.build(None, x)
        d1, i1 = brute_force.search(None, index, q, 10, db_tile=64)
        d2, i2 = brute_force.search(None, index, q, 10, db_tile=100000)
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-5, atol=1e-5)

    def test_query_tiling(self, rng_np):
        x = rng_np.standard_normal((100, 8)).astype(np.float32)
        q = rng_np.standard_normal((50, 8)).astype(np.float32)
        index = brute_force.build(None, x)
        d1, i1 = brute_force.search(None, index, q, 5, query_tile=7)
        d2, i2 = brute_force.search(None, index, q, 5, query_tile=1000)
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_k_one(self, rng_np):
        x = rng_np.standard_normal((64, 4)).astype(np.float32)
        q = x[:5] + 1e-4  # queries near known rows
        dist, idx = brute_force.knn(None, x, q, 1)
        np.testing.assert_array_equal(np.asarray(idx)[:, 0], np.arange(5))

    def test_merge_parts(self, rng_np):
        x = rng_np.standard_normal((400, 8)).astype(np.float32)
        q = rng_np.standard_normal((12, 8)).astype(np.float32)
        # shard database in two, search each, merge
        parts_d, parts_i = [], []
        for shard, offset in ((x[:200], 0), (x[200:], 200)):
            d, i = brute_force.knn(None, shard, q, 6)
            parts_d.append(np.asarray(d))
            parts_i.append(np.asarray(i) + offset)
        md, mi = brute_force.knn_merge_parts(np.stack(parts_d), np.stack(parts_i))
        gt_d, gt_i = _groundtruth(x, q, 6)
        eval_neighbours(gt_i, np.asarray(mi), gt_d, np.asarray(md), min_recall=0.99)

    def test_serialization_roundtrip(self, rng_np, tmp_path):
        x = rng_np.standard_normal((50, 6)).astype(np.float32)
        index = brute_force.build(None, x, metric=DistanceType.CosineExpanded)
        path = str(tmp_path / "bf.bin")
        brute_force.save(index, path)
        loaded = brute_force.load(None, path)
        assert loaded.metric == DistanceType.CosineExpanded
        np.testing.assert_array_equal(np.asarray(loaded.dataset), x)
        q = rng_np.standard_normal((4, 6)).astype(np.float32)
        d1, i1 = brute_force.search(None, index, q, 3)
        d2, i2 = brute_force.search(None, loaded, q, 3)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


class TestBf16Storage:
    """Half-width dataset storage (the reference's fp16 dataset analog)."""

    def test_bf16_recall_and_dtype(self, rng_np):
        import jax.numpy as jnp

        from raft_tpu.neighbors import brute_force

        x = rng_np.standard_normal((3000, 32)).astype(np.float32)
        q = rng_np.standard_normal((16, 32)).astype(np.float32)
        index = brute_force.build(None, x, storage_dtype=jnp.bfloat16)
        assert index.dataset.dtype == jnp.bfloat16
        d, i = brute_force.search(None, index, q, 10)
        # vs exact fp32 ground truth: bf16 quantization may flip rare
        # near-ties only
        d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        gt = np.argsort(d2, axis=1, kind="stable")[:, :10]
        overlap = np.mean([
            len(set(gt[r]) & set(np.asarray(i)[r])) / 10
            for r in range(len(q))
        ])
        assert overlap >= 0.97, overlap
        # distances approximately exact
        ref = np.take_along_axis(d2, np.asarray(i), axis=1)
        np.testing.assert_allclose(np.asarray(d), ref, rtol=0.03, atol=0.5)


class TestApproxScan:
    def test_approx_overlaps_exact(self, rng_np):
        from raft_tpu.neighbors import brute_force

        x = rng_np.standard_normal((5000, 32)).astype(np.float32)
        q = rng_np.standard_normal((16, 32)).astype(np.float32)
        index = brute_force.build(None, x)
        _, i1 = brute_force.search(None, index, q, 10)
        _, i2 = brute_force.search(None, index, q, 10, approx=True)
        overlap = np.mean([
            len(set(np.asarray(i1)[r]) & set(np.asarray(i2)[r])) / 10
            for r in range(len(q))
        ])
        assert overlap >= 0.9, overlap
