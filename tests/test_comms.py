"""Comms + distributed tests — reference pattern
(raft_dask/test/test_comms.py: LocalCUDACluster standing in for a real
cluster; here the 8-virtual-CPU-device mesh): per-collective validation
(cpp comms_test.hpp analogs), distributed kmeans vs single-device,
distributed kNN vs single-device, index-per-shard ANN recall."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tpu.comms import Comms, Op, local_comms
from raft_tpu.comms.comms import (
    allgather,
    allreduce,
    alltoall,
    barrier,
    bcast,
    device_send,
    rank,
    reducescatter,
)
from raft_tpu.distance.types import DistanceType
from raft_tpu.distributed import (
    brute_force_knn,
    build_sharded,
    kmeans_fit,
)
from raft_tpu.neighbors import brute_force, ivf_flat
from raft_tpu.utils import eval_recall


@pytest.fixture(scope="module")
def comms():
    return local_comms()


N_DEV = 8


class TestCollectives:
    """Analog of test_collectives (raft_dask test_comms.py:220) — each
    collective validated against its definition."""

    def _shard(self, comms, x):
        return jax.device_put(jnp.asarray(x), comms.row_sharded())

    def test_allreduce_sum(self, comms):
        x = np.arange(N_DEV, dtype=np.float32)
        out = comms.run(lambda v: allreduce(v, Op.SUM, comms.axis),
                        self._shard(comms, x),
                        in_specs=P(comms.axis), out_specs=P(comms.axis))
        np.testing.assert_allclose(np.asarray(out), x.sum())

    @pytest.mark.parametrize("op,ref", [(Op.MAX, np.max), (Op.MIN, np.min),
                                        (Op.PROD, np.prod)])
    def test_allreduce_ops(self, comms, op, ref):
        x = np.arange(1, N_DEV + 1, dtype=np.float32)
        out = comms.run(lambda v: allreduce(v, op, comms.axis),
                        self._shard(comms, x),
                        in_specs=P(comms.axis), out_specs=P(comms.axis))
        np.testing.assert_allclose(np.asarray(out), ref(x))

    @pytest.mark.parametrize("root", [0, 3, 7])
    def test_bcast(self, comms, root):
        x = np.arange(N_DEV, dtype=np.float32) + 5
        out = comms.run(lambda v: bcast(v, root, comms.axis),
                        self._shard(comms, x),
                        in_specs=P(comms.axis), out_specs=P(comms.axis))
        np.testing.assert_allclose(np.asarray(out), x[root])

    def test_allgather(self, comms):
        x = np.arange(N_DEV, dtype=np.float32)
        out = comms.run(lambda v: allgather(v, comms.axis),
                        self._shard(comms, x),
                        in_specs=P(comms.axis),
                        out_specs=P(comms.axis, None))
        # each rank's local output is the stacked (8, 1) gather; the
        # sharded global view concatenates them to (64, 1)
        got = np.asarray(out).reshape(N_DEV, N_DEV)
        np.testing.assert_allclose(got, np.broadcast_to(x, (N_DEV, N_DEV)))

    def test_reducescatter(self, comms):
        # each rank contributes (8,) → each rank gets one summed element
        x = np.tile(np.arange(N_DEV, dtype=np.float32), N_DEV)
        out = comms.run(lambda v: reducescatter(v, Op.SUM, comms.axis),
                        self._shard(comms, x),
                        in_specs=P(comms.axis), out_specs=P(comms.axis))
        np.testing.assert_allclose(np.asarray(out),
                                   np.arange(N_DEV, dtype=np.float32) * N_DEV)

    def test_ledger_one_bump_per_call(self, comms):
        """Delegating veneers (reduce → allreduce body, non-SUM
        reducescatter, device_recv → ring permute, the quantized
        collectives → gather/alltoall bodies) must bump the trace-time
        collective ledger exactly once, under their OWN family — a
        scrape reading comms.* must not see one logical collective
        double-counted (graftscope v2 wire-cost ledger)."""
        from raft_tpu.comms.comms import (
            allreduce_quantized,
            device_recv,
            reduce,
            reducescatter_quantized,
        )
        from raft_tpu.core import tracing

        x = np.tile(np.arange(N_DEV, dtype=np.float32), N_DEV)
        fams = ("reducescatter", "allreduce", "reduce", "device_send",
                "device_recv", "allreduce_quantized",
                "reducescatter_quantized", "allgather", "alltoall")
        before = {k: tracing.get_counter(f"comms.{k}.calls")
                  for k in fams}
        comms.run(lambda v: reducescatter(v, Op.MAX, comms.axis),
                  self._shard(comms, x),
                  in_specs=P(comms.axis), out_specs=P(comms.axis))
        comms.run(lambda v: reduce(v, 0, Op.SUM, comms.axis),
                  self._shard(comms, x),
                  in_specs=P(comms.axis), out_specs=P(comms.axis))
        comms.run(lambda v: device_recv(v, 1, comms.axis),
                  self._shard(comms, np.arange(N_DEV, dtype=np.float32)),
                  in_specs=P(comms.axis), out_specs=P(comms.axis))
        # int8 wires route through the uncounted all_gather/alltoall
        # bodies — only the quantized family may bump
        m = x.reshape(N_DEV * N_DEV, 1)
        comms.run(lambda v: allreduce_quantized(
                      v, Op.SUM, comms.axis, wire_dtype="int8"),
                  self._shard(comms, m),
                  in_specs=P(comms.axis, None),
                  out_specs=P(comms.axis, None))
        comms.run(lambda v: reducescatter_quantized(
                      v, Op.SUM, comms.axis, wire_dtype="int8"),
                  self._shard(comms, m),
                  in_specs=P(comms.axis, None),
                  out_specs=P(comms.axis, None))
        delta = {k: tracing.get_counter(f"comms.{k}.calls") - before[k]
                 for k in before}
        assert delta == {"reducescatter": 1.0, "allreduce": 0.0,
                         "reduce": 1.0, "device_send": 0.0,
                         "device_recv": 1.0, "allreduce_quantized": 1.0,
                         "reducescatter_quantized": 1.0,
                         "allgather": 0.0, "alltoall": 0.0}

    def test_alltoall(self, comms):
        # rank r holds rows [r*8, (r+1)*8); after alltoall rank r holds
        # block r of every rank
        x = np.arange(N_DEV * N_DEV, dtype=np.float32)
        out = comms.run(lambda v: alltoall(v, comms.axis),
                        self._shard(comms, x),
                        in_specs=P(comms.axis), out_specs=P(comms.axis))
        got = np.asarray(out).reshape(N_DEV, N_DEV)
        want = np.arange(N_DEV * N_DEV).reshape(N_DEV, N_DEV).T
        np.testing.assert_allclose(got, want)

    def test_p2p_ring(self, comms):
        """test_pointToPoint_simple_send_recv analog."""
        x = np.arange(N_DEV, dtype=np.float32)
        out = comms.run(lambda v: device_send(v, 1, comms.axis),
                        self._shard(comms, x),
                        in_specs=P(comms.axis), out_specs=P(comms.axis))
        np.testing.assert_allclose(np.asarray(out), np.roll(x, 1))

    def test_barrier_and_rank(self, comms):
        out = comms.run(
            lambda v: v + barrier(comms.axis) + rank(comms.axis),
            self._shard(comms, np.zeros(N_DEV, np.int32)),
            in_specs=P(comms.axis), out_specs=P(comms.axis))
        np.testing.assert_array_equal(np.asarray(out),
                                      N_DEV + np.arange(N_DEV))

    def test_selftests(self, comms):
        assert comms.test_allreduce()
        assert comms.test_bcast()
        assert comms.test_pointToPoint_simple_send_recv()

    def test_split_2d(self):
        c = local_comms(axis_names=("row", "col"), shape=(4, 2))
        assert c.size == 4
        sub = c.split("col")
        assert sub.size == 2
        with pytest.raises(ValueError):
            c.split("nope")


class TestQuantizedCollectives:
    """graftwire: the EQuARX-style quantized reducing collectives —
    block-wise scales on the wire, ONE dequantized f32 epilog (never
    per-hop accumulation in the narrow dtype), integer payloads always
    exact int32."""

    def _run(self, comms, fn, x):
        return np.asarray(comms.run(
            fn, jax.device_put(jnp.asarray(x), comms.sharding("data",
                                                              None)),
            in_specs=P("data", None), out_specs=P("data", None)))

    @pytest.fixture(scope="class")
    def payload(self):
        rng = np.random.default_rng(7)
        # mixed magnitudes so per-block scales matter: column blocks
        # at very different dynamic ranges
        x = rng.standard_normal((N_DEV * 16, 300)).astype(np.float32)
        x[:, 128:256] *= 100.0
        return x

    @pytest.mark.parametrize("wire,tol", [
        ("f32", 0.0), ("bf16", 5e-3), ("int8", 2e-2)])
    def test_allreduce_sum_close(self, comms, payload, wire, tol):
        from raft_tpu.comms.comms import allreduce_quantized

        got = self._run(comms, lambda v: allreduce_quantized(
            v, Op.SUM, "data", wire_dtype=wire), payload)
        ref = payload.reshape(N_DEV, -1, 300).sum(axis=0)
        ref_full = np.tile(ref, (N_DEV, 1))
        scale = np.abs(ref).max()
        if wire == "f32":
            np.testing.assert_array_equal(got, ref_full)
        else:
            assert np.abs(got - ref_full).max() / scale <= tol

    def test_allreduce_integer_exact(self, comms, payload):
        """Counts and other integer payloads NEVER quantize — the wire
        is exact int32 whatever wire_dtype asks for."""
        from raft_tpu.comms.comms import allreduce_quantized

        xi = (payload * 10).astype(np.int32)
        got = self._run(comms, lambda v: allreduce_quantized(
            v, Op.SUM, "data", wire_dtype="int8"), xi)
        ref = np.tile(xi.reshape(N_DEV, -1, 300).sum(axis=0),
                      (N_DEV, 1))
        np.testing.assert_array_equal(got, ref)
        assert got.dtype == np.int32

    def test_narrow_non_sum_raises(self, comms, payload):
        from raft_tpu.comms.comms import allreduce_quantized

        with pytest.raises(ValueError, match="SUM"):
            self._run(comms, lambda v: allreduce_quantized(
                v, Op.MAX, "data", wire_dtype="int8"), payload)

    def test_bad_wire_dtype_raises(self, comms, payload):
        from raft_tpu.comms.comms import allreduce_quantized

        with pytest.raises(ValueError, match="wire_dtype"):
            self._run(comms, lambda v: allreduce_quantized(
                v, Op.SUM, "data", wire_dtype="f16"), payload)

    @pytest.mark.parametrize("wire,tol", [
        ("f32", 0.0), ("bf16", 5e-3), ("int8", 2e-2)])
    def test_reducescatter_sum_close(self, comms, payload, wire, tol):
        from raft_tpu.comms.comms import reducescatter_quantized

        got = self._run(comms, lambda v: reducescatter_quantized(
            v, Op.SUM, "data", wire_dtype=wire), payload)
        ref = payload.reshape(N_DEV, -1, 300).sum(axis=0)
        scale = np.abs(ref).max()
        if wire == "f32":
            np.testing.assert_array_equal(got, ref)
        else:
            assert np.abs(got - ref).max() / scale <= tol

    def test_reducescatter_max(self, comms, payload):
        from raft_tpu.comms.comms import reducescatter_quantized

        got = self._run(comms, lambda v: reducescatter_quantized(
            v, Op.MAX, "data", wire_dtype="f32"), payload)
        ref = payload.reshape(N_DEV, -1, 300).max(axis=0)
        np.testing.assert_array_equal(got, ref)

    def test_fold_hook(self, comms, payload):
        """The ``fold`` epilog receives the stacked per-source blocks
        — the 2-D scatter-merge's entry point (it folds a top-k merge
        instead of a sum)."""
        from raft_tpu.comms.comms import reducescatter_quantized

        got = self._run(comms, lambda v: reducescatter_quantized(
            v, axis="data", wire_dtype="f32",
            fold=lambda stack: jnp.min(stack, axis=0)), payload)
        ref = payload.reshape(N_DEV, -1, 300).min(axis=0)
        np.testing.assert_array_equal(got, ref)


class TestDistributedKMeans:
    @pytest.mark.parametrize("wire", ["bf16", "int8", "auto"])
    def test_quantized_wire_converges(self, rng_np, wire):
        """Acceptance (graftwire): the quantized centroid-sum wire
        converges to an inertia within a pinned tolerance of the f32
        EM on >= 4 shards, and the modeled per-iteration bytes order
        int8 < bf16 < f32."""
        from raft_tpu.comms import Comms
        from raft_tpu.comms.bootstrap import make_mesh
        from raft_tpu.distributed import kmeans as dkm

        comms4 = Comms(make_mesh(("data",),
                                 devices=jax.devices()[:4]), "data")
        centers_true = rng_np.standard_normal((16, 48)) * 6
        x = (centers_true[rng_np.integers(0, 16, 4096)]
             + rng_np.standard_normal((4096, 48))).astype(np.float32)
        _, in_f32 = dkm.fit(comms4, x, 16, n_iters=12, wire_dtype="f32")
        _, in_q = dkm.fit(comms4, x, 16, n_iters=12, wire_dtype=wire)
        assert float(in_q) <= float(in_f32) * 1.02, (wire, float(in_q),
                                                     float(in_f32))

    def test_payload_model_and_auto(self):
        from raft_tpu.distributed import kmeans as dkm

        models = {wd: dkm.collective_payload_model(64, 96, wd)
                  for wd in ("f32", "bf16", "int8")}
        # counts always ride the exact int32 wire
        assert all(m["counts_bytes"] == 64 * 4 for m in models.values())
        # int8 pays one f32 scale per 128-feature block per centroid
        assert models["int8"]["sums_bytes"] == 64 * 96 + 64 * 4
        assert (models["int8"]["iter_bytes"]
                < models["bf16"]["iter_bytes"]
                < models["f32"]["iter_bytes"])
        assert dkm.resolve_kmeans_wire("auto", 64, 96) == "int8"
        with pytest.raises(ValueError, match="wire_dtype"):
            dkm.resolve_kmeans_wire("f16", 64, 96)

    def test_params_carry_wire_dtype(self, rng_np):
        """KMeansParams.wire_dtype is the opt-in surface: a params
        object with a narrow wire serves the same fit as the keyword."""
        from raft_tpu.cluster.kmeans import KMeansParams
        from raft_tpu.distributed import kmeans as dkm

        comms = local_comms()
        x = rng_np.standard_normal((1024, 32)).astype(np.float32)
        _, i_kw = dkm.fit(comms, x, 8, n_iters=5, wire_dtype="int8")
        _, i_p = dkm.fit(comms, x, 8, n_iters=5,
                         params=KMeansParams(wire_dtype="int8"))
        assert float(i_kw) == float(i_p)

    def test_matches_global_clustering(self, rng_np):
        comms = local_comms()
        centers_true = rng_np.standard_normal((8, 16)) * 6
        x = (centers_true[rng_np.integers(0, 8, 4096)]
             + rng_np.standard_normal((4096, 16))).astype(np.float32)
        centers, inertia = kmeans_fit(comms, x, 8, n_iters=15)
        assert centers.shape == (8, 16)
        # noise floor: E[inertia] ≈ n * d * std² = 4096*16
        assert float(inertia) < 4096 * 16 * 1.3
        # every true center recovered
        d = np.linalg.norm(
            np.asarray(centers)[:, None, :] - centers_true[None], axis=2)
        assert (d.min(axis=0) < 1.0).sum() >= 7


class TestDistributedKnn:
    def test_matches_single_device(self, rng_np):
        comms = local_comms()
        x = rng_np.standard_normal((2048, 32)).astype(np.float32)
        q = rng_np.standard_normal((16, 32)).astype(np.float32)
        d_dist, i_dist = brute_force_knn(comms, x, q, 10)
        d_ref, i_ref = brute_force.knn(None, x, q, 10)
        np.testing.assert_array_equal(np.asarray(i_dist), np.asarray(i_ref))
        np.testing.assert_allclose(np.asarray(d_dist), np.asarray(d_ref),
                                   rtol=1e-4, atol=1e-4)

    def test_inner_product(self, rng_np):
        comms = local_comms()
        x = rng_np.standard_normal((1024, 16)).astype(np.float32)
        q = rng_np.standard_normal((8, 16)).astype(np.float32)
        d_dist, i_dist = brute_force_knn(comms, x, q, 5,
                                         metric=DistanceType.InnerProduct)
        _, i_ref = brute_force.knn(None, x, q, 5,
                                   metric=DistanceType.InnerProduct)
        np.testing.assert_array_equal(np.asarray(i_dist), np.asarray(i_ref))

    def test_ring_matches_single_device(self, rng_np):
        """Ring-pass variant: sharded queries circulate via ppermute;
        results must equal single-device brute force."""
        from raft_tpu.distributed import brute_force_knn_ring

        comms = local_comms()
        x = rng_np.standard_normal((2048, 32)).astype(np.float32)
        q = rng_np.standard_normal((64, 32)).astype(np.float32)
        d_dist, i_dist = brute_force_knn_ring(comms, x, q, 10)
        d_ref, i_ref = brute_force.knn(None, x, q, 10)
        np.testing.assert_array_equal(np.asarray(i_dist), np.asarray(i_ref))
        np.testing.assert_allclose(np.asarray(d_dist), np.asarray(d_ref),
                                   rtol=1e-4, atol=1e-4)

    def test_ring_inner_product(self, rng_np):
        from raft_tpu.distributed import brute_force_knn_ring

        comms = local_comms()
        x = rng_np.standard_normal((1024, 16)).astype(np.float32)
        q = rng_np.standard_normal((32, 16)).astype(np.float32)
        _, i_dist = brute_force_knn_ring(comms, x, q, 5,
                                         metric=DistanceType.InnerProduct)
        _, i_ref = brute_force.knn(None, x, q, 5,
                                   metric=DistanceType.InnerProduct)
        np.testing.assert_array_equal(np.asarray(i_dist), np.asarray(i_ref))


class TestShardedAnn:
    def test_ivf_flat_shards(self, rng_np):
        centers = rng_np.standard_normal((10, 24)) * 5
        x = (centers[rng_np.integers(0, 10, 4000)]
             + rng_np.standard_normal((4000, 24))).astype(np.float32)
        q = (centers[rng_np.integers(0, 10, 24)]
             + rng_np.standard_normal((24, 24))).astype(np.float32)

        def build_fn(res, part):
            params = ivf_flat.IvfFlatIndexParams(n_lists=8, kmeans_n_iters=8)
            return ivf_flat.build(res, params, part)

        def search_fn(res, index, queries, k):
            sp = ivf_flat.IvfFlatSearchParams(n_probes=8)
            return ivf_flat.search(res, sp, index, queries, k)

        sharded = build_sharded(None, build_fn, search_fn, x, n_shards=4)
        assert sharded.n_shards == 4
        d, i = sharded.search(None, q, 10)
        _, gt_i = brute_force.knn(None, x, q, 10)
        r, _, _ = eval_recall(np.asarray(gt_i), np.asarray(i))
        assert r >= 0.95, f"sharded recall {r}"
        # merged distances ascending
        assert np.all(np.diff(np.asarray(d), axis=1) >= -1e-4)

    def test_ivf_bq_shards(self, rng_np):
        """The 1-bit index composes with the index-per-shard pattern
        (shard-local over-fetch + global merge, then exact refine)."""
        from raft_tpu.neighbors import ivf_bq
        from raft_tpu.neighbors.refine import refine

        centers = rng_np.standard_normal((10, 32)) * 5
        x = (centers[rng_np.integers(0, 10, 4000)]
             + rng_np.standard_normal((4000, 32))).astype(np.float32)
        q = (centers[rng_np.integers(0, 10, 24)]
             + rng_np.standard_normal((24, 32))).astype(np.float32)

        def build_fn(res, part):
            return ivf_bq.build(
                res, ivf_bq.IvfBqIndexParams(n_lists=8), part)

        def search_fn(res, index, queries, k):
            return ivf_bq.search(
                res, ivf_bq.IvfBqSearchParams(n_probes=8), index,
                queries, k)

        sharded = build_sharded(None, build_fn, search_fn, x, n_shards=4)
        # each shard's fused scan re-ranks on-chip, so the cross-shard
        # merge exchanges EXACT distances — the bound-derived budget
        # collapses to k and the retired hand constant 240 (estimate
        # noise was per-shard-center dependent) is gone; pin derived
        # <= retired at a recall target above what 240 measured (0.95)
        budget = max(ivf_bq.overfetch_budget(s, 10)
                     for s in sharded.shards)
        assert budget <= 240, budget
        _, cand = sharded.search(None, q, budget)
        _, i = refine(None, x, q, cand, 10)
        _, gt_i = brute_force.knn(None, x, q, 10)
        r, _, _ = eval_recall(np.asarray(gt_i), np.asarray(i))
        assert r >= 0.95, f"sharded bq recall {r}"


class TestDistributedIvfFlat:
    """SPMD list-sharded IVF: recall vs exact, parity with the
    single-device index at matched probe budget."""

    def test_recall_vs_exact(self, comms, rng_np):
        from raft_tpu.distributed import ivf_flat as dist_ivf
        from raft_tpu.neighbors.ivf_flat import (
            IvfFlatIndexParams,
            IvfFlatSearchParams,
        )

        x = rng_np.standard_normal((4096, 32)).astype(np.float32)
        q = rng_np.standard_normal((32, 32)).astype(np.float32)
        params = IvfFlatIndexParams(n_lists=64)
        index = dist_ivf.build(None, comms, params, x)
        assert index.n_lists % comms.size == 0
        assert index.size == 4096

        d, i = dist_ivf.search(None, IvfFlatSearchParams(n_probes=32),
                               index, q, 10)
        assert d.shape == (32, 10) and i.shape == (32, 10)
        # approximate local mode still close
        _, i_loc = dist_ivf.search(None, IvfFlatSearchParams(n_probes=32),
                                   index, q, 10, probe_mode="local")
        # exact ground truth
        d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        gt = np.argsort(d2, axis=1, kind="stable")[:, :10]
        # recall floor tracks the single-chip index (bit-identity is
        # asserted by tests/test_distributed_serving.py); balanced
        # kmeans varies slightly across jax versions, so leave margin
        r, _, _ = eval_recall(gt, np.asarray(i))
        assert r >= 0.93, r
        r_loc, _, _ = eval_recall(gt, np.asarray(i_loc))
        assert r_loc >= 0.85, r_loc
        # distances ascending + exact for returned ids
        dn = np.asarray(d)
        assert (np.diff(dn, axis=1) >= -1e-3).all()
        ref = np.take_along_axis(d2, np.asarray(i), axis=1)
        np.testing.assert_allclose(dn, ref, rtol=1e-3, atol=1e-2)

    def test_full_probe_parity_with_exact(self, comms, rng_np):
        """Probing every list must equal brute force exactly."""
        from raft_tpu.distributed import ivf_flat as dist_ivf
        from raft_tpu.neighbors.ivf_flat import (
            IvfFlatIndexParams,
            IvfFlatSearchParams,
        )

        x = rng_np.standard_normal((1024, 16)).astype(np.float32)
        q = rng_np.standard_normal((8, 16)).astype(np.float32)
        index = dist_ivf.build(None, comms, IvfFlatIndexParams(n_lists=16),
                               x)
        d, i = dist_ivf.search(None, IvfFlatSearchParams(n_probes=16),
                               index, q, 5)
        d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        gt = np.argsort(d2, axis=1, kind="stable")[:, :5]
        assert np.array_equal(np.asarray(i), gt)


class TestDistributedIvfPq:
    def test_recall(self, comms, rng_np):
        from raft_tpu.distributed import ivf as dist_ivf
        from raft_tpu.neighbors.ivf_pq import (
            IvfPqIndexParams,
            IvfPqSearchParams,
        )

        x = rng_np.standard_normal((4096, 32)).astype(np.float32)
        q = rng_np.standard_normal((32, 32)).astype(np.float32)
        index = dist_ivf.build_pq(
            None, comms, IvfPqIndexParams(n_lists=32, pq_dim=16), x)
        assert index.size == 4096
        d, i = dist_ivf.search_pq(
            None, IvfPqSearchParams(n_probes=32), index, q, 10)
        d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        gt = np.argsort(d2, axis=1, kind="stable")[:, :10]
        r, _, _ = eval_recall(gt, np.asarray(i))
        # full probes, 8x compression: PQ approximation bounds recall
        assert r >= 0.55, r

        # parity with the single-device PQ index at identical settings:
        # same recall ballpark (codebooks differ only by list permutation)
        from raft_tpu.neighbors import ivf_pq as sd
        si = sd.build(None, IvfPqIndexParams(n_lists=32, pq_dim=16), x)
        _, i2 = sd.search(None, IvfPqSearchParams(n_probes=32), si, q, 10)
        r2, _, _ = eval_recall(gt, np.asarray(i2))
        assert abs(r - r2) < 0.1, (r, r2)

    def test_per_cluster_codebooks(self, comms, rng_np):
        """PER_CLUSTER codebooks shard with the lists they describe; the
        distributed result must track the single-device per-cluster index."""
        from raft_tpu.distributed import ivf as dist_ivf
        from raft_tpu.neighbors import ivf_pq as sd
        from raft_tpu.neighbors.ivf_pq import (
            CodebookKind,
            IvfPqIndexParams,
            IvfPqSearchParams,
        )

        x = rng_np.standard_normal((4096, 32)).astype(np.float32)
        q = rng_np.standard_normal((32, 32)).astype(np.float32)
        params = IvfPqIndexParams(
            n_lists=32, pq_dim=16, codebook_kind=CodebookKind.PER_CLUSTER)
        index = dist_ivf.build_pq(None, comms, params, x)
        assert index.codebook_kind == CodebookKind.PER_CLUSTER
        assert index.codebooks.shape[0] == index.n_lists
        d, i = dist_ivf.search_pq(
            None, IvfPqSearchParams(n_probes=32), index, q, 10)
        d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        gt = np.argsort(d2, axis=1, kind="stable")[:, :10]
        r, _, _ = eval_recall(gt, np.asarray(i))
        assert r >= 0.55, r
        si = sd.build(None, params, x)
        _, i2 = sd.search(None, IvfPqSearchParams(n_probes=32), si, q, 10)
        r2, _, _ = eval_recall(gt, np.asarray(i2))
        assert abs(r - r2) < 0.1, (r, r2)
        # onehot MXU scoring agrees with the gather path
        d3, i3 = dist_ivf.search_pq(
            None, IvfPqSearchParams(n_probes=32, score_mode="onehot"),
            index, q, 10)
        r3, _, _ = eval_recall(np.asarray(i), np.asarray(i3))
        assert r3 >= 0.9, r3

    def test_local_mode_and_refine(self, comms, rng_np):
        from raft_tpu.distributed import ivf as dist_ivf
        from raft_tpu.neighbors import refine
        from raft_tpu.neighbors.ivf_pq import (
            IvfPqIndexParams,
            IvfPqSearchParams,
        )

        x = rng_np.standard_normal((4096, 32)).astype(np.float32)
        q = rng_np.standard_normal((16, 32)).astype(np.float32)
        index = dist_ivf.build_pq(
            None, comms, IvfPqIndexParams(n_lists=32, pq_dim=16), x)
        _, cand = dist_ivf.search_pq(
            None, IvfPqSearchParams(n_probes=32), index, q, 40,
            probe_mode="local")
        # distributed PQ + exact refine: the production recipe
        _, i = refine(None, x, q, cand, 10)
        d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        gt = np.argsort(d2, axis=1, kind="stable")[:, :10]
        r, _, _ = eval_recall(gt, np.asarray(i))
        assert r >= 0.9, r


class TestDistributedCoarseAlgo:
    def test_approx_coarse_close_to_exact(self, comms, rng_np):
        """coarse_algo plumbs through the distributed searches (was
        silently ignored — ADVICE r2): 'approx' routes the probe top-k
        through approx_max_k and must stay close to exact; invalid
        values fail loudly."""
        import pytest as _pytest

        from raft_tpu.core.validation import RaftError
        from raft_tpu.distributed import ivf as dist_ivf
        from raft_tpu.neighbors.ivf_flat import (
            IvfFlatIndexParams,
            IvfFlatSearchParams,
        )
        from raft_tpu.utils import eval_recall

        x = rng_np.standard_normal((4096, 32)).astype(np.float32)
        q = rng_np.standard_normal((16, 32)).astype(np.float32)
        index = dist_ivf.build(None, comms,
                               IvfFlatIndexParams(n_lists=32), x)
        _, i_exact = dist_ivf.search(
            None, IvfFlatSearchParams(n_probes=16), index, q, 10)
        _, i_approx = dist_ivf.search(
            None, IvfFlatSearchParams(n_probes=16, coarse_algo="approx"),
            index, q, 10)
        r, _, _ = eval_recall(np.asarray(i_exact), np.asarray(i_approx))
        assert r >= 0.9, r
        with _pytest.raises(RaftError, match="coarse_algo"):
            dist_ivf.search(None,
                            IvfFlatSearchParams(coarse_algo="bogus"),
                            index, q, 10)


class TestDistributedStreamingBuild:
    def test_streamed_equals_exact_at_full_probes(self, comms, rng_np,
                                                  tmp_path):
        from raft_tpu.distributed import ivf as dist_ivf
        from raft_tpu.io import BinDataset, write_bin
        from raft_tpu.neighbors.ivf_flat import (
            IvfFlatIndexParams,
            IvfFlatSearchParams,
        )

        x = rng_np.standard_normal((2048, 16)).astype(np.float32)
        q = rng_np.standard_normal((8, 16)).astype(np.float32)
        write_bin(tmp_path / "d.fbin", x)
        with BinDataset(tmp_path / "d.fbin") as ds:
            index = dist_ivf.build_streaming(
                None, comms, IvfFlatIndexParams(n_lists=16), ds,
                chunk_rows=512)
        assert index.size == 2048
        d, i = dist_ivf.search(None, IvfFlatSearchParams(n_probes=16),
                               index, q, 5)
        d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        gt = np.argsort(d2, axis=1, kind="stable")[:, :5]
        assert np.array_equal(np.asarray(i), gt)


class TestTwoDimGrid:
    def test_list_by_query_grid(self, rng_np):
        """2-D mesh: lists shard over one axis, queries over the other."""
        import jax
        from jax.sharding import Mesh

        from raft_tpu.distributed import ivf as dist_ivf
        from raft_tpu.neighbors.ivf_flat import (
            IvfFlatIndexParams,
            IvfFlatSearchParams,
        )

        devs = np.array(jax.devices()[:8]).reshape(4, 2)
        comms = Comms(Mesh(devs, ("lists", "queries")), "lists")
        x = rng_np.standard_normal((2048, 16)).astype(np.float32)
        q = rng_np.standard_normal((16, 16)).astype(np.float32)
        index = dist_ivf.build(None, comms, IvfFlatIndexParams(n_lists=16),
                               x)
        d, i = dist_ivf.search(None, IvfFlatSearchParams(n_probes=16),
                               index, q, 5, query_axis="queries")
        d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        gt = np.argsort(d2, axis=1, kind="stable")[:, :5]
        assert np.array_equal(np.asarray(i), gt)

        # PQ variant on the same grid
        from raft_tpu.neighbors.ivf_pq import (
            IvfPqIndexParams,
            IvfPqSearchParams,
        )
        pqi = dist_ivf.build_pq(
            None, comms, IvfPqIndexParams(n_lists=16, pq_dim=16), x)
        _, ip = dist_ivf.search_pq(
            None, IvfPqSearchParams(n_probes=16), pqi, q, 5,
            query_axis="queries")
        r, _, _ = eval_recall(gt, np.asarray(ip))
        assert r >= 0.5, r

        # BQ variant on the same grid: must match the 1-D (replicated
        # query) distributed result exactly
        from raft_tpu.distributed import bq as dist_bq
        from raft_tpu.neighbors import ivf_bq

        bqi = dist_bq.build_bq(
            None, comms, ivf_bq.IvfBqIndexParams(n_lists=16), x)
        sp = ivf_bq.IvfBqSearchParams(n_probes=16)
        _, ib_grid = dist_bq.search_bq(None, sp, bqi, q, 20,
                                       query_axis="queries")
        _, ib_rep = dist_bq.search_bq(None, sp, bqi, q, 20)
        # per-device shapes differ between the two runs, so tied
        # estimates may order differently — compare the id SETS
        for row_g, row_r in zip(np.asarray(ib_grid), np.asarray(ib_rep)):
            assert set(row_g.tolist()) == set(row_r.tolist())


class TestDistributedCheckpoint:
    """Sharded-index save/load — the MNMG checkpoint/resume story the
    reference's raft-dask lacks (single-GPU serialize only)."""

    def test_flat_roundtrip_and_reshard(self, rng_np, tmp_path):
        from raft_tpu.comms import Comms
        from raft_tpu.comms.bootstrap import make_mesh
        from raft_tpu.distributed import checkpoint, ivf_flat as divf
        from raft_tpu.neighbors.ivf_flat import (
            IvfFlatIndexParams,
            IvfFlatSearchParams,
        )
        import jax

        comms = local_comms()
        x = rng_np.standard_normal((4096, 32)).astype(np.float32)
        q = rng_np.standard_normal((16, 32)).astype(np.float32)
        idx = divf.build(None, comms, IvfFlatIndexParams(n_lists=32), x)
        sp = IvfFlatSearchParams(n_probes=16)
        d0, i0 = divf.search(None, sp, idx, q, 5)

        path = tmp_path / "flat.bin"
        checkpoint.save_flat(idx, path)
        idx2 = checkpoint.load_flat(None, comms, path)
        d1, i1 = divf.search(None, sp, idx2, q, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_allclose(np.asarray(d0), np.asarray(d1),
                                   rtol=1e-5, atol=1e-5)

        # restore onto a DIFFERENT shard count (4 of the 8 devices)
        comms4 = Comms(make_mesh(devices=jax.devices()[:4]), "data")
        idx4 = checkpoint.load_flat(None, comms4, path)
        d2, i2 = divf.search(None, sp, idx4, q, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d0), np.asarray(d2),
                                   rtol=1e-5, atol=1e-5)

    def test_pq_roundtrip(self, rng_np, tmp_path):
        from raft_tpu.distributed import checkpoint, ivf_flat as divf
        from raft_tpu.neighbors.ivf_pq import (
            IvfPqIndexParams,
            IvfPqSearchParams,
        )

        comms = local_comms()
        x = rng_np.standard_normal((4096, 32)).astype(np.float32)
        q = rng_np.standard_normal((8, 32)).astype(np.float32)
        idx = divf.build_pq(None, comms,
                            IvfPqIndexParams(n_lists=16, pq_dim=16), x)
        sp = IvfPqSearchParams(n_probes=8)
        d0, i0 = divf.search_pq(None, sp, idx, q, 5)

        path = tmp_path / "pq.bin"
        checkpoint.save_pq(idx, path)
        idx2 = checkpoint.load_pq(None, comms, path)
        d1, i1 = divf.search_pq(None, sp, idx2, q, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_allclose(np.asarray(d0), np.asarray(d1),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("kind", ["flat", "pq", "pq_cluster", "bq"])
    def test_multihost_scheme_roundtrip(self, rng_np, tmp_path, kind):
        """The per-process part-file scheme on a single-process mesh
        (one part): results identical through save -> reshard 8->4 ->
        load, for all three index families (the cross-process case is
        tests/test_multiprocess.py)."""
        import jax
        from raft_tpu.comms import Comms
        from raft_tpu.comms.bootstrap import make_mesh
        from raft_tpu.distributed import bq as dist_bq
        from raft_tpu.distributed import checkpoint, ivf_flat as divf
        from raft_tpu.neighbors import ivf_bq
        from raft_tpu.neighbors.ivf_flat import (
            IvfFlatIndexParams,
            IvfFlatSearchParams,
        )
        from raft_tpu.neighbors.ivf_pq import (
            CodebookKind,
            IvfPqIndexParams,
            IvfPqSearchParams,
        )

        comms = local_comms()
        x = rng_np.standard_normal((4096, 32)).astype(np.float32)
        q = rng_np.standard_normal((8, 32)).astype(np.float32)
        if kind == "flat":
            idx = divf.build(None, comms, IvfFlatIndexParams(n_lists=16), x)
            sp = IvfFlatSearchParams(n_probes=8)
            search = lambda c, i: divf.search(None, sp, i, q, 5)
            save, load = checkpoint.save_flat_multihost, checkpoint.load_flat_multihost
        elif kind in ("pq", "pq_cluster"):
            ck = (CodebookKind.PER_CLUSTER if kind == "pq_cluster"
                  else CodebookKind.PER_SUBSPACE)
            idx = divf.build_pq(
                None, comms,
                IvfPqIndexParams(n_lists=16, pq_dim=16, codebook_kind=ck), x)
            sp = IvfPqSearchParams(n_probes=8)
            search = lambda c, i: divf.search_pq(None, sp, i, q, 5)
            save, load = checkpoint.save_pq_multihost, checkpoint.load_pq_multihost
        else:
            idx = dist_bq.build_bq(
                None, comms, ivf_bq.IvfBqIndexParams(n_lists=16), x)
            sp = ivf_bq.IvfBqSearchParams(n_probes=8)
            search = lambda c, i: dist_bq.search_bq(None, sp, i, q, 5)
            save, load = checkpoint.save_bq_multihost, checkpoint.load_bq_multihost

        d0, i0 = search(comms, idx)
        ckpt = str(tmp_path / "mh")
        save(idx, ckpt)
        comms4 = Comms(make_mesh(devices=jax.devices()[:4]), "data")
        idx4 = load(None, comms4, ckpt)
        d1, i1 = search(comms4, idx4)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_allclose(np.asarray(d0), np.asarray(d1),
                                   rtol=1e-5, atol=1e-5)

    def test_multihost_resave_clears_stale_parts(self, rng_np, tmp_path):
        """Re-saving into a directory that previously held MORE parts
        (a larger process count) must not leave stale part files the
        loader would reject as a mixed checkpoint."""
        from raft_tpu.distributed import checkpoint, ivf_flat as divf
        from raft_tpu.neighbors.ivf_flat import (
            IvfFlatIndexParams,
            IvfFlatSearchParams,
        )

        comms = local_comms()
        x = rng_np.standard_normal((2048, 16)).astype(np.float32)
        q = rng_np.standard_normal((8, 16)).astype(np.float32)
        idx = divf.build(None, comms, IvfFlatIndexParams(n_lists=16), x)
        ckpt = str(tmp_path / "resave")
        checkpoint.save_flat_multihost(idx, ckpt)
        # plant stale higher-ordinal parts from an imaginary prior
        # 3-process save
        for stale in ("part00001.bin", "part00002.bin"):
            (tmp_path / "resave" / stale).write_bytes(b"junk")
        checkpoint.save_flat_multihost(idx, ckpt)
        loaded = checkpoint.load_flat_multihost(None, comms, ckpt)
        sp = IvfFlatSearchParams(n_probes=8)
        d0, i0 = divf.search(None, sp, idx, q, 5)
        d1, i1 = divf.search(None, sp, loaded, q, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))

    def test_wrong_kind_fails_clearly(self, rng_np, tmp_path):
        """Loading a PQ checkpoint with the flat loader (or vice versa)
        raises a version mismatch, not a shape error mid-parse."""
        from raft_tpu.distributed import checkpoint, ivf_flat as divf
        from raft_tpu.neighbors.ivf_pq import IvfPqIndexParams

        comms = local_comms()
        x = rng_np.standard_normal((2048, 32)).astype(np.float32)
        idx = divf.build_pq(None, comms,
                            IvfPqIndexParams(n_lists=16, pq_dim=16), x)
        path = tmp_path / "pq.bin"
        checkpoint.save_pq(idx, path)
        with pytest.raises(ValueError, match="version mismatch"):
            checkpoint.load_flat(None, comms, path)


class TestDistributedIvfBq:
    def test_global_matches_single_chip(self, rng_np):
        """probe_mode='global' distributed BQ returns the same estimated
        ranking as the single-chip index built with the same params."""
        from raft_tpu.distributed import bq as dist_bq
        from raft_tpu.neighbors import brute_force, ivf_bq
        from raft_tpu.neighbors.refine import refine

        comms = local_comms()
        centers = rng_np.standard_normal((10, 32)) * 5
        x = (centers[rng_np.integers(0, 10, 4096)]
             + rng_np.standard_normal((4096, 32))).astype(np.float32)
        q = (centers[rng_np.integers(0, 10, 16)]
             + rng_np.standard_normal((16, 32))).astype(np.float32)

        didx = dist_bq.build_bq(
            None, comms, ivf_bq.IvfBqIndexParams(n_lists=16), x)
        sp = ivf_bq.IvfBqSearchParams(n_probes=16)
        d_dist, i_dist = dist_bq.search_bq(None, sp, didx, q, 120)

        sidx = ivf_bq.build(None, ivf_bq.IvfBqIndexParams(n_lists=16), x)
        d_single, i_single = ivf_bq.search(None, sp, sidx, q, 120)
        np.testing.assert_array_equal(np.asarray(i_dist),
                                      np.asarray(i_single))
        np.testing.assert_allclose(np.asarray(d_dist),
                                   np.asarray(d_single),
                                   rtol=1e-4, atol=1e-4)

        # end-to-end recall with exact re-rank
        _, gt = brute_force.knn(None, x, q, 10)
        _, i = refine(None, x, q, i_dist, 10)
        r, _, _ = eval_recall(np.asarray(gt), np.asarray(i))
        assert r >= 0.9, r

    def test_local_probe_mode(self, rng_np):
        from raft_tpu.distributed import bq as dist_bq
        from raft_tpu.neighbors import ivf_bq

        comms = local_comms()
        x = rng_np.standard_normal((2048, 32)).astype(np.float32)
        didx = dist_bq.build_bq(
            None, comms, ivf_bq.IvfBqIndexParams(n_lists=16), x)
        d, i = dist_bq.search_bq(
            None, ivf_bq.IvfBqSearchParams(n_probes=16), didx, x[:4], 20,
            probe_mode="local")
        assert np.asarray(i).shape == (4, 20)
        assert np.isfinite(np.asarray(d)).all()

    def test_checkpoint_roundtrip_reshard(self, rng_np, tmp_path):
        """BQ checkpoint restores onto a different shard count with
        identical search results."""
        from raft_tpu.comms.bootstrap import make_mesh
        from raft_tpu.distributed import bq as dist_bq, checkpoint
        from raft_tpu.neighbors import ivf_bq

        comms = local_comms()
        x = rng_np.standard_normal((2048, 32)).astype(np.float32)
        q = rng_np.standard_normal((8, 32)).astype(np.float32)
        didx = dist_bq.build_bq(
            None, comms, ivf_bq.IvfBqIndexParams(n_lists=16), x)
        sp = ivf_bq.IvfBqSearchParams(n_probes=8)
        d0, i0 = dist_bq.search_bq(None, sp, didx, q, 20)

        path = tmp_path / "bq_dist.bin"
        checkpoint.save_bq(didx, path)
        comms4 = Comms(make_mesh(devices=jax.devices()[:4]), "data")
        didx4 = checkpoint.load_bq(None, comms4, path)
        d1, i1 = dist_bq.search_bq(None, sp, didx4, q, 20)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_allclose(np.asarray(d0), np.asarray(d1),
                                   rtol=1e-4, atol=1e-4)
