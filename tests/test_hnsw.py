"""CAGRA <-> hnswlib interop tests — role of the reference's hnswlib
bridge tests (serialize_to_hnswlib round-trip + recall-after-load).
hnswlib isn't shipped in this image, so the file-format contract is
enforced two ways: a byte-level header check against the layout
hnswlib's ``loadIndex`` requires, and a full round-trip through
``load_hnswlib`` (an independent parser of the same format) verifying
the graph, the vectors, and the search recall survive. When hnswlib IS
importable the load_index check runs for real."""

import struct

import numpy as np
import pytest
import scipy.spatial.distance as spd

from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import cagra, hnsw
from raft_tpu.neighbors.cagra import (
    BuildAlgo,
    CagraIndexParams,
    CagraSearchParams,
)
from raft_tpu.utils import eval_recall

try:
    import hnswlib as hnswlib_mod
except ImportError:
    hnswlib_mod = None


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(11)
    centers = rng.standard_normal((12, 24)) * 4
    labels = rng.integers(0, 12, 2000)
    x = (centers[labels] + rng.standard_normal((2000, 24))).astype(np.float32)
    q = (centers[rng.integers(0, 12, 32)]
         + rng.standard_normal((32, 24))).astype(np.float32)
    return x, q


@pytest.fixture(scope="module")
def index(dataset):
    x, _ = dataset
    params = CagraIndexParams(graph_degree=16, intermediate_graph_degree=32,
                              build_algo=BuildAlgo.NN_DESCENT)
    return cagra.build(None, params, x)


def _gt(x, q, k):
    d = spd.cdist(q, x, "sqeuclidean")
    return np.argsort(d, axis=1, kind="stable")[:, :k]


class TestSaveHnswlib:
    def test_header_layout(self, index, tmp_path):
        path = str(tmp_path / "cagra.hnsw")
        hnsw.save_hnswlib(None, index, path)
        raw = open(path, "rb").read()
        hdr = struct.Struct("<QQQQQQiIQQQdQ")
        (off0, max_elems, count, per_elem, label_off, data_off,
         maxlevel, entry, max_m, max_m0, m, mult, efc) = \
            hdr.unpack_from(raw, 0)
        n, degree = index.graph.shape
        dim = index.dataset.shape[1]
        # exactly the arithmetic hnswlib's loadIndex recomputes and asserts
        assert off0 == 0 and max_elems == n and count == n
        assert max_m0 == degree and m == max_m == degree // 2
        assert data_off == 4 + 4 * degree
        assert label_off == data_off + 4 * dim
        assert per_elem == label_off + 8
        assert maxlevel == 0 and entry == 0
        assert mult == pytest.approx(1.0 / np.log(degree // 2))
        assert efc > 0
        # file length: header + n elements + n u32 zero link-list sizes
        assert len(raw) == hdr.size + n * per_elem + 4 * n
        # the trailing per-element upper-level sizes are all zero
        tail = np.frombuffer(raw, dtype="<u4", offset=hdr.size + n * per_elem)
        assert (tail == 0).all()

    def test_round_trip_graph_and_data(self, index, tmp_path):
        path = str(tmp_path / "cagra.hnsw")
        hnsw.save_hnswlib(None, index, path)
        loaded = hnsw.load_hnswlib(None, path, index.dataset.shape[1],
                                   metric=index.metric)
        np.testing.assert_array_equal(np.asarray(loaded.graph),
                                      np.asarray(index.graph))
        np.testing.assert_array_equal(np.asarray(loaded.dataset),
                                      np.asarray(index.dataset))

    def test_search_after_round_trip(self, dataset, index, tmp_path):
        x, q = dataset
        path = str(tmp_path / "cagra.hnsw")
        hnsw.save_hnswlib(None, index, path)
        loaded = hnsw.load_hnswlib(None, path, x.shape[1])
        sp = CagraSearchParams(itopk_size=64)
        _, ids = cagra.search(None, sp, loaded, q, 10)
        r, _, _ = eval_recall(_gt(x, q, 10), np.asarray(ids))
        assert r >= 0.9

    def test_wrong_dim_rejected(self, index, tmp_path):
        path = str(tmp_path / "cagra.hnsw")
        hnsw.save_hnswlib(None, index, path)
        with pytest.raises(Exception, match="layout mismatch"):
            hnsw.load_hnswlib(None, path, index.dataset.shape[1] + 3)

    def test_int8_dataset(self, index, tmp_path):
        rng = np.random.default_rng(3)
        x8 = rng.integers(-100, 100, (64, 16), dtype=np.int8)
        g = np.tile(np.arange(16, dtype=np.int32), (64, 1))
        idx8 = cagra.CagraIndex(dataset=x8, graph=g,
                                metric=DistanceType.L2Expanded)
        path = str(tmp_path / "int8.hnsw")
        hnsw.save_hnswlib(None, idx8, path)
        loaded = hnsw.load_hnswlib(None, path, 16, dtype=np.int8)
        np.testing.assert_array_equal(np.asarray(loaded.dataset), x8)

    @pytest.mark.skipif(hnswlib_mod is None, reason="hnswlib not installed")
    def test_hnswlib_loads_and_searches(self, dataset, index, tmp_path):
        x, q = dataset
        path = str(tmp_path / "cagra.hnsw")
        hnsw.save_hnswlib(None, index, path)
        h = hnswlib_mod.Index(space="l2", dim=x.shape[1])
        h.load_index(path)
        h.set_ef(64)
        ids, _ = h.knn_query(q, k=10)
        r, _, _ = eval_recall(_gt(x, q, 10), ids)
        assert r >= 0.9


class TestLoadForeign:
    """load_hnswlib on a file that mimics hnswlib's own output: permuted
    insertion order (labels != internal ids) and ragged link counts."""

    def test_permuted_ragged_file(self, tmp_path):
        rng = np.random.default_rng(5)
        n, dim, max_m0 = 50, 8, 6
        vecs = rng.standard_normal((n, dim)).astype(np.float32)
        labels = rng.permutation(n).astype(np.uint64)
        counts = rng.integers(1, max_m0 + 1, n)
        links = rng.integers(0, n, (n, max_m0)).astype(np.uint32)

        hdr = struct.Struct("<QQQQQQiIQQQdQ")
        data_off = 4 + 4 * max_m0
        label_off = data_off + 4 * dim
        per_elem = label_off + 8
        path = str(tmp_path / "foreign.hnsw")
        with open(path, "wb") as f:
            f.write(hdr.pack(0, n, n, per_elem, label_off, data_off,
                             2, 17, max_m0 // 2, max_m0, max_m0 // 2,
                             1.0, 200))
            for i in range(n):
                f.write(struct.pack("<I", counts[i]))
                f.write(links[i].tobytes())
                f.write(vecs[i].tobytes())
                f.write(struct.pack("<Q", labels[i]))
            # pretend some nodes have upper levels hnswlib would read;
            # load_hnswlib only needs level 0 so sizes may be nonzero
            f.write(np.zeros(n, dtype="<u4").tobytes())

        loaded = hnsw.load_hnswlib(None, path, dim)
        got = np.asarray(loaded.dataset)
        # row for label L must hold the vector inserted with label L
        inv = np.argsort(labels)
        np.testing.assert_allclose(got, vecs[inv])
        g = np.asarray(loaded.graph)
        assert g.shape == (n, max_m0)
        assert g.min() >= 0 and g.max() < n
        # padded entries repeat the first link (label space)
        i0 = inv[0]  # internal id whose label is 0
        expected_first = labels[links[i0, 0]]
        assert g[0, 0] == expected_first
        if counts[i0] < max_m0:
            assert (g[0, counts[i0]:] == expected_first).all()
