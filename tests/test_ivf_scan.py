"""List-major IVF scan engine tests (ops/ivf_scan): interpret-mode
parity of the Pallas kernel and the XLA list-major scan against the
rank-major scan across metrics and filters; bucketing/query-tile
invariance through SearchExecutor; engine-keyed AOT cache with the
zero-recompile guarantee.

Parity contract: the two list-major engines are bit-identical to EACH
OTHER (same contraction, and zero-padding is reduction-invariant);
against the rank-major scan the returned indices are bit-identical and
distances agree to XLA's dot-reassociation tolerance — the batched
(q, m, d) matvec and the (q, d)x(d, m) GEMM reassociate the f32
reduction differently (1-2 ulp), the same caveat as ``beam_search``'s
two lowerings.
"""

import numpy as np
import pytest

from raft_tpu import SearchExecutor
from raft_tpu.core import tracing
from raft_tpu.core.bitset import Bitset
from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.neighbors.filters import BitmapFilter
from raft_tpu.neighbors.ivf_flat import IvfFlatIndexParams, IvfFlatSearchParams
from raft_tpu.neighbors.ivf_pq import IvfPqIndexParams, IvfPqSearchParams

METRICS = [DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
           DistanceType.InnerProduct]


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    x = rng.standard_normal((2000, 24)).astype(np.float32)
    q = rng.standard_normal((33, 24)).astype(np.float32)
    return x, q


@pytest.fixture(scope="module")
def indexes(data):
    x, _ = data
    return {m: ivf_flat.build(
        None, IvfFlatIndexParams(n_lists=16, metric=m), x)
        for m in METRICS}


def _run(index, q, k, engine, n_probes=5, sample_filter=None):
    sp = IvfFlatSearchParams(n_probes=n_probes, scan_engine=engine)
    d, i = ivf_flat.search(None, sp, index, q, k,
                           sample_filter=sample_filter)
    return np.asarray(d), np.asarray(i)


def _assert_engine_parity(index, q, k, n_probes=5, sample_filter=None):
    """pallas == xla bit-identical; both vs rank: ids bit-identical,
    distances to reassociation tolerance."""
    ref_d, ref_i = _run(index, q, k, "rank", n_probes, sample_filter)
    out = {e: _run(index, q, k, e, n_probes, sample_filter)
           for e in ("pallas", "xla")}
    np.testing.assert_array_equal(out["pallas"][1], out["xla"][1])
    np.testing.assert_array_equal(out["pallas"][0], out["xla"][0])
    for e in ("pallas", "xla"):
        np.testing.assert_array_equal(out[e][1], ref_i)
        np.testing.assert_allclose(out[e][0], ref_d, rtol=1e-5, atol=1e-5)


class TestEngineParity:
    @pytest.mark.parametrize("metric", METRICS)
    def test_matches_rank_major(self, data, indexes, metric):
        _, q = data
        _assert_engine_parity(indexes[metric], q, 10)

    @pytest.mark.parametrize("metric", METRICS)
    def test_bitset_filter(self, data, indexes, metric):
        x, q = data
        filt = Bitset.from_mask(np.arange(len(x)) % 3 != 0)
        _assert_engine_parity(indexes[metric], q, 10, n_probes=8,
                              sample_filter=filt)
        # filtered-out ids must never surface
        _, i = _run(indexes[metric], q, 10, "pallas", 8, filt)
        valid = i[i >= 0]
        assert (valid % 3 != 0).all()

    def test_bitmap_filter_falls_back(self, data, indexes):
        """Per-query (2-D) filters route the pallas engine to the XLA
        list-major scan — results still match rank-major ids."""
        x, q = data
        mask = np.ones((len(q), len(x)), bool)
        mask[:, ::2] = False
        bm = BitmapFilter.from_mask(mask)
        index = indexes[DistanceType.L2Expanded]
        ref_d, ref_i = _run(index, q, 10, "rank", 8, bm)
        for engine in ("pallas", "xla"):
            d, i = _run(index, q, 10, engine, 8, bm)
            np.testing.assert_array_equal(i, ref_i)
            np.testing.assert_allclose(d, ref_d, rtol=1e-5, atol=1e-5)

    def test_ragged_k_exceeds_probed(self, data, indexes):
        """k larger than the probed candidate pool: the -1/inf fill
        pattern must match the rank-major scan exactly."""
        _, q = data
        index = indexes[DistanceType.L2Expanded]
        ref_d, ref_i = _run(index, q[:4], 400, "rank", n_probes=1)
        assert (ref_i == -1).any()
        for engine in ("pallas", "xla"):  # pallas falls back (k > cap)
            d, i = _run(index, q[:4], 400, engine, n_probes=1)
            np.testing.assert_array_equal(i, ref_i)
            np.testing.assert_allclose(d, ref_d, rtol=1e-5, atol=1e-5)
            assert not np.isfinite(d[i == -1]).any() or (
                d[i == -1] == np.inf).all()

    def test_exhaustive_probes_all_lists(self, data, indexes):
        """n_probes == n_lists: the union is every list — the dense
        degenerate case (brute force as list-major GEMMs)."""
        _, q = data
        _assert_engine_parity(indexes[DistanceType.L2Expanded], q, 10,
                              n_probes=16)

    def test_bf16_storage(self, data):
        """bf16 lists stream half-width; the kernel upcasts in VMEM and
        must match the rank-major scan's f32 math."""
        import jax.numpy as jnp

        x, q = data
        index = ivf_flat.build(None, IvfFlatIndexParams(n_lists=16),
                               jnp.asarray(x, jnp.bfloat16))
        assert index.data.dtype == jnp.bfloat16
        _assert_engine_parity(index, q, 10)

    def test_int8_falls_back_to_xla(self, data):
        rng = np.random.default_rng(0)
        x8 = rng.integers(-100, 100, (1000, 16)).astype(np.int8)
        q = x8[:8].astype(np.float32)
        index = ivf_flat.build(None, IvfFlatIndexParams(n_lists=8), x8)
        ref_d, ref_i = _run(index, q, 3, "rank", 8)
        d, i = _run(index, q, 3, "pallas", 8)  # resolves to xla
        np.testing.assert_array_equal(i, ref_i)
        np.testing.assert_allclose(d, ref_d, rtol=1e-5, atol=1e-5)

    def test_exact_ties_smallest_id(self, data):
        """Exact duplicate vectors produce genuinely tied distances;
        both list-major engines break ties by smallest dataset id (the
        ``_extract_topk`` order), so they stay bit-identical to each
        other even on ties — the property ``merge_topk``'s positional
        tie-break would not give."""
        x, q = data
        x = x.copy()
        x[1000:1200] = x[:200]  # 200 exact duplicate pairs
        index = ivf_flat.build(None, IvfFlatIndexParams(n_lists=16), x)
        queries = x[:40]        # self-queries guarantee tied top hits
        out = {e: _run(index, queries, 10, e, n_probes=16)
               for e in ("pallas", "xla")}
        np.testing.assert_array_equal(out["pallas"][1], out["xla"][1])
        np.testing.assert_array_equal(out["pallas"][0], out["xla"][0])
        # both members of a duplicate pair must surface among the
        # top hits of their self-query (distance 0 twice)
        ids = out["pallas"][1]
        for r in range(40):
            assert r in ids[r] and (r + 1000) in ids[r]

    def test_multiple_query_tiles_in_kernel(self, data, indexes,
                                            monkeypatch):
        """A tiny VMEM budget forces the kernel's query-tile grid
        dimension > 1; results must not depend on the tiling."""
        _, q = data
        index = indexes[DistanceType.L2Expanded]
        want_d, want_i = _run(index, q, 10, "pallas")
        monkeypatch.setenv("RAFT_TPU_VMEM_MB", "1")
        got_d, got_i = _run(index, q, 10, "pallas")
        np.testing.assert_array_equal(got_i, want_i)
        np.testing.assert_array_equal(got_d, want_d)


class TestUniqueLists:
    def test_union_sorted_sentinel_padded(self):
        import jax.numpy as jnp

        from raft_tpu.ops.ivf_scan import unique_lists

        probes = jnp.asarray([[3, 1, 3], [7, 1, 0], [7, 7, 7]], jnp.int32)
        u = np.asarray(unique_lists(probes, 16))
        assert u.shape == (9,)  # min(16, 3*3)
        np.testing.assert_array_equal(u[:4], [0, 1, 3, 7])
        assert (u[4:] == 16).all()  # sentinel

    def test_cap_at_n_lists(self):
        import jax.numpy as jnp

        from raft_tpu.ops.ivf_scan import unique_lists

        rng = np.random.default_rng(0)
        probes = jnp.asarray(rng.integers(0, 8, (64, 4)), jnp.int32)
        u = np.asarray(unique_lists(probes, 8))
        assert u.shape == (8,)
        np.testing.assert_array_equal(np.sort(u), np.arange(8))


class TestResolveEngine:
    def test_auto_off_tpu_is_xla_list_major(self):
        from raft_tpu.ops.ivf_scan import resolve_scan_engine

        assert resolve_scan_engine("auto") == "xla"
        assert resolve_scan_engine("rank") == "rank"
        assert resolve_scan_engine("xla") == "xla"

    def test_pallas_precondition_fallbacks(self):
        import jax.numpy as jnp

        from raft_tpu.ops.ivf_scan import resolve_scan_engine

        data = jnp.zeros((4, 8, 16), jnp.float32)
        assert resolve_scan_engine("pallas", data=data) == "pallas"
        # 2-D per-query filter words
        fw = jnp.zeros((3, 4), jnp.uint32)
        assert resolve_scan_engine("pallas", data=data,
                                   filter_words=fw) == "xla"
        # shared 1-D words are fine
        assert resolve_scan_engine(
            "pallas", data=data, filter_words=fw[0]) == "pallas"
        # int8 storage
        assert resolve_scan_engine(
            "pallas", data=data.astype(jnp.int8)) == "xla"
        # k beyond the unrolled-merge budget
        assert resolve_scan_engine("pallas", data=data, k=512) == "xla"
        # a single list block that cannot fit VMEM
        big = jnp.zeros((2, 65536, 256), jnp.float32)
        assert resolve_scan_engine("pallas", data=big, vmem_mb=16) == "xla"

    def test_rejects_unknown_engine(self):
        from raft_tpu.core.validation import RaftError
        from raft_tpu.ops.ivf_scan import resolve_scan_engine

        with pytest.raises(RaftError):
            resolve_scan_engine("mosaic")


class TestDirectKernelEntry:
    def test_list_major_scan_direct(self, data, indexes):
        """Drive ops.list_major_scan directly (the guard-test anchor:
        interpret=True reference for the ivf_scan pallas_call)."""
        import jax.numpy as jnp

        from raft_tpu.neighbors._batching import coarse_select
        from raft_tpu.ops.ivf_scan import list_major_scan

        _, q = data
        index = indexes[DistanceType.L2Expanded]
        qf = jnp.asarray(q)
        ip = qf @ index.centers.T
        score = -(index.center_norms[None, :] - 2.0 * ip)
        probes = coarse_select(score, 5, "exact")
        outs = {}
        for engine in ("pallas", "xla"):
            d, i = list_major_scan(
                qf, index.data, index.data_norms, index.indices, probes,
                k=10, metric=DistanceType.L2Expanded, engine=engine,
                interpret=True)
            outs[engine] = (np.asarray(d), np.asarray(i))
        np.testing.assert_array_equal(outs["pallas"][1], outs["xla"][1])
        np.testing.assert_array_equal(outs["pallas"][0], outs["xla"][0])


class TestExecutorIntegration:
    @pytest.mark.parametrize("engine", ["pallas", "xla"])
    def test_bucketing_invariance(self, data, indexes, engine):
        """Query-tile / bucket invariance: the probed-list union grows
        with pad rows and tile boundaries move, but per-query masking
        keeps every real row bit-stable."""
        _, q = data
        index = indexes[DistanceType.L2Expanded]
        p = IvfFlatSearchParams(n_probes=8, scan_engine=engine)
        want_d, want_i = ivf_flat.search(None, p, index, q, 10)
        # direct path, small query tiles (ragged tail padded into tile)
        d, i = ivf_flat.search(None, p, index, q, 10, query_tile=16)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(want_i))
        np.testing.assert_array_equal(np.asarray(d), np.asarray(want_d))
        # serving path at two bucket ladders (pad rows + tiling)
        for ex in (SearchExecutor(),
                   SearchExecutor(min_bucket=8, max_bucket=16)):
            d, i = ex.search(index, q, 10, params=p)
            np.testing.assert_array_equal(np.asarray(i),
                                          np.asarray(want_i))
            np.testing.assert_array_equal(np.asarray(d),
                                          np.asarray(want_d))

    def test_engine_keyed_aot_cache_zero_recompile(self, data, indexes):
        """The resolved scan engine is part of the AOT cache key: the
        pallas engine compiles once per bucket, steady state triggers
        ZERO backend compiles (asserted against jax's own monitoring),
        and switching engines compiles a distinct executable."""
        _, q = data
        index = indexes[DistanceType.L2Expanded]
        tracing.install_xla_compile_listener()
        ex = SearchExecutor()
        p = IvfFlatSearchParams(n_probes=8, scan_engine="pallas")
        for n in (16, 13, 9):  # prime the bucket + pad/slice programs
            ex.search(index, q[:n], 5, params=p)
        assert ex.stats.compile_count == 1
        backend0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        for n in (16, 13, 9, 13, 16, 9):
            ex.search(index, q[:n], 5, params=p)
        assert ex.stats.compile_count == 1
        assert tracing.get_counter(tracing.XLA_COMPILE_COUNT) == backend0
        # a different engine is a different executable, not a reuse
        p2 = IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        d_x, i_x = ex.search(index, q[:16], 5, params=p2)
        assert ex.stats.compile_count == 2
        d_p, i_p = ex.search(index, q[:16], 5, params=p)
        assert ex.stats.compile_count == 2  # both entries live
        np.testing.assert_array_equal(np.asarray(i_x), np.asarray(i_p))
        np.testing.assert_array_equal(np.asarray(d_x), np.asarray(d_p))

    def test_executor_matches_direct_per_engine(self, data, indexes):
        _, q = data
        index = indexes[DistanceType.InnerProduct]
        for engine in ("pallas", "xla", "rank"):
            p = IvfFlatSearchParams(n_probes=8, scan_engine=engine)
            d0, i0 = ivf_flat.search(None, p, index, q[:11], 5)
            d1, i1 = SearchExecutor().search(index, q[:11], 5, params=p)
            np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
            np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))


class TestIvfPqListMajor:
    """The same list-major union formulation on the PQ gathered-codes
    scan: per-list code planes stream once and score the whole tile;
    bit-identical to the rank-major PQ scan on tie-free data (scoring
    is per-element LUT sums — no contraction reassociation in play;
    exact cross-list ADC ties resolve smallest-id in the list-major
    engine vs probe-order in rank-major)."""

    @pytest.fixture(scope="class")
    def pq_setup(self):
        rng = np.random.default_rng(3)
        x = rng.standard_normal((1500, 32)).astype(np.float32)
        q = rng.standard_normal((21, 32)).astype(np.float32)
        return x, q

    @pytest.mark.parametrize("metric", [DistanceType.L2Expanded,
                                        DistanceType.InnerProduct])
    def test_matches_rank_major(self, pq_setup, metric):
        x, q = pq_setup
        index = ivf_pq.build(None, IvfPqIndexParams(
            n_lists=12, pq_dim=8, metric=metric), x)
        filt = Bitset.from_mask(np.arange(len(x)) % 3 != 0)
        for sf in (None, filt):
            ref_d, ref_i = ivf_pq.search(
                None, IvfPqSearchParams(n_probes=4, scan_engine="rank"),
                index, q, 7, sample_filter=sf)
            d, i = ivf_pq.search(
                None, IvfPqSearchParams(n_probes=4, scan_engine="xla"),
                index, q, 7, sample_filter=sf)
            np.testing.assert_array_equal(np.asarray(i), np.asarray(ref_i))
            np.testing.assert_array_equal(np.asarray(d), np.asarray(ref_d))

    def test_executor_engine_keyed(self, pq_setup):
        x, q = pq_setup
        index = ivf_pq.build(None, IvfPqIndexParams(n_lists=12, pq_dim=8),
                             x)
        ex = SearchExecutor()
        for engine in ("rank", "xla"):
            p = IvfPqSearchParams(n_probes=4, scan_engine=engine)
            d0, i0 = ivf_pq.search(None, p, index, q[:9], 5)
            d1, i1 = ex.search(index, q[:9], 5, params=p)
            np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
            np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
        assert ex.stats.compile_count == 2  # one executable per engine


class TestRaggedFront:
    """The ragged query-tile front (ops/ivf_scan.ragged_row_probes /
    ragged_probes + ivf_flat._search_ragged_fn): per-request probe
    budgets resolve through the engines' membership mask, so one
    packed tile is bit-identical per request to solo searches."""

    def test_row_probes_descriptor(self):
        from raft_tpu.ops.ivf_scan import ragged_row_probes

        rp = ragged_row_probes([3, 2, 4], [5, 9, 2], tile=12)
        np.testing.assert_array_equal(
            rp, [5, 5, 5, 9, 9, 2, 2, 2, 2, 0, 0, 0])
        with pytest.raises(Exception):
            ragged_row_probes([8, 8], [1, 1], tile=12)  # overflow

    def test_ragged_probes_masks_to_sentinel(self):
        import jax.numpy as jnp

        from raft_tpu.ops.ivf_scan import ragged_probes

        probes = jnp.arange(12, dtype=jnp.int32).reshape(3, 4)
        rp = jnp.asarray([2, 0, 4], jnp.int32)
        out = np.asarray(ragged_probes(probes, rp, n_lists=99))
        np.testing.assert_array_equal(out[0], [0, 1, 99, 99])
        np.testing.assert_array_equal(out[1], [99] * 4)  # pad row
        np.testing.assert_array_equal(out[2], [8, 9, 10, 11])

    @pytest.mark.parametrize("engine", ["pallas", "xla"])
    @pytest.mark.parametrize("metric", METRICS)
    def test_packed_tile_bit_identical_to_solo(self, data, indexes,
                                               metric, engine):
        """pallas ≡ xla ≡ solo per packed request, mixed n_probes/k."""
        import jax.numpy as jnp

        from raft_tpu.ops.ivf_scan import ragged_row_probes

        _, q = data
        index = indexes[metric]
        sizes, nps, ks = [3, 2, 4, 1], [5, 9, 2, 16], [3, 7, 5, 10]
        tile, np_cap, k_cap = 16, 16, 16
        packed = np.zeros((tile, q.shape[1]), np.float32)
        row = 0
        for m in sizes:
            packed[row:row + m] = q[row:row + m]
            row += m
        rp = ragged_row_probes(sizes, nps, tile)
        # jitted like the serving path compiles it: eager-vs-jit is
        # NOT bit-stable (XLA fuses/reassociates), the contract is
        # jitted-ragged ≡ jitted-solo
        import functools

        import jax

        ragged_jit = jax.jit(functools.partial(
            ivf_flat._search_ragged_fn, n_probes=np_cap, k=k_cap,
            metric=index.metric, scan_engine=engine))
        d, i = ragged_jit(
            jnp.asarray(packed), jnp.asarray(rp), index.centers,
            index.center_norms, index.data, index.data_norms,
            index.indices, None)
        d, i = np.asarray(d), np.asarray(i)
        row = 0
        for m, npb, k in zip(sizes, nps, ks):
            sd, si = _run(index, q[row:row + m], k, engine,
                          n_probes=npb)
            np.testing.assert_array_equal(i[row:row + m, :k], si)
            np.testing.assert_array_equal(d[row:row + m, :k], sd)
            row += m
        # tile pad rows (budget 0) probe nothing: empty results
        assert (i[row:] == -1).all()

    def test_pad_rows_never_pollute_probe_histogram(self, data, indexes):
        import jax.numpy as jnp

        from raft_tpu.ops.ivf_scan import (
            probe_histogram,
            ragged_probes,
            ragged_row_probes,
        )

        index = indexes[DistanceType.L2Expanded]
        _, q = data
        qf = jnp.asarray(q[:8])
        import jax

        ip = qf @ index.centers.T
        _, probes = jax.lax.top_k(-(index.center_norms[None, :] - 2 * ip),
                                  8)
        rp = jnp.asarray(ragged_row_probes([3, 2], [4, 8], tile=8))
        masked = ragged_probes(probes.astype(jnp.int32), rp,
                               index.n_lists)
        counts = probe_histogram(masked,
                                 jnp.zeros((index.n_lists,), jnp.int32))
        assert int(np.asarray(counts).sum()) == 3 * 4 + 2 * 8
