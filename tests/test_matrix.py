"""select_k + matrix ops tests (analog of cpp/test/matrix/*)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import matrix
from raft_tpu.matrix import SelectAlgo, select_k


class TestSelectK:
    @pytest.mark.parametrize("select_min", [True, False])
    @pytest.mark.parametrize("algo", [SelectAlgo.TOPK, SelectAlgo.SORT])
    def test_exact(self, rng_np, select_min, algo):
        vals = rng_np.standard_normal((13, 200)).astype(np.float32)
        k = 17
        got_v, got_i = select_k(None, vals, k, select_min=select_min, algo=algo)
        order = np.argsort(vals if select_min else -vals, axis=1, kind="stable")
        want_v = np.take_along_axis(vals, order[:, :k], axis=1)
        np.testing.assert_allclose(np.sort(np.asarray(got_v), 1), np.sort(want_v, 1),
                                   rtol=1e-6, atol=1e-6)
        # values at returned indices must match returned values
        np.testing.assert_allclose(
            np.take_along_axis(vals, np.asarray(got_i), axis=1),
            np.asarray(got_v), rtol=1e-6, atol=1e-6,
        )

    def test_large_n_large_k(self, rng_np):
        """The reference's extreme regime (matrix/detail/select_radix:
        k up to 2048 over very wide rows) at CI-sized width."""
        vals = rng_np.standard_normal((2, 200_000)).astype(np.float32)
        k = 2048
        got_v, _ = select_k(None, vals, k, select_min=True)
        want_v = np.sort(vals, axis=1)[:, :k]
        np.testing.assert_allclose(np.sort(np.asarray(got_v), 1), want_v,
                                   rtol=1e-6, atol=1e-6)

    def test_index_payload(self, rng_np):
        vals = rng_np.standard_normal((4, 50)).astype(np.float32)
        payload = rng_np.integers(1000, 2000, (4, 50)).astype(np.int32)
        _, got_i = select_k(None, vals, 5, index_values=payload)
        pos = np.argsort(vals, 1)[:, :5]
        want = np.take_along_axis(payload, pos, 1)
        assert set(np.asarray(got_i).ravel()) == set(want.ravel())

    def test_k_equals_n(self, rng_np):
        vals = rng_np.standard_normal((3, 8)).astype(np.float32)
        got_v, _ = select_k(None, vals, 8)
        np.testing.assert_allclose(np.asarray(got_v), np.sort(vals, 1), rtol=1e-6)

    def test_auto_dispatcher(self):
        """AUTO resolves per the documented heuristic: full sort when
        the selection is (near-)full width, top_k otherwise — always an
        exact algorithm. The TILES (Pallas streamed merge) route only
        engages on a real TPU backend, so on the CPU test mesh wide
        rows stay on top_k."""
        from raft_tpu.matrix.select_k import _choose_algo

        assert _choose_algo(4, 100, 100) == SelectAlgo.SORT
        assert _choose_algo(4, 100, 80) == SelectAlgo.SORT
        assert _choose_algo(4, 100, 10) == SelectAlgo.TOPK
        assert _choose_algo(1, 2, 1) == SelectAlgo.TOPK
        assert _choose_algo(4, 100, 75) == SelectAlgo.TOPK
        assert _choose_algo(4, 1 << 20, 10) == SelectAlgo.TOPK  # cpu mesh

    def test_tiles_matches_topk(self, rng_np):
        """TILES (the Pallas streamed merge behind AUTO's wide-row TPU
        route; interpret mode here) must match the top_k path exactly,
        including the stable first-occurrence tie-break."""
        v = rng_np.standard_normal((5, 20000)).astype(np.float32)
        v[:, 1000] = v[:, 40]  # cross-tile duplicates exercise ties
        v[:, 3] = v[:, 2]      # adjacent duplicates too
        for select_min in (True, False):
            d_t, i_t = select_k(None, v, 9, select_min=select_min,
                                algo=SelectAlgo.TOPK)
            d_p, i_p = select_k(None, v, 9, select_min=select_min,
                                algo=SelectAlgo.TILES)
            np.testing.assert_array_equal(np.asarray(i_t), np.asarray(i_p))
            np.testing.assert_array_equal(np.asarray(d_t), np.asarray(d_p))

    def test_approx_recall(self, rng_np):
        vals = rng_np.standard_normal((4, 4096)).astype(np.float32)
        k = 10
        got_v, got_i = select_k(None, vals, k, algo=SelectAlgo.APPROX)
        want_i = np.argsort(vals, 1)[:, :k]
        recall = np.mean([
            len(set(np.asarray(got_i)[b]) & set(want_i[b])) / k
            for b in range(vals.shape[0])
        ])
        assert recall >= 0.7


class TestMatrixOps:
    def test_gather_scatter(self, rng_np):
        m = rng_np.standard_normal((10, 4)).astype(np.float32)
        idx = np.array([3, 1, 7])
        g = np.asarray(matrix.gather(m, idx))
        np.testing.assert_array_equal(g, m[idx])
        s = np.asarray(matrix.scatter(np.zeros_like(m), idx, g))
        np.testing.assert_array_equal(s[idx], m[idx])

    def test_gather_if(self, rng_np):
        m = rng_np.standard_normal((6, 3)).astype(np.float32)
        idx = np.array([0, 2, 4])
        stencil = np.array([1, 0, 1])
        out = np.asarray(matrix.gather_if(m, idx, stencil, lambda s: s > 0))
        np.testing.assert_array_equal(out[1], 0)
        np.testing.assert_array_equal(out[0], m[0])

    def test_argmax_argmin(self, rng_np):
        m = rng_np.standard_normal((5, 9)).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(matrix.argmax(m)), m.argmax(1))
        np.testing.assert_array_equal(np.asarray(matrix.argmin(m)), m.argmin(1))

    def test_col_sort(self, rng_np):
        m = rng_np.standard_normal((4, 7)).astype(np.float32)
        keys, order = matrix.col_sort(m)
        np.testing.assert_allclose(np.asarray(keys), np.sort(m, 1), rtol=1e-6)

    def test_slice_reverse_tri(self, rng_np):
        m = rng_np.standard_normal((6, 6)).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(matrix.slice(m, (1, 4), (2, 5))), m[1:4, 2:5])
        np.testing.assert_array_equal(np.asarray(matrix.reverse(m)), m[:, ::-1])
        np.testing.assert_array_equal(np.asarray(matrix.triangular_upper(m)), np.triu(m))

    def test_linewise(self, rng_np):
        m = rng_np.standard_normal((3, 5)).astype(np.float32)
        v = rng_np.standard_normal(5).astype(np.float32)
        out = np.asarray(matrix.linewise_op(m, v, True, jnp.add))
        np.testing.assert_allclose(out, m + v[None, :], rtol=1e-6)


class TestMatrixMath:
    """The small `raft/matrix/*.cuh` math headers: copy/diagonal/init/
    power/sqrt/reciprocal/ratio/sign_flip/threshold/norm."""

    def test_copy_fill_eye_diag(self, rng_np):
        m = rng_np.standard_normal((4, 6)).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(matrix.copy(m)), m)
        np.testing.assert_array_equal(np.asarray(matrix.fill(m, 3.0)),
                                      np.full_like(m, 3.0))
        np.testing.assert_array_equal(np.asarray(matrix.eye(3)), np.eye(3))
        np.testing.assert_array_equal(np.asarray(matrix.diagonal(m)),
                                      np.diagonal(m))
        d = rng_np.standard_normal(4).astype(np.float32)
        out = np.asarray(matrix.set_diagonal(m, d))
        np.testing.assert_array_equal(np.diagonal(out), d)

    def test_elementwise_math(self, rng_np):
        m = np.abs(rng_np.standard_normal((3, 4))).astype(np.float32) + 0.1
        np.testing.assert_allclose(np.asarray(matrix.power(m, 2.0)), m ** 2,
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(matrix.sqrt(m)), np.sqrt(m),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(matrix.ratio(m)), m / m.sum(),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(matrix.l2_norm(m)),
                                   np.linalg.norm(m), rtol=1e-5)

    def test_reciprocal_guard_threshold_signflip(self):
        x = np.array([[0.0, 2.0, -4.0]], np.float32)
        np.testing.assert_allclose(
            np.asarray(matrix.reciprocal(x, 1.0, 1e-6)),
            [[0.0, 0.5, -0.25]])
        # zero_small_values semantics: zero by MAGNITUDE — large
        # negative entries survive
        np.testing.assert_array_equal(
            np.asarray(matrix.threshold(x, 1.0)), [[0.0, 2.0, -4.0]])
        np.testing.assert_array_equal(
            np.asarray(matrix.zero_small_values(
                np.array([[0.5, -0.5, 3.0]], np.float32), 0.5)),
            [[0.0, 0.0, 3.0]])
        m = np.array([[1.0, -3.0], [-2.0, 1.0]], np.float32)
        out = np.asarray(matrix.sign_flip(m))
        # max-|value| entry of each column must come out positive
        piv = np.abs(out).argmax(axis=0)
        assert (out[piv, np.arange(2)] > 0).all()
