"""CAGRA + NN-descent tests — reference pattern
(cpp/test/neighbors/ann_cagra.cuh, ann_nn_descent.cuh): random dataset,
ground truth by brute force, recall >= threshold; graph-quality checks
for NN-descent; optimize invariants; serialization round-trip."""

import io

import numpy as np
import pytest
import scipy.spatial.distance as spd

from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import cagra, nn_descent
from raft_tpu.neighbors.cagra import (
    BuildAlgo,
    CagraIndexParams,
    CagraSearchParams,
)
from raft_tpu.neighbors.nn_descent import NNDescentParams
from raft_tpu.utils import eval_recall


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(7)
    centers = rng.standard_normal((16, 24)) * 4
    labels = rng.integers(0, 16, 3000)
    x = (centers[labels] + rng.standard_normal((3000, 24))).astype(np.float32)
    q = (centers[rng.integers(0, 16, 32)]
         + rng.standard_normal((32, 24))).astype(np.float32)
    return x, q


def _gt(x, q, k):
    d = spd.cdist(q, x, "sqeuclidean")
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=1), idx


def _knn_graph_recall(x, graph, k):
    """Fraction of true k-NN (excluding self) present in the graph rows."""
    d = spd.cdist(x, x, "sqeuclidean")
    np.fill_diagonal(d, np.inf)
    gt = np.argsort(d, axis=1, kind="stable")[:, :k]
    r, _, _ = eval_recall(gt, np.asarray(graph)[:, :k])
    return r


class TestNNDescent:
    def test_graph_recall(self, dataset):
        x, _ = dataset
        params = NNDescentParams(graph_degree=16, intermediate_graph_degree=32,
                                 max_iterations=12, seed=1)
        graph = nn_descent.build(None, params, x)
        assert graph.shape == (len(x), 16)
        g = np.asarray(graph)
        # no self loops, valid ids
        assert not np.any(g == np.arange(len(x))[:, None])
        assert g.max() < len(x)
        r = _knn_graph_recall(x, g, 16)
        assert r >= 0.85, f"graph recall {r}"

    def test_returns_sorted_distances(self, dataset):
        x, _ = dataset
        params = NNDescentParams(graph_degree=8, intermediate_graph_degree=24,
                                 max_iterations=8, seed=2)
        graph, dists = nn_descent.build(None, params, x, return_distances=True)
        d = np.asarray(dists)
        assert np.all(np.diff(d, axis=1) >= -1e-4)
        # distances match the actual pairs
        g = np.asarray(graph)
        ref = np.sum((x[:50, None, :] - x[g[:50]]) ** 2, axis=2)
        np.testing.assert_allclose(d[:50], ref, rtol=1e-3, atol=1e-3)


class TestClusterJoin:
    def test_graph_recall(self, dataset):
        """Merged within-cluster passes + one polish round reach the
        same recall bar as full NN-descent."""
        from raft_tpu.neighbors import cluster_join

        x, _ = dataset
        params = cluster_join.ClusterJoinParams(
            graph_degree=16, passes=3, target_cluster_size=400,
            polish_rounds=1, seed=5)
        graph = cluster_join.build(None, params, x)
        g = np.asarray(graph)
        assert g.shape == (len(x), 16)
        assert not np.any(g == np.arange(len(x))[:, None])
        assert g.max() < len(x)
        r = _knn_graph_recall(x, g, 16)
        assert r >= 0.85, f"graph recall {r}"

    def test_single_cluster_is_exact(self):
        """target >= n degenerates to one exact brute-force pass."""
        from raft_tpu.neighbors import cluster_join

        rng = np.random.default_rng(0)
        x = rng.standard_normal((300, 16)).astype(np.float32)
        params = cluster_join.ClusterJoinParams(
            graph_degree=8, target_cluster_size=512, polish_rounds=0)
        graph, dists = cluster_join.build(None, params, x,
                                          return_distances=True)
        r = _knn_graph_recall(x, np.asarray(graph), 8)
        assert r == 1.0, r
        d = np.asarray(dists)
        assert np.all(np.diff(d, axis=1) >= -1e-4)

    def test_cagra_build_algo(self, dataset):
        """End-to-end CAGRA with the CLUSTER_JOIN source."""
        x, q = dataset
        index = cagra.build(None, CagraIndexParams(
            graph_degree=16, intermediate_graph_degree=32,
            build_algo=BuildAlgo.CLUSTER_JOIN), x)
        d, i = cagra.search(None, CagraSearchParams(itopk_size=32), index,
                            q, 10)
        _, gt = _gt(x, q, 10)
        r, _, _ = eval_recall(gt, np.asarray(i))
        assert r >= 0.9, r


class TestCagraOptimize:
    def test_detour_counts_match_naive(self):
        """The sort+binary-search counting equals the O(k^3) definition:
        count[i, r] = #{l < r : g[i, r] in graph[g[i, l]]}."""
        import jax.numpy as jnp

        from raft_tpu.neighbors.cagra import _detour_counts

        rng = np.random.default_rng(3)
        n, k = 60, 9
        g = rng.integers(0, n, (n, k)).astype(np.int32)
        g[rng.random((n, k)) < 0.15] = -1          # some invalid edges
        want = np.zeros((n, k), np.int32)
        for i in range(n):
            for r in range(k):
                if g[i, r] < 0:
                    continue
                for ell in range(r):
                    if g[i, ell] >= 0 and g[i, r] in g[g[i, ell]]:
                        want[i, r] += 1
        for method in ("search", "compare"):
            got = np.asarray(_detour_counts(jnp.asarray(g), tile=16,
                                            method=method))
            np.testing.assert_array_equal(got, want, err_msg=method)

    def test_degree_and_validity(self, dataset):
        x, _ = dataset
        params = NNDescentParams(graph_degree=32, intermediate_graph_degree=48,
                                 max_iterations=10, seed=3)
        knn_graph = nn_descent.build(None, params, x)
        graph = cagra.optimize(None, knn_graph, 16)
        g = np.asarray(graph)
        assert g.shape == (len(x), 16)
        assert g.max() < len(x)
        # rows are dedup'd (ignoring -1 padding)
        for row in g[:100]:
            vals = row[row >= 0]
            assert len(set(vals.tolist())) == len(vals)
        # pruning keeps the graph mostly full
        assert (g >= 0).mean() > 0.95


class TestBufferMerge:
    def test_dedup_and_priority(self):
        """Buffer copies win over candidate copies (explored flags
        survive); earlier candidates win over later duplicates; -1
        candidates never enter."""
        import jax.numpy as jnp
        from raft_tpu.neighbors.cagra import _buffer_merge

        ids = jnp.asarray([[5, 9, -1, -1]])
        dists = jnp.asarray([[1.0, 2.0, np.inf, np.inf]])
        explored = jnp.asarray([[True, False, False, False]])
        # cand 5 duplicates buffer (worse d must NOT replace the
        # explored flag), the two 7s dedup to the first, -1 is invalid
        cand = jnp.asarray([[5, 7, 7, -1]])
        cand_d = jnp.asarray([[0.5, 3.0, 0.1, 0.0]])
        out_i, out_d, out_e = _buffer_merge(ids, dists, explored,
                                            cand, cand_d, 4)
        oi, od, oe = (np.asarray(out_i)[0], np.asarray(out_d)[0],
                      np.asarray(out_e)[0])
        assert oi[:3].tolist() == [5, 9, 7]
        np.testing.assert_allclose(od[:3], [1.0, 2.0, 3.0])
        assert oe[:3].tolist() == [True, False, False]
        assert not np.isfinite(od[3])


class TestCagraSearch:
    @pytest.mark.parametrize("algo", [BuildAlgo.NN_DESCENT, BuildAlgo.IVF_PQ])
    def test_recall(self, dataset, algo):
        x, q = dataset
        params = CagraIndexParams(
            intermediate_graph_degree=48, graph_degree=24, build_algo=algo
        )
        index = cagra.build(None, params, x)
        assert index.graph.shape == (len(x), 24)
        sp = CagraSearchParams(itopk_size=64, search_width=4)
        d, i = cagra.search(None, sp, index, q, 10)
        gt_d, gt_i = _gt(x, q, 10)
        r, _, _ = eval_recall(gt_i, np.asarray(i), gt_d, np.asarray(d))
        assert r >= 0.9, f"recall {r} ({algo})"
        # distances are exact for returned ids
        ref = np.sum((q[:, None, :] - x[np.asarray(i)]) ** 2, axis=2)
        np.testing.assert_allclose(np.asarray(d), ref, rtol=1e-3, atol=1e-2)

    def test_inner_product(self, dataset):
        x, q = dataset
        xn = x / np.linalg.norm(x, axis=1, keepdims=True)
        qn = q / np.linalg.norm(q, axis=1, keepdims=True)
        params = CagraIndexParams(
            intermediate_graph_degree=48, graph_degree=24,
            build_algo=BuildAlgo.NN_DESCENT,
            metric=DistanceType.InnerProduct,
        )
        index = cagra.build(None, params, xn)
        sp = CagraSearchParams(itopk_size=64, search_width=4)
        d, i = cagra.search(None, sp, index, qn, 10)
        sims = qn @ xn.T
        gt = np.argsort(-sims, axis=1, kind="stable")[:, :10]
        r, _, _ = eval_recall(gt, np.asarray(i))
        assert r >= 0.85, f"ip recall {r}"
        # similarities descending
        assert np.all(np.diff(np.asarray(d), axis=1) <= 1e-4)

    def test_serialization_roundtrip(self, dataset):
        x, q = dataset
        params = CagraIndexParams(intermediate_graph_degree=32,
                                  graph_degree=16,
                                  build_algo=BuildAlgo.NN_DESCENT)
        index = cagra.build(None, params, x)
        buf = io.BytesIO()
        cagra.save(index, buf)
        buf.seek(0)
        loaded = cagra.load(None, buf)
        np.testing.assert_array_equal(np.asarray(index.graph),
                                      np.asarray(loaded.graph))
        sp = CagraSearchParams(itopk_size=32, search_width=2)
        d0, i0 = cagra.search(None, sp, index, q, 5)
        d1, i1 = cagra.search(None, sp, loaded, q, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))

    def test_save_without_dataset(self, dataset):
        x, _ = dataset
        params = CagraIndexParams(intermediate_graph_degree=32,
                                  graph_degree=16,
                                  build_algo=BuildAlgo.NN_DESCENT)
        index = cagra.build(None, params, x)
        buf = io.BytesIO()
        cagra.save(index, buf, include_dataset=False)
        buf.seek(0)
        loaded = cagra.load(None, buf, dataset=x)
        np.testing.assert_array_equal(np.asarray(index.graph),
                                      np.asarray(loaded.graph))


class TestCagraFilter:
    def test_sample_filter(self, dataset):
        """search_with_filtering semantics: filtered-out ids never
        returned; recall over the allowed subset stays high."""
        from raft_tpu.core.bitset import Bitset

        x, q = dataset
        params = CagraIndexParams(graph_degree=32,
                                  intermediate_graph_degree=64,
                                  build_algo=BuildAlgo.NN_DESCENT)
        index = cagra.build(None, params, x)
        mask = np.ones(len(x), bool)
        mask[::2] = False  # remove even ids
        filt = Bitset.from_mask(mask)
        sp = CagraSearchParams(itopk_size=64)
        _, idx = cagra.search(None, sp, index, q, 10, sample_filter=filt)
        idx = np.asarray(idx)
        valid = idx[idx >= 0]
        assert valid.size > 0
        assert (valid % 2 == 1).all()

        # recall against the filtered ground truth
        d = spd.cdist(q, x, "sqeuclidean")
        d[:, ~mask] = np.inf
        gt = np.argsort(d, axis=1, kind="stable")[:, :10]
        r, _, _ = eval_recall(gt, idx)
        assert r >= 0.7, r


class TestPooledSeeding:
    def test_seed_pool_beats_random_on_clusters(self, dataset):
        """Query-aware seeding removes the random-seed recall ceiling on
        clustered data (pathological case: many tight clusters)."""
        rng = np.random.default_rng(3)
        centers = rng.standard_normal((64, 16)) * 6
        x = (centers[rng.integers(0, 64, 8000)]
             + rng.standard_normal((8000, 16))).astype(np.float32)
        q = (centers[rng.integers(0, 64, 64)]
             + rng.standard_normal((64, 16))).astype(np.float32)
        params = CagraIndexParams(graph_degree=24,
                                  intermediate_graph_degree=48,
                                  build_algo=BuildAlgo.NN_DESCENT)
        index = cagra.build(None, params, x)
        gt = np.argsort(spd.cdist(q, x, "sqeuclidean"), axis=1,
                        kind="stable")[:, :10]
        sp_rand = CagraSearchParams(itopk_size=32, search_width=1)
        _, i_rand = cagra.search(None, sp_rand, index, q, 10)
        r_rand, _, _ = eval_recall(gt, np.asarray(i_rand))
        sp_pool = CagraSearchParams(itopk_size=32, search_width=1,
                                    seed_pool=2048)
        _, i_pool = cagra.search(None, sp_pool, index, q, 10)
        r_pool, _, _ = eval_recall(gt, np.asarray(i_pool))
        assert r_pool >= r_rand, (r_pool, r_rand)
        assert r_pool >= 0.95, (r_pool, r_rand)


class TestIntDataset:
    def test_int8_dataset_self_hit(self):
        rng = np.random.default_rng(0)
        x = rng.integers(-100, 100, (2000, 32)).astype(np.int8)
        q = x[:8].astype(np.float32)
        index = cagra.build(
            None,
            CagraIndexParams(graph_degree=16, intermediate_graph_degree=32,
                             build_algo=BuildAlgo.NN_DESCENT), x)
        _, i = cagra.search(
            None, CagraSearchParams(itopk_size=32, search_width=4),
            index, q, 5)
        assert (np.asarray(i)[:, 0] == np.arange(8)).all()


class TestCagraBitmapTiling:
    def test_per_query_bitmap_across_tiles(self, dataset):
        """BitmapFilter rows must follow their queries through host-side
        query tiling."""
        from raft_tpu.neighbors.filters import BitmapFilter

        x, q = dataset
        n = len(x)
        # 32 queries, force tiny tiles so tiling engages
        mask = np.ones((len(q), n), bool)
        for r in range(len(q)):
            mask[r, r % 2 :: 2] = False   # each query forbids one parity
        filt = BitmapFilter.from_mask(mask)
        params = CagraIndexParams(graph_degree=16,
                                  intermediate_graph_degree=32,
                                  build_algo=BuildAlgo.NN_DESCENT)
        index = cagra.build(None, params, x)
        sp = CagraSearchParams(itopk_size=32, search_width=4, query_tile=8)
        _, idx = cagra.search(None, sp, index, q, 5, sample_filter=filt)
        idx = np.asarray(idx)
        for r in range(len(q)):
            valid = idx[r][idx[r] >= 0]
            assert valid.size > 0
            assert mask[r, valid].all(), r


class TestBeamKernel:
    """The one-dispatch Pallas beam-search path (ops/beam_search), run
    in interpret mode on CPU; parity vs the XLA while_loop engine."""

    @pytest.fixture(scope="class")
    def wide_dataset(self):
        rng = np.random.default_rng(21)
        centers = rng.standard_normal((10, 128)) * 4
        labels = rng.integers(0, 10, 1500)
        x = (centers[labels]
             + rng.standard_normal((1500, 128))).astype(np.float32)
        q = (centers[rng.integers(0, 10, 20)]
             + rng.standard_normal((20, 128))).astype(np.float32)
        return x, q

    @pytest.fixture(scope="class")
    def wide_index(self, wide_dataset):
        x, _ = wide_dataset
        return cagra.build(None, CagraIndexParams(
            graph_degree=16, intermediate_graph_degree=32,
            build_algo=BuildAlgo.NN_DESCENT), x)

    @pytest.mark.parametrize("kw", [
        dict(itopk_size=64, search_width=4),
        # L (128) > w*deg (64): chunked seed rounds must keep parity
        dict(itopk_size=128, search_width=4),
        # extra seed draws ride the same chunked path
        dict(itopk_size=64, search_width=4, num_random_samplings=2),
    ])
    def test_matches_xla_engine_exactly(self, wide_dataset, wide_index,
                                        kw):
        """Both engines draw one shared seed set -> identical ids."""
        x, q = wide_dataset
        idx = wide_index
        dx, ix = cagra.search(None, CagraSearchParams(algo="xla", **kw),
                              idx, q, 10)
        dp, ip = cagra.search(None, CagraSearchParams(algo="pallas", **kw),
                              idx, q, 10)
        np.testing.assert_array_equal(np.asarray(ix), np.asarray(ip))
        np.testing.assert_allclose(np.asarray(dx), np.asarray(dp),
                                   rtol=1e-4, atol=1e-4)

    def test_recall_and_bf16(self, wide_dataset, wide_index):
        import jax.numpy as jnp

        x, q = wide_dataset
        _, gt = _gt(x, q, 10)
        idx16 = cagra.CagraIndex(dataset=jnp.asarray(x, jnp.bfloat16),
                                 graph=wide_index.graph,
                                 metric=wide_index.metric)
        for idx in (wide_index, idx16):
            _, i = cagra.search(
                None, CagraSearchParams(itopk_size=64, search_width=4,
                                        algo="pallas"), idx, q, 10)
            r, _, _ = eval_recall(gt, np.asarray(i))
            assert r >= 0.9, r

    def test_int8_dataset(self, wide_dataset, wide_index):
        """CAGRA-Q role: int8-quantized dataset rides the kernel (a
        quarter of the f32 VMEM residency); uniform scaling preserves
        the L2 ranking, so recall holds without refine here."""
        import jax.numpy as jnp

        x, q = wide_dataset
        scale = np.abs(x).max() / 127.0
        x8 = np.clip(np.round(x / scale), -127, 127).astype(np.int8)
        idx8 = cagra.CagraIndex(dataset=jnp.asarray(x8),
                                graph=wide_index.graph,
                                metric=wide_index.metric)
        _, i = cagra.search(
            None, CagraSearchParams(itopk_size=64, search_width=4,
                                    algo="pallas"),
            idx8, q / scale, 10)
        _, gt = _gt(x, q, 10)
        r, _, _ = eval_recall(gt, np.asarray(i))
        assert r >= 0.85, r

    def test_inner_product(self, wide_dataset):
        x, q = wide_dataset
        xn = (x / np.linalg.norm(x, axis=1, keepdims=True)).astype(np.float32)
        qn = (q / np.linalg.norm(q, axis=1, keepdims=True)).astype(np.float32)
        idx = cagra.build(None, CagraIndexParams(
            graph_degree=16, intermediate_graph_degree=32,
            build_algo=BuildAlgo.NN_DESCENT,
            metric=DistanceType.InnerProduct), xn)
        d, i = cagra.search(None, CagraSearchParams(
            itopk_size=64, search_width=4, algo="pallas"), idx, qn, 10)
        sim = qn @ xn.T
        gt = np.argsort(-sim, axis=1, kind="stable")[:, :10]
        r, _, _ = eval_recall(gt, np.asarray(i))
        assert r >= 0.9, r
        # distances come back as similarities (larger = closer)
        np.testing.assert_allclose(
            np.asarray(d)[:, 0], np.take_along_axis(sim, np.asarray(i), 1)[:, 0],
            rtol=1e-4, atol=1e-4)

    def test_constraint_errors(self, dataset):
        from raft_tpu.core.validation import RaftError

        x, _ = dataset   # dim=24, not lane-aligned
        idx = cagra.build(None, CagraIndexParams(
            graph_degree=16, intermediate_graph_degree=32,
            build_algo=BuildAlgo.NN_DESCENT), x)
        with pytest.raises(RaftError, match="pallas"):
            cagra.search(None, CagraSearchParams(algo="pallas"), idx,
                         x[:4], 5)


class TestGraftbeamSeeds:
    """graftbeam seed contract: coarse seeding from the build-time
    plane, purity under batching, and the ~8x seed_pool reduction the
    acceptance criteria pin."""

    @pytest.fixture(scope="class")
    def clustered(self):
        rng = np.random.default_rng(3)
        centers = rng.standard_normal((64, 16)) * 6
        x = (centers[rng.integers(0, 64, 8000)]
             + rng.standard_normal((8000, 16))).astype(np.float32)
        q = (centers[rng.integers(0, 64, 64)]
             + rng.standard_normal((64, 16))).astype(np.float32)
        index = cagra.build(None, CagraIndexParams(
            graph_degree=24, intermediate_graph_degree=48,
            build_algo=BuildAlgo.NN_DESCENT), x)
        gt = np.argsort(spd.cdist(q, x, "sqeuclidean"), axis=1,
                        kind="stable")[:, :10]
        return x, q, index, gt

    def test_batching_invariance(self, dataset):
        """Seeds are a pure function of query content: any
        concatenation of query blocks returns each block's solo rows
        bit-identically (the property the executor's per-block
        dispatch exemption died for)."""
        x, q = dataset
        index = cagra.build(None, CagraIndexParams(
            graph_degree=16, intermediate_graph_degree=32,
            build_algo=BuildAlgo.NN_DESCENT), x)
        sp = CagraSearchParams(itopk_size=32, search_width=2)
        d_all, i_all = cagra.search(None, sp, index, q, 5)
        d_all, i_all = np.asarray(d_all), np.asarray(i_all)
        for lo, hi in ((0, 7), (7, 12), (12, 32)):
            d, i = cagra.search(None, sp, index, q[lo:hi], 5)
            np.testing.assert_array_equal(np.asarray(i), i_all[lo:hi])
            np.testing.assert_array_equal(np.asarray(d), d_all[lo:hi])

    def test_coarse_beats_pool_at_8x_smaller_budget(self, clustered):
        """The frontier shift in miniature: coarse seeding at
        seed_pool=256 reaches the recall the strided pool needs
        seed_pool=2048 for (8x)."""
        x, q, index, gt = clustered
        assert index.seed_centers is not None
        sp_pool = CagraSearchParams(itopk_size=32, search_width=1,
                                    seed_mode="pool", seed_pool=2048)
        _, i_pool = cagra.search(None, sp_pool, index, q, 10)
        r_pool, _, _ = eval_recall(gt, np.asarray(i_pool))
        sp_coarse = CagraSearchParams(itopk_size=32, search_width=1,
                                      seed_mode="coarse", seed_pool=256)
        _, i_coarse = cagra.search(None, sp_coarse, index, q, 10)
        r_coarse, _, _ = eval_recall(gt, np.asarray(i_coarse))
        assert r_coarse >= r_pool, (r_coarse, r_pool)
        assert r_coarse >= 0.95, r_coarse

    def test_seed_plane_serializes(self, clustered):
        """Round-tripped indexes keep the coarse plane (and hence
        bit-identical coarse-seeded results)."""
        _, q, index, _ = clustered
        buf = io.BytesIO()
        cagra.save(index, buf)
        buf.seek(0)
        loaded = cagra.load(None, buf)
        assert loaded.seed_centers is not None
        sp = CagraSearchParams(itopk_size=32, seed_mode="coarse")
        d0, i0 = cagra.search(None, sp, index, q, 10)
        d1, i1 = cagra.search(None, sp, loaded, q, 10)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))

    def test_degenerate_data_drops_empty_lists(self):
        """Duplicate-heavy data collapses balanced k-means; the plane
        must keep only non-empty lists so every probed list yields at
        least one valid seed (a query probing an empty list would open
        the beam with no entries -> all-inf row)."""
        from raft_tpu.core.resources import ensure_resources
        from raft_tpu.neighbors.cagra import _build_seed_plane

        rng = np.random.default_rng(0)
        base = rng.standard_normal((10, 16)).astype(np.float32)
        x = np.concatenate([np.repeat(base, 90, axis=0),
                            np.zeros((100, 16), np.float32)])
        centers, members = _build_seed_plane(
            ensure_resources(None), x, DistanceType.L2Expanded, 32)
        sizes = np.asarray((np.asarray(members) >= 0).sum(axis=1))
        assert (sizes > 0).all()
        assert centers.shape[0] == members.shape[0] <= 32
        # every dataset row appears exactly once across the lists
        flat = np.asarray(members).ravel()
        assert np.array_equal(np.sort(flat[flat >= 0]),
                              np.arange(x.shape[0]))

    def test_plane_less_index_falls_back_to_pool(self, dataset):
        """Hand-assembled indexes (no build(): hnsw round-trips, raw
        CagraIndex) keep working through the query-aware pool."""
        import jax.numpy as jnp

        x, q = dataset
        built = cagra.build(None, CagraIndexParams(
            graph_degree=16, intermediate_graph_degree=32,
            build_algo=BuildAlgo.NN_DESCENT), x)
        bare = cagra.CagraIndex(dataset=jnp.asarray(x),
                                graph=built.graph, metric=built.metric)
        _, gt = _gt(x, q, 10)
        _, i = cagra.search(None, CagraSearchParams(itopk_size=64),
                            bare, q, 10)
        r, _, _ = eval_recall(gt, np.asarray(i))
        assert r >= 0.9, r
        from raft_tpu.core.validation import RaftError

        with pytest.raises(RaftError, match="coarse"):
            cagra.search(None, CagraSearchParams(seed_mode="coarse"),
                         bare, q, 10)


class TestBqTraversal:
    """graftbeam BQ-coded traversal: estimate-then-exact-rerank on the
    neighbor-gather path, engine parity with the plane on and off."""

    @pytest.fixture(scope="class")
    def bq_setup(self):
        rng = np.random.default_rng(11)
        centers = rng.standard_normal((10, 128)) * 4
        x = (centers[rng.integers(0, 10, 1500)]
             + rng.standard_normal((1500, 128))).astype(np.float32)
        q = (centers[rng.integers(0, 10, 20)]
             + rng.standard_normal((20, 128))).astype(np.float32)
        index = cagra.build(None, CagraIndexParams(
            graph_degree=16, intermediate_graph_degree=32,
            build_algo=BuildAlgo.NN_DESCENT, bq_bits=2), x)
        return x, q, index

    def test_recall_holds_with_bq_pruning(self, bq_setup):
        """Exact rerank of estimate-survivors: the margin keeps the
        pruned beam's recall at the unpruned beam's level."""
        x, q, index = bq_setup
        assert index.bq_records is not None and index.bq_bits == 2
        _, gt = _gt(x, q, 10)
        sp_off = CagraSearchParams(itopk_size=64, search_width=4,
                                   bq_traversal="off")
        _, i_off = cagra.search(None, sp_off, index, q, 10)
        r_off, _, _ = eval_recall(gt, np.asarray(i_off))
        sp_on = CagraSearchParams(itopk_size=64, search_width=4,
                                  bq_traversal="on")
        _, i_on = cagra.search(None, sp_on, index, q, 10)
        r_on, _, _ = eval_recall(gt, np.asarray(i_on))
        assert r_on >= r_off - 0.02, (r_on, r_off)
        assert r_on >= 0.9, r_on

    @pytest.mark.parametrize("bq", ["on", "off"])
    def test_pallas_xla_parity(self, bq_setup, bq):
        """The kernel's per-candidate record gather + estimate prunes
        the SAME candidates as the XLA twin: identical ids either
        way."""
        _, q, index = bq_setup
        kw = dict(itopk_size=64, search_width=4, bq_traversal=bq)
        dx, ix = cagra.search(None, CagraSearchParams(algo="xla", **kw),
                              index, q, 10)
        dp, ip = cagra.search(
            None, CagraSearchParams(algo="pallas", **kw), index, q, 10)
        np.testing.assert_array_equal(np.asarray(ix), np.asarray(ip))
        np.testing.assert_allclose(np.asarray(dx), np.asarray(dp),
                                   rtol=1e-4, atol=1e-4)

    def test_bq_serializes(self, bq_setup):
        _, q, index = bq_setup
        buf = io.BytesIO()
        cagra.save(index, buf)
        buf.seek(0)
        loaded = cagra.load(None, buf)
        assert loaded.bq_bits == 2 and loaded.bq_records is not None
        sp = CagraSearchParams(itopk_size=64, bq_traversal="on")
        _, i0 = cagra.search(None, sp, index, q, 10)
        _, i1 = cagra.search(None, sp, loaded, q, 10)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))

    def test_bq_on_requires_plane(self, dataset):
        from raft_tpu.core.validation import RaftError

        x, q = dataset
        index = cagra.build(None, CagraIndexParams(
            graph_degree=16, intermediate_graph_degree=32,
            build_algo=BuildAlgo.NN_DESCENT), x)
        with pytest.raises(RaftError, match="bq_bits"):
            cagra.search(None, CagraSearchParams(bq_traversal="on"),
                         index, q, 5)


class TestBf16Dataset:
    def test_store_dtype_build(self, dataset):
        """build(store_dtype='bfloat16') halves storage; search quality
        holds and serialization round-trips the half-width dataset."""
        import io as _io

        import jax.numpy as jnp

        x, q = dataset
        idx = cagra.build(None, CagraIndexParams(
            graph_degree=16, intermediate_graph_degree=32,
            build_algo=BuildAlgo.NN_DESCENT,
            storage_dtype="bfloat16"), x)
        assert idx.dataset.dtype == jnp.bfloat16
        _, gt = _gt(x, q, 10)
        _, i = cagra.search(None, CagraSearchParams(itopk_size=64,
                                                    search_width=4),
                            idx, q, 10)
        r, _, _ = eval_recall(gt, np.asarray(i))
        assert r >= 0.9, r
        buf = _io.BytesIO()
        cagra.save(idx, buf)
        buf.seek(0)
        idx2 = cagra.load(None, buf)
        assert idx2.dataset.dtype == jnp.bfloat16

    def test_bf16_search(self, dataset):
        """CAGRA over a bf16-stored dataset (halves the per-iteration
        gather bytes): search quality matches the f32 index."""
        import jax.numpy as jnp

        x, q = dataset
        idx32 = cagra.build(None, CagraIndexParams(
            graph_degree=16, intermediate_graph_degree=32,
            build_algo=BuildAlgo.NN_DESCENT), x)
        idx16 = cagra.CagraIndex(
            dataset=jnp.asarray(x, jnp.bfloat16),
            graph=idx32.graph, metric=idx32.metric)
        _, gt = _gt(x, q, 10)
        _, i = cagra.search(None, CagraSearchParams(itopk_size=32),
                            idx16, q, 10)
        r, _, _ = eval_recall(gt, np.asarray(i))
        assert r >= 0.9, r
