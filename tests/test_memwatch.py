"""graftledger tests (PR 13) — the memory-truth plane.

The acceptance criteria this file carries: the resident-bytes model is
pinned BYTE-EXACT against at least one real index per family (flat /
PQ / BQ, the BQ one with AND without its rerank plane) and per shard
on the mesh; ``memory_stats()``-unsupported backends (CPU — the tier-1
environment) degrade honestly to ``supported: False`` instead of
invented numbers; the reservation forecast's arithmetic is pinned
against the executor's real donated-state/temp reservations; the
opt-in capacity gate refuses a build host-side with a typed
:class:`~raft_tpu.core.memwatch.CapacityExceeded` BEFORE any device
allocation; zero-recompile and bit-identity stay green with the
ledger fully enabled, single-chip AND mesh; and the exporter /
flight-recorder surfaces (``/memory.json``, ``/memory_profile``, the
low-headroom incident trigger) serve the same numbers.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from raft_tpu.comms import local_comms
from raft_tpu.core import memwatch, tracing
from raft_tpu.core.executor import SearchExecutor
from raft_tpu.core.memwatch import CapacityExceeded, MemoryLedger
from raft_tpu.distributed import ivf as dist_ivf
from raft_tpu.neighbors import brute_force, ivf_bq, ivf_flat, ivf_pq
from raft_tpu.serving import metrics
from raft_tpu.serving.harness import ManualClock


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    x = rng.standard_normal((512, 32)).astype(np.float32)
    q = rng.standard_normal((16, 32)).astype(np.float32)
    return x, q


@pytest.fixture(scope="module")
def flat_index(data):
    x, _ = data
    return ivf_flat.build(None, ivf_flat.IvfFlatIndexParams(n_lists=8), x)


@pytest.fixture(autouse=True)
def _reset():
    metrics.reset()
    memwatch.remove_gate()
    yield
    memwatch.remove_gate()


def model_vs_nbytes(index):
    """The byte-exact pin: the model must equal the arrays' own
    ``nbytes``, component by component and in total."""
    import dataclasses

    model = memwatch.index_memory_model(index)
    total = 0
    for f in dataclasses.fields(index):
        v = getattr(index, f.name, None)
        if v is None or not hasattr(v, "nbytes"):
            continue
        assert model["components"][f.name]["bytes"] == v.nbytes, f.name
        total += v.nbytes
    assert model["resident_bytes"] == total
    return model


class TestResidentModel:
    """Byte-exact pins of the resident-bytes model, per family."""

    def test_flat_byte_exact(self, flat_index):
        model = model_vs_nbytes(flat_index)
        # single-chip: per-shard == global (nothing is sharded)
        assert model["shard_resident_bytes"] == model["resident_bytes"]
        assert set(model["components"]) == {
            "centers", "center_norms", "data", "data_norms",
            "indices", "list_sizes"}

    def test_pq_byte_exact(self, data):
        x, _ = data
        idx = ivf_pq.build(
            None, ivf_pq.IvfPqIndexParams(n_lists=8, pq_dim=8), x)
        model = model_vs_nbytes(idx)
        assert "codes" in model["components"]
        assert "codebooks" in model["components"]

    def test_bq_byte_exact_with_and_without_rerank_plane(self, data):
        x, _ = data
        with_plane = ivf_bq.build(
            None, ivf_bq.IvfBqIndexParams(n_lists=8), x)
        codes_only = ivf_bq.build(
            None, ivf_bq.IvfBqIndexParams(n_lists=8,
                                          store_vectors=False), x)
        m1 = model_vs_nbytes(with_plane)
        m0 = model_vs_nbytes(codes_only)
        # the rerank plane is exactly the raw-vector + norm planes:
        # the codes-only model must be smaller by exactly their bytes
        assert "data" in m1["components"]
        assert "data" not in m0["components"]
        plane = (m1["components"]["data"]["bytes"]
                 + m1["components"]["data_norms"]["bytes"])
        assert m1["resident_bytes"] - m0["resident_bytes"] == plane
        # the correction scalars and packed words are all modeled
        for comp in ("codes", "rnorm", "cfac", "errw"):
            assert comp in m0["components"]

    def test_brute_force_byte_exact(self, data):
        x, _ = data
        idx = brute_force.build(None, x)
        model = model_vs_nbytes(idx)
        assert model["components"]["dataset"]["bytes"] == x.nbytes

    def test_known_layout_pinned(self):
        """The model against hand-computed numbers for a fixed
        layout — a model change must move THIS pin, not only the
        nbytes identity."""
        x = np.zeros((256, 32), np.float32)
        idx = ivf_flat.build(
            None, ivf_flat.IvfFlatIndexParams(n_lists=4), x)
        n_lists, mls, d = 4, idx.max_list_size, 32
        expected = (
            n_lists * d * 4            # centers f32
            + n_lists * 4              # center_norms f32
            + n_lists * mls * d * 4    # data f32
            + n_lists * mls * 4        # data_norms f32
            + n_lists * mls * 4        # indices i32
            + n_lists * 4)             # list_sizes i32
        model = memwatch.index_memory_model(idx)
        assert model["resident_bytes"] == expected

    def test_mesh_per_shard(self, data):
        """Mesh-sharded index: global bytes match nbytes; per-shard
        bytes follow each array's own sharding (list-sharded planes
        are 1/R of global on the 8-virtual-device mesh)."""
        x, _ = data
        comms = local_comms()
        didx = dist_ivf.build(
            None, comms, ivf_flat.IvfFlatIndexParams(n_lists=32), x)
        model = model_vs_nbytes(didx)
        r = comms.size
        comp = model["components"]
        # list-sharded planes shard 1/R per device
        assert comp["data"]["shard_bytes"] == comp["data"]["bytes"] // r
        assert comp["indices"]["shard_bytes"] == \
            comp["indices"]["bytes"] // r
        assert model["shard_resident_bytes"] < model["resident_bytes"]
        # per-device map covers every mesh device, and sums to the
        # global total (each byte lands on exactly one device for
        # fully-sharded planes)
        assert set(model["per_device_bytes"]) == {
            int(d.id) for d in comms.mesh.devices.flat}


class TestLiveStatsFallback:
    """The memory_stats()-unsupported path — CPU is the tier-1
    backend, so this IS the honest-fallback proof."""

    def test_supported_false_on_cpu(self):
        stats = memwatch.device_memory_stats()
        assert stats["supported"] is False
        assert stats["devices"] == {}

    def test_snapshot_degrades_honestly(self, flat_index):
        ledger = MemoryLedger()
        ledger.watch("flat", flat_index)
        snap = ledger.snapshot()
        assert snap["supported"] is False
        assert snap["devices"] == {}
        # no live truth -> no divergence, no invented headroom
        assert snap["divergence_bytes"] is None
        assert snap["headroom_bytes"] is None
        # ... but the MODEL keeps working
        assert snap["resident_total_bytes"] > 0
        ledger.publish()
        assert tracing.get_gauge("memory.live.supported") == 0.0
        assert tracing.get_gauge("memory.hbm.headroom_bytes") == -1.0
        assert tracing.get_gauge(
            "memory.index.flat.resident_bytes") > 0

    def test_capacity_override_restores_headroom(self, flat_index):
        model = memwatch.index_memory_model(flat_index)
        cap = model["resident_bytes"] + 10_000
        ledger = MemoryLedger(capacity_bytes=cap)
        ledger.watch("flat", flat_index)
        room = ledger.headroom_bytes()
        assert room == pytest.approx(
            cap - ledger.forecast()["peak_bytes"])


class TestLiveArithmetic:
    """The supported-backend arithmetic (headroom, divergence,
    watermark), pinned with injected stats — CPU cannot produce them
    live, but the formulas must not wait for a TPU to be wrong."""

    def fake_stats(self):
        return {"supported": True, "devices": {
            0: {"in_use_bytes": 6e6, "peak_bytes": 7e6,
                "limit_bytes": 8e6},
            1: {"in_use_bytes": 5e6, "peak_bytes": 6e6,
                "limit_bytes": 8e6},
        }}

    def test_headroom_divergence_watermark(self, flat_index,
                                           monkeypatch):
        monkeypatch.setattr(memwatch, "device_memory_stats",
                            lambda devices=None: self.fake_stats())
        ledger = MemoryLedger()
        ledger.watch("flat", flat_index)
        # headroom = min over devices of limit - in_use (device 0)
        assert ledger.headroom_bytes() == 8e6 - 6e6
        snap = ledger.snapshot()
        assert snap["supported"] is True
        # divergence = total live in-use - modeled residency terms
        model = memwatch.index_memory_model(flat_index)
        assert snap["divergence_bytes"] == \
            (6e6 + 5e6) - model["resident_bytes"]
        # the dispatch watermark folds the in-use total
        ledger.sample_dispatch()
        assert ledger.snapshot()["watermark"]["in_use_peak_bytes"] \
            == 6e6 + 5e6
        ledger.publish()
        assert tracing.get_gauge("memory.live.supported") == 1.0
        assert tracing.get_gauge(
            "memory.device.0.in_use_bytes") == 6e6
        assert tracing.get_gauge("memory.hbm.headroom_bytes") == 2e6

    def test_live_limit_beats_configured_capacity(self, flat_index,
                                                  monkeypatch):
        monkeypatch.setattr(memwatch, "device_memory_stats",
                            lambda devices=None: self.fake_stats())
        # a configured capacity is the fallback, not an override:
        # measured truth wins when the backend provides it
        ledger = MemoryLedger(capacity_bytes=1.0)
        assert ledger.headroom_bytes() == 2e6

    def test_snapshot_reads_backend_once(self, flat_index,
                                         monkeypatch):
        """Review hardening: one snapshot = one backend stats read +
        one model walk — headroom/divergence derive from the same
        inputs instead of re-reading per field."""
        calls = {"n": 0}

        def counting(devices=None):
            calls["n"] += 1
            return self.fake_stats()

        monkeypatch.setattr(memwatch, "device_memory_stats", counting)
        ledger = MemoryLedger(capacity_bytes=1e9)
        ledger.watch("flat", flat_index)
        ledger.snapshot()
        assert calls["n"] == 1


class TestForecast:
    """The reservation forecast pinned against the executor's real
    reservations — byte-exact arithmetic, no tolerance."""

    def test_terms_pinned(self, data, flat_index):
        _, q = data
        ex = SearchExecutor(min_bucket=16, max_bucket=16)
        ledger = MemoryLedger(executor=ex)
        label = ledger.watch("flat", flat_index)
        assert label == "flat"
        ex.search(flat_index, q, 5,
                  ivf_flat.IvfFlatSearchParams(scan_engine="xla"))
        res = ex.memory_reservations()
        # ONE xla-engine entry at bucket 16, k 5: the donated (16, 5)
        # f32 + i32 state pair
        assert sum(res["donated_state_bytes"].values()) == \
            16 * 5 * (4 + 4)
        assert res["executables"] == 1
        costs = ex.executable_costs()
        max_temp = max(c.get("temp_bytes", 0.0) for c in costs.values())
        assert res["max_temp_bytes"] == max_temp
        fc = ledger.forecast()
        model = memwatch.index_memory_model(flat_index)
        assert fc["resident_bytes"] == model["resident_bytes"]
        assert fc["donated_state_bytes"] == 16 * 5 * 8
        assert fc["max_temp_bytes"] == max_temp
        # single chip: everything lands on device 0, and the peak is
        # exactly the sum of the three terms
        assert fc["peak_bytes"] == (model["resident_bytes"]
                                    + 16 * 5 * 8 + max_temp)

    def test_probe_plane_term(self, data, flat_index):
        _, q = data
        ex = SearchExecutor(min_bucket=16, max_bucket=16,
                            probe_accounting=True)
        ledger = MemoryLedger(executor=ex)
        ledger.watch("flat", flat_index)
        ex.search(flat_index, q, 5)
        fc = ledger.forecast()
        # one int32 plane of n_lists entries
        assert fc["probe_plane_bytes"] == flat_index.n_lists * 4

    def test_dead_index_drops_from_model(self, data):
        x, _ = data
        ledger = MemoryLedger()
        idx = ivf_flat.build(
            None, ivf_flat.IvfFlatIndexParams(n_lists=4), x)
        ledger.watch("tmp", idx)
        assert "tmp" in ledger.resident()
        del idx
        import gc

        gc.collect()
        assert "tmp" not in ledger.resident()


class TestCapacityGate:
    """fits() + the opt-in CapacityExceeded gate on build/extend."""

    def test_fits_unknown_is_distinguishable(self, flat_index):
        ledger = MemoryLedger()          # no live stats, no capacity
        verdict = ledger.fits(flat_index)
        assert verdict["fits"] is True and verdict["unknown"] is True
        assert verdict["headroom_bytes"] is None

    def test_fits_against_capacity(self, flat_index):
        model = memwatch.index_memory_model(flat_index)
        # room for one-and-a-half copies: the first fits, a second
        # copy next to it does not
        ledger = MemoryLedger(
            capacity_bytes=1.5 * model["resident_bytes"])
        assert ledger.fits(flat_index)["fits"] is True
        ledger.watch("flat", flat_index)
        assert ledger.fits(flat_index)["fits"] is False
        # safety_fraction tightens the verdict further: with the full
        # capacity free, reserving 60% refuses what 0% admits
        empty = MemoryLedger(
            capacity_bytes=1.5 * model["resident_bytes"])
        assert empty.fits(flat_index,
                          safety_fraction=0.6)["fits"] is False

    def test_gate_refuses_build_host_side(self, data, flat_index):
        x, _ = data
        ledger = MemoryLedger(capacity_bytes=1000)
        memwatch.install_gate(ledger)
        with pytest.raises(CapacityExceeded) as e:
            ivf_flat.build(
                None, ivf_flat.IvfFlatIndexParams(n_lists=8), x)
        assert e.value.required_bytes > 1000
        assert e.value.headroom_bytes == 1000
        assert "ivf_flat.extend" in str(e.value)
        assert tracing.get_counter("memory.gate.refused") >= 1
        # gate removed -> same build admits again
        memwatch.remove_gate()
        ivf_flat.build(None, ivf_flat.IvfFlatIndexParams(n_lists=8), x)

    def test_gate_covers_every_family(self, data):
        x, _ = data
        memwatch.install_gate(MemoryLedger(capacity_bytes=100))
        with pytest.raises(CapacityExceeded, match="ivf_pq.extend"):
            ivf_pq.build(
                None, ivf_pq.IvfPqIndexParams(n_lists=8, pq_dim=8), x)
        with pytest.raises(CapacityExceeded, match="ivf_bq.extend"):
            ivf_bq.build(None, ivf_bq.IvfBqIndexParams(n_lists=8), x)
        with pytest.raises(CapacityExceeded,
                           match="brute_force.build"):
            brute_force.build(None, x)

    def test_gate_covers_build_streaming(self, data, tmp_path):
        """Review hardening: the streaming builds allocate the full
        padded layout directly — the gate must see them too (the
        'corpus ≫ headroom' path is exactly what streaming serves)."""
        from raft_tpu.io import BinDataset, write_bin

        x, _ = data
        path = tmp_path / "d.fbin"
        write_bin(path, x)
        memwatch.install_gate(MemoryLedger(capacity_bytes=100))
        with BinDataset(path) as ds:
            with pytest.raises(CapacityExceeded,
                               match="ivf_flat.build_streaming"):
                ivf_flat.build_streaming(
                    None, ivf_flat.IvfFlatIndexParams(n_lists=8), ds,
                    chunk_rows=256)
            with pytest.raises(CapacityExceeded,
                               match="ivf_pq.build_streaming"):
                ivf_pq.build_streaming(
                    None, ivf_pq.IvfPqIndexParams(n_lists=8, pq_dim=8),
                    ds, chunk_rows=256)
            with pytest.raises(CapacityExceeded,
                               match="ivf_bq.build_streaming"):
                ivf_bq.build_streaming(
                    None, ivf_bq.IvfBqIndexParams(n_lists=8), ds,
                    chunk_rows=256)

    def test_gate_admits_within_capacity(self, data):
        x, _ = data
        memwatch.install_gate(MemoryLedger(capacity_bytes=10**9))
        admitted0 = tracing.get_counter("memory.gate.admitted")
        idx = ivf_flat.build(
            None, ivf_flat.IvfFlatIndexParams(n_lists=8), x)
        assert idx.size == x.shape[0]
        assert tracing.get_counter("memory.gate.admitted") > admitted0

    def test_extend_gated_on_growth(self, data):
        """An extend that must grow the padded extent re-allocates —
        the gate sees exactly that repack."""
        x, _ = data
        idx = ivf_flat.build(
            None, ivf_flat.IvfFlatIndexParams(n_lists=8), x)
        model = memwatch.index_memory_model(idx)
        # capacity admits the index once but not a grown repack
        memwatch.install_gate(
            MemoryLedger(capacity_bytes=model["resident_bytes"]))
        rng = np.random.default_rng(3)
        with pytest.raises(CapacityExceeded):
            ivf_flat.extend(
                None, idx,
                rng.standard_normal((512, 32)).astype(np.float32))


class TestLedgerOnIdentity:
    """The acceptance criterion: zero-recompile + bit-identity stay
    green with the memory ledger fully enabled (watermark sampling on
    every dispatch), single-chip and mesh."""

    def test_single_chip(self, data, flat_index):
        _, q = data
        tracing.install_xla_compile_listener()
        params = ivf_flat.IvfFlatSearchParams(n_probes=4)
        bare = SearchExecutor(min_bucket=16, max_bucket=16)
        d0, i0 = bare.search(flat_index, q, 5, params)
        ex = SearchExecutor(min_bucket=16, max_bucket=16)
        ledger = MemoryLedger(executor=ex)
        ledger.watch("flat", flat_index)
        samples0 = tracing.get_counter(memwatch.SAMPLES)
        d1, i1 = ex.search(flat_index, q, 5, params)
        # bit-identity vs the ledger-free executor
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
        # zero-recompile in steady state with sampling live
        backend0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        compiles0 = ex.stats.compile_count
        for _ in range(4):
            ex.search(flat_index, q, 5, params)
            ledger.publish()
        assert ex.stats.compile_count == compiles0
        assert tracing.get_counter(
            tracing.XLA_COMPILE_COUNT) == backend0
        # the heartbeat the CI snapshot floor checks: one sample per
        # dispatch, even on a backend without live stats
        assert tracing.get_counter(memwatch.SAMPLES) == samples0 + 5

    def test_mesh(self, data):
        x, q = data
        comms = local_comms()
        didx = dist_ivf.build(
            None, comms, ivf_flat.IvfFlatIndexParams(n_lists=32), x)
        tracing.install_xla_compile_listener()
        params = ivf_flat.IvfFlatSearchParams(n_probes=8)
        bare = SearchExecutor(min_bucket=16, max_bucket=16)
        d0, i0 = bare.search(didx, q, 5, params)
        ex = SearchExecutor(min_bucket=16, max_bucket=16)
        ledger = MemoryLedger(executor=ex)
        ledger.watch("dist-flat", didx)
        d1, i1 = ex.search(didx, q, 5, params)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
        backend0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        compiles0 = ex.stats.compile_count
        for _ in range(3):
            ex.search(didx, q, 5, params)
            ledger.publish()
        assert ex.stats.compile_count == compiles0
        assert tracing.get_counter(
            tracing.XLA_COMPILE_COUNT) == backend0
        # the mesh model reached the gauges per shard
        assert tracing.get_gauge(
            "memory.index.dist-flat.shard_bytes") < tracing.get_gauge(
            "memory.index.dist-flat.resident_bytes")


class TestExporterSurface:
    """/memory.json + /memory_profile + the labeled families."""

    def test_memory_json_and_labeled_families(self, data, flat_index):
        from raft_tpu.serving import MetricsExporter

        _, q = data
        ex = SearchExecutor(min_bucket=16, max_bucket=16)
        ledger = MemoryLedger(executor=ex)
        ledger.watch("flat", flat_index)
        ex.search(flat_index, q, 5)
        with MetricsExporter(executor=ex, memory=ledger) as exp:
            body = json.loads(urllib.request.urlopen(
                exp.url("/memory.json"), timeout=10).read())
            text = urllib.request.urlopen(
                exp.url("/metrics"), timeout=10).read().decode()
            snap = json.loads(urllib.request.urlopen(
                exp.url("/snapshot.json"), timeout=10).read())
        model = memwatch.index_memory_model(flat_index)
        assert body["supported"] is False
        assert body["indexes"]["flat"]["resident_bytes"] == \
            model["resident_bytes"]
        assert body["forecast"]["peak_bytes"] >= model["resident_bytes"]
        lines = text.splitlines()
        assert any(l.startswith(
            'memory_index_resident_bytes{index="flat"} ')
            for l in lines)
        assert "# TYPE memory_index_resident_bytes gauge" in lines
        # the federation block rides /snapshot.json
        assert snap["memory"]["resident"]["flat"] == \
            model["resident_bytes"]
        assert snap["memory"]["headroom_bytes"] is None

    def test_memory_json_404_without_ledger(self):
        from raft_tpu.serving import MetricsExporter

        with MetricsExporter() as exp:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(exp.url("/memory.json"),
                                       timeout=10)
            assert e.value.code == 404

    def test_memory_profile_gated_and_armed(self, tmp_path):
        from raft_tpu.serving import MetricsExporter

        # unarmed: 403, same gate as /profile
        with MetricsExporter() as exp:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(exp.url("/memory_profile"),
                                       timeout=10)
            assert e.value.code == 403
        # armed: the pprof bytes land under profile_dir and the
        # response names the file
        with MetricsExporter(profile_dir=str(tmp_path)) as exp:
            out = json.loads(urllib.request.urlopen(
                exp.url("/memory_profile"), timeout=10).read())
        assert out["bytes"] > 0
        import os

        assert os.path.exists(out["path"])
        assert out["path"].startswith(str(tmp_path))

    def test_memory_profile_shares_profile_lock(self, tmp_path):
        """One profiler customer at a time, both directions: a held
        profile lock 409s /memory_profile."""
        from raft_tpu.serving import MetricsExporter

        exp = MetricsExporter(profile_dir=str(tmp_path))
        assert exp._profile_lock.acquire(blocking=False)
        try:
            with pytest.raises(RuntimeError):
                exp.memory_profile()
        finally:
            exp._profile_lock.release()
        # released -> works
        assert exp.memory_profile()["bytes"] > 0

    def test_memory_profile_never_overwrites_across_restarts(
            self, tmp_path):
        """Review hardening: the capture sequence restarts with the
        process — a 'restarted' exporter must skip existing names,
        never overwrite the pre-crash evidence."""
        from raft_tpu.serving import MetricsExporter

        first = MetricsExporter(profile_dir=str(tmp_path))
        p1 = first.memory_profile()["path"]
        restarted = MetricsExporter(profile_dir=str(tmp_path))
        p2 = restarted.memory_profile()["path"]
        assert p1 != p2
        import os

        assert os.path.exists(p1) and os.path.exists(p2)


class TestLowHeadroomIncident:
    """The graftledger -> graftflight wiring: low headroom arms an
    incident bundle carrying the memory snapshot; ManualClock pins
    the cooldown."""

    def make_flight(self, ledger, clock, **cfg):
        from raft_tpu.serving.flight import FlightConfig, FlightRecorder

        config = FlightConfig(cooldown_s=60.0, latency=None,
                              low_headroom_bytes=10_000, **cfg)
        return FlightRecorder(config=config, clock=clock,
                              capture_fn=lambda: None, memory=ledger)

    def test_trigger_and_bundle(self, flat_index):
        model = memwatch.index_memory_model(flat_index)
        # capacity barely above residency -> headroom under threshold
        ledger = MemoryLedger(
            capacity_bytes=model["resident_bytes"] + 100)
        ledger.watch("flat", flat_index)
        assert ledger.headroom_bytes() <= 10_000
        clock = ManualClock()
        flight = self.make_flight(ledger, clock)
        bundle = flight.check(clock.now())
        assert bundle is not None
        assert bundle["triggers"] == ["low_headroom"]
        # the bundle carries the evidence: the full memory snapshot
        assert bundle["memory"]["headroom_bytes"] == \
            ledger.headroom_bytes()
        assert bundle["memory"]["indexes"]["flat"]["resident_bytes"] \
            == model["resident_bytes"]
        assert tracing.get_counter(
            "incident.trigger.low_headroom") == 1

    def test_cooldown_rate_limits(self, flat_index):
        model = memwatch.index_memory_model(flat_index)
        ledger = MemoryLedger(
            capacity_bytes=model["resident_bytes"] + 100)
        ledger.watch("flat", flat_index)
        clock = ManualClock()
        flight = self.make_flight(ledger, clock)
        assert flight.check(clock.now()) is not None
        clock.advance(1.0)
        assert flight.check(clock.now()) is None    # suppressed
        assert tracing.get_counter("incident.suppressed") == 1
        clock.advance(120.0)
        assert flight.check(clock.now()) is not None

    def test_unknown_headroom_never_fires(self, flat_index):
        # CPU, no capacity configured: headroom is None — ignorance
        # is not an incident
        ledger = MemoryLedger()
        ledger.watch("flat", flat_index)
        assert ledger.headroom_bytes() is None
        clock = ManualClock()
        flight = self.make_flight(ledger, clock)
        assert flight.check(clock.now()) is None


class TestHostTierModel:
    """PR 14 (grafttier): host-tier bytes join the resident model —
    components living OFF their device's default memory (or in plain
    numpy) fold into ``host_resident_bytes`` and OUT of the device
    totals the forecast/headroom/divergence arithmetic runs on."""

    class _StubSharding:
        def __init__(self, kind):
            self.memory_kind = kind
            self.device_set = ()

    class _StubArray:
        def __init__(self, shape, dtype, kind=None):
            self.shape = shape
            self.dtype = np.dtype(dtype)
            self.sharding = (TestHostTierModel._StubSharding(kind)
                            if kind else None)

    def test_memory_tier_classification(self):
        assert memwatch.memory_tier(np.zeros((2, 2))) == "host"
        assert memwatch.memory_tier(
            self._StubArray((2, 2), np.float32)) == "device"
        assert memwatch.memory_tier(
            self._StubArray((2, 2), np.float32,
                            kind="pinned_host")) == "host"

    def test_cpu_default_host_kind_counts_as_device(self):
        """The CPU backend's default memory IS unpinned_host — an
        ordinary CPU array must never be misclassified off-device
        (that would zero the whole resident model on tier-1)."""
        import jax.numpy as jnp

        a = jnp.zeros((4, 4), jnp.float32)
        assert memwatch.memory_tier(a) == "device"

    def test_host_bytes_split_pinned(self):
        """Byte-exact pin of the device/host split on a stub index
        with one pinned-host component."""
        import dataclasses as dc

        @dc.dataclass(frozen=True)
        class StubIndex:
            hot: object
            cold: object

        idx = StubIndex(
            hot=self._StubArray((8, 16, 4), np.float32),
            cold=self._StubArray((24, 16, 4), np.float32,
                                 kind="pinned_host"))
        m = memwatch.index_memory_model(idx)
        assert m["resident_bytes"] == 8 * 16 * 4 * 4
        assert m["host_resident_bytes"] == 24 * 16 * 4 * 4
        assert m["components"]["hot"]["tier"] == "device"
        assert m["components"]["cold"]["tier"] == "host"
        # the forecast's device peak excludes the host tier
        ledger = MemoryLedger()
        ledger.watch("stub", idx)
        fc = ledger.forecast()
        assert fc["resident_bytes"] == 8 * 16 * 4 * 4
        snap = ledger.snapshot()
        assert snap["host_resident_total_bytes"] == 24 * 16 * 4 * 4
        ledger.publish()
        g = tracing.gauges()
        assert g["memory.host.resident_bytes"] == 24 * 16 * 4 * 4
        assert g["memory.index.stub.host_bytes"] == 24 * 16 * 4 * 4
        # the federation block carries it too
        assert ledger.federation_payload()[
            "host_resident_total_bytes"] == 24 * 16 * 4 * 4


class TestDistributedDealGate:
    """PR 14 satellite (ROADMAP graftledger follow-on (b)): the mesh
    deal's per-shard staging is ADMITTED, not just modeled."""

    def test_dealt_shard_bytes_arithmetic(self):
        import jax
        import jax.numpy as jnp

        arrays = (
            jax.ShapeDtypeStruct((32, 64, 8), jnp.float32),
            jax.ShapeDtypeStruct((32, 64), jnp.int32),
            jax.ShapeDtypeStruct((32,), jnp.int32),
        )
        # 8 shards: 4 rows each of every tensor
        want = 4 * 64 * 8 * 4 + 4 * 64 * 4 + 4 * 4
        assert memwatch.dealt_shard_bytes(arrays, 8) == want
        # non-dividing row counts round up (the last shard's slack
        # must not make the model optimistic), None entries skip
        assert memwatch.dealt_shard_bytes(
            (jax.ShapeDtypeStruct((10, 2), jnp.float32), None), 4) \
            == 3 * 2 * 4

    def test_deal_admission_refuses_per_shard_model(self, data):
        """admit_deal is the distributed builds' gate seam: a
        capacity below the per-shard slot model refuses with the
        exact per-shard byte count, host-side."""
        x, _ = data
        idx = ivf_flat.build(
            None, ivf_flat.IvfFlatIndexParams(n_lists=8), x)
        arrays = (idx.centers, idx.data, idx.data_norms, idx.indices,
                  idx.list_sizes)
        per_shard = memwatch.dealt_shard_bytes(arrays, 8)
        memwatch.install_gate(MemoryLedger(
            capacity_bytes=per_shard - 1))
        with pytest.raises(CapacityExceeded,
                           match="ivf_flat.build.deal") as e:
            dist_ivf.admit_deal(arrays, 8,
                                "distributed.ivf_flat.build.deal")
        assert e.value.required_bytes == per_shard

    def test_distributed_build_is_gated_end_to_end(self, data):
        """The whole mesh build path refuses under a tiny gate —
        typed CapacityExceeded, never a backend OOM."""
        from raft_tpu.comms import local_comms

        x, _ = data
        memwatch.install_gate(MemoryLedger(capacity_bytes=100))
        with pytest.raises(CapacityExceeded):
            dist_ivf.build(None, local_comms(),
                           ivf_flat.IvfFlatIndexParams(n_lists=8), x)

    def test_deal_admissions_counted(self, data):
        """With ample capacity the mesh build's deal admission rides
        the same decision ledger as the single-chip gates."""
        from raft_tpu.comms import local_comms

        x, _ = data
        memwatch.install_gate(MemoryLedger(capacity_bytes=10**12))
        before = tracing.get_counter(memwatch.GATE_ADMITTED)
        dist_ivf.build(None, local_comms(),
                       ivf_flat.IvfFlatIndexParams(n_lists=8), x)
        after = tracing.get_counter(memwatch.GATE_ADMITTED)
        # at least the single-chip extend admit AND the deal admit
        assert after - before >= 2


def _encode_varint(v):
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _encode_field(fnum, wtype, payload):
    tag = _encode_varint((fnum << 3) | wtype)
    if wtype == 0:
        return tag + _encode_varint(payload)
    return tag + _encode_varint(len(payload)) + payload


def _make_pprof(samples, strings):
    """Minimal pprof Profile: sample_type (objects, bytes), samples
    with labels, a string table. ``samples`` is a list of
    (label_pairs, objects, bytes) with label_pairs as (key_idx,
    str_idx) tuples."""
    body = bytearray()
    # sample_type: {type, unit} — unit indices point at "objects"/"bytes"
    for unit_idx in (strings.index("objects"), strings.index("bytes")):
        vt = _encode_field(1, 0, 0) + _encode_field(2, 0, unit_idx)
        body += _encode_field(1, 2, vt)
    for label_pairs, n_obj, n_bytes in samples:
        sample = bytearray()
        sample += _encode_field(2, 0, n_obj)
        sample += _encode_field(2, 0, n_bytes)
        for key_idx, str_idx in label_pairs:
            lab = (_encode_field(1, 0, key_idx)
                   + _encode_field(2, 0, str_idx))
            sample += _encode_field(3, 2, bytes(lab))
        body += _encode_field(2, 2, bytes(sample))
    for s in strings:
        body += _encode_field(6, 2, s.encode())
    return bytes(body)


class TestMemoryProfileDiff:
    """PR 14 satellite (ROADMAP graftledger follow-on (c)): two
    sequence-numbered pprof captures diff into per-buffer divergence
    attribution."""

    STRINGS = ["", "objects", "bytes", "kind", "buffer", "shape",
               "f32[128,64]", "f32[8,8]"]

    def _profile(self, buf_bytes, small_bytes):
        s = self.STRINGS
        return _make_pprof(
            [([(s.index("kind"), s.index("buffer")),
               (s.index("shape"), s.index("f32[128,64]"))], 1,
              buf_bytes),
             ([(s.index("kind"), s.index("buffer")),
               (s.index("shape"), s.index("f32[8,8]"))], 1,
              small_bytes)],
            s)

    def test_parse_aggregates_by_label_set(self):
        parsed = memwatch.parse_memory_profile(
            self._profile(32768, 256))
        assert parsed == {
            "kind=buffer,shape=f32[128,64]": 32768,
            "kind=buffer,shape=f32[8,8]": 256,
        }

    def test_parse_handles_gzip(self):
        import gzip

        raw = self._profile(1024, 64)
        assert memwatch.parse_memory_profile(gzip.compress(raw)) \
            == memwatch.parse_memory_profile(raw)

    def test_parse_picks_bytes_value_column(self):
        """The bytes-typed sample value is summed — not the objects
        column sitting before it."""
        parsed = memwatch.parse_memory_profile(self._profile(500, 7))
        assert sum(parsed.values()) == 507

    def test_diff_attributes_per_buffer_group(self):
        before = memwatch.parse_memory_profile(
            self._profile(32768, 256))
        after = memwatch.parse_memory_profile(
            self._profile(65536, 256))
        diff = memwatch.diff_memory_profiles(before, after)
        assert diff["total_delta_bytes"] == 32768
        assert diff["deltas"] == [{
            "label": "kind=buffer,shape=f32[128,64]",
            "from_bytes": 32768, "to_bytes": 65536,
            "delta_bytes": 32768,
        }]
        # disappearing and appearing groups both attribute
        gone = memwatch.diff_memory_profiles(before, {})
        assert gone["total_delta_bytes"] == -(32768 + 256)
        assert len(gone["deltas"]) == 2
        assert gone["deltas"][0]["delta_bytes"] == -32768

    def test_http_diff_round_trip(self, tmp_path):
        """?diff=<seq> over HTTP: capture, capture-with-diff, and the
        400 contract for unknown/malformed sequence numbers."""
        from raft_tpu.serving import MetricsExporter

        exp = MetricsExporter(profile_dir=str(tmp_path))
        exp.start()
        try:
            r1 = json.loads(urllib.request.urlopen(
                exp.url("/memory_profile")).read())
            assert r1["seq"] == 1 and r1["bytes"] > 0
            r2 = json.loads(urllib.request.urlopen(
                exp.url("/memory_profile?diff=1")).read())
            assert r2["seq"] == 2
            d = r2["diff"]
            assert d["from_seq"] == 1 and d["to_seq"] == 2
            assert isinstance(d["deltas"], list)
            assert d["total_delta_bytes"] == (
                d["total_after_bytes"] - d["total_before_bytes"])
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    exp.url("/memory_profile?diff=99"))
            assert e.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    exp.url("/memory_profile?diff=abc"))
            assert e.value.code == 400
        finally:
            exp.close()


class TestNamedReservations:
    """graftcast: named byte holds (the prefetcher's staged miss
    cache) subtract from headroom and pass growth through the
    capacity gate."""

    def test_reserve_subtracts_from_headroom(self):
        ledger = MemoryLedger(capacity_bytes=1000)
        assert ledger.headroom_bytes() == 1000
        ledger.reserve("tier.prefetch", 300)
        assert ledger.reserved_bytes() == 300
        assert ledger.headroom_bytes() == 700
        # a second named hold stacks; same-name re-reserve replaces
        ledger.reserve("other", 100)
        assert ledger.headroom_bytes() == 600
        ledger.reserve("tier.prefetch", 200)
        assert ledger.reserved_bytes() == 300
        assert ledger.headroom_bytes() == 700

    def test_release_is_idempotent(self):
        ledger = MemoryLedger(capacity_bytes=1000)
        ledger.reserve("tier.prefetch", 400)
        ledger.release("tier.prefetch")
        assert ledger.reserved_bytes() == 0
        assert ledger.headroom_bytes() == 1000
        ledger.release("tier.prefetch")   # no such hold: no error
        ledger.release("never-held")
        assert ledger.headroom_bytes() == 1000

    def test_growth_gated_refusal_restores_prior_hold(self):
        ledger = MemoryLedger(capacity_bytes=1000)
        ledger.reserve("tier.prefetch", 400)
        refused0 = tracing.get_counter("memory.gate.refused")
        # growth is judged WITHOUT the prior hold: 900 <= 1000 - 0
        # admits even though 900 > headroom-with-hold (600)
        ledger.reserve("tier.prefetch", 900)
        assert ledger.reserved_bytes() == 900
        # but beyond capacity refuses and keeps the 900 hold intact
        with pytest.raises(CapacityExceeded) as e:
            ledger.reserve("tier.prefetch", 1200)
        assert e.value.required_bytes == 1200
        assert ledger.reserved_bytes() == 900
        assert (tracing.get_counter("memory.gate.refused")
                == refused0 + 1)

    def test_shrink_always_admitted(self):
        ledger = MemoryLedger(capacity_bytes=1000)
        ledger.reserve("tier.prefetch", 800)
        # other pressure appears: even with zero headroom, shrinking
        # (and zeroing) the hold must never raise
        ledger.reserve("other", 200)
        assert ledger.headroom_bytes() == 0
        ledger.reserve("tier.prefetch", 100)
        assert ledger.reserved_bytes() == 300
        ledger.reserve("tier.prefetch", 0)
        assert ledger.reserved_bytes() == 200

    def test_gate_admission_sees_holds(self, data):
        """A build racing the prefetcher's hold is refused the bytes
        the hold already claimed."""
        x, _ = data
        model_bytes = memwatch.index_memory_model(
            ivf_flat.build(
                None, ivf_flat.IvfFlatIndexParams(n_lists=8), x)
        )["resident_bytes"]
        ledger = MemoryLedger(capacity_bytes=1.5 * model_bytes)
        memwatch.install_gate(ledger)
        ledger.reserve("tier.prefetch", model_bytes)
        with pytest.raises(CapacityExceeded):
            ivf_flat.build(
                None, ivf_flat.IvfFlatIndexParams(n_lists=8), x)
        ledger.release("tier.prefetch")
        ivf_flat.build(None, ivf_flat.IvfFlatIndexParams(n_lists=8), x)

    def test_snapshot_and_gauge_publish_holds(self, flat_index):
        ledger = MemoryLedger(capacity_bytes=10**9)
        ledger.watch("flat", flat_index)
        ledger.reserve("tier.prefetch", 12345)
        snap = ledger.publish()
        assert snap["reserved_held_bytes"] == 12345
        assert (tracing.gauges().get("memory.reserved.held_bytes")
                == 12345)

    def test_unknown_headroom_stays_unknown(self, flat_index):
        """No capacity + no live stats: holds don't invent a number —
        headroom stays None and growth is un-gateable (admitted)."""
        ledger = MemoryLedger()
        ledger.reserve("tier.prefetch", 500)
        assert ledger.headroom_bytes() is None
        assert ledger.reserved_bytes() == 500
        verdict = ledger.fits(flat_index)
        assert verdict["fits"] is True and verdict["unknown"] is True
