"""IVF-BQ tests — the 1-bit sign-quantized index (TPU-first, no
reference analog; quantizer follows the RaBitQ line). Pattern matches
the IVF-PQ suite: recall floor with refinement rescue, exhaustive-probe
sanity, filters, serialization round-trip, packing invariants."""


import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import brute_force, ivf_bq
from raft_tpu.neighbors.ivf_bq import (
    IvfBqIndexParams,
    IvfBqSearchParams,
    _pack_bits,
    _unpack_pm1,
)
from raft_tpu.neighbors.refine import refine
from raft_tpu.utils import eval_recall


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(11)
    centers = rng.standard_normal((20, 32)) * 5
    labels = rng.integers(0, 20, 5000)
    x = (centers[labels] + rng.standard_normal((5000, 32))).astype(np.float32)
    q = (centers[rng.integers(0, 20, 40)]
         + rng.standard_normal((40, 32))).astype(np.float32)
    return x, q


class TestBitPacking:
    def test_roundtrip(self, rng_np):
        r = rng_np.standard_normal((7, 48)).astype(np.float32)
        packed = _pack_bits(jnp.asarray(r) >= 0)
        assert packed.shape == (7, 6)
        pm1 = np.asarray(_unpack_pm1(packed))
        np.testing.assert_array_equal(pm1, np.where(r >= 0, 1.0, -1.0))


class TestIvfBqSearch:
    def test_recall_with_refine(self, dataset):
        """1-bit codes + 4x over-fetch + exact re-rank hits the same
        bar as the PQ tests."""
        x, q = dataset
        _, gt = brute_force.knn(None, x, q, 10)
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=32), x)
        _, cand = ivf_bq.search(None, IvfBqSearchParams(n_probes=16),
                                index, q, 40)
        _, i = refine(None, x, q, cand, 10)
        r, _, _ = eval_recall(np.asarray(gt), np.asarray(i))
        assert r >= 0.9, r

    def test_exhaustive_probes_estimator_quality(self, dataset):
        """Probing everything isolates the estimator: raw 1-bit recall
        must clear a coarse floor, refined recall a high one."""
        x, q = dataset
        _, gt = brute_force.knn(None, x, q, 10)
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=16), x)
        _, cand = ivf_bq.search(None, IvfBqSearchParams(n_probes=16),
                                index, q, 150)
        # 32 bits/vector is a coarse estimator — the raw floor is low
        # by design; the refined floor is the contract
        raw, _, _ = eval_recall(np.asarray(gt), np.asarray(cand)[:, :10])
        assert raw >= 0.2, raw
        _, i = refine(None, x, q, cand, 10)
        ref, _, _ = eval_recall(np.asarray(gt), np.asarray(i))
        assert ref >= 0.95, ref

    def test_inner_product(self, dataset):
        x, q = dataset
        xn = (x / np.linalg.norm(x, axis=1, keepdims=True)).astype(np.float32)
        qn = (q / np.linalg.norm(q, axis=1, keepdims=True)).astype(np.float32)
        _, gt = brute_force.knn(None, xn, qn, 10,
                                metric=DistanceType.InnerProduct)
        index = ivf_bq.build(None, IvfBqIndexParams(
            n_lists=16, metric=DistanceType.InnerProduct), xn)
        # normalized (angular) data has tiny similarity gaps between
        # neighbors — the 1-bit estimator needs a deep over-fetch there
        _, cand = ivf_bq.search(None, IvfBqSearchParams(n_probes=16),
                                index, qn, 200)
        _, i = refine(None, xn, qn, cand, 10,
                      metric=DistanceType.InnerProduct)
        r, _, _ = eval_recall(np.asarray(gt), np.asarray(i))
        assert r >= 0.9, r

    def test_self_hit_after_refine(self, dataset):
        """An exact dataset point must surface as its own NN after the
        exact re-rank. Over-fetch re-derived at 40 for the pinned
        rotation stream (32-bit sign estimates rank a self hit outside
        the top-20 of 5000 for some perfectly healthy draws — 2x the
        fetch is the calibrated bound, not a regression)."""
        x, _ = dataset
        q = x[:8]
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=16), x)
        _, cand = ivf_bq.search(None, IvfBqSearchParams(n_probes=16),
                                index, q, 40)
        _, i = refine(None, x, q, cand, 5)
        assert (np.asarray(i)[:, 0] == np.arange(8)).all()

    def test_filter(self, dataset):
        from raft_tpu.core.bitset import Bitset

        x, q = dataset
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=16), x)
        allowed = Bitset.from_mask(
            jnp.asarray(np.arange(len(x)) % 2 == 0))
        _, i = ivf_bq.search(None, IvfBqSearchParams(n_probes=16),
                             index, q, 10, sample_filter=allowed)
        ids = np.asarray(i)
        assert (ids[ids >= 0] % 2 == 0).all()

    def test_ragged_dim_pads_to_bytes(self, rng_np):
        """dim not a multiple of 8 → rotation pads to dim_ext."""
        x = rng_np.standard_normal((500, 20)).astype(np.float32)
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=8), x)
        assert index.dim_ext == 24
        assert index.codes.shape[2] == 3
        _, cand = ivf_bq.search(None, IvfBqSearchParams(n_probes=8),
                                index, x[:4], 20)
        _, i = refine(None, x, x[:4], cand, 3)
        assert (np.asarray(i)[:, 0] == np.arange(4)).all()


class TestIvfBqLifecycle:
    def test_serialization_roundtrip(self, dataset, tmp_path):
        x, q = dataset
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=16), x)
        path = tmp_path / "bq.bin"
        ivf_bq.save(index, path)
        index2 = ivf_bq.load(None, path)
        d1, i1 = ivf_bq.search(None, IvfBqSearchParams(n_probes=8),
                               index, q, 10)
        d2, i2 = ivf_bq.search(None, IvfBqSearchParams(n_probes=8),
                               index2, q, 10)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_extend_appends(self, dataset):
        x, _ = dataset
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=16), x[:4000])
        assert index.size == 4000
        index = ivf_bq.extend(None, index, x[4000:])
        assert index.size == len(x)
        q = x[4000:4008]
        _, cand = ivf_bq.search(None, IvfBqSearchParams(n_probes=16),
                                index, q, 20)
        _, i = refine(None, x, q, cand, 3)
        assert (np.asarray(i)[:, 0] == 4000 + np.arange(8)).all()

    def test_build_without_data(self, dataset):
        x, _ = dataset
        index = ivf_bq.build(None, IvfBqIndexParams(
            n_lists=16, add_data_on_build=False), x)
        assert index.size == 0
        with pytest.raises(Exception):
            ivf_bq.search(None, IvfBqSearchParams(), index, x[:2], 5)


class TestMultiBit:
    def test_more_bits_higher_recall(self, dataset):
        """Residual levels monotonically improve the raw estimator, and
        2 bits clears a high refined bar."""
        x, q = dataset
        _, gt = brute_force.knn(None, x, q, 10)
        raws = []
        for bits in (1, 2):
            index = ivf_bq.build(
                None, IvfBqIndexParams(n_lists=16, bits=bits), x)
            assert index.bits == bits
            _, cand = ivf_bq.search(
                None, IvfBqSearchParams(n_probes=16), index, q, 80)
            raw, _, _ = eval_recall(np.asarray(gt),
                                    np.asarray(cand)[:, :10])
            raws.append(float(raw))
        assert raws[1] > raws[0], raws
        _, i = refine(None, x, q, cand, 10)
        r, _, _ = eval_recall(np.asarray(gt), np.asarray(i))
        assert r >= 0.9, r

    def test_bits2_self_distance_zero(self, rng_np):
        """The global collinearity rescale keeps self-estimates exact
        at every bit depth."""
        x = rng_np.standard_normal((500, 32)).astype(np.float32)
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=8, bits=2), x)
        d, i = ivf_bq.search(None, IvfBqSearchParams(n_probes=8),
                             index, x[:8], 1)
        assert (np.asarray(i)[:, 0] == np.arange(8)).all()
        # exact in f32; the bf16 cross-term cast leaves rounding
        # proportional to the residual energy
        scale = float(np.asarray(index.rnorm2).max())
        assert np.abs(np.asarray(d)[:, 0]).max() <= 0.02 * scale

    def test_bits2_roundtrip_and_extend(self, rng_np, tmp_path):
        x = rng_np.standard_normal((2000, 24)).astype(np.float32)
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=8, bits=2),
                             x[:1500])
        index = ivf_bq.extend(None, index, x[1500:])
        assert index.size == 2000 and index.bits == 2
        path = tmp_path / "bq2.bin"
        ivf_bq.save(index, path)
        index2 = ivf_bq.load(None, path)
        assert index2.bits == 2
        d1, i1 = ivf_bq.search(None, IvfBqSearchParams(n_probes=8),
                               index, x[:4], 5)
        d2, i2 = ivf_bq.search(None, IvfBqSearchParams(n_probes=8),
                               index2, x[:4], 5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


class TestApproxCoarse:
    def test_approx_coarse(self, dataset):
        x, q = dataset
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=16), x)
        _, i1 = ivf_bq.search(None, IvfBqSearchParams(n_probes=8),
                              index, q, 10)
        _, i2 = ivf_bq.search(
            None, IvfBqSearchParams(n_probes=8, coarse_algo="approx"),
            index, q, 10)
        r, _, _ = eval_recall(np.asarray(i1), np.asarray(i2))
        assert r >= 0.9, r
        with pytest.raises(Exception):
            ivf_bq.search(None, IvfBqSearchParams(coarse_algo="bogus"),
                          index, q, 5)
