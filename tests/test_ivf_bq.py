"""IVF-BQ tests — the RaBitQ-grade sign-quantized index. Covers the
geometry-aware construction (word packing, unbiased estimator with a
measured error bound), the fused estimate-then-rerank engines
(pallas ≡ xla bit-parity, exact output distances), the bound-derived
over-fetch budgets that retired the three hand-calibrated constants
(self-hit 40, sharded merge 240, streamed-bits2 60), filters,
serialization round-trip, and the estimate-only legacy path."""


import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import brute_force, ivf_bq
from raft_tpu.neighbors.ivf_bq import (
    IvfBqIndexParams,
    IvfBqSearchParams,
    _encode,
    _pack_words,
    _unpack_pm1,
    estimator_margin,
    estimator_stats,
    overfetch_budget,
)
from raft_tpu.neighbors.refine import refine
from raft_tpu.utils import eval_recall


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(11)
    centers = rng.standard_normal((20, 32)) * 5
    labels = rng.integers(0, 20, 5000)
    x = (centers[labels] + rng.standard_normal((5000, 32))).astype(np.float32)
    q = (centers[rng.integers(0, 20, 40)]
         + rng.standard_normal((40, 32))).astype(np.float32)
    return x, q


class TestBitPacking:
    def test_roundtrip(self, rng_np):
        r = rng_np.standard_normal((7, 64)).astype(np.float32)
        packed = _pack_words(jnp.asarray(r) >= 0)
        assert packed.shape == (7, 2)
        assert packed.dtype == jnp.int32
        pm1 = np.asarray(_unpack_pm1(packed))
        np.testing.assert_array_equal(pm1, np.where(r >= 0, 1.0, -1.0))


class TestEstimatorContract:
    """The RaBitQ construction's statistical contracts: unbiasedness
    and the measured per-candidate error bound — what replaced the
    calibrated fudge budgets."""

    def test_collinearity_exact_self_reconstruction(self, rng_np):
        """⟨r, Σ a_l s_l⟩ = ‖r‖² exactly (the gamma rescale), so a
        vector's estimated distance to itself is 0 at any bit depth."""
        r = rng_np.standard_normal((200, 64)).astype(np.float32)
        for bits in (1, 2):
            codes, rnorm, cfac, errw = _encode(jnp.asarray(r), bits)
            pm1 = np.asarray(_unpack_pm1(codes, jnp.float32)).reshape(
                200, bits, 64)
            a = (np.asarray(rnorm)[:, None] * np.asarray(cfac))
            recon = (a[:, :, None] * pm1).sum(axis=1)
            ip = (r * recon).sum(axis=1)
            rn2 = (r * r).sum(axis=1)
            np.testing.assert_allclose(ip, rn2, rtol=1e-4)
            # errw really is the unexplained residual norm
            e = np.linalg.norm(r - recon, axis=1)
            np.testing.assert_allclose(np.asarray(errw), e, rtol=1e-3,
                                       atol=1e-4)

    def test_unbiased_and_bound_holds(self):
        """Across seeds: the popcount estimator's signed error is ~0
        (unbiased), and |error| stays inside estimator_margin at
        epsilon=3 for >= 97% of candidates (the stated confidence the
        fused prune relies on)."""
        from raft_tpu.ops.bq_scan import _estimate_block

        means, covers, scales = [], [], []
        for seed in range(6):
            rng = np.random.default_rng(seed)
            D = 64
            resid = rng.standard_normal((800, D)).astype(np.float32)
            qt = rng.standard_normal((4, D)).astype(np.float32)
            codes, rnorm, cfac, errw = _encode(jnp.asarray(resid), 1)
            cross, delta = _estimate_block(
                jnp.asarray(qt), codes, jnp.asarray(rnorm)[None, :],
                jnp.transpose(jnp.asarray(cfac)), dim_ext=D, bits=1,
                query_bits=4)
            exact = qt @ resid.T                          # (4, 800)
            err = 2.0 * (exact - np.asarray(cross))
            qcn = np.linalg.norm(qt, axis=1, keepdims=True)
            m = np.asarray(estimator_margin(
                jnp.asarray(qcn), jnp.asarray(rnorm)[None, :],
                jnp.asarray(errw)[None, :], delta, D, 3.0))
            means.append(err.mean())
            covers.append((np.abs(err) <= m).mean())
            scales.append(np.abs(err).mean())
        # signed mean error two orders below the per-candidate error
        # scale = unbiased for every practical purpose
        assert abs(np.mean(means)) < 0.1 * np.mean(scales), (
            np.mean(means), np.mean(scales))
        assert min(covers) >= 0.97, covers

    def test_derived_budgets_at_most_retired_constants(self, dataset):
        """The bound-derived budgets are <= the three hand-calibrated
        constants they retired, at the same recall targets (the
        recall legs live in the tests that used each constant:
        self-hit below, streamed-bits2 in test_io, sharded merge in
        test_comms)."""
        x, _ = dataset
        est_only = ivf_bq.build(None, IvfBqIndexParams(
            n_lists=16, store_vectors=False), x)
        b_selfhit = overfetch_budget(est_only, 5)
        assert 5 < b_selfhit <= 40, b_selfhit          # retired: 40

        rng = np.random.default_rng(42)
        x2 = rng.standard_normal((4000, 32)).astype(np.float32)
        bits2 = ivf_bq.build(None, IvfBqIndexParams(
            n_lists=16, bits=2, store_vectors=False), x2)
        b_streamed = overfetch_budget(bits2, 10)
        assert 10 < b_streamed <= 60, b_streamed       # retired: 60
        # more bits -> tighter measured bound -> smaller relative
        # over-fetch
        assert (estimator_stats(bits2)["rel_err"]
                < estimator_stats(est_only)["rel_err"])

        # an index carrying the rerank plane needs no over-fetch at
        # all: the fused scan returns exact distances (the sharded
        # merge's retired 240 collapses to k — recall leg in
        # test_comms::test_ivf_bq_shards)
        reranked = ivf_bq.build(None, IvfBqIndexParams(n_lists=16), x)
        assert overfetch_budget(reranked, 10) == 10


class TestIvfBqSearch:
    def test_fused_recall_no_refine(self, dataset):
        """The fused engines return exact distances — recall at k
        directly, no over-fetch, no separate refine pass."""
        x, q = dataset
        gt_d, gt = brute_force.knn(None, x, q, 10)
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=32), x)
        d, i = ivf_bq.search(None, IvfBqSearchParams(n_probes=16),
                             index, q, 10)
        r, _, _ = eval_recall(np.asarray(gt), np.asarray(i))
        assert r >= 0.95, r
        # output distances are exact (match brute force on agreeing ids)
        match = np.asarray(i) == np.asarray(gt)
        err = np.abs(np.asarray(d) - np.asarray(gt_d))[match]
        assert err.max() <= 1e-2, err.max()

    def test_pallas_xla_bit_parity(self, dataset):
        """The two fused engines agree bit-for-bit (ids AND
        distances) — one shared estimate/margin/prune code path."""
        x, q = dataset
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=32), x)
        d_x, i_x = ivf_bq.search(
            None, IvfBqSearchParams(n_probes=16, scan_engine="xla"),
            index, q, 10)
        d_p, i_p = ivf_bq.search(
            None, IvfBqSearchParams(n_probes=16, scan_engine="pallas"),
            index, q, 10)
        np.testing.assert_array_equal(np.asarray(i_p), np.asarray(i_x))
        np.testing.assert_array_equal(np.asarray(d_p), np.asarray(d_x))

    def test_kernel_interpret_reference(self, dataset):
        """Direct interpret-mode call of the fused kernel against the
        XLA engine — the R6 ops-guard reference for bq_scan."""
        from raft_tpu.ops.bq_scan import bq_list_major_scan

        x, q = dataset
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=16), x)
        qf = jnp.asarray(q[:8], jnp.float32)
        qrot = qf @ index.rotation.T
        crot = index.centers @ index.rotation.T
        cn = jnp.sum(jnp.square(index.centers), axis=1)
        ip = qf @ index.centers.T
        score = -(cn[None, :] - 2.0 * ip)
        probes = jnp.argsort(-score, axis=1)[:, :8].astype(jnp.int32)
        args = (qf, qrot, crot, index.codes, index.rnorm, index.cfac,
                index.errw, index.indices, index.data,
                index.data_norms, probes)
        d_p, i_p = bq_list_major_scan(
            *args, k=5, metric=index.metric, epsilon=3.0,
            engine="pallas", interpret=True)
        d_x, i_x = bq_list_major_scan(
            *args, k=5, metric=index.metric, epsilon=3.0, engine="xla")
        np.testing.assert_array_equal(np.asarray(i_p), np.asarray(i_x))
        np.testing.assert_array_equal(np.asarray(d_p), np.asarray(d_x))

    def test_inner_product(self, dataset):
        x, q = dataset
        xn = (x / np.linalg.norm(x, axis=1, keepdims=True)).astype(np.float32)
        qn = (q / np.linalg.norm(q, axis=1, keepdims=True)).astype(np.float32)
        _, gt = brute_force.knn(None, xn, qn, 10,
                                metric=DistanceType.InnerProduct)
        index = ivf_bq.build(None, IvfBqIndexParams(
            n_lists=16, metric=DistanceType.InnerProduct), xn)
        # the fused rerank is exact, so normalized (angular) data's
        # tiny similarity gaps no longer need a deep over-fetch
        _, i = ivf_bq.search(None, IvfBqSearchParams(n_probes=16),
                             index, qn, 10)
        r, _, _ = eval_recall(np.asarray(gt), np.asarray(i))
        assert r >= 0.95, r

    def test_self_hit_fused(self, dataset):
        """An exact dataset point surfaces as its own NN directly —
        its estimate is exactly 0 (collinearity rescale), so the fused
        prune always reranks it and the exact distance wins."""
        x, _ = dataset
        q = x[:8]
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=16), x)
        d, i = ivf_bq.search(None, IvfBqSearchParams(n_probes=16),
                             index, q, 5)
        assert (np.asarray(i)[:, 0] == np.arange(8)).all()
        assert np.asarray(d)[:, 0].max() <= 1e-3

    def test_self_hit_estimate_only_derived_budget(self, dataset):
        """The estimate-only path still rescues the self hit with the
        bound-derived budget (<= the retired constant 40 — the recall
        leg of the derived-budget contract)."""
        x, _ = dataset
        q = x[:8]
        index = ivf_bq.build(None, IvfBqIndexParams(
            n_lists=16, store_vectors=False), x)
        budget = overfetch_budget(index, 5)
        _, cand = ivf_bq.search(None, IvfBqSearchParams(n_probes=16),
                                index, q, budget)
        _, i = refine(None, x, q, cand, 5)
        assert (np.asarray(i)[:, 0] == np.arange(8)).all()

    def test_filter(self, dataset):
        from raft_tpu.core.bitset import Bitset

        x, q = dataset
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=16), x)
        allowed = Bitset.from_mask(
            jnp.asarray(np.arange(len(x)) % 2 == 0))
        for engine in ("pallas", "xla", "rank"):
            _, i = ivf_bq.search(
                None, IvfBqSearchParams(n_probes=16, scan_engine=engine),
                index, q, 10, sample_filter=allowed)
            ids = np.asarray(i)
            assert (ids[ids >= 0] % 2 == 0).all(), engine

    def test_ragged_dim_pads_to_words(self, rng_np):
        """dim not a multiple of 32 → rotation pads to the int32 word
        extent."""
        x = rng_np.standard_normal((500, 20)).astype(np.float32)
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=8), x)
        assert index.dim_ext == 32
        assert index.codes.shape[2] == 1
        _, i = ivf_bq.search(None, IvfBqSearchParams(n_probes=8),
                             index, x[:4], 3)
        assert (np.asarray(i)[:, 0] == np.arange(4)).all()


class TestIvfBqLifecycle:
    def test_serialization_roundtrip(self, dataset, tmp_path):
        x, q = dataset
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=16), x)
        path = tmp_path / "bq.bin"
        ivf_bq.save(index, path)
        index2 = ivf_bq.load(None, path)
        assert index2.data is not None
        d1, i1 = ivf_bq.search(None, IvfBqSearchParams(n_probes=8),
                               index, q, 10)
        d2, i2 = ivf_bq.search(None, IvfBqSearchParams(n_probes=8),
                               index2, q, 10)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_serialization_roundtrip_codes_only(self, dataset, tmp_path):
        x, q = dataset
        index = ivf_bq.build(None, IvfBqIndexParams(
            n_lists=16, store_vectors=False), x)
        path = tmp_path / "bq_codes.bin"
        ivf_bq.save(index, path)
        index2 = ivf_bq.load(None, path)
        assert index2.data is None
        _, i1 = ivf_bq.search(None, IvfBqSearchParams(n_probes=8),
                              index, q, 10)
        _, i2 = ivf_bq.search(None, IvfBqSearchParams(n_probes=8),
                              index2, q, 10)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_extend_appends(self, dataset):
        x, _ = dataset
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=16), x[:4000])
        assert index.size == 4000
        index = ivf_bq.extend(None, index, x[4000:])
        assert index.size == len(x)
        assert index.data is not None and index.data.shape[2] == 32
        q = x[4000:4008]
        _, i = ivf_bq.search(None, IvfBqSearchParams(n_probes=16),
                             index, q, 3)
        assert (np.asarray(i)[:, 0] == 4000 + np.arange(8)).all()

    def test_build_without_data(self, dataset):
        x, _ = dataset
        index = ivf_bq.build(None, IvfBqIndexParams(
            n_lists=16, add_data_on_build=False), x)
        assert index.size == 0
        with pytest.raises(Exception):
            ivf_bq.search(None, IvfBqSearchParams(), index, x[:2], 5)


class TestMultiBit:
    def test_more_bits_tighter_estimates(self, dataset):
        """Residual levels monotonically shrink the measured
        unexplained residual (the estimator's whole error budget) and
        the raw estimate-only recall improves with them."""
        x, q = dataset
        _, gt = brute_force.knn(None, x, q, 10)
        raws, errs = [], []
        for bits in (1, 2):
            index = ivf_bq.build(None, IvfBqIndexParams(
                n_lists=16, bits=bits, store_vectors=False), x)
            assert index.bits == bits
            errs.append(estimator_stats(index)["rel_err"])
            _, cand = ivf_bq.search(
                None, IvfBqSearchParams(n_probes=16), index, q, 80)
            raw, _, _ = eval_recall(np.asarray(gt),
                                    np.asarray(cand)[:, :10])
            raws.append(float(raw))
        assert raws[1] > raws[0], raws
        assert errs[1] < errs[0], errs

    def test_bits2_self_distance_zero(self, rng_np):
        """The global collinearity rescale keeps self-estimates exact
        at every bit depth (estimate-only path)."""
        x = rng_np.standard_normal((500, 32)).astype(np.float32)
        index = ivf_bq.build(None, IvfBqIndexParams(
            n_lists=8, bits=2, store_vectors=False), x)
        d, i = ivf_bq.search(None, IvfBqSearchParams(n_probes=8),
                             index, x[:8], 1)
        assert (np.asarray(i)[:, 0] == np.arange(8)).all()
        # exact in f32; the bf16 cross-term cast leaves rounding
        # proportional to the residual energy
        scale = float(np.square(np.asarray(index.rnorm)).max())
        assert np.abs(np.asarray(d)[:, 0]).max() <= 0.02 * scale

    def test_bits2_roundtrip_and_extend(self, rng_np, tmp_path):
        x = rng_np.standard_normal((2000, 24)).astype(np.float32)
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=8, bits=2),
                             x[:1500])
        index = ivf_bq.extend(None, index, x[1500:])
        assert index.size == 2000 and index.bits == 2
        path = tmp_path / "bq2.bin"
        ivf_bq.save(index, path)
        index2 = ivf_bq.load(None, path)
        assert index2.bits == 2
        d1, i1 = ivf_bq.search(None, IvfBqSearchParams(n_probes=8),
                               index, x[:4], 5)
        d2, i2 = ivf_bq.search(None, IvfBqSearchParams(n_probes=8),
                               index2, x[:4], 5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


class TestQueryBitsLadder:
    """graftbeam satellite: multi-bit query quantization for the
    bits>=3 code ladder — auto resolution, engine parity at the wide
    grid, and the re-calibrated over-fetch margins pinned."""

    def test_auto_query_bits_per_ladder(self):
        from raft_tpu.ops.bq_scan import auto_query_bits

        assert auto_query_bits(1) == 4
        assert auto_query_bits(2) == 4
        assert auto_query_bits(3) == 8
        assert auto_query_bits(4) == 8

    def test_engine_parity_at_8bit_grid(self, dataset):
        """The wide query grid rides BOTH fused engines through the
        shared estimate path: ids and distances stay bit-identical."""
        x, q = dataset
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=32, bits=4),
                             x)
        p = dict(n_probes=16, query_bits=8)
        d_x, i_x = ivf_bq.search(
            None, IvfBqSearchParams(scan_engine="xla", **p), index, q,
            10)
        d_p, i_p = ivf_bq.search(
            None, IvfBqSearchParams(scan_engine="pallas", **p), index,
            q, 10)
        np.testing.assert_array_equal(np.asarray(i_p), np.asarray(i_x))
        np.testing.assert_array_equal(np.asarray(d_p), np.asarray(d_x))

    def test_explicit_4bit_matches_auto_below_ladder(self, dataset):
        """query_bits=0 resolves to the pinned 4-bit grid below 3 code
        bits — explicit 4 is the SAME executable path, bit-identical."""
        x, q = dataset
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=16, bits=2),
                             x)
        d0, i0 = ivf_bq.search(None, IvfBqSearchParams(n_probes=8),
                               index, q, 10)
        d4, i4 = ivf_bq.search(
            None, IvfBqSearchParams(n_probes=8, query_bits=4), index,
            q, 10)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i4))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d4))

    def test_overfetch_recalibrated_pins(self, dataset):
        """kappa_eff identity at the calibration grid (4-bit) and a
        monotone budget ladder: wider query grids buy strictly smaller
        over-fetch, never below k, never above the 4-bit pin."""
        x, _ = dataset
        est_only = ivf_bq.build(None, IvfBqIndexParams(
            n_lists=16, bits=4, store_vectors=False), x)
        b4 = overfetch_budget(est_only, 5, query_bits=4)
        assert b4 == overfetch_budget(est_only, 5)    # identity pin
        b2 = overfetch_budget(est_only, 5, query_bits=2)
        b8 = overfetch_budget(est_only, 5, query_bits=8)
        assert b8 <= b4 <= b2, (b8, b4, b2)
        assert b8 < b2, (b8, b2)
        assert b8 >= 5
        # recall leg: the 8-bit-grid budget still recovers the 4-bit
        # arm's self-hit recall after exact re-rank
        q8 = x[:16]
        _, cand = ivf_bq.search(
            None, IvfBqSearchParams(n_probes=16, query_bits=8),
            est_only, q8, int(b8))
        hit = (np.asarray(cand) == np.arange(16)[:, None]).any(axis=1)
        assert hit.mean() >= 0.95, hit.mean()


class TestApproxCoarse:
    def test_approx_coarse(self, dataset):
        x, q = dataset
        index = ivf_bq.build(None, IvfBqIndexParams(n_lists=16), x)
        _, i1 = ivf_bq.search(None, IvfBqSearchParams(n_probes=8),
                              index, q, 10)
        _, i2 = ivf_bq.search(
            None, IvfBqSearchParams(n_probes=8, coarse_algo="approx"),
            index, q, 10)
        r, _, _ = eval_recall(np.asarray(i1), np.asarray(i2))
        assert r >= 0.9, r
        with pytest.raises(Exception):
            ivf_bq.search(None, IvfBqSearchParams(coarse_algo="bogus"),
                          index, q, 5)
